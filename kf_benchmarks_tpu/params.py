"""Parameter corpus + Params object + cross-flag validation.

TPU-native re-design of the reference's flag corpus and Params plumbing
(ref: benchmark_cnn.py:114-636 for the corpus, :953-1034 for Params /
make_params / make_params_from_flags / validation). GPU-specific knobs
(winograd env vars, TensorRT, MKL, NCCL specs) map to their TPU analogs:
XLA flag plumbing, AOT compilation, ICI collectives. Names are kept close
to the reference so users of the reference CLI can switch with minimal
churn; `num_gpus` is accepted as an alias for `num_devices`.
"""

from __future__ import annotations

import collections
from typing import Any, Dict

from kf_benchmarks_tpu import flags

# ---------------------------------------------------------------------------
# Flag corpus (ref: benchmark_cnn.py:114-636)
# ---------------------------------------------------------------------------

flags.DEFINE_string("model", "trivial",
                    "Name of the model to run (ref :116-118).")
flags.DEFINE_integer("batch_size", 0, "Per-device batch size (0 = model "
                     "default; ref :130-133).", lower_bound=0)
flags.DEFINE_integer("batch_group_size", 1,
                     "Number of batches the input feeder keeps in flight "
                     "ahead of the step loop (ref :134-136; wired to the "
                     "DeviceFeeder prefetch depth).", lower_bound=1)
flags.DEFINE_integer("steps_per_dispatch", 1,
                     "Device-resident multi-step training: compile K "
                     "train steps into one lax.scan program so host "
                     "dispatch, tunnel RTT, and metric fetches are paid "
                     "once per K steps (the TPU-native analog of the "
                     "reference's in-graph loops / amortized sess.run "
                     "fetches, ref: benchmark_cnn.py:786-884 step "
                     "semantics). 1 = one dispatch per step. Per-step "
                     "losses are unchanged; wall-clock timing is honest "
                     "at chunk granularity (utils/pipeline.py).",
                     lower_bound=1)
flags.DEFINE_integer("num_grad_accum", 1,
                     "Gradient accumulation: split each per-device batch "
                     "into M microbatches scanned inside the train step, "
                     "accumulating gradients in f32 before ONE gradient "
                     "collective and ONE optimizer apply (Megatron-style "
                     "microbatching, Shoeybi et al. 2019 -- no reference "
                     "analog; its per-GPU towers never exceeded memory). "
                     "Backward-pass activation residuals shrink to "
                     "batch/M; per-device batch size must be divisible by "
                     "M (validation.py). Batch-norm models note: BN "
                     "statistics are computed per MICROBATCH (batch/M "
                     "samples) and the running-stats EMA advances M times "
                     "per step -- standard microbatching semantics, NOT "
                     "numerically equivalent to M=1 for BN models (a "
                     "run-time note is logged). Composes with "
                     "--steps_per_dispatch (dispatch chunking outside, "
                     "microbatching inside). 1 = the monolithic step.",
                     lower_bound=1)
flags.DEFINE_boolean("packed_sequences", False,
                     "Variable-length sequence packing for "
                     "transformer_lm (the standard LM-pretraining "
                     "input form; no reference analog -- its inputs "
                     "are fixed-shape images): a deterministic "
                     "host-side first-fit bin-packer (data/packing.py) "
                     "draws variable-length documents from a seeded "
                     "length distribution and packs them into (B, T) "
                     "rows with segment ids + per-document positions; "
                     "segment-aware masks run through BOTH attention "
                     "implementations (block-level cross-segment tile "
                     "skip, parallel/sequence.py), the chunked fused "
                     "loss weighs real tokens only (ops/fused_loss.py) "
                     "and step metrics combine token-weighted "
                     "(train_step.py). Batches stream through the "
                     "DeviceFeeder (prefetch overlap measured via "
                     "feed_stall_fraction). transformer_lm training "
                     "only; composes with --steps_per_dispatch/"
                     "--num_grad_accum/--overlap_gradient_reduction; "
                     "exclusions in validation.py.")
flags.DEFINE_integer("input_prefetch_depth", None,
                     "Host->device prefetch depth of the DeviceFeeder "
                     "in batches (the StagingArea/MultiDeviceIterator "
                     "buffer depth analog, ref: benchmark_cnn.py:"
                     "2572-2600, preprocessing.py:368-399). None = "
                     "derived: max(--datasets_prefetch_buffer_size, "
                     "--batch_group_size), the historical default. "
                     "The measured consumer-side knob: "
                     "feed_stall_fraction in the benchmark stats / "
                     "bench JSON shows whether the depth hides host "
                     "preprocessing behind device compute.",
                     lower_bound=1)
flags.DEFINE_string("autotuned_config", None,
                    "Path to a tuned-config table "
                    "(analysis/autotune.py; train_dir/tuned_configs.json "
                    "from `python -m kf_benchmarks_tpu.analysis autotune` "
                    "or `experiments/zoo_sweep.py --autotune`). At "
                    "startup the entry matching this run's base "
                    "fingerprint (analysis/baseline.base_fingerprint_key "
                    "-- the config sans the tuned knobs) is applied over "
                    "the flag values of --steps_per_dispatch, "
                    "--num_grad_accum, --reduce_bucket_mb, "
                    "--input_prefetch_depth and --attn_block, with a "
                    "logged provenance line; no matching entry logs a "
                    "note and runs with the flag values. Replaces the "
                    "reference's per-model hand-tuned flag defaults "
                    "(SURVEY 2) with a measured, per-host table. "
                    "Training runs only (validation.py).")
flags.DEFINE_integer("attn_block", None,
                     "Attention K/V block size of the transformer_lm "
                     "family's tiled/flash attention (parallel/"
                     "sequence.py blockwise_attention; the q-block is "
                     "matched to it). None = the model default "
                     "(models/transformer_lm.ATTN_BLOCK). Must divide "
                     "the model's sequence length (validation.py); a "
                     "program-shaping knob the autotuner searches "
                     "(analysis/autotune.py TUNED_KNOBS).", lower_bound=8)
flags.DEFINE_integer("num_batches", None,
                     "Number of timed batches to run (ref :137-139).")
flags.DEFINE_float("num_epochs", None,
                   "Number of epochs to run (mutually exclusive with "
                   "num_batches; ref :140-144).")
flags.DEFINE_integer("num_warmup_batches", None,
                     "Number of warmup batches before timing (ref :145-146).")
flags.DEFINE_integer("num_devices", 1,
                     "Number of accelerator devices to use per process "
                     "(ref num_gpus :122-123).", lower_bound=1)
flags.DEFINE_enum("device", "tpu", ("tpu", "cpu", "gpu"),
                  "Device to run compute on (ref :179-181; TPU added per "
                  "north star).")
flags.DEFINE_enum("data_format", "NHWC", ("NHWC", "NCHW"),
                  "Tensor layout. NHWC is the TPU-native layout (the "
                  "reference defaults to NCHW for cuDNN, ref :182-185).")
flags.DEFINE_boolean("eval", False, "Run evaluation instead of training "
                     "(ref :119).")
flags.DEFINE_integer("eval_interval_secs", 0,
                     "How often eval polls for new checkpoints (ref :147-151).")
flags.DEFINE_integer("num_eval_batches", None,
                     "Number of eval batches (ref :152-155).")
flags.DEFINE_float("num_eval_epochs", None,
                   "Number of eval epochs (ref :156-160).")
flags.DEFINE_integer("eval_during_training_every_n_steps", None,
                     "Mid-training eval cadence in steps (ref :161-166).")
flags.DEFINE_float("eval_during_training_every_n_epochs", None,
                   "Mid-training eval cadence in epochs (ref :140-143).")
flags.DEFINE_list("eval_during_training_at_specified_steps", [],
                  "Explicit training steps after which to run eval "
                  "(ref :144-147).")
flags.DEFINE_list("eval_during_training_at_specified_epochs", [],
                  "Explicit training epochs after which to run eval "
                  "(ref :148-152).")
flags.DEFINE_float("stop_at_top_1_accuracy", None,
                   "Stop training early once this top-1 is reached "
                   "(ref :167-172).")
flags.DEFINE_boolean("forward_only", False,
                     "Only run forward pass (ref :124-126).")
flags.DEFINE_boolean("print_training_accuracy", False,
                     "Compute and print top-1/top-5 during training "
                     "(ref :127-129).")
flags.DEFINE_integer("display_every", 10,
                     "Print step stats every N steps (ref :173-175).",
                     lower_bound=1)
flags.DEFINE_string("data_dir", None,
                    "Path to dataset; synthetic data if empty (ref :186-190).")
flags.DEFINE_string("data_name", None,
                    "Dataset name, sniffed from data_dir if empty "
                    "(ref :191-194).")
flags.DEFINE_boolean("distortions", False,
                     "Enable full image distortions (ref :199-202; reference "
                     "default True, flipped off here: synthetic-first).")
flags.DEFINE_float("gpu_memory_frac_for_testing", 0.0,
                   "Kept for CLI parity; no-op on TPU (ref :336-342).")
flags.DEFINE_boolean("use_fp16", False,
                     "Use reduced precision activations/gradients. On TPU "
                     "this means bfloat16 (ref use_fp16 :464-470).")
flags.DEFINE_float("fp16_loss_scale", None,
                   "Loss scale; None = model default. bfloat16 does not "
                   "need loss scaling so TPU default is 1 (ref :471-480).")
flags.DEFINE_boolean("fp16_vars", False,
                     "Keep variables in reduced precision too (ref :481-485).")
flags.DEFINE_boolean("fp16_enable_auto_loss_scale", False,
                     "Auto loss-scaling state machine (ref :486-490).")
flags.DEFINE_integer("fp16_inc_loss_scale_every_n", 1000,
                     "Double loss scale after N clean steps (ref :491-495).")
flags.DEFINE_string("mesh_shape", None,
                    "Named 2-D device mesh 'BxM' (e.g. 8x1, 4x2): B = "
                    "'batch' axis (data parallelism; global batch = B x "
                    "per-device batch), M = 'model' axis (state-sharding "
                    "/ tensor dimension; the composed LM trainer refines "
                    "it into seq x tensor, parallel/transformer.py). "
                    "B*M must equal --num_devices; M > 1 requires "
                    "--shard_optimizer_state (its only consumer in the "
                    "core step). Unset = the 1-D replica mesh "
                    "(--shard_optimizer_state alone resolves to Nx1). "
                    "The GSPMD named-mesh idiom (Xu et al. 2021).")
flags.DEFINE_boolean("shard_optimizer_state", False,
                     "ZeRO-shard optimizer state over the whole "
                     "('batch', 'model') mesh (Rajbhandari et al.): "
                     "gradients meet in a reduce-scatter of the batch "
                     "mean (bit-identical to the replicated pmean at "
                     "f32), the optimizer applies on each device's 1/n "
                     "flat state shard only, and updated params "
                     "all-gather for the next forward -- per-device "
                     "optimizer HBM drops to ~|state|/n and gradient "
                     "wire bytes to (B-1)/B + (n-1)/n of |grads| (the "
                     "TPU analog of the reference's central variable "
                     "placement, variable_mgr.py:201-243; ops/"
                     "sharded.py). Synchronous replicated/"
                     "parameter_server family only; composes with "
                     "--steps_per_dispatch and --num_grad_accum; "
                     "exclusions in validation.py.")
flags.DEFINE_boolean("shard_params", False,
                     "Full FSDP (ZeRO-3, Rajbhandari et al.): params "
                     "live as 1/n flat shards between steps (the same "
                     "(n, k) stacked layout as the sharded optimizer "
                     "state; per-layer rows for scanned stacks) and "
                     "the step re-assembles them per builder-layer "
                     "bucket / per scanned transformer block INSIDE "
                     "the forward/backward with one packed all-gather "
                     "each (ops/overlap.py gather_params; the bucket "
                     "bound is --reduce_bucket_mb, default 4 MiB), so "
                     "peak param residency is one bucket/block and "
                     "steady-state per-device param HBM is |params|/n "
                     "-- the full tree never materializes and the "
                     "sharded path's trailing all-gather is gone. "
                     "Gradients arrive reduce-scattered by the gather "
                     "hooks' backward (bit-identical per element to "
                     "the post-hoc scatter at f32). Requires "
                     "--shard_optimizer_state (elementwise-optimizer "
                     "family, same exclusions; validation.py); under "
                     "--num_grad_accum the in-compute gathers "
                     "disengage (one whole-tree gather per step, like "
                     "the overlap hooks' accum rule).")
flags.DEFINE_enum("partitioner", None, ("manual", "gspmd"),
                  "Who places the collectives in the sharded training "
                  "step. 'manual' (the None default) = the hand-placed "
                  "shard_map programs (ops/sharded.py + ops/overlap.py; "
                  "every golden contract pins them byte-identically). "
                  "'gspmd' = the SAME step body lowered under plain "
                  "jit with NamedSharding-annotated state/batch on the "
                  "same ('batch', 'model') mesh, letting the XLA SPMD "
                  "partitioner insert/re-place the collectives (Xu et "
                  "al. 2021); losses stay bit-identical at f32 and the "
                  "analysis/audit.py twin-referee rule classifies every "
                  "inventory divergence. Sharded families "
                  "(--shard_optimizer_state [+ --shard_params]) and "
                  "serving only -- the gossip/async-PS/independent/"
                  "staged/hierarchical modes are semantic hand "
                  "placements (validation.py). Program-shaping: a "
                  "tuned knob (analysis/baseline.TUNED_KNOBS), so "
                  "gspmd runs never mix with manual run-store history. "
                  "None default keeps non-sharded fingerprints "
                  "untouched (fingerprints drop None fields).")
flags.DEFINE_enum("variable_update", "replicated",
                  ("independent", "parameter_server", "replicated",
                   "distributed_replicated", "distributed_all_reduce",
                   "collective_all_reduce", "horovod", "kungfu"),
                  "Parallelism strategy (ref :523-531).")
flags.DEFINE_enum("kungfu_option", "sync_sgd",
                  ("sync_sgd", "async_sgd", "sma"),
                  "KungFu optimizer wrapper. The reference enum advertises "
                  "'ada_sgd' but dispatches on 'sma' (quirk, ref :530 vs "
                  ":1199); we expose the reachable set.")
flags.DEFINE_string("all_reduce_spec", None,
                    "All-reduce algorithm spec, BNF alg#shards:limit:... "
                    "(ref :532-553). TPU algs: psum, rsag (reduce-scatter + "
                    "all-gather), hierarchical; size-ranged hybrids kept.")
flags.DEFINE_integer("agg_small_grads_max_bytes", 0,
                     "Pack gradients smaller than this into one tensor "
                     "before the all-reduce (ref :554-557; 0 = off).")
flags.DEFINE_integer("agg_small_grads_max_group", 10,
                     "Max number of small gradients per pack (ref :558-560).")
flags.DEFINE_integer("allreduce_merge_scope", 1,
                     "Accepted for parity, no TPU effect: ScopedAllocator "
                     "merge hint; XLA schedules collectives itself "
                     "(ref :561-566).")
flags.DEFINE_integer("gradient_repacking", 0,
                     "Re-split the concatenated gradient vector into this "
                     "many evenly-sized chunks for reduction (ref "
                     ":499-502; 0 = off; exclusive with --all_reduce_spec).",
                     lower_bound=0)
flags.DEFINE_boolean("compact_gradient_transfer", True,
                     "Compact gradients to a 16-bit wire format (bf16) for "
                     "the all-reduce when --use_fp16 is on (ref :503-506).")
flags.DEFINE_boolean("compact_gradient_transfer_f32", False,
                     "Engage the 16-bit (bf16) all-reduce wire format for "
                     "f32 training too -- the reference compacted only "
                     "fp16 gradients (ref: batch_allreduce.py:96-103); "
                     "this is the explicit f32 opt-in (halves reduction "
                     "bytes; a precision note is logged -- NOT "
                     "bit-identical to the f32 wire). Requires "
                     "--compact_gradient_transfer AND a reduction path "
                     "that repacks the wire (--overlap_gradient_reduction "
                     "or a packed reducer flag); the default per-leaf "
                     "pmean has nothing to compact (validation.py).")
flags.DEFINE_boolean("overlap_gradient_reduction", False,
                     "Overlap gradient communication with backward "
                     "compute: size-bounded gradient buckets "
                     "(--reduce_bucket_mb) each reduce as one collective "
                     "issued IN the backward pass (identity-with-"
                     "custom_vjp hooks at layer boundaries; per scanned "
                     "block for scan-over-layers models), so layer L's "
                     "all-reduce runs while layer L-1's backward is still "
                     "computing -- the pipelining the reference's chunked "
                     "batch_allreduce/--gradient_repacking existed for "
                     "(ref: batch_allreduce.py:391-481). f32 wire "
                     "gradients stay bit-identical to the post-hoc path "
                     "(ops/overlap.py). Replicated-family "
                     "--variable_update only; under --num_grad_accum the "
                     "reduction stays post-hoc on the accumulated tree "
                     "(one collective per step); exclusive with the "
                     "spec/repacking/small-grad/hierarchical reducers "
                     "(validation.py).")
flags.DEFINE_integer("reduce_bucket_mb", None,
                     "Gradient-reduction bucket bound in MiB for "
                     "--overlap_gradient_reduction (default 4): leaves "
                     "group at builder-layer granularity and merge into "
                     "buckets of at most this size, one collective per "
                     "bucket (ops/overlap.py; the granularity lever the "
                     "reference's --gradient_repacking chunk count "
                     "turned, ref :499-502).", lower_bound=1)
flags.DEFINE_boolean("hierarchical_copy", False,
                     "Two-level reduction: grouped psum within contiguous "
                     "device groups, then across them (ref :507-513).")
flags.DEFINE_integer("network_topology", 0,
                     "Topology hint index (ref constants.py:21-24).")
flags.DEFINE_enum("local_parameter_device", "cpu", ("cpu", "gpu", "tpu"),
                  "Device for parameter-server-style variable placement "
                  "(ref :514-517).")
flags.DEFINE_enum("optimizer", "sgd", ("sgd", "momentum", "rmsprop", "adam",
                                       "lars"),
                  "Optimizer (ref :414-417; lars added: standard for "
                  "large-batch ResNet on TPU).")
flags.DEFINE_float("init_learning_rate", None,
                   "Initial LR; None = model default (ref :418-422).")
flags.DEFINE_string("piecewise_learning_rate_schedule", None,
                    "Schedule 'LR0;E1;LR1;...;En;LRn' (ref :423-429).")
flags.DEFINE_float("num_epochs_per_decay", 0,
                   "Epochs between LR decays (ref :430-434).")
flags.DEFINE_float("learning_rate_decay_factor", 0,
                   "Exponential decay factor (ref :435-440).")
flags.DEFINE_float("num_learning_rate_warmup_epochs", 0,
                   "Linear LR warmup epochs (ref :441-444).")
flags.DEFINE_float("minimum_learning_rate", 0,
                   "LR floor (requires decay flags; ref :445-449).")
flags.DEFINE_float("momentum", 0.9, "Momentum (ref :450).")
flags.DEFINE_float("rmsprop_decay", 0.9, "RMSProp decay (ref :451-452).")
flags.DEFINE_float("rmsprop_momentum", 0.9, "RMSProp momentum (ref :453-454).")
flags.DEFINE_float("rmsprop_epsilon", 1.0, "RMSProp epsilon (ref :455-456).")
flags.DEFINE_float("adam_beta1", 0.9, "Adam beta1 (ref :457-458).")
flags.DEFINE_float("adam_beta2", 0.999, "Adam beta2 (ref :459-460).")
flags.DEFINE_float("adam_epsilon", 1e-8, "Adam epsilon (ref :461-462).")
flags.DEFINE_float("weight_decay", 4e-5, "L2 weight decay (ref :496-498).")
flags.DEFINE_boolean("single_l2_loss_op", False,
                     "Compute L2 loss on concatenated weights instead of "
                     "per-tensor (ref :499-502 single_l2_loss_op).")
flags.DEFINE_float("gradient_clip", None, "Gradient clip magnitude "
                   "(ref :412-413).")
flags.DEFINE_boolean("use_xla_compile", True,
                     "jit the whole step function. Must stay true: XLA "
                     "compilation IS the TPU execution model; false is "
                     "rejected in validation (ref xla_compile :413-416).")
flags.DEFINE_boolean("sync_on_finish", False,
                     "Barrier across workers at exit (ref :567-569; KungFu "
                     "run_barrier analog, ref tf_cnn_benchmarks.py:58-60).")
flags.DEFINE_boolean("track_grad_noise_scale", False,
                     "Measure the gradient noise scale in the train step "
                     "(per-replica vs replica-mean gradients) and report "
                     "the EMA-smoothed B_simple -- the statistic KungFu's "
                     "adaptation policies monitor (SURVEY 2.9 north star).")
flags.DEFINE_boolean("elastic", False,
                     "Enable elastic resize: watch the coordination "
                     "service (KFCOORD_* env) for target-size changes and "
                     "re-jit over the new device mesh, carrying state via "
                     "checkpointed rescale (KungFu resize_cluster analog).")
flags.DEFINE_integer("elastic_check_every_n_steps", 10,
                     "How often the train loop polls for elastic resize / "
                     "adaptive-batch decisions.", lower_bound=1)
flags.DEFINE_string("fault_schedule", None,
                    "Deterministic fault injection (faults.py): "
                    "comma-separated <kind>@<step>[:rank=R][:secs=S] "
                    "entries with kind in kill | sigterm | "
                    "heartbeat_delay | drop_msg | corrupt_ckpt; each "
                    "fires ONCE at the dispatch boundary after the "
                    "named step (one-shot across checkpoint-restart "
                    "generations via train_dir markers). The "
                    "reproducible-preemption harness behind the "
                    "kill/rejoin tests; no reference analog.")
flags.DEFINE_boolean("adaptive_batch_size", False,
                     "Adapt the per-device batch size to the measured "
                     "gradient noise scale (implies "
                     "track_grad_noise_scale; KungFu adaptive batch "
                     "policy analog).")
flags.DEFINE_integer("adaptive_batch_min", 1,
                     "Lower bound for the adaptive per-device batch size.",
                     lower_bound=1)
flags.DEFINE_integer("adaptive_batch_max", 1024,
                     "Upper bound for the adaptive per-device batch size.",
                     lower_bound=1)
flags.DEFINE_boolean("cross_replica_sync", True,
                     "Synchronous data-parallel updates (ref :520-522).")
flags.DEFINE_enum("variable_consistency", "strong", ("strong", "relaxed"),
                  "relaxed applies one-step-stale gradients (double-"
                  "buffered in the step carry; ref :242, "
                  "batch_allreduce.py:353-388 deferred StagingArea "
                  "gradients).")
flags.DEFINE_boolean("staged_vars", False,
                     "Forward/backward read one-step-stale weights while "
                     "updates land on the live ones (ref :406, "
                     "variable_mgr.py:246-274 StagedVariableGetter).")
flags.DEFINE_string("train_dir", None,
                    "Checkpoint/summary directory (ref :585-588).")
flags.DEFINE_string("compilation_cache_dir", None,
                    "Persistent XLA compilation-cache directory "
                    "(jax.config compilation_cache_dir, set in "
                    "benchmark.py before the first trace): a program "
                    "shape compiles ONCE ever -- later runs deserialize "
                    "the cached executable, so the 30-min first-compile-"
                    "over-the-tunnel hazard (CLAUDE.md) is paid once "
                    "per shape. Unset = derived as <train_dir>/"
                    "xla_cache when --train_dir is set, else off; the "
                    "compile ledger's cache_hit column (tracing.py) "
                    "records which episodes the cache covered.")
flags.DEFINE_boolean("health_stats", None,
                     "In-step training-health stats (telemetry.py): the "
                     "train step additionally returns a compact f32 "
                     "vector (global grad norm, update/param norm ratio, "
                     "non-finite leaf count, loss scale + skip flag) "
                     "computed inside the compiled program and packed "
                     "into the existing loss pmean, so it adds NO extra "
                     "collective (pinned in tests/test_telemetry.py); "
                     "feeds the flight recorder and stall watchdog. "
                     "Unset = auto: on for training runs that reduce "
                     "gradients replica-synchronously (replicated family "
                     "/ kungfu sync_sgd) AND have a telemetry sink "
                     "(--train_dir or --benchmark_log_dir); off with a "
                     "note for per-replica/gossip/async modes, off "
                     "quietly for sink-less runs (the readout rides the "
                     "step's tail, so it is not free). No reference "
                     "analog -- its observability is post-hoc only "
                     "(SURVEY 5.1/9; ref: benchmark_cnn.py:585-620 "
                     "summaries/benchmark logs).")
flags.DEFINE_float("health_grad_norm_sigma", 6.0,
                   "Flight-recorder anomaly threshold: a step whose "
                   "global grad norm exceeds the trailing window's mean "
                   "by this many standard deviations dumps the window "
                   "(telemetry.py).", lower_bound=0.1)
flags.DEFINE_integer("flight_recorder_window", 64,
                     "Per-step records the flight recorder retains (and "
                     "continuously rewrites to train_dir/"
                     "flight_recorder.jsonl); the post-mortem window "
                     "dumped on anomaly/signal/exit (telemetry.py).",
                     lower_bound=4)
flags.DEFINE_float("stall_watchdog_factor", 10.0,
                   "Mid-run stall threshold: silence beyond this factor "
                   "times the trailing mean chunk wall emits a watchdog "
                   "diagnostic (never a kill -- a kill mid-claim is the "
                   "documented tunnel-wedge trigger). 0 disables the "
                   "watchdog thread; the first compile is always exempt "
                   "(patient, log-only) (telemetry.py).", lower_bound=0)
flags.DEFINE_integer("metrics_port", None,
                     "Serve a live scrape endpoint from the metric "
                     "registry (metrics.py) on this port: /metrics in "
                     "Prometheus text format, /healthz from watchdog + "
                     "flight-recorder state. Under kfrun each rank "
                     "binds port + rank, so every worker of a "
                     "single-host job gets its own scrape target. "
                     "Host-side only: the metrics-on step program is "
                     "structurally identical to the metrics-off golden "
                     "(analysis/audit.rule_metrics_twin). Unset = no "
                     "socket is ever bound. Training runs only "
                     "(validation.py). No reference analog -- its "
                     "results ship post-hoc (BenchmarkLogger / BigQuery "
                     "upload, ref: benchmark_cnn.py:1594-1608).",
                     lower_bound=1, upper_bound=65535)
flags.DEFINE_string("run_store_dir", None,
                    "Append one schema-versioned run record (config "
                    "fingerprint, git rev, jax version, platform, full "
                    "metric snapshot) to the append-only JSONL run "
                    "store in this directory at run end (metrics.py "
                    "RunStore; rank 0 only) -- the cross-run history "
                    "the regression sentinel (bench.py "
                    "--check-regression) compares against. Unset = no "
                    "record for training runs; bench.py defaults its "
                    "own store next to the BENCH_*.json trajectory. "
                    "Training runs only (validation.py).")
flags.DEFINE_integer("summary_verbosity", 0,
                     "0-3: none / scalars / grad histograms / everything "
                     "(ref :589-593).", lower_bound=0, upper_bound=3)
flags.DEFINE_integer("save_summaries_steps", 0,
                     "Summary cadence, 0 = off (ref :594-597).")
flags.DEFINE_integer("save_model_secs", 0,
                     "Checkpoint cadence in seconds (ref :598-601).")
flags.DEFINE_integer("save_model_steps", 0,
                     "Checkpoint cadence in steps (ref :602-605).")
flags.DEFINE_integer("max_ckpts_to_keep", 5,
                     "Max checkpoints kept (ref :606-608).")
flags.DEFINE_string("trace_file", None,
                    "Profiler trace output path (ref :270-275; jax.profiler "
                    "trace dir on TPU).")
flags.DEFINE_string("trace_events_file", None,
                    "Whole-run host-side span timeline (tracing.py; the "
                    "run-wide successor of the reference's one-step "
                    "timeline, ref :806-817): DeviceFeeder fetches/waits, "
                    "dispatch issue + per-chunk device completion, "
                    "compile episodes, checkpoint save/restore, eval, "
                    "elastic reseams and fault injections, exported as "
                    "Chrome trace-event JSON (loads in Perfetto / "
                    "chrome://tracing; pid=rank, tid=subsystem; "
                    "--use_chrome_trace_format=false writes the raw span "
                    "JSONL instead). Host-only: the step program and "
                    "per-step losses are bit-identical trace-on vs off "
                    "(auditor twin rule). Per-rank files under kfrun, "
                    "rank 0 merges at exit. Independent of the "
                    "jax.profiler --trace_file device capture. Training "
                    "runs only (validation.py).")
flags.DEFINE_string("tfprof_file", None,
                    "Per-op profile output (ref tfprof_file :276-289; "
                    "compiled-HLO cost analysis dump on TPU).")
flags.DEFINE_string("graph_file", None,
                    "Dump the optimized program text (StableHLO) to this "
                    "path (ref :2142-2148 GraphDef dump).")
flags.DEFINE_string("benchmark_log_dir", None,
                    "Structured JSON benchmark-log directory "
                    "(ref :1594-1608).")
flags.DEFINE_integer("tf_random_seed", 1234,
                     "Graph-level random seed (ref :609-612).")
flags.DEFINE_string("backbone_model_path", None,
                    "Warm-start backbone checkpoint (SSD; ref :613-614).")
flags.DEFINE_string("aot_save_path", None,
                    "Forward-only mode: serialize the frozen forward "
                    "program (AOT compile + weights-as-constants) to this "
                    "path -- the serving-graph/TensorRT analog "
                    "(ref trt_mode :615-620, _preprocess_graph "
                    ":2405-2525).")
flags.DEFINE_string("aot_load_path", None,
                    "Forward-only mode: load a frozen forward program "
                    "exported via --aot_save_path and benchmark ITS "
                    "images/sec -- the serving benchmark on the frozen "
                    "artifact (ref: the TRT-converted-graph timing path, "
                    "_preprocess_graph + forward-only loop).")
flags.DEFINE_boolean("use_synthetic_gpu_images", False,
                     "(parity alias; synthetic data is data_dir=None)")
# Serving engine (kf_benchmarks_tpu/serving/; bench.py --serving and
# experiments/serving_sweep.py --engine consume these). All default
# None = the engine's own defaults, so a non-serving run's config
# fingerprint is untouched (fingerprints drop None fields).
flags.DEFINE_string("serving_bucket_ladder", None,
                    "Comma-separated ascending batch buckets the "
                    "serving engine may compile decode/prefill "
                    "executables at (serving/engine.py; e.g. "
                    "'1,4,16,64'). The ladder BOUNDS the executable "
                    "set -- the auditor's serving_decode golden and "
                    "the compile-ledger e2e pin it. None = the engine "
                    "default ladder.")
flags.DEFINE_string("serving_batching", None,
                    "Serving batch policy: 'continuous' (in-flight "
                    "batching -- freed decode slots refill from the "
                    "queue every step) or 'static' (batch-and-drain: "
                    "admit a wave, decode to completion, then admit "
                    "again -- the A/B baseline arm). None = "
                    "continuous (validation.py).")
flags.DEFINE_integer("serving_max_new_tokens", None,
                     "Default per-request generation cap of the "
                     "serving engine. None = the engine default.",
                     lower_bound=1)
flags.DEFINE_integer("serving_queue_depth", None,
                     "Admission queue bound: a submit beyond this "
                     "depth is REJECTED (first-class shed result + "
                     "serving/shed metric, never an exception). None "
                     "= the engine default.", lower_bound=1)
flags.DEFINE_float("serving_ttft_slo_ms", None,
                   "TTFT service-level objective in ms: a queued "
                   "request older than this at coalesce time is "
                   "EXPIRED (deadline shedding) instead of wasting a "
                   "prefill it can no longer meet. None = no "
                   "deadline.", lower_bound=0.0)
flags.DEFINE_float("serving_tenant_tokens_per_s", None,
                   "Per-tenant token-budget rate (prompt + generated "
                   "tokens charged at submit against a token bucket): "
                   "an over-budget request is REJECTED with the "
                   "tenant_budget shed reason. None = unmetered.",
                   lower_bound=0.0)
# Decode-cost variants (ISSUE 16). All default None/off so a
# variant-off run's config fingerprint is byte-identical to before.
flags.DEFINE_enum("serving_quantize", None, ("int8",),
                  "Weight-only quantization of the served model: "
                  "'int8' stores per-out-channel {int8, f32 scale} "
                  "leaves (quantization.py) dequantized INSIDE the "
                  "compiled step -- the TPU-native analog of the "
                  "reference's --trt_mode=INT8 (ref :615-620). None "
                  "= bf16/f32 weights.")
flags.DEFINE_integer("serving_kv_page_size", None,
                     "Paged KV cache: replace the dense per-slot "
                     "(T_max) ring slab with a shared fixed-size "
                     "block pool + per-request page tables at this "
                     "page size (tokens/page; must divide the "
                     "context length -- validation.py). None = the "
                     "dense ring slab.", lower_bound=1)
flags.DEFINE_integer("serving_speculative_k", None,
                     "Speculative decoding: a shallow draft proposes "
                     "k tokens per target dispatch; the target "
                     "verifies all k in ONE prefill-shaped call "
                     "(greedy output stays token-identical to plain "
                     "greedy). Requires --serving_draft_layers "
                     "(validation.py).", lower_bound=2)
flags.DEFINE_integer("serving_draft_layers", None,
                     "Depth of the speculative draft model (same "
                     "transformer_lm family; must be < the served "
                     "model's layer count). Only meaningful with "
                     "--serving_speculative_k (validation.py).",
                     lower_bound=1)
flags.DEFINE_integer("serving_model_shards", None,
                     "Tensor-parallel serving: shard the served LM's "
                     "weights and KV cache over an M-way 'model' mesh "
                     "axis (serving/decode.py model_shardings) and let "
                     "GSPMD place the decode/prefill/verify "
                     "collectives -- the serving leg of "
                     "--partitioner=gspmd. Must divide the model's "
                     "head count and the device count "
                     "(validation.py). None = single-replica "
                     "executables (fingerprints drop None fields, so "
                     "existing serving history is untouched).",
                     lower_bound=2)
# Distributed / cluster flags (ref :570-583).
flags.DEFINE_enum("job_name", "", ("ps", "worker", "controller", ""),
                  "Job role for multi-process runs (ref :571-573).")
flags.DEFINE_list("ps_hosts", [], "Parameter-server hosts (ref :574).")
flags.DEFINE_list("worker_hosts", [], "Worker hosts (ref :575).")
flags.DEFINE_string("controller_host", None, "Controller host (ref :576).")
flags.DEFINE_integer("task_index", 0, "Task index (ref :577).")
flags.DEFINE_string("server_protocol", "grpc", "Cluster wire protocol "
                    "(ref :578); the TPU coordination service speaks its "
                    "own protocol, flag kept for parity.")
flags.DEFINE_string("coordinator_address", None,
                    "host:port of the DCN coordination service "
                    "(kungfu-run analog, SURVEY 2.9).")
flags.DEFINE_integer("num_processes", 1,
                     "Number of cooperating host processes (kungfu-run -np).")
flags.DEFINE_integer("process_index", 0, "This process's rank.")
# Input pipeline knobs (ref :203-269).
flags.DEFINE_integer("num_intra_threads", None,
                     "Host compute threads (ref :203-208).")
flags.DEFINE_integer("num_inter_threads", None,
                     "Host inter-op threads (ref :209-214).")
flags.DEFINE_integer("datasets_prefetch_buffer_size", 2,
                     "Device prefetch depth (ref datasets_* :243-269).")
flags.DEFINE_integer("datasets_num_private_threads", None,
                     "Private threadpool for input pipeline (ref :248-253).")
flags.DEFINE_boolean("datasets_use_caching", False,
                     "Cache the input dataset in memory (ref :254-258).")
flags.DEFINE_integer("input_preprocessing_parallelism", 16,
                     "Parallel parse/augment calls (ref map parallelism).")
flags.DEFINE_boolean("use_datasets", True,
                     "Must stay true: the framework has one host input "
                     "pipeline; the reference's legacy RecordInput path "
                     "has no TPU analog and false is rejected "
                     "(ref :215-217).")
flags.DEFINE_enum("resize_method", "bilinear",
                  ("round_robin", "nearest", "bilinear", "bicubic", "area"),
                  "Eval/train resize method (ref :195-198).")
flags.DEFINE_string("input_preprocessor", "default",
                    "Name of the input preprocessor to use "
                    "(ref: benchmark_cnn.py:179-182).")
flags.DEFINE_boolean("winograd_nonfused", True,
                     "No-op on TPU; kept for CLI parity (ref :3285-3297).")
flags.DEFINE_boolean("sparse_to_dense_grads", False,
                     "Densify sparse gradients (ref :518-519; JAX grads are "
                     "dense, kept for parity).")
flags.DEFINE_enum("loss_type_to_report", "total_loss",
                  ("base_loss", "total_loss"),
                  "Which loss the step line prints (ref :346-353).")

# -- Reference-CLI parity corpus ---------------------------------------------
# The remaining reference flags, so its command lines parse here. Wired
# ones say so; the rest are accepted no-ops (changing them from their
# defaults logs a note at setup -- benchmark._NOOP_PARITY_FLAGS) or are
# rejected in validation with the TPU-native alternative named.
flags.DEFINE_boolean("datasets_repeat_cached_sample", False,
                     "Repeat the first input sample forever to emulate "
                     "memory-speed IO (wired into the record stream; "
                     "ref :259-263).")
flags.DEFINE_string("benchmark_test_id", None,
                    "Test id attached to the benchmark-log run info "
                    "(wired; ref :344-348).")
flags.DEFINE_string("eval_dir", "/tmp/tf_cnn_benchmarks/eval",
                    "Directory for eval benchmark logs (wired; "
                    "ref :585-586).")
flags.DEFINE_string("partitioned_graph_file_prefix", None,
                    "Dump the compiled (partitioned) program text to "
                    "<prefix>.txt (wired; ref :293-296 per-device "
                    "GraphDef dumps).")
flags.DEFINE_string("debugger", None,
                    "tfdbg has no TPU analog; any value is rejected "
                    "(ref :370-377).")
flags.DEFINE_string("trt_mode", "",
                    "Precision of the frozen serving export (the "
                    "TensorRT-conversion analog, ref :615-620): FP32, "
                    "FP16 (bf16 compute on TPU), or INT8 (weight-only "
                    "post-training quantization, quantization.py). "
                    "Requires --forward_only with --aot_save_path; "
                    "empty keeps the training compute dtype.")
flags.DEFINE_boolean("freeze_when_forward_only", False,
                     "Accepted for parity: freezing IS the AOT export "
                     "(--aot_save_path folds weights into constants; "
                     "ref :155-157).")
flags.DEFINE_integer("trt_max_workspace_size_bytes", 4 << 30,
                     "No-op on TPU (TensorRT knob, ref :619-620).")
flags.DEFINE_boolean("use_chrome_trace_format", True,
                     "Export --trace_events_file as Chrome trace-event "
                     "JSON (the reference's timeline.Timeline toggle, "
                     "ref :271-275, wired to the run-trace exporter in "
                     "tracing.py); false writes the raw span records as "
                     "JSONL instead. The jax.profiler --trace_file "
                     "capture is unaffected (it writes its own format).")
flags.DEFINE_boolean("xla", False,
                     "No-op: XLA is the only execution path on TPU "
                     "(ref :413).")
flags.DEFINE_boolean("xla_compile", False,
                     "No-op: the whole step is always jitted "
                     "(ref :414-416).")
flags.DEFINE_boolean("fuse_decode_and_crop", True,
                     "No-op: the host pipeline always crops before the "
                     "expensive resize (ref :227-230).")
flags.DEFINE_boolean("distort_color_in_yiq", True,
                     "No-op: color jitter runs via PIL enhancers, not "
                     "the YIQ rotation (ref :231-234).")
flags.DEFINE_boolean("datasets_use_prefetch", True,
                     "No-op: the DeviceFeeder always prefetches "
                     "(ref :243-247).")
flags.DEFINE_integer("datasets_parallel_interleave_cycle_length", None,
                     "No-op: shard reads interleave via the thread pool "
                     "(ref :264-266).")
flags.DEFINE_boolean("datasets_sloppy_parallel_interleave", False,
                     "No-op (tf.data interleave knob, ref :267-269).")
flags.DEFINE_integer("datasets_parallel_interleave_prefetch", None,
                     "No-op (tf.data interleave knob, ref :270-272).")
flags.DEFINE_boolean("use_multi_device_iterator", True,
                     "No-op: the DeviceFeeder is the MultiDeviceIterator "
                     "analog (ref :254-258).")
flags.DEFINE_integer("multi_device_iterator_max_buffer_size", 1,
                     "No-op (MultiDeviceIterator knob, ref :259-261).")
flags.DEFINE_boolean("use_resource_vars", False,
                     "No-op: JAX state is functional (ref :417-421).")
flags.DEFINE_boolean("use_tf_layers", True,
                     "No-op: one flax layer path (ref :422-425).")
flags.DEFINE_boolean("use_python32_barrier", False,
                     "No-op (CPython barrier workaround, ref :426-428).")
flags.DEFINE_boolean("compute_lr_on_cpu", False,
                     "No-op: the LR schedule is fused into the jitted "
                     "step (ref :429-431).")
flags.DEFINE_boolean("enable_optimizations", True,
                     "No-op: XLA optimizations are always on "
                     "(ref :432-434).")
flags.DEFINE_string("rewriter_config", None,
                    "No-op (grappler RewriterConfig, ref :435-438).")
flags.DEFINE_boolean("allow_growth", None,
                     "No-op (GPU memory growth, ref :330-332).")
flags.DEFINE_boolean("force_gpu_compatible", False,
                     "No-op (GPU pinned-memory knob, ref :333-335).")
flags.DEFINE_string("gpu_indices", "",
                    "No-op (GPU ring-order indices, ref :319-320).")
flags.DEFINE_enum("gpu_thread_mode", "gpu_private",
                  ("global", "gpu_private", "gpu_shared"),
                  "No-op (GPU thread pools, ref :321-324).")
flags.DEFINE_integer("per_gpu_thread_count", 0,
                     "No-op (GPU thread pools, ref :325-329).")
flags.DEFINE_boolean("use_unified_memory", False,
                     "No-op (CUDA unified memory, ref :336-338).")
flags.DEFINE_boolean("batchnorm_persistent", True,
                     "No-op (cuDNN CUDNN_BATCHNORM_SPATIAL_PERSISTENT, "
                     "ref :407-409).")
flags.DEFINE_integer("autotune_threshold", None,
                     "No-op (cuDNN autotune, ref :316-318).")
flags.DEFINE_string("horovod_device", "",
                    "No-op (Horovod device pinning; the SPMD data plane "
                    "covers it, ref :568-569).")
flags.DEFINE_boolean("mkl", False, "No-op (MKL build knob, ref :451).")
flags.DEFINE_integer("kmp_blocktime", 0,
                     "No-op (MKL env var, ref :452-455).")
flags.DEFINE_string("kmp_affinity", "granularity=fine,verbose,compact,1,0",
                    "No-op (MKL env var, ref :456-458).")
flags.DEFINE_integer("kmp_settings", 1,
                     "No-op (MKL env var, ref :459-460).")

# Accepted in both paths: make_params(**kw) translates them, and
# define_flags(aliases=ALIASES) materializes them as absl alias flags so
# reference command lines (--num_gpus=8) keep working.
ALIASES = {"num_gpus": "num_devices"}
_ALIASES = ALIASES

Params = None  # rebuilt by _rebuild_params_type()


def _rebuild_params_type():
  global Params
  Params = collections.namedtuple("Params", list(flags.param_specs.keys()))


def _params_type():
  """Rebuild Params when late DEFINEs grew the registry (the platform-hook
  / aux-CLI extension point: modules like all_reduce_benchmark register
  extra params at import, the analog of define_platform_params,
  ref: platforms/default/util.py:28-33)."""
  if Params is None or Params._fields != tuple(flags.param_specs.keys()):
    _rebuild_params_type()
  return Params


_rebuild_params_type()


def validate_params(params) -> None:
  """Per-field bounds/enum validation (ref: benchmark_cnn.py:962-990)."""
  for name, spec in flags.param_specs.items():
    flags.check_value(spec, getattr(params, name))


def make_params(**kwargs) -> "Params":
  """Construct Params from defaults + overrides (ref: benchmark_cnn.py:993)."""
  translated = {}
  for k, v in kwargs.items():
    k = _ALIASES.get(k, k)
    if k not in flags.param_specs:
      raise ValueError(f"Unknown param: {k}")
    translated[k] = flags.canonicalize_value(flags.param_specs[k], v)
  defaults = {name: spec.default_value
              for name, spec in flags.param_specs.items()}
  defaults.update(translated)
  params = _params_type()(**defaults)
  validate_params(params)
  return params


def make_params_from_flags() -> "Params":
  """Construct Params from parsed absl FLAGS (ref: benchmark_cnn.py:1013)."""
  values = flags.flag_values_as_dict()
  params = _params_type()(
      **{k: flags.canonicalize_value(flags.param_specs[k], v)
         if v is not None else None
         for k, v in values.items()})
  validate_params(params)
  return params


def remove_param_fields(params, field_names) -> "Params":
  """Null out fields (eval-mode stripping; ref: benchmark_cnn.py:1026)."""
  return params._replace(**{f: None for f in field_names
                            if f in params._fields})
