"""Chunked fused LM-head cross-entropy: loss and top-k metrics computed
from (hidden, head-kernel) without ever materializing the full
(B, T, V) logits tensor.

BEYOND-REFERENCE: the reference zoo has no LM family and its losses all
fit comfortably in device memory (ref: models/model.py:287-302 sparse
softmax xent over nclass <= 1001). At transformer_lm scale the f32
logits tensor IS the HBM peak: (8, 2048, 32768) f32 = 2 GiB before the
softmax-backward temps double it (measured OOM at bs=8 on the 16 GiB
chip, PERF.md round 4). The round-6 loss already chunked the softmax,
but the Dense head still materialized the full logits; this module
fuses the head matmul INTO the chunked scan, so peak temp is
O(B * chunk * V) on the forward AND the backward path:

* ``lax.scan`` over sequence slices: each iteration computes the
  slice's logits (hidden_chunk @ kernel), upcasts to f32, log-softmax,
  gathers the label log-probs, and adds the slice sum to a scalar
  carry.
* ``jax.checkpoint`` on the scan body: the backward pass recomputes
  each slice's logits/softmax instead of keeping every slice's
  residuals alive -- the same schedule flash-attention applies to the
  score matrix (Dao et al. 2022), applied to the vocabulary axis.
* The kernel gradient accumulates per-slice through the scan
  transpose (one (D, V) accumulator), never a logits-sized cotangent.

Numerics contract (pinned by tests/test_fused_loss.py): in f32 the
loss AND the gradients are bit-exact against a monolithic head that
materializes the full logits tensor and reduces in the same chunk
order (``monolithic_softmax_xent`` below) -- chunking a matmul along
rows and log-softmax along its batch axes is exact, so the only
freedom is summation order, which both sides fix identically.

Packed sequences (--packed_sequences): both reductions take optional
per-token ``weights`` (data/packing.py token_weights_from_segments --
0 at padding and document-final slots) and normalize by the REAL-token
count; ``weights=None`` keeps the exact unweighted program, so every
pre-packing pin is untouched.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from kf_benchmarks_tpu.parallel import sequence as sequence_lib


class FusedLMHead(NamedTuple):
  """A model head deferred into the loss: final hidden states plus the
  unembedding kernel, in place of materialized logits.

  Models whose vocabulary makes (B, T, V) logits the memory peak return
  this from their module as the ``logits`` slot of BuildNetworkResult;
  their loss/accuracy functions dispatch on it and reduce chunk-wise
  (models/transformer_lm.py is the zoo member that does).
  """
  hidden: Any  # (B, T, D) final hidden states (model compute dtype)
  kernel: Any  # (D, V) unembedding matrix (param dtype)


def chunk_of(t: int, limit: int) -> int:
  """Largest divisor of ``t`` within ``limit``: the bounded-memory
  guarantee must hold for EVERY sequence length (never a silent
  full-tensor fallback; worst case chunk=1)."""
  return max(c for c in range(1, min(limit, t) + 1) if t % c == 0)


def _chunked(x, chunk: int):
  """(B, T, ...) -> (T/chunk, B, chunk, ...) scan layout."""
  b, t = x.shape[:2]
  return x.reshape((b, t // chunk, chunk) + x.shape[2:]).swapaxes(0, 1)


def fused_softmax_xent(hidden, kernel, labels, chunk_size: int = 256,
                       weights=None):
  """Mean next-token NLL from (hidden, kernel) with O(B*chunk*V) temps.

  ``hidden`` (B, T, D) stays in the model compute dtype through the
  per-chunk head matmul (bf16 on TPU under --use_fp16: the head computes
  in the model dtype, exactly like the Dense head it replaces); the
  softmax upcasts the CHUNK to f32. Returns a f32 scalar.

  ``weights`` (B, T) engages packed-sequence masking (data/packing.py
  token_weights_from_segments): each slot's log-likelihood is scaled by
  its weight inside the scan and the mean normalizes by the REAL-token
  count ``sum(weights)`` instead of B*T -- padding and document-final
  slots (weight 0) contribute exact zeros, so a packed document's
  contribution is bit-identical to the same document alone. ``None``
  keeps the exact unweighted program (the pinned fused-head oracle).
  """
  labels = labels.astype(jnp.int32)
  b, t, _ = hidden.shape
  chunk = chunk_of(t, chunk_size)
  hc = _chunked(hidden, chunk)
  yc = _chunked(labels, chunk)
  wc = None if weights is None else _chunked(
      weights.astype(jnp.float32), chunk)

  @jax.checkpoint
  def body(carry, xs):
    hh, yy, ww = xs
    # Per-chunk head matmul: rows of the monolithic logits, bit-exact
    # (matmul output rows depend only on their own input rows).
    lg = hh @ kernel.astype(hh.dtype)
    logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, yy[..., None], axis=-1)
    if ww is not None:
      ll = ll * ww[..., None]
    return carry + jnp.sum(ll), None

  # Inside a shard_map body the hidden states are device-varying, so the
  # carry must be pcast to match (no-op on pre-vma jax; sequence.py).
  (zero,) = sequence_lib.vary_like(hidden,
                                   (jnp.zeros((), jnp.float32),))
  total, _ = jax.lax.scan(body, zero, (hc, yc, wc))
  if weights is None:
    return -total / (b * t)
  return -total / jnp.maximum(jnp.sum(weights.astype(jnp.float32)), 1.0)


def fused_top_k_accuracy(hidden, kernel, labels, chunk_size: int = 256,
                         weights=None):
  """top-1/top-5 fractions from (hidden, kernel), chunk at a time.

  argmax/top_k reduce away the vocab axis inside the scan, so the live
  set per iteration is one (B, chunk, V) logits slice -- no f32 upcast
  is needed for an order statistic, matching the Dense-head accuracy
  path's dtype behavior. ``weights`` (B, T): packed-sequence masking --
  hits are weighted and the fractions normalize by the real-token count
  (see ``fused_softmax_xent``).
  """
  labels = labels.astype(jnp.int32)
  b, t, _ = hidden.shape
  chunk = chunk_of(t, chunk_size)
  hc = _chunked(hidden, chunk)
  yc = _chunked(labels, chunk)
  wc = None if weights is None else _chunked(
      weights.astype(jnp.float32), chunk)

  def body(carry, xs):
    hh, yy, ww = xs
    lg = hh @ kernel.astype(hh.dtype)
    hit1 = (jnp.argmax(lg, -1) == yy).astype(jnp.float32)
    hit5 = jnp.any(jax.lax.top_k(lg, 5)[1] == yy[..., None],
                   axis=-1).astype(jnp.float32)
    if ww is not None:
      hit1 = hit1 * ww
      hit5 = hit5 * ww
    c1, c5 = carry
    return (c1 + jnp.sum(hit1), c5 + jnp.sum(hit5)), None

  zeros = sequence_lib.vary_like(
      hidden, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)))
  (n1, n5), _ = jax.lax.scan(body, tuple(zeros), (hc, yc, wc))
  denom = (jnp.float32(b * t) if weights is None else
           jnp.maximum(jnp.sum(weights.astype(jnp.float32)), 1.0))
  return {"top_1_accuracy": n1 / denom, "top_5_accuracy": n5 / denom}


def monolithic_softmax_xent(hidden, kernel, labels,
                            chunk_size: int = 256):
  """The memory-unbounded oracle: materialize the FULL (B, T, V) logits
  tensor, then reduce in the same chunk order as the fused scan.

  Built from per-chunk matmuls concatenated into the full tensor so the
  backward pass accumulates the kernel gradient chunk-by-chunk in the
  same order as the scan transpose -- which is what makes the fused
  head's f32 gradients BIT-exact against it, not merely close
  (tests/test_fused_loss.py pins this). Peak memory is O(B*T*V): tests
  compile it to measure the logits-sized footprint the fused path
  eliminates.
  """
  labels = labels.astype(jnp.int32)
  b, t, _ = hidden.shape
  chunk = chunk_of(t, chunk_size)
  n = t // chunk
  logits = jnp.concatenate(
      [hidden[:, i * chunk:(i + 1) * chunk] @ kernel.astype(hidden.dtype)
       for i in range(n)], axis=1)
  total = jnp.zeros((), jnp.float32)
  for i in range(n):
    lg = logits[:, i * chunk:(i + 1) * chunk]
    yy = labels[:, i * chunk:(i + 1) * chunk]
    logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
    total = total + jnp.sum(
        jnp.take_along_axis(logp, yy[..., None], axis=-1))
  return -total / (b * t)
