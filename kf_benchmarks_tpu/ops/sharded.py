"""ZeRO-style sharded optimizer state: scatter / shard / gather helpers.

The TPU reformulation of the reference's central variable placement
(parameter_server / distributed_replicated variable placement,
ref: variable_mgr.py:201-243, :704-831; SURVEY 5.8): instead of a host
process owning the "server copy" of the variables and optimizer slots,
each device owns a flat 1/n shard of them (Rajbhandari et al., ZeRO),
and the collectives the graph-mode PS expressed as send/recv become
compiler-scheduled reduce-scatter / all-gather on the named 2-D
``('batch', 'model')`` mesh (parallel/mesh.py build_mesh_2d) -- the
GSPMD pattern (Xu et al. 2021).

Layout contract (everything here depends on it):

* A leaf of ``size`` elements pads with zeros to ``n * k`` where
  ``k = ceil(size / n)`` and ``n`` is the TOTAL device count; flat
  block ``i`` belongs to the device with flat shard index
  ``i = axis_index('batch') * M + axis_index('model')`` -- row-major
  over the mesh, the order a tiled ``all_gather(('batch', 'model'))``
  concatenates in.
* The gradient mean reduce-scatters over the ``'batch'`` axis ONLY
  (model-axis peers hold the same batch shard and the same fold_in rng,
  so their local gradients are identical by construction): the
  summation meets the same ``B`` distinct contributions in the same
  group order as the replicated path's all-reduce, which is what makes
  the scattered mean BIT-IDENTICAL to the ``pmean`` it replaces
  (pinned in tests/test_sharded_optimizer.py). The model-axis split of
  the batch-block is then a free local slice.
* Optimizer updates on the zero-padded tail are harmless: gradients
  there are exactly zero (pad-in, sum-of-zeros out), every stock
  optimizer maps (g=0, state=0) to update 0, and the tail is dropped at
  gather time regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kf_benchmarks_tpu.parallel.mesh import BATCH_AXIS, MODEL_AXIS


def shard_len(size: int, num_shards: int) -> int:
  """Per-device flat shard length: ceil(size / num_shards)."""
  return -(-size // num_shards)


def _pad_flat(x, num_shards: int):
  k = shard_len(x.size, num_shards)
  flat = jnp.ravel(x)
  return jnp.pad(flat, (0, num_shards * k - x.size)), k


def stacked_shards(tree, num_shards: int):
  """Full tree -> host-global stacked shard tree: each leaf flattened,
  zero-padded and reshaped ``(n, k)`` so row ``i`` is device ``i``'s
  shard. Global memory stays ~|leaf| (one padded copy, no n-fold
  stacking); sharding row 0 over the mesh axes puts exactly one row on
  each device. This is the layout ``TrainState.opt_state`` carries
  under --shard_optimizer_state (train_step.py)."""
  def f(x):
    flat, k = _pad_flat(x, num_shards)
    return flat.reshape(num_shards, k)
  return jax.tree.map(f, tree)


def scatter_mean(grads, batch_axis: str = BATCH_AXIS,
                 model_axis: str = MODEL_AXIS):
  """Local full-gradient tree -> this device's flat mean-shard.

  Reduce-scatter of the batch-axis mean (wire: ``(B-1)/B * |grads|``
  per device instead of the all-reduce's ``2(n-1)/n``), then the free
  model-axis sub-slice. Runs inside the shard_mapped step body."""
  nb = lax.axis_size(batch_axis)
  nm = lax.axis_size(model_axis)
  n = nb * nm
  mi = lax.axis_index(model_axis)

  def f(x):
    flat, k = _pad_flat(x, n)
    # Each batch group's scatter meets B distinct contributions in
    # group order -- the same association as the replicated pmean.
    block = lax.psum_scatter(flat, batch_axis, tiled=True) / nb
    return lax.dynamic_slice(block, (mi * k,), (k,))
  return jax.tree.map(f, grads)


def local_shards(tree, batch_axis: str = BATCH_AXIS,
                 model_axis: str = MODEL_AXIS):
  """Full (replica-identical) tree -> this device's flat shard by local
  slice -- no collective: every device already holds the whole value."""
  nb = lax.axis_size(batch_axis)
  nm = lax.axis_size(model_axis)
  n = nb * nm
  idx = lax.axis_index(batch_axis) * nm + lax.axis_index(model_axis)

  def f(x):
    flat, k = _pad_flat(x, n)
    return lax.dynamic_slice(flat, (idx * k,), (k,))
  return jax.tree.map(f, tree)


def combined_all_gather(x, batch_axis: str = BATCH_AXIS,
                        model_axis: str = MODEL_AXIS, axis: int = 0,
                        nested: bool = False):
  """Tiled all-gather over the combined ``(batch, model)`` axes.

  ``nested=False`` is the manual-path form: ONE collective over the
  axes tuple (every existing golden contract pins this inventory).
  ``nested=True`` decomposes it into model-then-batch single-axis
  tiled gathers -- element-identical (inner gather tiles the model
  peers, outer gather tiles the batch groups, reproducing the
  row-major ``b * M + m`` concatenation order exactly) but required on
  the --partitioner=gspmd path: jax 0.4.x has no vmap batching rule
  for a tuple-axis all_gather, and the gspmd twin traces the step body
  under double ``jax.vmap`` (train_step.py)."""
  if not nested:
    return lax.all_gather(x, (batch_axis, model_axis), axis=axis,
                          tiled=True)
  inner = lax.all_gather(x, model_axis, axis=axis, tiled=True)
  return lax.all_gather(inner, batch_axis, axis=axis, tiled=True)


def gather_tree(shards, template, batch_axis: str = BATCH_AXIS,
                model_axis: str = MODEL_AXIS, nested: bool = False):
  """Flat shard tree -> full tree: tiled all-gather over the combined
  ``(batch, model)`` axes (row-major concatenation matches the
  scatter/slice block order), drop the pad, restore leaf shapes.
  ``nested`` selects the vmap-safe decomposed gather (see
  :func:`combined_all_gather`) for the gspmd twin."""
  def f(s, t):
    full = combined_all_gather(s, batch_axis, model_axis, nested=nested)
    return full[:t.size].reshape(t.shape).astype(t.dtype)
  return jax.tree.map(f, shards, template)


# -- FSDP parameter layout (--shard_params) ----------------------------------
#
# The round-11 layout above, applied to the PARAMETER tree itself
# (Rajbhandari et al. ZeRO-3 / the SNIPPETS.md [3] "shard W along the
# model axis" pattern): params live as shards between steps, the step
# re-assembles them per bucket / per scanned block INSIDE the
# forward/backward (ops/overlap.py gather_params), and the optimizer
# applies on the shard -- no full tree ever materializes, and the
# round-11 trailing all-gather disappears from the steady state.
#
# Two leaf families, so the scanned transformer can gather ONE block at
# a time:
#
# * non-scanned leaf (*s):        (n, k),    k = ceil(prod(s) / n)
# * scanned-prefix leaf (L, *s):  (n, L, k), k = ceil(prod(s) / n)
#   -- the (n, k) stacking applied PER LAYER, transposed so the shard
#   row leads uniformly: the whole TrainState keeps one leading
#   stacked-device dim (P over the combined mesh axes), and the
#   nn.scan/lax.scan bodies slice layer l's local shard as row l of the
#   squeezed (L, k) view.


def top_level_key(path) -> str:
  """Top-level pytree key of a jax key path (builder-layer / scanned-
  stack granularity; the same convention as ops/overlap.py bucketing)."""
  if not path:
    return ""
  p = path[0]
  return str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))


def _leaf_map(tree, scanned_prefixes, f_plain, f_scanned):
  def f(path, leaf):
    if top_level_key(path) in scanned_prefixes:
      return f_scanned(leaf)
    return f_plain(leaf)
  return jax.tree_util.tree_map_with_path(f, tree)


def fsdp_stacked_shards(tree, num_shards: int, scanned_prefixes=()):
  """Full param tree -> host-global FSDP shard stacks (see module
  notes): sharding the leading dim over the combined mesh axes puts
  exactly this device's flat shard (rows of every layer, for scanned
  leaves) on each device."""
  def plain(x):
    flat, k = _pad_flat(x, num_shards)
    return flat.reshape(num_shards, k)

  def scanned(x):
    if x.ndim < 1:
      raise ValueError(
          "scanned-prefix FSDP leaves need a leading layer axis; got a "
          f"scalar leaf of shape {tuple(x.shape)}")
    n_layers = x.shape[0]
    size = int(x.size) // n_layers
    k = shard_len(size, num_shards)
    flat = x.reshape(n_layers, size)
    flat = jnp.pad(flat, ((0, 0), (0, num_shards * k - size)))
    # (L, n, k) -> (n, L, k): shard row leads, like every other leaf.
    return jnp.moveaxis(flat.reshape(n_layers, num_shards, k), 1, 0)

  return _leaf_map(tree, scanned_prefixes, plain, scanned)


def fsdp_gather_full(local, template, scanned_prefixes=(),
                     batch_axis: str = BATCH_AXIS,
                     model_axis: str = MODEL_AXIS, nested: bool = False):
  """Local FSDP shard tree (leaves (k,) / (L, k), i.e. the squeezed
  per-device rows) -> the FULL tree, inside the shard_mapped body.

  The whole-tree re-assembly: the eval step and the --num_grad_accum
  path use it (the accumulated-gradient path keeps the full tree
  resident for the microbatch scan, exactly like the round-11 steady
  state -- the in-compute per-bucket gathers disengage there the same
  way the overlap hooks do). ``nested`` selects the vmap-safe
  decomposed gather (:func:`combined_all_gather`) for the gspmd twin."""
  def plain(s, t):
    full = combined_all_gather(s, batch_axis, model_axis, nested=nested)
    return full[:t.size].reshape(t.shape).astype(t.dtype)

  def scanned(s, t):
    size = int(np.prod(t.shape[1:], dtype=np.int64)) if t.ndim > 1 else 1
    full = combined_all_gather(s, batch_axis, model_axis, axis=1,
                               nested=nested)  # (L, n*k)
    return full[:, :size].reshape(t.shape).astype(t.dtype)

  by_path = dict(jax.tree_util.tree_flatten_with_path(template)[0])

  def f(path, s):
    t = by_path[tuple(path)]
    if top_level_key(path) in scanned_prefixes:
      return scanned(s, t)
    return plain(s, t)
  return jax.tree_util.tree_map_with_path(f, local)


def fsdp_scatter_mean(grads, scanned_prefixes=(),
                      batch_axis: str = BATCH_AXIS,
                      model_axis: str = MODEL_AXIS):
  """Full local gradient tree -> this device's FSDP-layout mean shards
  (the post-hoc scatter of the accumulated-gradient path).

  Per element this is EXACTLY :func:`scatter_mean` -- the batch-axis
  psum_scatter meets the same B contributions in the same group order,
  then the free model sub-slice -- only the shard ADDRESSING differs
  (per-layer rows for scanned leaves), so the elementwise optimizer
  sees bit-identical values in either layout."""
  nb = lax.axis_size(batch_axis)
  nm = lax.axis_size(model_axis)
  n = nb * nm
  mi = lax.axis_index(model_axis)

  def plain(x):
    flat, k = _pad_flat(x, n)
    block = lax.psum_scatter(flat, batch_axis, tiled=True) / nb
    return lax.dynamic_slice(block, (mi * k,), (k,))

  def scanned(x):
    n_layers = x.shape[0]
    size = int(x.size) // n_layers
    k = shard_len(size, n)
    flat = jnp.pad(x.reshape(n_layers, size),
                   ((0, 0), (0, n * k - size)))
    block = lax.psum_scatter(flat, batch_axis, scatter_dimension=1,
                             tiled=True) / nb  # (L, nm * k)
    return lax.dynamic_slice(block, (0, mi * k), (n_layers, k))

  return _leaf_map(grads, scanned_prefixes, plain, scanned)


def fsdp_param_bytes(template) -> int:
  """Full-tree parameter bytes of a (possibly abstract) template --
  the denominator of the residency contract (analysis/audit.py
  rule_fsdp_residency)."""
  total = 0
  for leaf in jax.tree.leaves(template):
    total += int(np.prod(leaf.shape, dtype=np.int64)) * jnp.dtype(
        leaf.dtype).itemsize
  return total
