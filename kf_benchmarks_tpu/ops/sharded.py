"""ZeRO-style sharded optimizer state: scatter / shard / gather helpers.

The TPU reformulation of the reference's central variable placement
(parameter_server / distributed_replicated variable placement,
ref: variable_mgr.py:201-243, :704-831; SURVEY 5.8): instead of a host
process owning the "server copy" of the variables and optimizer slots,
each device owns a flat 1/n shard of them (Rajbhandari et al., ZeRO),
and the collectives the graph-mode PS expressed as send/recv become
compiler-scheduled reduce-scatter / all-gather on the named 2-D
``('batch', 'model')`` mesh (parallel/mesh.py build_mesh_2d) -- the
GSPMD pattern (Xu et al. 2021).

Layout contract (everything here depends on it):

* A leaf of ``size`` elements pads with zeros to ``n * k`` where
  ``k = ceil(size / n)`` and ``n`` is the TOTAL device count; flat
  block ``i`` belongs to the device with flat shard index
  ``i = axis_index('batch') * M + axis_index('model')`` -- row-major
  over the mesh, the order a tiled ``all_gather(('batch', 'model'))``
  concatenates in.
* The gradient mean reduce-scatters over the ``'batch'`` axis ONLY
  (model-axis peers hold the same batch shard and the same fold_in rng,
  so their local gradients are identical by construction): the
  summation meets the same ``B`` distinct contributions in the same
  group order as the replicated path's all-reduce, which is what makes
  the scattered mean BIT-IDENTICAL to the ``pmean`` it replaces
  (pinned in tests/test_sharded_optimizer.py). The model-axis split of
  the batch-block is then a free local slice.
* Optimizer updates on the zero-padded tail are harmless: gradients
  there are exactly zero (pad-in, sum-of-zeros out), every stock
  optimizer maps (g=0, state=0) to update 0, and the tail is dropped at
  gather time regardless.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.parallel.mesh import BATCH_AXIS, MODEL_AXIS


def shard_len(size: int, num_shards: int) -> int:
  """Per-device flat shard length: ceil(size / num_shards)."""
  return -(-size // num_shards)


def _pad_flat(x, num_shards: int):
  k = shard_len(x.size, num_shards)
  flat = jnp.ravel(x)
  return jnp.pad(flat, (0, num_shards * k - x.size)), k


def stacked_shards(tree, num_shards: int):
  """Full tree -> host-global stacked shard tree: each leaf flattened,
  zero-padded and reshaped ``(n, k)`` so row ``i`` is device ``i``'s
  shard. Global memory stays ~|leaf| (one padded copy, no n-fold
  stacking); sharding row 0 over the mesh axes puts exactly one row on
  each device. This is the layout ``TrainState.opt_state`` carries
  under --shard_optimizer_state (train_step.py)."""
  def f(x):
    flat, k = _pad_flat(x, num_shards)
    return flat.reshape(num_shards, k)
  return jax.tree.map(f, tree)


def scatter_mean(grads, batch_axis: str = BATCH_AXIS,
                 model_axis: str = MODEL_AXIS):
  """Local full-gradient tree -> this device's flat mean-shard.

  Reduce-scatter of the batch-axis mean (wire: ``(B-1)/B * |grads|``
  per device instead of the all-reduce's ``2(n-1)/n``), then the free
  model-axis sub-slice. Runs inside the shard_mapped step body."""
  nb = lax.axis_size(batch_axis)
  nm = lax.axis_size(model_axis)
  n = nb * nm
  mi = lax.axis_index(model_axis)

  def f(x):
    flat, k = _pad_flat(x, n)
    # Each batch group's scatter meets B distinct contributions in
    # group order -- the same association as the replicated pmean.
    block = lax.psum_scatter(flat, batch_axis, tiled=True) / nb
    return lax.dynamic_slice(block, (mi * k,), (k,))
  return jax.tree.map(f, grads)


def local_shards(tree, batch_axis: str = BATCH_AXIS,
                 model_axis: str = MODEL_AXIS):
  """Full (replica-identical) tree -> this device's flat shard by local
  slice -- no collective: every device already holds the whole value."""
  nb = lax.axis_size(batch_axis)
  nm = lax.axis_size(model_axis)
  n = nb * nm
  idx = lax.axis_index(batch_axis) * nm + lax.axis_index(model_axis)

  def f(x):
    flat, k = _pad_flat(x, n)
    return lax.dynamic_slice(flat, (idx * k,), (k,))
  return jax.tree.map(f, tree)


def gather_tree(shards, template, batch_axis: str = BATCH_AXIS,
                model_axis: str = MODEL_AXIS):
  """Flat shard tree -> full tree: tiled all-gather over the combined
  ``(batch, model)`` axes (row-major concatenation matches the
  scatter/slice block order), drop the pad, restore leaf shapes."""
  axes = (batch_axis, model_axis)

  def f(s, t):
    full = lax.all_gather(s, axes, tiled=True)
    return full[:t.size].reshape(t.shape).astype(t.dtype)
  return jax.tree.map(f, shards, template)
