"""Collective-communication ops: spec parsing, packing, reduction planning
(ref: scripts/tf_cnn_benchmarks/allreduce.py, batch_allreduce.py)."""
