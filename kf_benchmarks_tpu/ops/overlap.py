"""Overlapped gradient reduction: bucketed in-backward all-reduce.

The reference's batched-collective layer exists to PIPELINE gradient
reduction against compute: chunked collectives "let XLA overlap"
transfers and ``--gradient_repacking`` re-shapes reduction granularity
away from tensor boundaries for exactly that reason (ref:
batch_allreduce.py:391-481 _TensorPacker; our port notes the intent at
ops/allreduce.py repack_reduce). The rebuild's post-hoc reduction --
one pass over the whole gradient tree AFTER the backward finishes
(train_step.py) -- preserves the tuning surface but serializes
communication strictly after compute.

This module restores the pipelining, TPU-natively
(``--overlap_gradient_reduction``):

* **Bucket scheduler**: gradient leaves are grouped at builder-layer
  granularity (top-level param-tree key) and merged into size-bounded
  buckets (``--reduce_bucket_mb``; allreduce.plan_size_buckets). Each
  bucket reduces as ONE packed collective (allreduce.pack_tensors /
  unpack_tensors -- the same pack metadata the post-hoc paths use), so
  the compiled program carries one collective per bucket instead of a
  single trailing fused reduction.

* **In-backward hooks**: each bucket's parameters pass through an
  identity-with-custom_vjp wrapper inside the loss function. The
  forward is the identity; the BACKWARD reduces the bucket's cotangent
  the moment it is complete -- at the point in the autodiff graph where
  that layer's backward finishes -- so layer L's gradients start
  reducing while layer L-1's backward is still running, and XLA's
  scheduler is free to interleave the collectives with the remaining
  backward compute. Applied per scanned block (models/transformer_lm.py
  nn.scan via nn.map_variables; parallel/transformer.py lax.scan body)
  the collective lands INSIDE the backward scan's while body -- one
  reduction per layer per backward iteration (tests pin this at the
  compiled-HLO level).

Numerics: pmean is elementwise across replicas, so packing, bucket
boundaries, and reduction placement never change values -- overlapped
gradients are BIT-IDENTICAL to the post-hoc path at the f32 wire dtype
(tests/test_overlap_reduction.py pins it on the 8-device mesh). With a
16-bit wire format (compact_gradient_transfer) the usual rounding
applies, as on the post-hoc paths.

Composition (validation.py enforces the exclusions):

* ``--num_grad_accum=M``: reduction stays POST-HOC on the accumulated
  tree -- one collective per step is a pinned invariant
  (tests/test_grad_accum.py HLO assertion); the hooks disengage.
* ``--steps_per_dispatch=K``: the hooks live inside the scanned step
  body; composes freely.
* auto loss scale: the finite-check runs on the reduced tree exactly
  as on the post-hoc path (the hooks reduce BEFORE the unscale, and
  pmean is linear in the scale).
* excluded: spec/repacking/small-grad/hierarchical reducers (each owns
  reduction granularity, ref: batch_allreduce.py:300-317 selects one
  algorithm), async-PS (consumes unaveraged per-replica gradients),
  gossip/independent modes (no reduction), and
  --track_grad_noise_scale (the estimator needs the pre-reduction
  per-replica gradients, which in-backward reduction never
  materializes).
"""

from __future__ import annotations

import functools
import math
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.ops import allreduce

# Default bucket bound. The reference's --gradient_repacking=8 on a
# ~100 MB ResNet-50 gradient vector works out to ~12 MB chunks; 4 MB
# keeps several buckets in flight on the smaller zoo members too while
# staying far above the per-collective latency floor.
DEFAULT_BUCKET_MB = 4


class OverlapSpec(NamedTuple):
  """Resolved --overlap_gradient_reduction configuration."""
  bucket_bytes: int
  compact_dtype: Optional[Any]  # 16-bit wire format, or None


def build(params) -> Optional[OverlapSpec]:
  """Flag-resolved overlap spec, or None when the mode is off.

  Callers decide engagement per composition rule (train_step.py
  disengages the hooks under --num_grad_accum; validation.py has
  already rejected the excluded reducer/strategy combinations)."""
  if not getattr(params, "overlap_gradient_reduction", False):
    return None
  mb = getattr(params, "reduce_bucket_mb", None) or DEFAULT_BUCKET_MB
  return OverlapSpec(
      bucket_bytes=int(mb) * 1024 * 1024,
      compact_dtype=allreduce.compact_wire_dtype(params))


# -- the identity-with-custom_vjp hook --------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def reduce_identity(reduce_fn, tree):
  """Identity on the forward; ``reduce_fn`` on the backward cotangent.

  The reduction runs at the exact point in the autodiff graph where
  ``tree``'s cotangent is complete, which for layer-local parameters is
  the moment that layer's backward finishes."""
  del reduce_fn
  return tree


def _reduce_identity_fwd(reduce_fn, tree):
  del reduce_fn
  return tree, None


def _reduce_identity_bwd(reduce_fn, _, cotangent):
  return (reduce_fn(cotangent),)


reduce_identity.defvjp(_reduce_identity_fwd, _reduce_identity_bwd)


# -- bucket reduction (one packed collective per bucket) --------------------

def packed_pmean(leaves: Sequence[jax.Array], axis_name,
                 compact_dtype=None):
  """Replica-mean of a leaf list as ONE collective: pack into a flat
  vector (allreduce.pack_tensors -- the post-hoc paths' pack metadata),
  optionally compact to the 16-bit wire format, pmean, unpack.

  pmean is elementwise, so at the f32 wire dtype this is bit-identical
  to per-leaf pmean regardless of packing."""
  leaves = list(leaves)
  if not leaves:
    return leaves
  vec, meta = allreduce.pack_tensors(leaves)
  orig = vec.dtype
  if compact_dtype is not None and vec.dtype != compact_dtype:
    vec = vec.astype(compact_dtype)
  vec = lax.pmean(vec, axis_name).astype(orig)
  return allreduce.unpack_tensors(vec, meta)


def _bucket_reduce_fn(axis_name, compact_dtype):
  def reduce_fn(cotangent):
    leaves, treedef = jax.tree_util.tree_flatten(cotangent)
    return jax.tree_util.tree_unflatten(
        treedef, packed_pmean(leaves, axis_name, compact_dtype))
  return reduce_fn


# -- bucket planning (builder-layer granularity, size-bounded) --------------

def _leaf_nbytes(leaf) -> int:
  return int(leaf.size) * jnp.dtype(leaf.dtype).itemsize


def _top_key(path) -> str:
  """Builder-layer granularity: the top-level param-tree key (flax
  modules name one submodule per builder layer: 'conv0', 'cell_1',
  'blocks', ...). Single-sourced in ops/sharded.py -- the FSDP layout
  and this bucketing must classify prefixes identically."""
  from kf_benchmarks_tpu.ops import sharded as sharded_lib
  return sharded_lib.top_level_key(path)


def plan_buckets(tree, bucket_bytes: int,
                 exclude_prefixes: Tuple[str, ...] = ()):
  """Group ``tree``'s leaves into size-bounded reduction buckets.

  Leaves group by top-level key (layer granularity), keeping
  tree-flatten order so adjacent layers share buckets; groups merge
  into buckets of at most ``bucket_bytes`` via
  allreduce.plan_size_buckets (a single oversized layer keeps its own
  bucket -- hook units cannot split below the leaf the cotangent
  arrives on). Leaves under ``exclude_prefixes`` (top-level keys whose
  gradients a module already reduces in-backward, e.g. the scanned
  'blocks' stack) are left out.

  Returns (buckets, excluded): lists of leaf-index lists / the excluded
  leaf indices.
  """
  flat = jax.tree_util.tree_flatten_with_path(tree)[0]
  groups = []  # (key, [leaf indices], nbytes) in flatten order
  excluded = []
  for idx, (path, leaf) in enumerate(flat):
    key = _top_key(path)
    if key in exclude_prefixes:
      excluded.append(idx)
      continue
    if groups and groups[-1][0] == key:
      groups[-1][1].append(idx)
      groups[-1][2] += _leaf_nbytes(leaf)
    else:
      groups.append([key, [idx], _leaf_nbytes(leaf)])
  merged = allreduce.plan_size_buckets([g[2] for g in groups],
                                       bucket_bytes)
  buckets = [[i for g in span for i in groups[g][1]] for span in merged]
  return buckets, excluded


def wrap_tree(tree, axis_name, bucket_bytes: int, compact_dtype=None,
              exclude_prefixes: Tuple[str, ...] = ()):
  """Pass each bucket of ``tree`` through :func:`reduce_identity`.

  Apply to the parameter tree at the top of the loss function (every
  parameter use must flow through the wrapped copy); the gradient
  returned by jax.grad is then already replica-reduced, one collective
  per bucket, each issued in-backward."""
  leaves, treedef = jax.tree_util.tree_flatten(tree)
  buckets, _ = plan_buckets(tree, bucket_bytes,
                            exclude_prefixes=exclude_prefixes)
  reduce_fn = _bucket_reduce_fn(axis_name, compact_dtype)
  out = list(leaves)
  for bucket in buckets:
    wrapped = reduce_identity(reduce_fn, tuple(leaves[i] for i in bucket))
    for i, leaf in zip(bucket, wrapped):
      out[i] = leaf
  return jax.tree_util.tree_unflatten(treedef, out)


# -- FSDP per-bucket parameter gather (--shard_params) -----------------------
#
# The gather-side twin of reduce_identity: a custom_vjp whose FORWARD
# re-assembles a bucket of parameter shards with ONE packed tiled
# all-gather and whose BACKWARD reduce-scatters the bucket's cotangent
# (batch-axis mean + free model sub-slice -- elementwise identical to
# ops/sharded.scatter_mean, see there for the bit-identity argument)
# back onto the shard layout. Placed per builder-layer bucket at the
# top of the loss (train_step.py) and per scanned block inside the
# nn.scan/lax.scan body (models/transformer_lm.py,
# parallel/transformer.py), the gather lands INSIDE the loop body with
# exactly one collective per bucket -- the same one-slot-ahead position
# the in-backward reduction hooks earn for the gradient collectives:
# block l+1's gather is issued while block l's compute is still in
# flight, and XLA's async collectives overlap the two
# (observability.collective_overlap_stats measures the in-loop
# fraction; experiments/fsdp_gather_probe.py reports it).


class FsdpGatherSpec(NamedTuple):
  """Static (hashable) half of a gather bucket: full leaf shapes in
  bucket order plus the mesh axes. The shard half is the runtime
  argument. ``nested`` selects the vmap-safe decomposed forward gather
  (ops/sharded.combined_all_gather) for the --partitioner=gspmd twin;
  the default single tuple-axis collective is the manual-path form the
  goldens pin."""
  batch_axis: str
  model_axis: str
  shapes: Tuple[Tuple[int, ...], ...]
  dtypes: Tuple[str, ...]
  nested: bool = False


def _fsdp_mesh(spec):
  nb = lax.axis_size(spec.batch_axis)
  nm = lax.axis_size(spec.model_axis)
  return nb, nm, nb * nm


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def gather_params(spec: FsdpGatherSpec, shards):
  """Tuple of flat local (k_i,) param shards -> tuple of FULL leaves.

  Forward: concat the bucket's shards, ONE tiled all-gather over the
  combined (batch, model) axes, split rows back per leaf (row-major
  device order matches the flat shard index, ops/sharded.py). Backward:
  the bucket's full-leaf cotangents pack into one (n, K) matrix and
  reduce-scatter as ONE collective (batch mean + model sub-slice),
  returning shard-layout cotangents bit-identical per element to the
  post-hoc ops/sharded.scatter_mean."""
  return _gather_fwd_impl(spec, shards)


# Shared packing primitives: BOTH FSDP gather hooks (this module's
# mesh-2-D gather_params and the composed trainer's
# parallel/transformer._fsdp_block_hook) build on these, so the row
# addressing and pad handling cannot drift between the two legs.

def packed_gather_rows(axes, shapes, dtypes, shards, nested=False):
  """Tuple of flat local (k_i,) shards -> tuple of FULL leaves via ONE
  tiled all-gather over ``axes``: concat the shards, gather, split the
  (n, K) row matrix back per leaf (row-major device order over the
  axes tuple matches the flat shard index). ``nested`` decomposes the
  tuple-axis gather into per-axis gathers (innermost first -- same
  row-major order) for the gspmd twin, whose double-vmap trace has no
  tuple-axis all_gather batching rule in jax 0.4.x."""
  n = math.prod(lax.axis_size(a) for a in axes)
  ks = tuple(int(s.shape[0]) for s in shards)
  vec = jnp.concatenate(list(shards)) if len(shards) > 1 else shards[0]
  if nested:
    full = vec
    for a in reversed(axes):
      full = lax.all_gather(full, a, tiled=True)
    mat = full.reshape(n, sum(ks))
  else:
    mat = lax.all_gather(vec, axes, tiled=True).reshape(n, sum(ks))
  outs, off = [], 0
  for k, shape, dtype in zip(ks, shapes, dtypes):
    size = int(math.prod(shape)) if shape else 1
    leaf = mat[:, off:off + k].reshape(n * k)[:size].reshape(shape)
    outs.append(leaf.astype(dtype))
    off += k
  return tuple(outs)


def pack_cotangent_rows(cots, shapes, n, common_dtype):
  """Full-leaf cotangents -> (the packed (n, K) row matrix, per-leaf
  shard lengths): each leaf flattens, zero-pads to n * k and lands as
  a k-wide column block, so row i of the matrix is device i's packed
  shard cotangent."""
  cols, ks = [], []
  for cot, shape in zip(cots, shapes):
    size = int(math.prod(shape)) if shape else 1
    k = -(-size // n)
    flat = jnp.ravel(cot).astype(common_dtype)
    cols.append(jnp.pad(flat, (0, n * k - size)).reshape(n, k))
    ks.append(k)
  mat = jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
  return mat, ks


def split_shard_row(row, ks, dtypes):
  """One packed (K,) shard row -> the per-leaf flat (k_i,) shards."""
  outs, off = [], 0
  for k, dtype in zip(ks, dtypes):
    outs.append(row[off:off + k].astype(dtype))
    off += k
  return tuple(outs)


def _gather_fwd_impl(spec, shards):
  return packed_gather_rows((spec.batch_axis, spec.model_axis),
                            spec.shapes, spec.dtypes, shards,
                            nested=spec.nested)


def _gather_params_fwd(spec, shards):
  # No residuals: the shard dtypes equal the full-leaf dtypes (the
  # storage is re-stacked from the full init), so spec carries all the
  # backward needs.
  return _gather_fwd_impl(spec, shards), None


def _gather_params_bwd(spec, _, cotangents):
  nb, nm, n = _fsdp_mesh(spec)
  mi = lax.axis_index(spec.model_axis)
  # The packed wire rides the bucket's own dtype (f32 for f32 params,
  # bf16 under --fp16_vars) -- same wire class as the post-hoc
  # scatter's per-leaf collectives.
  common = jnp.result_type(*spec.dtypes)
  mat, ks = pack_cotangent_rows(cotangents, spec.shapes, n, common)
  # ONE packed reduce-scatter: batch-group rows sum elementwise in the
  # same order as the per-leaf scatter, so packing changes no values.
  rows = lax.psum_scatter(mat, spec.batch_axis, scatter_dimension=0,
                          tiled=True) / nb  # (nm, K)
  row = lax.dynamic_slice_in_dim(rows, mi, 1, axis=0)[0]
  return (split_shard_row(row, ks, spec.dtypes),)


gather_params.defvjp(_gather_params_fwd, _gather_params_bwd)


def _template_nbytes(leaf) -> int:
  shape = tuple(leaf.shape)
  return (int(math.prod(shape)) if shape else 1) * jnp.dtype(
      leaf.dtype).itemsize


def fsdp_plan_buckets(template, bucket_bytes: int,
                      exclude_prefixes: Tuple[str, ...] = ()):
  """Gather buckets over the FULL-shape template: builder-layer
  granularity merged under ``bucket_bytes``, exactly the
  :func:`plan_buckets` scheduler (leaf sizes read from the template --
  the shards are uniformly flat). Returns (buckets, excluded) as leaf
  index lists in template flatten order."""
  flat = jax.tree_util.tree_flatten_with_path(template)[0]
  groups, excluded = [], []
  for idx, (path, leaf) in enumerate(flat):
    key = _top_key(path)
    if key in exclude_prefixes:
      excluded.append(idx)
      continue
    if groups and groups[-1][0] == key:
      groups[-1][1].append(idx)
      groups[-1][2] += _template_nbytes(leaf)
    else:
      groups.append([key, [idx], _template_nbytes(leaf)])
  merged = allreduce.plan_size_buckets([g[2] for g in groups],
                                       bucket_bytes)
  buckets = [[i for g in span for i in groups[g][1]] for span in merged]
  return buckets, excluded


def fsdp_wrap_shards(shard_tree, template, bucket_bytes: int,
                     batch_axis, model_axis,
                     exclude_prefixes: Tuple[str, ...] = (),
                     nested: bool = False):
  """Shard-layout param tree -> the tree the loss consumes: every
  non-excluded leaf replaced by its gathered FULL value (one
  :func:`gather_params` per builder-layer bucket), excluded
  (module-gathered scanned-stack) leaves passed through as shards for
  the per-block hooks inside the scan body.

  The returned tree is what jax.grad differentiates: gradients arrive
  already reduce-scattered onto the shard layout, one collective per
  bucket, each issued at the point in the backward where that bucket's
  cotangent completes."""
  leaves, treedef = jax.tree_util.tree_flatten(shard_tree)
  t_leaves = jax.tree_util.tree_flatten(template)[0]
  buckets, _ = fsdp_plan_buckets(template, bucket_bytes,
                                 exclude_prefixes=exclude_prefixes)
  out = list(leaves)
  for bucket in buckets:
    spec = FsdpGatherSpec(
        batch_axis=batch_axis, model_axis=model_axis,
        shapes=tuple(tuple(t_leaves[i].shape) for i in bucket),
        dtypes=tuple(jnp.dtype(t_leaves[i].dtype).name for i in bucket),
        nested=nested)
    full = gather_params(spec, tuple(leaves[i] for i in bucket))
    for i, leaf in zip(bucket, full):
      out[i] = leaf
  return jax.tree_util.tree_unflatten(treedef, out)


def fsdp_block_gatherer(block_template, batch_axis, model_axis,
                        nested: bool = False):
  """Per-scanned-block gather hook (``nn.map_variables(...,
  trans_in_fn=hook, init=True)`` under nn.scan, or applied to the
  sliced xs at the top of a lax.scan body): stored per-block flat
  shards -> the block's full param tree via ONE packed gather, whose
  backward reduce-scatters the block's cotangent INSIDE the backward
  scan iteration.

  Init never gathers: at init time flax routes the EMPTY pre-creation
  store through trans_in_fn (passed through below), the module creates
  params at FULL shapes (no collective can run under plain jit init),
  and the identity trans_out stores them full; the step's init_state
  then re-stacks the whole tree into the shard layout host-side
  (ops/sharded.fsdp_stacked_shards)."""
  t_leaves, t_def = jax.tree_util.tree_flatten(block_template)
  spec = FsdpGatherSpec(
      batch_axis=batch_axis, model_axis=model_axis,
      shapes=tuple(tuple(t.shape) for t in t_leaves),
      dtypes=tuple(jnp.dtype(t.dtype).name for t in t_leaves),
      nested=nested)

  def hook(stored):
    leaves, treedef = jax.tree_util.tree_flatten(stored)
    if not leaves:
      # Init, first trace: the EMPTY pre-creation store routes through
      # trans_in_fn; pass it through so the module creates its
      # full-shape params.
      return stored
    if tuple(tuple(l.shape) for l in leaves) == spec.shapes:
      # Init, re-trace: flax's scan re-runs the body with the params
      # it just created -- still FULL shapes (init runs under plain
      # jit, before init_state re-stacks to shards; no mesh axis is
      # bound there). Statically distinguishable from the apply path,
      # whose stored leaves are flat (k,) shards.
      return stored
    if len(leaves) != len(t_leaves):
      raise ValueError(
          f"FSDP block gather: stored block has {len(leaves)} leaves, "
          f"template has {len(t_leaves)} -- the module structure "
          "drifted from the template built at construction time")
    full = gather_params(spec, tuple(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(full))

  return hook


def scan_block_hook(axis_name, compact_dtype=None):
  """Per-scanned-block hook: wrap one layer's parameter slice as a
  single bucket.

  Use as ``nn.map_variables(Block, "params", trans_in_fn=hook,
  init=True)`` under nn.scan (models/transformer_lm.py) or applied to
  the carry-free xs slice at the top of a lax.scan body
  (parallel/transformer.py). Each backward scan iteration then issues
  that layer's reduction INSIDE the loop body, interleaved with the
  next iteration's backward compute."""
  reduce_fn = _bucket_reduce_fn(axis_name, compact_dtype)

  def hook(block_params):
    return reduce_identity(reduce_fn, block_params)

  return hook
