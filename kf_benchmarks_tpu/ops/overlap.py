"""Overlapped gradient reduction: bucketed in-backward all-reduce.

The reference's batched-collective layer exists to PIPELINE gradient
reduction against compute: chunked collectives "let XLA overlap"
transfers and ``--gradient_repacking`` re-shapes reduction granularity
away from tensor boundaries for exactly that reason (ref:
batch_allreduce.py:391-481 _TensorPacker; our port notes the intent at
ops/allreduce.py repack_reduce). The rebuild's post-hoc reduction --
one pass over the whole gradient tree AFTER the backward finishes
(train_step.py) -- preserves the tuning surface but serializes
communication strictly after compute.

This module restores the pipelining, TPU-natively
(``--overlap_gradient_reduction``):

* **Bucket scheduler**: gradient leaves are grouped at builder-layer
  granularity (top-level param-tree key) and merged into size-bounded
  buckets (``--reduce_bucket_mb``; allreduce.plan_size_buckets). Each
  bucket reduces as ONE packed collective (allreduce.pack_tensors /
  unpack_tensors -- the same pack metadata the post-hoc paths use), so
  the compiled program carries one collective per bucket instead of a
  single trailing fused reduction.

* **In-backward hooks**: each bucket's parameters pass through an
  identity-with-custom_vjp wrapper inside the loss function. The
  forward is the identity; the BACKWARD reduces the bucket's cotangent
  the moment it is complete -- at the point in the autodiff graph where
  that layer's backward finishes -- so layer L's gradients start
  reducing while layer L-1's backward is still running, and XLA's
  scheduler is free to interleave the collectives with the remaining
  backward compute. Applied per scanned block (models/transformer_lm.py
  nn.scan via nn.map_variables; parallel/transformer.py lax.scan body)
  the collective lands INSIDE the backward scan's while body -- one
  reduction per layer per backward iteration (tests pin this at the
  compiled-HLO level).

Numerics: pmean is elementwise across replicas, so packing, bucket
boundaries, and reduction placement never change values -- overlapped
gradients are BIT-IDENTICAL to the post-hoc path at the f32 wire dtype
(tests/test_overlap_reduction.py pins it on the 8-device mesh). With a
16-bit wire format (compact_gradient_transfer) the usual rounding
applies, as on the post-hoc paths.

Composition (validation.py enforces the exclusions):

* ``--num_grad_accum=M``: reduction stays POST-HOC on the accumulated
  tree -- one collective per step is a pinned invariant
  (tests/test_grad_accum.py HLO assertion); the hooks disengage.
* ``--steps_per_dispatch=K``: the hooks live inside the scanned step
  body; composes freely.
* auto loss scale: the finite-check runs on the reduced tree exactly
  as on the post-hoc path (the hooks reduce BEFORE the unscale, and
  pmean is linear in the scale).
* excluded: spec/repacking/small-grad/hierarchical reducers (each owns
  reduction granularity, ref: batch_allreduce.py:300-317 selects one
  algorithm), async-PS (consumes unaveraged per-replica gradients),
  gossip/independent modes (no reduction), and
  --track_grad_noise_scale (the estimator needs the pre-reduction
  per-replica gradients, which in-backward reduction never
  materializes).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.ops import allreduce

# Default bucket bound. The reference's --gradient_repacking=8 on a
# ~100 MB ResNet-50 gradient vector works out to ~12 MB chunks; 4 MB
# keeps several buckets in flight on the smaller zoo members too while
# staying far above the per-collective latency floor.
DEFAULT_BUCKET_MB = 4


class OverlapSpec(NamedTuple):
  """Resolved --overlap_gradient_reduction configuration."""
  bucket_bytes: int
  compact_dtype: Optional[Any]  # 16-bit wire format, or None


def build(params) -> Optional[OverlapSpec]:
  """Flag-resolved overlap spec, or None when the mode is off.

  Callers decide engagement per composition rule (train_step.py
  disengages the hooks under --num_grad_accum; validation.py has
  already rejected the excluded reducer/strategy combinations)."""
  if not getattr(params, "overlap_gradient_reduction", False):
    return None
  mb = getattr(params, "reduce_bucket_mb", None) or DEFAULT_BUCKET_MB
  return OverlapSpec(
      bucket_bytes=int(mb) * 1024 * 1024,
      compact_dtype=allreduce.compact_wire_dtype(params))


# -- the identity-with-custom_vjp hook --------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def reduce_identity(reduce_fn, tree):
  """Identity on the forward; ``reduce_fn`` on the backward cotangent.

  The reduction runs at the exact point in the autodiff graph where
  ``tree``'s cotangent is complete, which for layer-local parameters is
  the moment that layer's backward finishes."""
  del reduce_fn
  return tree


def _reduce_identity_fwd(reduce_fn, tree):
  del reduce_fn
  return tree, None


def _reduce_identity_bwd(reduce_fn, _, cotangent):
  return (reduce_fn(cotangent),)


reduce_identity.defvjp(_reduce_identity_fwd, _reduce_identity_bwd)


# -- bucket reduction (one packed collective per bucket) --------------------

def packed_pmean(leaves: Sequence[jax.Array], axis_name,
                 compact_dtype=None):
  """Replica-mean of a leaf list as ONE collective: pack into a flat
  vector (allreduce.pack_tensors -- the post-hoc paths' pack metadata),
  optionally compact to the 16-bit wire format, pmean, unpack.

  pmean is elementwise, so at the f32 wire dtype this is bit-identical
  to per-leaf pmean regardless of packing."""
  leaves = list(leaves)
  if not leaves:
    return leaves
  vec, meta = allreduce.pack_tensors(leaves)
  orig = vec.dtype
  if compact_dtype is not None and vec.dtype != compact_dtype:
    vec = vec.astype(compact_dtype)
  vec = lax.pmean(vec, axis_name).astype(orig)
  return allreduce.unpack_tensors(vec, meta)


def _bucket_reduce_fn(axis_name, compact_dtype):
  def reduce_fn(cotangent):
    leaves, treedef = jax.tree_util.tree_flatten(cotangent)
    return jax.tree_util.tree_unflatten(
        treedef, packed_pmean(leaves, axis_name, compact_dtype))
  return reduce_fn


# -- bucket planning (builder-layer granularity, size-bounded) --------------

def _leaf_nbytes(leaf) -> int:
  return int(leaf.size) * jnp.dtype(leaf.dtype).itemsize


def _top_key(path) -> str:
  """Builder-layer granularity: the top-level param-tree key (flax
  modules name one submodule per builder layer: 'conv0', 'cell_1',
  'blocks', ...)."""
  if not path:
    return ""
  p = path[0]
  return str(getattr(p, "key", getattr(p, "name", getattr(p, "idx", p))))


def plan_buckets(tree, bucket_bytes: int,
                 exclude_prefixes: Tuple[str, ...] = ()):
  """Group ``tree``'s leaves into size-bounded reduction buckets.

  Leaves group by top-level key (layer granularity), keeping
  tree-flatten order so adjacent layers share buckets; groups merge
  into buckets of at most ``bucket_bytes`` via
  allreduce.plan_size_buckets (a single oversized layer keeps its own
  bucket -- hook units cannot split below the leaf the cotangent
  arrives on). Leaves under ``exclude_prefixes`` (top-level keys whose
  gradients a module already reduces in-backward, e.g. the scanned
  'blocks' stack) are left out.

  Returns (buckets, excluded): lists of leaf-index lists / the excluded
  leaf indices.
  """
  flat = jax.tree_util.tree_flatten_with_path(tree)[0]
  groups = []  # (key, [leaf indices], nbytes) in flatten order
  excluded = []
  for idx, (path, leaf) in enumerate(flat):
    key = _top_key(path)
    if key in exclude_prefixes:
      excluded.append(idx)
      continue
    if groups and groups[-1][0] == key:
      groups[-1][1].append(idx)
      groups[-1][2] += _leaf_nbytes(leaf)
    else:
      groups.append([key, [idx], _leaf_nbytes(leaf)])
  merged = allreduce.plan_size_buckets([g[2] for g in groups],
                                       bucket_bytes)
  buckets = [[i for g in span for i in groups[g][1]] for span in merged]
  return buckets, excluded


def wrap_tree(tree, axis_name, bucket_bytes: int, compact_dtype=None,
              exclude_prefixes: Tuple[str, ...] = ()):
  """Pass each bucket of ``tree`` through :func:`reduce_identity`.

  Apply to the parameter tree at the top of the loss function (every
  parameter use must flow through the wrapped copy); the gradient
  returned by jax.grad is then already replica-reduced, one collective
  per bucket, each issued in-backward."""
  leaves, treedef = jax.tree_util.tree_flatten(tree)
  buckets, _ = plan_buckets(tree, bucket_bytes,
                            exclude_prefixes=exclude_prefixes)
  reduce_fn = _bucket_reduce_fn(axis_name, compact_dtype)
  out = list(leaves)
  for bucket in buckets:
    wrapped = reduce_identity(reduce_fn, tuple(leaves[i] for i in bucket))
    for i, leaf in zip(bucket, wrapped):
      out[i] = leaf
  return jax.tree_util.tree_unflatten(treedef, out)


def scan_block_hook(axis_name, compact_dtype=None):
  """Per-scanned-block hook: wrap one layer's parameter slice as a
  single bucket.

  Use as ``nn.map_variables(Block, "params", trans_in_fn=hook,
  init=True)`` under nn.scan (models/transformer_lm.py) or applied to
  the carry-free xs slice at the top of a lax.scan body
  (parallel/transformer.py). Each backward scan iteration then issues
  that layer's reduction INSIDE the loop body, interleaved with the
  next iteration's backward compute."""
  reduce_fn = _bucket_reduce_fn(axis_name, compact_dtype)

  def hook(block_params):
    return reduce_identity(reduce_fn, block_params)

  return hook
