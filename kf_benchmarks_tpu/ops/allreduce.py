"""All-reduce spec parsing, gradient packing, and the reduction planner.

TPU-native re-design of the reference's collective layer (ref:
scripts/tf_cnn_benchmarks/allreduce.py:32-104 spec BNF, :420-588
small-tensor packing; batch_allreduce.py:32-153 batched algorithms;
allreduce_legacy.py:320-368 ring/hierarchical builders).

The spec grammar is preserved as a tuning surface:

    spec        := alg_spec (":" limit ":" alg_spec)*
    alg_spec    := alg ("#" shards)?
    alg         := "psum" | "rsag" | "hier" | reference aliases
    limit       := <int>[kKmM]?      (byte threshold; tensors smaller than
                                      the limit use the preceding alg)

e.g. ``psum:32k:rsag#2`` -- tensors under 32KiB all-reduce directly
(latency-bound: one fused psum), larger ones go through a sharded
reduce-scatter + all-gather (bandwidth-optimal on an ICI ring, the analog
of the reference's ``xring``).

``hier`` is UNVALIDATED AT SCALE (only ever measured on single-chip /
virtual meshes; VERDICT weak #4): the default remains ``psum``, and
selecting hier on a single-process mesh logs a warning at build time
(_warn_hier_selected).

Reference algorithm names map onto TPU implementations so reference specs
keep working: nccl->psum, xring->rsag, pscpu/psgpu->psum,
collective->psum, nccl/xring & friends->hier.

On TPU, XLA already lowers ``psum`` to topology-aware ICI rings; the
decompositions here exist to (a) preserve the spec-driven tuning surface,
(b) let the planner pack small gradients into one fused collective
(bandwidth + latency win the reference gets from pack_small_tensors), and
(c) shard large reductions the way the reference's ``#shards`` did.
"""

from __future__ import annotations

import re
from typing import Any, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class AllReduceSpecTuple(NamedTuple):
  """(ref: allreduce.py:32-56)"""
  alg: str
  shards: int
  limit: Optional[int]  # byte threshold; None = no upper bound


_TPU_ALGS = ("psum", "rsag", "hier")
_ALIASES = {
    "nccl": "psum",
    "collective": "psum",
    "pscpu": "psum",
    "psgpu": "psum",
    "xring": "rsag",
    "nccl/xring": "hier",
    "nccl/rechd": "hier",
    "nccl/pscpu": "hier",
    "pscpu/pscpu": "hier",
}


def _parse_limit(limit_str: str) -> int:
  m = re.fullmatch(r"(\d+)([kKmM]?)", limit_str)
  if not m:
    raise ValueError(f"Invalid all-reduce spec limit {limit_str!r}")
  val = int(m.group(1))
  suffix = m.group(2).lower()
  if suffix == "k":
    val *= 1024
  elif suffix == "m":
    val *= 1024 * 1024
  return val


def _parse_alg(alg_str: str) -> AllReduceSpecTuple:
  if "#" in alg_str:
    alg, _, shards_str = alg_str.partition("#")
    try:
      shards = int(shards_str)
    except ValueError:
      raise ValueError(f"Invalid all-reduce spec shards {alg_str!r}")
  else:
    alg, shards = alg_str, 1
  alg = _ALIASES.get(alg, alg)
  if alg not in _TPU_ALGS:
    raise ValueError(
        f"Invalid all-reduce algorithm {alg_str!r}; TPU algs are "
        f"{_TPU_ALGS} (reference aliases {sorted(_ALIASES)} accepted)")
  return AllReduceSpecTuple(alg=alg, shards=shards, limit=None)


def parse_all_reduce_spec(spec: str) -> List[AllReduceSpecTuple]:
  """Parse the spec BNF into range-limited tuples (ref: allreduce.py:58-104).

  Returns tuples ordered small-to-large; each tuple's ``limit`` is the
  exclusive upper byte bound it handles (None for the last)."""
  parts = spec.split(":")
  if len(parts) % 2 == 0:
    raise ValueError(f"Spec must alternate alg:limit:alg...: {spec!r}")
  tuples = []
  for i, part in enumerate(parts):
    if i % 2 == 0:
      tuples.append(_parse_alg(part))
    else:
      limit = _parse_limit(part)
      prev = tuples[-1]
      if prev.limit is not None:
        raise ValueError(f"Duplicate limit in spec {spec!r}")
      tuples[-1] = prev._replace(limit=limit)
      if len(tuples) >= 2 and tuples[-2].limit is not None and \
          limit <= tuples[-2].limit:
        raise ValueError(f"Limits must be increasing in spec {spec!r}")
  if tuples[-1].limit is not None:
    raise ValueError(f"Last algorithm in spec must be unbounded: {spec!r}")
  return tuples


# -- packing ----------------------------------------------------------------

def plan_size_buckets(sizes: Sequence[int], bucket_bytes: int):
  """Greedy size-bounded bucketing of an ordered size list.

  The scheduler behind --reduce_bucket_mb (ops/overlap.py): consecutive
  items merge into a bucket until adding the next would exceed
  ``bucket_bytes``; an item alone larger than the bound keeps its own
  bucket (reduction units cannot split below the granularity the caller
  hands in). Order is preserved -- the overlap hooks rely on buckets
  covering ADJACENT layers so each bucket's cotangent completes in one
  contiguous stretch of the backward. Returns a list of index lists
  covering ``range(len(sizes))`` exactly.
  """
  buckets = []
  cur, cur_bytes = [], 0
  for i, size in enumerate(sizes):
    if cur and cur_bytes + size > bucket_bytes:
      buckets.append(cur)
      cur, cur_bytes = [], 0
    cur.append(i)
    cur_bytes += size
  if cur:
    buckets.append(cur)
  return buckets


# One precision note per process: compact_wire_dtype is consulted by
# every builder that can consume the wire format (strategy reducer,
# overlap spec, module hooks), and repeating the identical note per
# consumer would read as several distinct engagements.
_compact_f32_noted = False


def compact_wire_dtype(params):
  """The 16-bit wire format the packed reduction paths ride, or None.

  compact_gradient_transfer historically engaged only under --use_fp16
  (ref: batch_allreduce.py:96-103 compacts fp16 gradients); on TPU the
  bf16 wire format is equally valid for f32 training -- the all-reduce
  moves half the bytes while master params and the optimizer apply stay
  f32 -- so --compact_gradient_transfer_f32 opts f32 runs in explicitly
  (validation.py requires a packed path that actually consumes the
  format; the default per-leaf pmean has no wire repacking to compact).
  The opt-in logs a precision note once: gradients ride the wire at
  bf16 (8 mantissa bits), a rounding the f32 post-hoc path does not
  have.
  """
  if not params.compact_gradient_transfer:
    return None
  if params.use_fp16:
    return jnp.bfloat16
  if getattr(params, "compact_gradient_transfer_f32", False):
    global _compact_f32_noted
    if not _compact_f32_noted:
      _compact_f32_noted = True
      from kf_benchmarks_tpu.utils import log as log_util
      log_util.log_fn(
          "compact_gradient_transfer_f32: f32 gradients ride the "
          "all-reduce wire at bfloat16 (8 mantissa bits) -- halves "
          "reduction bytes; NOT bit-identical to the f32 wire path")
    return jnp.bfloat16
  return None


class PackMeta(NamedTuple):
  shapes: tuple
  dtypes: tuple
  sizes: tuple
  pad: int


def pack_tensors(leaves: Sequence[jax.Array], multiple_of: int = 1):
  """Flatten+concat a tensor list into one fp32-width-preserving vector
  (ref: pack_small_tensors / pack_range, allreduce.py:420-510).

  Padding to ``multiple_of`` makes the vector evenly shardable for
  reduce-scatter. Returns (vector, PackMeta)."""
  shapes = tuple(l.shape for l in leaves)
  dtypes = tuple(l.dtype for l in leaves)
  sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
  flat = [jnp.ravel(l) for l in leaves]
  common = jnp.result_type(*dtypes) if leaves else jnp.float32
  vec = jnp.concatenate([f.astype(common) for f in flat]) if flat else \
      jnp.zeros((0,), common)
  pad = (-vec.shape[0]) % multiple_of
  if pad:
    vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
  return vec, PackMeta(shapes, dtypes, sizes, pad)


def unpack_tensors(vec: jax.Array, meta: PackMeta) -> List[jax.Array]:
  """Inverse of pack_tensors (ref: unpack_small_tensors,
  allreduce.py:560-588)."""
  if meta.pad:
    vec = vec[:-meta.pad] if meta.pad else vec
  out = []
  offset = 0
  for shape, dtype, size in zip(meta.shapes, meta.dtypes, meta.sizes):
    out.append(vec[offset:offset + size].reshape(shape).astype(dtype))
    offset += size
  return out


# -- algorithms -------------------------------------------------------------

def _pmean_direct(vec, axis_name):
  return lax.pmean(vec, axis_name)


def _rsag(vec, axis_name, shards=1):
  """Reduce-scatter + all-gather: the bandwidth-optimal ring decomposition
  (the analog of the reference's ring builders, allreduce_legacy.py:338-360).

  ``shards`` subdivides the vector into independently-reduced chunks --
  the reference's ``alg#shards`` ring subdivision (ref: allreduce.py:32-56
  spec, subdiv offsets :185-219): chunked collectives let XLA overlap the
  chunks' scatter/gather phases."""
  n = lax.axis_size(axis_name)
  shards = max(1, int(shards))
  size = vec.shape[0]
  pad = (-size) % (n * shards)
  if pad:
    vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])

  def one(v):
    scattered = lax.psum_scatter(v, axis_name, scatter_dimension=0,
                                 tiled=True)
    return lax.all_gather(scattered, axis_name, axis=0, tiled=True)

  if shards > 1:
    vec = jnp.concatenate([one(part) for part in jnp.split(vec, shards)])
  else:
    vec = one(vec)
  if pad:
    vec = vec[:size]
  return vec / n


def topology_groups(devices, num_groups: Optional[int] = None):
  """Axis-position -> group id for hierarchical reduction, derived from
  real machine topology the way the reference's HierarchicalCopy encodes
  it (ref: batch_allreduce.py:173-267 topology tables).

  ``devices`` is the mesh axis's device order. Multi-process: groups are
  the process (host) boundaries, so the intra-group ring rides ICI and
  only the cross-group ring crosses DCN. Single-process (no topology to
  read): contiguous split into ``num_groups`` (default 2, the reference's
  two-group HierarchicalCopy shape)."""
  procs = [getattr(d, "process_index", 0) for d in devices]
  uniq = sorted(set(procs))
  if len(uniq) > 1:
    gid = {p: i for i, p in enumerate(uniq)}
    return [gid[p] for p in procs]
  n = len(devices)
  k = max(2, int(num_groups or 2))
  if n % k != 0:
    return [0] * n  # degenerate; _hier falls back to pmean
  return [i // (n // k) for i in range(n)]


def _ring_sum(vec, axis_name, cycles, rounds):
  """Sum values around disjoint position cycles: ``rounds`` applications
  of the cycles' successor permutation, accumulating each arrival."""
  perm = []
  for cycle in cycles:
    for j, pos in enumerate(cycle):
      perm.append((pos, cycle[(j + 1) % len(cycle)]))
  acc, cur = vec, vec
  for _ in range(rounds):
    cur = lax.ppermute(cur, axis_name, perm)
    acc = acc + cur
  return acc


def _hier(vec, axis_name, num_groups=2, groups=None):
  """Two-level hierarchical reduction: a ring all-reduce within each
  group (intra-host ICI), then a ring across same-offset members of each
  group -- (g-1) + (num_groups-1) exchange rounds instead of a flat
  ring's n-1 (the analog of the reference's two-group reduce ->
  cross-group reduce -> broadcast HierarchicalCopy,
  batch_allreduce.py:173-267, and 'nccl/rechd',
  allreduce_legacy.py:344-348).

  ``groups`` maps axis position -> group id (from :func:`topology_groups`,
  i.e. process/host boundaries); absent, groups are ``num_groups``
  contiguous blocks. Falls back to a direct pmean when groups are not
  equal-sized (the reference requires symmetric topology too)."""
  n = lax.axis_size(axis_name)
  if groups is not None and len(groups) != n:
    # Stale topology capture (e.g. a reducer built for a different mesh
    # surviving an elastic resize): permuting with wrong-length groups
    # would drop or zero replicas, so reduce flat instead.
    groups = None
  if groups is None:
    num_groups = max(2, int(num_groups))
    if n <= 1 or n % num_groups != 0:
      return lax.pmean(vec, axis_name)
    groups = [i // (n // num_groups) for i in range(n)]
  members = {}
  for pos, g in enumerate(groups):
    members.setdefault(g, []).append(pos)
  sizes = {len(m) for m in members.values()}
  if n <= 1 or len(members) < 2 or len(sizes) != 1:
    return lax.pmean(vec, axis_name)
  gsize = sizes.pop()
  ordered = [members[g] for g in sorted(members)]
  # Intra-group rings (one cycle per group), then cross-group rings (one
  # cycle per member offset, linking the j-th member of every group).
  vec = _ring_sum(vec, axis_name, ordered, gsize - 1)
  cross = [[grp[j] for grp in ordered] for j in range(gsize)]
  vec = _ring_sum(vec, axis_name, cross, len(ordered) - 1)
  return vec / n


# -- planner ----------------------------------------------------------------

def _reduce_packed(vec, spec: AllReduceSpecTuple, axis_name,
                   compact_dtype=None):
  """Reduce one packed vector per its spec, optionally compacted to a
  16-bit wire format (ref: compact_gradient_transfer,
  batch_allreduce.py:96-103 fp16 compaction)."""
  orig_dtype = vec.dtype
  if compact_dtype is not None and vec.dtype != compact_dtype:
    vec = vec.astype(compact_dtype)
  if spec.alg == "psum":
    vec = _pmean_direct(vec, axis_name)
  elif spec.alg == "rsag":
    vec = _rsag(vec, axis_name, spec.shards)
  elif spec.alg == "hier":
    vec = _hier(vec, axis_name, max(spec.shards, 2))
  else:
    raise ValueError(f"Unknown alg {spec.alg!r}")
  return vec.astype(orig_dtype)


class CollectivePlanner:
  """Spec-driven gradient reduction with small-tensor packing.

  The analog of sum_gradients_all_reduce + AllReduceSpec batching
  (ref: allreduce.py:344-417, batch_allreduce.py:270-297): gradients are
  bucketed by byte size per the spec ranges, each bucket packed into one
  flat vector, and reduced with the bucket's algorithm.

  ``agg_max_bytes``/``agg_max_group`` apply the small-gradient packing
  limits within each bucket: only tensors under ``agg_max_bytes`` join
  group packs, capped at ``agg_max_group`` tensors each; larger tensors
  share the bucket-wide pack as before (ref: agg_small_grads_max_bytes/
  _group threading into sum_gradients_all_reduce, allreduce.py:344-417,
  extract_ranges :420-460). ``compact_dtype`` compacts the packed wire
  format to 16 bits (ref: compact_gradient_transfer).
  """

  def __init__(self, spec_tuples: Sequence[AllReduceSpecTuple],
               num_replicas_hint: int = 8, agg_max_bytes: int = 0,
               agg_max_group: Optional[int] = None, compact_dtype=None):
    self.spec_tuples = list(spec_tuples)
    self.num_replicas_hint = num_replicas_hint
    self.agg_max_bytes = agg_max_bytes
    self.agg_max_group = agg_max_group
    self.compact_dtype = compact_dtype

  def _bucket_of(self, nbytes: int) -> int:
    for i, t in enumerate(self.spec_tuples):
      if t.limit is None or nbytes < t.limit:
        return i
    return len(self.spec_tuples) - 1

  def reduce(self, grads, axis_name):
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    n = self.num_replicas_hint
    buckets = {}
    for idx, leaf in enumerate(leaves):
      b = self._bucket_of(leaf.size * leaf.dtype.itemsize)
      buckets.setdefault(b, []).append(idx)
    reduced = [None] * len(leaves)
    for b, idxs in sorted(buckets.items()):
      spec = self.spec_tuples[b]
      if self.agg_max_bytes > 0:
        small = [i for i in idxs
                 if leaves[i].size * leaves[i].dtype.itemsize <
                 self.agg_max_bytes]
        rest = [i for i in idxs if i not in small]
        group = max(1, self.agg_max_group or len(small) or 1)
        chunks = [small[s:s + group] for s in range(0, len(small), group)]
        if rest:
          chunks.append(rest)
      else:
        chunks = [idxs]
      for chunk in chunks:
        vec, meta = pack_tensors([leaves[i] for i in chunk], multiple_of=n)
        vec = _reduce_packed(vec, spec, axis_name, self.compact_dtype)
        for i, t in zip(chunk, unpack_tensors(vec, meta)):
          reduced[i] = t
    return jax.tree_util.tree_unflatten(treedef, reduced)


def pack_small_reduce(grads, axis_name, max_bytes: int, max_group: int,
                      num_replicas: int, compact_dtype=None):
  """Default-path (no spec) small-gradient aggregation: pack tensors
  smaller than ``max_bytes`` into groups of at most ``max_group`` and
  all-reduce each pack as one tensor; larger tensors reduce individually
  (ref: agg_small_grads_max_bytes/_group, allreduce.py:420-588
  pack_small_tensors/unpack_small_tensors)."""
  spec = AllReduceSpecTuple(alg="psum", shards=1, limit=None)
  leaves, treedef = jax.tree_util.tree_flatten(grads)
  reduced = [None] * len(leaves)
  small = [i for i, l in enumerate(leaves)
           if l.size * l.dtype.itemsize < max_bytes]
  for i, leaf in enumerate(leaves):
    if i not in small:
      reduced[i] = _reduce_packed(
          jnp.ravel(leaf), spec, axis_name, compact_dtype).reshape(leaf.shape)
  group = max(1, max_group)
  for start in range(0, len(small), group):
    chunk = small[start:start + group]
    vec, meta = pack_tensors([leaves[i] for i in chunk],
                             multiple_of=num_replicas)
    vec = _reduce_packed(vec, spec, axis_name, compact_dtype)
    for i, t in zip(chunk, unpack_tensors(vec, meta)):
      reduced[i] = t
  return jax.tree_util.tree_unflatten(treedef, reduced)


def repack_reduce(grads, axis_name, num_chunks: int, num_replicas: int,
                  compact_dtype=None):
  """Default-path gradient repacking: concatenate ALL gradients into one
  vector, re-split it into ``num_chunks`` even chunks, and reduce each --
  the reference's --gradient_repacking, which re-shapes the reduction
  granularity away from tensor boundaries so chunks pipeline
  (ref: batch_allreduce.py:391-481 _TensorPacker)."""
  spec = AllReduceSpecTuple(alg="psum", shards=1, limit=None)
  leaves, treedef = jax.tree_util.tree_flatten(grads)
  vec, meta = pack_tensors(leaves, multiple_of=num_replicas)
  num_chunks = max(1, int(num_chunks))
  chunk = -(-vec.shape[0] // num_chunks)
  pad = chunk * num_chunks - vec.shape[0]
  size = vec.shape[0]
  if pad:
    vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
  parts = [_reduce_packed(part, spec, axis_name, compact_dtype)
           for part in jnp.split(vec, num_chunks)]
  vec = jnp.concatenate(parts)[:size]
  return jax.tree_util.tree_unflatten(treedef,
                                      unpack_tensors(vec, meta))


def hier_reduce(grads, axis_name, num_groups: int = 2, compact_dtype=None,
                groups=None):
  """Default-path two-level reduction (ref: --hierarchical_copy,
  batch_allreduce.py:173-267 HierarchicalCopy): on TPU, a grouped psum
  within device groups (process/host boundaries via ``groups``, else
  contiguous) then across them."""
  def one(x):
    orig = x.dtype
    if compact_dtype is not None and x.dtype != compact_dtype:
      x = x.astype(compact_dtype)
    return _hier(x, axis_name, num_groups, groups=groups).astype(orig)
  return jax.tree.map(one, grads)


def _warn_hier_selected(source: str) -> None:
  """One-line operator warning at hier selection time.

  The 'hier' algorithm is UNVALIDATED AT SCALE: its two-level ring
  decomposition has only ever been measured on the single-chip /
  virtual-mesh configurations this repo can reach (PERF.md; VERDICT
  weak #4) -- the default remains psum, which XLA lowers to
  topology-aware ICI rings itself. On a single-process mesh the
  process/host boundary hier exists to exploit does not exist, so the
  decomposition can only add latency over the fused psum."""
  from kf_benchmarks_tpu.utils import log as log_util
  if jax.process_count() > 1:
    return
  log_util.log_fn(
      f"Warning: 'hier' all-reduce selected ({source}) on a "
      "single-process mesh: the two-level decomposition is unvalidated "
      "at scale and has no host boundary to exploit here -- the psum "
      "default is the measured-fast path (PERF.md)")


def build_reducer(params):
  """Flag-selected gradient reducer for the replicated-family strategies,
  or None for the direct-pmean default (ref selection:
  batch_allreduce.py:300-317 algorithm_from_params -- spec > repacking >
  small-grad aggregation > hierarchical copy > plain copy).

  Returns fn(grads, axis_name) or None. compact_gradient_transfer rides
  every packed path when reduced precision is on (the fp16-compaction
  analog; bf16 wire format on TPU) or under the explicit f32 opt-in
  (--compact_gradient_transfer_f32; compact_wire_dtype)."""
  compact = compact_wire_dtype(params)
  if params.all_reduce_spec:
    return build_planner(params).reduce
  if params.gradient_repacking:
    return lambda g, ax: repack_reduce(
        g, ax, params.gradient_repacking, params.num_devices, compact)
  if params.agg_small_grads_max_bytes > 0:
    return lambda g, ax: pack_small_reduce(
        g, ax, params.agg_small_grads_max_bytes,
        params.agg_small_grads_max_group, params.num_devices, compact)
  if params.hierarchical_copy:
    # Groups come from real topology (process/host boundaries) on a
    # multi-process mesh, so the intra-group ring rides ICI; num_groups
    # defaults to the process count there and to the reference's 2-group
    # shape single-process (ref: batch_allreduce.py:173-267).
    _warn_hier_selected("--hierarchical_copy")
    from kf_benchmarks_tpu.parallel import mesh as mesh_lib
    devices = mesh_lib.get_devices(params.device, params.num_devices)
    groups = topology_groups(devices, num_groups=jax.process_count()
                             if jax.process_count() > 1 else 2)
    return lambda g, ax: hier_reduce(g, ax, compact_dtype=compact,
                                     groups=groups)
  return None


def build_planner(params) -> Optional[CollectivePlanner]:
  """Construct the planner from --all_reduce_spec (ref selection:
  batch_allreduce.py:300-317 algorithm_from_params), honoring the
  agg_small_grads group cap and 16-bit wire compaction."""
  if not params.all_reduce_spec:
    return None
  tuples = parse_all_reduce_spec(params.all_reduce_spec)
  if any(t.alg == "hier" for t in tuples):
    _warn_hier_selected(f"--all_reduce_spec={params.all_reduce_spec}")
  compact = compact_wire_dtype(params)
  return CollectivePlanner(tuples, num_replicas_hint=params.num_devices,
                           agg_max_bytes=params.agg_small_grads_max_bytes,
                           agg_max_group=params.agg_small_grads_max_group,
                           compact_dtype=compact)
