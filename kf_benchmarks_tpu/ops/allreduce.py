"""All-reduce spec parsing, gradient packing, and the reduction planner.

TPU-native re-design of the reference's collective layer (ref:
scripts/tf_cnn_benchmarks/allreduce.py:32-104 spec BNF, :420-588
small-tensor packing; batch_allreduce.py:32-153 batched algorithms;
allreduce_legacy.py:320-368 ring/hierarchical builders).

The spec grammar is preserved as a tuning surface:

    spec        := alg_spec (":" limit ":" alg_spec)*
    alg_spec    := alg ("#" shards)?
    alg         := "psum" | "rsag" | "hier" | reference aliases
    limit       := <int>[kKmM]?      (byte threshold; tensors smaller than
                                      the limit use the preceding alg)

e.g. ``psum:32k:rsag#2`` -- tensors under 32KiB all-reduce directly
(latency-bound: one fused psum), larger ones go through a sharded
reduce-scatter + all-gather (bandwidth-optimal on an ICI ring, the analog
of the reference's ``xring``).

Reference algorithm names map onto TPU implementations so reference specs
keep working: nccl->psum, xring->rsag, pscpu/psgpu->psum,
collective->psum, nccl/xring & friends->hier.

On TPU, XLA already lowers ``psum`` to topology-aware ICI rings; the
decompositions here exist to (a) preserve the spec-driven tuning surface,
(b) let the planner pack small gradients into one fused collective
(bandwidth + latency win the reference gets from pack_small_tensors), and
(c) shard large reductions the way the reference's ``#shards`` did.
"""

from __future__ import annotations

import re
from typing import Any, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class AllReduceSpecTuple(NamedTuple):
  """(ref: allreduce.py:32-56)"""
  alg: str
  shards: int
  limit: Optional[int]  # byte threshold; None = no upper bound


_TPU_ALGS = ("psum", "rsag", "hier")
_ALIASES = {
    "nccl": "psum",
    "collective": "psum",
    "pscpu": "psum",
    "psgpu": "psum",
    "xring": "rsag",
    "nccl/xring": "hier",
    "nccl/rechd": "hier",
    "nccl/pscpu": "hier",
    "pscpu/pscpu": "hier",
}


def _parse_limit(limit_str: str) -> int:
  m = re.fullmatch(r"(\d+)([kKmM]?)", limit_str)
  if not m:
    raise ValueError(f"Invalid all-reduce spec limit {limit_str!r}")
  val = int(m.group(1))
  suffix = m.group(2).lower()
  if suffix == "k":
    val *= 1024
  elif suffix == "m":
    val *= 1024 * 1024
  return val


def _parse_alg(alg_str: str) -> AllReduceSpecTuple:
  if "#" in alg_str:
    alg, _, shards_str = alg_str.partition("#")
    try:
      shards = int(shards_str)
    except ValueError:
      raise ValueError(f"Invalid all-reduce spec shards {alg_str!r}")
  else:
    alg, shards = alg_str, 1
  alg = _ALIASES.get(alg, alg)
  if alg not in _TPU_ALGS:
    raise ValueError(
        f"Invalid all-reduce algorithm {alg_str!r}; TPU algs are "
        f"{_TPU_ALGS} (reference aliases {sorted(_ALIASES)} accepted)")
  return AllReduceSpecTuple(alg=alg, shards=shards, limit=None)


def parse_all_reduce_spec(spec: str) -> List[AllReduceSpecTuple]:
  """Parse the spec BNF into range-limited tuples (ref: allreduce.py:58-104).

  Returns tuples ordered small-to-large; each tuple's ``limit`` is the
  exclusive upper byte bound it handles (None for the last)."""
  parts = spec.split(":")
  if len(parts) % 2 == 0:
    raise ValueError(f"Spec must alternate alg:limit:alg...: {spec!r}")
  tuples = []
  for i, part in enumerate(parts):
    if i % 2 == 0:
      tuples.append(_parse_alg(part))
    else:
      limit = _parse_limit(part)
      prev = tuples[-1]
      if prev.limit is not None:
        raise ValueError(f"Duplicate limit in spec {spec!r}")
      tuples[-1] = prev._replace(limit=limit)
      if len(tuples) >= 2 and tuples[-2].limit is not None and \
          limit <= tuples[-2].limit:
        raise ValueError(f"Limits must be increasing in spec {spec!r}")
  if tuples[-1].limit is not None:
    raise ValueError(f"Last algorithm in spec must be unbounded: {spec!r}")
  return tuples


# -- packing ----------------------------------------------------------------

class PackMeta(NamedTuple):
  shapes: tuple
  dtypes: tuple
  sizes: tuple
  pad: int


def pack_tensors(leaves: Sequence[jax.Array], multiple_of: int = 1):
  """Flatten+concat a tensor list into one fp32-width-preserving vector
  (ref: pack_small_tensors / pack_range, allreduce.py:420-510).

  Padding to ``multiple_of`` makes the vector evenly shardable for
  reduce-scatter. Returns (vector, PackMeta)."""
  shapes = tuple(l.shape for l in leaves)
  dtypes = tuple(l.dtype for l in leaves)
  sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
  flat = [jnp.ravel(l) for l in leaves]
  common = jnp.result_type(*dtypes) if leaves else jnp.float32
  vec = jnp.concatenate([f.astype(common) for f in flat]) if flat else \
      jnp.zeros((0,), common)
  pad = (-vec.shape[0]) % multiple_of
  if pad:
    vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
  return vec, PackMeta(shapes, dtypes, sizes, pad)


def unpack_tensors(vec: jax.Array, meta: PackMeta) -> List[jax.Array]:
  """Inverse of pack_tensors (ref: unpack_small_tensors,
  allreduce.py:560-588)."""
  if meta.pad:
    vec = vec[:-meta.pad] if meta.pad else vec
  out = []
  offset = 0
  for shape, dtype, size in zip(meta.shapes, meta.dtypes, meta.sizes):
    out.append(vec[offset:offset + size].reshape(shape).astype(dtype))
    offset += size
  return out


# -- algorithms -------------------------------------------------------------

def _pmean_direct(vec, axis_name):
  return lax.pmean(vec, axis_name)


def _rsag(vec, axis_name, shards=1):
  """Reduce-scatter + all-gather: the bandwidth-optimal ring decomposition
  (the analog of the reference's ring builders, allreduce_legacy.py:338-360).
  ``vec`` must be padded to a multiple of the axis size."""
  n = lax.axis_size(axis_name)
  scattered = lax.psum_scatter(vec, axis_name, scatter_dimension=0,
                               tiled=True)
  gathered = lax.all_gather(scattered, axis_name, axis=0, tiled=True)
  return gathered / n


def _hier(vec, axis_name, num_groups=2):
  """Hierarchical reduction by recursive doubling: log2(n) ppermute
  exchange rounds with XOR partners (the analog of the reference's
  recursive halving-doubling 'nccl/rechd' and two-level HierarchicalCopy,
  batch_allreduce.py:173-267 / allreduce_legacy.py:344-348). Low-bit
  rounds exchange with near neighbors (intra-host ICI on a (host,chip)
  layout) before high-bit rounds cross hosts. Requires power-of-2 axis
  size; falls back to a direct pmean otherwise."""
  del num_groups
  n = lax.axis_size(axis_name)
  if n <= 1 or (n & (n - 1)) != 0:
    return lax.pmean(vec, axis_name)
  bit = 1
  while bit < n:
    perm = [(i, i ^ bit) for i in range(n)]
    vec = vec + lax.ppermute(vec, axis_name, perm)
    bit <<= 1
  return vec / n


# -- planner ----------------------------------------------------------------

class CollectivePlanner:
  """Spec-driven gradient reduction with small-tensor packing.

  The analog of sum_gradients_all_reduce + AllReduceSpec batching
  (ref: allreduce.py:344-417, batch_allreduce.py:270-297): gradients are
  bucketed by byte size per the spec ranges, each bucket packed into one
  flat vector, and reduced with the bucket's algorithm.
  """

  def __init__(self, spec_tuples: Sequence[AllReduceSpecTuple],
               num_replicas_hint: int = 8):
    self.spec_tuples = list(spec_tuples)
    self.num_replicas_hint = num_replicas_hint

  def _bucket_of(self, nbytes: int) -> int:
    for i, t in enumerate(self.spec_tuples):
      if t.limit is None or nbytes < t.limit:
        return i
    return len(self.spec_tuples) - 1

  def reduce(self, grads, axis_name):
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    n = self.num_replicas_hint
    buckets = {}
    for idx, leaf in enumerate(leaves):
      b = self._bucket_of(leaf.size * leaf.dtype.itemsize)
      buckets.setdefault(b, []).append(idx)
    reduced = [None] * len(leaves)
    for b, idxs in sorted(buckets.items()):
      spec = self.spec_tuples[b]
      vec, meta = pack_tensors([leaves[i] for i in idxs], multiple_of=n)
      if spec.alg == "psum":
        vec = _pmean_direct(vec, axis_name)
      elif spec.alg == "rsag":
        vec = _rsag(vec, axis_name, spec.shards)
      elif spec.alg == "hier":
        vec = _hier(vec, axis_name, max(spec.shards, 2))
      else:
        raise ValueError(f"Unknown alg {spec.alg!r}")
      for i, t in zip(idxs, unpack_tensors(vec, meta)):
        reduced[i] = t
    return jax.tree_util.tree_unflatten(treedef, reduced)


def build_planner(params) -> Optional[CollectivePlanner]:
  """Construct the planner from --all_reduce_spec (ref selection:
  batch_allreduce.py:300-317 algorithm_from_params)."""
  if not params.all_reduce_spec:
    return None
  tuples = parse_all_reduce_spec(params.all_reduce_spec)
  return CollectivePlanner(tuples, num_replicas_hint=params.num_devices)
