"""kfrun: multi-process launcher, the ``kungfu-run`` analog.

The reference launches one process per device with
``kungfu-run -np N python3 tf_cnn_benchmarks.py ...`` and the KungFu
config server wires the peer mesh, capturing per-process logs as
``127.0.0.1.<port>.{stdout,stderr}.log`` (ref: README.md "Running
KungFu"; the committed log files of that shape are kungfu-run output).

kfrun reproduces that contract on the native coordination service
(native/kfcoord.cc): it starts a coordinator, spawns N worker processes
with KFCOORD_* env vars (host, port, world size, per-process name), and
captures per-process logs with the same naming scheme. Workers find
their rank by JOINing the coordinator; `run_barrier()` rides the same
service at exit.

Cross-process elastic resize (the KungFu resize_cluster restart leg,
SURVEY 5.3/7.4 "checkpointed rescale"): a live JAX world cannot change
its process count, so when a kfcoord RESIZE requires one, every worker
checkpoints, enters a restart barrier, and exits with
``RESTART_EXIT_CODE``. kfrun treats that exit as a coordinated restart
request: it reads the target size from its coordinator and relaunches
the SAME command with the new world size (logs append across
generations). Workers resume from the checkpoint in ``--train_dir``.

Usage:
    python -m kf_benchmarks_tpu.kfrun -np 4 -- python -m \
        kf_benchmarks_tpu.cli --model=resnet50 --variable_update=kungfu

On real multi-host TPU pods the TPU runtime launches one process per
host and JAX's distributed init handles the device mesh; kfrun covers
the single-host-many-process and CPU-test topologies, and the
coordinator serves as the DCN control plane in both cases.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
from typing import List, Optional, Tuple

# Exit code a worker uses to request a coordinated checkpoint-restart
# resize (chosen outside the shell/POSIX reserved ranges).
RESTART_EXIT_CODE = 42


def _run_generation(server, np_: int, command: List[str], logdir: str,
                    host: str, extra_env: Optional[dict],
                    opened_logs: Optional[set] = None) -> Tuple[int, bool]:
  """Spawn one generation of ``np_`` workers; wait.

  Returns (exit_code, restart_requested). The first time THIS launch
  opens a worker's log file it truncates it (a fresh launch -- or a
  restart that grows past the previous world size -- must not
  accumulate an earlier job's output); later generations append so one
  job's output stays in one set of files."""
  if opened_logs is None:
    opened_logs = set()
  procs = []
  log_files = []
  try:
    for i in range(np_):
      env = dict(os.environ)
      env.update(extra_env or {})
      env["KFCOORD_HOST"] = host
      env["KFCOORD_PORT"] = str(server.port)
      env["KFCOORD_WORLD"] = str(np_)
      env["KFCOORD_NAME"] = f"worker-{i}"
      # RANK_HINT is the one env var host code may BRANCH on -- any
      # collective/barrier under such a branch needs an all-ranks:
      # justification (the rank-divergent-collective lint rule), and
      # rank-guarded artifact writes a rank0-owns: marker.
      env["KFCOORD_RANK_HINT"] = str(i)
      # Per-process log capture, named the way kungfu-run names them.
      tag = f"{host}.{10000 + i}"
      mode = "a" if tag in opened_logs else "w"
      opened_logs.add(tag)
      out = open(os.path.join(logdir, f"{tag}.stdout.log"), mode)
      err = open(os.path.join(logdir, f"{tag}.stderr.log"), mode)
      log_files += [out, err]
      procs.append(subprocess.Popen(command, env=env, stdout=out,
                                    stderr=err))
    # Monitor rather than blindly wait: if one worker dies abnormally
    # while its siblings are parked in the exit barrier, the barrier can
    # never fill -- tear the job down instead of hanging (the
    # kungfu-run failure contract). RESTART_EXIT_CODE is a coordinated
    # exit, not a failure.
    import time
    while True:
      codes = [p.poll() for p in procs]
      if all(c is not None for c in codes):
        break
      if any(c not in (None, 0, RESTART_EXIT_CODE) for c in codes):
        time.sleep(1.0)  # grace: let siblings exit on their own
        for p in procs:
          if p.poll() is None:
            p.terminate()
        for p in procs:
          try:
            p.wait(timeout=10)
          except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        codes = [p.poll() for p in procs]
        break
      time.sleep(0.1)
    if (any(c == RESTART_EXIT_CODE for c in codes) and
        all(c in (0, RESTART_EXIT_CODE) for c in codes)):
      return 0, True
    # Report the original failure, not the SIGTERM we delivered: a worker
    # killed by our teardown shows -15, which would mask the real code.
    failures = [c for c in codes
                if c not in (0, RESTART_EXIT_CODE, -signal.SIGTERM)]
    if failures:
      return max(abs(c) for c in failures), False
    return (1 if any(c == -signal.SIGTERM for c in codes) else 0), False
  except KeyboardInterrupt:
    for p in procs:
      p.send_signal(signal.SIGTERM)
    for p in procs:
      p.wait()
    return 130, False
  finally:
    for f in log_files:
      f.close()


def launch(np_: int, command: List[str], logdir: str = ".",
           host: str = "127.0.0.1", base_port: int = 0,
           extra_env: Optional[dict] = None,
           max_restarts: int = 16,
           restart_on_failure: bool = False) -> int:
  """Start coordinator + N workers; relaunch on coordinated restarts;
  return the final generation's worst exit code.

  ``restart_on_failure`` adds preemption survival (the kill/rejoin
  leg): a generation where any worker died abnormally -- SIGKILL'd by
  a preemptor, OOM-killed, crashed -- is relaunched at the SAME world
  size instead of failing the job, and the rejoined workers resume
  from the checkpoint in ``--train_dir`` (KungFu's config-server
  rejoin, SURVEY 2.9, rendered as checkpointed restart). Bounded by
  ``max_restarts`` so a deterministic crash loop still terminates."""
  from kf_benchmarks_tpu.parallel import coordination
  from kf_benchmarks_tpu import tracing

  server = coordination.CoordinatorServer(port=base_port)
  try:
    gen_np = np_
    opened_logs: set = set()
    # One run id for the whole job (all ranks, all restart
    # generations): workers inherit it via env, so their flight
    # recorders and run traces share one timeline identity and the
    # rank-0 trace merge is coherent (tracing.py).
    extra_env = dict(extra_env or {})
    extra_env.setdefault("KF_RUN_ID", tracing.resolve_run_id())
    # Per-rank scrape targets: a worker command carrying --metrics_port
    # binds base + rank per process (benchmark.py resolve_port), so the
    # launcher prints the whole job's endpoint list once up front --
    # the operator's copy-paste Prometheus targets. Always loopback:
    # the endpoint binds 127.0.0.1 regardless of the coordinator
    # --host (kfrun workers share this machine). Both flag spellings
    # (--metrics_port=P and --metrics_port P) are recognized.
    metrics_base = None
    for i, tok in enumerate(command):
      if tok.startswith("--metrics_port="):
        metrics_base = tok.partition("=")[2]
      elif tok == "--metrics_port" and i + 1 < len(command):
        metrics_base = command[i + 1]
    if metrics_base and metrics_base.isdigit():
      targets = ", ".join(
          f"http://127.0.0.1:{int(metrics_base) + r}/metrics"
          for r in range(np_))
      print(f"kfrun: metrics endpoints: {targets}",
            file=sys.stderr, flush=True)
      # Serving-mode children bind the engine's endpoint on the same
      # port: point the operator at /healthz too, which carries the
      # engine state AND the per-tenant SLO burn rates -- "up" vs "up
      # but burning error budget" is the probe's whole point.
      if any(tok == "--serving" or tok.startswith("--serving=")
             for tok in command):
        health = ", ".join(
            f"http://127.0.0.1:{int(metrics_base) + r}/healthz"
            for r in range(np_))
        print("kfrun: serving healthz (engine + SLO burn state): "
              f"{health}", file=sys.stderr, flush=True)
    for _ in range(max_restarts + 1):
      code, restart = _run_generation(server, gen_np, command, logdir,
                                      host, extra_env,
                                      opened_logs=opened_logs)
      if not restart:
        # 130 = KeyboardInterrupt teardown: the operator asked the job
        # to stop; survival must not resurrect it.
        if code in (0, 130) or not restart_on_failure:
          return code
        # Abnormal worker death with survival enabled: rejoin at the
        # same world size from the last checkpoint. No resize was
        # agreed, so the scheduled-restart key is not consulted.
        print(f"kfrun: worker died (exit {code}); rejoining "
              f"np={gen_np} from the last checkpoint",
              file=sys.stderr, flush=True)
        continue
      # The workers checkpointed and exited for a resize; relaunch at
      # the PROCESS count they agreed on in the scheduled-restart key
      # (the raw RESIZE target is a global DEVICE count -- with >1
      # device per process the two differ, and respawning at the device
      # count would churn restarts forever).
      with coordination.CoordinatorClient(host=host,
                                          port=server.port) as client:
        new_np = gen_np
        try:
          gen = client.current_generation()
          sched = client.kv_tryget(f"kf_restart_sched_{gen}")
          if sched:
            new_np = max(1, int(sched.decode().partition(":")[2]))
          # No fallback to try_target_size(): that is a global DEVICE
          # count, and respawning processes at it churns restarts
          # forever when capacity > 1 (the workers re-derive the right
          # process count from a fresh poll after respawn at gen_np).
        except Exception as e:  # noqa: BLE001
          print(f"kfrun: could not read restart target ({e}); "
                f"respawning at np={gen_np}", file=sys.stderr, flush=True)
      print(f"kfrun: coordinated restart, np {gen_np} -> {new_np}",
            file=sys.stderr, flush=True)
      gen_np = new_np
    print(f"kfrun: giving up after {max_restarts} restarts",
          file=sys.stderr, flush=True)
    return 1
  finally:
    server.stop()


def main(argv=None):
  parser = argparse.ArgumentParser(
      prog="kfrun", description="kungfu-run-style multi-process launcher")
  parser.add_argument("-np", type=int, required=True, dest="np_",
                      help="number of worker processes")
  parser.add_argument("--logdir", default=".",
                      help="directory for per-process logs")
  parser.add_argument("--host", default="127.0.0.1")
  parser.add_argument("--port", type=int, default=0,
                      help="coordinator port (0 = ephemeral)")
  parser.add_argument("--restart-on-failure", action="store_true",
                      dest="restart_on_failure",
                      help="relaunch the world at the same size when a "
                           "worker dies abnormally (preemption "
                           "survival; workers resume from --train_dir)")
  parser.add_argument("command", nargs=argparse.REMAINDER,
                      help="worker command (prefix with --)")
  args = parser.parse_args(argv)
  command = args.command
  if command and command[0] == "--":
    command = command[1:]
  if not command:
    parser.error("no worker command given")
  sys.exit(launch(args.np_, command, logdir=args.logdir, host=args.host,
                  base_port=args.port,
                  restart_on_failure=args.restart_on_failure))


if __name__ == "__main__":
  main()
