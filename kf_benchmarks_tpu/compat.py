"""JAX API compatibility for the versions this tree meets in the wild.

The codebase targets the current ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` API. Some execution environments (this
container ships jax 0.4.37) predate the top-level export: there the entry
point is ``jax.experimental.shard_map.shard_map`` and the per-output
replication checker is spelled ``check_rep`` rather than ``check_vma``.

Importing :mod:`kf_benchmarks_tpu` installs a thin forwarding wrapper at
``jax.shard_map`` when (and only when) the top-level API is absent, so
every call site -- library and tests -- runs unmodified on both API
generations. On current jax this module is a no-op: nothing is patched
and the native implementation is used directly.
"""

from __future__ import annotations

import jax


def _install_shard_map_shim() -> None:
  if hasattr(jax, "shard_map"):
    return
  from jax.experimental import shard_map as _experimental_shard_map

  def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                check_vma=True, **kwargs):
    # check_vma maps to 0.4.x's check_rep, but pre-vma check_rep is
    # force-disabled: without lax.pcast there is no way to align the
    # replication types it infers for cond branches / scan carries
    # (sequence.py vary_like), so it rejects valid programs with
    # "branches of cond produced mismatched replication types". The
    # checker still runs wherever the real jax.shard_map exists.
    del check_vma
    return _experimental_shard_map.shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, **kwargs)

  jax.shard_map = shard_map


def _install_axis_size_shim() -> None:
  from jax import lax
  if hasattr(lax, "axis_size"):
    return

  def axis_size(axis_name):
    # The pre-export idiom: psum of a literal constant folds to the
    # STATIC axis size (a Python int) inside collective contexts.
    return lax.psum(1, axis_name)

  lax.axis_size = axis_size


_install_shard_map_shim()
_install_axis_size_shim()
