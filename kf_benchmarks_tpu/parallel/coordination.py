"""ctypes bindings for the native DCN coordination service (native/kfcoord.cc).

The control-plane replacement for what the reference delegates to
KungFu's Go runtime + kungfu-run config server (SURVEY 2.9: membership /
rank assignment, `run_barrier` at ref tf_cnn_benchmarks.py:58-60,
cluster-size queries at ref benchmark_cnn.py:1408-1410, elastic
membership in SURVEY 5.3). The XLA SPMD runtime owns the data plane;
this owns host-side coordination over DCN:

  CoordinatorServer  -- in-process coordinator (rank-0 host runs one)
  CoordinatorClient  -- join / barrier / kv_put / kv_get / resize

The library is built on demand with ``make -C native`` (g++ is in the
image; pybind11 is not, hence ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libkfcoord.so")

_lib = None
_lib_lock = threading.Lock()


def _load_library() -> ctypes.CDLL:
  """Load (building if needed) the native library."""
  global _lib
  with _lib_lock:
    if _lib is not None:
      return _lib
    src = os.path.join(_NATIVE_DIR, "kfcoord.cc")
    stale = (not os.path.exists(_LIB_PATH) or
             (os.path.exists(src) and
              os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)))
    if stale:
      subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                     capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.kfcoord_server_start.restype = ctypes.c_void_p
    lib.kfcoord_server_start.argtypes = [ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_int)]
    lib.kfcoord_server_stop.argtypes = [ctypes.c_void_p]
    lib.kfcoord_connect.restype = ctypes.c_void_p
    lib.kfcoord_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                    ctypes.c_int]
    lib.kfcoord_close.argtypes = [ctypes.c_void_p]
    lib.kfcoord_join.restype = ctypes.c_int
    lib.kfcoord_join.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int),
                                 ctypes.POINTER(ctypes.c_long)]
    lib.kfcoord_cluster_size.restype = ctypes.c_int
    lib.kfcoord_cluster_size.argtypes = [ctypes.c_void_p]
    lib.kfcoord_generation.restype = ctypes.c_long
    lib.kfcoord_generation.argtypes = [ctypes.c_void_p]
    lib.kfcoord_barrier.restype = ctypes.c_int
    lib.kfcoord_barrier.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
    lib.kfcoord_kv_put.restype = ctypes.c_int
    lib.kfcoord_kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p]
    lib.kfcoord_kv_get.restype = ctypes.c_int
    lib.kfcoord_kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int]
    lib.kfcoord_kv_tryget.restype = ctypes.c_int
    lib.kfcoord_kv_tryget.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int]
    lib.kfcoord_resize.restype = ctypes.c_long
    lib.kfcoord_resize.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.kfcoord_leave.restype = ctypes.c_int
    lib.kfcoord_leave.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def _decode_kv_token(token: str) -> bytes:
  """Inverse of kv_put's wire encoding ('x' + hex for binary payloads;
  raw tokens like RESIZE's decimal target pass through)."""
  return bytes.fromhex(token[1:]) if token.startswith("x") else \
      token.encode()


class CoordinatorServer:
  """In-process coordinator (the config-server role of kungfu-run)."""

  def __init__(self, port: int = 0):
    lib = _load_library()
    out_port = ctypes.c_int(0)
    self._handle = lib.kfcoord_server_start(port, ctypes.byref(out_port))
    if not self._handle:
      raise RuntimeError(f"Failed to start coordinator on port {port}")
    self.port = out_port.value

  def stop(self) -> None:
    if self._handle:
      _load_library().kfcoord_server_stop(self._handle)
      self._handle = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.stop()

  def __del__(self):
    try:
      self.stop()
    except Exception:
      pass


class CoordinatorClient:
  """One worker's connection to the coordinator."""

  def __init__(self, host: str = "127.0.0.1", port: int = 0,
               timeout_ms: int = 10000):
    lib = _load_library()
    self._lib = lib
    self._handle = lib.kfcoord_connect(host.encode(), port, timeout_ms)
    if not self._handle:
      raise RuntimeError(f"Failed to connect to coordinator {host}:{port}")
    self.rank: Optional[int] = None
    self.size: Optional[int] = None
    self.generation: Optional[int] = None

  def join(self, name: str) -> int:
    """Register and get a stable rank (idempotent per name)."""
    size = ctypes.c_int(0)
    gen = ctypes.c_long(0)
    rank = self._lib.kfcoord_join(self._handle, name.encode(),
                                  ctypes.byref(size), ctypes.byref(gen))
    if rank < 0:
      raise RuntimeError("JOIN failed")
    self.rank, self.size, self.generation = rank, size.value, gen.value
    return rank

  def cluster_size(self) -> int:
    n = self._lib.kfcoord_cluster_size(self._handle)
    if n < 0:
      raise RuntimeError("SIZE failed")
    return n

  def current_generation(self) -> int:
    g = self._lib.kfcoord_generation(self._handle)
    if g < 0:
      raise RuntimeError("GEN failed")
    return g

  def barrier(self, name: str, count: int) -> None:
    """Block until ``count`` participants enter barrier ``name``
    (the run_barrier analog, ref: tf_cnn_benchmarks.py:58-60)."""
    # all-ranks: the barrier PRIMITIVE itself -- attendance is the
    # caller's contract (count is the explicit expected world).
    if self._lib.kfcoord_barrier(self._handle, name.encode(), count) != 0:
      raise RuntimeError(f"BARRIER {name} failed")

  def kv_put(self, key: str, value: bytes) -> None:
    # "x" prefix keeps the token non-empty (protocol is space-delimited)
    # and distinguishes hex payloads from raw tokens like RESIZE's
    # decimal target size.
    if self._lib.kfcoord_kv_put(self._handle, key.encode(),
                                ("x" + value.hex()).encode()) != 0:
      raise RuntimeError(f"PUT {key} failed")

  def _kv_get_raw(self, key: str, max_len: int = 1 << 20) -> str:
    buf = ctypes.create_string_buffer(max_len)
    n = self._lib.kfcoord_kv_get(self._handle, key.encode(), buf, max_len)
    if n == -2:
      raise ValueError(f"value for {key} exceeds {max_len} bytes")
    if n < 0:
      raise RuntimeError(f"GET {key} failed")
    return buf.value.decode()

  def kv_get(self, key: str, max_len: int = 1 << 20) -> bytes:
    """Blocking fetch (bootstrap exchange: workers GET what rank 0 PUT)."""
    return _decode_kv_token(self._kv_get_raw(key, max_len))

  def _kv_tryget_raw(self, key: str,
                     max_len: int = 1 << 20) -> Optional[str]:
    """Non-blocking probe; None when the key is absent."""
    buf = ctypes.create_string_buffer(max_len)
    n = self._lib.kfcoord_kv_tryget(self._handle, key.encode(), buf,
                                    max_len)
    if n == -3:
      return None
    if n == -2:
      raise ValueError(f"value for {key} exceeds {max_len} bytes")
    if n < 0:
      raise RuntimeError(f"TRYGET {key} failed")
    return buf.value.decode()

  def kv_tryget(self, key: str, max_len: int = 1 << 20) -> Optional[bytes]:
    """Non-blocking kv_get; None when the key is absent."""
    token = self._kv_tryget_raw(key, max_len)
    return None if token is None else _decode_kv_token(token)

  def resize(self, new_size: int) -> int:
    """Request an elastic resize; returns the new generation
    (SURVEY 5.3: config-server-driven cluster resize)."""
    gen = self._lib.kfcoord_resize(self._handle, new_size)
    if gen < 0:
      raise RuntimeError("RESIZE failed")
    return gen

  def target_size(self) -> int:
    """The most recently requested elastic target size (blocks until a
    RESIZE has been issued)."""
    return int(self._kv_get_raw("__target_size__"))

  def try_target_size(self) -> Optional[int]:
    """Non-blocking variant; None when no RESIZE was ever issued."""
    token = self._kv_tryget_raw("__target_size__")
    return int(token) if token is not None else None

  def leave(self) -> None:
    self._lib.kfcoord_leave(self._handle)

  def close(self) -> None:
    if self._handle:
      self._lib.kfcoord_close(self._handle)
      self._handle = None

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
