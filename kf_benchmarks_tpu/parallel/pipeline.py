"""Pipeline (stage) parallelism over the named ``stage`` mesh axis.

Beyond-reference capability: the reference replicates the whole model
per worker (SURVEY 2.3) and has no inter-layer pipelining. The TPU
idiom is the SPMD pipeline: every device holds ONE stage's parameters
(the layer stack is sharded over the 'stage' axis), microbatches flow
device-to-device via non-cyclic ``lax.ppermute`` shifts, and a single
``lax.scan`` of M + S - 1 ticks executes the GPipe schedule -- the
bubble is (S-1)/(M+S-1) of the ticks, shrinking as microbatch count
grows. The construction is differentiable end-to-end (scan + ppermute
transpose), so one jax.grad gives pipeline-parallel training.

Equivalence vs the sequential layer stack (forward and backward) is
pinned by tests/test_pipeline_parallel.py on the virtual mesh.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kf_benchmarks_tpu.parallel.sequence import vary_like

STAGE_AXIS = "stage"


def spmd_pipeline(stage_fn: Callable, params_local, x,
                  num_microbatches: int, axis_name: str = STAGE_AXIS):
  """Run the S-stage GPipe schedule inside a shard_map body.

  stage_fn(params, x) -> y applies ONE stage; params_local is this
  device's stage's parameters (global layout: leading stage axis,
  sharded). x: (batch, ...) the full input, replicated over the stage
  axis; batch must divide by num_microbatches. Returns the full
  (batch, ...) output, replicated (every device ends with a copy).
  """
  s = lax.axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  m = num_microbatches
  batch = x.shape[0]
  if batch % m != 0:
    raise ValueError(f"batch {batch} not divisible by "
                     f"num_microbatches {m}")
  mb = batch // m
  mbatches = x.reshape((m, mb) + x.shape[1:])
  # Both carries become device-varying inside the loop (ppermute /
  # axis_index-dependent updates); mark the zero-initialised values
  # varying up front so the scan carry types line up. Under a COMPOSED
  # mesh (dp x pp x sp x ...) the input already varies on the data
  # axes, so the carries must carry that whole set plus the stage axis.
  out_accum, state = vary_like(
      mbatches,
      (jnp.zeros_like(mbatches),
       # The inter-stage register travelling the pipeline.
       jnp.zeros((mb,) + x.shape[1:], x.dtype)),
      extra_axes=(axis_name,))

  shift = [(i, i + 1) for i in range(s - 1)]  # non-cyclic: stage i -> i+1

  def tick(carry, t):
    state, out_accum = carry
    # Stage 0 injects microbatch t while t < M; later stages consume the
    # shifted register. The clamp keeps the gather in bounds during the
    # drain ticks (the result is masked off by `injecting`).
    inject = lax.dynamic_index_in_dim(
        mbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
    injecting = jnp.logical_and(idx == 0, t < m)
    x_in = jnp.where(injecting, inject, state)
    y = stage_fn(params_local, x_in)
    # The last stage retires microbatch t-(S-1) once the fill completes.
    out_t = t - (s - 1)
    retiring = jnp.logical_and(idx == s - 1, out_t >= 0)
    updated = lax.dynamic_update_index_in_dim(
        out_accum, y.astype(out_accum.dtype), jnp.clip(out_t, 0, m - 1),
        axis=0)
    out_accum = jnp.where(retiring, updated, out_accum)
    state = lax.ppermute(y, axis_name, shift)
    return (state, out_accum), None

  (_, out_accum), _ = lax.scan(
      tick, (state, out_accum), jnp.arange(m + s - 1))
  # Only the last stage holds real outputs; broadcast them to every
  # stage so downstream (loss, metrics) is replicated over the axis.
  out_accum = lax.psum(
      jnp.where(idx == s - 1, out_accum, jnp.zeros_like(out_accum)),
      axis_name)
  return out_accum.reshape((batch,) + x.shape[1:])


def make_pipeline(mesh: Mesh, stage_fn: Callable, num_microbatches: int,
                  axis_name: str = STAGE_AXIS):
  """Jitted pipeline over GLOBAL stacked stage params.

  params: a pytree whose leaves carry a leading (num_stages,) axis,
  sharded over ``axis_name``; x replicated. stage_fn sees one stage's
  slice (leading axis squeezed).
  """

  n_stages = mesh.shape[axis_name]

  def body(params, x):
    def squeeze(p):
      # One stage per device: the local slice of the (num_stages, ...)
      # stack must be exactly one stage. A larger multiple would shard
      # legally but silently drop every stage after the first.
      if p.shape[0] != 1:
        raise ValueError(
            f"params leading axis must equal the '{axis_name}' axis "
            f"size {n_stages} (one stage per device); got a local "
            f"slice of {p.shape[0]} stages")
      return p[0]

    local = jax.tree.map(squeeze, params)
    return spmd_pipeline(stage_fn, local, x, num_microbatches,
                         axis_name=axis_name)

  # P(axis_name) is a pytree-prefix spec: every params leaf is sharded
  # on its leading (num_stages,) axis.
  sharded = jax.shard_map(
      body, mesh=mesh, in_specs=(P(axis_name), P()), out_specs=P())
  return jax.jit(sharded)
