"""Expert parallelism: switch-routed MoE over the ``expert`` mesh axis.

Beyond-reference capability (the reference has no conditional
computation). The TPU-native shape is the Switch/GShard pattern:
tokens are sharded over the 'expert' axis alongside data parallelism,
each device owns num_experts/n experts, and two ``lax.all_to_all``
calls carry the dispatch/combine permutation over ICI:

  gate (replicated matmul) -> top-1 expert + capacity mask
  -> dispatch einsum to (experts, capacity, d) slots
  -> all_to_all: token-sharded -> expert-sharded
  -> per-expert FFN (one batched einsum over the local expert slice)
  -> all_to_all back -> combine einsum * gate probability

Tokens over capacity are dropped (output 0 -- callers add the
residual), exactly the Switch Transformer semantic; the standard
load-balancing auxiliary loss is returned alongside. Equivalence vs a
hand-rolled per-token loop with identical capacity ordering is pinned
by tests/test_expert_parallel.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

EXPERT_AXIS = "expert"


def switch_moe(x, gate_w, w1, b1, w2, b2, capacity: int,
               axis_name: str = EXPERT_AXIS) -> Tuple[jnp.ndarray,
                                                      jnp.ndarray]:
  """Top-1 (Switch) MoE inside a shard_map body.

  x: (tokens_local, d) -- this device's token shard.
  gate_w: (d, num_experts_global) replicated router weights.
  w1/b1/w2/b2: this device's expert slice -- leading axis
  num_experts_local = num_experts_global / axis_size.
  capacity: per-expert slot count PER SOURCE DEVICE.

  Returns (out, aux_loss): out (tokens_local, d) with over-capacity
  tokens zeroed; aux_loss the Switch load-balance penalty (already
  pmean-ed over the axis).
  """
  n = lax.axis_size(axis_name)
  tokens, d = x.shape
  e_local = w1.shape[0]
  e_global = n * e_local
  f32 = jnp.float32

  logits = x.astype(f32) @ gate_w.astype(f32)        # (N, E)
  probs = jax.nn.softmax(logits, axis=-1)
  expert_idx = jnp.argmax(probs, axis=-1)            # (N,)
  gate = jnp.max(probs, axis=-1)                     # (N,)

  assign = jax.nn.one_hot(expert_idx, e_global, dtype=f32)   # (N, E)
  # Position of each token in its expert's queue, in token order --
  # the deterministic capacity-drop priority.
  pos = jnp.cumsum(assign, axis=0) - 1.0                     # (N, E)
  keep = assign * (pos < capacity)                           # (N, E)
  slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                        dtype=f32) * keep[..., None]         # (N, E, C)

  # Switch aux loss: E * sum_e( fraction_tokens_e * mean_prob_e ),
  # averaged over devices (token statistics are per-shard).
  frac_tokens = jnp.mean(assign, axis=0)
  frac_probs = jnp.mean(probs, axis=0)
  aux_loss = lax.pmean(
      e_global * jnp.sum(frac_tokens * frac_probs), axis_name)

  dispatch = jnp.einsum("nec,nd->ecd", slot, x.astype(f32))  # (E, C, d)
  # (E, C, d) -> (n, e_local, C, d); all_to_all swaps the leading
  # device-chunk axis so each device ends with ITS experts' slots from
  # every source device.
  dispatch = dispatch.reshape(n, e_local, capacity, d)
  dispatch = lax.all_to_all(dispatch, axis_name, split_axis=0,
                            concat_axis=0)          # (n_src, e_l, C, d)

  h = jnp.einsum("secd,edf->secf", dispatch, w1.astype(f32))
  h = jax.nn.gelu(h + b1.astype(f32)[None, :, None, :])
  y = jnp.einsum("secf,efd->secd", h, w2.astype(f32))
  y = y + b2.astype(f32)[None, :, None, :]

  y = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0)
  y = y.reshape(e_global, capacity, d)
  out = jnp.einsum("nec,ecd->nd", slot, y) * gate[:, None]
  return out.astype(x.dtype), aux_loss


def make_switch_moe(mesh: Mesh, capacity: int,
                    axis_name: str = EXPERT_AXIS):
  """Jitted Switch MoE over GLOBAL arrays: tokens (N, d) sharded over
  ``axis_name``, expert stacks (E, ...) likewise, router replicated."""

  def body(x, gate_w, w1, b1, w2, b2):
    return switch_moe(x, gate_w, w1, b1, w2, b2, capacity,
                      axis_name=axis_name)

  sharded = jax.shard_map(
      body, mesh=mesh,
      in_specs=(P(axis_name), P(), P(axis_name), P(axis_name),
                P(axis_name), P(axis_name)),
      out_specs=(P(axis_name), P()))
  return jax.jit(sharded)


def reference_switch_moe(x_grouped, gate_w, w1, b1, w2, b2,
                         capacity: int):
  """Hand-rolled single-device reference with the same semantics.

  x_grouped: (groups, tokens_per_group, d) -- one group per device
  shard, capacity applies within each group (matching the per-shard
  queues of the SPMD version). Pure Python loops; test-only.
  """
  import numpy as np
  groups, tokens, d = x_grouped.shape
  e_global = gate_w.shape[1]
  out = np.zeros((groups, tokens, d), np.float32)
  aux = 0.0
  for g in range(groups):
    xg = np.asarray(x_grouped[g], np.float32)
    logits = xg @ np.asarray(gate_w, np.float32)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    counts = np.zeros(e_global, np.int64)
    for t in range(tokens):
      e = int(idx[t])
      if counts[e] >= capacity:
        counts[e] += 1
        continue
      counts[e] += 1
      h = xg[t] @ np.asarray(w1[e], np.float32) + np.asarray(
          b1[e], np.float32)
      h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
      y = h @ np.asarray(w2[e], np.float32) + np.asarray(
          b2[e], np.float32)
      out[g, t] = y * probs[t, e]
    frac_tokens = np.bincount(idx, minlength=e_global) / tokens
    aux += e_global * float((frac_tokens * probs.mean(0)).sum())
  return out, aux / groups
