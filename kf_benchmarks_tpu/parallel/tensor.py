"""Tensor (model) parallelism over the named ``tensor`` mesh axis.

Beyond-reference capability: the reference's only model-parallel
machinery is parameter placement across parameter servers (SURVEY 2.3
-- it never splits a single layer's math). On TPU the idiomatic
pattern is Megatron-style intra-layer sharding expressed as shard_map
collectives so the MXU sees full-size matmuls on every device and ICI
carries exactly one all-reduce per MLP / attention block:

* ``column_parallel_dense`` -- weight sharded on the OUTPUT feature
  axis; activations replicated in, feature-sharded out; no collective.
* ``row_parallel_dense`` -- weight sharded on the INPUT feature axis;
  feature-sharded activations in, replicated out via one ``psum``.
* ``parallel_mlp`` -- column -> activation -> row: the canonical pair
  whose interior activation never materialises unsharded.
* ``parallel_attention_heads`` -- attention-head sharding: QKV
  projections column-parallel (each device owns heads/n heads), the
  output projection row-parallel; one psum per attention block.

All functions run INSIDE a shard_map body and take the LOCAL weight
shards; ``make_parallel_mlp`` wraps mesh + specs for global callers.
Equivalence vs single-device dense math (forward and backward) is
pinned by tests/test_tensor_parallel.py.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kf_benchmarks_tpu.parallel import sequence as _sequence

TENSOR_AXIS = "tensor"


def column_parallel_dense(x, w_local, b_local=None):
  """y_local = x @ W[:, shard] (+ b[shard]): output feature-sharded.

  x: (..., d_in) replicated over the tensor axis; w_local:
  (d_in, d_out/n); b_local: (d_out/n,). No collective -- the sharded
  output feeds a row-parallel consumer.
  """
  y = jnp.einsum("...i,ij->...j", x, w_local)
  if b_local is not None:
    y = y + b_local
  return y


def row_parallel_dense(x_local, w_local, b=None,
                       axis_name: str = TENSOR_AXIS):
  """y = psum_n(x[shard] @ W[shard, :]) (+ b): output replicated.

  x_local: (..., d_in/n) feature-sharded; w_local: (d_in/n, d_out);
  b: (d_out,) replicated -- added AFTER the psum so it lands once.
  """
  y = lax.psum(jnp.einsum("...i,ij->...j", x_local, w_local), axis_name)
  if b is not None:
    y = y + b
  return y


def parallel_mlp(x, w1_local, b1_local, w2_local, b2,
                 activation: Callable = jax.nn.gelu,
                 axis_name: str = TENSOR_AXIS):
  """Megatron MLP: column-parallel up-projection, activation on the
  shard, row-parallel down-projection; exactly one psum."""
  h = activation(column_parallel_dense(x, w1_local, b1_local))
  return row_parallel_dense(h, w2_local, b2, axis_name=axis_name)


def parallel_attention_heads(x, wqkv_local, wo_local, bo=None,
                             num_heads_local: Optional[int] = None,
                             causal: bool = False,
                             axis_name: str = TENSOR_AXIS):
  """Head-sharded self-attention inside a shard_map body.

  x: (batch, seq, d_model) replicated over the tensor axis.
  wqkv_local: (d_model, 3 * heads_local * head_dim) -- the column-
  parallel fused QKV projection for THIS device's heads.
  wo_local: (heads_local * head_dim, d_model) -- the row-parallel
  output projection shard. One psum total (inside row_parallel_dense).
  """
  b_, t, _ = x.shape
  qkv = column_parallel_dense(x, wqkv_local)          # (B,T,3*hl*hd)
  three_hd = qkv.shape[-1]
  if num_heads_local is None:
    raise ValueError("num_heads_local is required (static head split)")
  head_dim = three_hd // (3 * num_heads_local)
  qkv = qkv.reshape(b_, t, 3, num_heads_local, head_dim)
  q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B,T,hl,hd)
  out = _sequence.full_attention(q, k, v, causal=causal)
  out = out.reshape(b_, t, num_heads_local * head_dim)
  return row_parallel_dense(out, wo_local, bo, axis_name=axis_name)


def make_parallel_mlp(mesh: Mesh, axis_name: str = TENSOR_AXIS,
                      activation: Callable = jax.nn.gelu):
  """Jitted MLP over GLOBAL weights: w1 (d_in, d_hidden) sharded on its
  output axis, w2 (d_hidden, d_out) on its input axis, x replicated."""

  def body(x, w1, b1, w2, b2):
    return parallel_mlp(x, w1, b1, w2, b2, activation=activation,
                        axis_name=axis_name)

  sharded = jax.shard_map(
      body, mesh=mesh,
      in_specs=(P(), P(None, axis_name), P(axis_name),
                P(axis_name, None), P()),
      out_specs=P())
  return jax.jit(sharded)


def make_parallel_attention(mesh: Mesh, num_heads: int,
                            axis_name: str = TENSOR_AXIS,
                            causal: bool = False):
  """Jitted head-sharded attention over GLOBAL weights: wqkv
  (d_model, 3, num_heads, head_dim) sharded on the head axis, wo
  (num_heads, head_dim, d_model) likewise; x replicated."""
  n = mesh.shape[axis_name]
  if num_heads % n != 0:
    raise ValueError(
        f"tensor-parallel attention needs num_heads % axis_size == 0, "
        f"got {num_heads} heads over {n} '{axis_name}' devices")
  heads_local = num_heads // n

  def body(x, wqkv, wo, bo):
    d_model = x.shape[-1]
    head_dim = wqkv.shape[-1]
    wqkv_flat = wqkv.reshape(d_model, 3 * heads_local * head_dim)
    wo_flat = wo.reshape(heads_local * head_dim, d_model)
    return parallel_attention_heads(
        x, wqkv_flat, wo_flat, bo, num_heads_local=heads_local,
        causal=causal, axis_name=axis_name)

  sharded = jax.shard_map(
      body, mesh=mesh,
      in_specs=(P(), P(None, None, axis_name), P(axis_name), P()),
      out_specs=P())
  return jax.jit(sharded)
