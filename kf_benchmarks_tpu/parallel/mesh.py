"""Device mesh construction.

The reference enumerates raw device strings per tower
(ref: benchmark_cnn.py:1419-1426); the TPU-native analog is a named
jax.sharding.Mesh whose axes carry the parallelism semantics. Data
parallelism (the only axis the reference has) is the 'replica' axis;
model axes ('stage', 'tensor') are reserved for the pipeline/tensor
extensions.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPLICA_AXIS = "replica"


def get_devices(device_kind: str = "tpu", num_devices: Optional[int] = None):
  """Resolve the local device list (ref: benchmark_cnn.py:1419-1426)."""
  devices = jax.devices()
  if device_kind == "cpu":
    cpus = [d for d in devices if d.platform == "cpu"]
    devices = cpus or devices
  if num_devices is not None:
    if num_devices > len(devices):
      raise ValueError(
          f"Requested {num_devices} devices but only {len(devices)} "
          f"available ({[str(d) for d in devices]})")
    devices = devices[:num_devices]
  return devices


def build_mesh(num_devices: Optional[int] = None, device_kind: str = "tpu",
               devices: Optional[Sequence] = None) -> Mesh:
  """1-D data-parallel mesh over the replica axis."""
  if devices is None:
    devices = get_devices(device_kind, num_devices)
  return Mesh(np.asarray(devices), (REPLICA_AXIS,))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P(REPLICA_AXIS))
