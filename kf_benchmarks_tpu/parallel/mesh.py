"""Device mesh construction.

The reference enumerates raw device strings per tower
(ref: benchmark_cnn.py:1419-1426); the TPU-native analog is a named
jax.sharding.Mesh whose axes carry the parallelism semantics. Data
parallelism (the only axis the reference has) is the 'replica' axis;
model axes ('stage', 'tensor') are reserved for the pipeline/tensor
extensions.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPLICA_AXIS = "replica"


def get_devices(device_kind: str = "tpu", num_devices: Optional[int] = None):
  """Resolve the device list (ref: benchmark_cnn.py:1419-1426).

  ``num_devices`` counts devices PER PROCESS (the reference's
  one-process-per-GPU num_gpus); under multi-process SPMD the mesh spans
  every process's devices, so the resolved list is global."""
  devices = jax.devices()
  if device_kind == "cpu":
    cpus = [d for d in devices if d.platform == "cpu"]
    devices = cpus or devices
  if num_devices is not None:
    # Take the first num_devices of EACH process's devices (a global
    # prefix could exclude some processes entirely, leaving them with no
    # addressable shard of the mesh).
    by_proc = {}
    for d in devices:
      by_proc.setdefault(d.process_index, []).append(d)
    picked = []
    for pid in sorted(by_proc):
      if len(by_proc[pid]) < num_devices:
        raise ValueError(
            f"Requested {num_devices} devices per process but process "
            f"{pid} has only {len(by_proc[pid])} "
            f"({[str(d) for d in by_proc[pid]]})")
      picked.extend(by_proc[pid][:num_devices])
    devices = picked
  return devices


def build_mesh(num_devices: Optional[int] = None, device_kind: str = "tpu",
               devices: Optional[Sequence] = None) -> Mesh:
  """1-D data-parallel mesh over the replica axis (global under
  multi-process SPMD)."""
  if devices is None:
    devices = get_devices(device_kind, num_devices)
  return Mesh(np.asarray(devices), (REPLICA_AXIS,))


def put_batch(batch, sharding: NamedSharding):
  """Host batch -> device, sharded over the batch axis. Single-process:
  a plain device_put. Multi-process: each process contributes the shard
  for ITS devices (jax.make_array_from_process_local_data), the
  jax-native form of the reference's per-worker input splits
  (ref: preprocessing shift_ratio sharding + per-device StagingAreas)."""
  if jax.process_count() > 1:
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), batch)
  return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P(REPLICA_AXIS))


def chunk_batch_sharding(mesh: Mesh) -> NamedSharding:
  """Sharding for a staged multi-step chunk (--steps_per_dispatch):
  leading axis = staged steps (replicated), second axis = the global
  batch sharded over replicas -- the per-step batch_sharding behind a
  chunk dimension."""
  return NamedSharding(mesh, P(None, REPLICA_AXIS))
