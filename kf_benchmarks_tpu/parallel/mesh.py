"""Device mesh construction.

The reference enumerates raw device strings per tower
(ref: benchmark_cnn.py:1419-1426); the TPU-native analog is a named
jax.sharding.Mesh whose axes carry the parallelism semantics. Data
parallelism (the only axis the reference has) is the 'replica' axis;
model axes ('stage', 'tensor') are reserved for the pipeline/tensor
extensions.

Two mesh families serve the training runtime:

* the 1-D ``('replica',)`` mesh -- every replicated/gossip strategy
  (``build_mesh``), and
* the named 2-D ``('batch', 'model')`` mesh (``build_mesh_2d``) behind
  ``--mesh_shape=BxM`` / ``--shard_optimizer_state``: the batch shards
  over ``'batch'``; optimizer state shards 1/(B*M) over BOTH axes via
  the stacked ``(n, k)`` row layout of ops/sharded.py inside the
  shard_mapped step -- the GSPMD named-mesh idiom (Xu et al. 2021)
  applied to the reference's central variable placement
  (ref: variable_mgr.py:201-243). :func:`leaf_spec` /
  :func:`tree_shardings` express the SAME 1/n layout as a
  size-thresholded ``NamedSharding`` rule for jit-native
  (``in_shardings``) consumers at the library boundary -- the form the
  remaining FSDP forward leg needs (ROADMAP item 1); the core step
  does not consume them. The composed LM trainer refines the same
  ``'model'`` axis into its seq x tensor factors
  (parallel/transformer.py compose_on_model_axis), so every
  parallelism family shares one axis system.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPLICA_AXIS = "replica"
BATCH_AXIS = "batch"
MODEL_AXIS = "model"

# Leaves below this element count stay replicated under the GSPMD leaf
# rule (tree_shardings): sharding tiny tensors buys no memory and costs
# a collective each.
SHARD_MIN_ELEMS = 1024


def get_devices(device_kind: str = "tpu", num_devices: Optional[int] = None):
  """Resolve the device list (ref: benchmark_cnn.py:1419-1426).

  ``num_devices`` counts devices PER PROCESS (the reference's
  one-process-per-GPU num_gpus); under multi-process SPMD the mesh spans
  every process's devices, so the resolved list is global."""
  devices = jax.devices()
  if device_kind == "cpu":
    cpus = [d for d in devices if d.platform == "cpu"]
    devices = cpus or devices
  if num_devices is not None:
    # Take the first num_devices of EACH process's devices (a global
    # prefix could exclude some processes entirely, leaving them with no
    # addressable shard of the mesh).
    by_proc = {}
    for d in devices:
      by_proc.setdefault(d.process_index, []).append(d)
    picked = []
    for pid in sorted(by_proc):
      if len(by_proc[pid]) < num_devices:
        raise ValueError(
            f"Requested {num_devices} devices per process but process "
            f"{pid} has only {len(by_proc[pid])} "
            f"({[str(d) for d in by_proc[pid]]})")
      picked.extend(by_proc[pid][:num_devices])
    devices = picked
  return devices


def build_mesh(num_devices: Optional[int] = None, device_kind: str = "tpu",
               devices: Optional[Sequence] = None) -> Mesh:
  """1-D data-parallel mesh over the replica axis (global under
  multi-process SPMD)."""
  if devices is None:
    devices = get_devices(device_kind, num_devices)
  return Mesh(np.asarray(devices), (REPLICA_AXIS,))


def build_mesh_2d(num_batch: int, num_model: int,
                  device_kind: str = "tpu",
                  devices: Optional[Sequence] = None) -> Mesh:
  """Named 2-D ``(batch, model)`` mesh over ``num_batch * num_model``
  devices: axis ``'batch'`` carries data parallelism (the reference's
  replica axis), ``'model'`` carries the state-sharding/tensor
  dimension. Device order is row-major over (batch, model), so device
  ``(b, m)`` has flat shard index ``b * num_model + m`` -- the order
  ops/sharded.py's scatter/slice/gather blocks follow."""
  if num_batch < 1 or num_model < 1:
    raise ValueError(f"mesh shape {num_batch}x{num_model}: both axes "
                     "must be positive")
  if devices is None:
    devices = get_devices(device_kind, num_batch * num_model)
  if len(devices) != num_batch * num_model:
    raise ValueError(
        f"mesh shape {num_batch}x{num_model} needs "
        f"{num_batch * num_model} devices, have {len(devices)}")
  return Mesh(np.asarray(devices).reshape(num_batch, num_model),
              (BATCH_AXIS, MODEL_AXIS))


def data_axis(mesh: Mesh) -> str:
  """The axis the global batch is sharded over: 'batch' on the 2-D
  mesh, 'replica' on the 1-D family."""
  return BATCH_AXIS if BATCH_AXIS in mesh.axis_names else REPLICA_AXIS


def state_axes(mesh: Mesh):
  """Every mesh axis, as the tuple the stacked per-device state's
  leading dim is sharded over (and metric pmeans reduce over)."""
  return tuple(mesh.axis_names)


def num_data_replicas(mesh: Mesh) -> int:
  """Data-parallel width: the global batch is ``per_device_batch`` times
  this (model-axis peers re-compute the same batch shard)."""
  return int(mesh.shape[data_axis(mesh)])


def leaf_spec(shape, mesh: Mesh, min_elems: int = SHARD_MIN_ELEMS) -> P:
  """Size-thresholded GSPMD leaf rule for params/opt-state trees on the
  2-D mesh (the jit-inserted-collective idiom of GSPMD, Xu et al. 2021;
  the compiler analog of the reference's central variable placement,
  variable_mgr.py:201-243): shard dim 0 over the combined
  ``('batch', 'model')`` axes when the leaf is big enough and dim 0
  divides the mesh, else replicate."""
  n = mesh.devices.size
  ndims = len(shape)
  if (ndims == 0 or math.prod(shape) < min_elems or shape[0] % n):
    return P()
  return P(state_axes(mesh))


def tree_shardings(mesh: Mesh, tree):
  """NamedShardings for a params/opt-state pytree under the
  :func:`leaf_spec` rule -- the ``jax.jit`` ``in_shardings`` form of
  the sharded-state layout (SNIPPETS.md [2]/[3] pattern), for
  jit-native library consumers. The train step itself carries the
  equivalent stacked ``(n, k)`` row layout (ops/sharded.py) inside
  shard_map; see the module docstring."""
  return jax.tree.map(
      lambda x: NamedSharding(mesh, leaf_spec(tuple(x.shape), mesh)), tree)


def put_batch(batch, sharding: NamedSharding):
  """Host batch -> device, sharded over the batch axis. Single-process:
  a plain device_put. Multi-process: each process contributes the shard
  for ITS devices (jax.make_array_from_process_local_data), the
  jax-native form of the reference's per-worker input splits
  (ref: preprocessing shift_ratio sharding + per-device StagingAreas)."""
  if jax.process_count() > 1:
    # all-ranks: process_count() is identical on every process, and
    # every process feeds a batch each step -- all ranks reach this
    # cross-host assembly together.
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), batch)
  return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P(data_axis(mesh)))


def chunk_batch_sharding(mesh: Mesh) -> NamedSharding:
  """Sharding for a staged multi-step chunk (--steps_per_dispatch):
  leading axis = staged steps (replicated), second axis = the global
  batch sharded over replicas -- the per-step batch_sharding behind a
  chunk dimension."""
  return NamedSharding(mesh, P(None, data_axis(mesh)))
