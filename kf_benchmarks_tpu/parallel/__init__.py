"""Parallelism strategies + collective primitives.

Replaces the reference's VariableMgr hierarchy (ref:
scripts/tf_cnn_benchmarks/variable_mgr.py) and the KungFu distributed
runtime surface (SURVEY 2.9) with SPMD designs over a jax.sharding.Mesh.

Beyond the reference's batch-only parallelism, the model-parallel axes
are first-class: sequence/context (`sequence.py`: ring, zigzag
load-balanced causal ring, Ulysses, single-chip blockwise attention),
tensor (`tensor.py`: Megatron
column/row sharding), pipeline (`pipeline.py`: SPMD GPipe), expert
(`expert.py`: Switch MoE), and their dp x sp x tp composition
(`transformer.py`).
"""
