"""Parallelism strategies + collective primitives.

Replaces the reference's VariableMgr hierarchy (ref:
scripts/tf_cnn_benchmarks/variable_mgr.py) and the KungFu distributed
runtime surface (SURVEY 2.9) with SPMD designs over a jax.sharding.Mesh.
"""
