"""Parallelism strategies: the VariableMgr hierarchy, re-designed SPMD.

The reference's VariableMgr subclasses (ref: variable_mgr.py:28-831)
answer: where do variables live, how are gradients aggregated, what syncs
at init. Under SPMD all replicas run one program, so each strategy
becomes a set of pure hooks called inside the shard_mapped train step:

  reduce_gradients  -- gradient aggregation (psum / spec-driven / none)
  pre_update        -- weight transform before the optimizer step (SMA)
  post_update       -- weight transform after the step (pair-averaging)
  sync_batch_stats  -- BN running-stat treatment across replicas
  broadcast_init    -- replica-0 state broadcast at start

Mapping from --variable_update (ref selection: benchmark_cnn.py:1481-1524):
  independent            -> no reduction (ref: variable_mgr.py:164-198)
  replicated             -> pmean grads (ref: variable_mgr.py:277-368)
  parameter_server       -> pmean grads; sharded optimizer state is the
                            TPU analog of central variable placement
                            (ref: variable_mgr.py:201-243; SURVEY 5.8)
  distributed_replicated -> pmean within + across processes (one SPMD
                            program spans hosts; ref: variable_mgr.py:704-831)
  distributed_all_reduce / collective_all_reduce
                         -> spec-driven reduction (ref: variable_mgr.py:371-625)
  horovod                -> per-gradient pmean (ref: benchmark_cnn.py:3122-3130)
  kungfu                 -> optimizer-level hooks per --kungfu_option
                            (ref: benchmark_cnn.py:1192-1204)
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax import lax

from kf_benchmarks_tpu.parallel import kungfu
from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS


class Strategy:
  """Base: single-replica semantics (no cross-replica traffic)."""

  name = "independent"
  # Whether gradients are averaged across replicas (determines whether the
  # effective batch for LR scaling is the global batch).
  cross_replica = False

  def __init__(self, params=None):
    self.params = params

  def reduce_gradients(self, grads, axis_name=REPLICA_AXIS):
    return grads

  def pre_update(self, model_params, step, axis_name=REPLICA_AXIS):
    return model_params

  def post_update(self, model_params, step, axis_name=REPLICA_AXIS):
    return model_params

  def sync_batch_stats(self, batch_stats, axis_name=REPLICA_AXIS):
    """Replicated modes keep BN stats identical (pmean); independent modes
    keep tower-local stats like the reference's per-tower BN."""
    return batch_stats

  def broadcast_init(self, tree, axis_name=REPLICA_AXIS):
    """Replica-0 broadcast at session start (ref: benchmark_cnn.py:2094-2100).
    Under SPMD, identical init makes this a no-op for most strategies, but
    independent/kungfu keep it for parity with explicitly diverged state."""
    return tree


class IndependentStrategy(Strategy):
  """(ref: variable_mgr.py:164-198)"""
  name = "independent"


class ReplicatedStrategy(Strategy):
  """All-reduce averaged gradients, replicated weights
  (ref: variable_mgr.py:277-368).

  ``reducer`` is the flag-selected reduction path built by
  ops/allreduce.build_reducer -- all_reduce_spec planner, gradient
  repacking, small-grad aggregation, or hierarchical copy (ref:
  batch_allreduce.py:300-317 algorithm_from_params); None = direct pmean.
  """

  name = "replicated"
  cross_replica = True

  def __init__(self, params=None, reducer=None):
    super().__init__(params)
    self.reducer = reducer

  def reduce_gradients(self, grads, axis_name=REPLICA_AXIS):
    if self.reducer is not None:
      return self.reducer(grads, axis_name)
    return kungfu.allreduce_mean(grads, axis_name)

  def sync_batch_stats(self, batch_stats, axis_name=REPLICA_AXIS):
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), batch_stats)


class ParameterServerStrategy(ReplicatedStrategy):
  """PS analog: synchronous aggregation; on TPU the 'server' is the
  sharded optimizer state, not a host process (SURVEY 5.8 gRPC-PS row)."""
  name = "parameter_server"


class ShardedOptimizerStrategy(ReplicatedStrategy):
  """ZeRO/FSDP sharded optimizer state (--shard_optimizer_state) on the
  named 2-D ('batch', 'model') mesh: the faithful TPU rendering of the
  reference's central variable placement (the PS "server copy" of
  variables + optimizer slots, ref: variable_mgr.py:201-243; across
  hosts :704-831; SURVEY 5.8) -- the server is the 1/n state shard each
  device owns, gradients meet in a reduce-scatter instead of the
  all-reduce, and updated params return by all-gather.

  The hooks here are markers only: the scatter/apply/gather mechanics
  live in train_step.py's sharded branch + ops/sharded.py (the step
  owns gradient packing and the optimizer apply, exactly as it owns
  them for sequential_apply). ``sync_batch_stats`` stays the inherited
  pmean -- BN statistics remain replicated; only optimizer state
  shards."""

  name = "parameter_server(sharded)"
  cross_replica = True
  sharded_state = True

  def reduce_gradients(self, grads, axis_name=REPLICA_AXIS):
    raise NotImplementedError(
        "sharded-state gradient reduction is the step's reduce-scatter "
        "(train_step.py + ops/sharded.py), not a strategy hook")


class AsyncParameterServerStrategy(ReplicatedStrategy):
  """Async PS (--cross_replica_sync=false, ref: benchmark_cnn.py:520-522).

  In the reference every worker applies its own UNAGGREGATED gradient to
  the one PS-hosted weight + optimizer-state copy; the state stays
  shared, only the averaging disappears. The SPMD reformulation keeps
  exactly those properties, by optimizer class:

  * plain SGD: N sequential unaveraged applications to shared weights
    collapse into ONE update by the gradient SUM -- gradients are
    psum-summed and applied once (exact, and cheapest).
  * stateful optimizers (momentum/rmsprop/adam): the collapse does not
    hold, so ``sequential_apply`` makes the train step all-gather the
    per-replica gradients and apply them ONE AT A TIME through the
    shared optimizer state (a lax.scan over replicas) -- a faithful
    serialization of the PS's nondeterministic interleaving, fixed to
    replica-index order so every replica computes the identical result.

  The reference's timing asynchrony itself (workers at different steps,
  GlobalStepWatcher) has no SPMD analog -- steps run in lockstep; the
  per-step window math is therefore exact (see KungFuStrategy's
  throughput note).

  Cost: ``sequential_apply`` is O(n) optimizer applications per step plus
  an all-gather of n full gradient trees -- a CORRECTNESS mode, not a
  scaling mode. validation.py caps it at
  ASYNC_PS_SEQUENTIAL_MAX_DEVICES; the measured cost curve vs n is in
  PERF.md (async-PS micro-benchmark)."""

  name = "parameter_server(async)"
  # Unaveraged gradients: the effective step scale follows the
  # per-worker batch, as the reference's async mode behaves.
  cross_replica = False

  def __init__(self, params=None, reducer=None):
    super().__init__(params, reducer=reducer)
    self.sequential_apply = bool(
        params is not None and getattr(params, "optimizer", "sgd") != "sgd")

  def reduce_gradients(self, grads, axis_name=REPLICA_AXIS):
    if self.sequential_apply:
      # The train step gathers and serializes these local gradients
      # through the shared optimizer state; summing here would apply
      # every gradient n times.
      return grads
    if self.reducer is not None:
      grads = self.reducer(grads, axis_name)
      n = lax.axis_size(axis_name)
      return jax.tree.map(lambda g: g * n, grads)  # undo the mean
    return jax.tree.map(lambda g: lax.psum(g, axis_name), grads)


class CollectiveAllReduceStrategy(ReplicatedStrategy):
  """Spec-driven reduction (ref: variable_mgr.py:486-625). The all-reduce
  spec planner (ops/allreduce.py) may decompose pmean into
  reduce-scatter + all-gather or hierarchical 2-level reductions."""
  name = "collective_all_reduce"

  def __init__(self, params=None, planner=None, reducer=None):
    if planner is not None and reducer is None:
      reducer = planner.reduce
    super().__init__(params, reducer=reducer)
    self.planner = planner


class KungFuStrategy(Strategy):
  """KungFu optimizer-wrapper semantics (ref: benchmark_cnn.py:1192-1204;
  SURVEY 2.9), dispatched on --kungfu_option:

    sync_sgd  -- SynchronousSGDOptimizer: pmean gradients before apply
    async_sgd -- PairAveragingOptimizer: local grads + pairwise weight
                 gossip (ppermute), reformulated synchronous (SURVEY 7.4)
    sma       -- SynchronousAveragingOptimizer: average weights, then
                 local gradient step

  Throughput semantics under async_sgd/sma: AD-PSGD's asynchrony does
  not exist under SPMD -- every replica executes the same step in
  lockstep, so a "global step" is one synchronized step of all replicas
  and the standard window math applies unchanged. The reference's
  GlobalStepWatcher (ref: benchmark_cnn.py:639-684), which existed to
  measure true global-step rate when replicas advanced independently,
  has nothing to measure here by construction; the asynchrony is
  reformulated into the deterministic gossip schedule, not the timing.
  """

  name = "kungfu"

  def __init__(self, params=None, option: str = "sync_sgd"):
    super().__init__(params)
    if option not in ("sync_sgd", "async_sgd", "sma"):
      raise ValueError(f"Invalid kungfu_option {option!r}")
    self.option = option
    self.cross_replica = option == "sync_sgd"

  def reduce_gradients(self, grads, axis_name=REPLICA_AXIS):
    if self.option == "sync_sgd":
      return kungfu.allreduce_mean(grads, axis_name)
    return grads

  def pre_update(self, model_params, step, axis_name=REPLICA_AXIS):
    if self.option == "sma":
      return kungfu.sync_average(model_params, axis_name)
    return model_params

  def post_update(self, model_params, step, axis_name=REPLICA_AXIS):
    if self.option == "async_sgd":
      return kungfu.pair_average(model_params, step, axis_name)
    return model_params

  def sync_batch_stats(self, batch_stats, axis_name=REPLICA_AXIS):
    if self.option == "sync_sgd":
      return jax.tree.map(lambda x: lax.pmean(x, axis_name), batch_stats)
    return batch_stats

  def broadcast_init(self, tree, axis_name=REPLICA_AXIS):
    return kungfu.broadcast(tree, root=0, axis_name=axis_name)


def get_strategy(params) -> Strategy:
  """Strategy selection (ref: benchmark_cnn.py:1481-1524)."""
  vu = params.variable_update
  if getattr(params, "shard_optimizer_state", False):
    # validation.validate_cross_flags restricts this to the synchronous
    # replicated/parameter_server family; the sharded strategy subsumes
    # both (the state shard IS the central placement).
    return ShardedOptimizerStrategy(params)
  if vu == "independent":
    return IndependentStrategy(params)
  if vu == "kungfu":
    return KungFuStrategy(params, option=params.kungfu_option)
  from kf_benchmarks_tpu.ops import allreduce
  reducer = allreduce.build_reducer(params)
  if vu in ("replicated", "distributed_replicated"):
    return ReplicatedStrategy(params, reducer=reducer)
  if vu == "parameter_server":
    if not params.cross_replica_sync:
      return AsyncParameterServerStrategy(params, reducer=reducer)
    return ParameterServerStrategy(params, reducer=reducer)
  if vu in ("collective_all_reduce", "distributed_all_reduce"):
    return CollectiveAllReduceStrategy(
        params, planner=allreduce.build_planner(params), reducer=reducer)
  if vu == "horovod":
    # Horovod's per-gradient allreduce has the same SPMD data plane as
    # replicated (ref: benchmark_cnn.py:3122-3130).
    s = ReplicatedStrategy(params, reducer=reducer)
    s.name = "horovod"
    return s
  raise ValueError(f"Unknown variable_update {vu!r}")
