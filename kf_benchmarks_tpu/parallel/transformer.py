"""Composed parallelism: a decoder-only LM trained over dp x sp x tp
(x ep via Switch-MoE blocks), or dp x pp x sp x tp with the layer
stack sharded over the GPipe stage axis (make_pipelined_train_step).

Beyond-reference capability, and the composition proof for the
parallel/ primitives: one shard_map training step over a
('replica', 'seq', 'tensor') mesh where

* the batch axis rides data parallelism ('replica'),
* the sequence axis rides ring attention ('seq',
  parallel/sequence.py) so context length scales with ring size,
* heads + MLP features ride Megatron sharding ('tensor',
  parallel/tensor.py) with one psum per attention/MLP block.

Gradients for axis-replicated parameters are pmean-ed over the data
and sequence axes (tensor-sharded leaves keep their shard gradients),
so the whole step is a single jit -- XLA overlaps the ring permutes,
the block matmuls, and the gradient reduction. Numerical equivalence
of loss AND the trained parameters against a single-device dense
implementation is pinned by tests/test_transformer_parallel.py.

The reference has nothing in this family (its parallelism is batch-only,
SURVEY 2.3/5.7); this module is the long-context/distributed design the
TPU rebuild treats as first-class.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kf_benchmarks_tpu.parallel import expert as ep_lib
from kf_benchmarks_tpu.parallel import pipeline as pp_lib
from kf_benchmarks_tpu.parallel import sequence as seq_lib
from kf_benchmarks_tpu.parallel import tensor as tp_lib
from kf_benchmarks_tpu.parallel.mesh import BATCH_AXIS, REPLICA_AXIS

SEQ_AXIS = seq_lib.SEQ_AXIS
TENSOR_AXIS = tp_lib.TENSOR_AXIS


def _data_axis(mesh: Mesh) -> str:
  """The data-parallel axis name of a composed-trainer mesh: 'batch' on
  the shared named-mesh family (compose_on_model_axis -- the same axis
  system as parallel/mesh.py build_mesh_2d), 'replica' on the legacy
  3-D/4-D grids. Axis NAMES carry no numerics: the two families produce
  bit-identical programs (tests/test_transformer_parallel.py)."""
  return BATCH_AXIS if BATCH_AXIS in mesh.axis_names else REPLICA_AXIS


def init_params(key, *, vocab: int, d_model: int, n_layers: int,
                n_heads: int, head_dim: int, d_ff: int, max_len: int,
                moe_every: int = 0, n_experts: int = 0) -> Dict[str, Any]:
  """Global (unsharded) parameter pytree; sharding comes from the
  in_specs of make_train_step, so the same tree drives both the
  parallel step and the single-device reference.

  moe_every > 0 replaces every moe_every-th block's dense MLP with a
  Switch-MoE layer of n_experts experts (expert parallelism rides the
  REPLICA axis -- experts are sharded where the tokens already are).
  """
  if moe_every and n_experts < 1:
    raise ValueError(
        f"moe_every={moe_every} needs n_experts >= 1, got {n_experts} "
        f"(a zero-expert gate would only fail later inside switch_moe)")
  scale = 0.02
  ks = iter(jax.random.split(key, 4 + 8 * n_layers))
  params = {
      "embed": jax.random.normal(next(ks), (vocab, d_model)) * scale,
      "pos": jax.random.normal(next(ks), (max_len, d_model)) * scale,
      "ln_f": jnp.ones((d_model,)),
      "blocks": [],
  }
  for i in range(n_layers):
    block = {
        "ln1": jnp.ones((d_model,)),
        "wqkv": jax.random.normal(
            next(ks), (d_model, 3, n_heads, head_dim)) * scale,
        "wo": jax.random.normal(
            next(ks), (n_heads, head_dim, d_model)) * scale,
        "ln2": jnp.ones((d_model,)),
    }
    if moe_every and (i + 1) % moe_every == 0:
      block["gate_w"] = jax.random.normal(
          next(ks), (d_model, n_experts)) * scale
      block["ew1"] = jax.random.normal(
          next(ks), (n_experts, d_model, d_ff)) * scale
      block["eb1"] = jnp.zeros((n_experts, d_ff))
      block["ew2"] = jax.random.normal(
          next(ks), (n_experts, d_ff, d_model)) * scale
      block["eb2"] = jnp.zeros((n_experts, d_model))
    else:
      block["w1"] = jax.random.normal(next(ks), (d_model, d_ff)) * scale
      block["b1"] = jnp.zeros((d_ff,))
      block["w2"] = jax.random.normal(next(ks), (d_ff, d_model)) * scale
      block["b2"] = jnp.zeros((d_model,))
    params["blocks"].append(block)
  return params


def param_specs(params, data_axis: str = REPLICA_AXIS) -> Dict[str, Any]:
  """PartitionSpecs: tensor-sharded leaves on TENSOR_AXIS (heads for
  attention, features for the dense MLP); MoE expert stacks sharded on
  the DATA axis (the expert axis -- experts live where the tokens are;
  'batch' on compose_on_model_axis meshes); everything else
  replicated."""
  dense = {
      "w1": P(None, TENSOR_AXIS), "b1": P(TENSOR_AXIS),
      "w2": P(TENSOR_AXIS, None), "b2": P(),
  }
  moe = {
      "gate_w": P(),
      "ew1": P(data_axis), "eb1": P(data_axis),
      "ew2": P(data_axis), "eb2": P(data_axis),
  }
  blocks = []
  for bp in params["blocks"]:
    spec = {
        "ln1": P(), "ln2": P(),
        "wqkv": P(None, None, TENSOR_AXIS),
        "wo": P(TENSOR_AXIS),
    }
    spec.update(moe if "gate_w" in bp else dense)
    blocks.append(spec)
  return {"embed": P(), "pos": P(), "ln_f": P(), "blocks": blocks}


def stack_blocks(params):
  """Per-layer block list -> ONE stacked block pytree (leading layer
  axis on every leaf), the layout the scan-over-layers path consumes.

  Requires a homogeneous (dense) stack: MoE blocks are heterogeneous
  under moe_every and their capacity queues are per data shard -- the
  same restriction to_pipelined() enforces for the stage axis.
  """
  blocks = params["blocks"]
  if any("gate_w" in b for b in blocks):
    raise ValueError(
        "scan-over-layers requires a homogeneous (dense) layer stack; "
        "MoE blocks are heterogeneous -- use the unscanned "
        "make_train_step for dp x sp x tp x ep")
  out = {k: v for k, v in params.items() if k != "blocks"}
  out["blocks"] = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
  return out


def unstack_blocks(params):
  """Inverse of stack_blocks (so trained scanned state compares
  leaf-for-leaf against the per-layer oracle's)."""
  stacked = params["blocks"]
  n_layers = jax.tree.leaves(stacked)[0].shape[0]
  blocks = [jax.tree.map(lambda x: x[i], stacked)
            for i in range(n_layers)]
  out = {k: v for k, v in params.items() if k != "blocks"}
  out["blocks"] = blocks
  return out


def fsdp_stack_blocks(stacked_params, n_shards: int):
  """stack_blocks() tree -> FSDP storage: every 'blocks' leaf (L, *s)
  becomes its per-layer flat shard stack (L, n, k), k = ceil(prod(s)/n)
  -- the ops/sharded.py (n, k) layout applied per layer, sharded over
  the combined (data, seq) axes by :func:`fsdp_param_specs` so each
  device holds one (L, 1, k) slice. The scan body re-assembles ONE
  layer per iteration (--shard_params's composed-trainer leg)."""
  out = {k: v for k, v in stacked_params.items() if k != "blocks"}

  def f(x):
    n_layers = x.shape[0]
    size = int(x.size) // n_layers
    k = -(-size // n_shards)
    flat = jnp.pad(x.reshape(n_layers, size),
                   ((0, 0), (0, n_shards * k - size)))
    return flat.reshape(n_layers, n_shards, k)

  out["blocks"] = jax.tree.map(f, stacked_params["blocks"])
  return out


def fsdp_unstack_blocks(fsdp_params, block_template):
  """Inverse of :func:`fsdp_stack_blocks` (host-side; tests compare the
  trained FSDP state against the dense oracle's): (L, n, k) stacks
  flatten back per layer, pad drops, full shapes restore from
  ``block_template`` (the stacked blocks tree of the ORIGINAL
  layout)."""
  out = {k: v for k, v in fsdp_params.items() if k != "blocks"}

  def f(x, t):
    n_layers = x.shape[0]
    size = int(math.prod(t.shape[1:]))
    return jnp.asarray(x).reshape(n_layers, -1)[:, :size].reshape(
        tuple(t.shape)).astype(t.dtype)

  out["blocks"] = jax.tree.map(f, fsdp_params["blocks"], block_template)
  return out


def _fsdp_block_hook(block_template, axes):
  """Per-iteration FSDP gather for the scanned composed trainer: sliced
  per-layer flat shards (k,) -> the block's full param tree via one
  packed tiled all-gather over ``axes`` (the combined (data, seq)
  data-parallel axes); the custom_vjp backward reduce-scatters the
  block's cotangent as one packed psum_scatter in the same loop
  position -- the SUM the pre-summed gradient convention of
  make_train_step expects (the /n_data divide happens outside, as for
  every other leaf). Built on ops/overlap.py's shared packing
  primitives (packed_gather_rows / pack_cotangent_rows /
  split_shard_row) so the row addressing cannot drift from the
  benchmark leg's gather_params; only the reduction differs: SUM over
  the combined axes (one shard row per device) instead of
  gather_params' batch-mean + model sub-slice. Works on vma and
  pre-vma jax alike: the collectives are explicit, like
  reduce_identity's pre-vma arm in _scan_grad_hook."""
  from kf_benchmarks_tpu.ops import overlap as overlap_lib
  t_leaves = jax.tree_util.tree_flatten(block_template)[0]
  shapes = tuple(tuple(t.shape) for t in t_leaves)
  dtypes = tuple(jnp.dtype(t.dtype).name for t in t_leaves)

  @functools.partial(jax.custom_vjp, nondiff_argnums=())
  def gather(shards):
    return overlap_lib.packed_gather_rows(axes, shapes, dtypes, shards)

  def fwd(shards):
    return gather(shards), None

  def bwd(_, cots):
    n = math.prod(lax.axis_size(a) for a in axes)
    mat, ks = overlap_lib.pack_cotangent_rows(cots, shapes, n,
                                              jnp.float32)
    # SUM over the data-parallel peers (matching the pre-summed
    # gradients of the replicated leaves): the tiled scatter over the
    # full n-device group hands each device exactly its own (1, K)
    # shard row -- the transpose of the gather's concatenation order.
    row = lax.psum_scatter(mat, axes, scatter_dimension=0,
                           tiled=True)[0]
    return (overlap_lib.split_shard_row(row, ks, dtypes),)

  gather.defvjp(fwd, bwd)

  def hook(lp):
    leaves, treedef = jax.tree_util.tree_flatten(lp)
    return jax.tree_util.tree_unflatten(treedef, list(gather(tuple(leaves))))

  return hook


def stacked_param_specs():
  """Specs for the stacked tree: a leading (replicated) layer axis on
  every block leaf; the tensor axis stays on the same dims as
  param_specs, shifted by one."""
  blocks = {
      "ln1": P(None), "ln2": P(None),
      "wqkv": P(None, None, None, TENSOR_AXIS),
      "wo": P(None, TENSOR_AXIS),
      "w1": P(None, None, TENSOR_AXIS), "b1": P(None, TENSOR_AXIS),
      "w2": P(None, TENSOR_AXIS, None), "b2": P(None),
  }
  return {"embed": P(), "pos": P(), "ln_f": P(), "blocks": blocks}


def fsdp_param_specs(data_axis: str):
  """Specs for an :func:`fsdp_stack_blocks` tree: every (L, n, k)
  blocks leaf shards its shard-row dim over the combined (data, seq)
  data-parallel axes (one row per device); non-block leaves keep the
  stacked layout's replication."""
  blocks_spec = P(None, (data_axis, SEQ_AXIS))
  return {"embed": P(), "pos": P(), "ln_f": P(),
          "blocks": {"ln1": blocks_spec, "ln2": blocks_spec,
                     "wqkv": blocks_spec, "wo": blocks_spec,
                     "w1": blocks_spec, "b1": blocks_spec,
                     "w2": blocks_spec, "b2": blocks_spec}}


def _scan_grad_hook(data_axes):
  """In-backward data-axis gradient reduction for the scanned layer
  stack (--overlap_gradient_reduction's composed-trainer analog): the
  returned hook wraps one layer's param slice at the top of the scan
  body so that layer's data-parallel gradient reduction is issued
  INSIDE the backward scan iteration -- overlapped with the next
  iteration's backward compute -- instead of trailing the whole
  backward.

  Two implementations, gated on the vma API (``lax.pcast`` is the
  missing API pre-vma, the same gate as compat.py/sequence.vary_like):

  * vma jax: pcast the slice to varying on the data axes. Downstream
    ops then need no implicit pbroadcast, and pcast's TRANSPOSE is the
    psum -- placed exactly here, in the scan body. Total reduction
    semantics are unchanged (the implicit machinery inserted the same
    psum); only its schedule position moves.
  * pre-vma jax: an identity-with-custom_vjp whose backward psums the
    slice cotangent over the data axes explicitly (pre-vma shard_map
    autodiff inserts no implicit psums).
  """
  if hasattr(lax, "pcast"):
    def hook(lp):
      return jax.tree.map(
          lambda t: lax.pcast(t, data_axes, to="varying"), lp)
    return hook
  from kf_benchmarks_tpu.ops import overlap as overlap_lib
  reduce_fn = lambda g: jax.tree.map(
      lambda t: lax.psum(t, data_axes), g)

  def hook(lp):
    return overlap_lib.reduce_identity(reduce_fn, lp)

  return hook


def _rmsnorm(x, scale, eps=1e-6):
  x = x.astype(jnp.float32)
  return (x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + eps)
          ) * scale


def _embed_positions(params, tokens, *, seq_axis, sp_layout):
  """Token + positional embedding of the LOCAL (B, T_local) shard;
  positions follow the shard's GLOBAL offsets (stripe pair offsets
  under the zigzag layout)."""
  b, t = tokens.shape
  global_t = t * lax.axis_size(seq_axis)
  max_len = params["pos"].shape[0]
  if global_t > max_len:
    # Without this, dynamic_slice would CLAMP later shards' offsets and
    # silently reuse the last pos rows (the single-device oracle fails
    # loudly on the same config).
    raise ValueError(
        f"global sequence length {global_t} exceeds the positional "
        f"table max_len={max_len}")
  x = params["embed"][tokens]
  if sp_layout == "zigzag":
    stripe = t // 2
    zidx = 2 * lax.axis_size(seq_axis) - 1 - lax.axis_index(seq_axis)
    ar = jnp.arange(stripe)
    pos_idx = jnp.concatenate(
        [lax.axis_index(seq_axis) * stripe + ar, zidx * stripe + ar])
    return x + jnp.take(params["pos"], pos_idx, axis=0)
  pos0 = lax.axis_index(seq_axis) * t
  return x + lax.dynamic_slice_in_dim(params["pos"], pos0, t, axis=0)


def _attention_residual(lp, x, *, seq_axis, tensor_axis, sp_layout,
                        attn_inner_block=None):
  """ln -> qkv -> (ring|zigzag) attention -> output proj residual.

  Returns (x_new, h) where h is the post-attention rmsnorm the MLP/MoE
  half of the block consumes -- shared by the flat and the pipelined
  forward paths. ``attn_inner_block`` is the ring schedules' K/V
  sub-block tiling knob (sequence.py): long-context memory control for
  the composed trainer.
  """
  b, t, _ = x.shape
  d_model = lp["wqkv"].shape[0]
  heads_local, head_dim = lp["wqkv"].shape[2], lp["wqkv"].shape[3]
  h = _rmsnorm(x, lp["ln1"])
  qkv = tp_lib.column_parallel_dense(
      h, lp["wqkv"].reshape(d_model, 3 * heads_local * head_dim))
  qkv = qkv.reshape(b, t, 3, heads_local, head_dim)
  if sp_layout == "zigzag":
    att = seq_lib.ring_attention_zigzag(
        qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], axis_name=seq_axis,
        inner_block=attn_inner_block)
  else:
    att = seq_lib.ring_attention(
        qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
        axis_name=seq_axis, causal=True,
        inner_block=attn_inner_block)
  x = x + tp_lib.row_parallel_dense(
      att.reshape(b, t, heads_local * head_dim),
      lp["wo"].reshape(heads_local * head_dim, d_model),
      axis_name=tensor_axis)
  return x, _rmsnorm(x, lp["ln2"])


def forward_local(params, tokens, *, seq_axis=SEQ_AXIS,
                  tensor_axis=TENSOR_AXIS, expert_axis=REPLICA_AXIS,
                  moe_capacity=None, sp_layout: str = "contiguous",
                  attn_inner_block=None, remat_policy=None,
                  grad_reduce_axes=None, fsdp_gather_hook=None):
  """Per-shard forward: tokens (B_local, T_local) -> (logits, moe_aux).

  Runs inside a shard_map body; params are the LOCAL shards
  (tensor-sharded leaves already sliced). MoE blocks (marked by a
  'gate_w' leaf) dispatch over ``expert_axis`` -- the data axis, where
  tokens are already sharded -- with per-shard capacity queues;
  moe_capacity=None means capacity = local token count (no drops).

  sp_layout='zigzag' expects the sequence axis sharded in
  sequence.zigzag_order (stripe pair (idx, 2n-1-idx) per device) and
  runs the load-balanced causal ring; positions follow the stripes.

  A ``params['blocks']`` that is a stack_blocks() pytree (leading layer
  axis) instead of a per-layer list runs the layer stack as ONE
  ``lax.scan`` body under ``jax.checkpoint`` -- compiled-program size
  and saved-residual footprint O(1) in depth instead of O(L).
  ``remat_policy`` is the explicit jax.checkpoint policy for that path
  (None = save nothing, recompute the whole block;
  e.g. jax.checkpoint_policies.dots_with_no_batch_dims_saveable keeps
  the matmul outputs and recomputes only the cheap elementwise work).
  ``grad_reduce_axes`` (scanned path only) hooks each layer's param
  slice with :func:`_scan_grad_hook` so the layer's data-axis gradient
  reduction runs inside the backward scan iteration.
  """
  b, t = tokens.shape
  x = _embed_positions(params, tokens, seq_axis=seq_axis,
                       sp_layout=sp_layout)
  moe_aux = jnp.zeros((), jnp.float32)
  if not isinstance(params["blocks"], (list, tuple)):
    # Scanned stack (homogeneous by stack_blocks construction).
    block_hook = (_scan_grad_hook(grad_reduce_axes)
                  if grad_reduce_axes else None)

    def one_block(xm, lp):
      if fsdp_gather_hook is not None:
        # --shard_params's composed-trainer leg: lp arrives as flat
        # per-layer shards; ONE packed all-gather re-assembles this
        # block INSIDE the scan body (under the jax.checkpoint below,
        # so the backward re-gathers during recompute) and the hook's
        # backward reduce-scatters the block's cotangent in the same
        # position (_fsdp_block_hook).
        lp = fsdp_gather_hook(lp)
      if block_hook is not None:
        lp = block_hook(lp)
      xm, h = _attention_residual(lp, xm, seq_axis=seq_axis,
                                  tensor_axis=tensor_axis,
                                  sp_layout=sp_layout,
                                  attn_inner_block=attn_inner_block)
      xm = xm + tp_lib.parallel_mlp(h, lp["w1"], lp["b1"], lp["w2"],
                                    lp["b2"], axis_name=tensor_axis)
      return xm, None

    body = jax.checkpoint(one_block, policy=remat_policy,
                          prevent_cse=False)
    x, _ = lax.scan(body, x, params["blocks"])
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x,
                        params["embed"].astype(jnp.float32))
    return logits, moe_aux
  for lp in params["blocks"]:
    d_model = lp["wqkv"].shape[0]
    x, h = _attention_residual(lp, x, seq_axis=seq_axis,
                               tensor_axis=tensor_axis,
                               sp_layout=sp_layout,
                               attn_inner_block=attn_inner_block)
    if "gate_w" in lp:
      cap = (b * t) if moe_capacity is None else moe_capacity
      y, aux = ep_lib.switch_moe(
          h.reshape(b * t, d_model), lp["gate_w"], lp["ew1"],
          lp["eb1"], lp["ew2"], lp["eb2"], capacity=cap,
          axis_name=expert_axis)
      x = x + y.reshape(b, t, d_model)
      moe_aux = moe_aux + aux
    else:
      x = x + tp_lib.parallel_mlp(h, lp["w1"], lp["b1"], lp["w2"],
                                  lp["b2"], axis_name=tensor_axis)
  x = _rmsnorm(x, params["ln_f"])
  logits = jnp.einsum("btd,vd->btv", x,
                      params["embed"].astype(jnp.float32))
  return logits, moe_aux


def _reference_moe(h, lp, groups, capacity, layout="contiguous"):
  """Dense (single-device) Switch-MoE with the SAME per-shard queue
  semantics as the SPMD dispatch: tokens grouped as (replica, seq)
  shards in row-major order, capacity per expert PER GROUP. jnp
  throughout, so the oracle is differentiable.

  layout='zigzag' mirrors sp_layout='zigzag': seq shard s holds the
  stripe pair (s, 2*ns-1-s), in that in-shard order, so the capacity
  queues fill exactly as on the SPMD devices.
  """
  if layout not in ("contiguous", "zigzag"):
    raise ValueError(f"unknown moe layout {layout!r}")
  b, t, d = h.shape
  nr, ns = groups
  bl, tl = b // nr, t // ns
  e_global = lp["gate_w"].shape[1]
  out = jnp.zeros((b, t, d), h.dtype)
  aux = jnp.zeros((), jnp.float32)
  for r in range(nr):
    for s in range(ns):
      if layout == "zigzag":
        # Shard s of the SAME permutation the SPMD data path applies.
        cols = seq_lib.zigzag_order(t, ns).reshape(ns, tl)[s]
      else:
        cols = jnp.arange(s * tl, (s + 1) * tl)
      hg = h[r * bl:(r + 1) * bl, cols].reshape(
          bl * tl, d).astype(jnp.float32)
      probs = jax.nn.softmax(hg @ lp["gate_w"].astype(jnp.float32), -1)
      idx = jnp.argmax(probs, -1)
      assign = jax.nn.one_hot(idx, e_global, dtype=jnp.float32)
      pos = jnp.cumsum(assign, axis=0) - 1.0
      keep = assign * (pos < capacity)
      gate = jnp.max(probs, -1)
      hh = jax.nn.gelu(jnp.einsum("td,edf->tef", hg, lp["ew1"])
                       + lp["eb1"])
      y = jnp.einsum("tef,efd->ted", hh, lp["ew2"]) + lp["eb2"]
      picked = jnp.einsum("te,ted->td", keep, y) * gate[:, None]
      out = out.at[r * bl:(r + 1) * bl, cols].set(
          picked.reshape(bl, tl, d).astype(h.dtype))
      aux = aux + e_global * jnp.sum(
          jnp.mean(assign, 0) * jnp.mean(probs, 0))
  return out, aux / (nr * ns)


def forward_reference(params, tokens, moe_groups=(1, 1),
                      moe_capacity=None, moe_layout="contiguous"):
  """Single-device dense forward from the same GLOBAL params -- the
  equivalence oracle (and the degenerate 1-device program).

  moe_groups = (n_replica, n_seq) of the mesh being mirrored: MoE
  capacity queues are per data shard in the SPMD run, so the oracle
  reproduces that grouping (irrelevant when capacity is never hit).
  """
  b, t = tokens.shape
  x = params["embed"][tokens] + params["pos"][:t]
  moe_aux = jnp.zeros((), jnp.float32)
  for lp in params["blocks"]:
    d_model = lp["wqkv"].shape[0]
    heads, head_dim = lp["wqkv"].shape[2], lp["wqkv"].shape[3]
    h = _rmsnorm(x, lp["ln1"])
    qkv = (h @ lp["wqkv"].reshape(d_model, 3 * heads * head_dim)
           ).reshape(b, t, 3, heads, head_dim)
    att = seq_lib.full_attention(qkv[:, :, 0], qkv[:, :, 1],
                                 qkv[:, :, 2], causal=True)
    x = x + att.reshape(b, t, heads * head_dim) @ lp["wo"].reshape(
        heads * head_dim, d_model)
    h = _rmsnorm(x, lp["ln2"])
    if "gate_w" in lp:
      nr, ns = moe_groups
      cap = ((b // nr) * (t // ns) if moe_capacity is None
             else moe_capacity)
      y, aux = _reference_moe(h, lp, moe_groups, cap,
                              layout=moe_layout)
      x = x + y
      moe_aux = moe_aux + aux
    else:
      x = x + jax.nn.gelu(h @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
  x = _rmsnorm(x, params["ln_f"])
  logits = jnp.einsum("btd,vd->btv", x,
                      params["embed"].astype(jnp.float32))
  return logits, moe_aux


def _loss_from_logits(logits, labels):
  logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
  ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
  return -jnp.mean(ll)


def reference_loss(params, tokens, labels, moe_groups=(1, 1),
                   moe_capacity=None, moe_aux_weight=0.01,
                   moe_layout="contiguous"):
  logits, aux = forward_reference(params, tokens,
                                  moe_groups=moe_groups,
                                  moe_capacity=moe_capacity,
                                  moe_layout=moe_layout)
  return _loss_from_logits(logits, labels) + moe_aux_weight * aux


def _grid_mesh(sizes, axis_names, devices=None) -> Mesh:
  import numpy as np
  devices = devices if devices is not None else jax.devices()
  need = math.prod(sizes)
  if len(devices) < need:
    raise ValueError(f"need {need} devices, have {len(devices)}")
  return Mesh(np.array(devices[:need]).reshape(sizes), axis_names)


def build_mesh(n_replica: int, n_seq: int, n_tensor: int,
               devices=None) -> Mesh:
  return _grid_mesh((n_replica, n_seq, n_tensor),
                    (REPLICA_AXIS, SEQ_AXIS, TENSOR_AXIS), devices)


def compose_on_model_axis(n_batch: int, n_seq: int, n_tensor: int,
                          devices=None) -> Mesh:
  """The composed trainer on the SHARED axis system of the named 2-D
  mesh (parallel/mesh.py build_mesh_2d): the 'model' axis of a
  ``n_batch x (n_seq * n_tensor)`` 2-D mesh refined into its seq x
  tensor factors -- ``('batch', 'seq', 'tensor')``, same device order
  (row-major), same data axis name the core train step uses. One axis
  system for every parallelism family: collectives over
  ``('seq', 'tensor')`` are collectives over the 2-D family's 'model'
  axis, and the data-parallel legs (batch sharding, gradient pmeans)
  ride 'batch' exactly as train_step.py's sharded branch does --
  instead of the bespoke 'replica'-named wiring of :func:`build_mesh`.
  make_train_step detects the family from the axis names; programs are
  bit-identical across the two namings
  (tests/test_transformer_parallel.py)."""
  return _grid_mesh((n_batch, n_seq, n_tensor),
                    (BATCH_AXIS, SEQ_AXIS, TENSOR_AXIS), devices)


def make_train_step(mesh: Mesh, params_template, learning_rate: float,
                    moe_capacity=None, moe_aux_weight: float = 0.01,
                    sp_layout: str = "contiguous",
                    attn_inner_block=None, scan_layers: bool = False,
                    remat_policy=None,
                    overlap_grad_reduce: bool = False,
                    fsdp_blocks: bool = False):
  """Jitted SGD train step over GLOBAL (params, tokens, labels):
  tokens/labels (batch, seq) in NORMAL order, sharded (data, seq) --
  the data axis is 'batch' on compose_on_model_axis meshes, 'replica'
  on legacy build_mesh grids; params per param_specs. MoE blocks (if any in the template) add
  expert parallelism over the replica axis and fold the Switch aux
  loss in at ``moe_aux_weight``. sp_layout='zigzag' permutes the data
  into sequence.zigzag_order at the jit boundary and runs the
  load-balanced causal ring (input pipelines that store sequences
  pre-permuted should shard_map forward_local directly). Returns
  (new_params, loss) -- the token-mean loss is permutation-invariant,
  so the layout never leaks to the caller.

  scan_layers=True expects a stack_blocks() params tree and runs the
  layer stack as one scanned+rematerialized body (forward_local);
  ``remat_policy`` is its explicit jax.checkpoint policy. Losses and
  trained parameters stay numerically equivalent to the unscanned
  step (tests/test_transformer_parallel.py pins it).

  overlap_grad_reduce=True (scanned path only) hooks each layer's
  param slice in the scan body (_scan_grad_hook) so the layer's
  data-axis gradient reduction is issued inside the backward scan
  iteration, overlapped with the preceding layer's backward, instead
  of trailing the whole backward. Reduction semantics are unchanged on
  vma jax (the hook only moves the psum's schedule position); on
  pre-vma jax (no lax.pcast) the hook's explicit psums cover the
  hooked block leaves only -- the same limitation that gates the
  composed-trainer oracle tests there."""
  if sp_layout not in ("contiguous", "zigzag"):
    raise ValueError(f"unknown sp_layout {sp_layout!r}")
  if overlap_grad_reduce and not scan_layers:
    raise ValueError(
        "overlap_grad_reduce=True requires scan_layers=True: the hooks "
        "live in the scanned block body (an unscanned stack already "
        "exposes every layer's reduction to the scheduler separately)")
  data_axis = _data_axis(mesh)
  fsdp_hook = None
  if fsdp_blocks:
    # --shard_params's composed-trainer leg: the scanned layer stack
    # stores as fsdp_stack_blocks() per-layer shards over the combined
    # (data, seq) axes; each scan iteration gathers ONE block inside
    # the body and its cotangent reduce-scatters there too
    # (_fsdp_block_hook). Tensor sharding is a DIFFERENT decomposition
    # of the same leaves (each device holds a head/feature slice, not
    # a flat range), so composing both on one leaf is out of scope --
    # FSDP owns the whole block here.
    if not scan_layers:
      raise ValueError(
          "fsdp_blocks=True requires scan_layers=True: the per-block "
          "gather lives in the scanned body (an unscanned stack would "
          "re-assemble every layer at once -- full residency, nothing "
          "sharded)")
    if overlap_grad_reduce:
      raise ValueError(
          "fsdp_blocks=True cannot compose with overlap_grad_reduce: "
          "the gather hook's backward IS the block's in-loop gradient "
          "reduce-scatter; a second in-backward reduction would "
          "double-reduce the block cotangents")
    if int(mesh.shape[TENSOR_AXIS]) != 1:
      raise ValueError(
          "fsdp_blocks=True requires a 1-wide tensor axis: tensor "
          "sharding slices block leaves by head/feature while FSDP "
          "slices them by flat range -- one leaf cannot carry both "
          f"decompositions (got tensor axis {mesh.shape[TENSOR_AXIS]})")
    block_template = params_template["blocks"]
    if isinstance(block_template, (list, tuple)):
      raise ValueError(
          "fsdp_blocks=True takes the ORIGINAL stack_blocks() tree as "
          "params_template (full per-layer shapes drive the gather "
          "spec); convert the live params with fsdp_stack_blocks")
    per_layer_template = jax.tree.map(
        lambda t: jax.ShapeDtypeStruct(tuple(t.shape)[1:], t.dtype),
        block_template)
    fsdp_hook = _fsdp_block_hook(per_layer_template,
                                 (data_axis, SEQ_AXIS))
    specs = fsdp_param_specs(data_axis)
  elif scan_layers:
    if isinstance(params_template["blocks"], (list, tuple)):
      raise ValueError(
          "scan_layers=True takes a stack_blocks() params tree "
          "(leading layer axis), not the per-layer block list")
    specs = stacked_param_specs()
  else:
    specs = param_specs(params_template, data_axis=data_axis)
  data_spec = P(data_axis, SEQ_AXIS)
  n_data = mesh.shape[data_axis] * mesh.shape[SEQ_AXIS]
  n_seq = mesh.shape[SEQ_AXIS]

  def body(params, tokens, labels):
    def local_loss(p):
      if fsdp_hook is not None:
        # Local storage view: (L, 1, k) shard rows -> the (L, k) per-
        # layer flat shards the scan slices (the squeeze sits inside
        # the loss so the gradient lands back on the storage layout).
        p = dict(p)
        p["blocks"] = jax.tree.map(lambda x: x[:, 0], p["blocks"])
      logits, moe_aux = forward_local(
          p, tokens, moe_capacity=moe_capacity, sp_layout=sp_layout,
          attn_inner_block=attn_inner_block,
          remat_policy=remat_policy,
          expert_axis=data_axis,
          grad_reduce_axes=((data_axis, SEQ_AXIS)
                            if overlap_grad_reduce else None),
          fsdp_gather_hook=fsdp_hook)
      return (_loss_from_logits(logits, labels)
              + moe_aux_weight * moe_aux)

    loss, grads = jax.value_and_grad(local_loss)(params)
    # Token mean over the whole global batch: every shard holds the
    # same token count, so the pmean of shard means is the global mean.
    loss = lax.pmean(loss, (data_axis, SEQ_AXIS))
    # shard_map's vma-aware autodiff has already psum-ed each grad over
    # every axis its parameter is unvarying on (the transpose of the
    # implicit broadcast), so each leaf holds the SUM of the per-data-
    # shard contributions -- measured 4.0x on a (2,2,*) mesh. Turning
    # the global token-sum objective into the token mean is a plain
    # divide; no further collectives are needed (tensor-sharded leaves
    # keep their shard-local slice gradients).
    grads = jax.tree.map(lambda g: g / n_data, grads)
    new_params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
    return new_params, loss

  sharded = jax.shard_map(
      body, mesh=mesh,
      in_specs=(specs, data_spec, data_spec),
      out_specs=(specs, P()))
  if sp_layout == "contiguous":
    return jax.jit(sharded, donate_argnums=(0,))

  def call(params, tokens, labels):
    order = seq_lib.zigzag_order(tokens.shape[1], n_seq)
    return sharded(params, jnp.take(tokens, order, axis=1),
                   jnp.take(labels, order, axis=1))

  return jax.jit(call, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# The pipeline (stage) axis composed in: dp x pp x sp x tp in one jit.
#
# Scope: pipeline stages require a HOMOGENEOUS layer stack (every block
# the same pytree structure, so stages stack into leaves with a leading
# (n_stages, layers_per_stage) axis). MoE blocks are heterogeneous
# under moe_every and their capacity queues are defined per data shard,
# not per microbatch -- composing ep with pp would change the queue
# semantics silently -- so to_pipelined() rejects MoE trees; MoE
# composition is served by make_train_step (dp x sp x tp x ep).
# ---------------------------------------------------------------------------

STAGE_AXIS = pp_lib.STAGE_AXIS


def to_pipelined(params, n_stages: int):
  """Standard param tree -> pipelined tree: the per-layer block list
  becomes one stacked pytree with leading (n_stages, layers_per_stage)
  axes (sharded on STAGE_AXIS by pipelined_param_specs)."""
  blocks = params["blocks"]
  if any("gate_w" in b for b in blocks):
    raise ValueError(
        "pipeline composition requires a homogeneous (dense) layer "
        "stack; MoE blocks change per-shard capacity semantics under "
        "microbatching -- use make_train_step for dp x sp x tp x ep")
  if len(blocks) % n_stages != 0:
    raise ValueError(f"{len(blocks)} layers not divisible by "
                     f"{n_stages} stages")
  lps = len(blocks) // n_stages
  stacked = jax.tree.map(
      lambda *xs: jnp.stack(xs).reshape(
          (n_stages, lps) + xs[0].shape), *blocks)
  out = {k: v for k, v in params.items() if k != "blocks"}
  out["blocks"] = stacked
  return out


def from_pipelined(pparams):
  """Inverse of to_pipelined: stacked stage tree -> per-layer list (so
  the trained state compares leaf-for-leaf against the oracle's)."""
  stacked = pparams["blocks"]
  n_stages, lps = jax.tree.leaves(stacked)[0].shape[:2]
  flat = jax.tree.map(
      lambda x: x.reshape((n_stages * lps,) + x.shape[2:]), stacked)
  blocks = [jax.tree.map(lambda x: x[i], flat)
            for i in range(n_stages * lps)]
  out = {k: v for k, v in pparams.items() if k != "blocks"}
  out["blocks"] = blocks
  return out


def pipelined_param_specs():
  """Specs for the pipelined tree: stage axis leads every block leaf;
  the tensor axis stays on the same dims as param_specs, shifted by
  the two stacking axes."""
  blocks = {
      "ln1": P(STAGE_AXIS), "ln2": P(STAGE_AXIS),
      "wqkv": P(STAGE_AXIS, None, None, None, TENSOR_AXIS),
      "wo": P(STAGE_AXIS, None, TENSOR_AXIS),
      "w1": P(STAGE_AXIS, None, None, TENSOR_AXIS),
      "b1": P(STAGE_AXIS, None, TENSOR_AXIS),
      "w2": P(STAGE_AXIS, None, TENSOR_AXIS, None),
      "b2": P(STAGE_AXIS),
  }
  return {"embed": P(), "pos": P(), "ln_f": P(), "blocks": blocks}


def forward_local_pipelined(params, tokens, *, num_microbatches: int,
                            seq_axis=SEQ_AXIS, tensor_axis=TENSOR_AXIS,
                            stage_axis=STAGE_AXIS,
                            sp_layout: str = "contiguous",
                            attn_inner_block=None):
  """Per-shard forward with the layer stack sharded over the stage
  axis: embed/positions everywhere (stage-replicated), the GPipe scan
  (parallel/pipeline.py) carries activations stage-to-stage via
  ppermute, ring attention and Megatron psums run INSIDE each stage
  tick on the seq/tensor axes, and the retired microbatches are
  broadcast back so the loss/unembed is stage-replicated again."""
  x = _embed_positions(params, tokens, seq_axis=seq_axis,
                       sp_layout=sp_layout)
  n_local = jax.tree.leaves(params["blocks"])[0].shape[0]
  if n_local != 1:
    # Same hazard make_pipeline guards: a stage count that merely
    # DIVIDES the axis size shards legally but p[0] would silently
    # drop every local stage after the first.
    raise ValueError(
        f"blocks leading axis must equal the '{stage_axis}' mesh axis "
        f"size (one stage per device); got a local slice of {n_local} "
        f"stages")
  local = jax.tree.map(lambda p: p[0], params["blocks"])
  lps = local["ln1"].shape[0]

  def stage_fn(p, xm):
    for i in range(lps):
      lp = jax.tree.map(lambda a: a[i], p)
      xm, h = _attention_residual(lp, xm, seq_axis=seq_axis,
                                  tensor_axis=tensor_axis,
                                  sp_layout=sp_layout,
                                  attn_inner_block=attn_inner_block)
      xm = xm + tp_lib.parallel_mlp(h, lp["w1"], lp["b1"], lp["w2"],
                                    lp["b2"], axis_name=tensor_axis)
    return xm

  x = pp_lib.spmd_pipeline(stage_fn, local, x, num_microbatches,
                           axis_name=stage_axis)
  x = _rmsnorm(x, params["ln_f"])
  return jnp.einsum("btd,vd->btv", x,
                    params["embed"].astype(jnp.float32))


def build_mesh_pp(n_replica: int, n_stage: int, n_seq: int,
                  n_tensor: int, devices=None) -> Mesh:
  return _grid_mesh(
      (n_replica, n_stage, n_seq, n_tensor),
      (REPLICA_AXIS, STAGE_AXIS, SEQ_AXIS, TENSOR_AXIS), devices)


def make_pipelined_train_step(mesh: Mesh, pparams_template,
                              learning_rate: float,
                              num_microbatches: int,
                              sp_layout: str = "contiguous",
                              attn_inner_block=None):
  """Jitted SGD step over the 4-D (replica, stage, seq, tensor) mesh.

  pparams_template is a to_pipelined() tree; tokens/labels are GLOBAL
  (batch, seq) in normal order, sharded (replica, seq) and replicated
  over stage/tensor. GPipe with full-batch SGD is mathematically the
  sequential step, so loss AND trained params match the single-device
  oracle (tests/test_transformer_parallel.py); num_microbatches must
  divide the LOCAL batch (global batch / n_replica).
  """
  if sp_layout not in ("contiguous", "zigzag"):
    raise ValueError(f"unknown sp_layout {sp_layout!r}")
  del pparams_template  # shape-independent: specs are structural
  specs = pipelined_param_specs()
  data_spec = P(REPLICA_AXIS, SEQ_AXIS)
  n_data = mesh.shape[REPLICA_AXIS] * mesh.shape[SEQ_AXIS]
  n_seq = mesh.shape[SEQ_AXIS]

  def body(params, tokens, labels):
    def local_loss(p):
      logits = forward_local_pipelined(
          p, tokens, num_microbatches=num_microbatches,
          sp_layout=sp_layout, attn_inner_block=attn_inner_block)
      return _loss_from_logits(logits, labels)

    loss, grads = jax.value_and_grad(local_loss)(params)
    loss = lax.pmean(loss, (REPLICA_AXIS, SEQ_AXIS))
    # Same pre-summed-gradient accounting as make_train_step: data-axis
    # sums -> global token mean by a divide. Stage-sharded block leaves
    # vary on the stage axis, so their gradients stay stage-local, just
    # as tensor-sharded leaves stay shard-local.
    grads = jax.tree.map(lambda g: g / n_data, grads)
    new_params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
    return new_params, loss

  sharded = jax.shard_map(
      body, mesh=mesh,
      in_specs=(specs, data_spec, data_spec),
      out_specs=(specs, P()))
  if sp_layout == "contiguous":
    return jax.jit(sharded, donate_argnums=(0,))

  def call(params, tokens, labels):
    order = seq_lib.zigzag_order(tokens.shape[1], n_seq)
    return sharded(params, jnp.take(tokens, order, axis=1),
                   jnp.take(labels, order, axis=1))

  return jax.jit(call, donate_argnums=(0,))
