"""TPU-native KungFu API surface.

Re-implements the KungFu capabilities the reference consumes (SURVEY 2.9;
call sites: benchmark_cnn.py:1192-1204 optimizer wrap, :1408-1410 cluster
size, :2044-2048/:2629-2631 rank, :2097-2100 broadcast-at-init,
tf_cnn_benchmarks.py:58-60 exit barrier) on JAX collectives:

  allreduce            -> lax.pmean over the 'replica' mesh axis (ICI)
  pair-averaging gossip-> lax.ppermute of the weights (deterministic
                          synchronous schedule; see PairAveraging below)
  broadcast            -> replica-0 masked psum
  barrier              -> multihost sync_global_devices (DCN) or no-op
  cluster size / rank  -> mesh axis size / axis_index inside SPMD code,
                          jax.process_count/index on the host side

The KungFu runtime itself (Go peer mesh) is replaced by the XLA SPMD
runtime plus the native coordination service in native/ (control plane).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS


# -- host-side cluster introspection (ref: kungfu.python.*) -----------------

def current_cluster_size() -> int:
  """World size without global init (ref call: benchmark_cnn.py:1408-1410).

  In the SPMD design a "worker" of the reference maps to a device, so the
  cluster size is the global device count, not the process count.
  """
  return jax.device_count()


def current_rank() -> int:
  """Host-side rank (ref call: benchmark_cnn.py:2044-2048).

  Rank of this process's first device; chief election
  (``current_rank() == 0``) matches the reference's use.
  """
  return jax.process_index() * max(jax.local_device_count(), 1)


def run_barrier() -> None:
  """Global barrier before exit (ref: tf_cnn_benchmarks.py:58-60).

  Under the kfrun launcher (KFCOORD_HOST/PORT/WORLD set) the barrier
  rides the native coordination service over DCN; under multi-process
  JAX it uses sync_global_devices; single-process it is a no-op.
  """
  host = os.environ.get("KFCOORD_HOST")
  port = os.environ.get("KFCOORD_PORT")
  world = os.environ.get("KFCOORD_WORLD")
  if host and port and world:
    from kf_benchmarks_tpu.parallel import coordination
    with coordination.CoordinatorClient(host=host,
                                        port=int(port)) as client:
      client.join(os.environ.get("KFCOORD_NAME", f"proc-{os.getpid()}"))
      # all-ranks: kfrun exports KFCOORD_* to every child it launches,
      # so each of the WORLD processes takes this path and enters
      # "kf_exit" with the same expected count.
      client.barrier("kf_exit", int(world))
    return
  if jax.process_count() > 1:
    from jax.experimental import multihost_utils
    # all-ranks: process_count() is a global property (identical on
    # every process of a jax.distributed job), so this branch is
    # all-or-nothing -- full attendance at the sync.
    multihost_utils.sync_global_devices("kf_benchmarks_tpu_exit_barrier")


# -- in-SPMD collective ops (used inside shard_map bodies) ------------------

def allreduce_mean(tree, axis_name: str = REPLICA_AXIS):
  """Gradient averaging: the S-SGD data plane (KungFu allreduce -> psum)."""
  return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def broadcast(tree, root: int = 0, axis_name: str = REPLICA_AXIS):
  """Replica-``root`` broadcast of a pytree (ref: kungfu broadcast,
  benchmark_cnn.py:2097-2100): zero non-root values, psum.

  Dtype-preserving: the masked psum runs in each leaf's own dtype (ints
  stay ints -- routing int32 through float32 would corrupt values above
  2^24); bools ride an int32 psum."""
  idx = lax.axis_index(axis_name)

  def bcast(x):
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    if masked.dtype == jnp.bool_:
      return lax.psum(masked.astype(jnp.int32), axis_name).astype(jnp.bool_)
    return lax.psum(masked, axis_name)

  return jax.tree.map(bcast, tree)


# Axis size at or below which the gossip schedule is the full 1..n-1
# rotation; above it, the hypercube schedule keeps the program at
# ceil(log2 n) switch branches AND one send per step.
GOSSIP_SWITCH_MAX_N = 8


def _gossip_offsets(n: int):
  """Per-period partner offsets of the gossip schedule at axis size n.

  The single source of truth shared by gossip_shift (step -> offset
  lookup) and pair_average (one switch branch per offset), so the two
  can never drift. 2^k here is always < n (k < (n-1).bit_length()), so
  every offset is a valid non-zero cyclic shift.
  """
  if n <= GOSSIP_SWITCH_MAX_N:
    return list(range(1, n))
  return [1 << k for k in range((n - 1).bit_length())]


def gossip_shift(step, axis_size: int):
  """Deterministic peer offset for pair-averaging at this step.

  AD-PSGD's asynchronous random pairing has no SPMD analog, so the
  schedule is a deterministic synchronous rotation (SURVEY 7.4
  "Pair-averaging gossip on TPU"), sized to the axis:

  * n <= GOSSIP_SWITCH_MAX_N: the offset rotates through 1..n-1, so
    every replica pairs with every other within n-1 steps.
  * n > GOSSIP_SWITCH_MAX_N: HYPERCUBE offsets -- the schedule cycles
    through the ceil(log2 n) == (n-1).bit_length() power-of-two shifts
    2^0..2^(ceil(log2 n)-1) (each < n, so valid at ANY axis size, not
    just powers of two). Every offset is a single cyclic permutation
    (one ppermute, ONE tree-sized send), and because every residue
    0..n-1 is a subset-sum of those powers mod n, all n replicas mix
    within ceil(log2 n) steps -- at non-power-of-two n included
    (pinned by test_strategies.py's n=6 submesh case) -- faster mixing
    than the 1..n-1 rotation needs n-1 steps for, at 1/log2(n) of the
    wire cost the round-2 gated-hop lowering paid (which sent the tree
    on every of its log2 n hops and gated the result; measured 2.1x
    step time at n=32, PERF.md round 4).
  """
  step = jnp.asarray(step)
  if axis_size <= 1:
    return jnp.zeros_like(step)
  offsets = _gossip_offsets(axis_size)
  return jnp.asarray(offsets, jnp.int32)[step % len(offsets)]


def pair_average(tree, step, axis_name: str = REPLICA_AXIS):
  """One gossip round: average weights with the step's partner
  (KungFu PairAveragingOptimizer data plane -> ppermute).

  Each replica i receives from (i - shift) mod n and averages, with
  shift = gossip_shift(step, n). This is the row-stochastic gossip
  matrix W = (I + P)/2 with P a cyclic permutation: doubly stochastic,
  so the network average is preserved exactly -- the property
  AD-PSGD's analysis needs. Every branch of either lowering is a
  single ppermute of the whole tree, so a gossip step costs exactly
  one tree-sized send at ANY n; the schedules differ across the
  threshold (1..n-1 rotation vs hypercube offsets, see gossip_shift)
  but both are doubly stochastic every step and fully mixing over
  their window.
  """
  n = lax.axis_size(axis_name)
  if n == 1:
    return tree
  step = jnp.asarray(step)

  def make_branch(s):
    perm = [(i, (i + s) % n) for i in range(n)]
    return lambda t: jax.tree.map(
        lambda x: lax.ppermute(x, axis_name, perm), t)

  # One switch branch per schedule offset: n-1 branches of the full
  # rotation at small n, ceil(log2 n) hypercube branches at scale
  # (n=256 bakes 8, not 255) -- every branch a single tree-sized send.
  # The round-2 design instead decomposed the full rotation into gated
  # power-of-two hops, which kept the program O(log n) but sent the
  # tree on EVERY hop (measured 2.1x step time at n=32); restricting
  # the schedule itself to the power-of-two offsets removes the extra
  # sends instead of gating them.
  offsets = _gossip_offsets(n)
  shifted = lax.switch(step % len(offsets),
                       [make_branch(s) for s in offsets], tree)
  return jax.tree.map(lambda x, y: 0.5 * (x + y), tree, shifted)


def sync_average(tree, axis_name: str = REPLICA_AXIS):
  """Synchronous model averaging (KungFu SynchronousAveragingOptimizer /
  SMA, EA-SGD style): all-replica mean of the weights."""
  return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)
