"""TPU-native KungFu API surface.

Re-implements the KungFu capabilities the reference consumes (SURVEY 2.9;
call sites: benchmark_cnn.py:1192-1204 optimizer wrap, :1408-1410 cluster
size, :2044-2048/:2629-2631 rank, :2097-2100 broadcast-at-init,
tf_cnn_benchmarks.py:58-60 exit barrier) on JAX collectives:

  allreduce            -> lax.pmean over the 'replica' mesh axis (ICI)
  pair-averaging gossip-> lax.ppermute of the weights (deterministic
                          synchronous schedule; see PairAveraging below)
  broadcast            -> replica-0 masked psum
  barrier              -> multihost sync_global_devices (DCN) or no-op
  cluster size / rank  -> mesh axis size / axis_index inside SPMD code,
                          jax.process_count/index on the host side

The KungFu runtime itself (Go peer mesh) is replaced by the XLA SPMD
runtime plus the native coordination service in native/ (control plane).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS


# -- host-side cluster introspection (ref: kungfu.python.*) -----------------

def current_cluster_size() -> int:
  """World size without global init (ref call: benchmark_cnn.py:1408-1410).

  In the SPMD design a "worker" of the reference maps to a device, so the
  cluster size is the global device count, not the process count.
  """
  return jax.device_count()


def current_rank() -> int:
  """Host-side rank (ref call: benchmark_cnn.py:2044-2048).

  Rank of this process's first device; chief election
  (``current_rank() == 0``) matches the reference's use.
  """
  return jax.process_index() * max(jax.local_device_count(), 1)


def run_barrier() -> None:
  """Global barrier before exit (ref: tf_cnn_benchmarks.py:58-60).

  Under the kfrun launcher (KFCOORD_HOST/PORT/WORLD set) the barrier
  rides the native coordination service over DCN; under multi-process
  JAX it uses sync_global_devices; single-process it is a no-op.
  """
  host = os.environ.get("KFCOORD_HOST")
  port = os.environ.get("KFCOORD_PORT")
  world = os.environ.get("KFCOORD_WORLD")
  if host and port and world:
    from kf_benchmarks_tpu.parallel import coordination
    with coordination.CoordinatorClient(host=host,
                                        port=int(port)) as client:
      client.join(os.environ.get("KFCOORD_NAME", f"proc-{os.getpid()}"))
      client.barrier("kf_exit", int(world))
    return
  if jax.process_count() > 1:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("kf_benchmarks_tpu_exit_barrier")


# -- in-SPMD collective ops (used inside shard_map bodies) ------------------

def allreduce_mean(tree, axis_name: str = REPLICA_AXIS):
  """Gradient averaging: the S-SGD data plane (KungFu allreduce -> psum)."""
  return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def broadcast(tree, root: int = 0, axis_name: str = REPLICA_AXIS):
  """Replica-``root`` broadcast of a pytree (ref: kungfu broadcast,
  benchmark_cnn.py:2097-2100): zero non-root values, psum.

  Dtype-preserving: the masked psum runs in each leaf's own dtype (ints
  stay ints -- routing int32 through float32 would corrupt values above
  2^24); bools ride an int32 psum."""
  idx = lax.axis_index(axis_name)

  def bcast(x):
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    if masked.dtype == jnp.bool_:
      return lax.psum(masked.astype(jnp.int32), axis_name).astype(jnp.bool_)
    return lax.psum(masked, axis_name)

  return jax.tree.map(bcast, tree)


def gossip_shift(step, axis_size: int):
  """Deterministic peer offset for pair-averaging at this step.

  AD-PSGD's asynchronous random pairing has no SPMD analog; the
  convergence-equivalent synchronous schedule rotates the partner offset
  through 1..n-1 so every replica mixes with every other within n-1 steps
  (SURVEY 7.4 "Pair-averaging gossip on TPU").
  """
  if axis_size <= 1:
    return jnp.zeros_like(jnp.asarray(step))
  return 1 + jnp.asarray(step) % (axis_size - 1)


# Axis size at or below which pair_average bakes all shifts into a
# lax.switch (one send per step); above it, gated power-of-two hops keep
# the program O(log n) at the cost of up to log2(n) sends per step.
GOSSIP_SWITCH_MAX_N = 8


def pair_average(tree, step, axis_name: str = REPLICA_AXIS):
  """One gossip round: average weights with the step's partner
  (KungFu PairAveragingOptimizer data plane -> ppermute).

  Each replica i receives from (i - shift) mod n and averages. This is the
  row-stochastic gossip matrix W = (I + P)/2 with P a cyclic permutation:
  doubly stochastic, so the network average is preserved exactly -- the
  property AD-PSGD's analysis needs. Both lowerings below compute the
  identical permutation, so results are bit-equal across the threshold.
  """
  n = lax.axis_size(axis_name)
  if n == 1:
    return tree
  shift = jnp.asarray(gossip_shift(step, n), jnp.int32)
  if n <= GOSSIP_SWITCH_MAX_N:
    # Small axes: bake each cyclic shift as a switch branch -- exactly
    # ONE tree-sized send per gossip step, at n-1 branches of program.
    def make_branch(s):
      perm = [(i, (i + s) % n) for i in range(n)]
      return lambda t: jax.tree.map(
          lambda x: lax.ppermute(x, axis_name, perm), t)
    shifted = lax.switch(shift - 1, [make_branch(s) for s in range(1, n)],
                         tree)
  else:
    # At scale the cyclic shift decomposes into gated power-of-two hops
    # (binary digits of the shift), so the program holds ceil(log2 n)
    # static ppermutes instead of n-1 switch branches (n=256 would bake
    # 255). The trade is wire traffic: every hop sends the full tree and
    # the gate discards unused hops, so a gossip step costs up to
    # ceil(log2 n) tree-sized sends where the switch costs one -- paid
    # only above the threshold, where the O(n^2) program would be worse.
    # ppermute moves data without arithmetic, so the composed result is
    # bit-identical to a single shift-s permutation; the partner still
    # varies per step without retracing (the gates read the shift's
    # bits).
    shifted = tree
    for k in range((n - 1).bit_length()):
      # hop is never 0 mod n: for power-of-two n every 1<<k here is < n,
      # and otherwise n has an odd factor no power of two divides.
      hop = (1 << k) % n
      perm = [(i, (i + hop) % n) for i in range(n)]
      take_hop = ((shift >> k) & 1).astype(jnp.bool_)
      shifted = jax.tree.map(
          lambda x, p=perm: jnp.where(
              take_hop, lax.ppermute(x, axis_name, p), x),
          shifted)
  return jax.tree.map(lambda x, y: 0.5 * (x + y), tree, shifted)


def sync_average(tree, axis_name: str = REPLICA_AXIS):
  """Synchronous model averaging (KungFu SynchronousAveragingOptimizer /
  SMA, EA-SGD style): all-replica mean of the weights."""
  return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)
