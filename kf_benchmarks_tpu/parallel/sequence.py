"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

Beyond-reference capability. The reference predates ring attention and
splits nothing across the sequence axis (SURVEY 5.7: its only
sequence-dimension machinery is DeepSpeech2 utterance padding,
ref preprocessing.py:977-1112); on TPU, long-context work is
first-class, so the framework ships the two standard context-parallel
schedules as shard_map collectives over a named ``seq`` mesh axis:

* ``ring_attention`` -- blockwise attention with an online (streaming)
  softmax; K/V blocks rotate around the ring via ``lax.ppermute`` while
  every device keeps only its own Q block. Per-device score memory is
  O(Lq_local * Lk_local), so sequence length scales linearly with ring
  size. The schedule is the TPU-native form of Ring Attention (Liu et
  al.) -- ppermute rides the ICI ring; XLA overlaps the permute with
  the block matmuls.
* ``ulysses_attention`` -- the all-to-all schedule (DeepSpeed-Ulysses):
  two ``lax.all_to_all`` calls swap the sharded axis seq<->heads, local
  full attention runs on every device over the whole sequence for its
  head slice. Cheaper collectives for moderate L when heads divide the
  axis size.

Both are differentiable (ppermute/all_to_all have transpose rules, the
online softmax is plain jnp), accumulate in float32 regardless of input
dtype, and match ``full_attention`` to numerical tolerance -- pinned by
tests/test_sequence_parallel.py on the 8-device virtual mesh.

Memory: every block update runs under ``jax.checkpoint``
(flash-style recompute-in-backward), so the blockwise bound holds for
TRAINING too -- autodiff recomputes the per-block score/probability
tensors instead of saving them as residuals; what the backward pass
stores per step is the O(block) carry/operand set, not the score tile
(pinned by test_blockwise_grad_memory_is_blockwise).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

SEQ_AXIS = "seq"

# Finite stand-in for -inf: exp(_NEG - _NEG) stays defined (=1, zeroed
# by the explicit mask on p) where a fully-masked row would otherwise
# produce NaN via inf - inf.
_NEG = -1e30


def full_attention(q, k, v, causal: bool = False,
                   scale: Optional[float] = None, segment_ids=None):
  """Plain O(L^2) multi-head attention; (batch, seq, heads, head_dim).

  The single-device reference the parallel schedules are tested
  against, and the local inner step of ``ulysses_attention``.

  ``segment_ids`` (B, L) int: packed-sequence masking -- a query
  attends only keys of ITS segment (equality, the Pallas SegmentIds
  convention: padding id 0 attends padding, so no row is ever fully
  masked and the causal diagonal keeps every row finite).
  """
  d = q.shape[-1]
  scale = (1.0 / math.sqrt(d)) if scale is None else scale
  s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                 k.astype(jnp.float32)) * scale
  mask = None
  if causal:
    lq, lk = q.shape[1], k.shape[1]
    mask = (jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :])[None, None]
  if segment_ids is not None:
    seg_mask = (segment_ids[:, :, None] ==
                segment_ids[:, None, :])[:, None]
    mask = seg_mask if mask is None else (mask & seg_mask)
  if mask is not None:
    s = jnp.where(mask, s, _NEG)
  p = jax.nn.softmax(s, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
  return out.astype(q.dtype)


def vary_like(ref, arrays, default_axes=(), extra_axes=()):
  """pcast zero-initialised accumulators to ``ref``'s varying set.

  Inside a shard_map body the Q operand is device-varying and so are
  the softmax accumulators after one update; constants must be pcast
  up front or scan/cond type checks reject the carry. ``default_axes``
  applies when ref carries no vma information (identity if also empty);
  ``extra_axes`` are unioned in regardless (e.g. the pipeline's stage
  axis, which the input does not vary on but the carries will). Only
  the axes each array is MISSING are pcast -- pcast rejects
  already-varying axes.
  """
  if not hasattr(lax, "pcast"):
    # Pre-vma jax (e.g. 0.4.x): avals carry no varying-manual-axes type
    # information and shard_map's check_rep accepts untyped carries, so
    # there is nothing to cast.
    return arrays
  want = (set(getattr(ref.aval, "vma", ()) or default_axes)
          | set(extra_axes))
  if not want:
    return arrays

  def cast(x):
    missing = tuple(sorted(want - set(getattr(x.aval, "vma", ()))))
    return lax.pcast(x, missing, to="varying") if missing else x

  return tuple(cast(x) for x in arrays)


def _block_update(q, k, v, m, l, o, scale, mask):
  """One online-softmax accumulation step over a K/V block.

  q: (B,Tq,H,D); k,v: (B,Tk,H,D); running max m and denominator l:
  (B,H,Tq); running unnormalised output o: (B,Tq,H,D) float32.

  MXU-native mixed precision: the matmul MULTIPLICANDS stay in the
  input dtype (bf16 on TPU runs at full MXU rate) and only the
  ACCUMULATION is f32, via preferred_element_type -- upcasting the
  inputs to f32 first would force f32 matmuls at a fraction of peak
  (the signature of the round-4 ~29 TFLOP/s long-context measurement).
  The probability tile is cast to v's dtype for the PV matmul, the
  standard flash-attention precision class; softmax statistics (max,
  exp, denominators) remain f32 throughout. With f32 inputs every step
  is bit-identical to the previous all-f32 form.
  """
  s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                 preferred_element_type=jnp.float32) * scale
  if mask is not None:
    s = jnp.where(mask, s, _NEG)
  m_new = jnp.maximum(m, jnp.max(s, axis=-1))
  corr = jnp.exp(m - m_new)                      # (B,H,Tq)
  p = jnp.exp(s - m_new[..., None])              # (B,H,Tq,Tk)
  if mask is not None:
    # Where the whole row is masked m_new == _NEG and exp(s-m_new) == 1;
    # zero those entries so they never enter l or o.
    p = jnp.where(mask, p, 0.0)
  l_new = l * corr + jnp.sum(p, axis=-1)
  pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                  preferred_element_type=jnp.float32)
  o_new = o * corr.swapaxes(1, 2)[..., None] + pv
  return m_new, l_new, o_new


def _block_update_remat(q, k, v, m, l, o, scale, offsets=None,
                        prevent_cse=True, seg_q=None, seg_k=None):
  """``_block_update`` with recompute-in-backward (flash-style remat).

  Without this, autodiff saves the (.., Tq, Tk) score/probability
  tensors of EVERY block step as residuals -- ~5 full score-tensor
  copies across a scan/ring, erasing the blockwise memory win exactly
  when it matters (training). jax.checkpoint drops those residuals and
  recomputes the block matmuls in the backward pass; what remains per
  step is the O(Tq + Tk) carry/operand set.

  ``offsets`` is None (no mask) or the scalar (q_off, k_off) GLOBAL
  position offsets of the two blocks; the causal mask is rebuilt
  INSIDE the checkpointed region from them, so the per-step residual
  is two scalars -- passing a materialised (Tq, Tk) mask as an operand
  would make checkpoint save it, stacking an O(L^2) bool residual
  across the scan/ring. ``seg_q``/``seg_k`` are the two blocks'
  (B, Tq)/(B, Tk) packed segment ids; the cross-segment mask (id
  equality, the Pallas SegmentIds convention) is likewise rebuilt
  inside the checkpointed region from the O(Tq + Tk) id operands.
  ``prevent_cse=False`` is for lax.scan bodies, where scan already
  prevents the problematic CSE (per the jax.checkpoint docs) and the
  default would only wall off fusion.
  """
  def inner(q_, k_, v_, m_, l_, o_, off, sq, sk):
    if off is None:
      mask = None
    else:
      q_off, k_off = off
      qpos = q_off + jnp.arange(q_.shape[1])
      kpos = k_off + jnp.arange(k_.shape[1])
      mask = (qpos[:, None] >= kpos[None, :])[None, None]
    if sq is not None:
      seg_mask = (sq[:, :, None] == sk[:, None, :])[:, None]
      mask = seg_mask if mask is None else (mask & seg_mask)
    return _block_update(q_, k_, v_, m_, l_, o_, scale, mask)

  return jax.checkpoint(inner, prevent_cse=prevent_cse)(
      q, k, v, m, l, o, offsets, seg_q, seg_k)


def _scan_kv_blocks(q, k, v, m, l, o, scale, block: int, offsets):
  """Accumulate a LOCAL K/V shard in ``block``-sized sub-blocks.

  The inner level of the two-level tiling inside one ring step: the
  softmax carries stay q-sized while each score tile is (Tq, block).
  ``offsets`` is None (unmasked) or the scalar (q_off, k_off) GLOBAL
  offsets of q and of the K/V shard's first position; causal sub-blocks
  strictly in the q rows' future are skipped via lax.cond.
  """
  b, tk, h, d = k.shape
  if tk % block != 0:
    raise ValueError(f"local K/V length {tk} not divisible by inner "
                     f"block {block}")
  nb = tk // block
  kb = k.reshape(b, nb, block, h, d).swapaxes(0, 1)
  vb = v.reshape(b, nb, block, h, d).swapaxes(0, 1)

  def stepf(carry, inp):
    j, kj, vj = inp
    if offsets is None:
      return _block_update_remat(q, kj, vj, *carry, scale, None,
                                 prevent_cse=False), None
    q_off, k_off = offsets
    has_work = k_off + j * block <= q_off + q.shape[1] - 1
    carry = lax.cond(
        has_work,
        lambda c: _block_update_remat(q, kj, vj, *c, scale,
                                      (q_off, k_off + j * block),
                                      prevent_cse=False),
        lambda c: c, carry)
    return carry, None

  (m, l, o), _ = lax.scan(stepf, (m, l, o), (jnp.arange(nb), kb, vb))
  return m, l, o


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS,
                   causal: bool = False, scale: Optional[float] = None,
                   inner_block: Optional[int] = None):
  """Blockwise ring attention inside a shard_map body.

  Arguments are the LOCAL sequence shards, (batch, seq/n, heads,
  head_dim); the result is the local shard of exact (not approximate)
  attention over the full sequence. ``causal`` masks by GLOBAL
  position: block offsets follow each K/V block as it travels the ring.

  The n-step rotation is a Python loop: n is the static mesh-axis size,
  so the program holds n ppermute+matmul pairs XLA can pipeline --
  while-loop carries would serialize against the permute instead.

  ``inner_block`` composes the single-chip two-level tiling into each
  ring step: the local K/V shard is scanned in sub-blocks so the
  per-device score tile is (Tq, inner_block) instead of (Tq, Tk) --
  the multi-chip long-context memory knob (at 64k over 8 devices the
  per-step score tile drops from 8k x 8k to 8k x inner_block).
  """
  n = lax.axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  tq, tk = q.shape[1], k.shape[1]
  d = q.shape[-1]
  scale = (1.0 / math.sqrt(d)) if scale is None else scale

  b, h = q.shape[0], q.shape[2]
  # Under a composed mesh (e.g. dp x sp x tp) q varies over more axes
  # than the ring's own, and the accumulators must match from step 0.
  m, l, o = vary_like(
      q,
      (jnp.full((b, h, tq), _NEG, jnp.float32),
       jnp.zeros((b, h, tq), jnp.float32),
       jnp.zeros((b, tq, h, d), jnp.float32)),
      default_axes=(axis_name,))

  kc, vc = k, v
  perm = [(i, (i + 1) % n) for i in range(n)]
  for step in range(n):
    # After `step` +1-shifts, device idx holds the block that started on
    # device (idx - step) mod n; global key positions follow it.
    if causal:
      src = (idx - step) % n
      # A block strictly in this device's future (src > idx) is fully
      # masked; skip its matmuls entirely. The predicate is per-device,
      # so the conditional runs the update only where work exists --
      # without this, (n-1)/2n of the ring's block updates would be
      # dead FLOPs at large n. (The zigzag variant balances the skip
      # across devices.)
      if inner_block is None:
        update = lambda ops: _block_update_remat(
            *ops, scale, (idx * tq, src * tk))
      else:
        update = lambda ops: _scan_kv_blocks(
            *ops, scale, inner_block, (idx * tq, src * tk))
      m, l, o = lax.cond(
          src <= idx, update,
          lambda ops: (ops[3], ops[4], ops[5]),
          (q, kc, vc, m, l, o))
    elif inner_block is None:
      m, l, o = _block_update_remat(q, kc, vc, m, l, o, scale, None)
    else:
      m, l, o = _scan_kv_blocks(q, kc, vc, m, l, o, scale,
                                inner_block, None)
    if step != n - 1:
      kc = lax.ppermute(kc, axis_name, perm)
      vc = lax.ppermute(vc, axis_name, perm)

  out = o / jnp.maximum(l, 1e-30).swapaxes(1, 2)[..., None]
  return out.astype(q.dtype)


def zigzag_order(seq_len: int, n: int):
  """Permutation putting stripe pair (j, 2n-1-j) on device j.

  The causal load-balance placement (Megatron context-parallel style):
  the global sequence is cut into 2n stripes; device j's contiguous
  shard becomes [stripe j, stripe 2n-1-j], pairing an early stripe
  (little causal work) with a late one (much causal work) so every
  device executes ~2 block updates per ring step instead of device
  n-1 executing all n. Apply with jnp.take along the sequence axis
  before sharding; invert with ``zigzag_inverse``.
  """
  if seq_len % (2 * n) != 0:
    raise ValueError(f"seq len {seq_len} not divisible by 2n={2 * n}")
  t = seq_len // (2 * n)
  order = []
  for j in range(n):
    order.extend(range(j * t, (j + 1) * t))
    order.extend(range((2 * n - 1 - j) * t, (2 * n - j) * t))
  return jnp.asarray(order)


def zigzag_inverse(seq_len: int, n: int):
  order = zigzag_order(seq_len, n)
  inv = jnp.zeros_like(order)
  return inv.at[order].set(jnp.arange(seq_len))


def ring_attention_zigzag(q, k, v, axis_name: str = SEQ_AXIS,
                          scale: Optional[float] = None,
                          inner_block: Optional[int] = None):
  """Causal ring attention over ZIGZAG-placed shards, load-balanced.

  Local shards are [stripe idx, stripe 2n-1-idx] of the zigzag_order
  permutation (length 2t each). Per ring step each device runs two
  block updates (three on its one diagonal step src == idx) -- (2n+1)
  total per device, identical for every idx -- where the contiguous
  placement leaves device n-1 doing all n updates while device 0 idles
  (the wall-time bound of the lockstep ring). Returns the local shard
  of exact causal attention in the same zigzag layout.
  """
  n = lax.axis_size(axis_name)
  idx = lax.axis_index(axis_name)
  tq2 = q.shape[1]
  if tq2 % 2 != 0:
    raise ValueError(f"zigzag local shard length must be even, got {tq2}")
  t = tq2 // 2
  d = q.shape[-1]
  scale = (1.0 / math.sqrt(d)) if scale is None else scale
  b, h = q.shape[0], q.shape[2]
  z = 2 * n - 1  # stripe index of the latest stripe

  # Split the local shard into its early (stripe idx) and late
  # (stripe z-idx) halves; each accumulates independently.
  q1, q2 = q[:, :t], q[:, t:]
  acc1 = vary_like(
      q, (jnp.full((b, h, t), _NEG, jnp.float32),
          jnp.zeros((b, h, t), jnp.float32),
          jnp.zeros((b, t, h, d), jnp.float32)),
      default_axes=(axis_name,))
  acc2 = tuple(jnp.copy(x) for x in acc1)

  if inner_block is None:
    upd = lambda qq, kk, vv, acc, offs: _block_update_remat(
        qq, kk, vv, *acc, scale, offs)
  else:
    # Stripe-sized tiles shrink to (t, inner_block) -- the same knob as
    # the contiguous ring's, but dividing the STRIPE length t (= local
    # shard / 2), not the shard length.
    if t % inner_block != 0:
      raise ValueError(
          f"zigzag inner_block must divide the stripe length {t} "
          f"(= local shard {tq2} / 2), got {inner_block}")
    upd = lambda qq, kk, vv, acc, offs: _scan_kv_blocks(
        qq, kk, vv, *acc, scale, inner_block, offs)

  kc, vc = k, v
  perm = [(i, (i + 1) % n) for i in range(n)]
  for step in range(n):
    src = (idx - step) % n
    k1, k2 = kc[:, :t], kc[:, t:]
    v1, v2 = vc[:, :t], vc[:, t:]
    # Stripe indices: q1 -> idx, q2 -> z-idx; kv1 -> src, kv2 -> z-src.
    # q1 vs kv2 (z-src >= n > idx) is ALWAYS fully masked: skipped
    # statically. q2 vs kv1 (z-idx >= n > src) is ALWAYS fully
    # unmasked: runs mask-free. The two same-kind pairs gate on the
    # device-varying stripe comparison (diagonal => triangular mask).
    acc1 = lax.cond(
        idx >= src,
        lambda ops: upd(q1, k1, v1, ops, (idx * t, src * t)),
        lambda ops: ops, acc1)
    acc2 = upd(q2, k1, v1, acc2, None)
    acc2 = lax.cond(
        src >= idx,
        lambda ops: upd(q2, k2, v2, ops,
                        ((z - idx) * t, (z - src) * t)),
        lambda ops: ops, acc2)
    if step != n - 1:
      kc = lax.ppermute(kc, axis_name, perm)
      vc = lax.ppermute(vc, axis_name, perm)

  def finish(acc):
    m_, l_, o_ = acc
    return o_ / jnp.maximum(l_, 1e-30).swapaxes(1, 2)[..., None]

  out = jnp.concatenate([finish(acc1), finish(acc2)], axis=1)
  return out.astype(q.dtype)


def blockwise_attention(q, k, v, block_size: int, causal: bool = False,
                        scale: Optional[float] = None,
                        q_block_size: Optional[int] = None,
                        segment_ids=None):
  """Single-device flash-style attention: lax.scan over K/V blocks with
  the same online softmax as the ring schedule, so forward peak memory
  is O(L * block) instead of O(L^2) and long contexts fit in HBM on one
  chip. Exact (not windowed): every query still attends to every key.
  The scan body is rematerialised (``_block_update_remat``), so the
  backward pass recomputes each block's scores rather than stacking
  nblk full-score residuals; its stored state is the scan carry stack,
  O(L^2 * D / block) -- ~5*block/D x smaller than unrematerialised
  residuals (block=512, D=64: ~40x).

  ``q_block_size`` selects the two-level (flash-style) tiling: an
  outer scan over q blocks, an inner scan over K/V blocks, so the
  softmax accumulators are (.., q_block) tiles instead of full-length
  (.., L) arrays -- the single-level path re-reads O(L)-sized m/l/o
  from HBM on every K/V step, which is what made the measured
  long-context MFU bandwidth-lean (PERF.md round 4). Under ``causal``
  the inner scan also SKIPS K/V blocks strictly in the q block's
  future via lax.cond, recovering the ~2x of FLOPs the single-level
  path spends on fully-masked tiles.

  ``segment_ids`` (B, L) int engages packed-sequence masking: queries
  attend only keys of their own segment (id equality, the Pallas
  SegmentIds convention -- padding id 0 attends padding, so no row is
  ever fully masked). The two-level path additionally SKIPS any K/V
  tile that is fully cross-segment for EVERY batch row (per-block
  segment-id min/max interval test via lax.cond) -- first-fit packing
  lays segments contiguously with padding at the row tail, so most
  (q block, kv block) pairs outside the block-diagonal band carry no
  same-segment pair and their matmuls are dead FLOPs; this is what
  lets packing COMPOSE with the flash-style schedule instead of
  falling back to a dense (L, L) mask.

  (B, L, H, D) -> (B, L, H, D); L % block_size == 0. Composes with
  ring_attention -- inside a ring step each device could scan its local
  block -- but is exposed standalone as the single-chip long-context
  path.
  """
  b, l, h, d = q.shape
  if l % block_size != 0:
    raise ValueError(f"seq len {l} not divisible by block {block_size}")
  nblk = l // block_size
  scale_ = (1.0 / math.sqrt(d)) if scale is None else scale

  kb = k.reshape(b, nblk, block_size, h, d).swapaxes(0, 1)
  vb = v.reshape(b, nblk, block_size, h, d).swapaxes(0, 1)
  segb = seg_min = seg_max = None
  if segment_ids is not None:
    # Per-KV-block segment ids (nblk, B, block) plus their per-row
    # min/max -- the interval test the tile-skip cond keys on.
    segb = segment_ids.reshape(b, nblk, block_size).swapaxes(0, 1)
    seg_min = segb.min(axis=2)  # (nblk, B)
    seg_max = segb.max(axis=2)

  if q_block_size is None:
    m0, l0, o0 = vary_like(
        q,
        (jnp.full((b, h, l), _NEG, jnp.float32),
         jnp.zeros((b, h, l), jnp.float32),
         jnp.zeros((b, l, h, d), jnp.float32)))

    def step(carry, inp):
      m, acc_l, o = carry
      j, kj, vj, sj = inp
      offsets = (0, j * block_size) if causal else None
      m, acc_l, o = _block_update_remat(q, kj, vj, m, acc_l, o, scale_,
                                        offsets, prevent_cse=False,
                                        seg_q=(segment_ids
                                               if segb is not None
                                               else None),
                                        seg_k=sj)
      return (m, acc_l, o), None

    (m, acc_l, o), _ = lax.scan(
        step, (m0, l0, o0), (jnp.arange(nblk), kb, vb, segb))
    out = o / jnp.maximum(acc_l, 1e-30).swapaxes(1, 2)[..., None]
    return out.astype(q.dtype)

  if l % q_block_size != 0:
    raise ValueError(
        f"seq len {l} not divisible by q block {q_block_size}")
  nq = l // q_block_size
  qb = q.reshape(b, nq, q_block_size, h, d).swapaxes(0, 1)
  sqb = None
  if segb is not None:
    sqb = segment_ids.reshape(b, nq, q_block_size).swapaxes(0, 1)

  def q_step(_, q_inp):
    if segb is None:
      qi, qi_blk = q_inp
      sq_blk = None
    else:
      qi, qi_blk, sq_blk = q_inp
      q_min, q_max = sq_blk.min(axis=1), sq_blk.max(axis=1)  # (B,)
    acc0 = vary_like(
        q,
        (jnp.full((b, h, q_block_size), _NEG, jnp.float32),
         jnp.zeros((b, h, q_block_size), jnp.float32),
         jnp.zeros((b, q_block_size, h, d), jnp.float32)))

    def kv_step(carry, kv_inp):
      if segb is None:
        j, kj, vj = kv_inp
        sj = None
      else:
        j, kj, vj, sj, k_min, k_max = kv_inp

      def do(c):
        offs = (qi * q_block_size, j * block_size) if causal else None
        return _block_update_remat(qi_blk, kj, vj, *c, scale_, offs,
                                   prevent_cse=False, seg_q=sq_blk,
                                   seg_k=sj)

      has_work = None
      if causal:
        # K/V block j is strictly in this q block's future iff its
        # first key position exceeds the q block's last row.
        has_work = j * block_size <= qi * q_block_size + (
            q_block_size - 1)
      if segb is not None:
        # The tile is fully cross-segment when NO batch row's q-block
        # segment interval intersects its kv-block interval (segments
        # are contiguous per row, so min/max intervals are exact);
        # such a tile is all-masked and its matmuls are skipped.
        seg_work = jnp.any((k_min <= q_max) & (k_max >= q_min))
        has_work = seg_work if has_work is None else (has_work &
                                                      seg_work)
      if has_work is not None:
        carry = lax.cond(has_work, do, lambda c: c, carry)
      else:
        carry = do(carry)
      return carry, None

    kv_xs = ((jnp.arange(nblk), kb, vb) if segb is None else
             (jnp.arange(nblk), kb, vb, segb, seg_min, seg_max))
    (m, acc_l, o), _ = lax.scan(kv_step, acc0, kv_xs)
    out = o / jnp.maximum(acc_l, 1e-30).swapaxes(1, 2)[..., None]
    return None, out

  q_xs = ((jnp.arange(nq), qb) if segb is None else
          (jnp.arange(nq), qb, sqb))
  _, outs = lax.scan(q_step, None, q_xs)
  # (nq, B, qb, H, D) -> (B, L, H, D)
  return outs.swapaxes(0, 1).reshape(b, l, h, d).astype(q.dtype)


def decode_attention(q, k, v, pos, block: Optional[int] = None,
                     impl: str = "tiled", scale: Optional[float] = None,
                     cpu_fallback: Optional[bool] = None,
                     exact: bool = False, q_block: Optional[int] = None,
                     page_table=None):
  """Single-query attention over a KV ring buffer -- the serving decode
  step's core (serving/decode.py threads the cache through it).

  ``q`` is the current token's query, (B, 1, H, D); ``k``/``v`` are the
  (B, T, H, D) ring buffers with the current token's K/V already
  written; ``pos`` (B,) int32 is each slot's absolute position. A key
  slot ``s`` participates iff ``s <= pos[b]`` -- masked slots
  contribute EXACTLY zero on both paths (the ``_NEG`` -> zeroed-p /
  exp-underflow arithmetic), so stale ring contents and a foreign
  packed-prefill neighbor never perturb the result.

  ``impl='tiled'`` runs the ``_block_update`` online softmax over
  ``block``-sized key blocks; ``impl='flash'`` is the Pallas flash
  kernel's decode mode on TPU (SegmentIds masking, q length 1) with the
  :func:`full_attention`-style masked softmax as the CPU fallback --
  the same fallback split as :func:`pallas_flash_attention`.

  ``exact=True`` is the oracle mode: the single query is scattered into
  a zero q tile of the FULL ring length and run through the exact
  full-sequence attention program (:func:`blockwise_attention` /
  :func:`full_attention` -- identical shapes, identical op schedule),
  then its one row is gathered back. Per-row results of a fixed-shape
  XLA program are deterministic and row-independent, so exact-mode
  decode at position ``p`` is BIT-IDENTICAL to row ``p`` of the full
  forward -- the KV-cache correctness contract tests/test_serving.py
  pins. The fast default (``exact=False``) computes the 1-row program
  instead; XLA schedules the (1, T) contraction differently from the
  (T, T) one, so it agrees to float rounding (~1e-6 rel), not bitwise
  -- ~T x cheaper, the production serving path.

  ``page_table`` switches on the PAGED KV layout (the vLLM block-table
  idea on JAX gather indices; serving/decode.py paged caches): ``k``/
  ``v`` are then fixed-size page POOLS (P, page, H, D) shared across
  slots, and ``page_table`` (B, pages_per_slot) int32 maps each slot's
  logical page ``j`` to a pool row. The fast path is the SAME
  ``_block_update`` online-softmax scan as the dense tiled schedule
  with the reshape-slice replaced by a pool gather and the block size
  pinned to the page size -- per-block inputs are value-identical to a
  dense ring holding the same tokens, which is the paged/dense
  bit-identity contract tests/test_serving_variants.py pins at gemm
  shapes. Entries of unallocated table slots point at pool row 0 (the
  never-allocated scratch page); the position mask makes them
  contribute exactly zero, same as stale dense ring rows. Paged fast
  mode always runs the tiled gather schedule (the Pallas flash kernel
  has no block-table mode here); ``exact=True`` gathers the dense
  (B, T, H, D) view back out of the pool first and runs the dense
  oracle on it -- oracle/test mode only, since materializing the dense
  slab is exactly what paging exists to avoid.
  """
  b, tq, h, d = q.shape
  scale = (1.0 / math.sqrt(d)) if scale is None else scale
  if impl not in ("tiled", "flash"):
    raise ValueError(f"impl must be 'tiled' or 'flash', got {impl!r}")
  if page_table is not None:
    page = k.shape[1]
    pages_per_slot = page_table.shape[1]
    if exact:
      # Dense-view reconstruction: pool rows gathered back into each
      # slot's (T, page) layout. k[page_table] is (B, pps, page, H, D).
      kd = k[page_table].reshape(b, pages_per_slot * page, k.shape[2],
                                 k.shape[3])
      vd = v[page_table].reshape(b, pages_per_slot * page, v.shape[2],
                                 v.shape[3])
      return decode_attention(q, kd, vd, pos, block=block, impl=impl,
                              scale=scale, cpu_fallback=cpu_fallback,
                              exact=True, q_block=q_block)
    m0 = jnp.full((b, k.shape[2], tq), _NEG, jnp.float32)
    l0 = jnp.zeros((b, k.shape[2], tq), jnp.float32)
    o0 = jnp.zeros((b, tq, k.shape[2], d), jnp.float32)

    def page_step(carry, j):
      ids = lax.dynamic_index_in_dim(page_table, j, axis=1,
                                     keepdims=False)       # (B,)
      kj, vj = k[ids], v[ids]                    # (B, page, H, D)
      mask = (pos[:, None, None, None] >=
              (j * page + jnp.arange(page))[None, None, None, :])
      return _block_update(q, kj, vj, *carry, scale, mask), None

    (m, l, o), _ = lax.scan(page_step, (m0, l0, o0),
                            jnp.arange(pages_per_slot))
    out = o / jnp.maximum(l, 1e-30).swapaxes(1, 2)[..., None]
    return out.astype(q.dtype)
  t = k.shape[1]
  if exact:
    # Scatter row clamped to the LAST ring row once pos wraps past the
    # buffer: the causal mask at row t-1 admits every slot, which is
    # exactly the fast path's valid set for a wrapped ring (all slots
    # hold trailing-window keys). Below the wrap the row IS pos and
    # the full-forward graph identity holds bitwise; past it the mode
    # degrades to the same windowed semantics as the fast path.
    rows = jnp.minimum(pos, t - 1)
    qfull = jnp.zeros((b, t, h, d), q.dtype)
    qfull = qfull.at[jnp.arange(b), rows].set(q[:, 0])
    if impl == "flash":
      # The kernel's own reference form (pallas_flash_attention's CPU
      # fallback) -- the op graph the flash full forward executes off
      # TPU, so the oracle holds where it can actually run.
      out = full_attention(qfull, k, v, causal=True, scale=scale)
    else:
      blk = min(block or t, t)
      out = blockwise_attention(qfull, k, v, block_size=blk, causal=True,
                                scale=scale,
                                q_block_size=min(q_block or blk, t))
    return out[jnp.arange(b), rows][:, None]
  kpos = jnp.arange(t)
  if impl == "flash":
    if cpu_fallback is None:
      cpu_fallback = jax.default_backend() != "tpu"
    if not cpu_fallback:
      from jax.experimental.pallas.ops.tpu import flash_attention as fa
      seg = fa.SegmentIds(
          q=jnp.ones((b, tq), jnp.int32),
          kv=(kpos[None, :] <= pos[:, None]).astype(jnp.int32))
      blk = min(block or t, tq, t)
      qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
      out = fa.flash_attention(qt, kt, vt, None, seg, causal=False,
                               sm_scale=scale,
                               block_sizes=uniform_flash_block_sizes(blk))
      return out.swapaxes(1, 2).astype(q.dtype)
    # CPU fallback: the full_attention op sequence, row-for-row.
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = (kpos[None, None, None, :] <= pos[:, None, None, None])
    s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
  blk = min(block or t, t)
  if t % blk != 0:
    raise ValueError(f"cache length {t} not divisible by block {blk}")
  nb = t // blk
  kb = k.reshape(b, nb, blk, h, d).swapaxes(0, 1)
  vb = v.reshape(b, nb, blk, h, d).swapaxes(0, 1)
  m0 = jnp.full((b, h, tq), _NEG, jnp.float32)
  l0 = jnp.zeros((b, h, tq), jnp.float32)
  o0 = jnp.zeros((b, tq, h, d), jnp.float32)

  def step(carry, inp):
    j, kj, vj = inp
    # Mask rebuilt per block from the scalar offset, exactly as the
    # training path's _block_update_remat does; fully-masked blocks
    # no-op bitwise (m stays, corr == 1, p == 0), which is why decode
    # over the FULL ring matches the full forward's cond-skipped scan.
    mask = (pos[:, None, None, None] >=
            (j * blk + jnp.arange(blk))[None, None, None, :])
    return _block_update(q, kj, vj, *carry, scale, mask), None

  (m, l, o), _ = lax.scan(step, (m0, l0, o0), (jnp.arange(nb), kb, vb))
  out = o / jnp.maximum(l, 1e-30).swapaxes(1, 2)[..., None]
  return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False,
                      scale: Optional[float] = None,
                      local_block: Optional[int] = None):
  """All-to-all (Ulysses) attention inside a shard_map body.

  Sequence-sharded (B, L/n, H, D) inputs are re-sharded over heads --
  one tiled all_to_all each -- so every device runs full attention over
  the complete sequence for H/n heads, then the output is swapped back.
  Requires heads % axis_size == 0.

  ``local_block`` replaces the O(L^2) local score tensor with the
  blockwise (flash-style) schedule: without it, Ulysses at long L is
  exactly the full-attention OOM the blockwise path exists to avoid
  (the ring schedule never materialises it; this closes the same hole
  for the all-to-all schedule).
  """
  n = lax.axis_size(axis_name)
  h = q.shape[2]
  if h % n != 0:
    raise ValueError(
        f"ulysses_attention needs heads % axis_size == 0, got heads={h} "
        f"over {n} '{axis_name}' devices; use ring_attention for "
        f"head-count-agnostic sequence parallelism")

  def seq_to_heads(x):
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)

  qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
  if local_block is None:
    out = full_attention(qh, kh, vh, causal=causal, scale=scale)
  else:
    out = blockwise_attention(qh, kh, vh, block_size=local_block,
                              causal=causal, scale=scale,
                              q_block_size=local_block)
  return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)


def uniform_flash_block_sizes(block: int):
  """All-fields-equal BlockSizes for the Pallas kernel -- ONE place to
  build 'matched tiling' configurations, so A/Bs against the XLA-scan
  paths cannot silently diverge between call sites."""
  from jax.experimental.pallas.ops.tpu import flash_attention as fa
  return fa.BlockSizes(
      block_q=block, block_k_major=block, block_k=block, block_b=1,
      block_q_major_dkv=block, block_k_major_dkv=block,
      block_k_dkv=block, block_q_dkv=block, block_k_major_dq=block,
      block_k_dq=block, block_q_dq=block)


def pallas_flash_attention(q, k, v, causal: bool = False,
                           scale: Optional[float] = None,
                           block_sizes=None, block: Optional[int] = None,
                           segment_ids=None,
                           cpu_fallback: Optional[bool] = None):
  """JAX's TPU Pallas flash-attention kernel behind this module's
  (B, L, H, D) layout -- the hand-tiled alternative to the XLA-scan
  blockwise schedule, for A/B measurement on hardware
  (experiments/long_context_probe.py --impls flash).

  ``segment_ids`` (B, L) int rides the kernel's native SegmentIds
  support (packed sequences): the kernel masks cross-segment tiles and
  skips fully-masked blocks inside its own grid schedule, so packing
  composes with the hand-tiled path without a dense (L, L) mask.

  The kernel itself (jax.experimental.pallas.ops.tpu.flash_attention)
  has no CPU lowering. ``cpu_fallback=None`` (the default) therefore
  routes non-TPU backends to ``full_attention`` with the identical
  mask semantics -- the kernel's own reference form -- so CPU suites
  can EXECUTE flash-configured models (the packed-sequence oracle
  tests), not just trace them; ``False`` forces the kernel path (the
  trace-level BlockSizes drift guard wants the real call graph), and
  ``True`` forces the reference path on any backend. Differentiable on
  both paths -- the library ships fused dq/dkv backward kernels via
  custom_vjp.
  """
  if cpu_fallback is None:
    cpu_fallback = jax.default_backend() != "tpu"
  d = q.shape[-1]
  scale = (1.0 / math.sqrt(d)) if scale is None else scale
  if cpu_fallback:
    return full_attention(q, k, v, causal=causal, scale=scale,
                          segment_ids=segment_ids)
  from jax.experimental.pallas.ops.tpu import flash_attention as fa
  if block is not None:
    if block_sizes is not None:
      raise ValueError("pass block OR block_sizes, not both")
    # Clamp to BOTH sequence lengths: the uniform BlockSizes tile the
    # K/V axis too, so a short-KV (cross-attention-shaped) input with
    # kv_len < block would otherwise mis-tile the k-major grid
    # (advisor round-5).
    block_sizes = uniform_flash_block_sizes(
        min(block, q.shape[1], k.shape[1]))
  seg = None
  if segment_ids is not None:
    seg = fa.SegmentIds(q=segment_ids, kv=segment_ids)
  qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
  out = fa.flash_attention(qt, kt, vt, None, seg, causal=causal,
                           sm_scale=scale, block_sizes=block_sizes)
  return out.swapaxes(1, 2).astype(q.dtype)


_IMPLS = {"ring": ring_attention, "ulysses": ulysses_attention}


def make_sequence_parallel_attention(mesh: Mesh, impl: str = "ring",
                                     axis_name: str = SEQ_AXIS,
                                     causal: bool = False,
                                     scale: Optional[float] = None,
                                     inner_block: Optional[int] = None):
  """Jitted attention over GLOBAL (B, L, H, D) arrays sequence-sharded
  on ``axis_name`` of ``mesh``; batch/heads stay replicated across the
  seq axis (compose with a 'replica' batch axis for dp x sp).
  ``inner_block`` is the multi-chip long-context memory knob: ring
  scans each ring step's local K/V in sub-blocks; ulysses bounds its
  local full-sequence step with the blockwise schedule."""
  if impl not in _IMPLS:
    raise ValueError(f"impl must be one of {sorted(_IMPLS)}, got {impl!r}")
  fn = _IMPLS[impl]
  spec = P(None, axis_name, None, None)

  def body(q, k, v):
    if impl == "ring":
      return fn(q, k, v, axis_name=axis_name, causal=causal,
                scale=scale, inner_block=inner_block)
    # ulysses: the blockwise knob bounds its LOCAL full-sequence step.
    return fn(q, k, v, axis_name=axis_name, causal=causal, scale=scale,
              local_block=inner_block)

  sharded = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
  return jax.jit(sharded)


def make_zigzag_attention(mesh: Mesh, axis_name: str = SEQ_AXIS,
                          scale: Optional[float] = None,
                          inner_block: Optional[int] = None):
  """Jitted load-balanced causal ring attention over GLOBAL (B, L, H,
  D) arrays in NORMAL sequence order.

  The zigzag permutation is applied (and inverted) inside the jit for
  convenience -- XLA lowers it to a cross-shard gather, so pipelines
  that can store their sequences pre-permuted (zigzag_order) should
  call ring_attention_zigzag directly inside their own shard_map and
  skip both gathers.
  """
  spec = P(None, axis_name, None, None)
  n = mesh.shape[axis_name]

  def body(q, k, v):
    return ring_attention_zigzag(q, k, v, axis_name=axis_name,
                                 scale=scale, inner_block=inner_block)

  sharded = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)

  def call(q, k, v):
    order = zigzag_order(q.shape[1], n)
    inv = jnp.argsort(order)
    qz, kz, vz = (jnp.take(x, order, axis=1) for x in (q, k, v))
    return jnp.take(sharded(qz, kz, vz), inv, axis=1)

  return jax.jit(call)
