"""Core benchmark runtime: build -> compile -> warmup -> timed loop -> report.

Re-design of the reference's BenchmarkCNN (ref: benchmark_cnn.py:1230-2391).
The TF "graph + sess.run" pair becomes "jitted step fn + host loop"; the
fetches dict becomes the step-output metrics pytree; warmup = compile + N
discarded steps; the images/sec + uncertainty + jitter math and the
per-step line format are kept exactly (SURVEY 7.1).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kf_benchmarks_tpu import checkpoint
from kf_benchmarks_tpu import cluster as cluster_lib
from kf_benchmarks_tpu import elastic as elastic_lib
from kf_benchmarks_tpu import faults as faults_lib
from kf_benchmarks_tpu import learning_rate
from kf_benchmarks_tpu import metrics as metrics_lib
from kf_benchmarks_tpu import observability
from kf_benchmarks_tpu import optimizers
from kf_benchmarks_tpu import telemetry as telemetry_lib
from kf_benchmarks_tpu import tracing as tracing_lib
from kf_benchmarks_tpu import train_step as train_step_lib
from kf_benchmarks_tpu import validation
from kf_benchmarks_tpu.data import datasets
from kf_benchmarks_tpu.models import model_config
from kf_benchmarks_tpu.parallel import mesh as mesh_lib
from kf_benchmarks_tpu.parallel import strategies
from kf_benchmarks_tpu.parallel import kungfu
from kf_benchmarks_tpu.utils import log as log_util
from kf_benchmarks_tpu.utils import pipeline as pipeline_lib
from kf_benchmarks_tpu.utils import sync

def log_fn(msg):
  """Late-bound so tests/bench can monkey-patch log_util.log_fn."""
  log_util.log_fn(msg)


# The persistent-compile-cache dir this PROCESS last applied: jax
# initializes the cache object lazily and keeps it for the process
# lifetime, so re-pointing the config alone would silently keep
# writing to the first run's directory -- reset_cache() drops the
# stale cache object before the new dir takes effect.
_active_compile_cache_dir = None

# The provenance of the LAST --autotuned_config application setup()
# performed ({path, entry} or None): BenchmarkCNN reuses it (matched by
# table path) instead of re-reading the table from disk, so the
# recorded provenance can never disagree with what was applied (e.g. a
# concurrent table rewrite between setup and construction).
_applied_tuned_provenance = None


def _configure_compile_cache(cache_dir) -> None:
  """Apply ``cache_dir`` (or None = off) as the process's persistent
  XLA compilation cache, resetting jax's cached cache object when the
  directory changes (see _active_compile_cache_dir)."""
  global _active_compile_cache_dir
  if cache_dir == _active_compile_cache_dir:
    return
  from jax.experimental.compilation_cache import compilation_cache as cc
  cc.reset_cache()
  jax.config.update("jax_compilation_cache_dir", cache_dir)
  if cache_dir:
    # Serialize EVERY compile, not just those over jax's default
    # 1-second floor: the once-per-shape contract (and the ledger's
    # cache_hit accounting) must not depend on how fast a given
    # backend happens to compile a given program.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
  _active_compile_cache_dir = cache_dir


def opt_state_bytes_per_device(opt_state) -> int:
  """Per-device optimizer-state HBM of a stacked opt_state tree: every
  leaf carries a leading stacked-replica (or shard-row) dim, so
  per-device bytes are total bytes / leading dim -- ~|state| on the
  replicated layout, ~|state|/n under --shard_optimizer_state (the
  ZeRO partitioning claim, surfaced in bench.py's JSON line).

  Shape/dtype-based, so it accounts concrete device arrays and the
  auditor's ``jax.eval_shape`` ShapeDtypeStructs identically
  (analysis/contracts.py trace_contract aux)."""
  total = 0
  for leaf in jax.tree.leaves(opt_state):
    shape = tuple(leaf.shape)
    lead = shape[0] if shape else 1
    nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(
        leaf.dtype).itemsize
    total += nbytes // max(int(lead), 1)
  return total


def compute_eval_step_set(params, global_batch_size: int,
                          num_train_examples: int, num_batches: int,
                          start_step: int = 0, start_examples: int = 0):
  """Training steps after which mid-training eval runs, from the
  epoch-based and explicit-list schedules (ref: benchmark_cnn.py:1449-1476;
  the every-n-steps cadence is checked separately in the loop).

  ``start_step``/``start_examples`` re-anchor the epoch->step mapping
  after an elastic reshape changes the global batch size mid-run (epoch
  boundaries are example counts, not step counts)."""
  steps = set()

  def epoch_to_step(e):
    # Ref formula: ceil(e * examples / batch) via int arithmetic,
    # re-anchored at the examples already consumed.
    remaining = int(e * num_train_examples) - start_examples
    return (start_step +
            (remaining + global_batch_size - 1) // global_batch_size)

  if params.eval_during_training_every_n_epochs:
    n = float(params.eval_during_training_every_n_epochs)
    num_epochs = ((start_examples +
                   (num_batches - start_step) * global_batch_size) /
                  max(num_train_examples, 1))
    # The endpoint is included when the run lands exactly on an epoch
    # boundary (the reference's exclusive np.arange silently dropped the
    # end-of-training eval for runs of exactly k*n epochs).
    epochs = [e for e in np.arange(n, num_epochs + 1e-9, n)
              if e * num_train_examples > start_examples]
    steps |= {epoch_to_step(e) for e in epochs}
  if params.eval_during_training_at_specified_steps:
    try:
      steps |= set(
          map(int, params.eval_during_training_at_specified_steps))
    except ValueError:
      raise validation.ParamError(
          "eval_during_training_at_specified_steps value of "
          f"{params.eval_during_training_at_specified_steps} cannot be "
          "converted to a list of integers (ref :1457-1463)")
  if params.eval_during_training_at_specified_epochs:
    try:
      epochs = [float(e)
                for e in params.eval_during_training_at_specified_epochs]
    except ValueError:
      raise validation.ParamError(
          "eval_during_training_at_specified_epochs value of "
          f"{params.eval_during_training_at_specified_epochs} cannot be "
          "converted to a list of floats (ref :1465-1476)")
    steps |= {epoch_to_step(e) for e in epochs
              if e * num_train_examples > start_examples}
  return steps


def feeder_prefetch(params) -> int:
  """Host->device prefetch depth: --input_prefetch_depth when set,
  else the deeper of the dataset prefetch buffer and
  --batch_group_size (the reference's input producers hand the staging
  areas ``batch_group_size`` batches at a time, ref: cnn_util.py:118-198
  ImageProducer, benchmark_cnn.py:134-136)."""
  explicit = getattr(params, "input_prefetch_depth", None)
  if explicit:
    return int(explicit)
  return max(params.datasets_prefetch_buffer_size or 1,
             params.batch_group_size or 1)


# Flags accepted for reference-CLI parity with no TPU effect. Changing
# them from their defaults logs a note at setup (silent acceptance of an
# ineffective flag was a round-1 defect); flags with real consumers never
# belong here.
_NOOP_PARITY_FLAGS = {
    "winograd_nonfused": ("cuDNN autotune env knob; no TPU analog (ref :3285-3297)"),
    "gpu_memory_frac_for_testing": ("per-process GPU memory split for tests; TPU memory is not " "fractionally reservable (ref :336-342)"),
    "network_topology": ("GPU box topology table index; the TPU mesh topology comes " "from the runtime (ref constants.py:21-24)"),
    "sparse_to_dense_grads": ("JAX gradients are already dense (ref :518-519)"),
    "allreduce_merge_scope": ("ScopedAllocator merge hint; XLA schedules collectives itself " "(ref :561-566)"),
    "server_protocol": ("the coordination service speaks its own protocol " "(ref :578)"),
    "trt_max_workspace_size_bytes": ("TensorRT knob"),
    "xla": ("XLA is the only execution path on TPU"),
    "xla_compile": ("the whole step is always jitted"),
    "freeze_when_forward_only": ("freezing IS the AOT export; " "use --aot_save_path"),
    "fuse_decode_and_crop": ("the host pipeline always crops " "before resizing"),
    "distort_color_in_yiq": ("color jitter runs via PIL " "enhancers"),
    "datasets_use_prefetch": ("the DeviceFeeder always prefetches"),
    "datasets_parallel_interleave_cycle_length": ("shard reads interleave via the thread pool"),
    "datasets_sloppy_parallel_interleave": ("tf.data knob"),
    "datasets_parallel_interleave_prefetch": ("tf.data knob"),
    "use_multi_device_iterator": ("the DeviceFeeder is the " "MultiDeviceIterator analog"),
    "multi_device_iterator_max_buffer_size": ("MultiDeviceIterator " "knob"),
    "use_resource_vars": ("JAX state is functional"),
    "use_tf_layers": ("one flax layer path"),
    "use_python32_barrier": ("CPython barrier workaround"),
    "compute_lr_on_cpu": ("the LR schedule is fused into the " "jitted step"),
    "enable_optimizations": ("XLA optimizations are always on"),
    "rewriter_config": ("grappler knob"),
    "allow_growth": ("GPU memory knob"),
    "force_gpu_compatible": ("GPU pinned-memory knob"),
    "gpu_indices": ("GPU ring-order indices"),
    "gpu_thread_mode": ("GPU thread pools"),
    "per_gpu_thread_count": ("GPU thread pools"),
    "use_unified_memory": ("CUDA unified memory"),
    "batchnorm_persistent": ("cuDNN batchnorm knob"),
    "autotune_threshold": ("cuDNN autotune"),
    "horovod_device": ("the SPMD data plane covers device pinning"),
    "mkl": ("MKL build knob"),
    "kmp_blocktime": ("MKL env var"),
    "kmp_affinity": ("MKL env var"),
    "kmp_settings": ("MKL env var"),
    "local_parameter_device": (
        "PS-style variable placement maps to sharded state on TPU "
        "(SURVEY 5.8); the mesh determines placement"),
    "num_inter_threads": (
        "host inter-op scheduling belongs to XLA (ref :209-214)"),
}


def report_noop_parity_flags(params) -> None:
  from kf_benchmarks_tpu import flags as flags_lib
  for name, why in _NOOP_PARITY_FLAGS.items():
    spec = flags_lib.param_specs.get(name)
    default = spec.default_value if spec is not None else None
    if getattr(params, name, default) != default:
      log_fn(f"Note: --{name} is accepted for reference-CLI parity but "
             f"has no effect on TPU: {why}")


# Machine-checkable probe-failure markers. bench.py's retry policy keys
# on these (timeout => never retry: the killed probe is the action that
# wedges the tunnel; no-TPU => permanent), so they are constants rather
# than free-form text that could drift apart.
PROBE_TIMEOUT_MARKER = "did not come up"
PROBE_NO_TPU_MARKER = "no TPU on this host"


def tpu_reachable(timeout: int | None = None):
  """Probe TPU backend liveness in a subprocess -> (ok, detail).

  A wedged device tunnel makes jax.devices() block forever in-process,
  so the probe runs out-of-process with a timeout. The default timeout
  (KF_TPU_PROBE_TIMEOUT, 600s) sits far above worst-case claim latency
  because killing a probe mid-claim is itself what wedges the tunnel --
  callers must treat a timed-out probe as non-retryable. A successful
  probe is cached in the environment (inherited by children), so
  bench.py's fallback check and setup()'s guard share one real probe
  per run.
  """
  if timeout is None:
    try:
      timeout = int(os.environ.get("KF_TPU_PROBE_TIMEOUT", "600"))
    except ValueError:
      timeout = 600
  if os.environ.get("KF_TPU_PROBE_RESULT") == "ok":
    return True, ""
  import subprocess
  import sys
  try:
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=timeout)
  except subprocess.TimeoutExpired:
    return False, (f"jax.devices() {PROBE_TIMEOUT_MARKER} within "
                   f"{timeout}s (wedged device tunnel?)")
  if probe.returncode != 0:
    return False, (f"device probe exited with code {probe.returncode}: "
                   f"{(probe.stderr or '').strip()[-500:]}")
  if "cpu" in probe.stdout:
    return False, f"only CPU devices present ({PROBE_NO_TPU_MARKER})"
  os.environ["KF_TPU_PROBE_RESULT"] = "ok"
  return True, ""


def setup(params):
  """Process-level setup (ref: benchmark_cnn.py:3356-3395).

  The reference sets cuDNN/MKL env vars and runs a dummy session; the TPU
  analogs are XLA flag plumbing and an eager device touch to trigger
  runtime init ahead of the timed region.
  """
  if getattr(params, "autotuned_config", None):
    # --autotuned_config: apply the tuned-table entry matching this
    # run's base fingerprint over the flag values, FIRST -- every
    # caller (cli.py, bench.py, kfrun workers) goes through setup, so
    # the params the rest of the process sees (and fingerprints) are
    # the applied ones. One provenance line either way
    # (analysis/autotune.py apply_tuned_config).
    from kf_benchmarks_tpu.analysis import autotune as autotune_lib
    global _applied_tuned_provenance
    params, _applied_tuned_provenance = autotune_lib.apply_tuned_config(
        params, log_fn=log_fn)
  if params.device == "cpu":
    # Explicit CPU request. Note: must go through jax.config AFTER import,
    # not the JAX_PLATFORMS env var -- this environment pins the env var
    # to the axon TPU plugin at interpreter start.
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if (params.num_devices > 1 and
        "xla_force_host_platform_device_count" not in xla_flags):
      # Provision virtual host devices for multi-replica CPU runs. Only
      # effective if the CPU backend has not been initialized yet.
      os.environ["XLA_FLAGS"] = (
          xla_flags + " --xla_force_host_platform_device_count="
          f"{params.num_devices}").strip()
    jax.config.update("jax_platforms", "cpu")
  # Platform pre-run hook (ref: platforms_util.initialize, called from
  # setup at benchmark_cnn.py:3356-3395). The cluster manager also goes
  # through the platform dispatch so vendor overrides take effect.
  # --coordinator_address/--num_processes/--process_index map onto the
  # KFCOORD_* env the coordination-service clients read (kfrun sets the
  # env directly; these flags cover hand-launched processes,
  # ref: kungfu-run env propagation, SURVEY 2.9).
  if params.coordinator_address and "KFCOORD_HOST" not in os.environ:
    host, _, port = params.coordinator_address.partition(":")
    os.environ["KFCOORD_HOST"] = host
    os.environ["KFCOORD_PORT"] = port or "0"
    os.environ["KFCOORD_WORLD"] = str(params.num_processes)
    os.environ.setdefault("KFCOORD_RANK_HINT", str(params.process_index))
  from kf_benchmarks_tpu.platforms import util as platforms_util
  platforms_util.initialize(params)
  platforms_util.get_cluster_manager(params)
  report_noop_parity_flags(params)
  multi_process = (
      len(params.worker_hosts or []) > 1 or
      (params.num_processes or 1) > 1 or
      int(os.environ.get("KFCOORD_WORLD") or 1) > 1)
  if params.device == "tpu" and not multi_process:
    # Fail loudly instead of hanging on a wedged device tunnel.
    # Single-process only: in a kfrun / multi-worker launch, N probe
    # subprocesses would contend with each other and the real workers
    # for the exclusively-held chips.
    ok, detail = tpu_reachable()
    if not ok:
      raise RuntimeError(
          f"TPU backend unreachable: {detail}. Re-run with --device=cpu, "
          "or retry once the TPU is reachable.")
  jax.devices()  # force backend init (ref dummy session :3383-3393)
  return params


class BenchmarkCNN:
  """Benchmark driver (ref: benchmark_cnn.py:1230).

  Args mirror the reference: Params plus optional dataset/model injection
  (tests inject fake datasets/models the same way,
  ref: benchmark_cnn.py:1230-1233).
  """

  def __init__(self, params, dataset=None, model=None):
    from kf_benchmarks_tpu import params as params_lib
    params_lib.validate_params(params)
    validation.validate_cross_flags(params)
    # Tuned-config provenance for the stats/run record: reuse what
    # setup() just applied (matched by table path -- no second disk
    # read, and the record cannot disagree with the application); a
    # direct construction without setup falls back to the lookup,
    # done BEFORE the auto-resolutions below mutate params (the table
    # keys on the make_params-level config, analysis/autotune.py).
    # None when --autotuned_config is unset.
    self._tuned_provenance = None
    if getattr(params, "autotuned_config", None):
      prov = _applied_tuned_provenance
      if prov and prov.get("path") == params.autotuned_config:
        self._tuned_provenance = dict(prov)
      else:
        from kf_benchmarks_tpu.analysis import autotune as autotune_lib
        self._tuned_provenance = autotune_lib.tuned_provenance(params)
    if params.adaptive_batch_size and not params.track_grad_noise_scale:
      # The adaptive-batch policy keys on the measured noise scale.
      params = params._replace(track_grad_noise_scale=True)
    self.params = params
    # Optional resize driver (tests inject a ScheduledController; the
    # elastic flag wires the coordination service via KFCOORD_* env).
    self.elastic_controller = None
    # --use_synthetic_gpu_images forces synthetic inputs even when a
    # data_dir is set (ref: the flag gates use_synthetic_gpu_inputs).
    data_dir = None if params.use_synthetic_gpu_images else params.data_dir
    self.dataset = dataset or datasets.create_dataset(
        data_dir, params.data_name)
    self.model = model or model_config.get_model_config(
        params.model, self.dataset.name, params)
    if params.batch_size:
      self.model.set_batch_size(params.batch_size)
    self.batch_size_per_device = self.model.get_batch_size()
    gacc = int(params.num_grad_accum or 1)
    if gacc > 1 and self.batch_size_per_device % gacc:
      # validation.py checked an EXPLICIT --batch_size; a model-default
      # batch resolves here, so the divisibility contract is re-checked
      # against the resolved value.
      raise validation.ParamError(
          f"--num_grad_accum={gacc} must divide the per-device batch "
          f"size {self.batch_size_per_device} (model default for "
          f"{self.model.get_name()}); pass a divisible --batch_size")
    self.num_devices = params.num_devices
    # Multi-process (multi-host) runs multiply further (ref num_workers).
    self.num_workers = jax.process_count()
    # Mesh family: --mesh_shape / --shard_optimizer_state select the
    # named 2-D ('batch', 'model') mesh (sharded alone resolves Nx1);
    # everything else keeps the 1-D replica mesh. The GLOBAL batch
    # follows the DATA-parallel width only: model-axis peers re-compute
    # the same batch shard (train_step.py), so a 4x2 mesh feeds the
    # global batch of 4 replicas, not 8.
    if params.mesh_shape or params.shard_optimizer_state:
      nb, nm = (validation.parse_mesh_shape(params.mesh_shape)
                if params.mesh_shape else (self.num_devices, 1))
      self.mesh = mesh_lib.build_mesh_2d(nb, nm, params.device)
    else:
      self.mesh = mesh_lib.build_mesh(self.num_devices, params.device)
    self.num_data_replicas = mesh_lib.num_data_replicas(self.mesh)
    self.batch_size = self.batch_size_per_device * self.num_data_replicas
    self.strategy = strategies.get_strategy(params)
    # --shard_optimizer_state: checkpoints must save/restore the FULL
    # stacked shard rows, not the v0 slice (checkpoint.py).
    self._sharded_state = bool(getattr(self.strategy, "sharded_state",
                                       False))
    # --shard_params (full FSDP): params join the shard-stack layout --
    # same checkpoint rule, plus the params_layout marker so cross-
    # layout restores fail loudly (checkpoint.py).
    self._sharded_params = bool(getattr(params, "shard_params", False))
    # Training-health telemetry (telemetry.py): resolve the auto
    # default (--health_stats unset) against the strategy's reduction
    # semantics ONCE, so the step builder and the host-side recorder/
    # watchdog see the same concrete decision.
    hs, self._health_note = telemetry_lib.resolve_health_stats(
        params, self.strategy)
    if bool(params.health_stats) != hs or params.health_stats is None:
      params = params._replace(health_stats=hs)
      self.params = params
    self._telemetry = None
    # Run-trace session default: the no-op sink until _benchmark_train
    # installs the real one (tracing.py) -- direct _train_loop callers
    # (tests) trace nothing rather than crash.
    self._trace = tracing_lib.NULL_TRACE
    self._compiled_programs = set()
    # Deterministic fault injection (--fault_schedule, faults.py): the
    # named faults fire at dispatch boundaries; the dispatch planner
    # treats their steps as events so a chunk never crosses one.
    self._faults = faults_lib.FaultInjector.from_params(
        params, rank=cluster_lib.process_rank(), log_fn=log_fn)
    self.num_batches = self._get_num_batches()
    # Device-resident multi-step dispatch (--steps_per_dispatch=K): K
    # train steps per compiled program (train_step.py train_chunk), so
    # dispatch + tunnel RTT amortize K-fold. A run shorter than one
    # chunk scans the whole run in a single dispatch. Validation has
    # already rejected K > 1 with --eval/--forward_only.
    spd = max(1, params.steps_per_dispatch or 1)
    if spd > self.num_batches:
      spd = max(1, self.num_batches)
    if spd != (params.steps_per_dispatch or 1):
      params = params._replace(steps_per_dispatch=spd)
      self.params = params
    self.steps_per_dispatch = spd
    self.eval_step_set = compute_eval_step_set(
        params, self.batch_size * max(self.num_workers, 1),
        self.dataset.num_examples_per_epoch("train"), self.num_batches)
    # Default matches the reference: max(10, autotune warmup) with no
    # autotune phase on TPU (ref: benchmark_cnn.py:1257).
    self.num_warmup_batches = (
        params.num_warmup_batches if params.num_warmup_batches is not None
        else 10)
    self.display_every = params.display_every
    dtype = jnp.float32
    if params.use_fp16:
      # bfloat16 on TPU; float16 kept for parity when explicitly requested
      # through fp16_vars on non-TPU backends.
      dtype = jnp.bfloat16 if params.device == "tpu" else jnp.float16
    self.compute_dtype = dtype
    self.param_dtype = dtype if params.fp16_vars else jnp.float32

  def _get_num_batches(self) -> int:
    p = self.params
    if p.num_batches is not None:
      return p.num_batches
    if p.num_epochs is not None:
      per_epoch = self.dataset.num_examples_per_epoch("train")
      global_batch = self.batch_size * max(self.num_workers, 1)
      return int(np.ceil(p.num_epochs * per_epoch / global_batch))
    return 100  # reference default (ref: benchmark_cnn.py:137-139)

  def _num_eval_batches_from_epochs(self):
    """--num_eval_epochs -> batches over the validation set (ref:
    get_num_batches_and_epochs applied to eval params,
    benchmark_cnn.py:1429-1446)."""
    p = self.params
    if p.num_eval_epochs is None:
      return None
    per_epoch = self.dataset.num_examples_per_epoch("validation")
    global_batch = self.batch_size * max(self.num_workers, 1)
    return int(np.ceil(p.num_eval_epochs * per_epoch / global_batch))

  # -- info ----------------------------------------------------------------

  def print_info(self):
    """Run-config banner (ref: benchmark_cnn.py:1633-1692)."""
    p = self.params
    mode = "forward-only" if p.forward_only else (
        "evaluation" if p.eval else "training")
    log_fn("TensorFlow:   n/a (kf_benchmarks_tpu, JAX %s)" % jax.__version__)
    log_fn("Model:       %s" % self.model.get_name())
    log_fn("Dataset:     %s (%s)" % (
        self.dataset.name,
        "synthetic" if self.dataset.use_synthetic_gpu_inputs() else
        self.dataset.data_dir))
    log_fn("Mode:        %s" % mode)
    log_fn("Batch size:  %d global" % (
        self.batch_size * max(self.num_workers, 1)))
    log_fn("             %d per device" % self.batch_size_per_device)
    log_fn("Num batches: %d" % self.num_batches)
    log_fn("Num devices: %d (%s)" % (self.num_devices, p.device))
    if mesh_lib.BATCH_AXIS in self.mesh.axis_names:
      log_fn("Mesh:        %dx%d (batch x model)%s%s" % (
          self.mesh.shape[mesh_lib.BATCH_AXIS],
          self.mesh.shape[mesh_lib.MODEL_AXIS],
          ", sharded optimizer state" if p.shard_optimizer_state else "",
          ", sharded params (FSDP)" if getattr(p, "shard_params", False)
          else ""))
    log_fn("Data format: %s" % p.data_format)
    log_fn("Precision:   %s (params: %s)" % (
        jnp.dtype(self.compute_dtype).name,
        jnp.dtype(self.param_dtype).name))
    log_fn("Optimizer:   %s" % p.optimizer)
    log_fn("Variables:   %s%s" % (
        p.variable_update,
        f" ({p.kungfu_option})" if p.variable_update == "kungfu" else ""))
    log_fn("==========")

  # -- build ---------------------------------------------------------------

  def _build(self):
    p = self.params
    nclass = self.dataset.num_classes
    module = self.model.make_module(
        nclass=nclass, phase_train=not (p.eval or p.forward_only),
        data_format=p.data_format, dtype=self.compute_dtype,
        param_dtype=self.param_dtype)
    eval_module = self.model.make_module(
        nclass=nclass, phase_train=False, data_format=p.data_format,
        dtype=self.compute_dtype, param_dtype=self.param_dtype)
    lr_fn = learning_rate.make_learning_rate_fn(
        p, self.model,
        self.batch_size_per_device * (
            # Effective batch = per-device x DATA-parallel width (model-
            # axis peers add no examples); == num_devices on 1-D meshes.
            self.num_data_replicas if self.strategy.cross_replica else 1),
        self.dataset.num_examples_per_epoch("train"), self.num_workers)
    tx = optimizers.get_optimizer(p, lr_fn)
    self._lr_fn = lr_fn
    return train_step_lib.make_step_fns(
        self.model, module, eval_module, self.strategy, tx, lr_fn, p,
        self.mesh, compute_dtype=self.compute_dtype,
        # The RESOLVED step count (--num_batches default / --num_epochs
        # derivation, _get_num_batches) -- params.num_batches may be None.
        total_train_steps=self.num_batches)

  def _synthetic_global_batch(self, rng):
    """Device-resident synthetic inputs, sharded over replicas
    (ref: "minor hack to avoid H2D copy", benchmark_cnn.py:3008-3011)."""
    nclass = self.dataset.num_classes
    # Build the global batch with the model's per-device shape scaled up.
    self.model.set_batch_size(self.batch_size_per_device)
    images, labels = self.model.get_synthetic_inputs(rng, nclass)
    # Feed floating inputs at the compute dtype: the first model op casts
    # anyway, and a bf16-resident batch halves the HBM read of the largest
    # input tensor every step.
    if jnp.issubdtype(images.dtype, jnp.floating):
      images = images.astype(self.compute_dtype)
    # Labels may be a pytree (e.g. SSD's (boxes, classes, num_matched)).
    # Tile covers THIS process's DATA replicas (model-axis peers read
    # the same shard); put_batch assembles the global array from
    # per-process shards under multi-process SPMD.
    tile = lambda x: jnp.tile(
        x, (self.num_data_replicas,) + (1,) * (x.ndim - 1))
    batch_sharding = mesh_lib.batch_sharding(self.mesh)
    return mesh_lib.put_batch(
        (tile(images), jax.tree.map(tile, labels)), batch_sharding)

  def _input_iterator(self, rng, subset: str = "train", chunk: int = 1):
    """Per-step input source.

    Synthetic (no data_dir): one device-resident batch reused every step
    (ref: benchmark_cnn.py:3008-3011). Real data: preprocessor host
    pipeline + double-buffered DeviceFeeder (the StagingArea/
    MultiDeviceIterator analog, ref: benchmark_cnn.py:2572-2600).
    Returns (next_fn, stop_fn).

    ``chunk`` > 1 stages --steps_per_dispatch batches per fetch: real
    data arrives as one (chunk, batch, ...) staged array; synthetic
    arrives with a leading axis of 1 (the scanned program reuses the
    resident batch, so no K-wide staging footprint exists at all).

    --packed_sequences: the seeded host-side packer (data/packing.py)
    is a REAL host pipeline even though no data_dir is set -- fresh
    variable-length documents are drawn and bin-packed per batch, so
    the stream runs through the DeviceFeeder like record data and the
    feed instrumentation measures whether packing work hides behind
    the step (feed_stall_fraction).
    """
    from kf_benchmarks_tpu.data import device_feed
    p = self.params
    self._feeder = None
    self._packed_stream = None
    if getattr(p, "packed_sequences", False):
      from kf_benchmarks_tpu.data import packing as packing_lib
      # Seeded from the run's data rng (+ the elastic incarnation fold
      # _open_input applied): same seed -> same document stream.
      seed = int(np.asarray(
          jax.random.randint(rng, (), 0, 2**31 - 1, jnp.int32)))
      stream = packing_lib.PackedBatchStream(
          seq_len=self.model.get_input_shapes(subset)[0][-1],
          batch_size=self.batch_size, vocab=self._packed_vocab(),
          seed=seed)
      self._packed_stream = stream
      return self._make_feeder(stream, chunk)
    if self.dataset.use_synthetic_gpu_inputs():
      batch = self._synthetic_global_batch(rng)
      if chunk > 1:
        chunk_sharding = mesh_lib.chunk_batch_sharding(self.mesh)
        batch = jax.tree.map(
            lambda x: jax.device_put(x[None], chunk_sharding), batch)
      return (lambda: batch), (lambda: None)
    pre = self.dataset.get_input_preprocessor(p.input_preprocessor)
    if isinstance(pre, type):
      shape = self._model_image_shape()
      pre = pre(
          batch_size=self.batch_size,
          output_shape=shape,
          train=(subset == "train") and not (p.eval or p.forward_only),
          distortions=bool(p.distortions),
          resize_method=p.resize_method,
          # The incarnation term reshuffles after each elastic reshape so
          # reopened streams do not replay the dataset's leading examples.
          seed=((p.tf_random_seed or 301) + kungfu.current_rank() +
                7919 * getattr(self, "_input_incarnation", 0)),
          shift_ratio=(kungfu.current_rank() /
                       max(kungfu.current_cluster_size(), 1)),
          # Thread-count precedence: the dataset-private pool flag, then
          # the host intra-op pool size, then the parse-parallelism
          # default (ref :203-208, :248-253, map parallelism).
          num_threads=(p.datasets_num_private_threads or
                       p.num_intra_threads or
                       p.input_preprocessing_parallelism or 8),
          repeat_cached_sample=bool(p.datasets_repeat_cached_sample),
          use_caching=bool(p.datasets_use_caching))
      if hasattr(pre, "max_label_length"):
        # Speech: label padding must match the model's static label slot.
        pre.max_label_length = getattr(self.model, "max_label_length",
                                       pre.max_label_length)
    host_iter = pre.minibatches(self.dataset, subset)
    if self.compute_dtype != jnp.float32:
      host_iter = self._cast_images(host_iter)
    return self._make_feeder(host_iter, chunk)

  def _make_feeder(self, host_iter, chunk: int):
    """The ONE DeviceFeeder recipe (sharding pick, prefetch depth,
    stats bookkeeping) shared by the record-data and packed-stream
    input paths, so a prefetch/sharding policy change cannot apply to
    one and silently diverge the other."""
    from kf_benchmarks_tpu.data import device_feed
    feeder = device_feed.DeviceFeeder(
        host_iter,
        mesh_lib.chunk_batch_sharding(self.mesh) if chunk > 1
        else mesh_lib.batch_sharding(self.mesh),
        prefetch=max(feeder_prefetch(self.params), chunk), chunk=chunk)
    self._feeder = feeder
    it = iter(feeder)
    return (lambda: next(it)), feeder.stop

  def _packed_vocab(self) -> int:
    from kf_benchmarks_tpu.models import transformer_lm as lm
    return lm.VOCAB

  def _cast_images(self, host_iter):
    """Cast float32 host batches to the compute dtype before the H2D copy
    (halves the transfer; the model's first op performs this cast
    otherwise)."""
    np_dtype = np.dtype(self.compute_dtype)
    try:
      for images, labels in host_iter:
        if images.dtype == np.float32:
          images = images.astype(np_dtype)
        yield images, labels
    finally:
      close = getattr(host_iter, "close", None)
      if close is not None:
        close()

  def _model_image_shape(self):
    """(H, W, C) the model consumes, from its input spec."""
    self.model.set_batch_size(self.batch_size_per_device)
    image_shape = self.model.get_input_shapes("train")[0]
    return tuple(image_shape[1:])

  # -- run -----------------------------------------------------------------

  def run(self) -> Dict[str, Any]:
    """(ref: benchmark_cnn.py:1726-1755)"""
    self.print_info()
    if self.params.eval:
      return self._run_eval()
    if self.params.forward_only and self.params.aot_load_path:
      return self._benchmark_aot_serving()
    return self._benchmark_train()

  def _benchmark_aot_serving(self) -> Dict[str, Any]:
    """Serving benchmark on a frozen AOT artifact: deserialize the
    exported forward program (weights baked in as constants) in THIS
    process and time it -- the analog of benchmarking the
    TensorRT-converted graph (ref: _preprocess_graph freeze+convert,
    benchmark_cnn.py:2405-2525, timed by the forward-only loop)."""
    from kf_benchmarks_tpu import aot
    p = self.params
    shape = (self.batch_size_per_device,) + self._model_image_shape()
    # Signature-validated load (aot.py): a batch/shape mismatch fails
    # HERE with the exported signature and the available bucket list,
    # not as an XLA arity error mid-loop; the serving-mode diff
    # (quantize sidecar vs this process's --trt_mode) fails a bf16
    # engine pointed at an INT8 export before deserialization.
    trt_mode = (p.trt_mode or "").upper()
    serving_fn = aot.load_forward(p.aot_load_path,
                                  expect_batch=self.batch_size_per_device,
                                  expect_shape=shape,
                                  expect_quantize="int8" if
                                  trt_mode == "INT8" else None)
    log_fn(f"Loaded frozen forward program from {p.aot_load_path}")
    images = jax.random.uniform(jax.random.PRNGKey(p.tf_random_seed or 0),
                                shape, jnp.float32)
    sync.drain(images)  # block_until_ready lies on this backend
    log_fn("Running warm up")
    t0 = time.time()
    for _ in range(max(self.num_warmup_batches, 1)):
      out = serving_fn(images)
    # The timed loop must start with an empty device queue
    # (utils/sync.py on why block_until_ready is not enough).
    sync.drain(out)
    log_fn("Warmup (load + %d steps): %.1f s" %
           (max(self.num_warmup_batches, 1), time.time() - t0))
    log_fn("Step\tImg/sec\t" + p.loss_type_to_report)
    step_times = []
    last_display_len = 0
    pipe = pipeline_lib.MetricsPipeline(lag=2)
    pipe.reset_clock()

    def _handle(done):
      nonlocal last_display_len
      step_times.append(done.interval)
      i1 = done.index
      if i1 % self.display_every == 0 or i1 == self.num_batches:
        window = step_times[last_display_len:]
        # The artifact returns logits only; the loss column reports the
        # mean logit as a liveness value (no labels in serving).
        log_fn(log_util.format_step_line(
            i1, self.batch_size_per_device, window,
            float(done.metrics["mean_logit"])))
        last_display_len = len(step_times)

    loop_start = time.time()
    for i in range(self.num_batches):
      out = serving_fn(images)
      for done in pipe.push(i + 1, {"mean_logit": jnp.mean(out)}):
        _handle(done)
    for done in pipe.flush():
      _handle(done)
    total_time = time.time() - loop_start
    images_per_sec = (self.num_batches * self.batch_size_per_device /
                      max(total_time, 1e-9))
    log_fn("-" * 64)
    log_fn(log_util.format_total_line(images_per_sec))
    log_fn("-" * 64)
    return {
        "num_workers": 1,
        "num_steps": self.num_batches,
        "average_wall_time": total_time / max(self.num_batches, 1),
        "images_per_sec": images_per_sec,
        "aot_load_path": p.aot_load_path,
    }

  def _benchmark_train(self) -> Dict[str, Any]:
    p = self.params
    if self._health_note:
      log_fn(self._health_note)
    # Run-trace session (tracing.py): ONE run id shared with the flight
    # recorder so a post-mortem dump lays over the timeline. Always
    # created -- the latency percentiles and compile ledger ride the
    # stats/bench JSON even without --trace_events_file (span retention
    # and the file export engage only with the flag). Under kfrun the
    # world size comes from the launcher env (jax.process_count() is 1
    # per CPU worker there), so rank files and the rank-0 merge cover
    # every worker of the job.
    rank = cluster_lib.process_rank()
    world = (int(os.environ.get("KFCOORD_WORLD") or 0) or
             max(self.num_workers, 1))
    run_id = tracing_lib.resolve_run_id()
    self._trace = tracing_lib.RunTrace(
        path=p.trace_events_file, rank=rank, num_ranks=world,
        run_id=run_id, chrome_format=bool(p.use_chrome_trace_format),
        log_fn=log_fn)
    tracing_lib.activate(self._trace)
    # Metric-registry session (metrics.py): always created -- the
    # registry is the single render source for run stats and the run
    # record -- with the scrape endpoint bound only when --metrics_port
    # asks for it (per-rank offset under kfrun: rank r serves
    # port + r). Host-side only, like the trace session: the metrics-on
    # step program is structurally identical to the metrics-off golden
    # (analysis/audit.rule_metrics_twin).
    self._registry = metrics_lib.MetricRegistry()
    metrics_lib.activate(self._registry)
    self._registry.set("run_id", run_id)
    self._metrics_server = None
    if p.metrics_port:
      port = metrics_lib.resolve_port(p.metrics_port, rank)
      try:
        self._metrics_server = metrics_lib.MetricsServer(
            self._registry, port, healthz_fn=self._healthz_payload)
        log_fn("metrics endpoint: http://127.0.0.1:%d/metrics"
               % self._metrics_server.port)
      except (OSError, OverflowError) as e:
        # A taken port must not cost the run: train without the scrape
        # surface, loudly. (OverflowError: a per-rank offset can push
        # the resolved port past 65535, which bind() rejects with a
        # non-OSError.)
        log_fn(f"metrics endpoint: bind to port {port} failed ({e}); "
               "serving disabled for this run")
    self._compiled_programs = set()
    # Persistent XLA compilation cache (ROADMAP item 3 groundwork),
    # configured BEFORE the first trace: a program shape compiles once
    # ever -- later runs (and every future tunnel window) deserialize
    # the cached executable, so the 30-min first-compile hazard
    # (CLAUDE.md) is paid once per shape. --compilation_cache_dir, or
    # <train_dir>/xla_cache when a train_dir exists; explicitly
    # cleared otherwise (the jax config is process-global, and a stale
    # dir from an earlier in-process run must not leak in).
    cache_dir = p.compilation_cache_dir or (
        os.path.join(p.train_dir, "xla_cache") if p.train_dir else None)
    self._compile_cache_dir = cache_dir
    _configure_compile_cache(cache_dir)
    if cache_dir:
      log_fn(f"XLA compilation cache: {cache_dir}")
    # Prior compile-ledger keys (train_dir/compile_ledger.json,
    # tracing.py write_ledger): a fingerprint seen by an earlier run
    # of this train_dir AND a live cache dir means this run's compile
    # episode is served from the persistent cache -- the ledger row's
    # cache_hit field makes the once-per-shape payoff visible.
    self._prior_ledger_keys = set()
    # ... and only when the cache dir actually HOLDS entries: jax
    # exposes no public per-compile hit signal, so cache_hit is the
    # conjunction "shape ledgered by an earlier run AND a warm
    # persistent cache exists" -- a deleted/empty cache dir (or a
    # prior run whose compiles all fell under jax's
    # persistent_cache_min_compile_time threshold and were never
    # serialized) must not read as a hit while the compile is paid in
    # full again.
    self._compile_cache_warm = False
    if cache_dir:
      try:
        self._compile_cache_warm = any(os.scandir(cache_dir))
      except OSError:
        self._compile_cache_warm = False
    if self._compile_cache_warm and p.train_dir:
      # The ledger query API (tracing.py read_ledger) -- the same read
      # the autotuner's warm pass cross-references, so a warmed
      # train_dir reads as prior history here and the warmed shapes
      # report cache_hit below.
      self._prior_ledger_keys = tracing_lib.ledger_keys(
          tracing_lib.read_ledger(p.train_dir))
    # Everything from the build on runs under the try: a raise anywhere
    # (compile error, bad data_dir, sink failure) must still deactivate
    # the module-global trace session (a leaked active session would
    # swallow later emitters in this process) and export what was
    # captured.
    try:
      init_state, train_step, eval_step, broadcast_init, train_chunk = \
          self._build()
      rng = jax.random.PRNGKey(p.tf_random_seed or 0)
      data_rng, init_rng = jax.random.split(rng)
      self._data_rng = data_rng
      next_batch = self._open_input(data_rng, "train")
      # Flight recorder + stall watchdog for the whole build->train span
      # (the watchdog's patient first-compile regime must cover the init
      # and warmup compiles, not just the timed loop). None when the
      # resolved --health_stats is off. Same launcher-derived world as
      # the trace session: under kfrun jax.process_count() is 1 per CPU
      # worker, and num_ranks=1 would silently disable the rank-0
      # flight-recorder merge at exit.
      self._telemetry = telemetry_lib.TelemetrySession.create(
          p, rank=rank, log_fn=log_fn, num_ranks=world, run_id=run_id)
      return self._train_loop(init_state, train_step, eval_step,
                              broadcast_init, init_rng, next_batch,
                              train_chunk)
    finally:
      if self._telemetry is not None:
        self._telemetry.close()
        self._telemetry = None
      stop_input = getattr(self, "_input_stop", None)
      if stop_input is not None:
        stop_input()
      # Endpoint down, then registry session: a scrape arriving during
      # teardown reads the final published snapshot, never a
      # half-closed server. Deactivate AFTER the input stop (the feeder
      # worker publishes feed lanes until it joins), then export: the
      # per-rank span file + the rank-0 multi-rank merge (tracing.py).
      if self._metrics_server is not None:
        self._metrics_server.close()
        self._metrics_server = None
      metrics_lib.deactivate()
      tracing_lib.deactivate()
      try:
        self._trace.export()
      except Exception as e:  # an export failure must not eat the run
        log_fn(f"trace export failed (non-fatal): {e!r}")

  def _healthz_payload(self) -> Dict[str, Any]:
    """The /healthz body (metrics.MetricsServer calls this from its
    serving thread): watchdog + flight-recorder state when a telemetry
    session is live, a bare liveness ack otherwise. Reads only."""
    payload: Dict[str, Any] = {"status": "ok",
                               "run_id": self._trace.run_id}
    tele = getattr(self, "_telemetry", None)
    if tele is not None:
      payload.update(tele.healthz())
    return payload

  def _open_input(self, rng, subset: str, bump: bool = True):
    """Open a fresh input stream, closing the previous one (elastic
    reshapes swap streams mid-run). ``bump=False`` reopens at the
    CURRENT incarnation (the checkpoint-resume path, which sets the
    incarnation from the snapshot rather than advancing it)."""
    stop_prev = getattr(self, "_input_stop", None)
    if stop_prev is not None:
      stop_prev()
      if bump:
        self._input_incarnation = getattr(self, "_input_incarnation",
                                          0) + 1
    incarnation = getattr(self, "_input_incarnation", 0)
    if incarnation:
      # Folded only for incarnation >= 1 (a plain run's stream is the
      # seed rng exactly, keeping every pre-elastic pin); keyed on the
      # COUNT rather than the fold history so a run resuming after a
      # reshape can reproduce stream k exactly by presetting
      # _input_incarnation -- the bit-identity A/B of the elastic
      # rescale tests depends on it.
      rng = jax.random.fold_in(rng, incarnation)
    # Training streams stage --steps_per_dispatch batches per fetch
    # (already 1 in eval/forward-only modes, validation.py).
    chunk = self.steps_per_dispatch if subset == "train" else 1
    next_batch, stop = self._input_iterator(rng, subset, chunk=chunk)
    self._input_stop = stop
    return next_batch

  def _reshape_topology(self, state, num_devices: int,
                        batch_per_device: int, init_rng,
                        steps_done: int = 0, examples_done: int = 0):
    """Elastic rescale: rebuild mesh + jitted steps for a new topology and
    carry training state across via the checkpoint snapshot/restore path
    (SURVEY 7.4: XLA programs are topology-fixed, so resize == re-jit +
    state re-shard; the KungFu resize_cluster analog).
    """
    # State-dict form, the same shape restore_state consumes when reading
    # a checkpoint file (namedtuple opt states become plain dicts).
    # Under --shard_optimizer_state the snapshot carries the FULL (n, k)
    # shard stack, which restore_state re-slices onto the new topology
    # (checkpoint.py _reshard -- the cross-mesh rescale).
    from flax import serialization
    sharded = self._sharded_state
    snapshot = serialization.to_state_dict(
        checkpoint.savable_state(state, sharded_opt_state=sharded,
                                 sharded_params=self._sharded_params))
    self.num_devices = num_devices
    params_new = self.params._replace(num_devices=num_devices)
    self.batch_size_per_device = batch_per_device
    self.model.set_batch_size(batch_per_device)
    if mesh_lib.BATCH_AXIS in self.mesh.axis_names:
      # 2-D family: the model-axis width survives the resize (the poll
      # path rejected targets it does not divide); the batch axis takes
      # the rest, so the global batch follows the DATA width only.
      nm = int(self.mesh.shape[mesh_lib.MODEL_AXIS])
      self.mesh = mesh_lib.build_mesh_2d(num_devices // nm, nm,
                                         params_new.device)
      if params_new.mesh_shape:
        params_new = params_new._replace(
            mesh_shape=f"{num_devices // nm}x{nm}")
    else:
      self.mesh = mesh_lib.build_mesh(num_devices, params_new.device)
    self.params = params_new
    self.num_data_replicas = mesh_lib.num_data_replicas(self.mesh)
    self.batch_size = batch_per_device * self.num_data_replicas
    # Rebuild the strategy: its reducer may capture topology-derived
    # constants sized to the OLD axis (hierarchical_copy groups,
    # planner replica hints), which would mis-permute on the new mesh.
    self.strategy = strategies.get_strategy(self.params)
    # Epoch-based eval schedules are example counts; re-anchor their
    # step mapping to the new global batch size.
    self.eval_step_set = compute_eval_step_set(
        self.params, self.batch_size * max(self.num_workers, 1),
        self.dataset.num_examples_per_epoch("train"), self.num_batches,
        start_step=steps_done, start_examples=examples_done)
    init_state, train_step, eval_step, broadcast_init, train_chunk = \
        self._build()
    # The rebuilt programs recompile at the new topology: their first
    # dispatches are fresh compile-ledger episodes (the config
    # fingerprint differs -- num_devices/mesh_shape changed).
    self._compiled_programs = set()
    next_batch = self._open_input(self._data_rng, "train")
    shape = (batch_per_device,) + self._model_image_shape()
    new_state = init_state(init_rng, jnp.zeros(shape, jnp.float32))
    new_state = checkpoint.restore_state(
        new_state, snapshot, sharded_opt_state=sharded,
        sharded_params=self._sharded_params)
    new_state = new_state.replace(
        params=broadcast_init(new_state.params))
    self._verify_resumed_state(new_state)
    return new_state, train_step, eval_step, next_batch, train_chunk

  def _save_checkpoint(self, state, incarnation_bump: int = 0) -> None:
    """The ONE checkpoint-write path: layout flag + the input-stream
    incarnation a resumed run must reopen at. ``incarnation_bump=1`` at
    the resize seam: the snapshot's resume point is the POST-resize
    stream (the rebuild bumps the incarnation right after this save).
    Also the ONE place checkpoint-save wall time enters the run trace
    (span + p50/p90/p99 sample, tracing.py)."""
    trace = tracing_lib.active()
    t0 = trace.now()
    checkpoint.save_checkpoint(
        self.params.train_dir, state, self.params.max_ckpts_to_keep,
        sharded_opt_state=self._sharded_state,
        input_incarnation=getattr(self, "_input_incarnation", 0)
        + incarnation_bump,
        sharded_params=self._sharded_params)
    dur = trace.now() - t0
    trace.add_span("checkpoint", "save", t0, dur,
                   {"incarnation_bump": incarnation_bump})
    trace.add_sample("checkpoint_save", dur)
    metrics_lib.active().observe("checkpoint_save_s", dur)

  def _verify_resumed_state(self, state) -> None:
    """Resume-time contract re-verification (analysis/audit.py): every
    state rebuilt onto a new (or restored) mesh must structurally match
    it BEFORE training continues -- a wrong-topology state would train
    under broadcast semantics and corrupt the run long after the seam.
    The traced-program half of the same contract is the
    ``sharded_rescale`` golden (run_tests.py --audit)."""
    from kf_benchmarks_tpu.analysis import audit as audit_lib
    problems = audit_lib.check_resumed_state(state, self.mesh,
                                             self._sharded_state)
    if problems:
      raise RuntimeError(
          "resume contract violated on the rebuilt mesh: "
          + "; ".join(problems))

  def _train_loop(self, init_state, train_step, eval_step, broadcast_init,
                  init_rng, next_batch, train_chunk=None) -> Dict[str, Any]:
    p = self.params
    tele = getattr(self, "_telemetry", None)
    K = self.steps_per_dispatch
    chunked = K > 1 and train_chunk is not None
    # "synthetic" here means the RESIDENT single-batch feed (reused
    # every step, staged once); a --packed_sequences run has no
    # data_dir but streams fresh host-packed batches through the
    # DeviceFeeder, so it takes the real-data cursor/chunk paths.
    synthetic = (self.dataset.use_synthetic_gpu_inputs() and
                 not getattr(p, "packed_sequences", False))
    images, labels = next_batch()

    def _step_slice(ims, lbs, j: int = 0):
      """One per-step batch out of a staged chunk (identity when
      unchunked). The synthetic resident chunk has a single slot."""
      if not chunked:
        return ims, lbs
      jj = 0 if synthetic else min(j, ims.shape[0] - 1)
      return ims[jj], jax.tree.map(lambda x: x[jj], lbs)

    single_images, _ = _step_slice(images, labels)
    sample = jax.ShapeDtypeStruct(
        (self.batch_size_per_device,) + tuple(single_images.shape[1:]),
        single_images.dtype)
    replicated = mesh_lib.replicated_sharding(self.mesh)
    log_fn("Generating training model")
    t0 = time.time()
    # init_state is already jitted with explicit state shardings
    # (train_step.make_step_fns).
    state = init_state(init_rng, jnp.zeros(sample.shape, sample.dtype))
    # Resume from the newest checkpoint if the train_dir has one; the run
    # then executes num_batches MORE steps from the restored global step
    # (ref: Supervisor auto-restore, benchmark_cnn.py:2122-2157).
    resumed = False
    if p.train_dir:
      t_restore = self._trace.now()
      try:
        # Parse-once resolve that skips torn/corrupt files with a
        # logged warning (checkpoint.load_latest_checkpoint).
        snapshot, path, ckpt_step = checkpoint.load_latest_checkpoint(
            p.train_dir)
        state = checkpoint.restore_state(
            state, snapshot, sharded_opt_state=self._sharded_state,
            sharded_params=self._sharded_params)
        # Cross-topology resumes (a sharded checkpoint written at a
        # different mesh re-slices in restore_state) re-verify the
        # structural contract exactly like an in-run rescale.
        self._verify_resumed_state(state)
        # Reopen the input stream at the snapshot's incarnation: a
        # rejoin after an elastic reshape must continue the POST-resize
        # stream, not silently reset to stream 0.
        snap_inc = int(snapshot.get("input_incarnation", 0) or 0)
        if snap_inc != getattr(self, "_input_incarnation", 0):
          self._input_incarnation = snap_inc
          next_batch = self._open_input(self._data_rng, "train",
                                        bump=False)
          images, labels = next_batch()
          log_fn(f"Resumed input stream at incarnation {snap_inc}")
        log_fn(f"Restored checkpoint at global step {ckpt_step}")
        self._trace.add_span(
            "checkpoint", "restore", t_restore,
            self._trace.now() - t_restore, {"global_step": ckpt_step})
        resumed = True
      except checkpoint.CheckpointNotFoundException:
        pass
    # Backbone warm-start before training (ref: benchmark_cnn.py:2204-2205
    # load_backbone_model at session start). Skipped on resume: the
    # resumed checkpoint's backbone is further-trained than the
    # warm-start values, which must not overwrite it mid-trajectory.
    if p.backbone_model_path and not resumed:
      state, n_restored = checkpoint.restore_backbone(
          state, p.backbone_model_path)
      if not n_restored:
        raise ValueError(
            f"--backbone_model_path={p.backbone_model_path} matched no "
            "variables of this model (wrong checkpoint?)")
      log_fn(f"Loaded {n_restored} backbone tensors from "
             f"{p.backbone_model_path}")
    if int(p.num_grad_accum or 1) > 1 and jax.tree.leaves(
        state.batch_stats):
      # Microbatched BN is standard Megatron-style semantics, but it is
      # a semantics CHANGE, not a pure memory lever: each microbatch
      # normalizes over batch/M samples and the running-stats EMA
      # advances M times per step. Losses/accuracy are NOT expected to
      # match the M=1 run for batch-norm models -- say so up front
      # rather than letting an operator chase a phantom regression.
      log_fn(f"Note: --num_grad_accum={p.num_grad_accum} with a "
             "batch-norm model: BN statistics are per-microbatch "
             f"(batch/{p.num_grad_accum}) and running stats update "
             f"{p.num_grad_accum}x per step; not numerically "
             "equivalent to the monolithic step (BN-free models are)")
    # Replica-0 broadcast at start (ref: benchmark_cnn.py:2094-2100).
    state = state.replace(params=broadcast_init(state.params))
    # Resolve the broadcast so the reported initialization time covers
    # the real device work (utils/sync.py on why block_until_ready is
    # not enough).
    sync.drain(state.params)
    log_fn("Initialization: %.1f s" % (time.time() - t0))

    def make_run_step(train_step, eval_step):
      if p.forward_only:
        # Forward-only benchmarks inference speed: no gradients, no
        # optimizer, eval-phase module (ref: benchmark_cnn.py:124-126).
        def run_step(state, images, labels):
          return state, eval_step(state, images, labels)
        return run_step
      return train_step

    run_step = make_run_step(train_step, eval_step)

    if p.forward_only and p.aot_save_path:
      # The freeze+TRT analog (ref: _preprocess_graph :2405-2525): export
      # the trained forward pass with weights folded in as constants.
      from kf_benchmarks_tpu import aot
      variables = {"params": jax.tree.map(lambda x: x[0], state.params)}
      bs = jax.tree.map(lambda x: x[0], state.batch_stats)
      if bs:
        variables["batch_stats"] = bs
      trt_mode = (p.trt_mode or "").upper()
      export_dtype = {"FP32": jnp.float32, "FP16": jnp.bfloat16,
                      "INT8": jnp.bfloat16}.get(trt_mode,
                                                self.compute_dtype)
      from kf_benchmarks_tpu.analysis import baseline as baseline_lib
      nbytes = aot.export_forward(
          self.model, variables, self.batch_size_per_device,
          p.aot_save_path, nclass=self.dataset.num_classes,
          dtype=export_dtype, quantize=trt_mode == "INT8",
          # Exporting run's program identity, recorded in the signature
          # sidecar (aot.py): a serving process can tie the artifact
          # back to the config that froze it.
          fingerprint=baseline_lib.config_fingerprint_key(
              p._asdict(), "aot_forward"))
      log_fn(f"Exported frozen forward program to {p.aot_save_path} "
             f"({nbytes} bytes"
             + (f", {trt_mode} serving precision" if trt_mode else "")
             + ")")

    # Observability wiring (SURVEY 5.1/5.5; see observability.py).
    bench_logger = None
    if p.benchmark_log_dir:
      bench_logger = observability.BenchmarkLogger(p.benchmark_log_dir)
      bench_logger.log_run_info(p, self.model.get_name(),
                                self.dataset.name, self.num_devices,
                                self.batch_size)
    summary_writer = None
    if p.train_dir and p.save_summaries_steps and p.summary_verbosity:
      summary_writer = observability.SummaryWriter(p.train_dir,
                                                   p.summary_verbosity)
    if p.graph_file or p.tfprof_file or p.partitioned_graph_file_prefix:
      # One lowering feeds all dumps (tracing a big model twice is
      # minutes of redundant startup work). Forward-only dumps the eval
      # program it actually runs; chunked runs dump the K-step scanned
      # program (the unit of dispatch the timed loop executes).
      dump_fn = eval_step if p.forward_only else (
          train_chunk if chunked else train_step)
      lowered = dump_fn.lower(state, images, labels)
      if p.graph_file:
        observability.dump_program_text(lowered, p.graph_file)
        log_fn(f"Wrote program text to {p.graph_file}")
      # The compiled dumps share ONE compilation.
      compiled = (lowered.compile()
                  if p.tfprof_file or p.partitioned_graph_file_prefix
                  else None)
      if p.tfprof_file:
        observability.dump_cost_analysis(lowered, p.tfprof_file,
                                         compiled=compiled)
        log_fn("Wrote cost analysis to %s (note: the analysis compiles "
               "the step once ahead of the jit cache's own compile)"
               % p.tfprof_file)
        # The operator-facing top-op ranking the reference printed from
        # tfprof (ref: benchmark_cnn.py:1208-1228).
        table = observability.dump_per_op_profile(
            compiled, p.tfprof_file + ".ops.txt",
            steps_per_dispatch=self.steps_per_dispatch)
        for line in table.splitlines():
          log_fn(line)
        try:
          # The footprint the HBM levers (--num_grad_accum, the
          # chunked fused head, scanned-layer remat) actually move.
          log_fn(observability.hbm_breakdown_line(
              compiled.memory_analysis()))
        except Exception as e:  # backend-dependent surface
          log_fn(f"peak HBM line unavailable: {e!r}")
      if p.partitioned_graph_file_prefix:
        path = p.partitioned_graph_file_prefix + ".txt"
        observability.dump_partitioned_text(compiled, path)
        log_fn(f"Wrote partitioned program text to {path}")

    # Elastic / adaptive-batch drivers (north-star KungFu capabilities;
    # see elastic.py).
    noise_ema = (elastic_lib.NoiseScaleEMA()
                 if p.track_grad_noise_scale else None)
    if noise_ema is not None and self.num_devices < 2:
      # The estimator contrasts per-replica vs replica-mean gradients;
      # with one replica there is no contrast and no metrics are emitted.
      log_fn("track_grad_noise_scale: needs >= 2 devices, no estimates "
             "will be produced (adaptive_batch_size will hold steady)")
    batch_policy = (elastic_lib.AdaptiveBatchPolicy(
        p.adaptive_batch_min, p.adaptive_batch_max)
        if p.adaptive_batch_size else None)
    controller = self.elastic_controller
    if controller is None and p.elastic:
      controller = elastic_lib.ElasticController.from_env(
          max_devices=len(mesh_lib.get_devices(p.device)))
      if controller is None:
        log_fn("elastic: no coordination service in env (KFCOORD_*); "
               "resize polling disabled")
    reshape_events = []

    # Snapshot pre-existing profiler runs so the measured per-op table is
    # pinned to the trace THIS run captures (a stale dump at the same
    # --trace_file path must never be reported as this run's profile).
    trace_dir = observability.trace_dir_of(p.trace_file)
    pre_trace_runs = (observability.list_profile_runs(trace_dir)
                      if p.trace_file and p.tfprof_file else [])

    # Host-side dispatch accounting for the BENCH trajectory: the FIRST
    # dispatch call blocks on trace+compile (compile_s); later calls
    # measure the per-dispatch host overhead (jit-call machinery +
    # transfer/RTT) that --steps_per_dispatch amortizes. Timed-loop
    # entries only feed dispatch_overhead_s (warmup's are cleared), and
    # the measurement brackets the async fn call alone -- never the
    # trace drain.
    dispatch_stats = {"compile_s": None, "call_times": []}
    trace = self._trace

    def _note_compile(label: str, wall_s: float) -> None:
      """First host call of a jitted program blocks on trace+compile:
      ledger the episode under the program-shape fingerprint key
      (analysis/baseline.config_fingerprint_key -- the identity the
      persistent compile cache of ROADMAP item 5 will share)."""
      from kf_benchmarks_tpu.analysis import baseline as baseline_lib
      self._compiled_programs.add(label)
      key = baseline_lib.config_fingerprint_key(self.params._asdict(),
                                                label)
      trace.note_compile(
          key, label, wall_s, model=self.model.get_name(),
          num_devices=self.num_devices,
          # True when the persistent XLA cache is WARM (dir holds
          # entries) AND an earlier run of this train_dir already
          # ledgered this shape: the episode deserialized a cached
          # executable rather than paying the full compile (the
          # once-per-shape contract; best-effort -- jax exposes no
          # per-compile hit signal, see _benchmark_train).
          cache_hit=bool(
              getattr(self, "_compile_cache_warm", False)
              and key in getattr(self, "_prior_ledger_keys", ())))

    def _traced(trace_file, idx, trace_at, label, fn, *args):
      """One dispatch under the single-dispatch trace policy: trace it
      when ``idx == trace_at`` (warmup traces its LAST dispatch, ref
      :806-817 traces step -2 for the same reason; with zero warmup the
      timed loop traces its first) and -- dispatch being async -- drain
      inside the profiler context so the trace spans the device
      execution (utils/sync.py on why block_until_ready is not enough).
      The ONE place this invariant lives; every dispatch site routes
      through it. ``label`` names the dispatched program for the
      dispatch-issue span and the compile ledger: the span brackets the
      ASYNC jit call only (device completion is attributed
      differentially from the pipeline arrival intervals in _handle --
      never block_until_ready)."""
      with observability.maybe_trace_step(trace_file, idx, trace_at):
        t0 = trace.now()
        t_call = time.monotonic()
        new_state, out_metrics = fn(*args)
        dt = time.monotonic() - t_call
        first = label not in self._compiled_programs
        trace.add_span("dispatch", label, t0, trace.now() - t0,
                       {"step": idx, "first_call": first})
        if dispatch_stats["compile_s"] is None:
          dispatch_stats["compile_s"] = dt
        dispatch_stats["call_times"].append(dt)
        if first:
          _note_compile(label, dt)
        if trace_file and idx == trace_at:
          sync.drain(out_metrics)
      return new_state, out_metrics

    log_fn("Running warm up")
    t0 = time.time()
    t0_warm = trace.now()
    cursor = 0  # consumed slices of the current staged real-data chunk
    if chunked:
      # Exactly num_warmup_batches warmup steps, like K=1: q whole
      # chunks first, then r = W mod K single steps consuming slices of
      # the next staged chunk. The warmed-up STATE and (real data) the
      # stream position are therefore identical to the K=1 loop's,
      # which is what keeps the timed per-step losses bit-identical
      # across K. The chunk program compiles here when q >= 1 and the
      # single-step program when r >= 1; a program not exercised by
      # this split compiles at its first use instead (a tail/event
      # dispatch, or -- when W < K -- the first timed chunk).
      q, r = divmod(self.num_warmup_batches, K)
      n_dispatches = q + r
      w = 0
      for _ in range(q):
        state, metrics = _traced(p.trace_file, w, n_dispatches - 1,
                                 "train_chunk", train_chunk, state,
                                 images, labels)
        images, labels = next_batch()
        w += 1
      for _ in range(r):
        state, metrics = _traced(p.trace_file, w, n_dispatches - 1,
                                 "train_step", run_step, state,
                                 *_step_slice(images, labels, cursor))
        if not synthetic:
          cursor += 1
          if cursor >= images.shape[0]:
            images, labels = next_batch()
            cursor = 0
        w += 1
      warm_steps = self.num_warmup_batches
      if n_dispatches and not p.trace_file:
        sync.drain(metrics)
    else:
      for w in range(self.num_warmup_batches):
        state, metrics = _traced(p.trace_file, w,
                                 self.num_warmup_batches - 1,
                                 "train_step", run_step, state, images,
                                 labels)
        images, labels = next_batch()
      warm_steps = self.num_warmup_batches
      if self.num_warmup_batches and not p.trace_file:
        # Empty the device queue before the clock starts: timing must not
        # begin with warmup steps still executing (utils/sync.py). With
        # --trace_file the traced last step already drained in-context.
        sync.drain(metrics)
    log_fn("Warmup (compile + %d steps): %.1f s" %
           (warm_steps, time.time() - t0))
    trace.add_span("run", "warmup", t0_warm, trace.now() - t0_warm,
                   {"steps": warm_steps})
    if tele is not None and self.num_warmup_batches:
      # First heartbeat: compile + warmup completed (the drain above is
      # a real value fetch, utils/sync.py) -- the watchdog leaves its
      # patient first-compile regime here. With --num_warmup_batches=0
      # no dispatch has run yet, so the beat is withheld and the
      # watchdog stays in the patient regime through the first timed
      # dispatch (which IS the first compile then, per the chunked
      # warmup-split comment above).
      tele.beat()
    # Base for globally-meaningful step numbers in metric/summary streams
    # (resumed runs must not restart their step axis at 1).
    start_step = int(state.step)

    header = "Step\tImg/sec\t" + p.loss_type_to_report
    if p.print_training_accuracy:
      header += "\ttop_1_accuracy\ttop_5_accuracy"
    log_fn(header)

    step_train_times = []
    chunk_times = []  # wall interval per K-step dispatch (chunked mode)
    loss = float("nan")
    stopped_early = False
    restart_requested = None
    images_processed = 0
    last_save_time = time.time()
    last_display_len = 0
    # Lag-2 pipelined metric fetch (utils/pipeline.py): blocking on each
    # step's metrics costs a full host<->device round trip per step
    # (measured 389 vs ~2560 img/s behind the TPU tunnel, PERF.md).
    # Reading each step's metrics two dispatches later keeps the device
    # queue full, every printed number is still the exact value for its
    # step, and the read-arrival intervals are real per-step times for
    # the mean/uncertainty/jitter stats (ref: benchmark_cnn.py:887-902).
    pipe = pipeline_lib.MetricsPipeline(lag=2)

    # The device span of the dispatch currently resolving through
    # _handle: opened at its FIRST completed step (every member carries
    # the full chunk interval), shared by all K rows, closed at
    # chunk_end -- so every flight-recorder row cross-links the span
    # it lies inside. issue_walls pairs each resolving dispatch with
    # ITS OWN host-issue wall: the pipeline resolves dispatches FIFO
    # but lag-2 behind the issues, so call_times[-1] would belong to a
    # LATER dispatch (and make the wall - issue differential lie).
    dispatch_span = {"id": None}
    issue_walls = []

    def _handle(done: "pipeline_lib.CompletedStep"):
      nonlocal loss, last_display_len
      step_train_times.append(done.interval)
      if done.chunk_len > 1 and done.chunk_end:
        chunk_times.append(done.chunk_interval)
      m = done.metrics
      loss = float(m[p.loss_type_to_report])
      # Live registry lanes (metrics.py): the /metrics scrape shows the
      # run's current step/loss/health WHILE it trains. Registered-key
      # sets only; host dict writes, nothing device-side.
      registry = metrics_lib.active()
      registry.set("step", start_step + done.index)
      registry.set("loss", loss)
      if "learning_rate" in m:
        registry.set("learning_rate", float(m["learning_rate"]))
      for health_name, health_value in \
          telemetry_lib.health_scalars(m).items():
        registry.set(health_name, health_value)
      if dispatch_span["id"] is None:
        # Device completion attributed DIFFERENTIALLY: the pipeline's
        # read-arrival interval is the dispatch's real wall (the lag-2
        # fetch IS the sync signal, utils/pipeline.py); the SAME
        # dispatch's host-issue share rides in the args so device time
        # can be read as wall - issue (~70 ms tunnel RTT, the roofline
        # discipline).
        issue_s = issue_walls.pop(0) if issue_walls else None
        t_now = trace.now()
        dispatch_span["id"] = trace.add_span(
            "device", "chunk" if done.chunk_len > 1 else "step",
            t_now - done.chunk_interval, done.chunk_interval,
            {"steps": done.chunk_len,
             "end_step": start_step + done.index + done.chunk_len - 1
             if not done.chunk_end else start_step + done.index,
             "issue_ms": (round(issue_s * 1e3, 3)
                          if issue_s is not None else None)})
      if tele is not None:
        # One flight-recorder row per STEP (chunked dispatches resolve
        # to per-step metrics host-side, utils/pipeline.py); heartbeat
        # once per completed dispatch with its real wall interval. The
        # pipeline's metric fetch IS the drain-semantics liveness
        # signal (utils/sync.py) -- block_until_ready is never used.
        tele.record(
            step=start_step + done.index, loss=loss,
            lr=m.get("learning_rate"), health=m.get("health"),
            wall_ms=done.interval * 1e3, chunk_len=done.chunk_len,
            rtt_ms=(dispatch_stats["call_times"][-1] * 1e3
                    if dispatch_stats["call_times"] else None),
            span_id=dispatch_span["id"] or None)
        if done.chunk_end:
          tele.beat(done.chunk_interval)
      if done.chunk_end:
        trace.add_sample("chunk_wall", done.chunk_interval)
        metrics_lib.active().observe("chunk_wall_s", done.chunk_interval)
        dispatch_span["id"] = None
      if noise_ema is not None and "noise_scale_g2" in m:
        noise_ema.update(float(m["noise_scale_g2"]),
                         float(m["noise_scale_s"]))
      i1 = done.index
      if i1 % self.display_every == 0 or i1 == self.num_batches:
        top1 = float(m["top_1_accuracy"]) if "top_1_accuracy" in m else None
        top5 = float(m["top_5_accuracy"]) if "top_5_accuracy" in m else None
        window = step_train_times[last_display_len:]
        log_fn(log_util.format_step_line(
            i1, self.batch_size * max(self.num_workers, 1), window, loss,
            top1, top5))
        registry.set(
            "step_images_per_sec",
            self.batch_size * max(self.num_workers, 1) /
            max(sum(window) / max(len(window), 1), 1e-9))
        if bench_logger is not None:
          # Per-step metric emission (ref: benchmark_cnn.py:847-854).
          window_avg = sum(window) / max(len(window), 1)
          bench_logger.log_metric(
              "current_examples_per_sec",
              self.batch_size * max(self.num_workers, 1) /
              max(window_avg, 1e-9),
              unit="examples/sec", global_step=start_step + i1)
          bench_logger.log_metric(p.loss_type_to_report, loss,
                                  global_step=start_step + i1)
        last_display_len = len(step_train_times)
      if summary_writer is not None and i1 % p.save_summaries_steps == 0:
        scalars = {k: v for k, v in m.items() if np.ndim(v) == 0}
        # The packed health vector expands into the SAME health/<key>
        # scalars the flight-recorder rows carry (one shared schema,
        # telemetry.py).
        scalars.update(telemetry_lib.health_scalars(m))
        summary_writer.write_scalars(start_step + i1, scalars)
        if summary_writer.verbosity >= 2:  # slice only when it will be used
          # Histograms read the live state (may be up to `lag` steps ahead
          # of i1 -- histogram verbosity is a debugging surface).
          # --shard_params never reaches here: validation.py rejects it
          # with verbosity >= 2 (row 0 would be a 1/n flat shard, not
          # the replica-0 parameter copy the histogram keys claim).
          summary_writer.write_histograms(
              start_step + i1,
              jax.tree.map(lambda x: x[0], state.params), "params",
              stacked_prefixes=tuple(
                  getattr(self.model, "scanned_param_prefixes", ())
                  or ()))

    # Step-keyed schedule predicates. The SAME functions feed both the
    # dispatch-length planner (_event_due) and the post-dispatch due
    # flags below, so the chunk-shortening contract ("a chunk never
    # crosses a scheduled step") cannot drift from the schedule that
    # actually fires. The seconds-based checkpoint cadence is not
    # step-keyed: it is checked at dispatch boundaries only, so under
    # chunking it can land up to K-1 steps late -- it is a wall-clock
    # schedule already.
    def _save_steps_due(s: int) -> bool:
      return bool(p.train_dir and p.save_model_steps and
                  s % p.save_model_steps == 0)

    def _eval_sched_due(s: int) -> bool:
      return bool((p.eval_during_training_every_n_steps and
                   s % p.eval_during_training_every_n_steps == 0) or
                  s in self.eval_step_set)

    def _elastic_sched_due(s: int) -> bool:
      return bool((controller is not None or batch_policy is not None) and
                  s % p.elastic_check_every_n_steps == 0)

    def _fault_due(s: int) -> bool:
      return self._faults is not None and self._faults.due(s)

    def _event_due(s: int) -> bool:
      """A host intervention is scheduled immediately after step ``s``."""
      return (_save_steps_due(s) or _eval_sched_due(s) or
              _elastic_sched_due(s) or _fault_due(s))

    def _dispatch_len(done_steps: int) -> int:
      """Length of the next dispatch: up to K steps, stopping at the run
      end and BEFORE any step-keyed event strictly inside the window, so
      checkpoints/eval/elastic keep exact K=1 step semantics (the chunk
      shortens; the short remainder runs as single steps)."""
      n = min(K, self.num_batches - done_steps)
      for d in range(1, n):
        if _event_due(done_steps + d):
          return d
      return n

    loop_start = time.time()
    pipe.reset_clock()
    # Warmup dispatches (incl. the compile call) must not skew the
    # timed loop's per-dispatch host-overhead average.
    dispatch_stats["call_times"].clear()
    i = 0  # steps completed (cursor carries over from warmup)
    # Injected drop_msg (faults.py) is STICKY: the fault may fire at a
    # non-poll boundary, and what it must suppress is the NEXT actual
    # coordination poll -- consumed there, not at its own step.
    drop_next_poll = False
    while i < self.num_batches:
      n_dispatch = _dispatch_len(i) if chunked else 1
      if chunked and not synthetic and cursor:
        # Mid-chunk (warmup remainder or an event-shortened dispatch
        # consumed part of the staged chunk): run single steps only up
        # to the chunk boundary, so the NEXT dispatch meets a fully
        # unconsumed chunk. Without this cap an event-free run would
        # execute K singles per iteration, land on the same cursor
        # residue forever, and never dispatch a chunk at all.
        n_dispatch = min(n_dispatch, images.shape[0] - cursor)
      # A full-K dispatch needs a chunk-aligned input: the synthetic
      # resident batch always is; a staged real-data chunk only when
      # fully unconsumed.
      use_chunk = (chunked and n_dispatch == K and
                   (synthetic or (cursor == 0 and images.shape[0] == K)))
      # (trace fallback: with zero warmup dispatches the trace runs on
      # the FIRST timed dispatch, via _traced's trace_at == i == 0)
      timed_trace = p.trace_file if self.num_warmup_batches == 0 else None
      if use_chunk:
        state, metrics = _traced(timed_trace, i, 0, "train_chunk",
                                 train_chunk, state, images, labels)
        issue_walls.append(dispatch_stats["call_times"][-1])
        images, labels = next_batch()
        i += K
        images_processed += K * self.batch_size * max(self.num_workers, 1)
        for done in pipe.push(i, metrics, count=K):
          _handle(done)
      else:
        for _ in range(n_dispatch):
          state, metrics = _traced(timed_trace, i, 0, "train_step",
                                   run_step, state,
                                   *_step_slice(images, labels, cursor))
          issue_walls.append(dispatch_stats["call_times"][-1])
          if not chunked:
            images, labels = next_batch()
          elif not synthetic:
            cursor += 1
            if cursor >= images.shape[0]:
              images, labels = next_batch()
              cursor = 0
          i += 1
          images_processed += self.batch_size * max(self.num_workers, 1)
          for done in pipe.push(i, metrics):
            _handle(done)
      save_due = _save_steps_due(i) or bool(
          p.train_dir and p.save_model_secs and
          time.time() - last_save_time >= p.save_model_secs)
      eval_due = _eval_sched_due(i)
      elastic_due = _elastic_sched_due(i)
      fault_due = _fault_due(i)
      if save_due or eval_due or elastic_due or fault_due:
        # Sync point: resolve everything in flight so checkpoint/eval/
        # resize wall time stays out of the per-step timing, then exclude
        # it from the next interval via note_aux_time.
        for done in pipe.flush():
          _handle(done)
        aux_start = time.time()
        if fault_due:
          # Faults fire FIRST at the boundary (a preemption does not
          # wait for the checkpoint cadence): kill/sigterm never
          # return; corrupt_ckpt truncates the newest snapshot already
          # ON DISK (i.e. before this boundary's own save lands); the
          # recorder row is written BEFORE firing so a kill still
          # leaves its trace in the continuous window.
          if tele is not None:
            for f in self._faults.peek_due(i):
              tele.fault_event(f.describe(), i)
          fired = self._faults.fire_due(i, train_dir=p.train_dir)
          if fired.dropped_message:
            drop_next_poll = True
        if save_due:
          # Periodic checkpoint by steps (ref: benchmark_cnn.py:2304-2309)
          # or seconds (ref: Supervisor save_model_secs, :2137).
          self._save_checkpoint(state)
          last_save_time = time.time()
        if eval_due:
          # Mid-training eval + early stop (ref: benchmark_cnn.py:2310-2324).
          t_eval = trace.now()
          acc = eval_step(state, *_step_slice(images, labels, cursor))
          # The ledger convention brackets the ASYNC first call only
          # (blocks on trace+compile) -- the device_get below adds
          # execution + transfer wall, which belongs to the eval span,
          # not the compile episode.
          eval_issue = trace.now() - t_eval
          if "eval_step" not in self._compiled_programs:
            _note_compile("eval_step", eval_issue)
          acc = jax.device_get(acc)
          trace.add_span("eval", "mid_train_eval", t_eval,
                         trace.now() - t_eval, {"step": i})
          top1 = float(acc["top_1_accuracy"])
          log_fn("Accuracy @ 1 = %.4f Accuracy @ 5 = %.4f [%d examples]" %
                 (top1, float(acc["top_5_accuracy"]), self.batch_size))
          if p.stop_at_top_1_accuracy and top1 >= p.stop_at_top_1_accuracy:
            log_fn(f"Stopping early at top-1 accuracy {top1:.4f} "
                   f">= {p.stop_at_top_1_accuracy}")
            stopped_early = True
            break
        # Elastic resize / adaptive batch (north-star KungFu capabilities;
        # SURVEY 2.9, 5.3). Polled at a fixed cadence to keep the hot loop
        # collective-free.
        if elastic_due and i < self.num_batches:
          new_n = None
          restart_np = None
          under_kfrun = "KFCOORD_WORLD" in os.environ
          if controller is not None and drop_next_poll:
            # Injected drop_msg (faults.py): this poll is the lost
            # message. The poll-side dedup never advanced, so a
            # pending RESIZE must re-surface at the next poll instead
            # of vanishing (pinned in tests/test_faults.py).
            drop_next_poll = False
            log_fn(f"fault drop_msg: coordination poll at step {i} "
                   "dropped; a pending resize stays pending")
          elif controller is not None:
            poll_at = getattr(controller, "poll_at", None)
            new_n = poll_at(i) if poll_at else controller.poll()
            raw = getattr(controller, "last_raw_target", None)
            if new_n is not None and raw and under_kfrun:
              # Under the kfrun launcher the RESIZE target is a GLOBAL
              # device count. If it fits the current process set at
              # PER-PROCESS capacity (locally attached devices -- the
              # controller's max_devices is global), reshape in-mesh;
              # otherwise a live JAX world cannot change its process
              # count, so SCHEDULE the checkpoint-restart leg a couple
              # of poll windows ahead -- workers poll at the same step
              # but different wall times, and an immediate restart
              # would split-brain (SURVEY 5.3/7.4 "checkpointed
              # rescale"; KungFu resize_cluster's config-server-
              # synchronized resize).
              action, value = elastic_lib.plan_resize(
                  raw, procs=max(self.num_workers, 1),
                  capacity=jax.local_device_count(),
                  # The restart can only spawn processes that have
                  # somewhere to live: cap at the provisioned host list
                  # (absent one there is no distributed world to
                  # re-form, so scaling stays in-mesh).
                  max_procs=len(p.worker_hosts or []) or 1)
              if action == "restart":
                if (hasattr(controller, "scheduled_restart") and
                    controller.scheduled_restart() is None):
                  k = max(1, p.elastic_check_every_n_steps)
                  controller.schedule_restart(i + 2 * k, value)
                # The restart owns this resize: the clamped global poll
                # value must not fall through to the per-process
                # in-mesh reshape below.
                new_n = None
              else:
                new_n = value
            # Agreement point: adopt any pending scheduled restart. A
            # schedule whose target equals this incarnation's world is
            # already satisfied (stale key from before the re-exec).
            if under_kfrun and hasattr(controller, "scheduled_restart"):
              sched = controller.scheduled_restart()
              if sched is not None:
                sched_step, sched_np = sched
                if (sched_np != max(self.num_workers, 1) and
                    i >= sched_step):
                  restart_np = sched_np
            if restart_np is None and new_n == self.num_devices:
              new_n = None
          if restart_np is not None:
            if not p.train_dir:
              log_fn("Elastic restart to %d worker(s) requested but "
                     "--train_dir is unset; cannot checkpoint-restart, "
                     "ignoring" % restart_np)
            else:
              for done in pipe.flush():
                _handle(done)
              self._save_checkpoint(state)
              log_fn("Elastic restart at step %d: workers %d -> %d "
                     "(checkpoint + re-exec under the launcher)" % (
                         i, max(self.num_workers, 1), restart_np))
              # SPMD lockstep: every worker reaches this at the same
              # step; the barrier holds exits until the chief's
              # checkpoint write completed (the chief enters after
              # writing).
              try:
                controller.restart_barrier(
                    f"kf_restart_{controller.generation()}",
                    max(self.num_workers, 1))
              except Exception as e:  # noqa: BLE001
                log_fn(f"restart barrier failed ({e}); exiting anyway")
              trace.instant("elastic", "checkpoint_restart", step=i,
                            workers=restart_np)
              restart_requested = restart_np
              break
          new_bs = None
          if batch_policy is not None and noise_ema is not None:
            proposed = batch_policy.propose(
                self.batch_size_per_device, noise_ema.b_simple,
                new_n or self.num_devices)
            if proposed != self.batch_size_per_device:
              new_bs = proposed
          nm_axis = (int(self.mesh.shape[mesh_lib.MODEL_AXIS])
                     if mesh_lib.BATCH_AXIS in self.mesh.axis_names else 1)
          if new_n and new_n % nm_axis:
            # 2-D family: the model axis survives a resize, so the
            # target must be a multiple of its width.
            log_fn(f"Elastic reshape to {new_n} devices rejected: the "
                   f"model-axis width ({nm_axis}) must divide the "
                   "target on the 2-D mesh; keeping current topology")
            new_n = None
          if new_n:
            # A resize must honor the same cross-flag rules as startup
            # (e.g. the async-PS sequential-apply device cap): an
            # in-mesh up-resize is the one path that changes num_devices
            # without re-running startup validation, so check here and
            # hold topology rather than grow into a configuration the
            # CLI would have rejected.
            try:
              check = self.params._replace(num_devices=new_n)
              if check.mesh_shape:
                check = check._replace(
                    mesh_shape=f"{new_n // nm_axis}x{nm_axis}")
              validation.validate_cross_flags(check)
            except validation.ParamError as e:
              log_fn(f"Elastic reshape to {new_n} devices rejected by "
                     f"flag validation ({e}); keeping current topology")
              new_n = None
          if new_n or new_bs:
            event = {"step": i,
                     "num_devices": new_n or self.num_devices,
                     "batch_size_per_device":
                         new_bs or self.batch_size_per_device,
                     "b_simple": noise_ema.b_simple if noise_ema else None}
            log_fn("Elastic reshape at step %d: devices %d -> %d, "
                   "per-device batch %d -> %d" % (
                       i, self.num_devices, event["num_devices"],
                       self.batch_size_per_device,
                       event["batch_size_per_device"]))
            old_mesh = "x".join(
                str(int(s)) for s in self.mesh.devices.shape)
            t_seam = trace.now()
            if p.train_dir:
              # Drain happened at the sync point above; snapshot to
              # disk BEFORE the rebuild, so a crash mid-rescale (or a
              # preemption racing it) resumes from this exact seam --
              # and a peer run at the new size can start from the same
              # snapshot (the bit-identity contract of the rescale
              # tests). incarnation_bump=1: the seam's resume point is
              # the POST-resize input stream.
              self._save_checkpoint(state, incarnation_bump=1)
              last_save_time = time.time()
            state, train_step, eval_step, next_batch, train_chunk = \
                self._reshape_topology(state, event["num_devices"],
                                       event["batch_size_per_device"],
                                       init_rng, steps_done=i,
                                       examples_done=images_processed)
            run_step = make_run_step(train_step, eval_step)
            images, labels = next_batch()
            cursor = 0
            reshape_events.append(event)
            # ONE elastic event line (generation, old -> new mesh,
            # resume step) -- the operator-facing record a preemption
            # story needs instead of silence -- mirrored into the
            # flight-recorder window when a telemetry session exists.
            generation = len(reshape_events)
            if controller is not None and hasattr(controller,
                                                  "generation"):
              try:
                generation = controller.generation()
              except Exception:
                pass
            new_mesh = "x".join(
                str(int(s)) for s in self.mesh.devices.shape)
            event["mesh"] = f"{old_mesh}->{new_mesh}"
            log_fn("elastic event: generation %d: mesh %s -> %s, "
                   "resume step %d" % (generation, old_mesh, new_mesh,
                                       i))
            # One span per generation on the elastic track: the whole
            # seam (seam snapshot + mesh rebuild + re-jit + restore +
            # contract re-verification), so the timeline shows where a
            # resize's wall went.
            trace.add_span(
                "elastic", f"resize_gen{generation}", t_seam,
                trace.now() - t_seam,
                {"generation": generation, "mesh": event["mesh"],
                 "resume_step": i})
            if tele is not None:
              tele.elastic_event(generation, old_mesh, new_mesh, i)
        pipe.note_aux_time(time.time() - aux_start)
    for done in pipe.flush():
      _handle(done)
    total_time = time.time() - loop_start
    trace.add_span("run", "timed_loop", trace.now() - total_time,
                   total_time, {"steps": len(step_train_times)})
    if controller is not None and controller is not self.elastic_controller:
      controller.close()

    num_steps = len(step_train_times)
    average_wall_time = total_time / num_steps if num_steps else 0
    images_per_sec = images_processed / total_time
    log_fn("-" * 64)
    log_fn(log_util.format_total_line(images_per_sec))
    log_fn("-" * 64)
    if chunked and chunk_times:
      # Per-chunk timing rows: the dispatch-granularity wall clock the
      # amortized per-step numbers above are derived from (honest-timing
      # note in utils/pipeline.py).
      for line in observability.chunk_timing_rows(
          K, chunk_times, self.batch_size * max(self.num_workers, 1)):
        log_fn(line)
    # Input-pipeline line (next to the timing rows; the roofline table
    # covers the device side, this covers the host edge): packing
    # efficiency of the document packer plus the measured feed-stall
    # fraction proving (or disproving) that the DeviceFeeder prefetch
    # overlapped host work with the step (observability.py).
    feeder = getattr(self, "_feeder", None)
    feed_stats = feeder.stats() if feeder is not None else None
    packing_stats = (self._packed_stream.stats()
                     if getattr(self, "_packed_stream", None) is not None
                     else None)
    if feed_stats is not None and feed_stats["fetches"]:
      log_fn(observability.packing_feed_line(feed_stats, packing_stats))
    if bench_logger is not None:
      # Final throughput metrics (ref: _log_benchmark_run
      # average_examples_per_sec emission).
      bench_logger.log_metric("average_examples_per_sec", images_per_sec,
                              unit="examples/sec",
                              global_step=start_step + num_steps)
      if chunked and chunk_times:
        bench_logger.log_metric(
            "chunk_wall_time_mean",
            sum(chunk_times) / len(chunk_times), unit="seconds",
            global_step=start_step + num_steps,
            extras={"steps_per_dispatch": K,
                    "num_chunks": len(chunk_times)})
    if p.tfprof_file:
      # The measured half of the tfprof analog (ref: benchmark_cnn.py:
      # 1208-1228 ranks ops by MEASURED accelerator time from RunMetadata):
      # parse the step trace captured above back into per-op device time,
      # next to the static roofline .ops.txt. Without --trace_file this
      # run captured nothing: no scan (CWD's plugins/profile is not
      # ours to read), but a stale table a previous traced run left at
      # the profile path is still cleared. Best-effort throughout -- an
      # observability failure must never cost a finished run its final
      # checkpoint below.
      try:
        measured_path = p.tfprof_file + ".measured_ops.txt"
        if p.trace_file:
          table = observability.dump_measured_op_profile(
              trace_dir, measured_path, exclude=pre_trace_runs)
          if table is not None:
            for line in table.splitlines():
              log_fn(line)
        elif os.path.exists(measured_path):
          os.unlink(measured_path)
      except Exception as e:  # pragma: no cover - defensive tail
        log_fn(f"measured per-op profile failed (non-fatal): {e!r}")
    # Run-health summary (telemetry.py): the aggregate the one-line
    # BENCH JSON carries next to throughput (bench.py).
    health_summary = None
    if tele is not None:
      health_summary = tele.summary()
      if bench_logger is not None and \
          health_summary.get("max_grad_norm") is not None:
        bench_logger.log_metric(
            "max_grad_norm", health_summary["max_grad_norm"],
            global_step=start_step + num_steps,
            extras={"nonfinite_steps": health_summary["nonfinite_steps"],
                    "watchdog_stalls": health_summary["watchdog_stalls"]})
    # Final checkpoint (ref: benchmark_cnn.py:2374-2378).
    if p.train_dir:
      self._save_checkpoint(state)
    # Streaming latency percentiles (chunk wall / feed wait / checkpoint
    # save) + the compile ledger table -- AFTER the final save so the
    # printed sample counts match the stats fields below; whole lines
    # only (the scrape guard: nothing interleaves inside step lines).
    # The ledger persists to train_dir/compile_ledger.json keyed on
    # contract fingerprints (tracing.py; ROADMAP items 2 and 5).
    for line in self._trace.latency_lines():
      log_fn(line)
    for line in self._trace.ledger_lines():
      log_fn(line)
    if p.train_dir:
      self._trace.write_ledger(p.train_dir)
    if p.sync_on_finish:
      # all-ranks: --sync_on_finish is a launch-wide flag (same command
      # line on every kfrun worker), so every rank takes this branch or
      # none do -- the exit barrier always has full attendance.
      kungfu.run_barrier()
    # (ref stats dict: benchmark_cnn.py:2383-2391)
    stats = {
        "num_workers": max(self.num_workers, 1),
        "num_steps": num_steps,
        "average_wall_time": average_wall_time,
        "images_per_sec": images_per_sec,
        "last_average_loss": loss,
        "stopped_early": stopped_early,
        "steps_per_dispatch": K,
        "num_chunks": len(chunk_times),
        # BENCH-trajectory fields: the first dispatch call's wall time
        # (blocks on trace+compile) and the mean host time per TIMED
        # dispatch call (the jit-call + transfer/RTT cost that
        # --steps_per_dispatch amortizes K-fold).
        "compile_s": dispatch_stats["compile_s"],
        "dispatch_overhead_s": (
            sum(dispatch_stats["call_times"]) /
            len(dispatch_stats["call_times"])
            if dispatch_stats["call_times"] else None),
        # Set when a cross-process resize needs the launcher to re-exec
        # this worker set at a new world size (kfrun restart leg).
        "restart_for_resize": restart_requested,
        "reshape_events": reshape_events,
        "grad_noise_scale": noise_ema.b_simple if noise_ema else None,
        # Training-health aggregate (None when --health_stats resolved
        # off): max grad norm, nonfinite_steps, loss_scale_final,
        # watchdog_stalls, anomaly_dumps (telemetry.py).
        "health": health_summary,
        # Mesh topology + per-device optimizer-state HBM: "8" on the
        # 1-D replica mesh, "BxM" on the named 2-D mesh; the bytes
        # field is what --shard_optimizer_state divides by ~n
        # (bench.py forwards both into its one-line JSON).
        "mesh_shape": "x".join(
            str(int(s)) for s in self.mesh.devices.shape),
        "opt_state_bytes_per_device": opt_state_bytes_per_device(
            state.opt_state),
        # Per-device parameter HBM, same leading-dim accounting:
        # ~|params| on the replicated/stacked layouts, ~|params|/n
        # under --shard_params -- the FSDP memory claim, next to the
        # optimizer one (bench.py forwards it).
        "param_bytes_per_device": opt_state_bytes_per_device(
            state.params),
        # Input-pipeline health: fraction of the consume window the
        # loop spent BLOCKED on the feed (None for the resident
        # synthetic batch, which has no feeder) and the packer's
        # measured efficiency (None unless --packed_sequences).
        "feed_stall_fraction": (feed_stats["feed_stall_fraction"]
                                if feed_stats else None),
        "packing_efficiency": (packing_stats["packing_efficiency"]
                               if packing_stats else None),
        # Run-trace aggregates (tracing.py): flat <key>_p50/p90/p99
        # seconds fields over chunk wall / feed wait / checkpoint save
        # (SLO-telemetry groundwork, ROADMAP item 2) and the per-shape
        # compile ledger (persistent-compile-cache groundwork, item 5).
        # bench.py forwards both into its one-line JSON.
        "latency_percentiles": self._trace.percentile_fields() or None,
        "compile_ledger": self._trace.compile_ledger(),
        # Tuned-config provenance (--autotuned_config,
        # analysis/autotune.py): table path + the matched entry's base
        # fingerprint (entry None when the table had no row for this
        # config); None when the flag is unset. bench.py forwards it
        # into its one-line JSON and the run-store snapshot, so
        # --check-regression histories stay attributable.
        "tuned_config": self._tuned_provenance,
        "run_id": self._trace.run_id or None,
        "state": state,
    }
    # Final registry publication (the endpoint serves this snapshot
    # until teardown) + the run record: one schema-versioned JSONL line
    # per run in the cross-run store (metrics.py RunStore; rank 0 only
    # -- the ranks share one store and the record describes the job).
    metrics_lib.publish_stats(metrics_lib.active(), stats)
    if p.run_store_dir and cluster_lib.process_rank() == 0:
      try:
        from kf_benchmarks_tpu.analysis import baseline as baseline_lib
        record = metrics_lib.run_record(
            metric="images_per_sec", value=images_per_sec,
            unit="images/sec",
            fingerprint=baseline_lib.config_fingerprint_key(
                p._asdict(), "train"),
            run_id=self._trace.run_id,
            platform=p.device,
            git_rev=metrics_lib.git_revision(),
            jax_version=jax.__version__,
            snapshot=metrics_lib.flatten_stats(stats))
        store = metrics_lib.RunStore(p.run_store_dir)
        store.append(record)
        log_fn("run record appended: %s" % store.path)
      except (OSError, ValueError) as e:
        log_fn(f"run record append failed (non-fatal): {e}")
    return stats

  def _eval_once(self, state, eval_step, images, labels,
                 next_batch=None) -> Dict[str, Any]:
    """One pass over the eval batches (ref: benchmark_cnn.py:1864-1923)."""
    p = self.params
    num_eval = p.num_eval_batches or self._num_eval_batches_from_epochs() \
        or self.num_batches
    top1_sum = top5_sum = 0.0
    start = time.time()
    # Same lag-2 fetch pipeline as the train loop (utils/pipeline.py).
    pipe = pipeline_lib.MetricsPipeline(lag=2)
    accs = []
    for i in range(num_eval):
      acc = eval_step(state, images, labels)
      for done in pipe.push(i + 1, acc):
        accs.append(done.metrics)
      if next_batch is not None and i + 1 < num_eval:
        try:
          images, labels = next_batch()
        except StopIteration:
          # Real-data validation streams are one-pass (data/preprocessing
          # _record_stream); stopping at exhaustion bounds eval by
          # min(num_eval_batches, one epoch), as the reference does.
          break
    for done in pipe.flush():
      accs.append(done.metrics)
    for acc in accs:
      top1_sum += float(acc["top_1_accuracy"])
      top5_sum += float(acc["top_5_accuracy"])
    elapsed = time.time() - start
    evaluated = max(len(accs), 1)
    top1, top5 = top1_sum / evaluated, top5_sum / evaluated
    log_fn("Accuracy @ 1 = %.4f Accuracy @ 5 = %.4f [%d examples]" %
           (top1, top5, evaluated * self.batch_size))
    eval_ips = evaluated * self.batch_size / max(elapsed, 1e-9)
    if p.eval and p.eval_dir:
      # Eval summary stream (ref: --eval_dir FileWriter,
      # benchmark_cnn.py:585-586, :1770-1772).
      observability.SummaryWriter(p.eval_dir, 1).write_scalars(
          int(state.step), {"eval_top_1_accuracy": top1,
                            "eval_top_5_accuracy": top5,
                            "eval_images_per_sec": eval_ips})
    if p.benchmark_log_dir:
      # Eval-result emission (ref: benchmark_cnn.py:1915-1922). The
      # state's step is the restored checkpoint's global step, so
      # successive poll-loop evals stay distinguishable in metric.log.
      gs = int(state.step)
      logger = observability.BenchmarkLogger(p.benchmark_log_dir)
      logger.log_metric("eval_top_1_accuracy", top1, global_step=gs)
      logger.log_metric("eval_top_5_accuracy", top5, global_step=gs)
      logger.log_metric("eval_images_per_sec", eval_ips,
                        unit="examples/sec", global_step=gs)
    return {"top_1_accuracy": top1, "top_5_accuracy": top5,
            "eval_images_per_sec": eval_ips}

  def _run_eval(self) -> Dict[str, Any]:
    """Evaluation driver (ref: benchmark_cnn.py:1757-1794).

    With a train_dir: poll for new checkpoints every eval_interval_secs,
    evaluating each; terminate after a staleness window (10 polls with no
    new checkpoint) -- the reference loops until killed and its own TODO
    admits the missing staleness abort (ref :1774); bounding it is a
    deliberate improvement. Without a train_dir: single-shot eval of a
    fresh-init model on synthetic data.
    """
    p = self.params
    init_state, train_step, eval_step, broadcast_init, _ = self._build()
    rng = jax.random.PRNGKey(p.tf_random_seed or 0)
    data_rng, init_rng = jax.random.split(rng)
    shape = self._model_image_shape()
    state = init_state(
        init_rng, jnp.zeros((self.batch_size_per_device,) + shape,
                            jnp.float32))
    # Detection (and other accumulate-then-postprocess) models own their
    # real-data eval: per-image prediction accumulation + mAP has no
    # scalar top-k loop to share (ref: ssd postprocess, ssd_model.py:481-539).
    custom_eval = getattr(self.model, "evaluate_real_data", None)
    if custom_eval is not None and not self.dataset.use_synthetic_gpu_inputs():
      if p.train_dir:
        try:
          snapshot, _, _ = checkpoint.load_latest_checkpoint(p.train_dir)
          state = checkpoint.restore_state(state, snapshot,
                                           restore_opt_state=False)
        except checkpoint.CheckpointNotFoundException:
          pass
      variables = {"params": jax.tree.map(lambda x: x[0], state.params)}
      bs = jax.tree.map(lambda x: x[0], state.batch_stats)
      if bs:
        variables["batch_stats"] = bs
      return custom_eval(variables, p, self.dataset)
    if not p.train_dir:
      return self._eval_pass(state, eval_step, data_rng)
    return self._eval_poll_loop(state, eval_step, data_rng)

  def _eval_pass(self, state, eval_step, data_rng) -> Dict[str, Any]:
    """One full eval over a FRESH validation stream, so every checkpoint
    is scored on the same data (the reference re-runs its input pipeline
    per eval, ref: benchmark_cnn.py:1829-1862 _initialize_eval_graph)."""
    next_batch, stop_input = self._input_iterator(data_rng, "validation")
    try:
      try:
        images, labels = next_batch()
      except StopIteration:
        log_fn("Validation stream yielded no batches (fewer examples "
               "than the global batch size?)")
        return {"top_1_accuracy": 0.0, "top_5_accuracy": 0.0,
                "eval_images_per_sec": 0.0}
      real_data = not self.dataset.use_synthetic_gpu_inputs()
      return self._eval_once(state, eval_step, images, labels,
                             next_batch if real_data else None)
    finally:
      stop_input()

  def _eval_poll_loop(self, state, eval_step, data_rng):
    p = self.params
    last_evaluated_step = -1
    results = None
    stale_polls = 0
    max_stale_polls = 10
    while True:
      try:
        path, ckpt_step = checkpoint.latest_checkpoint(p.train_dir)
      except checkpoint.CheckpointNotFoundException:
        # Missing checkpoints are tolerated: wait (ref :1784-1785), but a
        # never-appearing checkpoint still counts toward the staleness
        # bound so the poll loop cannot spin forever.
        if not p.eval_interval_secs:
          raise
        stale_polls += 1
        if stale_polls >= max_stale_polls:
          return results
        time.sleep(p.eval_interval_secs)
        continue
      if ckpt_step > last_evaluated_step:
        try:
          # Parse-once + torn-file skip; the resolve above stays cheap
          # (no parse) for the common nothing-new poll.
          snapshot, path, ckpt_step = checkpoint.load_latest_checkpoint(
              p.train_dir)
        except checkpoint.CheckpointNotFoundException:
          snapshot = None
        if snapshot is None or ckpt_step <= last_evaluated_step:
          # The newest checkpoint was pruned between resolution and
          # read, or is torn with nothing newer behind it: treat as
          # not-yet-available and re-poll.
          stale_polls += 1
          if stale_polls >= max_stale_polls:
            return results
          time.sleep(p.eval_interval_secs or 1)
          continue
        # Model variables only: the eval process's optimizer flags need
        # not match the trainer's (the eval graph has no slots to fill).
        state = checkpoint.restore_state(state, snapshot,
                                         restore_opt_state=False)
        log_fn(f"Evaluating checkpoint at global step {ckpt_step}")
        results = self._eval_pass(state, eval_step, data_rng)
        results["global_step"] = ckpt_step
        last_evaluated_step = ckpt_step
        stale_polls = 0
      else:
        stale_polls += 1
      if not p.eval_interval_secs or stale_polls >= max_stale_polls:
        return results
      time.sleep(p.eval_interval_secs)
