"""kf_benchmarks_tpu: a TPU-native benchmark framework.

A ground-up JAX/XLA/pjit re-design of the capabilities of
``Panlichen/kf-benchmarks`` (reference ``scripts/tf_cnn_benchmarks``):
high-performance CNN training benchmarks with pluggable data-parallel
strategies, including TPU-native equivalents of the KungFu distributed
optimizers (synchronous SGD via ``psum``, pair-averaging gossip via
``ppermute``, synchronous model averaging).

Layer map (mirrors reference SURVEY layer map):
  cli.py            -- CLI entry (ref: tf_cnn_benchmarks.py)
  flags.py          -- ParamSpec registry / absl bridge (ref: flags.py)
  params.py         -- Params + validation (ref: benchmark_cnn.py:953-1034)
  benchmark.py      -- core runtime driver (ref: benchmark_cnn.py)
  parallel/         -- parallelism strategies (ref: variable_mgr*.py)
  ops/              -- collectives: spec parser, packing (ref: allreduce.py)
  models/           -- model zoo + builder (ref: models/, convnet_builder.py)
  data/             -- datasets + preprocessing (ref: datasets.py, preprocessing.py)
  utils/            -- logging, timing, cluster helpers (ref: cnn_util.py)
"""

__version__ = "0.1.0"

# API-version bridging (jax.shard_map availability); must run before any
# submodule builds a sharded program. No-op on current jax.
from kf_benchmarks_tpu import compat as _compat  # noqa: E402,F401
