"""Cross-flag validation rules.

The reference scatters ~35 cross-flag checks through BenchmarkCNN.__init__
(ref: benchmark_cnn.py:1268-1352); here they are standalone, unit-testable
validators run before the runtime is constructed (SURVEY 7.1).
"""

from __future__ import annotations


# Largest world for async-PS's sequential_apply path (stateful
# optimizers): each step costs n optimizer applications (lax.scan) plus an
# all-gather of n full gradient trees, so the mode is bounded to sizes
# where that stays tractable (see validate_cross_flags and PERF.md).
ASYNC_PS_SEQUENTIAL_MAX_DEVICES = 32


class ParamError(ValueError):
  pass


def parse_mesh_shape(mesh_shape: str):
  """'BxM' -> (B, M), both positive ints (ParamError otherwise). Pure
  (no jax): callable from validation and from the mesh builder."""
  parts = str(mesh_shape).lower().split("x")
  try:
    dims = [int(v) for v in parts]
  except ValueError:
    dims = []
  if len(dims) != 2 or any(d < 1 for d in dims):
    raise ParamError(
        f"--mesh_shape={mesh_shape!r}: expected 'BxM' with positive "
        "integer batch and model axis sizes (e.g. 8x1, 4x2)")
  return dims[0], dims[1]


def parse_bucket_ladder(ladder: str):
  """'1,4,16,64' -> (1, 4, 16, 64): strictly ascending positive ints
  (ParamError otherwise). Pure (no jax): callable from validation and
  from bench.py / the serving sweep when they build an EngineConfig."""
  parts = [s.strip() for s in str(ladder).split(",") if s.strip()]
  try:
    buckets = tuple(int(v) for v in parts)
  except ValueError:
    buckets = ()
  if not buckets or any(b < 1 for b in buckets) or \
      list(buckets) != sorted(set(buckets)):
    raise ParamError(
        f"--serving_bucket_ladder={ladder!r}: expected strictly "
        "ascending positive integers (e.g. '1,4,16,64'); the ladder "
        "bounds the serving engine's executable set")
  return buckets


# Flags with NO cross-flag constraint, each with the reason -- the
# explicit no-validation marker the hazard lint requires (analysis/
# lint.py rule 'flag-validation'): every flag in the params registry
# must either appear in validate_cross_flags below or carry an entry
# here, so a new flag cannot silently skip validation. A flag that
# appears in BOTH is a stale marker and fails the lint.
NO_CROSS_FLAG_VALIDATION = {
    # Optimizer hyperparameters: numerically free knobs; the per-spec
    # bounds in the flags registry are the whole contract.
    "adam_beta1": "free hyperparameter (registry bounds only)",
    "adam_beta2": "free hyperparameter (registry bounds only)",
    "adam_epsilon": "free hyperparameter (registry bounds only)",
    "momentum": "free hyperparameter (registry bounds only)",
    "rmsprop_decay": "free hyperparameter (registry bounds only)",
    "rmsprop_epsilon": "free hyperparameter (registry bounds only)",
    "rmsprop_momentum": "free hyperparameter (registry bounds only)",
    "weight_decay": "free hyperparameter (registry bounds only)",
    "gradient_clip": "free hyperparameter; None disables",
    "fp16_loss_scale": "numeric knob; engagement gated by use_fp16 "
                       "checks above",
    "fp16_inc_loss_scale_every_n": "numeric knob of the auto-loss-scale "
                                   "machine; engagement validated via "
                                   "fp16_enable_auto_loss_scale",
    "single_l2_loss_op": "numerically identical formulation toggle "
                         "(train_step.l2_loss)",
    # Display / logging / artifact sinks: consumed as-is by the
    # observability layer; any path works, nothing to cross-check.
    "display_every": "display cadence only",
    "print_training_accuracy": "adds metric columns only",
    "benchmark_log_dir": "artifact sink path",
    "compilation_cache_dir": "cache directory path; any writable path "
                             "works with every mode (benchmark.py "
                             "derives <train_dir>/xla_cache when unset)",
    "benchmark_test_id": "artifact metadata string",
    "eval_dir": "artifact sink path",
    "eval_interval_secs": "eval-loop cadence only",
    "save_summaries_steps": "summary cadence only",
    # (summary_verbosity left this list when --shard_params began
    # cross-checking the tier-2 histogram surface.)
    "loss_type_to_report": "display column selector",
    "use_chrome_trace_format": "output-format toggle of the "
                               "--trace_events_file exporter (tracing.py:"
                               " Chrome trace-event JSON when true, raw "
                               "span JSONL when false); reference CLIs "
                               "also pass it with --trace_file, where it "
                               "stays inert (jax.profiler owns that "
                               "format), so no hard cross-check",
    "max_ckpts_to_keep": "checkpoint GC depth",
    "tf_random_seed": "seed value; any int is valid",
    "num_warmup_batches": "None = runtime default (benchmark.py:_run)",
    # Input pipeline knobs: consumed by data/ preprocessing with safe
    # fallbacks; no cross-flag interaction. (data_dir and
    # use_synthetic_gpu_images left this list when --packed_sequences
    # began cross-checking them.)
    "data_name": "dataset selector; inferred from data_dir when unset",
    "batch_group_size": "host pipeline batching depth",
    "distortions": "preprocessing toggle",
    "distort_color_in_yiq": "preprocessing toggle",
    "resize_method": "preprocessing method selector",
    "fuse_decode_and_crop": "preprocessing toggle",
    "input_preprocessor": "preprocessor selector (datasets resolve it)",
    "input_preprocessing_parallelism": "host thread count",
    "datasets_num_private_threads": "host thread count",
    "datasets_parallel_interleave_cycle_length": "accepted for reference "
                                                 "CLI parity; interleave "
                                                 "is TF-pipeline-only",
    "datasets_parallel_interleave_prefetch": "accepted for reference CLI "
                                             "parity; TF-pipeline-only",
    "datasets_prefetch_buffer_size": "feeder prefetch depth",
    "input_prefetch_depth": "explicit feeder prefetch depth override "
                            "(benchmark.feeder_prefetch); any depth "
                            ">= 1 is valid with every input path",
    "datasets_repeat_cached_sample": "pipeline toggle",
    "datasets_sloppy_parallel_interleave": "accepted for reference CLI "
                                           "parity; TF-pipeline-only",
    "datasets_use_caching": "pipeline toggle",
    "datasets_use_prefetch": "pipeline toggle",
    "use_multi_device_iterator": "accepted for reference CLI parity; the "
                                 "DeviceFeeder is the only input path",
    "multi_device_iterator_max_buffer_size": "accepted for reference CLI "
                                             "parity (see above)",
    # Telemetry knobs (PR 4): numeric thresholds with registry bounds;
    # engagement is validated through health_stats above.
    "health_grad_norm_sigma": "anomaly threshold (registry bounds only)",
    "flight_recorder_window": "ring size (registry bounds only)",
    "elastic_check_every_n_steps": "resize-poll cadence only",
    # Cluster wiring: free-form host lists/ids consumed by cluster.py;
    # the modes that REQUIRE them are validated via job_name above.
    "ps_hosts": "cluster wiring string (cluster.py)",
    "task_index": "cluster wiring index (cluster.py)",
    "process_index": "cluster wiring index (cluster.py)",
    "horovod_device": "accepted for reference CLI parity; TPU runs have "
                      "no per-process device pick",
    "server_protocol": "accepted for reference CLI parity; no grpc "
                       "server exists here",
    "sync_on_finish": "accepted for reference CLI parity; drain() is "
                      "unconditional at run end",
    # GPU/TF-graph knobs accepted for reference command-line parity but
    # inert on this backend (params.validate_params notes them; SURVEY
    # 5.6 library/CLI duality keeps reference invocations working).
    "allow_growth": "inert GPU allocator knob (reference parity)",
    "autotune_threshold": "inert TF autotune knob (reference parity)",
    "backbone_model_path": "SSD backbone restore path; model-private",
    "batchnorm_persistent": "inert cuDNN knob (reference parity)",
    "compute_lr_on_cpu": "inert placement knob (reference parity)",
    "enable_optimizations": "inert TF graph-option (reference parity)",
    "force_gpu_compatible": "inert GPU knob (reference parity)",
    "freeze_when_forward_only": "subsumed by aot_save_path validation "
                                "(the freeze analog)",
    "gpu_indices": "inert GPU knob (reference parity)",
    "gpu_memory_frac_for_testing": "inert GPU knob (reference parity)",
    "gpu_thread_mode": "inert GPU knob (reference parity)",
    "per_gpu_thread_count": "inert GPU knob (reference parity)",
    "kmp_affinity": "inert MKL env knob (reference parity)",
    "kmp_blocktime": "inert MKL env knob (reference parity)",
    "kmp_settings": "inert MKL env knob (reference parity)",
    "mkl": "inert MKL toggle (reference parity)",
    "num_inter_threads": "host thread pool size",
    "num_intra_threads": "host thread pool size",
    "rewriter_config": "inert TF graph-rewriter knob (reference parity)",
    "sparse_to_dense_grads": "inert: JAX grads are dense already",
    "use_python32_barrier": "inert TF threading knob (reference parity)",
    "use_resource_vars": "inert TF variable knob (reference parity)",
    "use_tf_layers": "builder always uses flax modules (reference parity)",
    "use_unified_memory": "inert GPU knob (reference parity)",
    "winograd_nonfused": "inert cuDNN env knob (reference parity)",
    "partitioned_graph_file_prefix": "inert TF graph-dump knob "
                                     "(reference parity)",
    "trt_max_workspace_size_bytes": "inert TRT knob; trt_mode itself IS "
                                    "validated above",
    "xla_compile": "legacy alias surface; use_xla_compile is the "
                   "validated switch",
    "allreduce_merge_scope": "reducer batching depth (ops/allreduce.py)",
    "agg_small_grads_max_group": "reducer group bound; engagement "
                                 "validated via agg_small_grads_max_bytes",
    "network_topology": "hierarchical-copy shape hint (ops/allreduce.py)",
    "local_parameter_device": "PS placement hint; no TPU cross-check",
}


def eval_during_training_enabled(params) -> bool:
  """Any of the four mid-training eval schedules set
  (ref: benchmark_cnn.py:1317-1327)."""
  return any(map(bool, [
      params.eval_during_training_every_n_steps,
      params.eval_during_training_every_n_epochs,
      params.eval_during_training_at_specified_steps,
      params.eval_during_training_at_specified_epochs,
  ]))


def validate_cross_flags(params) -> None:
  """Raise ParamError on inconsistent flag combinations."""
  p = params
  if p.eval:
    if p.forward_only:
      raise ParamError("--eval is incompatible with --forward_only "
                       "(ref :1269-1270)")
    if p.job_name:
      raise ParamError("--job_name is unsupported with --eval (ref :1273)")
  if p.num_batches is not None and p.num_epochs is not None:
    raise ParamError("At most one of --num_batches and --num_epochs may be "
                     "set (ref :1300-1303)")
  # Serving-engine knobs (bench.py --serving / serving_sweep --engine):
  # value-validated here so a bad ladder or policy fails at parse time,
  # not mid-serve. serving_max_new_tokens / serving_queue_depth /
  # serving_ttft_slo_ms / serving_tenant_tokens_per_s carry their whole
  # contract in the registry bounds (lower_bound), nothing to cross.
  if getattr(p, "serving_bucket_ladder", None):
    parse_bucket_ladder(p.serving_bucket_ladder)
  batching = getattr(p, "serving_batching", None)
  if batching is not None and batching not in ("continuous", "static"):
    raise ParamError(
        f"--serving_batching={batching!r}: expected 'continuous' "
        "(in-flight batching) or 'static' (batch-and-drain)")
  # Decode-cost variants (ISSUE 16). serving_quantize carries its
  # whole contract in the registry enum; the two below cross flags.
  page = getattr(p, "serving_kv_page_size", None)
  if page is not None:
    # The serving context length defaults to the zoo transformer_lm's
    # SEQ_LEN (serving/decode.py LMSpec.max_len); LMSpec.__post_init__
    # re-validates against the per-spec max_len when a caller
    # overrides it.
    from kf_benchmarks_tpu.models import transformer_lm as _lm
    if _lm.SEQ_LEN % page:
      raise ParamError(
          f"--serving_kv_page_size={page} must divide the serving "
          f"context length ({_lm.SEQ_LEN}): partial pages would break "
          "the page-table <-> ring position bijection "
          "(serving/decode.py)")
  spec_k = getattr(p, "serving_speculative_k", None)
  draft_layers = getattr(p, "serving_draft_layers", None)
  if spec_k is not None and draft_layers is None:
    raise ParamError(
        f"--serving_speculative_k={spec_k} requires a draft spec: set "
        "--serving_draft_layers (< the served model's layer count; "
        "serving/decode.py draft_spec)")
  if draft_layers is not None and spec_k is None:
    raise ParamError(
        f"--serving_draft_layers={draft_layers} is inert without "
        "--serving_speculative_k (the draft only runs inside "
        "speculative rounds)")
  if p.num_batches is not None and p.num_batches <= 0:
    raise ParamError("--num_batches must be positive")
  if (getattr(p, "steps_per_dispatch", 1) or 1) > 1:
    # Chunked dispatch wraps the TRAIN step in a device-resident scan
    # (train_step.py); eval/forward-only loops dispatch a stateless
    # forward per step and are not chunked (yet).
    if p.eval:
      raise ParamError("--steps_per_dispatch > 1 applies to training "
                       "only; it cannot be combined with --eval")
    if p.forward_only:
      raise ParamError("--steps_per_dispatch > 1 applies to training "
                       "only; it cannot be combined with --forward_only")
  if (getattr(p, "num_grad_accum", 1) or 1) > 1:
    m = p.num_grad_accum
    # Microbatching wraps the TRAIN step's forward/backward in a scan
    # (train_step.py); the modes below either have no gradient to
    # accumulate or consume gradients in a shape the scan cannot feed.
    if p.eval:
      raise ParamError("--num_grad_accum > 1 applies to training only; "
                       "it cannot be combined with --eval")
    if p.forward_only:
      raise ParamError("--num_grad_accum > 1 applies to training only; "
                       "it cannot be combined with --forward_only")
    if p.batch_size and p.batch_size % m:
      raise ParamError(
          f"--num_grad_accum={m} must divide --batch_size="
          f"{p.batch_size}: the step splits each per-device batch into "
          "M equal microbatches (a ragged tail microbatch would change "
          "the gradient weighting silently)")
    if p.staged_vars:
      raise ParamError(
          "--num_grad_accum > 1 cannot be combined with --staged_vars: "
          "staged reads hand the forward one-step-stale weights from a "
          "single staging slot per step (variable_mgr.py:246-274); "
          "microbatches would all read the same stale copy while the "
          "accumulated update lands once, making the effective "
          "staleness M-dependent in a way the reference semantics "
          "never defined")
    if (p.variable_update == "parameter_server"
        and not p.cross_replica_sync):
      raise ParamError(
          "--num_grad_accum > 1 cannot be combined with async "
          "parameter_server (--cross_replica_sync=false): the "
          "sequential-apply path consumes each replica's UNAVERAGED "
          "per-batch gradient (train_step.py sequential_apply); an "
          "accumulated mean-of-microbatches gradient would silently "
          "change what each of its n optimizer applications sees. Use "
          "a synchronous --variable_update with accumulation")
    if p.adaptive_batch_size:
      raise ParamError(
          "--num_grad_accum > 1 cannot be combined with "
          "--adaptive_batch_size: the policy re-picks the per-device "
          "batch mid-run and cannot guarantee divisibility by M")
  if getattr(p, "packed_sequences", False):
    # Packing re-shapes the LM input (tokens -> the (B, 3, T) packed
    # stack) and re-weights losses by real-token count; only the
    # segment-aware transformer_lm family consumes that form.
    if p.model != "transformer_lm":
      raise ParamError(
          "--packed_sequences is a transformer_lm input form (segment-"
          f"aware attention + weighted LM loss); got --model={p.model}. "
          "The CNN/speech/recsys families have no variable-length "
          "sequence axis to pack")
    if p.eval or p.forward_only:
      raise ParamError(
          "--packed_sequences applies to training only (the packed "
          "stream feeds the train loop); it cannot be combined with "
          "--eval or --forward_only")
    if p.data_dir and not p.use_synthetic_gpu_images:
      raise ParamError(
          "--packed_sequences draws documents from its seeded "
          "synthetic length distribution (data/packing.py); packing a "
          "real --data_dir corpus is not wired yet -- drop --data_dir "
          "or add --use_synthetic_gpu_images")
    # --elastic / --adaptive_batch_size compose: every reshape reopens
    # the input stream (benchmark._open_input), and the packer is
    # re-instantiated at the new row count/incarnation seed.
  if getattr(p, "autotuned_config", None) and (p.eval or p.forward_only):
    # The tuned table tunes the TRAINING step's program-shaping knobs
    # (--steps_per_dispatch and friends, analysis/autotune.py); applying
    # it to eval/forward-only would silently set training-only flags
    # (the round-1 ineffective-flag defect class, same rule as
    # --trace_events_file). benchmark.setup() re-checks before applying
    # so the failure names this flag, not the knob it would have set.
    raise ParamError(
        "--autotuned_config tunes the training step's program-shaping "
        "knobs (analysis/autotune.py); it cannot be combined with "
        "--eval or --forward_only")
  if getattr(p, "attn_block", None):
    if p.model != "transformer_lm":
      raise ParamError(
          "--attn_block sizes the transformer_lm attention tiling "
          f"(parallel/sequence.py); got --model={p.model}. The CNN/"
          "speech/recsys families have no attention blocks to tile")
    # Lazy import (the models package imports jax/flax; every caller of
    # cross-flag validation has them, but module import must stay light).
    from kf_benchmarks_tpu.models import transformer_lm as _lm
    if _lm.SEQ_LEN % p.attn_block:
      raise ParamError(
          f"--attn_block={p.attn_block} must divide the transformer_lm "
          f"sequence length {_lm.SEQ_LEN} (blockwise_attention tiles "
          "the K/V axis in whole blocks)")
  mesh_shape = getattr(p, "mesh_shape", None)
  sharded = bool(getattr(p, "shard_optimizer_state", False))
  if mesh_shape:
    b, m = parse_mesh_shape(mesh_shape)
    if b * m != p.num_devices:
      raise ParamError(
          f"--mesh_shape={mesh_shape} spans {b * m} devices but "
          f"--num_devices={p.num_devices}: the named 2-D mesh must "
          "cover exactly the requested devices")
    if m > 1 and not sharded:
      raise ParamError(
          f"--mesh_shape={mesh_shape}: a model axis > 1 requires "
          "--shard_optimizer_state -- without it the core step has no "
          "consumer for the axis and would silently duplicate every "
          "forward/backward M times")
  if sharded:
    # --shard_optimizer_state exclusion matrix. The sharded step
    # replaces the strategy's gradient pass with reduce-scatter +
    # all-gather and applies the optimizer on 1/n flat state shards
    # (ops/sharded.py); modes below either own gradient aggregation
    # themselves, need per-replica gradient trees the scatter never
    # materializes, or read full-tree state the shards no longer hold.
    if p.eval or p.forward_only:
      raise ParamError(
          "--shard_optimizer_state applies to training only (there is "
          "no optimizer state to shard in --eval/--forward_only)")
    if p.variable_update not in ("replicated", "parameter_server"):
      raise ParamError(
          "--shard_optimizer_state requires --variable_update="
          f"replicated or parameter_server (got {p.variable_update!r}): "
          "independent/gossip modes keep per-replica diverged state "
          "with no global reduction to scatter, and the distributed_* "
          "modes' multi-process worlds are not wired to the sharded "
          "checkpoint layout yet")
    if not p.cross_replica_sync:
      raise ParamError(
          "--shard_optimizer_state cannot be combined with async "
          "parameter_server (--cross_replica_sync=false): the "
          "sequential-apply path serializes each replica's UNAVERAGED "
          "gradient through one shared full state copy "
          "(train_step.py); sharded state has no such copy")
    if p.job_name or (p.worker_hosts or []) or (p.num_processes or 1) > 1:
      raise ParamError(
          "--shard_optimizer_state is single-process for now: the "
          "checkpoint path saves the sharded optimizer state from "
          "locally-addressable rows (checkpoint.py), which a "
          "multi-host mesh cannot do chief-only without a cross-host "
          "gather leg")
    if p.optimizer == "lars":
      raise ParamError(
          "--shard_optimizer_state cannot be combined with "
          "--optimizer=lars: the LARS trust ratio needs per-LAYER "
          "param/update norms, and the flat 1/n shard cuts across "
          "layer boundaries. Every other stock optimizer updates "
          "elementwise, so the shard apply stays exact")
    if p.staged_vars:
      raise ParamError(
          "--shard_optimizer_state cannot be combined with "
          "--staged_vars: staged reads keep a second full weight copy "
          "per device (variable_mgr.py:246-274), the exact footprint "
          "sharded state exists to retire")
    if p.variable_consistency == "relaxed":
      raise ParamError(
          "--shard_optimizer_state cannot be combined with "
          "--variable_consistency=relaxed: the deferred-gradient bank "
          "stores a full gradient tree per device "
          "(train_step.py buffers); banking shards instead would "
          "change the staleness semantics silently")
    if p.adaptive_batch_size or p.track_grad_noise_scale:
      raise ParamError(
          "--shard_optimizer_state cannot be combined with "
          "--adaptive_batch_size/--track_grad_noise_scale: the "
          "noise-scale estimator contrasts PRE-reduction per-replica "
          "gradients with their replica mean (elastic.py), and the "
          "scattered reduction never materializes the replica mean")
    if getattr(p, "overlap_gradient_reduction", False):
      raise ParamError(
          "--shard_optimizer_state cannot be combined with "
          "--overlap_gradient_reduction: the in-backward hooks issue "
          "bucket pmeans (all-reduce), which is exactly the collective "
          "the sharded path replaces with reduce-scatter")
    for flag, name in ((p.all_reduce_spec, "--all_reduce_spec"),
                       (p.gradient_repacking, "--gradient_repacking"),
                       (p.agg_small_grads_max_bytes > 0,
                        "--agg_small_grads_max_bytes"),
                       (p.hierarchical_copy, "--hierarchical_copy")):
      if flag:
        raise ParamError(
            f"--shard_optimizer_state cannot be combined with {name}: "
            "each reducer owns the reduction granularity (ref: "
            "batch_allreduce.py:300-317 selects one algorithm); the "
            "sharded path's reduction IS the per-leaf reduce-scatter")
    # --elastic composes since the cross-mesh rescale landed: a resize
    # re-slices the saved (n, k) shard stack onto the new topology
    # (checkpoint.py _reshard), preserving the model-axis width -- a
    # target the model axis does not divide is rejected at poll time,
    # not here (the target is only known mid-run).
    if p.health_stats:
      raise ParamError(
          "--health_stats cannot be combined with "
          "--shard_optimizer_state: the in-step stats read the full "
          "per-step update tree (telemetry.py health_partials), and "
          "the sharded apply only materializes this device's 1/n "
          "update shard. Drop the flag (auto-off with a note)")
  if getattr(p, "shard_params", False):
    # --shard_params (full FSDP): params join the optimizer state on
    # the (n, k) shard layout and re-assemble inside the compute
    # (train_step.py + ops/overlap.py). Requiring
    # --shard_optimizer_state makes the whole sharded exclusion matrix
    # above binding here too -- elementwise-optimizer family only (no
    # LARS), synchronous replicated/parameter_server only (no
    # async-PS, no independent/gossip), no staged vars / relaxed
    # consistency / overlap reducers, single-process.
    if not sharded:
      raise ParamError(
          "--shard_params requires --shard_optimizer_state: the FSDP "
          "forward rides the sharded family's scatter/apply machinery "
          "(reduce-scatter mean, 1/n shard apply, the (n, k) "
          "checkpoint layout), and params-sharded-but-state-replicated "
          "would re-create exactly the per-device footprint ZeRO "
          "removes. Add --shard_optimizer_state (which also brings its "
          "exclusion matrix: elementwise optimizers only, synchronous "
          "replicated/parameter_server only, no --staged_vars)")
    if (p.summary_verbosity or 0) >= 2:
      raise ParamError(
          "--summary_verbosity >= 2 cannot be combined with "
          "--shard_params: the tier-2 parameter histograms read the "
          "replica-0 FULL parameter tree (observability.py "
          "write_histograms), which the FSDP layout stores as 1/n "
          "flat shards -- the histograms would silently describe one "
          "shard. Use verbosity 1 (scalars) or drop --shard_params "
          "for histogram debugging")
  if getattr(p, "partitioner", None) == "gspmd":
    # --partitioner=gspmd cross-flag matrix. The compiler-partitioned
    # twin (train_step.py) covers programs whose collectives are
    # PARTITIONING choices -- the sharded training families
    # (--shard_optimizer_state [+ --shard_params]) and the serving
    # decode leg (--serving_model_shards). Modes whose collectives ARE
    # the semantics stay manual-only and are rejected here with the
    # reason; note most also fall out of the sharded matrix above, but
    # a bare --partitioner=gspmd with one of them set deserves the
    # specific message, not the generic requires-sharded one.
    if p.staged_vars:
      raise ParamError(
          "--partitioner=gspmd cannot be combined with --staged_vars: "
          "the staging double-buffer is a hand-placed staleness "
          "pattern (variable_mgr.py:246-274), not a partitioning "
          "choice -- there is nothing for GSPMD to re-place")
    if p.variable_update == "independent":
      raise ParamError(
          "--partitioner=gspmd cannot be combined with "
          "--variable_update=independent: independent replicas run NO "
          "collectives at all; a partitioner twin would have an empty "
          "inventory to referee")
    if p.variable_update == "kungfu" and p.kungfu_option != "sync_sgd":
      raise ParamError(
          "--partitioner=gspmd cannot be combined with the gossip "
          f"modes (--kungfu_option={p.kungfu_option}): pair-averaging "
          "ppermutes and SMA weight pmeans are semantic hand "
          "placements (parallel/strategies.py), not compiler-"
          "placeable data movement")
    if (p.variable_update == "parameter_server"
        and not p.cross_replica_sync):
      raise ParamError(
          "--partitioner=gspmd cannot be combined with async "
          "parameter_server (--cross_replica_sync=false): the "
          "sequential-apply scan consumes per-replica UNAVERAGED "
          "gradients in replica order -- the collective order IS the "
          "semantics there")
    if p.hierarchical_copy or p.all_reduce_spec:
      raise ParamError(
          "--partitioner=gspmd cannot be combined with "
          "--hierarchical_copy/--all_reduce_spec: the hierarchical/"
          "spec'd reducers hand-pick the reduction algorithm (ref: "
          "batch_allreduce.py:300-317), which is exactly the choice "
          "gspmd delegates to the compiler")
    if not bool(getattr(p, "shard_optimizer_state", False)) and \
        not getattr(p, "serving_model_shards", None):
      raise ParamError(
          "--partitioner=gspmd covers the sharded training families "
          "(--shard_optimizer_state [+ --shard_params]) and the "
          "tensor-parallel serving leg (--serving_model_shards): the "
          "replicated 1-D program has no NamedSharding-annotated "
          "state for GSPMD to partition (train_step.py)")
  shards_tp = getattr(p, "serving_model_shards", None)
  if shards_tp:
    # Tensor-parallel serving (serving/decode.py model_shardings): the
    # head axis of the attention KV cache and the sharded weight
    # matrices split M ways, so M must divide both the head count and
    # the device pool the serving mesh draws from.
    from kf_benchmarks_tpu.models import transformer_lm as _lm
    if _lm.N_HEADS % shards_tp:
      raise ParamError(
          f"--serving_model_shards={shards_tp} must divide the served "
          f"LM's head count ({_lm.N_HEADS}): the KV cache and "
          "attention projections shard on the head axis "
          "(serving/decode.py model_shardings)")
    if p.num_devices % shards_tp:
      raise ParamError(
          f"--serving_model_shards={shards_tp} must divide "
          f"--num_devices={p.num_devices}: the serving 'model' mesh "
          "draws whole devices")
  if getattr(p, "fault_schedule", None):
    # Malformed schedules fail at startup, not at the named step: a
    # fault harness that silently skips its fault proves nothing.
    from kf_benchmarks_tpu import faults
    try:
      entries = faults.parse_schedule(p.fault_schedule)
    except faults.FaultScheduleError as e:
      raise ParamError(str(e))
    if any(f.kind == "corrupt_ckpt" for f in entries) and not p.train_dir:
      raise ParamError(
          "--fault_schedule=corrupt_ckpt@... requires --train_dir: "
          "there is no checkpoint to corrupt without one")
    if any(f.kind in ("kill", "sigterm") for f in entries) \
        and not p.train_dir:
      raise ParamError(
          "--fault_schedule kill/sigterm entries require --train_dir: "
          "the one-shot-across-generations marker lives there "
          "(faults.py) -- without it every relaunched generation "
          "re-kills itself at the same step, and there is no "
          "checkpoint to rejoin from anyway")
    if any(f.kind == "drop_msg" for f in entries) and not p.elastic:
      raise ParamError(
          "--fault_schedule=drop_msg@... requires --elastic: the fault "
          "suppresses a coordination-service poll, and without elastic "
          "polling there is no message to drop -- the injection would "
          "log success while testing nothing")
    if any(f.kind == "heartbeat_delay" for f in entries) and (
        not p.stall_watchdog_factor or
        not (p.train_dir or p.health_stats)):
      raise ParamError(
          "--fault_schedule=heartbeat_delay@... requires a live stall "
          "watchdog to starve: --stall_watchdog_factor > 0 plus a "
          "telemetry session (--train_dir, or explicit --health_stats) "
          "-- otherwise the injected silence is observed by nothing")
    if p.eval or p.forward_only:
      raise ParamError(
          "--fault_schedule applies to training runs only (the faults "
          "fire at train-dispatch boundaries); it cannot be combined "
          "with --eval or --forward_only")
  if (p.adaptive_batch_size and
      p.adaptive_batch_min > p.adaptive_batch_max):
    raise ParamError(
        f"--adaptive_batch_min={p.adaptive_batch_min} exceeds "
        f"--adaptive_batch_max={p.adaptive_batch_max}: the adaptive "
        "policy's search interval is empty")
  if p.num_epochs is not None and p.num_epochs <= 0:
    raise ParamError("--num_epochs must be positive")
  if p.num_eval_batches is not None and p.num_eval_epochs is not None:
    raise ParamError("At most one of --num_eval_batches and "
                     "--num_eval_epochs may be set (ref "
                     "get_num_batches_and_epochs, :782-800)")
  if p.num_eval_batches is not None and p.num_eval_batches <= 0:
    raise ParamError("--num_eval_batches must be positive")
  if p.num_eval_epochs is not None and p.num_eval_epochs <= 0:
    raise ParamError("--num_eval_epochs must be positive")
  if p.coordinator_address and ":" not in p.coordinator_address:
    raise ParamError("--coordinator_address must be host:port "
                     f"(got {p.coordinator_address!r})")
  if p.forward_only and p.variable_update in ("distributed_replicated",
                                              "distributed_all_reduce",
                                              "collective_all_reduce"):
    raise ParamError(f"--forward_only cannot be used with "
                     f"--variable_update={p.variable_update} (ref :1306-1310)")
  if p.variable_update in ("horovod", "kungfu"):
    # The reference requires one GPU per process for external DP runtimes
    # (ref :1287-1297). On TPU the SPMD program owns every local chip, so we
    # relax the device-count rule but keep the job_name exclusion.
    if p.job_name:
      raise ParamError(f"--job_name is incompatible with "
                       f"--variable_update={p.variable_update} "
                       f"(ref :1293-1297)")
  if p.variable_update == "distributed_replicated":
    if not p.job_name:
      raise ParamError("distributed_replicated requires --job_name "
                       "(ref :1311-1314)")
    if not p.cross_replica_sync:
      raise ParamError("distributed_replicated requires "
                       "--cross_replica_sync=true (ref :1315-1318)")
  if p.variable_update == "distributed_all_reduce" and not p.all_reduce_spec:
    raise ParamError("distributed_all_reduce requires --all_reduce_spec "
                     "(ref :1319-1321)")
  if p.fp16_vars and not p.use_fp16:
    raise ParamError("--fp16_vars requires --use_fp16 (ref :1330-1331)")
  if p.fp16_vars and p.gradient_repacking:
    raise ParamError("--fp16_vars cannot be used with --gradient_repacking "
                     "(ref :1284-1285)")
  if p.fp16_enable_auto_loss_scale and not p.use_fp16:
    raise ParamError("--fp16_enable_auto_loss_scale requires --use_fp16 "
                     "(ref :1334-1336)")
  if (p.variable_update == "parameter_server" and not p.cross_replica_sync
      and p.optimizer != "sgd"
      and p.num_devices > ASYNC_PS_SEQUENTIAL_MAX_DEVICES):
    # Async PS + stateful optimizer serializes every replica's gradient
    # through the shared optimizer state: O(n) optimizer applications per
    # step and an O(n * |grads|) all-gather (train_step.py
    # sequential_apply). Faithful to the PS semantics but a CORRECTNESS
    # mode -- at pod scale the scan alone would dominate the step and the
    # gather may not fit HBM, so large worlds are rejected up front
    # (VERDICT r3 weak #4). SGD is exempt: N sequential applications
    # collapse exactly into one summed update.
    raise ParamError(
        "async parameter_server (--cross_replica_sync=false) with a "
        f"stateful optimizer ({p.optimizer}) applies num_devices "
        "optimizer updates sequentially through shared state each step; "
        f"capped at {ASYNC_PS_SEQUENTIAL_MAX_DEVICES} devices. Use "
        "--optimizer=sgd (exact single-update collapse) or a "
        "synchronous --variable_update at this scale")
  if p.staged_vars and p.variable_update != "parameter_server":
    raise ParamError("--staged_vars is only supported with "
                     "--variable_update=parameter_server (ref :1478-1479)")
  if p.staged_vars and p.fp16_enable_auto_loss_scale:
    raise ParamError("Automatic loss scaling is not supported with "
                     "--staged_vars (ref :1304-1305)")
  if p.staged_vars and eval_during_training_enabled(p):
    raise ParamError("--eval_during_training_* is not compatible with "
                     "--staged_vars (ref :1335-1336)")
  if p.variable_consistency == "relaxed" and p.variable_update not in (
      "replicated", "distributed_replicated", "parameter_server",
      "collective_all_reduce", "distributed_all_reduce"):
    raise ParamError(
        "--variable_consistency=relaxed requires a replicated-family "
        "--variable_update (the deferral lives in the batched all-reduce, "
        "ref: batch_allreduce.py:32-153; independent/kungfu/horovod "
        "reduce outside it)")
  if (p.use_fp16 and p.fp16_enable_auto_loss_scale and
      p.variable_update not in ("parameter_server", "replicated",
                                "independent", "kungfu")):
    # Ref restricts auto loss scaling to ps/replicated/independent
    # (ref :1299-1303); kungfu is additionally allowed here because the
    # SPMD state machine makes the finite-decision replica-uniform via
    # pmin (train_step.py), which the reference's chief-only check could
    # not do for externally-reduced modes.
    raise ParamError("Automatic loss scaling is not supported with "
                     f"--variable_update={p.variable_update} (ref :1299-1303)")
  if p.hierarchical_copy and p.num_devices <= 1:
    raise ParamError("--hierarchical_copy requires more than one device "
                     "(ref :1310-1311)")
  if bool(p.learning_rate_decay_factor) != bool(p.num_epochs_per_decay):
    raise ParamError("--learning_rate_decay_factor and "
                     "--num_epochs_per_decay must be set together "
                     "(ref :1271-1277)")
  if p.learning_rate_decay_factor and p.init_learning_rate is None:
    raise ParamError("LR decay flags require --init_learning_rate "
                     "(ref :1271-1277)")
  if p.minimum_learning_rate and not (p.learning_rate_decay_factor and
                                      p.num_epochs_per_decay and
                                      p.init_learning_rate is not None):
    raise ParamError("--minimum_learning_rate requires "
                     "--init_learning_rate, --learning_rate_decay_factor "
                     "and --num_epochs_per_decay (ref :445-449, :1143-1146)")
  if p.piecewise_learning_rate_schedule and p.init_learning_rate is not None:
    raise ParamError("--piecewise_learning_rate_schedule cannot be combined "
                     "with --init_learning_rate (ref :1104-1120)")
  if (p.piecewise_learning_rate_schedule and
      (p.learning_rate_decay_factor or p.num_learning_rate_warmup_epochs)):
    raise ParamError("--piecewise_learning_rate_schedule cannot be combined "
                     "with decay/warmup flags (ref :1116-1120)")
  edt_flags = [p.eval_during_training_every_n_steps,
               p.eval_during_training_every_n_epochs,
               p.eval_during_training_at_specified_steps,
               p.eval_during_training_at_specified_epochs]
  if sum(map(bool, edt_flags)) > 1:
    raise ParamError("At most one --eval_during_training_* flag may be "
                     "specified (ref :1316-1325)")
  if eval_during_training_enabled(p):
    if p.eval:
      raise ParamError("eval-during-training flags are incompatible with "
                       "--eval (ref :1329-1330)")
    if p.forward_only:
      raise ParamError("eval-during-training flags are incompatible with "
                       "--forward_only (ref :1331-1332)")
    if p.job_name:
      raise ParamError("--eval_during_training_* is not supported in "
                       "distributed ps/controller mode (ref :1333-1334)")
  if p.stop_at_top_1_accuracy and not eval_during_training_enabled(p):
    # The reference allows it only with eval-during-training (ref :1339-1340).
    raise ParamError("--stop_at_top_1_accuracy requires eval-during-training "
                     "(ref :1339-1340)")
  if p.save_model_secs and p.save_model_steps:
    raise ParamError("At most one of --save_model_secs and "
                     "--save_model_steps may be set (ref :1341-1344)")
  if p.forward_only and p.job_name == "controller":
    raise ParamError("--forward_only is incompatible with controller jobs")
  if p.device == "cpu" and p.data_format == "NCHW":
    raise ParamError("NCHW is not supported on cpu device (ref :1323-1326)")
  if p.controller_host:
    raise ParamError(
        "--controller_host: the controller role has no TPU analog -- "
        "distributed_all_reduce's single-session graph maps to the flat "
        "SPMD program every worker runs (SURVEY 5.8; ref :576)")
  if getattr(p, "debugger", None):
    raise ParamError("--debugger: tfdbg has no TPU analog "
                     "(ref :370-377); use --trace_file / --tfprof_file "
                     "for profiling and --graph_file for program dumps")
  trt_mode = (getattr(p, "trt_mode", "") or "").upper()
  if trt_mode and trt_mode not in ("FP32", "FP16", "INT8"):
    raise ParamError(f"--trt_mode: unknown mode {p.trt_mode!r}; the "
                     "serving-export precisions are FP32, FP16, INT8 "
                     "(ref :615-620)")
  if trt_mode and not getattr(p, "aot_save_path", None):
    raise ParamError("--trt_mode sets the precision of the frozen "
                     "serving export and requires --forward_only with "
                     "--aot_save_path (the TRT conversion analog, ref "
                     ":615-620, :2466-2486)")
  if getattr(p, "trace_events_file", None) and (p.eval or p.forward_only):
    # The span timeline instruments the TRAINING loop's wall-clock
    # boundaries (feed, dispatch, compile, checkpoint, elastic seams);
    # the eval/forward-only drivers carry none of them, and silently
    # accepting the flag there would log success while tracing nothing
    # (the round-1 ineffective-flag defect class).
    raise ParamError(
        "--trace_events_file instruments training runs only (the span "
        "timeline covers the train loop's feed/dispatch/compile/"
        "checkpoint/elastic boundaries, tracing.py); it cannot be "
        "combined with --eval or --forward_only. The jax.profiler "
        "--trace_file capture works in every mode")
  if getattr(p, "metrics_port", None) and (p.eval or p.forward_only):
    # The live endpoint serves the TRAIN loop's registry session
    # (benchmark.py binds it around _train_loop); accepting the flag in
    # eval/forward-only would bind nothing and log success while
    # serving nothing (the round-1 ineffective-flag defect class, same
    # rule as --trace_events_file above).
    raise ParamError(
        "--metrics_port serves the training loop's metric registry "
        "(metrics.py); it cannot be combined with --eval or "
        "--forward_only")
  if getattr(p, "run_store_dir", None) and (p.eval or p.forward_only):
    raise ParamError(
        "--run_store_dir appends the TRAINING run's record to the "
        "run store (metrics.py RunStore, written at train-loop end); "
        "it cannot be combined with --eval or --forward_only. The "
        "bench/serving records come from bench.py, which owns its own "
        "store path")
  if p.aot_load_path and not p.forward_only:
    raise ParamError("--aot_load_path requires --forward_only (the "
                     "frozen artifact has no training program; ref: "
                     "TRT serving path, benchmark_cnn.py:2405-2525)")
  if p.aot_save_path and not p.forward_only:
    raise ParamError("--aot_save_path requires --forward_only (the "
                     "export freezes the inference program, the analog "
                     "of the reference's forward-only graph freeze; ref: "
                     "benchmark_cnn.py:2405-2525)")
  if p.aot_load_path and p.aot_save_path:
    raise ParamError("At most one of --aot_load_path and --aot_save_path "
                     "may be set")
  if not p.use_xla_compile:
    raise ParamError(
        "--use_xla_compile=false is unsupported: every step function is "
        "jitted -- XLA compilation IS the TPU execution model (the "
        "reference's per-tower xla.compile toggle, ref :413-416, has no "
        "non-XLA fallback here)")
  if not p.use_datasets:
    raise ParamError(
        "--use_datasets=false is unsupported: the framework has a single "
        "host input pipeline (the reference's legacy RecordInput path, "
        "ref :215-217/:601-617, has no TPU analog)")
  if p.gradient_repacking and p.all_reduce_spec:
    raise ParamError(
        "--gradient_repacking cannot be combined with --all_reduce_spec "
        "(repacking re-splits the full gradient vector; the spec planner "
        "owns packing on the spec path -- ref: batch_allreduce.py:300-317)")
  if p.gradient_repacking and p.agg_small_grads_max_bytes > 0:
    raise ParamError(
        "--gradient_repacking cannot be combined with "
        "--agg_small_grads_max_bytes (both re-shape reduction granularity)")
  if p.hierarchical_copy and p.all_reduce_spec:
    raise ParamError(
        "--hierarchical_copy cannot be combined with --all_reduce_spec "
        "(use the 'hier' algorithm inside the spec instead; "
        "ref :507-513 vs :532-553)")
  if getattr(p, "compact_gradient_transfer_f32", False):
    if not p.compact_gradient_transfer:
      raise ParamError(
          "--compact_gradient_transfer_f32 requires "
          "--compact_gradient_transfer: it widens WHEN the 16-bit wire "
          "format engages (f32 training too), it cannot engage a "
          "compaction that is switched off")
    if not (p.use_fp16 or p.all_reduce_spec or p.gradient_repacking
            or p.agg_small_grads_max_bytes > 0 or p.hierarchical_copy
            or getattr(p, "overlap_gradient_reduction", False)):
      raise ParamError(
          "--compact_gradient_transfer_f32 has no effect without a "
          "reduction path that repacks the wire: the default per-leaf "
          "pmean never re-encodes gradients (ops/allreduce.py "
          "build_reducer returns None). Select a packed path -- "
          "--overlap_gradient_reduction, --all_reduce_spec, "
          "--gradient_repacking, --agg_small_grads_max_bytes or "
          "--hierarchical_copy -- or drop the flag (a silent no-op "
          "that logs a halved-bytes note would misrecord the run)")
  if getattr(p, "reduce_bucket_mb", None) and \
      not (getattr(p, "overlap_gradient_reduction", False)
           or getattr(p, "shard_params", False)):
    raise ParamError(
        "--reduce_bucket_mb sizes the in-backward collective buckets "
        "and requires --overlap_gradient_reduction (reduction buckets) "
        "or --shard_params (FSDP gather buckets); the post-hoc paths' "
        "granularity levers are --gradient_repacking / "
        "--agg_small_grads_max_bytes / --all_reduce_spec")
  if getattr(p, "overlap_gradient_reduction", False):
    # In-backward reduction replaces the strategy's post-hoc gradient
    # pass with per-bucket pmeans issued inside the backward; it is
    # therefore only defined for strategies whose aggregation IS the
    # replica mean, and it cannot coexist with reducers that own
    # reduction granularity themselves (ref: batch_allreduce.py:300-317
    # selects exactly one algorithm).
    if p.variable_update not in ("replicated", "distributed_replicated",
                                 "parameter_server",
                                 "collective_all_reduce",
                                 "distributed_all_reduce", "horovod"):
      raise ParamError(
          "--overlap_gradient_reduction requires a replicated-family "
          f"--variable_update (got {p.variable_update!r}): "
          "independent/gossip modes have no gradient reduction to "
          "overlap")
    if p.variable_update == "parameter_server" and not p.cross_replica_sync:
      raise ParamError(
          "--overlap_gradient_reduction cannot be combined with async "
          "parameter_server (--cross_replica_sync=false): the async path "
          "consumes each replica's UNAVERAGED gradient (train_step.py "
          "sequential_apply / psum-sum collapse); in-backward pmeans "
          "would silently average them. Use a synchronous "
          "--variable_update")
    for flag, name in ((p.all_reduce_spec, "--all_reduce_spec"),
                       (p.gradient_repacking, "--gradient_repacking"),
                       (p.agg_small_grads_max_bytes > 0,
                        "--agg_small_grads_max_bytes"),
                       (p.hierarchical_copy, "--hierarchical_copy")):
      if flag:
        raise ParamError(
            f"--overlap_gradient_reduction cannot be combined with "
            f"{name}: each reducer owns the reduction granularity "
            "(ref: batch_allreduce.py:300-317 selects one algorithm); "
            "the overlap path's granularity lever is --reduce_bucket_mb")
    if p.track_grad_noise_scale:
      raise ParamError(
          "--overlap_gradient_reduction cannot be combined with "
          "--track_grad_noise_scale: the noise-scale estimator contrasts "
          "PRE-reduction per-replica gradients with their replica mean "
          "(elastic.noise_scale_stats), and in-backward reduction never "
          "materializes the pre-reduction tree. Cost of the exclusion: "
          "use the post-hoc default when monitoring noise scale")
  if getattr(p, "health_stats", None):
    # Explicit --health_stats (unset = auto-resolve, telemetry.py): the
    # in-step stats read the APPLIED gradient tree and are only global
    # values when that tree is replica-identical -- i.e. when the
    # strategy reduces gradients replica-synchronously. Modes below
    # would silently report replica-LOCAL norms as global health.
    if p.eval or p.forward_only:
      raise ParamError(
          "--health_stats applies to training only (the stats are "
          "computed from the step's gradient tree); it cannot be "
          "combined with --eval or --forward_only")
    if p.variable_update == "independent":
      raise ParamError(
          "--health_stats requires replica-synchronous gradient "
          "reduction: --variable_update=independent never reduces, so "
          "each replica's 'global' grad norm would be its own local "
          "one. Drop the flag (auto-off) or use a replicated-family "
          "mode")
    if p.variable_update == "kungfu" and p.kungfu_option != "sync_sgd":
      raise ParamError(
          "--health_stats cannot be combined with --kungfu_option="
          f"{p.kungfu_option}: gossip/model-averaging modes keep "
          "per-replica gradient trees (parallel/strategies.py); only "
          "sync_sgd reduces replica-synchronously")
    if p.variable_update == "parameter_server" and not p.cross_replica_sync:
      raise ParamError(
          "--health_stats cannot be combined with async "
          "parameter_server (--cross_replica_sync=false): the "
          "sequential-apply path consumes each replica's UNAVERAGED "
          "gradient (train_step.py sequential_apply), so no replica-"
          "identical reduced tree exists for the stats to read")
  if p.hierarchical_copy and p.gradient_repacking:
    raise ParamError(
        "--hierarchical_copy cannot be combined with --gradient_repacking "
        "(ref: batch_allreduce.py:300-317 selects one algorithm)")
  if p.hierarchical_copy and p.agg_small_grads_max_bytes > 0:
    raise ParamError(
        "--hierarchical_copy cannot be combined with "
        "--agg_small_grads_max_bytes "
        "(ref: batch_allreduce.py:300-317 selects one algorithm)")
