"""Cross-flag validation rules.

The reference scatters ~35 cross-flag checks through BenchmarkCNN.__init__
(ref: benchmark_cnn.py:1268-1352); here they are standalone, unit-testable
validators run before the runtime is constructed (SURVEY 7.1).
"""

from __future__ import annotations


class ParamError(ValueError):
  pass


def validate_cross_flags(params) -> None:
  """Raise ParamError on inconsistent flag combinations."""
  p = params
  if p.eval:
    if p.forward_only:
      raise ParamError("--eval is incompatible with --forward_only "
                       "(ref :1269-1270)")
    if p.job_name:
      raise ParamError("--job_name is unsupported with --eval (ref :1273)")
  if p.num_batches is not None and p.num_epochs is not None:
    raise ParamError("At most one of --num_batches and --num_epochs may be "
                     "set (ref :1300-1303)")
  if p.num_batches is not None and p.num_batches <= 0:
    raise ParamError("--num_batches must be positive")
  if p.num_epochs is not None and p.num_epochs <= 0:
    raise ParamError("--num_epochs must be positive")
  if p.forward_only and p.variable_update in ("distributed_replicated",
                                              "distributed_all_reduce",
                                              "collective_all_reduce"):
    raise ParamError(f"--forward_only cannot be used with "
                     f"--variable_update={p.variable_update} (ref :1306-1310)")
  if p.variable_update in ("horovod", "kungfu"):
    # The reference requires one GPU per process for external DP runtimes
    # (ref :1287-1297). On TPU the SPMD program owns every local chip, so we
    # relax the device-count rule but keep the job_name exclusion.
    if p.job_name:
      raise ParamError(f"--job_name is incompatible with "
                       f"--variable_update={p.variable_update} "
                       f"(ref :1293-1297)")
  if p.variable_update == "distributed_replicated":
    if not p.job_name:
      raise ParamError("distributed_replicated requires --job_name "
                       "(ref :1311-1314)")
    if not p.cross_replica_sync:
      raise ParamError("distributed_replicated requires "
                       "--cross_replica_sync=true (ref :1315-1318)")
  if p.variable_update == "distributed_all_reduce" and not p.all_reduce_spec:
    raise ParamError("distributed_all_reduce requires --all_reduce_spec "
                     "(ref :1319-1321)")
  if p.fp16_vars and not p.use_fp16:
    raise ParamError("--fp16_vars requires --use_fp16 (ref :1330-1331)")
  if p.fp16_enable_auto_loss_scale and not p.use_fp16:
    raise ParamError("--fp16_enable_auto_loss_scale requires --use_fp16 "
                     "(ref :1334-1336)")
  if bool(p.learning_rate_decay_factor) != bool(p.num_epochs_per_decay):
    raise ParamError("--learning_rate_decay_factor and "
                     "--num_epochs_per_decay must be set together "
                     "(ref :1271-1277)")
  if p.learning_rate_decay_factor and p.init_learning_rate is None:
    raise ParamError("LR decay flags require --init_learning_rate "
                     "(ref :1271-1277)")
  if p.minimum_learning_rate and not (p.learning_rate_decay_factor and
                                      p.num_epochs_per_decay and
                                      p.init_learning_rate is not None):
    raise ParamError("--minimum_learning_rate requires "
                     "--init_learning_rate, --learning_rate_decay_factor "
                     "and --num_epochs_per_decay (ref :445-449, :1143-1146)")
  if p.piecewise_learning_rate_schedule and p.init_learning_rate is not None:
    raise ParamError("--piecewise_learning_rate_schedule cannot be combined "
                     "with --init_learning_rate (ref :1104-1120)")
  if (p.piecewise_learning_rate_schedule and
      (p.learning_rate_decay_factor or p.num_learning_rate_warmup_epochs)):
    raise ParamError("--piecewise_learning_rate_schedule cannot be combined "
                     "with decay/warmup flags (ref :1116-1120)")
  if p.eval_during_training_every_n_steps and p.eval:
    raise ParamError("eval-during-training flags are incompatible with "
                     "--eval (ref :1276-1280)")
  if p.stop_at_top_1_accuracy and not p.eval_during_training_every_n_steps:
    # The reference allows it only with eval-during-training (ref :1281-1286).
    raise ParamError("--stop_at_top_1_accuracy requires eval-during-training "
                     "(ref :1281-1286)")
  if p.save_model_secs and p.save_model_steps:
    raise ParamError("At most one of --save_model_secs and "
                     "--save_model_steps may be set (ref :1341-1344)")
  if p.forward_only and p.job_name == "controller":
    raise ParamError("--forward_only is incompatible with controller jobs")
  if p.device == "cpu" and p.data_format == "NCHW":
    raise ParamError("NCHW is not supported on cpu device (ref :1323-1326)")
