"""AOT export of the forward (serving) program -- the TensorRT-path analog.

The reference's forward-only mode freezes variables into constants and
optionally converts the graph with TensorRT for serving speed (ref:
scripts/tf_cnn_benchmarks/benchmark_cnn.py:2405-2525 _preprocess_graph,
--trt_mode :615-620). The XLA-native equivalent is ahead-of-time
lowering + serialization via jax.export: the jitted eval step is
compiled for the target platform and written as a portable artifact that
later processes deserialize and call without retracing Python.

Freezing == closing the exported function over the trained variables
(they become constants in the serialized module), exactly the
variables-to-constants step of the reference.
"""

from __future__ import annotations

import os
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export


def export_forward(model, variables, batch_size: int, path: str,
                   nclass: int = 1001, dtype=jnp.float32,
                   quantize: bool = False) -> int:
  """Serialize the frozen forward pass to ``path``; returns byte size.

  ``variables`` (trained params + batch stats) are captured as constants
  (the freeze step); the exported module takes only the input batch.
  ``quantize`` stores the large kernels as int8 + per-channel scales
  and dequantizes inside the program -- the TRT INT8 analog
  (quantization.py; ref --trt_mode :615-620, conversion :2466-2486).
  """
  model.set_batch_size(batch_size)
  module = model.make_module(nclass=nclass, phase_train=False,
                             data_format="NHWC", dtype=dtype,
                             param_dtype=jnp.float32)

  if quantize:
    from kf_benchmarks_tpu import quantization
    variables = quantization.quantize_variables(variables)

  def frozen_forward(images):
    if quantize:
      from kf_benchmarks_tpu import quantization
      fvars = quantization.dequantize_variables(variables, jnp.float32)
    else:
      fvars = variables
    logits, _ = module.apply(fvars, images)
    return logits

  image_shape = tuple(model.get_input_shapes("eval")[0])
  spec = jax.ShapeDtypeStruct(image_shape, jnp.float32)
  exported = jax_export.export(jax.jit(frozen_forward))(spec)
  data = exported.serialize()
  os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
  with open(path, "wb") as f:
    f.write(data)
  return len(data)


def load_forward(path: str) -> Callable:
  """Deserialize an exported forward program into a callable."""
  with open(path, "rb") as f:
    exported = jax_export.deserialize(f.read())
  return exported.call
