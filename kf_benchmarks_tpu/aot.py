"""AOT export of the forward (serving) program -- the TensorRT-path analog.

The reference's forward-only mode freezes variables into constants and
optionally converts the graph with TensorRT for serving speed (ref:
scripts/tf_cnn_benchmarks/benchmark_cnn.py:2405-2525 _preprocess_graph,
--trt_mode :615-620). The XLA-native equivalent is ahead-of-time
lowering + serialization via jax.export: the jitted eval step is
compiled for the target platform and written as a portable artifact that
later processes deserialize and call without retracing Python.

Freezing == closing the exported function over the trained variables
(they become constants in the serialized module), exactly the
variables-to-constants step of the reference.

Every export carries a JSON signature sidecar (``<path>.sig.json``):
input shape/dtype, batch size, and the config fingerprint
(analysis/baseline.config_fingerprint_key) of the exporting run -- so a
serving process can validate a requested batch against what was
actually exported and fail with the AVAILABLE export list (the bucket
ladder, when a sweep exported several sizes) instead of an opaque XLA
arity error deep in the call.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import export as jax_export

SIGNATURE_SUFFIX = ".sig.json"
SIGNATURE_VERSION = 1


def signature_path(path: str) -> str:
  return path + SIGNATURE_SUFFIX


def _write_signature(path: str, image_shape, dtype, *, quantize: bool,
                     nclass: int, fingerprint: Optional[str],
                     kv_page_size: Optional[int] = None) -> None:
  sig = {
      "version": SIGNATURE_VERSION,
      "input_shape": [int(d) for d in image_shape],
      "input_dtype": jnp.dtype(jnp.float32).name,
      "batch_size": int(image_shape[0]),
      "nclass": int(nclass),
      "dtype": jnp.dtype(dtype).name,
      "quantize": bool(quantize),
      # Round 19: the serving-mode identity a loader can diff against
      # BEFORE deserializing -- a bf16 engine pointed at an INT8 export
      # (or a paged engine at a dense one) fails with this sidecar
      # diff, not a dtype/shape mismatch deep inside the XLA call.
      "quantize_mode": "int8" if quantize else None,
      "kv_page_size": int(kv_page_size) if kv_page_size else None,
      "fingerprint": fingerprint,
  }
  with open(signature_path(path), "w", encoding="utf-8") as f:
    json.dump(sig, f, indent=2, sort_keys=True)
    f.write("\n")


def read_signature(path: str) -> Optional[Dict[str, Any]]:
  """The export's signature sidecar, or None when absent/unreadable
  (pre-sidecar artifacts stay loadable)."""
  try:
    with open(signature_path(path), encoding="utf-8") as f:
      sig = json.load(f)
  except (OSError, ValueError):
    return None
  return sig if isinstance(sig, dict) else None


def sibling_batch_sizes(path: str) -> List[int]:
  """Batch sizes of every export signature in ``path``'s directory --
  the available bucket list a mis-sized load error reports (a serving
  sweep exports one artifact per ladder bucket side by side)."""
  out = []
  try:
    names = os.listdir(os.path.dirname(path) or ".")
  except OSError:
    return out
  for name in sorted(names):
    if not name.endswith(SIGNATURE_SUFFIX):
      continue
    sig = read_signature(os.path.join(os.path.dirname(path) or ".",
                                      name[:-len(SIGNATURE_SUFFIX)]))
    if sig and isinstance(sig.get("batch_size"), int):
      out.append(sig["batch_size"])
  return sorted(set(out))


def export_forward(model, variables, batch_size: int, path: str,
                   nclass: int = 1001, dtype=jnp.float32,
                   quantize: bool = False,
                   fingerprint: Optional[str] = None,
                   kv_page_size: Optional[int] = None) -> int:
  """Serialize the frozen forward pass to ``path``; returns byte size.

  ``variables`` (trained params + batch stats) are captured as constants
  (the freeze step); the exported module takes only the input batch.
  ``quantize`` stores the large kernels as int8 + per-channel scales
  and dequantizes inside the program -- the TRT INT8 analog
  (quantization.py; ref --trt_mode :615-620, conversion :2466-2486).
  ``fingerprint`` is the exporting run's config fingerprint
  (analysis/baseline.config_fingerprint_key), recorded in the signature
  sidecar so the artifact stays attributable to the program shape that
  produced it. ``kv_page_size`` records the exporting engine's paged-KV
  geometry (serving/decode.py LMSpec) in the sidecar -- the exported
  image forward has no KV cache, but a decode-family export's loader
  must be able to diff page geometry before the XLA call.
  """
  model.set_batch_size(batch_size)
  module = model.make_module(nclass=nclass, phase_train=False,
                             data_format="NHWC", dtype=dtype,
                             param_dtype=jnp.float32)

  if quantize:
    from kf_benchmarks_tpu import quantization
    variables = quantization.quantize_variables(variables)

  def frozen_forward(images):
    if quantize:
      from kf_benchmarks_tpu import quantization
      fvars = quantization.dequantize_variables(variables, jnp.float32)
    else:
      fvars = variables
    logits, _ = module.apply(fvars, images)
    return logits

  image_shape = tuple(model.get_input_shapes("eval")[0])
  spec = jax.ShapeDtypeStruct(image_shape, jnp.float32)
  exported = jax_export.export(jax.jit(frozen_forward))(spec)
  data = exported.serialize()
  os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
  with open(path, "wb") as f:
    f.write(data)
  _write_signature(path, image_shape, dtype, quantize=quantize,
                   nclass=nclass, fingerprint=fingerprint,
                   kv_page_size=kv_page_size)
  return len(data)


_UNSET = object()


def load_forward(path: str, expect_batch: Optional[int] = None,
                 expect_shape: Optional[tuple] = None,
                 expect_quantize=_UNSET,
                 expect_kv_page_size=_UNSET) -> Callable:
  """Deserialize an exported forward program into a callable.

  When the caller states what it is about to serve (``expect_batch`` /
  ``expect_shape``), the loaded executable's input signature is
  validated HERE, against the deserialized avals -- a mismatch names
  the exported signature, the request, and every sibling export's
  batch size (the available bucket list), instead of surfacing later
  as an opaque XLA arity/shape error inside the call.

  ``expect_quantize`` (None or "int8") and ``expect_kv_page_size``
  (None or int) state the caller's serving mode; when passed, they are
  diffed against the signature sidecar BEFORE deserialization -- a
  bf16 engine pointed at an INT8 export fails right here with the
  sidecar diff, not as a dtype mismatch deep in the XLA call.
  Pre-sidecar artifacts (no ``.sig.json``) skip the mode check and
  stay loadable."""
  sig = read_signature(path)
  mode_checks = []
  if expect_quantize is not _UNSET:
    mode_checks.append(("quantize_mode", expect_quantize))
  if expect_kv_page_size is not _UNSET:
    want_page = int(expect_kv_page_size) if expect_kv_page_size else None
    mode_checks.append(("kv_page_size", want_page))
  if mode_checks and sig is not None:
    def _got(key):
      if key == "quantize_mode" and key not in sig:
        # Pre-round-19 sidecars recorded only the quantize bool.
        return "int8" if sig.get("quantize") else None
      return sig.get(key)
    diffs = [f"{key}: sidecar={_got(key)!r}, requested={want!r}"
             for key, want in mode_checks if _got(key) != want]
    if diffs:
      raise ValueError(
          f"AOT export {path} was produced for a different serving "
          "mode -- " + "; ".join(diffs)
          + (f" (exporting fingerprint {sig.get('fingerprint')})" if
             sig.get("fingerprint") else "")
          + ". Re-export with the matching mode (e.g. --trt_mode=INT8 "
          "pairs with --serving_quantize=int8) or point the engine at "
          "the matching artifact.")
  with open(path, "rb") as f:
    exported = jax_export.deserialize(f.read())
  avals = list(exported.in_avals)
  if avals and (expect_batch is not None or expect_shape is not None):
    got = tuple(int(d) for d in avals[0].shape)
    want = tuple(int(d) for d in expect_shape) if expect_shape else None
    batch_ok = expect_batch is None or (got and got[0] == int(expect_batch))
    shape_ok = want is None or got == want
    if not (batch_ok and shape_ok):
      buckets = sibling_batch_sizes(path)
      sig = read_signature(path) or {}
      raise ValueError(
          f"AOT export {path} serves input {got} "
          f"(batch {got[0] if got else '?'}"
          + (f", fingerprint {sig.get('fingerprint')}" if
             sig.get("fingerprint") else "") + ")"
          + f"; requested batch {expect_batch}"
          + (f" shape {want}" if want else "")
          + (f". Available exported batch size(s) here: {buckets}"
             if buckets else "")
          + ". Re-export with --aot_save_path at the serving batch "
          "size (the bucket ladder bounds the executable set).")
  return exported.call
