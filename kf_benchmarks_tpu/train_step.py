"""Jitted train/eval step construction.

This is the TPU-native heart of the framework, replacing the reference's
graph build + per-tower loop + sess.run (ref: benchmark_cnn.py:2619-2731
_build_model, :2958-3209 add_forward_pass_and_gradients, :786-884
benchmark_one_step). Design:

* One SPMD program over a jax.sharding.Mesh: the 1-D 'replica' mesh for
  the replicated/gossip families, or the named 2-D ('batch', 'model')
  mesh (parallel/mesh.py build_mesh_2d) behind --mesh_shape /
  --shard_optimizer_state, where the batch shards over 'batch' and the
  ZeRO state shards span both axes (ops/sharded.py).
* Per-replica state convention: every TrainState leaf carries a leading
  replica dimension sharded P('replica') -- the exact analog of the
  reference's per-GPU variable copies (v0..vN scopes,
  variable_mgr.py:175-177, :277-368). Replicated strategies keep the
  copies bit-identical via collectives; independent/gossip strategies let
  them diverge, which pmap-style stacked state expresses naturally.
* Strategy hooks (parallel/strategies.py) run inside the shard_mapped
  body: gradient psum for replicated/sync-SGD, ppermute weight gossip for
  pair-averaging, weight pmean for SMA.
* Loss scaling: the reference's auto-loss-scale state machine
  (variable_mgr_util.py:51-139) is carried in TrainState and stepped with
  jnp.where -- halve-on-nonfinite + skip update, double every N clean
  steps.
* bf16: activations/compute in bfloat16 when --use_fp16 on TPU; params
  stay fp32 master copies (the fp16 custom-getter analog).
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
import flax
import optax

from kf_benchmarks_tpu import elastic as elastic_lib
from kf_benchmarks_tpu import telemetry as telemetry_lib
from kf_benchmarks_tpu.ops import overlap as overlap_lib
from kf_benchmarks_tpu.ops import sharded as sharded_lib
from kf_benchmarks_tpu.parallel import mesh as mesh_lib
from kf_benchmarks_tpu.parallel.mesh import (BATCH_AXIS, MODEL_AXIS,
                                             REPLICA_AXIS)


@flax.struct.dataclass
class TrainState:
  step: Any
  params: Any
  opt_state: Any
  batch_stats: Any
  loss_scale: Any
  loss_scale_normal_steps: Any
  rng: Any
  # Transient double-buffers for the staleness modes (SURVEY 7.4): the
  # XLA analog of the reference's StagingAreas. Holds 'deferred_grads'
  # under --variable_consistency=relaxed (ref: batch_allreduce.py:353-388
  # one-step-stale gradients) and/or 'staged_params' under --staged_vars
  # (ref: variable_mgr.py:246-274 staged variable reads). Not part of
  # checkpoints: a restart warms up with zeros/fresh copies exactly like
  # the reference's StagingArea warmup ops.
  buffers: Any = flax.struct.field(default_factory=dict)


def _is_batch_norm_param(path) -> bool:
  """L2 filtering: the reference excludes batch-norm variables from weight
  decay (ref: models/model.py filter_l2_loss_vars; benchmark_cnn.py:3078-3099)."""
  return any("bn" in str(k).lower() or "batchnorm" in str(k).lower()
             for k in path)


def l2_loss(params, single_op: bool = False):
  """0.5 * sum of squares over non-BN params (tf.nn.l2_loss semantics,
  ref: benchmark_cnn.py:3078-3099). ``single_op`` concatenates first
  (ref --single_l2_loss_op); numerically identical, kept as a knob."""
  leaves = []
  flat = jax.tree_util.tree_flatten_with_path(params)[0]
  for path, leaf in flat:
    if not _is_batch_norm_param(path):
      leaves.append(leaf)
  if not leaves:
    return jnp.float32(0.0)
  if single_op:
    flat_vec = jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                for l in leaves])
    return 0.5 * jnp.sum(flat_vec * flat_vec)
  return 0.5 * sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                   for l in leaves)


def _l2_loss_mixed(params, shard_prefixes, axis_all, single_op=False):
  """:func:`l2_loss` over a mixed FSDP tree (--shard_params on a
  scanned-stack model): non-prefix leaves are the gathered FULL values
  and keep the exact tf.nn.l2_loss formula; leaves under
  ``shard_prefixes`` are flat local shards of the scanned stacks, so
  their term reduces shard-locally and psums over the whole mesh --
  exact in value (the shards tile the stack exactly once and the zero
  pad contributes nothing) but reassociated, hence not bit-identical
  to the replicated-param L2 (logged once by make_step_fns)."""
  full_leaves, shard_leaves = [], []
  for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
    if _is_batch_norm_param(path):
      continue
    if sharded_lib.top_level_key(path) in shard_prefixes:
      shard_leaves.append(leaf)
    else:
      full_leaves.append(leaf)
  if single_op and full_leaves:
    flat_vec = jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                for l in full_leaves])
    base = 0.5 * jnp.sum(flat_vec * flat_vec)
  else:
    base = 0.5 * sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                     for l in full_leaves) if full_leaves \
        else jnp.float32(0.0)
  if shard_leaves:
    local = 0.5 * sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                      for l in shard_leaves)
    base = base + lax.psum(local, axis_all)
  return base


def _sync_schedule_counts(src_state, dst_state, bump: int = 0):
  """Copy every ``count`` leaf of ``src_state`` (+``bump``) into
  ``dst_state``.

  optax keys schedules and bias correction on the optimizer's internal
  update count. When one lockstep round applies the optimizer several
  times (async-PS sequential apply), the framework's time base is still
  the ROUND: without this, an N-replica round would advance count-keyed
  LR schedules N times -- decaying N times too early and diverging from
  the logged lr_fn(step).
  """
  src = {jax.tree_util.keystr(p): leaf for p, leaf in
         jax.tree_util.tree_flatten_with_path(src_state)[0]}

  def fix(path, leaf):
    if path and getattr(path[-1], "name", None) == "count":
      return src[jax.tree_util.keystr(path)] + bump
    return leaf

  flat, treedef = jax.tree_util.tree_flatten_with_path(dst_state)
  return jax.tree_util.tree_unflatten(
      treedef, [fix(p, l) for p, l in flat])


def make_step_fns(model, module, eval_module, strategy, tx, lr_fn, params,
                  mesh, compute_dtype=jnp.float32, total_train_steps=None):
  """Build (init_fn, train_step, eval_step, broadcast_init, train_chunk)
  jitted over ``mesh``.

  All operate on per-replica stacked state (leading replica dim).
  ``total_train_steps`` is the RESOLVED run length (callers must pass the
  derived count -- params.num_batches is None on default/--num_epochs
  runs); it drives progress-ramped modules (NASNet drop-path).

  ``train_chunk`` is the device-resident multi-step program
  (--steps_per_dispatch=K > 1, else None): K applications of the SAME
  per-replica train step under one ``lax.scan``, so host dispatch and
  tunnel RTT are paid once per K steps. Inputs carry a leading
  staged-steps axis -- size K for real-data chunks, size 1 for the
  synthetic resident batch (reused every scanned step, folding batch
  "generation" into the program: no staged-batch HBM footprint and no
  H2D at all). Per-step metrics come back stacked on a leading K axis;
  the carry is the ordinary TrainState, so step numbering, the
  fold_in(rng, step) dropout stream, LR schedules, and the loss-scale
  state machine advance exactly as in K dispatches of ``train_step``.

  ``--num_grad_accum=M`` > 1 microbatches INSIDE each train step (an
  inner lax.scan over M batch slices accumulating f32 gradients before
  one reduction + one optimizer apply), orthogonal to the K-step
  dispatch chunking outside: K amortizes host/dispatch cost, M bounds
  backward-residual HBM. Both default off (the exact monolithic
  program).
  """
  num_replicas = mesh.devices.size
  # Axis system. 1-D ('replica',) meshes keep the exact legacy program
  # (every golden contract is pinned against it); the named 2-D
  # ('batch', 'model') mesh behind --mesh_shape/--shard_optimizer_state
  # shards the batch over 'batch' only (model-axis peers re-compute the
  # same shard) while the stacked state and the metric pmeans span both
  # axes.
  two_d = BATCH_AXIS in mesh.axis_names
  axis_data = BATCH_AXIS if two_d else REPLICA_AXIS
  axis_all = mesh_lib.state_axes(mesh) if two_d else REPLICA_AXIS
  # --shard_optimizer_state: the strategy is the marker; the mechanics
  # (reduce-scatter mean, shard apply, param all-gather) live below +
  # ops/sharded.py. Requires the 2-D mesh (benchmark.py builds Nx1 when
  # --mesh_shape is unset).
  sharded_state = bool(getattr(strategy, "sharded_state", False))
  if sharded_state and not two_d:
    raise ValueError(
        "--shard_optimizer_state requires the named 2-D ('batch', "
        "'model') mesh (parallel/mesh.py build_mesh_2d); got axes "
        f"{mesh.axis_names}")
  # --shard_params (full FSDP, ZeRO-3): params live as the (n, k) /
  # (n, L, k) shard stacks of ops/sharded.fsdp_stacked_shards between
  # steps and are re-assembled per builder-layer bucket (loss top) /
  # per scanned block (inside the nn.scan body -- the module's own
  # gather hook, model.fsdp_gathered_prefixes) DURING the
  # forward/backward; the optimizer applies on the shard and NO
  # trailing full-tree all-gather remains -- peak param residency is
  # one bucket/block, steady-state per-device param HBM is |params|/n.
  sharded_params = bool(getattr(params, "shard_params", False))
  if sharded_params and not sharded_state:
    raise ValueError(
        "--shard_params requires --shard_optimizer_state: the FSDP "
        "forward consumes the sharded family's scatter/apply machinery "
        "(ops/sharded.py); validation.py rejects the pair upstream")
  # --partitioner: who places the collectives. 'manual' (default) keeps
  # the exact legacy shard_map programs every golden contract pins;
  # 'gspmd' lowers the SAME per-replica body under plain jit with
  # NamedSharding-annotated state/batch and lets the XLA SPMD
  # partitioner insert/re-place them (SNIPPETS [2]/[3] idiom; the
  # analysis/audit.py twin-referee rule diffs the two inventories).
  # Sharded families only: the replicated/gossip/PS strategies are
  # hand-placed BY DESIGN (their collectives ARE the semantics --
  # ppermute gossip, sequential PS apply); validation.py rejects the
  # combinations upstream, this re-guards direct callers.
  partitioner = getattr(params, "partitioner", None) or "manual"
  use_gspmd = partitioner == "gspmd"
  if use_gspmd and not sharded_state:
    raise ValueError(
        "--partitioner=gspmd covers the sharded training families "
        "(--shard_optimizer_state [+ --shard_params]): the other "
        "strategies' collectives are semantic hand placements, not "
        "partitioning choices (validation.py rejects these upstream)")
  fsdp_template = None
  fsdp_module_prefixes = ()
  fsdp_bucket_bytes = 0
  if sharded_params:
    fsdp_module_prefixes = tuple(
        getattr(model, "fsdp_gathered_prefixes", ()) or ())
    mb = (getattr(params, "reduce_bucket_mb", None)
          or overlap_lib.DEFAULT_BUCKET_MB)
    fsdp_bucket_bytes = int(mb) * 1024 * 1024
    # Full-shape template (abstract -- nothing executes): the gather
    # specs, the eval/accum whole-tree re-assembly and the checkpoint
    # layout all key on it. Mirrors init_state's module.init exactly.
    in_shapes = model.get_input_shapes("train")
    in_dtypes = model.get_input_data_types("train")
    sample = jnp.zeros(tuple(in_shapes[0]), in_dtypes[0])
    fsdp_template = jax.eval_shape(
        lambda: module.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(0)},
                            sample))["params"]
    if fsdp_module_prefixes and (params.weight_decay or 0.0):
      from kf_benchmarks_tpu.utils import log as log_util
      log_util.log_fn(
          "shard_params: weight decay over the scanned parameter "
          f"stack(s) {list(fsdp_module_prefixes)} reduces shard-"
          "locally + one mesh psum (full blocks exist only one at a "
          "time inside the scan): exact L2 value, reassociated -- "
          "total_loss is not bit-identical to the replicated-param L2 "
          "on this model family (pass --weight_decay=0 for bit-exact "
          "A/Bs)")
  weight_decay = params.weight_decay or 0.0
  # Loss-scale resolution (ref: benchmark_cnn.py:471-480 "None = model
  # default"): float16 compute defaults to the model's scale (128);
  # bfloat16 needs none unless explicitly requested.
  if params.use_fp16:
    if params.fp16_loss_scale is not None:
      init_loss_scale = float(params.fp16_loss_scale)
    elif compute_dtype == jnp.float16:
      init_loss_scale = float(model.get_fp16_loss_scale())
    else:
      init_loss_scale = 1.0
  else:
    init_loss_scale = 1.0
  auto_loss_scale = bool(params.use_fp16 and
                         params.fp16_enable_auto_loss_scale)
  use_loss_scale = auto_loss_scale or init_loss_scale != 1.0
  inc_every_n = params.fp16_inc_loss_scale_every_n

  state_specs = TrainState(
      step=P(), params=P(axis_all), opt_state=P(axis_all),
      batch_stats=P(axis_all), loss_scale=P(),
      loss_scale_normal_steps=P(), rng=P(), buffers=P(axis_all))
  staged_vars = bool(getattr(params, "staged_vars", False))
  relaxed = getattr(params, "variable_consistency", "strong") == "relaxed"
  steps_per_dispatch = int(
      getattr(params, "steps_per_dispatch", None) or 1)
  # --num_grad_accum=M: the step scans M microbatches (leading batch
  # split) accumulating gradients in f32 before ONE reduction collective
  # and ONE optimizer apply -- the Megatron-style memory lever (Shoeybi
  # et al. 2019): backward residuals are sized to B/M instead of B.
  # M=1 keeps the exact monolithic program (the PERF.md envelope).
  num_grad_accum = int(getattr(params, "num_grad_accum", None) or 1)
  # --overlap_gradient_reduction: bucketed in-backward all-reduce
  # (ops/overlap.py). Under microbatching the hooks disengage --
  # reduction stays post-hoc on the ACCUMULATED tree, preserving the
  # one-collective-per-step invariant (in-backward hooks inside the
  # microbatch scan would reduce M times per step).
  overlap_spec = overlap_lib.build(params)
  overlap_in_step = overlap_spec is not None and num_grad_accum == 1
  if overlap_spec is not None and num_grad_accum > 1:
    from kf_benchmarks_tpu.utils import log as log_util
    log_util.log_fn(
        f"overlap_gradient_reduction: --num_grad_accum="
        f"{num_grad_accum} keeps reduction post-hoc on the accumulated "
        "tree (one collective per step is the pinned invariant); "
        "in-backward hooks disengaged")
  # --health_stats: in-step device health stats (telemetry.py). The
  # step builder takes the CONCRETE boolean benchmark.py resolved
  # (None/auto never reaches here from the runtime); direct callers
  # passing an unresolved None get the exact legacy program, which is
  # what keeps the collective-count HLO pins in older tests meaningful.
  # (sequential_apply has no single optimizer-update tree to measure;
  # async PS is already health-rejected by validation/resolve -- this
  # keeps direct make_step_fns callers safe too.)
  # (sharded state never reaches here with health on -- validation.py
  # rejects the pair and resolve_health_stats auto-disables -- but the
  # builder re-guards for direct callers: the stats read the full
  # update tree, which the shard apply never materializes.)
  health_stats = (bool(getattr(params, "health_stats", None)) and
                  not getattr(strategy, "sequential_apply", False) and
                  not sharded_state)
  # --packed_sequences (models/transformer_lm.py): the model exposes
  # images -> (B, T) per-token loss weights; the cross-replica metric
  # combine then weights each replica by ITS real-label count (token-
  # weighted, not replica-weighted -- replicas pack different document
  # mixes), with the weighted loss terms PACKED into one vector pmean
  # so the packed program carries no more collectives than the
  # unpacked one (the lm_packed audit rule pins this).
  token_weight_fn = getattr(model, "token_weight_fn", None)
  # Top-level param-tree keys whose gradients the MODULE already
  # reduces in-backward (e.g. transformer_lm's scanned 'blocks' stack
  # hooks per layer inside the nn.scan); the step-level buckets skip
  # them so each gradient is reduced exactly once.
  module_reduced_prefixes = tuple(
      getattr(model, "in_backward_reduced_prefixes", ()) or ()
  ) if overlap_in_step else ()
  # Modules with a training-progress schedule (NASNet drop-path's
  # global-step ramp, ref: nasnet_utils.py:407-439) take ``progress`` =
  # step / total_training_steps; total steps is the run's --num_batches.
  import inspect
  module_takes_progress = (
      "progress" in inspect.signature(type(module).__call__).parameters)
  if total_train_steps is None:
    total_train_steps = int(getattr(params, "num_batches", None) or 0)
  total_train_steps = int(total_train_steps)

  def _squeeze(tree):
    return jax.tree.map(lambda x: jnp.squeeze(x, axis=0), tree)

  def _expand(tree):
    return jax.tree.map(lambda x: x[None], tree)

  # -- init -----------------------------------------------------------------

  def _init(rng, sample_images):
    variables = module.init({"params": rng, "dropout": rng}, sample_images)
    model_params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if sharded_params:
      # Full FSDP: the PARAM storage itself is the shard stack (per-
      # layer rows for the scanned prefixes), and the per-shard
      # optimizer state mirrors it leaf-for-leaf -- tx.init vmapped
      # over the uniform leading shard-row dim.
      params_store = sharded_lib.fsdp_stacked_shards(
          model_params, num_replicas, fsdp_module_prefixes)
      return params_store, jax.vmap(tx.init)(params_store), batch_stats
    if sharded_state:
      # Per-shard optimizer state: vmap tx.init over the stacked flat
      # param shards (ops/sharded.py layout), so every opt-state leaf
      # comes out (n, k) with row i = device i's shard -- global bytes
      # ~|state| instead of the replicated stack's n * |state|.
      opt_state = jax.vmap(tx.init)(
          sharded_lib.stacked_shards(model_params, num_replicas))
    else:
      opt_state = tx.init(model_params)
    return model_params, opt_state, batch_stats

  def init_state(rng, sample_images):
    """Builds the stacked per-replica TrainState (identical init on every
    replica == the reference's post-init broadcast, variable_mgr.py:342-356).
    Under --shard_optimizer_state the opt_state rows are per-device
    SHARDS, not copies (see _init); under --shard_params the params
    rows are shards too (the FSDP steady state -- per-device param HBM
    |params|/n)."""
    params_store, opt_state, batch_stats = _init(rng, sample_images)
    stack = lambda t: jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_replicas,) + x.shape), t)
    buffers = {}
    if relaxed:
      # Warmed up with zero gradients, like the reference's StagingArea
      # warmup put (ref: batch_allreduce.py:357-359).
      buffers["deferred_grads"] = stack(
          jax.tree.map(jnp.zeros_like, params_store))
    if staged_vars:
      buffers["staged_params"] = stack(params_store)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params_store if sharded_params else stack(params_store),
        opt_state=opt_state if sharded_state else stack(opt_state),
        batch_stats=stack(batch_stats),
        loss_scale=jnp.asarray(init_loss_scale, jnp.float32),
        loss_scale_normal_steps=jnp.zeros((), jnp.int32),
        rng=rng,
        buffers=buffers)

  # -- train step -----------------------------------------------------------

  # --shard_params engagement mirrors the overlap hooks' rule: under
  # --num_grad_accum the in-compute per-bucket gathers DISENGAGE -- the
  # full tree is re-assembled once before the microbatch scan and the
  # accumulated gradient is scattered post-hoc (so the scatter still
  # meets the accumulated sums in the same order as the round-11 path:
  # bit-identity is preserved; the param-residency win is accum=1's).
  fsdp_in_step = sharded_params and num_grad_accum == 1

  def per_replica_train(state, images, labels):
    model_params = _squeeze(state.params)
    opt_state = _squeeze(state.opt_state)
    batch_stats = _squeeze(state.batch_stats)
    buffers = _squeeze(state.buffers)
    # --staged_vars: forward/backward read one-step-stale weights while
    # updates land on the live ones (ref: StagedVariableGetter,
    # variable_mgr_util.py:313-393).
    forward_params = (buffers["staged_params"] if staged_vars
                      else model_params)
    if sharded_params and not fsdp_in_step:
      # FSDP + accumulation: one whole-tree gather up front (the
      # round-11 steady state, rotated to the step top), full-tree
      # microbatch scan, post-hoc scatter below.
      forward_params = sharded_lib.fsdp_gather_full(
          model_params, fsdp_template, fsdp_module_prefixes,
          nested=use_gspmd)
    # Data-replica id: on the 2-D mesh, model-axis peers fold the SAME
    # id (same batch shard, same dropout stream), which is what makes
    # their local gradients identical by construction -- the free
    # model-axis sub-slice in ops/sharded.py depends on it.
    replica_id = lax.axis_index(axis_data)
    step_rng = jax.random.fold_in(
        jax.random.fold_in(state.rng, state.step), replica_id)

    apply_kwargs = {}
    if module_takes_progress and total_train_steps > 0:
      apply_kwargs["progress"] = (
          state.step.astype(jnp.float32) / total_train_steps)

    def loss_fn(p, mb_images, mb_labels, bs, dropout_rng):
      if overlap_in_step:
        # Bucketed in-backward reduction (ops/overlap.py): every use of
        # p below flows through the wrapped copy, so jax.grad returns
        # ALREADY replica-reduced gradients, one collective per bucket
        # issued where that bucket's backward completes. The post-hoc
        # strategy reduction is skipped (overlap_in_step below).
        # Ordering vs the loss-scale unscale is exact: the hooks reduce
        # the SCALED cotangents and the unscale divides by a
        # power-of-two scale afterwards (exponent shift; bit-identical
        # to dividing first, as the post-hoc path does).
        p = overlap_lib.wrap_tree(
            p, axis_data, overlap_spec.bucket_bytes,
            compact_dtype=overlap_spec.compact_dtype,
            exclude_prefixes=module_reduced_prefixes)
      if fsdp_in_step:
        # FSDP per-bucket gather (ops/overlap.py gather_params): every
        # non-module-gathered leaf of p below is the RE-ASSEMBLED full
        # value (one packed all-gather per builder-layer bucket), the
        # module-gathered scanned stacks stay shards for the per-block
        # hook inside the nn.scan body; jax.grad then returns shard-
        # layout gradients already reduce-scattered (batch mean + free
        # model sub-slice), one collective per bucket/block, each
        # issued where that bucket's backward completes. The unscale-
        # after-scatter ordering is exact for the same power-of-two
        # reason as the overlap hooks above.
        p = overlap_lib.fsdp_wrap_shards(
            p, fsdp_template, fsdp_bucket_bytes, BATCH_AXIS, MODEL_AXIS,
            exclude_prefixes=fsdp_module_prefixes, nested=use_gspmd)
      variables = {"params": p}
      if bs:
        variables["batch_stats"] = bs
      (logits, aux_logits), updates = module.apply(
          variables, mb_images, mutable=["batch_stats"],
          rngs={"dropout": dropout_rng}, **apply_kwargs)
      new_bs = updates.get("batch_stats", bs)
      from kf_benchmarks_tpu.models.model import BuildNetworkResult
      result = BuildNetworkResult(logits=(logits, aux_logits))
      base_loss = model.loss_function(result, mb_labels)
      total_loss = base_loss
      if weight_decay:
        if fsdp_in_step and fsdp_module_prefixes:
          # The scanned-stack leaves of p are SHARDS here (their full
          # values exist only block-at-a-time inside the scan), so
          # their L2 term reduces shard-locally + one scalar psum over
          # the mesh -- exact in value (shards tile the stack once,
          # pad is zero) but reassociated, so total_loss is NOT
          # bit-identical to the replicated-param L2 for scanned
          # models with weight decay (the make_step_fns note logs
          # this; the gathered non-scanned leaves keep the exact
          # legacy term).
          total_loss = total_loss + weight_decay * _l2_loss_mixed(
              p, fsdp_module_prefixes, axis_all,
              single_op=params.single_l2_loss_op)
        else:
          total_loss = total_loss + weight_decay * l2_loss(
              p, single_op=params.single_l2_loss_op)
      scaled = total_loss * state.loss_scale
      return scaled, (base_loss, total_loss, new_bs, result)

    accum_acc_metrics = None
    accum_tok_w = None
    if num_grad_accum > 1:
      # Microbatched accumulation (--num_grad_accum=M): one scan
      # iteration per microbatch, so the compiled program carries ONE
      # microbatch-sized forward+backward regardless of M, and XLA
      # reuses that iteration's activation buffers M times. Gradients
      # accumulate in f32 (the master precision) and are divided once,
      # so the accumulated gradient is the mean over microbatches --
      # the same estimator as the monolithic step up to float
      # reassociation of the batch reduction. Everything downstream
      # (ONE strategy reduction, the loss-scale state machine, the
      # optimizer apply) sees exactly one gradient tree per step.
      m = num_grad_accum
      if images.shape[0] % m:
        raise ValueError(
            f"--num_grad_accum={m} must divide the per-replica batch "
            f"size {images.shape[0]} (validation.py admits only "
            "configurations where it can)")
      split = lambda x: x.reshape((m, x.shape[0] // m) + x.shape[1:])
      mb_images = split(images)
      mb_labels = jax.tree.map(split, labels)
      grad_fn = jax.grad(loss_fn, has_aux=True)
      want_acc = bool(params.print_training_accuracy)
      # Scan carries start as zeros; inside the shard_map body the
      # gradients/metrics they accumulate are device-varying, so the
      # zeros are pcast to match (identity on pre-vma jax; sequence.py).
      from kf_benchmarks_tpu.parallel import sequence as sequence_lib

      def _vary(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return jax.tree_util.tree_unflatten(
            treedef,
            list(sequence_lib.vary_like(images, tuple(leaves))))

      g0 = _vary(jax.tree.map(
          lambda p: jnp.zeros(p.shape, jnp.float32), forward_params))
      bl0, tl0, w0 = _vary((jnp.zeros((), jnp.float32),
                            jnp.zeros((), jnp.float32),
                            jnp.zeros((), jnp.float32)))
      bs0 = _vary(batch_stats)

      def mb_body(carry, xs):
        g_acc, bl_acc, tl_acc, w_acc, acc_acc, bs = carry
        imgs, lbls, idx = xs
        # Distinct dropout stream per microbatch (a shared one would
        # correlate masks across the effective batch).
        rng_i = jax.random.fold_in(step_rng, idx)
        g, (bl, tl, bs_next, result) = grad_fn(forward_params, imgs,
                                               lbls, bs, rng_i)
        # --packed_sequences: each microbatch's loss is its own
        # token-MEAN (ops/fused_loss.py); weight the accumulation by
        # the microbatch's real-label count so the accumulated step is
        # the PER-REPLICA monolithic token-weighted estimator -- sum
        # over tokens / total tokens -- not a mean-of-means over
        # unevenly packed microbatches. Deliberate scope: the CROSS-
        # replica gradient exchange stays the equal-weight pmean
        # (replicas' token counts concentrate tightly at ~97% packing,
        # and token-weighting the exchange would rebuild every pinned
        # reduction path -- strategies, overlap hooks, the sharded
        # scatter -- for a second-order correction), so the optimized
        # objective weights replicas equally while the REPORTED metrics
        # are exactly token-weighted (pmean(loss*w)/pmean(w) below).
        # Unpacked runs keep mb_w = 1 (the exact legacy equal-weight
        # program).
        if token_weight_fn is None:
          # Exact legacy equal-weight accumulation (bit-pinned).
          mb_w = jnp.float32(1.0)
          g_acc = jax.tree.map(lambda a, x: a + x.astype(jnp.float32),
                               g_acc, g)
          wb, wt = bl, tl
        else:
          mb_w = jnp.sum(token_weight_fn(imgs))
          g_acc = jax.tree.map(
              lambda a, x: a + x.astype(jnp.float32) * mb_w, g_acc, g)
          wb, wt = bl * mb_w, tl * mb_w
        if acc_acc is not None:
          mb_acc = model.accuracy_function(result, lbls)
          acc_acc = {k: acc_acc[k] + (v if token_weight_fn is None
                                      else v * mb_w)
                     for k, v in mb_acc.items() if k in acc_acc}
        return (g_acc, bl_acc + wb, tl_acc + wt,
                w_acc + mb_w, acc_acc, bs_next), None

      acc0 = None
      if want_acc:
        # Keys from an abstract eval (no FLOPs): scalar metrics only.
        lb0 = jax.tree.map(lambda x: x[0], mb_labels)
        shapes = jax.eval_shape(
            lambda: model.accuracy_function(
                loss_fn(forward_params, mb_images[0], lb0,
                        batch_stats, step_rng)[1][3], lb0))
        acc0 = _vary({k: jnp.zeros((), jnp.float32)
                      for k, v in shapes.items() if not v.shape})
      (g_acc, bl_acc, tl_acc, w_sum, acc_acc, new_bs), _ = lax.scan(
          mb_body, (g0, bl0, tl0, w0, acc0, bs0),
          (mb_images, mb_labels, jnp.arange(m)))
      # Normalizer: microbatch count on the legacy path; the summed
      # real-label count on the packed path (w_sum = sum of mb_w), so
      # gradients and losses come out as the monolithic token-weighted
      # estimator up to float reassociation of the batch split.
      norm = (jnp.float32(m) if token_weight_fn is None
              else jnp.maximum(w_sum, 1.0))
      if token_weight_fn is not None:
        # The scan's summed per-microbatch counts ARE this batch's
        # real-label total (0/1 weights in exact f32 integer range):
        # reused at metrics time so the two normalizers cannot drift.
        accum_tok_w = w_sum
      grads = jax.tree.map(lambda a, p: (a / norm).astype(p.dtype),
                           g_acc, forward_params)
      base_loss = bl_acc / norm
      total_loss = tl_acc / norm
      net_result = None
      if acc_acc is not None:
        accum_acc_metrics = {k: v / norm for k, v in acc_acc.items()}
    else:
      grads, (base_loss, total_loss, new_bs, net_result) = jax.grad(
          loss_fn, has_aux=True)(forward_params, images, labels,
                                 batch_stats, step_rng)
    if use_loss_scale or auto_loss_scale:
      grads = jax.tree.map(lambda g: g / state.loss_scale, grads)
    noise_stats = None
    if params.track_grad_noise_scale and num_replicas > 1:
      # Measured on the pre-reduction per-replica grads (the small-batch
      # estimate) vs their replica mean (the large-batch estimate); see
      # elastic.noise_scale_stats. This is the in-collective monitoring
      # KungFu's runtime does (SURVEY 2.9 "monitored gradient noise
      # scale").
      noise_stats = elastic_lib.noise_scale_stats(
          grads, axis_data, images.shape[0])
    grad_shards = None
    if fsdp_in_step:
      # Full FSDP: the in-backward gather hooks already reduce-
      # scattered every bucket/block cotangent onto the shard layout
      # (ops/overlap.py gather_params bwd -- elementwise identical to
      # the post-hoc scatter below); jax.grad's output IS the shard
      # tree. No full gradient tree ever existed.
      grad_shards = grads
    elif sharded_params:
      # FSDP + accumulation: post-hoc scatter of the accumulated full
      # tree onto the FSDP layout (per-layer rows for the scanned
      # stacks) -- elementwise the same values as scatter_mean.
      grad_shards = sharded_lib.fsdp_scatter_mean(grads,
                                                  fsdp_module_prefixes)
    elif sharded_state:
      # ZeRO gradient pass (ops/sharded.py): reduce-scatter of the
      # batch-axis mean -- each scatter group meets the same B distinct
      # contributions in the same group order as the replicated pmean,
      # so the scattered mean is BIT-IDENTICAL to it -- then the free
      # model-axis sub-slice. The full gradient tree dies here; only
      # this device's 1/n flat shard flows on.
      grad_shards = sharded_lib.scatter_mean(grads)
    elif not overlap_in_step:
      grads = strategy.reduce_gradients(grads, axis_data)
    # else: the in-backward hooks already reduced every bucket
    # (module-internal hooks for module_reduced_prefixes, the loss_fn
    # wrap for the rest); everything downstream -- the auto-loss-scale
    # finite check, relaxed-consistency banking, the optimizer apply --
    # sees the reduced tree exactly as on the post-hoc path.

    def _all_finite(tree, axis):
      ok = jnp.all(jnp.stack(
          [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(tree)]))
      # Globally uniform decision (pmin across replicas) so every carried
      # scalar stays replicated (ref chief-only NaN check + broadcast,
      # variable_mgr.py:186-193).
      return lax.pmin(ok.astype(jnp.int32), axis).astype(bool)

    # The loss-scale state machine keys on THIS step's fresh gradients
    # (they reflect the current scale), even when the applied gradients
    # are the deferred ones (ref: variable_mgr_util.py:51-139). On the
    # sharded path the shards tile the full reduced tree, so the pmin
    # over BOTH axes covers every element exactly once.
    if auto_loss_scale:
      fresh_finite = (_all_finite(grad_shards, axis_all) if sharded_state
                      else _all_finite(grads, axis_data))
    else:
      fresh_finite = None
    new_buffers = dict(buffers)
    if relaxed:
      # --variable_consistency=relaxed: apply the PREVIOUS step's reduced
      # gradients and bank this step's for the next -- the double-buffered
      # reformulation of the reference's deferred StagingArea gradients
      # (ref: batch_allreduce.py:353-388; SURVEY 7.4). Non-finite fresh
      # gradients are never banked (the deferred analog of the skipped
      # update): the old bank stays.
      banked = grads
      if fresh_finite is not None:
        banked = jax.tree.map(
            lambda a, b: jnp.where(fresh_finite, a, b),
            grads, buffers["deferred_grads"])
      new_buffers["deferred_grads"] = banked
      grads = buffers["deferred_grads"]

    model_params_pre = strategy.pre_update(model_params, state.step,
                                           axis_data)
    if sharded_state:
      # The ZeRO apply (the reference's central variable placement
      # rendered SPMD, variable_mgr.py:201-243): run the optimizer on
      # the 1/n shard ONLY (elementwise optimizers; validation.py
      # rejects LARS). Optimizer HBM per device is |state|/n.
      # --shard_params: the state ALREADY holds this device's shards
      # (the FSDP steady state) and the updated shards flow straight
      # back into it -- the round-11 trailing full-tree all-gather is
      # GONE; re-assembly happens inside the next step's compute, one
      # bucket/block at a time. Without it, params are replicated: the
      # shard is a free local slice and the updated params return by
      # all-gather for the next forward.
      param_shards = (model_params_pre if sharded_params
                      else sharded_lib.local_shards(model_params_pre))
      with jax.named_scope("optimizer_apply"):
        updates, new_opt_state = tx.update(grad_shards, opt_state,
                                           param_shards)
        new_shards = optax.apply_updates(param_shards, updates)
      new_params = (new_shards if sharded_params else
                    sharded_lib.gather_tree(new_shards, model_params_pre,
                                            nested=use_gspmd))
    elif getattr(strategy, "sequential_apply", False):
      # Async PS with a stateful optimizer (strategies.py): serialize
      # every replica's unaveraged gradient through the SHARED optimizer
      # state, in replica-index order -- the deterministic SPMD
      # rendering of the PS's one-at-a-time applications (ref async
      # mode: benchmark_cnn.py:520-522).
      g_all = jax.tree.map(
          lambda g: lax.all_gather(g, axis_data, axis=0), grads)

      def _apply_one(carry, g):
        prms, ost = carry
        upd, ost2 = tx.update(g, ost, prms)
        # Every application within the round sees the ROUND's schedule
        # count (momentum/variance state still advances per
        # application); the round bump happens once, below.
        ost2 = _sync_schedule_counts(ost, ost2)
        return (optax.apply_updates(prms, upd), ost2), None

      # The named_scope rides into HLO op_name metadata; the program-
      # contract auditor (analysis/contracts.py) keys the one-apply-
      # per-step check on it.
      with jax.named_scope("optimizer_apply"):
        (new_params, new_opt_state), _ = lax.scan(
            _apply_one, (model_params_pre, opt_state), g_all)
      new_opt_state = _sync_schedule_counts(opt_state, new_opt_state,
                                            bump=1)
    else:
      with jax.named_scope("optimizer_apply"):
        updates, new_opt_state = tx.update(grads, opt_state,
                                           model_params_pre)
        new_params = optax.apply_updates(model_params_pre, updates)
    new_params = strategy.post_update(new_params, state.step, axis_data)
    new_bs = strategy.sync_batch_stats(new_bs, axis_data)

    if auto_loss_scale:
      # Auto loss-scale state machine (ref: variable_mgr_util.py:51-139):
      # any non-finite FRESH grad -> skip the update, halve scale; else
      # count a normal step and double the scale every ``inc_every_n``.
      # Under relaxed consistency the APPLIED gradients are the previous
      # bank, which only ever admits finite values (banking gate above),
      # so the params/opt_state skip is unnecessary there by induction.
      keep = lambda new, old: jax.tree.map(
          lambda a, b: jnp.where(fresh_finite, a, b), new, old)
      if not relaxed:
        new_params = keep(new_params, model_params)
        new_opt_state = keep(new_opt_state, opt_state)
      # batch_stats come from THIS step's forward in both modes: an
      # overflowing forward must not poison the running statistics.
      new_bs = keep(new_bs, batch_stats)
      normal_steps = jnp.where(fresh_finite,
                               state.loss_scale_normal_steps + 1,
                               0)
      do_double = jnp.logical_and(fresh_finite,
                                  normal_steps >= inc_every_n)
      new_scale = jnp.where(
          fresh_finite,
          jnp.where(do_double, state.loss_scale * 2.0, state.loss_scale),
          jnp.maximum(state.loss_scale / 2.0, 1.0))
      normal_steps = jnp.where(do_double, 0, normal_steps)
    else:
      new_scale = state.loss_scale
      normal_steps = state.loss_scale_normal_steps

    lr = lr_fn(state.step)
    # Token-weighted metric combine (--packed_sequences): this
    # replica's real-label count; per-replica losses are already
    # normalized by it (ops/fused_loss.py), so the global token-mean is
    # pmean(loss * w) / pmean(w) -- computed from the SAME packed
    # vector collective that carries the losses.
    tok_w = None
    if token_weight_fn is not None:
      tok_w = (accum_tok_w if accum_tok_w is not None
               else jnp.sum(token_weight_fn(images)))
    wm_safe = None
    if health_stats:
      # In-step health stats (telemetry.py): grad norm, update/param
      # ratio, non-finite leaf count, loss scale + skip flag -- all
      # read from the step's post-reduction values, so they are
      # replica-identical for the replica-synchronous strategies
      # validation admits. Each replica reduces a 1/n SLICE of every
      # tree (telemetry.health_partials) and the pre-scaled partial
      # sums ride the LOSS pmean: one f32 vector all-reduce replaces
      # the two scalar loss pmeans, so the health-on program carries
      # NO extra collective (acceptance-pinned in
      # tests/test_telemetry.py) and no replicated full-tree passes.
      # Elementwise, the vector all-reduce computes bit-identical loss
      # values to the scalar ones (equivalence pinned in the same
      # tests). ``updates`` exists on every health-admitted path:
      # sequential_apply (async PS) is rejected/auto-disabled by
      # validation.py and resolve_health_stats.
      skipped = (1.0 - fresh_finite.astype(jnp.float32)
                 if fresh_finite is not None else jnp.float32(0.0))
      # The fresh-grad overflow skip only suppresses the applied
      # update on the non-relaxed path (the relaxed bank admits finite
      # gradients only, so its apply always lands).
      suppressed = jnp.float32(0.0) if relaxed else skipped
      # Under --packed_sequences the two loss slots ride token-weighted
      # (loss * w) and w itself is appended to the SAME vector, so the
      # weighted combine still costs the one loss pmean.
      bl32 = base_loss.astype(jnp.float32)
      tl32 = total_loss.astype(jnp.float32)
      loss_slots = (jnp.stack([bl32, tl32]) if tok_w is None else
                    jnp.stack([bl32 * tok_w, tl32 * tok_w]))
      vec = [loss_slots, telemetry_lib.health_partials(
          grads, model_params, updates, axis_data)]
      if tok_w is not None:
        vec.append(jnp.stack([tok_w]))
      packed = lax.pmean(jnp.concatenate(vec), axis_data)
      health_totals = packed[2:] if tok_w is None else packed[2:-1]
      if tok_w is None:
        bl_m, tl_m = packed[0], packed[1]
      else:
        wm_safe = jnp.maximum(packed[-1], 1e-30)
        bl_m, tl_m = packed[0] / wm_safe, packed[1] / wm_safe
      metrics = {
          "base_loss": bl_m,
          "total_loss": tl_m,
          "learning_rate": lr,
          "health": telemetry_lib.health_finalize(
              health_totals, new_scale, skipped, suppressed),
      }
    elif tok_w is not None:
      # One 3-vector pmean replaces the two scalar loss pmeans: the
      # packed program's collective count stays <= the unpacked one.
      packed = lax.pmean(
          jnp.stack([base_loss.astype(jnp.float32) * tok_w,
                     total_loss.astype(jnp.float32) * tok_w, tok_w]),
          axis_data)
      wm_safe = jnp.maximum(packed[2], 1e-30)
      metrics = {
          "base_loss": packed[0] / wm_safe,
          "total_loss": packed[1] / wm_safe,
          "learning_rate": lr,
      }
    else:
      # Metric pmeans reduce over the DATA axis only: model-axis peers
      # compute the identical loss from the identical batch shard, so
      # the batch-group mean is already the global value -- and it is
      # bit-identical to the replicated path's B-contribution pmean.
      metrics = {
          "base_loss": lax.pmean(base_loss, axis_data),
          "total_loss": lax.pmean(total_loss, axis_data),
          "learning_rate": lr,
      }
    if tok_w is not None and wm_safe is not None:
      # Label coverage of the packed batch (real label positions /
      # slots): the in-step packing-efficiency signal next to the
      # host-side feed line (observability.packing_feed_line). Post-
      # collective scalar math, no extra communication.
      metrics["real_token_fraction"] = wm_safe / jnp.float32(
          sum(math.prod(l.shape) for l in jax.tree.leaves(labels)) or 1)
    if steps_per_dispatch > 1:
      # Replica-mean global norm of the reduced gradients (under relaxed
      # consistency: of the APPLIED, one-step-stale bank) -- the
      # per-step training-health scalar the chunked mode stacks
      # alongside loss and lr, replacing what an operator would
      # otherwise probe with per-step fetches. K=1 omits it so the
      # single-step program stays the exact program behind PERF.md's
      # pinned envelope numbers.
      if "health" in metrics:
        # The health vector already carries this exact norm (same grads
        # tree, sharded reduction): reuse it rather than paying a second,
        # full-tree replicated square-sum pass -- the replicated pass is
        # the ~2x-step-time cost _sharded_sumsq exists to avoid.
        metrics["grad_norm"] = metrics["health"][0]
      elif sharded_state:
        # The flat shards tile the reduced gradient exactly once, so
        # the psum of per-shard square-sums over BOTH axes is the global
        # square-sum -- no full-tree pass, same cost argument as the
        # health path's sharded reduction.
        metrics["grad_norm"] = jnp.sqrt(lax.psum(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grad_shards)), axis_all))
      else:
        metrics["grad_norm"] = lax.pmean(
            jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree.leaves(grads))), axis_data)
    if params.print_training_accuracy:
      # Under microbatching the per-microbatch scalar accuracies were
      # averaged inside the scan (equal microbatch sizes make that the
      # effective-batch value); monolithic computes them here.
      acc = (accum_acc_metrics if accum_acc_metrics is not None
             else model.accuracy_function(net_result, labels))
      # Scalars only: detection accuracy_functions also return per-box
      # arrays (decoded predictions), which are not replicated step
      # metrics. Packed runs weight each replica's (already token-
      # weighted) accuracy by its real-label count, like the losses.
      if tok_w is not None and wm_safe is not None:
        metrics.update({k: lax.pmean(v * tok_w, axis_data) / wm_safe
                        for k, v in acc.items() if jnp.ndim(v) == 0})
      else:
        metrics.update({k: lax.pmean(v, axis_data)
                        for k, v in acc.items() if jnp.ndim(v) == 0})
    if noise_stats is not None:
      metrics["noise_scale_g2"], metrics["noise_scale_s"] = noise_stats

    if staged_vars:
      # Next step's reads see this step's PRE-update weights: the value
      # that was in the staging area at read time (one-step staleness).
      new_buffers["staged_params"] = model_params
    new_state = TrainState(
        step=state.step + 1,
        params=_expand(new_params),
        opt_state=_expand(new_opt_state),
        batch_stats=_expand(new_bs),
        loss_scale=new_scale,
        loss_scale_normal_steps=normal_steps,
        rng=state.rng,
        buffers=_expand(new_buffers))
    return new_state, metrics

  # Explicit init output shardings: required under multi-process SPMD
  # (every process must agree where the stacked state lives) and a no-op
  # single-process.
  init_shardings = jax.tree.map(
      lambda spec: NamedSharding(mesh, spec), state_specs,
      is_leaf=lambda x: isinstance(x, P))
  init_state_fn = jax.jit(init_state, out_shardings=init_shardings)

  # Models built on library-internal scans (optax ctc_loss, flax RNN)
  # seed carries from unvarying constants, which trips the strict
  # varying-manual-axes checker even though the program is correct. Those
  # models opt out via relax_shard_map_vma; everyone else keeps the
  # checker (it catches missing pmeans under out_specs=P()).
  check_vma = not getattr(model, "relax_shard_map_vma", False)

  # -- the gspmd twin (--partitioner=gspmd) ---------------------------------
  #
  # Same per-replica body, compiler-placed collectives: the body still
  # speaks bound axis names (every lax.p* above), so instead of
  # shard_map it is traced under two nested jax.vmap's -- outer
  # 'batch', inner 'model' -- each binding axis_name AND
  # spmd_axis_name over the (B, M)-regridded stacked state. The
  # spmd_axis_name pins each vmap dimension to its mesh axis, the
  # surrounding plain jit carries the SAME NamedShardings the manual
  # path's specs induce, and GSPMD is then free to choose/re-place the
  # collectives (the twin-referee rule in analysis/audit.py diffs the
  # result against the hand placement). Batch inputs map on the outer
  # vmap only (model peers see the same shard, exactly like in_specs
  # P(axis_data)); scalars replicate in (in_axes=None) and come back
  # broadcast (out_axes=0 everywhere -- the [0, 0] pick below avoids
  # proving replication to vmap). eval_step and broadcast_init stay on
  # the manual shard_map path in both modes: neither is on the
  # steady-state hot path the twin A/B measures.
  def _gspmd_wrap(per_fn, batch_dim):
    grid_b = int(mesh.shape[BATCH_AXIS])
    grid_m = int(mesh.shape[MODEL_AXIS])
    stacked = ("params", "opt_state", "batch_stats", "buffers")
    vmap_axes = TrainState(
        step=None, params=0, opt_state=0, batch_stats=0, loss_scale=None,
        loss_scale_normal_steps=None, rng=None, buffers=0)

    def _map_stacked(state, f):
      return state.replace(**{
          name: jax.tree.map(f, getattr(state, name)) for name in stacked})

    def tile(state, images, labels):
      # The vmap's strip both grid dims; the body speaks the leading-1
      # per-replica stacking convention.
      new_state, metrics = per_fn(
          _map_stacked(state, lambda x: x[None]), images, labels)
      return _map_stacked(new_state,
                          lambda x: jnp.squeeze(x, axis=0)), metrics

    inner = jax.vmap(tile, in_axes=(vmap_axes, None, None),
                     axis_name=MODEL_AXIS, spmd_axis_name=MODEL_AXIS)
    outer = jax.vmap(inner, in_axes=(vmap_axes, batch_dim, batch_dim),
                     axis_name=BATCH_AXIS, spmd_axis_name=BATCH_AXIS)

    def global_fn(state, images, labels):
      gridded = _map_stacked(
          state,
          lambda x: x.reshape((grid_b, grid_m) + x.shape[1:]))
      split = lambda x: x.reshape(
          x.shape[:batch_dim] +
          (grid_b, x.shape[batch_dim] // grid_b) +
          x.shape[batch_dim + 1:])
      new_state, metrics = outer(gridded, split(images),
                                 jax.tree.map(split, labels))
      # Stacked leaves come back (B, M, ...) -> the flat (n, ...)
      # stacking; replicated scalars/metrics come back broadcast over
      # the grid -> any single copy (all bit-identical by SPMD).
      pick = lambda x: x[0, 0]
      out_state = _map_stacked(
          new_state,
          lambda x: x.reshape((grid_b * grid_m,) + x.shape[2:]))
      out_state = out_state.replace(
          step=pick(new_state.step), loss_scale=pick(new_state.loss_scale),
          loss_scale_normal_steps=pick(new_state.loss_scale_normal_steps),
          rng=pick(new_state.rng))
      return out_state, jax.tree.map(pick, metrics)

    data_spec = P(axis_data) if batch_dim == 0 else P(None, axis_data)
    data_sharding = NamedSharding(mesh, data_spec)
    return jax.jit(
        global_fn,
        in_shardings=(init_shardings, data_sharding, data_sharding),
        out_shardings=(init_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,))

  if use_gspmd:
    train_step = _gspmd_wrap(per_replica_train, 0)
  else:
    train_sharded = jax.shard_map(
        per_replica_train, mesh=mesh,
        in_specs=(state_specs, P(axis_data), P(axis_data)),
        out_specs=(state_specs, P()), check_vma=check_vma)
    train_step = jax.jit(train_sharded, donate_argnums=(0,))

  # -- chunked multi-step dispatch (--steps_per_dispatch) -------------------

  def per_replica_train_chunk(state, images, labels):
    """K train steps in one scanned program (leading axis = staged
    steps). A leading axis of 1 is the synthetic resident batch: the
    scan closes over it and runs K steps with no staged inputs -- the
    in-program analog of the reference's reused synthetic feed
    (ref: benchmark_cnn.py:3008-3011) at K steps per dispatch."""
    if images.shape[0] == 1 and steps_per_dispatch > 1:
      im0 = images[0]
      lb0 = jax.tree.map(lambda x: x[0], labels)
      new_state, metrics = lax.scan(
          lambda st, _: per_replica_train(st, im0, lb0), state, None,
          length=steps_per_dispatch)
      return new_state, metrics
    new_state, metrics = lax.scan(
        lambda st, batch: per_replica_train(st, *batch), state,
        (images, labels))
    return new_state, metrics

  train_chunk = None
  if steps_per_dispatch > 1:
    if use_gspmd:
      train_chunk = _gspmd_wrap(per_replica_train_chunk, 1)
    else:
      chunk_sharded = jax.shard_map(
          per_replica_train_chunk, mesh=mesh,
          in_specs=(state_specs, P(None, axis_data),
                    P(None, axis_data)),
          out_specs=(state_specs, P()), check_vma=check_vma)
      train_chunk = jax.jit(chunk_sharded, donate_argnums=(0,))

  # -- forward-only / eval step --------------------------------------------

  def per_replica_eval(state, images, labels):
    model_params = _squeeze(state.params)
    if sharded_params:
      # Mid-training eval re-assembles the full tree (the eval module
      # carries no FSDP hooks); eval is occasional, so the transient
      # full-tree residency is acceptable -- the steady-state training
      # program is what the residency contract binds.
      model_params = sharded_lib.fsdp_gather_full(
          model_params, fsdp_template, fsdp_module_prefixes)
    batch_stats = _squeeze(state.batch_stats)
    variables = {"params": model_params}
    if batch_stats:
      variables["batch_stats"] = batch_stats
    logits, aux_logits = eval_module.apply(variables, images)
    from kf_benchmarks_tpu.models.model import BuildNetworkResult
    result = BuildNetworkResult(logits=(logits, aux_logits))
    acc = model.accuracy_function(result, labels)
    loss = model.loss_function(result, labels)
    if token_weight_fn is not None:
      # Packed runs (mid-training eval; --eval itself is rejected in
      # validation.py): same token-weighted cross-replica combine as
      # the train metrics -- each replica's loss/accuracy is already
      # normalized by ITS real-label count, and replicas pack different
      # document mixes, so an equal-weight pmean would bias the global
      # value toward lightly-packed replicas.
      tok_w = jnp.sum(token_weight_fn(images))
      wm = jnp.maximum(lax.pmean(tok_w, axis_data), 1e-30)
      metrics = {k: lax.pmean(v * tok_w, axis_data) / wm
                 for k, v in acc.items() if jnp.ndim(v) == 0}
      metrics["base_loss"] = lax.pmean(loss * tok_w, axis_data) / wm
    else:
      metrics = {k: lax.pmean(v, axis_data)
                 for k, v in acc.items() if jnp.ndim(v) == 0}
      # Loss included so the forward-only timed loop can print the
      # standard step line (ref forward-only: benchmark_cnn.py:124-126).
      metrics["base_loss"] = lax.pmean(loss, axis_data)
    metrics["total_loss"] = metrics["base_loss"]
    return metrics

  eval_sharded = jax.shard_map(
      per_replica_eval, mesh=mesh,
      in_specs=(state_specs, P(axis_data), P(axis_data)),
      out_specs=P(), check_vma=check_vma)
  eval_step = jax.jit(eval_sharded)

  # -- broadcast-init (strategy-dependent; ref: benchmark_cnn.py:2094-2100) --

  def per_replica_broadcast(tree):
    return _expand(strategy.broadcast_init(_squeeze(tree), axis_data))

  broadcast_sharded = jax.shard_map(
      per_replica_broadcast, mesh=mesh,
      in_specs=(P(axis_all),), out_specs=P(axis_all))
  broadcast_init = jax.jit(broadcast_sharded)

  return init_state_fn, train_step, eval_step, broadcast_init, train_chunk
