"""Cluster manager: multi-host process wiring.

TPU-native re-design of the reference's gRPC cluster layer (ref:
scripts/tf_cnn_benchmarks/cnn_util.py:201-251 BaseClusterManager /
GrpcClusterManager; job roles benchmark_cnn.py:571-577). The reference
starts an in-process tf.train.Server per task and blocks ps/workers in
join_server(); under JAX the multi-host runtime is flat SPMD -- every
process runs the same program and the coordinator wires the distributed
backend -- so:

  * worker host lists + task index map onto jax.distributed.initialize
    (coordinator = worker 0, the reference's controller-targets-worker-0
    convention);
  * there are no ps/controller roles on TPU (PS capability maps to
    sharded state, SURVEY 5.8); requesting them raises with that
    explanation rather than silently doing the wrong thing;
  * join_server() maps to blocking until the coordination service says
    shutdown (the kfcoord barrier), for processes that only serve.
"""

from __future__ import annotations

from typing import List, Optional


def process_rank() -> int:
  """Stable per-process rank for telemetry record tagging.

  Flight-recorder rows carry it, and rank 0 owns the aggregated window
  at exit (telemetry.py aggregate_rank_windows). Under the kfrun
  launcher the env rank hint is authoritative even before
  jax.distributed initializes (ref: kungfu-run peer-list env
  propagation, SURVEY 2.9); otherwise the JAX process index -- the same
  chief-election convention as parallel/kungfu.py current_rank
  (ref call: benchmark_cnn.py:2044-2048), but a PROCESS index, not a
  device-weighted one: telemetry files are per process.
  """
  import os
  hint = os.environ.get("KFCOORD_RANK_HINT")
  if hint:
    try:
      return int(hint)
    except ValueError:
      pass
  import jax
  return jax.process_index()


class BaseClusterManager:
  """(ref: cnn_util.py:201-229)."""

  def __init__(self, params):
    worker_hosts = list(params.worker_hosts or [])
    ps_hosts = list(params.ps_hosts or [])
    # Under the kfrun launcher the LIVE world size is KFCOORD_WORLD (a
    # checkpoint-restart resize relaunches the same command with a new
    # world), so the static --worker_hosts list is truncated to the
    # generation's actual size; hosts beyond the provisioned list
    # cannot be invented, so the world is capped at the list length.
    import os
    env_world = os.environ.get("KFCOORD_WORLD")
    if env_world and worker_hosts:
      worker_hosts = worker_hosts[:max(1, min(int(env_world),
                                              len(worker_hosts)))]
    if params.job_name in ("ps", "controller"):
      raise ValueError(
          f"job_name={params.job_name!r} has no TPU analog: parameter "
          "servers map to sharded optimizer state and the controller "
          "role to the flat SPMD program (SURVEY 5.8); run every "
          "process as a worker.")
    if ps_hosts:
      raise ValueError("ps_hosts set but parameter-server processes are "
                       "not part of the TPU design (use sharded state)")
    self._cluster_spec = {"worker": worker_hosts}
    self.params = params

  def get_target(self) -> Optional[str]:
    """The coordinator address (ref get_target returns the session
    master; here: worker 0, where jax.distributed's coordinator runs)."""
    workers = self._cluster_spec["worker"]
    return workers[0] if workers else None

  def get_cluster_spec(self) -> dict:
    return dict(self._cluster_spec)

  def num_workers(self) -> int:
    return max(len(self._cluster_spec["worker"]), 1)

  def join_server(self):
    raise NotImplementedError


class JaxClusterManager(BaseClusterManager):
  """Wires this process into the multi-host JAX runtime
  (the GrpcClusterManager analog, ref: cnn_util.py:232-251)."""

  def __init__(self, params):
    super().__init__(params)
    self._initialized = False
    workers = self._cluster_spec["worker"]
    if len(workers) > 1:
      import os
      import jax
      if params.device == "cpu":
        # Cross-process CPU collectives need an explicit backend; gloo
        # ships with jaxlib (the CPU stand-in for TPU ICI collectives,
        # SURVEY 5.8 comm-backend table).
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
      # Under kfrun each worker gets its rank via env (the same command
      # line is launched N times; ref: kungfu-run peer-list env
      # propagation, SURVEY 2.9).
      task_index = int(os.environ.get("KFCOORD_RANK_HINT",
                                      params.task_index))
      # all-ranks: guarded on the shared worker LIST (len(workers)>1),
      # not on this process's rank -- every worker of a multi-host
      # launch reaches the distributed rendezvous together.
      jax.distributed.initialize(
          coordinator_address=workers[0],
          num_processes=len(workers),
          process_id=task_index)
      self._initialized = True

  def join_server(self):
    """Block until the job tears down (the ps join_server analog): wait
    on the coordination-service exit barrier when launched under kfrun,
    else return immediately (flat SPMD has no serve-only processes)."""
    from kf_benchmarks_tpu.parallel import kungfu
    # all-ranks: unconditional on every process that constructed a
    # cluster manager -- run_barrier itself degrades to a no-op
    # single-process, so attendance is exactly the world.
    kungfu.run_barrier()


def get_cluster_manager(params) -> Optional[BaseClusterManager]:
  """(ref: platforms/default/util.py get_cluster_manager)."""
  if not (params.worker_hosts or params.job_name):
    return None
  return JaxClusterManager(params)
