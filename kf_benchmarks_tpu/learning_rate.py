"""Learning-rate schedules.

Re-implements the reference's LR policy resolution (ref:
benchmark_cnn.py:1067-1169): piecewise 'LR0;E1;LR1;...' schedules,
exponential decay with a floor, linear warmup, and model-default
fallback -- as pure jnp functions of the global step (XLA-friendly:
jnp.where chains, no python control flow on traced values).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np


def parse_piecewise_schedule(schedule_str: str):
  """Parse 'LR0;E1;LR1;...;En;LRn' (ref: benchmark_cnn.py:1067-1101).

  Returns (values, epoch_boundaries). Alternates LR and epoch tokens; epochs
  must be strictly increasing positive ints.
  """
  pieces = schedule_str.split(";")
  if len(pieces) % 2 == 0:
    raise ValueError("--piecewise_learning_rate_schedule must have an odd "
                     "number of components")
  values = []
  boundaries = []
  for i, piece in enumerate(pieces):
    if i % 2 == 0:
      try:
        values.append(float(piece))
      except ValueError:
        raise ValueError(f"Invalid learning rate: {piece!r}")
    else:
      try:
        boundaries.append(int(piece))
      except ValueError:
        raise ValueError(f"Invalid epoch: {piece!r}")
  if any(b <= a for a, b in zip(boundaries, boundaries[1:])) or (
      boundaries and boundaries[0] <= 0):
    raise ValueError("Epochs must be positive and increasing")
  return np.array(values), np.array(boundaries)


def piecewise_learning_rate(step, values, epoch_boundaries,
                            num_batches_per_epoch: float):
  step = jnp.asarray(step, jnp.float32)
  lr = jnp.asarray(values[0], jnp.float32)
  for epoch, v in zip(epoch_boundaries, values[1:]):
    lr = jnp.where(step >= epoch * num_batches_per_epoch,
                   jnp.asarray(v, jnp.float32), lr)
  return lr


def make_learning_rate_fn(params, model, batch_size: int,
                          num_examples_per_epoch: int,
                          num_workers: int = 1) -> Callable:
  """Resolve the LR policy (ref: benchmark_cnn.py:1104-1169).

  Priority: piecewise schedule > init_learning_rate (+decay/floor) >
  model default. Warmup applies linearly over
  num_learning_rate_warmup_epochs (ref :1147-1157).
  """
  num_batches_per_epoch = num_examples_per_epoch / float(
      batch_size * max(num_workers, 1))

  if params.piecewise_learning_rate_schedule:
    values, boundaries = parse_piecewise_schedule(
        params.piecewise_learning_rate_schedule)

    def lr_fn(step):
      return piecewise_learning_rate(step, values, boundaries,
                                     num_batches_per_epoch)
  elif params.init_learning_rate is not None:
    init_lr = params.init_learning_rate

    def lr_fn(step):
      step = jnp.asarray(step, jnp.float32)
      lr = jnp.asarray(init_lr, jnp.float32)
      if params.num_epochs_per_decay and params.learning_rate_decay_factor:
        decay_steps = params.num_epochs_per_decay * num_batches_per_epoch
        num_decays = jnp.floor(step / decay_steps)
        lr = init_lr * jnp.power(params.learning_rate_decay_factor,
                                 num_decays)
        if params.minimum_learning_rate:
          lr = jnp.maximum(lr, params.minimum_learning_rate)
      return lr
  else:

    def lr_fn(step):
      return jnp.asarray(
          model.get_learning_rate(step, batch_size * max(num_workers, 1)),
          jnp.float32)

  if params.num_learning_rate_warmup_epochs:
    warmup_steps = params.num_learning_rate_warmup_epochs * \
        num_batches_per_epoch
    base_fn = lr_fn

    def lr_fn(step):  # noqa: F811
      step = jnp.asarray(step, jnp.float32)
      lr = base_fn(step)
      warmup_lr = lr * step / max(warmup_steps, 1.0)
      return jnp.where(step < warmup_steps, warmup_lr, lr)

  return lr_fn
