"""Default platform hooks (ref:
scripts/tf_cnn_benchmarks/platforms/default/util.py:28-72)."""

from __future__ import annotations

import os
import tempfile

from kf_benchmarks_tpu import cluster, flags


def define_platform_params() -> None:
  """Extra platform params (ref :28-33). The default platform defines
  none; vendor platforms register theirs here -- Params rebuilds
  automatically for late definitions (params._params_type)."""


def get_cluster_manager(params):
  """(ref :36-44)."""
  return cluster.get_cluster_manager(params)


def get_test_output_dir() -> str:
  """Where tests write outputs (ref :50-62): TEST_TMPDIR or a fresh
  tempdir."""
  base = os.environ.get("TEST_TMPDIR", "")
  if base:
    os.makedirs(base, exist_ok=True)
    return base
  return tempfile.mkdtemp(prefix="kf_benchmarks_test_")


def initialize(params) -> None:
  """Pre-run hook (ref :65-72). The default platform has nothing to do;
  the benchmark's own setup() handles backend init."""
  del params
