"""Platform dispatch: vendor-extension point.

The reference routes all platform-specific behavior through this module
so vendors can swap in their own (ref:
scripts/tf_cnn_benchmarks/platforms/util.py, which imports
platforms.default.util and re-exports its hooks). Set the
KF_BENCHMARKS_PLATFORM env var to a module path to substitute an
alternative platform implementation.
"""

from __future__ import annotations

import importlib
import os

_platform = importlib.import_module(
    os.environ.get("KF_BENCHMARKS_PLATFORM",
                   "kf_benchmarks_tpu.platforms.default.util"))

define_platform_params = _platform.define_platform_params
get_cluster_manager = _platform.get_cluster_manager
get_test_output_dir = _platform.get_test_output_dir
initialize = _platform.initialize
