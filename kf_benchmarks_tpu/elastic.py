"""Elastic scaling + adaptive batch size (the KungFu north-star features).

The reference delegates these to KungFu's external runtime: config-server
driven cluster resize and policy-driven hyperparameter adaptation fed by
gradient-noise-scale monitoring inside the collective ops (SURVEY 2.9
"elastic scaling / adaptive batch size", 5.3). Nothing in the reference
repo implements them; this module designs them TPU-natively:

* **Gradient noise scale** is measured inside the jitted train step
  (kf_benchmarks_tpu/train_step.py) from quantities the data-parallel
  step already has: per-replica gradients (small-batch estimate) vs the
  replica-mean gradient (large-batch estimate). Host-side EMAs turn the
  per-step estimates into the "simple noise scale" B_simple of
  McCandlish et al., "An Empirical Model of Large-Batch Training"
  (arXiv:1812.06162) -- the statistic KungFu's adaptation policies key on.
* **AdaptiveBatchPolicy** proposes a per-device batch size tracking
  B_simple with hysteresis (only power-of-two jumps, bounded range) so
  recompiles stay rare.
* **ElasticController** watches the native coordination service
  (native/kfcoord.cc) for generation bumps and returns the new target
  device count; the benchmark driver re-builds mesh + jitted steps and
  carries state across via the checkpoint snapshot/restore path
  ("checkpointed rescale", SURVEY 7.4: XLA programs are compiled for a
  fixed topology, so resize == re-jit + state re-shard).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


# -- in-step measurement (called from train_step inside shard_map) ----------

def noise_scale_stats(local_grads, axis_name, batch_size_per_replica: int):
  """Per-step (g2, s) estimates from per-replica vs replica-mean grads.

  With B_small = per-replica batch and B_big = global batch, the unbiased
  pair (arXiv:1812.06162 appendix A):
      g2 = (B_big*|G_big|^2 - B_small*E|G_small|^2) / (B_big - B_small)
      s  = (E|G_small|^2 - |G_big|^2) / (1/B_small - 1/B_big)
  and B_simple = s / g2 (host-side, after EMA smoothing).
  """
  n = lax.axis_size(axis_name)
  mean_grads = jax.tree.map(lambda g: lax.pmean(g, axis_name), local_grads)
  sq = lambda t: sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                     for x in jax.tree.leaves(t))
  local_sq = lax.pmean(sq(local_grads), axis_name)   # E|G_small|^2
  mean_sq = sq(mean_grads)                           # |G_big|^2
  b_small = float(batch_size_per_replica)
  b_big = b_small * n
  g2 = (b_big * mean_sq - b_small * local_sq) / (b_big - b_small)
  s = (local_sq - mean_sq) / (1.0 / b_small - 1.0 / b_big)
  return g2, s


# -- host-side smoothing -----------------------------------------------------

class NoiseScaleEMA:
  """EMA of the (g2, s) pair; B_simple = s_ema / g2_ema.

  Separate EMAs of numerator and denominator (not of the ratio) per
  arXiv:1812.06162 appendix A.3 -- the per-step ratio is wildly noisy.
  """

  def __init__(self, decay: float = 0.9):
    self.decay = decay
    self._g2 = None
    self._s = None

  def update(self, g2: float, s: float) -> None:
    if not (jnp.isfinite(g2) and jnp.isfinite(s)):
      return
    if self._g2 is None:
      self._g2, self._s = float(g2), float(s)
    else:
      d = self.decay
      self._g2 = d * self._g2 + (1 - d) * float(g2)
      self._s = d * self._s + (1 - d) * float(s)

  @property
  def b_simple(self) -> Optional[float]:
    if self._g2 is None or self._g2 <= 0:
      return None
    return max(self._s / self._g2, 0.0)


class AdaptiveBatchPolicy:
  """Propose a per-device batch size tracking the noise scale.

  KungFu's adaptive-batch policy grows the global batch as the gradient
  noise scale grows during training; here the proposal is
  B_simple / num_devices snapped to the nearest power of two within
  [min_batch, max_batch], with 2x hysteresis so the jitted step is only
  rebuilt on material changes.
  """

  def __init__(self, min_batch: int, max_batch: int):
    if min_batch < 1 or max_batch < min_batch:
      raise ValueError(f"invalid batch bounds [{min_batch}, {max_batch}]")
    self.min_batch = min_batch
    self.max_batch = max_batch

  def propose(self, current: int, b_simple: Optional[float],
              num_devices: int) -> int:
    if not b_simple or b_simple <= 0:
      return current
    target = max(b_simple / max(num_devices, 1), 1.0)
    # Snap to a power of two in bounds.
    snapped = 1 << max(round(float(jnp.log2(target))), 0)
    snapped = min(max(snapped, self.min_batch), self.max_batch)
    # Hysteresis: only move on >= 2x difference, and one octave at a time.
    if snapped >= current * 2:
      return current * 2
    if snapped * 2 <= current:
      return max(current // 2, self.min_batch)
    return current


# -- elastic membership ------------------------------------------------------

class ElasticController:
  """Polls the coordination service for resize requests.

  One client per process; ``poll()`` returns the new target device count
  when the coordinator's generation advanced past the last seen one, else
  None. Targets are clamped to the locally visible device count (on a
  real pod the membership service spans hosts; in-process we scale within
  the local mesh).
  """

  def __init__(self, client, max_devices: int):
    self._client = client
    self._max_devices = max_devices
    self._last_target: Optional[int] = None
    # The UNCLAMPED size of the last resize poll() surfaced: the
    # benchmark uses it to decide whether the target fits this process
    # set (in-mesh reshape) or needs a checkpoint-restart with a new
    # process count (kfrun restart leg, SURVEY 7.4).
    self.last_raw_target: Optional[int] = None

  @property
  def max_devices(self) -> int:
    """Per-process device capacity (locally visible devices)."""
    return self._max_devices

  @classmethod
  def from_env(cls, max_devices: int) -> Optional["ElasticController"]:
    host = os.environ.get("KFCOORD_HOST")
    port = os.environ.get("KFCOORD_PORT")
    if not (host and port):
      return None
    from kf_benchmarks_tpu.parallel import coordination
    try:
      client = coordination.CoordinatorClient(host=host, port=int(port),
                                              timeout_ms=2000)
    except RuntimeError:
      return None  # coordinator gone; run without elastic polling
    return cls(client, max_devices)

  def poll(self) -> Optional[int]:
    """Non-blocking: the new target device count if a RESIZE was issued
    since the last poll (including any issued before this controller
    started), else None."""
    try:
      target = self._client.try_target_size()
    except Exception:
      return None
    if target is None or target == self._last_target:
      return None
    self._last_target = target
    self.last_raw_target = target
    clamped = max(1, min(target, self._max_devices))
    # Run-trace marker at the poll that first SURFACED the resize (the
    # seam span itself is recorded by the benchmark driver around the
    # rebuild): the timeline then shows poll-to-reseam latency.
    from kf_benchmarks_tpu import tracing
    tracing.active().instant("elastic", "resize_target",
                             raw=int(target), clamped=int(clamped))
    return clamped

  def restart_barrier(self, name: str, count: int) -> None:
    """Rendezvous before a checkpoint-restart resize: guarantees the
    chief's snapshot is on disk (the chief enters after writing) before
    any worker exits for re-exec."""
    # all-ranks: every surviving worker of the resize enters with the
    # same (name, count) -- the caller passes the post-resize world
    # size, so attendance is exactly the agreed generation.
    self._client.barrier(name, count)

  def generation(self) -> int:
    return self._client.current_generation()

  # -- scheduled-restart agreement ------------------------------------------
  #
  # Workers poll the coordinator at the same STEP cadence but at
  # different WALL times, so a RESIZE can land between two workers'
  # polls of the same step -- an immediate restart would split-brain
  # (observed: one worker restarted, its sibling ran to completion).
  # Agreement: the first worker to see the target SCHEDULES the restart
  # at a future step in the coordinator's kv store; every worker adopts
  # the schedule at its own polls, so all restart at the same step (the
  # config-server-synchronized resize point of KungFu's runtime).

  def scheduled_restart(self):
    """(step, target_np) of the pending scheduled restart, else None."""
    try:
      gen = self._client.current_generation()
      val = self._client.kv_tryget(f"kf_restart_sched_{gen}")
    except Exception:
      return None
    if not val:
      return None
    step_s, _, np_s = val.decode().partition(":")
    return int(step_s), int(np_s)

  def schedule_restart(self, step: int, target_np: int) -> None:
    try:
      gen = self._client.current_generation()
      self._client.kv_put(f"kf_restart_sched_{gen}",
                          f"{step}:{target_np}".encode())
    except Exception as e:
      # poll() is one-shot per target (dedup on _last_target), so a
      # swallowed failure here would drop the resize silently. Reset the
      # dedup so the next poll re-sees the target and retries the put.
      import sys
      print(f"elastic: scheduling restart failed ({e}); will retry on "
            "the next poll", file=sys.stderr, flush=True)
      self._last_target = None

  def close(self) -> None:
    close = getattr(self._client, "close", None)
    if close:
      close()


def plan_resize(raw_target: int, procs: int, capacity: int,
                max_procs: int):
  """Classify a RESIZE target under the kfrun launcher.

  ``raw_target`` is the GLOBAL device count the coordinator was asked
  for; ``procs`` the live process count; ``capacity`` the per-process
  device capacity (locally attached devices); ``max_procs`` the
  provisioned host-list length (1 when no distributed world can form).

  Returns ("reshape", per_process_devices) whenever the target is
  EXACTLY satisfiable by the current process set (divisible by procs
  and within per-process capacity) -- an in-mesh re-jit is free compared
  to a restart, so it wins whenever it hits the requested size.
  Otherwise ("restart", required_procs): a live JAX world cannot change
  its process count, so the job must checkpoint + re-exec at the fewest
  processes that cover the target (a non-divisible target restarts too:
  the smaller process set can then hit it exactly in-mesh). Clamped to
  the provisioned hosts; if clamping lands back on the current count,
  the best-effort answer is a rounded-down in-mesh reshape.
  """
  capacity = max(1, capacity)
  procs = max(1, procs)
  if (raw_target % procs == 0 and
      procs <= raw_target <= procs * capacity):
    return "reshape", raw_target // procs
  required = min(max(1, -(-raw_target // capacity)), max(1, max_procs))
  if required == procs:
    return "reshape", min(max(1, raw_target // procs), capacity)
  return "restart", required


class ScheduledController:
  """Deterministic resize schedule {step: num_devices} -- the test/AB
  harness analog of coordinator-driven resizes."""

  def __init__(self, schedule: dict):
    self.schedule = dict(schedule)

  def poll_at(self, step: int) -> Optional[int]:
    return self.schedule.pop(step, None)
