"""COCO mAP computation, gated on pycocotools.

Host-side metric utility (ref: scripts/tf_cnn_benchmarks/coco_metric.py:
33-178 -- async mAP via pycocotools). pycocotools is not part of this
image's baked dependencies, so everything degrades gracefully: without
it (or without the annotation file) predictions pass through unchanged
and a note is attached instead of an mAP.

Non-max suppression runs here in numpy (the reference delegates NMS to
``tf.image.non_max_suppression`` inside its accuracy_function,
ssd_model.py:430-479).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from kf_benchmarks_tpu.models import ssd_constants
from kf_benchmarks_tpu.models import ssd_dataloader
from kf_benchmarks_tpu.utils import log as log_util


def nms(boxes: np.ndarray, scores: np.ndarray,
        iou_threshold: float = ssd_constants.OVERLAP_CRITERIA,
        max_out: int = ssd_constants.MAX_NUM_EVAL_BOXES) -> List[int]:
  """Greedy per-class NMS over ltrb boxes; returns kept indices."""
  order = np.argsort(-scores)
  keep: List[int] = []
  while order.size and len(keep) < max_out:
    i = order[0]
    keep.append(int(i))
    if order.size == 1:
      break
    rest = order[1:]
    iou = ssd_dataloader.calc_iou_matrix(boxes[i:i + 1], boxes[rest])[0]
    order = rest[iou <= iou_threshold]
  return keep


def select_detections(pred_boxes: np.ndarray, pred_scores: np.ndarray
                      ) -> List[Dict]:
  """Per-class score filter + NMS; detections as COCO-style dicts with
  normalized ltrb boxes and contiguous labels."""
  detections = []
  num_classes = pred_scores.shape[-1]
  for cls in range(1, num_classes):
    scores = pred_scores[:, cls]
    sel = scores > ssd_constants.MIN_SCORE
    if not np.any(sel):
      continue
    idx = np.nonzero(sel)[0]
    kept = nms(pred_boxes[idx], scores[idx])
    for k in kept:
      i = idx[k]
      detections.append({
          "label": cls,
          "score": float(scores[i]),
          "bbox_ltrb": pred_boxes[i].tolist(),
      })
  detections.sort(key=lambda d: -d["score"])
  return detections[:ssd_constants.MAX_NUM_EVAL_BOXES]


def maybe_compute_map(results: dict, params=None) -> dict:
  """Compute COCO mAP when possible; otherwise annotate and pass through
  (ref: coco_metric.py compute_map; async wrapper ssd_model.py:481-539).

  ``results`` carries accumulated per-image predictions under
  'predictions': a list of {source_id, pred_boxes, pred_scores,
  raw_shape}.
  """
  try:
    from pycocotools.coco import COCO
    from pycocotools.cocoeval import COCOeval
  except ImportError:
    results["coco_map_note"] = (
        "pycocotools unavailable in this environment; mAP skipped")
    return results
  data_dir = getattr(params, "data_dir", None) if params else None
  annotation_path = (os.path.join(data_dir, ssd_constants.ANNOTATION_FILE)
                     if data_dir else None)
  if not annotation_path or not os.path.exists(annotation_path):
    results["coco_map_note"] = "annotation file not found; mAP skipped"
    return results
  predictions = results.get("predictions", [])
  if not predictions:
    # Skip before parsing the ~450k-annotation json for nothing.
    results["coco_map_note"] = "no detections accumulated"
    return results
  coco_gt = COCO(annotation_path)
  detections = []
  for p in predictions:
    h, w = p["raw_shape"][:2]
    for d in select_detections(np.asarray(p["pred_boxes"]),
                               np.asarray(p["pred_scores"])):
      ymin, xmin, ymax, xmax = d["bbox_ltrb"]
      detections.append([
          int(p["source_id"]),
          xmin * w, ymin * h, (xmax - xmin) * w, (ymax - ymin) * h,
          d["score"],
          ssd_constants.CLASS_INV_MAP[d["label"]],
      ])
  if not detections:
    results["coco_map_note"] = "no detections accumulated"
    return results
  coco_dt = coco_gt.loadRes(np.asarray(detections))
  coco_eval = COCOeval(coco_gt, coco_dt, iouType="bbox")
  coco_eval.evaluate()
  coco_eval.accumulate()
  coco_eval.summarize()
  results["COCO/AP"] = float(coco_eval.stats[0])
  results["COCO/AP50"] = float(coco_eval.stats[1])
  log_util.log_fn("COCO mAP: %.4f" % results["COCO/AP"])
  return results
