"""COCO mAP computation, gated on pycocotools.

Host-side metric utility (ref: scripts/tf_cnn_benchmarks/coco_metric.py:
33-178 -- async mAP via pycocotools). pycocotools is not part of this
image's baked dependencies, so everything degrades gracefully: without
it (or without the annotation file) predictions pass through unchanged
and a note is attached instead of an mAP.

Non-max suppression runs here in numpy (the reference delegates NMS to
``tf.image.non_max_suppression`` inside its accuracy_function,
ssd_model.py:430-479).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from kf_benchmarks_tpu.models import ssd_constants
from kf_benchmarks_tpu.models import ssd_dataloader
from kf_benchmarks_tpu.utils import log as log_util


def nms(boxes: np.ndarray, scores: np.ndarray,
        iou_threshold: float = ssd_constants.OVERLAP_CRITERIA,
        max_out: int = ssd_constants.MAX_NUM_EVAL_BOXES) -> List[int]:
  """Greedy per-class NMS over ltrb boxes; returns kept indices."""
  order = np.argsort(-scores)
  keep: List[int] = []
  while order.size and len(keep) < max_out:
    i = order[0]
    keep.append(int(i))
    if order.size == 1:
      break
    rest = order[1:]
    iou = ssd_dataloader.calc_iou_matrix(boxes[i:i + 1], boxes[rest])[0]
    order = rest[iou <= iou_threshold]
  return keep


def select_detections(pred_boxes: np.ndarray, pred_scores: np.ndarray
                      ) -> List[Dict]:
  """Per-class score filter + NMS; detections as COCO-style dicts with
  normalized ltrb boxes and contiguous labels."""
  detections = []
  num_classes = pred_scores.shape[-1]
  for cls in range(1, num_classes):
    scores = pred_scores[:, cls]
    sel = scores > ssd_constants.MIN_SCORE
    if not np.any(sel):
      continue
    idx = np.nonzero(sel)[0]
    kept = nms(pred_boxes[idx], scores[idx])
    for k in kept:
      i = idx[k]
      detections.append({
          "label": cls,
          "score": float(scores[i]),
          "bbox_ltrb": pred_boxes[i].tolist(),
      })
  detections.sort(key=lambda d: -d["score"])
  return detections[:ssd_constants.MAX_NUM_EVAL_BOXES]


def _build_detections(predictions) -> List[List[float]]:
  """Accumulated per-image predictions -> COCO result rows
  [image_id, x, y, w, h, score, category_id] in pixel coords."""
  detections = []
  for p in predictions:
    h, w = p["raw_shape"][:2]
    for d in select_detections(np.asarray(p["pred_boxes"]),
                               np.asarray(p["pred_scores"])):
      ymin, xmin, ymax, xmax = d["bbox_ltrb"]
      detections.append([
          int(p["source_id"]),
          xmin * w, ymin * h, (xmax - xmin) * w, (ymax - ymin) * h,
          d["score"],
          ssd_constants.CLASS_INV_MAP[d["label"]],
      ])
  return detections


def _iou_xywh(det: np.ndarray, gts: np.ndarray) -> np.ndarray:
  """IoU of one [4] xywh box against [M,4] xywh boxes."""
  x0 = np.maximum(det[0], gts[:, 0])
  y0 = np.maximum(det[1], gts[:, 1])
  x1 = np.minimum(det[0] + det[2], gts[:, 0] + gts[:, 2])
  y1 = np.minimum(det[1] + det[3], gts[:, 1] + gts[:, 3])
  inter = np.clip(x1 - x0, 0, None) * np.clip(y1 - y0, 0, None)
  union = det[2] * det[3] + gts[:, 2] * gts[:, 3] - inter
  return inter / np.clip(union, 1e-12, None)


_IOU_THRS = np.arange(0.5, 1.0, 0.05)
_RECALL_THRS = np.linspace(0.0, 1.0, 101)


_MAX_DETS = 100  # COCOeval maxDets for the headline AP


def compute_map_numpy(gt_json: dict, detections: List[List[float]]) -> dict:
  """COCO bbox AP without pycocotools.

  Pure-numpy re-implementation of COCOeval's bbox protocol: top-100
  detections per image, greedy score-ordered matching per image/category
  at IoU thresholds .50:.05:.95, detections unmatched to real ground
  truth but overlapping an iscrowd region are ignored (neither TP nor
  FP; crowd overlap uses intersection/det_area as pycocotools does),
  101-point interpolated precision averaged over categories present in
  the ground truth. pycocotools (C) is what the reference uses
  (ref: coco_metric.py:33-178); it is not in this image, so this
  fallback keeps the mAP path executable end-to-end.
  """
  gt_by_img_cat = {}
  crowd_by_img_cat = {}
  cats_with_gt = set()
  for ann in gt_json.get("annotations", []):
    key = (int(ann["image_id"]), int(ann["category_id"]))
    if ann.get("iscrowd"):
      crowd_by_img_cat.setdefault(key, []).append(ann["bbox"])
    else:
      gt_by_img_cat.setdefault(key, []).append(ann["bbox"])
      cats_with_gt.add(int(ann["category_id"]))

  # maxDets cap: keep each image's top-100 detections by score.
  det_by_img = {}
  for row in detections:
    det_by_img.setdefault(int(row[0]), []).append(row)
  det_by_cat = {}
  for img, rows in det_by_img.items():
    rows.sort(key=lambda r: -r[5])
    for row in rows[:_MAX_DETS]:
      det_by_cat.setdefault(int(row[6]), []).append(row)

  ap_per_cat_thr = []  # [cats, thrs]
  for cat in sorted(cats_with_gt):
    rows = sorted(det_by_cat.get(cat, []), key=lambda r: -r[5])
    n_gt = sum(len(v) for (img, c), v in gt_by_img_cat.items() if c == cat)
    if n_gt == 0:
      continue
    # IoUs are threshold-independent: compute each detection's IoU
    # vector against its image's gt (and crowd overlap) exactly once.
    gt_arrays = {}
    det_ious = []      # per detection: (image_id, iou vector over gts)
    det_crowd = []     # per detection: max intersection/det_area vs crowds
    for row in rows:
      img = int(row[0])
      if img not in gt_arrays:
        gt_arrays[img] = np.asarray(gt_by_img_cat.get((img, cat), []),
                                    np.float64).reshape(-1, 4)
      gts = gt_arrays[img]
      det = np.asarray(row[1:5], np.float64)
      det_ious.append((img, _iou_xywh(det, gts) if len(gts) else
                       np.zeros((0,))))
      crowds = np.asarray(crowd_by_img_cat.get((img, cat), []),
                          np.float64).reshape(-1, 4)
      if len(crowds) and det[2] * det[3] > 0:
        x0 = np.maximum(det[0], crowds[:, 0])
        y0 = np.maximum(det[1], crowds[:, 1])
        x1 = np.minimum(det[0] + det[2], crowds[:, 0] + crowds[:, 2])
        y1 = np.minimum(det[1] + det[3], crowds[:, 1] + crowds[:, 3])
        inter = np.clip(x1 - x0, 0, None) * np.clip(y1 - y0, 0, None)
        det_crowd.append(float(np.max(inter / (det[2] * det[3]))))
      else:
        det_crowd.append(0.0)
    aps = np.zeros(len(_IOU_THRS))
    for ti, thr in enumerate(_IOU_THRS):
      matched = {}  # image_id -> set of matched gt indices
      tp = np.zeros(len(rows))
      ignored = np.zeros(len(rows), bool)
      for di, (img, ious) in enumerate(det_ious):
        used = matched.setdefault(img, set())
        hit = False
        for gi in np.argsort(-ious):
          if ious[gi] >= thr and int(gi) not in used:
            used.add(int(gi))
            tp[di] = 1.0
            hit = True
            break
        if not hit and det_crowd[di] >= thr:
          ignored[di] = True  # crowd overlap: neither TP nor FP
      keep = ~ignored
      cum_tp = np.cumsum(tp[keep])
      cum_fp = np.cumsum(1.0 - tp[keep])
      recall = cum_tp / n_gt
      precision = cum_tp / np.clip(cum_tp + cum_fp, 1e-12, None)
      # Monotone-decreasing precision envelope, then 101-point sample.
      for i in range(len(precision) - 2, -1, -1):
        precision[i] = max(precision[i], precision[i + 1])
      ap = 0.0
      for r in _RECALL_THRS:
        idx = np.searchsorted(recall, r, side="left")
        ap += precision[idx] if idx < len(precision) else 0.0
      aps[ti] = ap / len(_RECALL_THRS)
    ap_per_cat_thr.append(aps)
  if not ap_per_cat_thr:
    return {"COCO/AP": 0.0, "COCO/AP50": 0.0}
  stacked = np.stack(ap_per_cat_thr)  # [cats, thrs]
  return {"COCO/AP": float(stacked.mean()),
          "COCO/AP50": float(stacked[:, 0].mean())}


def maybe_compute_map(results: dict, params=None) -> dict:
  """Compute COCO mAP when possible; otherwise annotate and pass through
  (ref: coco_metric.py compute_map; async wrapper ssd_model.py:481-539).

  ``results`` carries accumulated per-image predictions under
  'predictions': a list of {source_id, pred_boxes, pred_scores,
  raw_shape}. Uses pycocotools when importable, else the in-repo numpy
  evaluator (results['coco_evaluator'] records which ran).
  """
  data_dir = getattr(params, "data_dir", None) if params else None
  annotation_path = (os.path.join(data_dir, ssd_constants.ANNOTATION_FILE)
                     if data_dir else None)
  if not annotation_path or not os.path.exists(annotation_path):
    results["coco_map_note"] = "annotation file not found; mAP skipped"
    return results
  predictions = results.get("predictions", [])
  if not predictions:
    # Skip before parsing the ~450k-annotation json for nothing.
    results["coco_map_note"] = "no detections accumulated"
    return results
  detections = _build_detections(predictions)
  if not detections:
    results["coco_map_note"] = "no detections accumulated"
    return results
  try:
    from pycocotools.coco import COCO
    from pycocotools.cocoeval import COCOeval
    coco_gt = COCO(annotation_path)
    coco_dt = coco_gt.loadRes(np.asarray(detections))
    coco_eval = COCOeval(coco_gt, coco_dt, iouType="bbox")
    coco_eval.evaluate()
    coco_eval.accumulate()
    coco_eval.summarize()
    results["COCO/AP"] = float(coco_eval.stats[0])
    results["COCO/AP50"] = float(coco_eval.stats[1])
    results["coco_evaluator"] = "pycocotools"
  except ImportError:
    import json
    with open(annotation_path) as f:
      gt_json = json.load(f)
    results.update(compute_map_numpy(gt_json, detections))
    results["coco_evaluator"] = "numpy"
  log_util.log_fn("COCO mAP: %.4f" % results["COCO/AP"])
  return results
