"""CLI entry point (ref: scripts/tf_cnn_benchmarks/tf_cnn_benchmarks.py).

Run with: python -m kf_benchmarks_tpu.cli --model=resnet50 --num_batches=100
"""

from __future__ import annotations

import sys

from absl import app

from kf_benchmarks_tpu import flags, params as params_lib


def main(positional_arguments):
  # Command-line arguments like '--model resnet50' are equivalent to
  # '--model=resnet50'; positional args are forbidden
  # (ref: tf_cnn_benchmarks.py:41-46).
  assert len(positional_arguments) >= 1
  if len(positional_arguments) > 1:
    raise app.UsageError(
        "Received unknown positional arguments: %s" % positional_arguments[1:])

  from kf_benchmarks_tpu import benchmark
  from kf_benchmarks_tpu.parallel import kungfu

  params = params_lib.make_params_from_flags()
  params = benchmark.setup(params)
  bench = benchmark.BenchmarkCNN(params)
  stats = bench.run()

  # Cross-process elastic resize: the run checkpointed and barriered;
  # exit with the launcher's restart code so kfrun re-execs this worker
  # set at the new world size (SURVEY 5.3/7.4 checkpointed rescale).
  if isinstance(stats, dict) and stats.get("restart_for_resize"):
    from kf_benchmarks_tpu import kfrun
    sys.exit(kfrun.RESTART_EXIT_CODE)

  # KungFu exit barrier (ref: tf_cnn_benchmarks.py:58-60).
  if params.variable_update == "kungfu":
    # all-ranks: --variable_update is identical on every kfrun worker
    # (one command line, N launches), so attendance is all-or-nothing.
    kungfu.run_barrier()


def run_main():
  # Vendor-extension point before flags materialize
  # (ref: tf_cnn_benchmarks.py main wiring; platforms/default/util.py:28).
  from kf_benchmarks_tpu.platforms import util as platforms_util
  platforms_util.define_platform_params()
  flags.define_flags(aliases=params_lib.ALIASES)
  app.run(main)


if __name__ == "__main__":
  run_main()
