"""Runtime training-health telemetry: in-step device stats, flight
recorder, stall watchdog.

TPU-native-only subsystem with no reference analog: the reference's
observability is post-hoc -- a Chrome trace of one step, tfprof top-ops
and tiered summaries (SURVEY 5.1/9) -- and nothing there watches a
RUNNING job. This deployment's dominant failure modes (tunnel wedges,
20-35 min backend hangs, silent CPU fallback, fp16 loss-scale collapse;
CLAUDE.md hazards) all strike mid-run, so this layer follows the
MLPerf structured-run-logging norm (Mattson et al., "MLPerf Training
Benchmark"): every step leaves an auditable record, and anomalies dump
a post-mortem window instead of a dead terminal.

Three cooperating pieces:

* In-step health stats: ``health_partials``/``health_finalize`` build
  the compact f32 vector (global grad norm, update/param norm ratio,
  non-finite leaf count, loss scale + skip flag) that train_step.py
  computes INSIDE the compiled step -- each replica reduces a 1/n
  slice of every tree and the pre-scaled partial sums ride the
  existing loss pmean, so the health-on program carries NO extra
  collective AND no replicated full-tree passes (the roofline-free
  claim holds on param-bound models too) -- gated by
  ``--health_stats`` (``resolve_health_stats``; default auto = on for
  replica-synchronous training with a telemetry sink --
  ``--train_dir``/``--benchmark_log_dir``).
* Flight recorder: a bounded ring of per-step JSON records continuously
  rewritten to ``train_dir/flight_recorder.jsonl`` (the file always
  holds the newest window), with the full window + a diagnosis line
  appended to ``flight_recorder.dump.jsonl`` on anomaly (non-finite
  grads/loss, grad-norm spike beyond a configurable sigma, loss-scale
  halving streak), on SIGTERM/SIGINT, and at run end.
* Stall watchdog: a daemon thread fed heartbeats at dispatch
  boundaries. Before the first completed dispatch it is PATIENT
  (first compiles over the tunnel legitimately run >30 min; log-only).
  Mid-run, silence beyond ``factor`` x the trailing mean chunk wall
  emits a diagnostic (last flight-recorder rows + tunnel state) and
  NEVER kills the process -- a kill mid-claim is exactly the
  tunnel-wedge trigger (CLAUDE.md); liveness signals come from real
  value fetches (utils/sync.py drain semantics), never
  ``block_until_ready``, which lies on this backend.
"""

from __future__ import annotations

import collections
import json
import math
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax

from kf_benchmarks_tpu import compat  # noqa: F401 (lax.axis_size shim)
from kf_benchmarks_tpu import metrics as metrics_lib
from kf_benchmarks_tpu.utils import log as log_util


# Order of the in-step health vector (health_finalize builds it from
# the pmean'd health_partials inside the step). Single-sourced in the
# metric registry (metrics.py), where every health/<key> scalar the
# recorder emits is registered.
HEALTH_KEYS = metrics_lib.HEALTH_KEYS


# -- in-step stats (compiled side) -------------------------------------------

def _sharded_sumsq(leaf, index, num):
  """This replica's partial square-sum of ``leaf``: row ``index`` of the
  flattened leaf reshaped (num, size//num), plus the < num-element tail
  on replica 0. Each replica touches ~1/num of the leaf, so the health
  pass costs one tree read TOTAL across the mesh instead of one per
  replica -- without this the stats were measured at ~2x step time on
  param-bound models (the reductions replicated n-fold)."""
  flat = leaf.reshape(-1).astype(jnp.float32)
  k = flat.size // num
  part = jnp.float32(0.0)
  if k:
    rows = flat[:num * k].reshape(num, k)
    row = lax.dynamic_index_in_dim(rows, index, axis=0, keepdims=False)
    part = jnp.sum(jnp.square(row))
  tail = flat[num * k:]
  if tail.size:
    part = part + jnp.where(index == 0, jnp.sum(jnp.square(tail)),
                            jnp.float32(0.0))
  return part


def health_partials(grads, params, updates, axis_name):
  """This replica's sharded partial sums for the in-step health stats,
  as one f32 vector ``[grad_sq(leaf 0..L-1), update_sq, param_sq]``
  pre-scaled by the replica count so the caller's single loss pmean
  (a MEAN) yields global SUMS; ``health_finalize`` turns the pmean'd
  totals into the HEALTH_KEYS vector.

  All inputs are replica-identical for the replica-synchronous
  strategies ``resolve_health_stats`` admits: ``grads`` is the APPLIED
  gradient tree (under relaxed consistency the deferred bank, matching
  the existing grad_norm metric convention), ``updates`` the optimizer
  update tree bracketing ``params``. Grad partials stay per-leaf so
  the non-finite LEAF count survives the reduction.
  """
  index = lax.axis_index(axis_name)
  num = lax.axis_size(axis_name)

  def _tree_sumsq(tree):
    leaves = jax.tree.leaves(tree)
    if not leaves:
      return jnp.float32(0.0)
    return sum(_sharded_sumsq(l, index, num) for l in leaves)

  grad_sq = [_sharded_sumsq(g, index, num)
             for g in jax.tree.leaves(grads)] or [jnp.float32(0.0)]
  vec = jnp.stack(grad_sq + [_tree_sumsq(updates), _tree_sumsq(params)])
  return vec * jnp.float32(num)


def health_finalize(totals, loss_scale, skipped, update_suppressed):
  """The HEALTH_KEYS vector from the pmean'd ``health_partials``
  (global per-leaf grad square-sums + update/param square-sums).

  A leaf counts as non-finite when its global square-sum is (any
  nan/inf element poisons the sum; a finite-value overflow of the f32
  sum also lands here, which is an anomaly worth flagging anyway).
  ``update_ratio`` is the per-step relative weight motion an operator
  eyeballs for LR sanity (~1e-3 healthy); ``update_suppressed`` zeroes
  it on steps whose apply was skipped by the loss-scale machine (the
  optimizer's would-be update tree is non-finite there).
  """
  grad_sq = totals[:-2]
  upd_sq, param_sq = totals[-2], totals[-1]
  grad_norm = jnp.sqrt(jnp.sum(grad_sq))
  nonfinite = jnp.sum(1.0 - jnp.isfinite(grad_sq).astype(jnp.float32))
  ratio = jnp.where(
      jnp.asarray(update_suppressed, jnp.float32) > 0, jnp.float32(0.0),
      jnp.sqrt(upd_sq) / jnp.maximum(jnp.sqrt(param_sq), 1e-12))
  return jnp.stack([grad_norm, ratio, nonfinite,
                    jnp.asarray(loss_scale, jnp.float32),
                    jnp.asarray(skipped, jnp.float32)])


def health_scalars(metrics) -> Dict[str, float]:
  """Expand a metrics dict's packed health vector into named scalars.

  The ONE schema shared by the flight-recorder records and the
  SummaryWriter scalar stream: both carry ``health/<key>`` entries, so
  a recorder row and a summary event line up field-for-field.
  """
  vec = metrics.get("health") if isinstance(metrics, dict) else None
  if vec is None:
    return {}
  arr = np.asarray(vec, np.float32).ravel()
  if arr.size != len(HEALTH_KEYS):
    return {}
  # Key construction goes through the registry's health_key helper --
  # the metric-key-literal lint bans assembling the health/ namespace
  # anywhere outside metrics.py.
  return {metrics_lib.health_key(k): float(v)
          for k, v in zip(HEALTH_KEYS, arr)}


# variable_update modes whose gradient reduction leaves every replica
# holding the SAME applied gradient tree -- the precondition for the
# in-step stats being global values rather than replica-local ones.
_SYNC_REPLICATED_UPDATES = (
    "replicated", "distributed_replicated", "parameter_server",
    "collective_all_reduce", "distributed_all_reduce", "horovod")


def resolve_health_stats(params, strategy=None):
  """Resolve ``--health_stats`` (None = auto) -> (enabled, note).

  Auto turns the stats ON for training runs that (a) reduce gradients
  replica-synchronously (``strategy.cross_replica``; replicated family
  / kungfu sync_sgd) and (b) have a telemetry SINK to record into
  (``--train_dir`` for the flight-recorder files, or
  ``--benchmark_log_dir`` for the health metric row). Gossip/async
  modes auto-off with a one-line note (the per-replica gradient trees
  diverge, so a "global" norm would silently be replica-local);
  sink-less runs auto-off quietly -- nothing durable would be recorded,
  and the in-step readout is not free (it rides the step's tail, after
  the optimizer apply). Explicit ``--health_stats`` always engages
  (the window stays in memory and anomalies still dump to the log);
  explicit True with an incompatible mode is rejected up front in
  validation.validate_cross_flags.
  """
  v = getattr(params, "health_stats", None)
  if v is False:
    return False, None
  if getattr(params, "eval", False) or getattr(params, "forward_only",
                                               False):
    # Training-only: there is no gradient tree to measure.
    return False, None
  if (getattr(params, "shard_optimizer_state", False) or
      (strategy is not None and getattr(strategy, "sharded_state",
                                        False))):
    # Sharded-state steps apply the optimizer on 1/n flat shards
    # (train_step.py + ops/sharded.py): the full update tree the stats
    # read never materializes. Explicit --health_stats is rejected up
    # front (validation.py); auto resolves off with a note when a sink
    # asked for telemetry, quietly otherwise.
    if getattr(params, "train_dir", None) or getattr(
        params, "benchmark_log_dir", None):
      return False, (
          "health_stats: --shard_optimizer_state applies the optimizer "
          "on per-device state shards; the full-tree in-step stats are "
          "disabled (elastic/fault-injected runs with a train_dir keep "
          "their flight-recorder/watchdog session regardless)")
    return False, None
  if strategy is not None:
    cross = bool(getattr(strategy, "cross_replica", False))
  else:
    cross = (
        (params.variable_update in _SYNC_REPLICATED_UPDATES and
         bool(getattr(params, "cross_replica_sync", True))) or
        (params.variable_update == "kungfu" and
         getattr(params, "kungfu_option", None) == "sync_sgd"))
  if not cross:
    return False, (
        "health_stats: --variable_update=%s keeps per-replica gradient "
        "trees (no replica-synchronous reduction); in-step health stats "
        "disabled -- pass --health_stats with a replicated-family mode "
        "to enable them" % params.variable_update)
  if v is None and not (getattr(params, "train_dir", None) or
                        getattr(params, "benchmark_log_dir", None)):
    return False, None
  return True, None


def flight_recorder_path(train_dir: Optional[str], rank: int = 0
                         ) -> Optional[str]:
  """Per-rank continuous-window path: rank 0 owns the canonical
  ``flight_recorder.jsonl``; other ranks write rank-suffixed files the
  rank-0 exit aggregation merges (``aggregate_rank_windows``)."""
  if not train_dir:
    return None
  name = ("flight_recorder.jsonl" if rank == 0
          else f"flight_recorder.rank{rank}.jsonl")
  return os.path.join(train_dir, name)


def aggregate_rank_windows(train_dir: str) -> List[dict]:
  """Merge every rank's continuous window under ``train_dir`` into one
  step-ordered record list (rank breaks ties), for the rank-0 exit
  aggregation in multi-process runs."""
  records = []
  try:
    names = sorted(os.listdir(train_dir))
  except OSError:
    return records
  for name in names:
    if not (name.startswith("flight_recorder") and
            name.endswith(".jsonl") and ".dump." not in name and
            name != "flight_recorder.all.jsonl"):
      continue
    try:
      with open(os.path.join(train_dir, name)) as f:
        for line in f:
          line = line.strip()
          if line:
            records.append(json.loads(line))
    except (OSError, ValueError):
      continue
  records.sort(key=lambda r: (r.get("step", 0), r.get("rank", 0)))
  return records


# -- flight recorder (host side) ---------------------------------------------

class FlightRecorder:
  """Bounded ring of per-step records with anomaly-triggered dumps.

  ``record()`` is called once per completed step with that step's
  scraped metrics; the newest ``window`` records are continuously
  rewritten to ``path`` (atomic replace, so a reader never sees a torn
  window), and anomalies append the full window + a diagnosis record to
  ``<dir>/flight_recorder.dump.jsonl`` -- append-mode, so a clean-exit
  dump never clobbers the mid-run post-mortem that mattered.
  """

  # Consecutive loss-scale halvings that count as a collapse streak
  # (each halving is one overflow-skipped step of the auto-loss-scale
  # machine; three in a row is divergence, not noise).
  HALVING_STREAK = 3

  def __init__(self, path: Optional[str] = None, window: int = 64,
               sigma: float = 6.0, rank: int = 0, log_fn=None,
               min_history: int = 8, run_id: Optional[str] = None):
    self.path = path
    # Shared with the run trace (tracing.py resolve_run_id): one run id
    # across recorder rows and trace events, so a post-mortem window
    # can be laid over the span timeline it belongs to.
    self.run_id = run_id
    self.dump_path = (os.path.join(os.path.dirname(path),
                                   "flight_recorder.dump.jsonl")
                      if path else None)
    if path:
      # The continuous window must hit disk from step 1 -- its whole
      # point is surviving a mid-run death. Checkpointing creates
      # train_dir only at the first save, so without this every
      # in-run _write_window dies on FileNotFoundError (a swallowed
      # OSError) and only the post-checkpoint exit dump ever lands.
      try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
      except OSError:
        pass  # unwritable sink: record() keeps the in-memory window
    self.window = max(1, int(window))
    self.sigma = float(sigma)
    self.rank = int(rank)
    self._log = log_fn or log_util.log_fn
    self._min_history = max(2, int(min_history))
    self._records: "collections.deque[dict]" = collections.deque(
        maxlen=self.window)
    self._prev_scale: Optional[float] = None
    self._halvings = 0
    self._skip_streak = 0
    self._in_anomaly = False
    self._old_handlers: Dict[int, Any] = {}
    # Summary counters (bench.py's health JSON fields).
    self._max_grad_norm: Optional[float] = None
    self._nonfinite_steps = 0
    self._anomaly_dumps = 0
    self._last_scale: Optional[float] = None

  # -- recording ------------------------------------------------------------

  def _stamp(self, rec: Dict[str, Any]) -> Dict[str, Any]:
    """Wall + MONOTONIC timestamps (and the shared run id) on every
    row: the wall clock anchors the row in operator time, the
    monotonic one lays it over the run-trace timeline (tracing.py uses
    the same clock for spans), immune to wall-clock steps mid-run."""
    rec["t_wall"] = round(time.time(), 3)
    rec["t_mono"] = round(time.monotonic(), 6)
    if self.run_id:
      rec["run_id"] = self.run_id
    return rec

  def record(self, step: int, loss: Optional[float] = None, lr=None,
             health=None, wall_ms: Optional[float] = None,
             chunk_len: int = 1, rtt_ms: Optional[float] = None,
             span_id: Optional[int] = None) -> dict:
    """Append one per-step record; detect anomalies against the
    TRAILING window (the current record is judged, not self-judged);
    rewrite the continuous window file. ``span_id`` cross-links the
    enclosing run-trace span (the dispatch this step resolved in), so
    a post-mortem dump can be laid over the exported timeline."""
    rec: Dict[str, Any] = self._stamp({"step": int(step),
                                       "rank": self.rank})
    if span_id:
      rec["span_id"] = int(span_id)
    if loss is not None:
      rec["loss"] = float(loss)
    if lr is not None:
      rec["lr"] = float(lr)
    if wall_ms is not None:
      rec["wall_ms"] = round(float(wall_ms), 3)
    if chunk_len != 1:
      rec["chunk_len"] = int(chunk_len)
    if rtt_ms is not None:
      rec["rtt_ms"] = round(float(rtt_ms), 3)
    rec.update(health_scalars({"health": health}))

    reasons = self._detect_anomalies(rec)
    self._records.append(rec)
    self._update_summary(rec)
    self._write_window()
    if reasons:
      if not self._in_anomaly:
        # Edge-triggered: one dump per anomaly episode, not per step of
        # a divergence that lasts the rest of the run.
        self._anomaly_dumps += 1
        self.dump("; ".join(reasons))
      self._in_anomaly = True
    else:
      self._in_anomaly = False
    return rec

  def _detect_anomalies(self, rec: dict) -> List[str]:
    reasons = []
    step = rec["step"]
    loss = rec.get("loss")
    nonfinite = rec.get("health/nonfinite_leaves", 0.0)
    gn = rec.get("health/grad_norm")
    if (nonfinite and nonfinite > 0) or (
        loss is not None and not math.isfinite(loss)) or (
        gn is not None and not math.isfinite(gn)):
      reasons.append(
          f"non-finite training signal at step {step} "
          f"(nonfinite_leaves={nonfinite:.0f}, loss={loss})")
    if gn is not None and math.isfinite(gn):
      trail = [r["health/grad_norm"] for r in self._records
               if math.isfinite(r.get("health/grad_norm", float("nan")))]
      if len(trail) >= self._min_history:
        mean = sum(trail) / len(trail)
        std = math.sqrt(sum((t - mean) ** 2 for t in trail) / len(trail))
        if std > 0 and gn > mean + self.sigma * std:
          reasons.append(
              f"grad-norm spike at step {step}: {gn:.3e} > trailing "
              f"mean {mean:.3e} + {self.sigma:g} sigma ({std:.3e})")
    scale = rec.get("health/loss_scale")
    skipped = rec.get("health/skipped", 0.0)
    if scale is not None:
      if self._prev_scale is not None and scale < self._prev_scale:
        self._halvings += 1
      elif self._prev_scale is not None and scale >= self._prev_scale:
        self._halvings = 0
      self._prev_scale = scale
      # The scale floors at 1.0 (train_step.py), so sustained overflow
      # stops halving but keeps skipping: count both signals.
      self._skip_streak = self._skip_streak + 1 if skipped else 0
      if max(self._halvings, self._skip_streak) == self.HALVING_STREAK:
        reasons.append(
            f"loss-scale collapse at step {step}: "
            f"{self.HALVING_STREAK} consecutive "
            f"{'halvings' if self._halvings >= self.HALVING_STREAK else 'skipped updates'}"
            f" (scale now {scale:g})")
    return reasons

  def _update_summary(self, rec: dict) -> None:
    gn = rec.get("health/grad_norm")
    if gn is not None and math.isfinite(gn):
      self._max_grad_norm = (gn if self._max_grad_norm is None
                             else max(self._max_grad_norm, gn))
    loss = rec.get("loss")
    if (rec.get("health/nonfinite_leaves", 0.0) > 0 or
        (loss is not None and not math.isfinite(loss))):
      self._nonfinite_steps += 1
    if rec.get("health/loss_scale") is not None:
      self._last_scale = rec["health/loss_scale"]

  def _write_window(self) -> None:
    if not self.path:
      return
    tmp = self.path + ".tmp"
    try:
      with open(tmp, "w") as f:
        for r in self._records:
          f.write(json.dumps(r) + "\n")
      os.replace(tmp, self.path)
    except OSError:
      pass  # a failed telemetry write must never take down the run

  def tail(self, n: int = 3) -> List[dict]:
    return list(self._records)[-n:]

  def note_event(self, event: Dict[str, Any]) -> dict:
    """Append a non-step event record (elastic resize, injected fault)
    to the ring + continuous window -- the post-mortem that follows a
    preemption must show WHAT the run was doing, not just its losses.
    Events bypass anomaly detection (they are operator actions, not
    training signals)."""
    rec = self._stamp({"rank": self.rank})
    rec.update(event)
    self._records.append(rec)
    self._write_window()
    return rec

  # -- dumps ----------------------------------------------------------------

  def dump(self, reason: str) -> None:
    """Append the full window + a diagnosis record to the dump file and
    emit one diagnosis line through log_fn (one whole line: telemetry
    must never interleave inside a step line, tests/test_benchmark.py)."""
    diagnosis = {
        "flight_recorder_dump": reason,
        "rank": self.rank,
        "records": len(self._records),
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    where = "window retained in memory (no --train_dir)"
    if self.dump_path:
      try:
        with open(self.dump_path, "a") as f:
          f.write(json.dumps(diagnosis) + "\n")
          for r in self._records:
            f.write(json.dumps(r) + "\n")
        where = f"{len(self._records)}-record window dumped to " \
                f"{self.dump_path}"
      except OSError as e:
        where = f"dump write failed ({e})"
    self._log(f"flight recorder: {reason} -- {where}")

  # -- signal handlers ------------------------------------------------------

  def install_signal_handlers(self) -> None:
    """Dump the window on SIGTERM/SIGINT, then chain to the previous
    handler (so ctrl-C still interrupts and a SIGTERM still terminates
    -- the recorder adds a post-mortem, it never swallows the signal)."""
    for signum in (signal.SIGTERM, signal.SIGINT):
      try:
        self._old_handlers[signum] = signal.signal(
            signum, self._handle_signal)
      except ValueError:
        # Not the main thread (e.g. a test harness worker): signals
        # cannot be installed there; recorder still works sans handlers.
        pass

  def _handle_signal(self, signum, frame) -> None:
    self.dump(f"signal {signal.Signals(signum).name}")
    old = self._old_handlers.get(signum)
    signal.signal(signum, old if old is not None else signal.SIG_DFL)
    signal.raise_signal(signum)

  def close(self) -> None:
    """Restore any installed signal handlers (tests run in-process;
    a leaked handler would outlive its recorder)."""
    for signum, old in self._old_handlers.items():
      try:
        if signal.getsignal(signum) == self._handle_signal:
          signal.signal(signum, old)
      except ValueError:
        pass
    self._old_handlers.clear()

  def summary(self) -> Dict[str, Any]:
    return {
        "records": len(self._records),
        "max_grad_norm": self._max_grad_norm,
        "nonfinite_steps": self._nonfinite_steps,
        "loss_scale_final": self._last_scale,
        "anomaly_dumps": self._anomaly_dumps,
    }


# -- stall watchdog ----------------------------------------------------------

class StallWatchdog:
  """Daemon thread that watches dispatch-boundary heartbeats.

  Two regimes, split on whether ANY dispatch has completed:

  * First compile / first claim (no heartbeat yet): PATIENT. A novel
    program over the tunnel can take >30 min with ~0 host CPU
    (CLAUDE.md); the watchdog logs a reassurance line every
    ``patience_s`` and does nothing else.
  * Mid-run: silence longer than ``factor`` x the trailing mean chunk
    wall (floored at ``min_stall_s``) emits ONE diagnostic per stall
    episode -- the last flight-recorder rows plus tunnel state -- and
    counts it. It NEVER kills, signals, or interrupts the process: the
    documented wedge trigger is exactly a client killed mid-claim.

  Heartbeats come from the host observing real completed work (metric
  fetches / drain, utils/sync.py) -- never ``block_until_ready``, which
  returns early on this backend.
  """

  TRAILING_WINDOW = 16

  def __init__(self, factor: float = 10.0, poll_s: float = 1.0,
               patience_s: float = 600.0, min_stall_s: float = 5.0,
               log_fn=None, recorder: Optional[FlightRecorder] = None,
               time_fn=time.monotonic):
    self.factor = float(factor)
    self.poll_s = float(poll_s)
    self.patience_s = float(patience_s)
    self.min_stall_s = float(min_stall_s)
    self._log = log_fn or log_util.log_fn
    self._recorder = recorder
    self._time = time_fn
    self._lock = threading.Lock()
    self._walls: "collections.deque[float]" = collections.deque(
        maxlen=self.TRAILING_WINDOW)
    self._last_beat = self._time()
    self._beats = 0
    self._stalls = 0
    self._stalled = False
    self._last_patient_log: Optional[float] = None
    self._stop_event = threading.Event()
    self._thread: Optional[threading.Thread] = None

  @property
  def enabled(self) -> bool:
    return self.factor > 0

  @property
  def stalls(self) -> int:
    return self._stalls

  def start(self) -> None:
    if not self.enabled or self._thread is not None:
      return
    with self._lock:
      self._last_beat = self._time()
    self._thread = threading.Thread(
        target=self._run, name="kf-stall-watchdog", daemon=True)
    self._thread.start()

  def beat(self, wall_s: Optional[float] = None) -> None:
    """Mark a completed dispatch; ``wall_s`` (the chunk wall interval)
    feeds the trailing-mean stall threshold."""
    with self._lock:
      self._last_beat = self._time()
      self._beats += 1
      self._stalled = False
      if wall_s is not None and wall_s > 0:
        self._walls.append(float(wall_s))

  def stop(self) -> None:
    self._stop_event.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None

  def _run(self) -> None:
    while not self._stop_event.wait(self.poll_s):
      try:
        self._check(self._time())
      except Exception as e:
        # A watchdog crash must never take down the run -- but one
        # failed evaluation (say, an OSError out of the injected
        # log_fn) must not silently retire the thread either, or every
        # later stall goes undetected while summary() reports healthy.
        try:
          self._log(f"stall watchdog: check failed ({e!r}); continuing")
        except Exception:
          pass  # the log sink itself is down; keep polling regardless

  def _check(self, now: float) -> None:
    """One watchdog evaluation at host time ``now`` (separated from the
    thread loop so tests can drive it with a fake clock)."""
    with self._lock:
      idle = now - self._last_beat
      beats = self._beats
      walls = list(self._walls)
      stalled = self._stalled
    if beats == 0:
      # First compile / first tunnel claim: patient, log-only.
      if idle > self.patience_s and (
          self._last_patient_log is None or
          now - self._last_patient_log > self.patience_s):
        self._last_patient_log = now
        self._log(
            "stall watchdog: no dispatch completed yet after "
            f"{idle / 60.0:.1f} min -- first compile/claim can "
            "legitimately exceed 30 min on this backend; staying "
            "patient (killing mid-claim wedges the tunnel, CLAUDE.md)")
      return
    trailing = sum(walls) / len(walls) if walls else None
    threshold = max(self.factor * trailing if trailing else 0.0,
                    self.min_stall_s)
    if idle > threshold and not stalled:
      with self._lock:
        self._stalls += 1
        self._stalled = True
      self._emit_diagnostic(idle, trailing)
    elif idle <= threshold and stalled:
      with self._lock:
        self._stalled = False

  def _emit_diagnostic(self, idle: float, trailing: Optional[float]
                       ) -> None:
    trail_txt = (f"{idle / trailing:.1f}x the {trailing:.2f}s trailing "
                 "mean chunk wall" if trailing else "no trailing mean yet")
    self._log(
        f"stall watchdog: no dispatch completed for {idle:.1f}s "
        f"({trail_txt}); diagnosing only -- NOT killing the process "
        "(a kill mid-claim is the tunnel-wedge trigger, CLAUDE.md)")
    probe = os.environ.get("KF_TPU_PROBE_RESULT", "unprobed")
    platforms = os.environ.get("JAX_PLATFORMS", "unset")
    # Env-only tunnel state: touching jax.devices() from the watchdog
    # could itself block forever on a wedged tunnel.
    self._log(f"stall watchdog: tunnel state: probe={probe} "
              f"JAX_PLATFORMS={platforms}")
    if self._recorder is not None:
      for rec in self._recorder.tail(3):
        self._log("stall watchdog: last record: " + json.dumps(rec))


# -- session (benchmark.py's single wiring point) ----------------------------

class TelemetrySession:
  """Flight recorder + stall watchdog bundled for one training run."""

  @classmethod
  def create(cls, params, rank: int = 0, log_fn=None,
             num_ranks: int = 1,
             run_id: Optional[str] = None) -> Optional["TelemetrySession"]:
    """None unless the run's resolved --health_stats is on (benchmark
    resolves auto -> bool before building the step) -- OR the run is
    elastic/fault-injected with a train_dir sink: a preemption must
    produce a flight-recorder post-mortem window and a recorded elastic
    event even when the in-step stats are off (e.g.
    --shard_optimizer_state auto-disables them). The recorder and
    watchdog are host-side only, so this changes no compiled program."""
    wants = bool(getattr(params, "health_stats", None)) or (
        bool(getattr(params, "train_dir", None)) and
        (bool(getattr(params, "elastic", False)) or
         bool(getattr(params, "fault_schedule", None))))
    if not wants:
      return None
    return cls(params, rank=rank, log_fn=log_fn, num_ranks=num_ranks,
               run_id=run_id)

  def __init__(self, params, rank: int = 0, log_fn=None,
               num_ranks: int = 1, run_id: Optional[str] = None):
    self.train_dir = getattr(params, "train_dir", None)
    self.rank = int(rank)
    self.num_ranks = max(1, int(num_ranks))
    self.recorder = FlightRecorder(
        path=flight_recorder_path(self.train_dir, self.rank),
        window=int(getattr(params, "flight_recorder_window", None) or 64),
        sigma=float(getattr(params, "health_grad_norm_sigma", None)
                    or 6.0),
        rank=self.rank, log_fn=log_fn, run_id=run_id)
    self.recorder.install_signal_handlers()
    self.watchdog = StallWatchdog(
        factor=float(getattr(params, "stall_watchdog_factor", None)
                     or 0.0),
        log_fn=log_fn, recorder=self.recorder)
    self.watchdog.start()
    self._slo_monitor = None
    self._closed = False

  def attach_slo(self, monitor) -> None:
    """Attach a metrics.SLOMonitor so /healthz carries its burn state
    (and its alert episodes already ride this session's recorder when
    the monitor was built with ``recorder=session.recorder``)."""
    self._slo_monitor = monitor

  def beat(self, wall_s: Optional[float] = None) -> None:
    self.watchdog.beat(wall_s)

  def record(self, **kwargs) -> None:
    self.recorder.record(**kwargs)

  def elastic_event(self, generation: int, old_mesh: str, new_mesh: str,
                    step: int) -> None:
    """One recorder row per resize (benchmark.py logs the matching
    single line): the post-mortem window shows generation, old -> new
    mesh and the resume step instead of an unexplained loss-curve
    seam."""
    self.recorder.note_event({
        "elastic_event": f"{old_mesh}->{new_mesh}",
        "generation": int(generation),
        "step": int(step),
    })

  def fault_event(self, description: str, step: int) -> None:
    self.recorder.note_event({"fault_event": description,
                              "step": int(step)})

  def summary(self) -> Dict[str, Any]:
    s = self.recorder.summary()
    s["watchdog_stalls"] = self.watchdog.stalls
    return s

  def healthz(self) -> Dict[str, Any]:
    """The /healthz payload half this session owns (metrics.py serves
    it): liveness read from watchdog + flight-recorder state. "stalled"
    means the watchdog is currently inside a stall episode -- a scrape
    can see a live job that stopped dispatching, which is exactly the
    wedge signature the watchdog exists to diagnose."""
    stalled = bool(getattr(self.watchdog, "_stalled", False))
    payload = {"status": "stalled" if stalled else "ok"}
    payload.update(self.summary())
    last = self.recorder.tail(1)
    if last:
      payload["last_step"] = last[0].get("step")
    if self._slo_monitor is not None:
      # "up" vs "up but burning error budget": a firing SLO stream
      # upgrades an otherwise-ok status (a stall still wins -- a
      # wedged dispatcher is the more urgent diagnosis).
      slo = self._slo_monitor.state()
      payload["slo"] = slo
      if payload["status"] == "ok" and slo["status"] != "ok":
        payload["status"] = slo["status"]
    return payload

  def close(self, reason: str = "run end") -> None:
    if self._closed:
      return
    self._closed = True
    self.watchdog.stop()
    self.recorder.dump(reason)
    if (self.rank == 0 and self.num_ranks > 1 and self.train_dir):
      # Rank-0 exit aggregation: merge every rank's window (shared
      # train_dir) into one step-ordered view next to the per-rank
      # files (cluster.py process_rank tags the rows).
      merged = aggregate_rank_windows(self.train_dir)
      if merged:
        try:
          path = os.path.join(self.train_dir, "flight_recorder.all.jsonl")
          with open(path, "w") as f:
            for r in merged:
              f.write(json.dumps(r) + "\n")
        except OSError:
          pass
    self.recorder.close()
