"""Standalone all-reduce microbenchmark CLI.

TPU-native analog of the reference's all-reduce microbenchmark
(ref: scripts/tf_cnn_benchmarks/all_reduce_benchmark.py:60-180): build
model-shaped random gradient tensors, chain ``iters_per_step`` all-reduce
iterations inside ONE compiled SPMD program (data-dependency chaining
replaces the reference's control-dependency fencing,
all_reduce_benchmark.py:89-151), run timed steps, and report the average
time per all-reduce.

Where the reference times ``sess.run`` of a chained graph, we time calls
of a jitted ``shard_map`` program over the replica mesh; the spec-driven
algorithm selection (psum / reduce-scatter+all-gather / hierarchical)
comes from ops/allreduce.py, sharing the reference's spec grammar.

Run: python -m kf_benchmarks_tpu.all_reduce_benchmark --model=resnet50 \
         --num_batches=10 --all_reduce_spec=psum

``--sweep`` replaces the single-config run with the PERF.md round-5
n x spec x size step-time table from ONE command (previously a hand-run
procedure): every (device count, algorithm, packed-vector size) cell is
timed the same way -- chained iterations inside one compiled program,
drain()-bounded windows -- and the result prints as a markdown table
plus one JSON line.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from kf_benchmarks_tpu import flags
from kf_benchmarks_tpu.models import model_config
from kf_benchmarks_tpu.ops import allreduce
from kf_benchmarks_tpu.parallel import mesh as mesh_lib
from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS
from kf_benchmarks_tpu.utils import sync
from kf_benchmarks_tpu.utils import log as log_util

if "iters_per_step" not in flags.param_specs:
  flags.DEFINE_integer(
      "iters_per_step", 5,
      "Number of chained all-reduce iterations inside one compiled step "
      "(ref: all_reduce_benchmark.py flag of the same name).")
if "sweep" not in flags.param_specs:
  flags.DEFINE_boolean(
      "sweep", False,
      "Emit the PERF round-5 n x spec x size step-time table (markdown "
      "+ one JSON line) instead of the single-config model-shaped run: "
      "device counts are powers of two up to --num_devices, algorithms "
      "from --sweep_specs, packed-vector sizes from --sweep_sizes.")
  flags.DEFINE_string(
      "sweep_specs", "psum,rsag,hier,reduce_scatter,all_gather",
      "Comma-separated algorithms for --sweep (spec grammar "
      "alg[#shards]; reference aliases accepted). The primitive names "
      "'reduce_scatter' and 'all_gather' time the raw collective "
      "instead of an all-reduce composition -- the sharded optimizer "
      "path's exchange (--shard_optimizer_state meets gradients in a "
      "reduce-scatter and returns params by all-gather), so its "
      "collective mix A/Bs against the all-reduce rows of the same "
      "n x size cell.")
  flags.DEFINE_string(
      "sweep_sizes", "256k,4m",
      "Comma-separated packed-vector byte sizes for --sweep "
      "(spec-grammar limits: <int>[kKmM]).")


def get_var_shapes(model, nclass: int = 1001) -> List[Tuple[int, ...]]:
  """Return the model's trainable-variable shapes (ref:
  all_reduce_benchmark.py:60-66 builds the graph just to read var shapes;
  here we init the flax module and read the param tree)."""
  module = model.make_module(nclass=nclass, phase_train=True,
                             data_format="NHWC")
  size = getattr(model, "image_size", 224)
  images = jnp.zeros((1, size, size, 3), jnp.float32)
  rng = jax.random.PRNGKey(0)
  variables = jax.eval_shape(
      lambda: module.init({"params": rng, "dropout": rng}, images))
  leaves = jax.tree_util.tree_leaves(variables.get("params", variables))
  return [tuple(l.shape) for l in leaves]


def build_all_reduce_step(shapes: Sequence[Tuple[int, ...]], mesh,
                          iters_per_step: int, planner=None):
  """Compile one step: ``iters_per_step`` chained all-reduces of the
  tensor list (ref: build_all_reduce_iterations,
  all_reduce_benchmark.py:89-151). Chaining by data dependency: the
  reduced output of iteration i is the input of iteration i+1, so XLA
  cannot elide or overlap the iterations away."""

  def body(*tensors):
    tensors = list(tensors)
    for i in range(iters_per_step):
      if planner is not None:
        tensors = planner.reduce(tensors, REPLICA_AXIS)
      else:
        tensors = [lax.pmean(t, REPLICA_AXIS) for t in tensors]
      # Perturb between iterations so successive reductions are not
      # fixpoints (pmean of an already-averaged value); mirrors the
      # reference reusing live gradient values per iteration.
      if i + 1 < iters_per_step:
        tensors = [t + jnp.asarray(1e-6, t.dtype) for t in tensors]
    return tuple(tensors)

  specs = tuple(P(REPLICA_AXIS) for _ in shapes)
  fn = jax.shard_map(body, mesh=mesh, in_specs=specs, out_specs=specs)
  jitted = jax.jit(lambda tensors: fn(*tensors))
  return jitted


def run_benchmark(params) -> Dict[str, float]:
  """Build + time the all-reduce program; returns timing stats
  (ref: all_reduce_benchmark.py:155-180 run_benchmark)."""
  from kf_benchmarks_tpu.data import datasets
  model = model_config.get_model_config(params.model, params.data_name)
  dataset = datasets.create_dataset(params.data_dir, params.data_name)
  shapes = get_var_shapes(model, nclass=dataset.num_classes)
  devices = mesh_lib.get_devices(params.device, params.num_devices or None)
  mesh = mesh_lib.build_mesh(devices=devices)
  n = mesh.devices.size
  planner = allreduce.build_planner(params)
  iters = getattr(params, "iters_per_step", 5)
  dtype = jnp.bfloat16 if params.use_fp16 else jnp.float32

  step = build_all_reduce_step(shapes, mesh, iters, planner)

  rng = np.random.RandomState(0)
  sharding = NamedSharding(mesh, P(REPLICA_AXIS))
  tensors = [
      jax.device_put(
          rng.normal(size=(n,) + s).astype(dtype), sharding)
      for s in shapes]

  num_bytes = sum(int(np.prod(s)) for s in shapes) * jnp.dtype(dtype).itemsize
  log_util.log_fn(
      f"All-reduce benchmark: {len(shapes)} tensors, "
      f"{num_bytes / 1e6:.2f} MB/replica, {n} replicas, "
      f"{iters} iters/step")

  num_steps = params.num_batches or 10
  warmup = params.num_warmup_batches
  if warmup is None:
    warmup = 2

  # Both regions end with a real value fetch of the smallest output
  # tensor: fetching the model-sized tensors themselves would time the
  # host transfer instead of the all-reduce, and block_until_ready does
  # not synchronize on the tunneled TPU backend (utils/sync.py).
  for _ in range(max(warmup, 1)):  # includes compile
    out = step(tensors)
  sync.drain(out)

  start = time.monotonic()
  for _ in range(num_steps):
    out = step(tensors)
  sync.drain(out)
  elapsed = time.monotonic() - start

  avg_step = elapsed / num_steps
  avg_all_reduce = avg_step / iters
  log_util.log_fn(f"Average time per step: {avg_step:.6f} sec")
  log_util.log_fn(f"Average all-reduce time: {avg_all_reduce:.6f} sec")
  return {
      "average_time_per_step": avg_step,
      "average_all_reduce_time": avg_all_reduce,
      "num_tensors": len(shapes),
      "bytes_per_replica": num_bytes,
  }


def sweep_device_counts(total: int) -> List[int]:
  """Powers of two up to the available device count (the round-5 table's
  n axis; a non-power-of-two total contributes itself as the last row)."""
  ns, n = [], 2
  while n <= total:
    ns.append(n)
    n *= 2
  if not ns or ns[-1] != total:
    ns.append(total)
  return [n for n in ns if n <= total]


def build_vector_step(mesh, spec_tuple, iters_per_step: int):
  """One compiled step: ``iters_per_step`` chained reductions of a
  single packed vector (the gradient-vector shape every packed path
  reduces), chained by data dependency like build_all_reduce_step."""

  def body(vec):
    vec = vec[0]  # (1, elems) local shard -> the flat packed vector
    for i in range(iters_per_step):
      vec = allreduce._reduce_packed(vec, spec_tuple, REPLICA_AXIS)
      if i + 1 < iters_per_step:
        vec = vec + jnp.asarray(1e-6, vec.dtype)
    return vec[None]

  fn = jax.shard_map(body, mesh=mesh, in_specs=P(REPLICA_AXIS),
                     out_specs=P(REPLICA_AXIS))
  return jax.jit(fn)


# The primitive-collective rows of --sweep: the sharded optimizer
# path's exchange (ops/sharded.py scatter_mean / gather_tree) timed in
# isolation, beside the all-reduce compositions of the same cell.
PRIMITIVE_COLLECTIVES = ("reduce_scatter", "all_gather")


def build_primitive_step(mesh, collective: str, iters_per_step: int):
  """One compiled step chaining ``iters_per_step`` raw reduce-scatters
  (or all-gathers) of the packed vector. The collective's output shape
  differs from its input (that is the point of the primitive), so the
  chain dependency is a SCALAR read of the output folded back into the
  next iteration's input -- one elementwise op, the same
  cannot-elide/cannot-overlap role as build_vector_step's perturbation.
  Wire bytes per iteration are (n-1)/n x the nominal cell size for
  both primitives, directly comparable to the all-reduce rows."""
  if collective not in PRIMITIVE_COLLECTIVES:
    raise ValueError(f"unknown primitive collective {collective!r}")

  def body(vec):
    vec = vec[0]  # (1, elems) local shard -> the flat packed vector
    n = lax.axis_size(REPLICA_AXIS)
    for _ in range(iters_per_step):
      if collective == "reduce_scatter":
        # Tiled scatter needs a multiple of n; zero-pad like the real
        # consumers do (ops/sharded.py _pad_flat, allreduce.py _rsag)
        # -- non-power-of-two meshes and odd --sweep_sizes otherwise
        # crash the default sweep.
        pad = (-vec.shape[0]) % n
        out = lax.psum_scatter(
            jnp.pad(vec, (0, pad)) if pad else vec,
            REPLICA_AXIS, tiled=True)
      else:
        # Gather of a 1/n shard re-assembles the full nominal size --
        # the param leg of the sharded exchange.
        out = lax.all_gather(vec[:vec.shape[0] // n], REPLICA_AXIS,
                             tiled=True)
      vec = vec + out.reshape(-1)[0] * jnp.asarray(1e-6, vec.dtype)
    return vec[None]

  fn = jax.shard_map(body, mesh=mesh, in_specs=P(REPLICA_AXIS),
                     out_specs=P(REPLICA_AXIS))
  return jax.jit(fn)


def run_sweep(params) -> List[Dict[str, float]]:
  """The round-5 n x spec x size table from one command (PERF.md
  "All-reduce on a 4 MiB gradient vector" was hand-run per cell).

  Per-all-reduce time is measured DIFFERENTIALLY: each cell times two
  compiled programs chaining k and 2k reductions and differences them,
  so per-dispatch host cost cancels -- on the tunneled chip a single
  dispatch pays ~70 ms RTT, which would otherwise swamp every
  microsecond-scale cell (CLAUDE.md measurement rule; PERF.md round-5
  measurement correction). step_ms stays the raw k-iteration dispatch
  wall for context.

  Markdown rows via the logger; ONE JSON line on stdout so a harness
  can scrape the whole table like bench.py's result line."""
  devices = mesh_lib.get_devices(params.device, params.num_devices or None)
  iters = getattr(params, "iters_per_step", 5)
  num_steps = params.num_batches or 10
  warmup = params.num_warmup_batches
  warmup = 2 if warmup is None else max(warmup, 1)
  sizes = [allreduce._parse_limit(s.strip())
           for s in params.sweep_sizes.split(",") if s.strip()]
  spec_names = [s.strip() for s in params.sweep_specs.split(",")
                if s.strip()]
  dtype = jnp.bfloat16 if params.use_fp16 else jnp.float32
  itemsize = jnp.dtype(dtype).itemsize
  rows = []
  log_util.log_fn(f"All-reduce sweep: n x spec x size over "
                  f"{len(devices)} available devices, {iters} "
                  f"iters/step, {num_steps} timed steps")
  log_util.log_fn("| n | spec | size | step ms | per-all-reduce ms |")
  log_util.log_fn("|---|---|---|---|---|")
  rng = np.random.RandomState(0)

  def timed(step, vec):
    for _ in range(warmup):  # includes compile
      out = step(vec)
    sync.drain(out)
    start = time.monotonic()
    for _ in range(num_steps):
      out = step(out)
    sync.drain(out)
    return (time.monotonic() - start) / num_steps

  for n in sweep_device_counts(len(devices)):
    mesh = mesh_lib.build_mesh(devices=devices[:n])
    for spec_name in spec_names:
      if spec_name in PRIMITIVE_COLLECTIVES:
        step_k = build_primitive_step(mesh, spec_name, iters)
        step_2k = build_primitive_step(mesh, spec_name, 2 * iters)
      else:
        tup = allreduce._parse_alg(spec_name)
        if tup.alg == "hier":
          tup = tup._replace(shards=max(tup.shards, 2))
        step_k = build_vector_step(mesh, tup, iters)
        step_2k = build_vector_step(mesh, tup, 2 * iters)
      for size in sizes:
        elems = max(size // itemsize, n)
        sharding = NamedSharding(mesh, P(REPLICA_AXIS))
        vec = jax.device_put(
            rng.normal(size=(n, elems)).astype(dtype), sharding)
        step_s = timed(step_k, vec)
        step2_s = timed(step_2k, vec)
        # Differencing the k- and 2k-iteration programs cancels the
        # per-dispatch host/tunnel cost; clamp at 0 (pure noise floor
        # on cells faster than the timer jitter).
        per_reduce_s = max(step2_s - step_s, 0.0) / iters
        rows.append({"n": n, "spec": spec_name, "bytes": int(size),
                     "step_ms": round(step_s * 1e3, 3),
                     "all_reduce_ms": round(per_reduce_s * 1e3, 3)})
        log_util.log_fn(
            "| %d | %s | %s | %.3f | %.3f |" % (
                n, spec_name, _fmt_bytes(size), step_s * 1e3,
                per_reduce_s * 1e3))
  print(json.dumps({"metric": "all_reduce_sweep",
                    "iters_per_step": iters, "num_steps": num_steps,
                    "dtype": jnp.dtype(dtype).name, "rows": rows}),
        flush=True)
  return rows


def _fmt_bytes(size: int) -> str:
  if size % (1024 * 1024) == 0:
    return f"{size // (1024 * 1024)}m"
  if size % 1024 == 0:
    return f"{size // 1024}k"
  return str(size)


def main(positional_arguments):
  from absl import app
  from kf_benchmarks_tpu import params as params_lib
  if len(positional_arguments) > 1:
    raise app.UsageError(
        "Received unknown positional arguments: %s" % positional_arguments[1:])
  from kf_benchmarks_tpu import benchmark
  params = params_lib.make_params_from_flags()
  params = benchmark.setup(params)
  if getattr(params, "sweep", False):
    run_sweep(params)
  else:
    run_benchmark(params)


def run_main():
  from absl import app
  from kf_benchmarks_tpu import params as params_lib
  flags.define_flags(aliases=params_lib.ALIASES)
  app.run(main)


if __name__ == "__main__":
  run_main()
