"""Standalone all-reduce microbenchmark CLI.

TPU-native analog of the reference's all-reduce microbenchmark
(ref: scripts/tf_cnn_benchmarks/all_reduce_benchmark.py:60-180): build
model-shaped random gradient tensors, chain ``iters_per_step`` all-reduce
iterations inside ONE compiled SPMD program (data-dependency chaining
replaces the reference's control-dependency fencing,
all_reduce_benchmark.py:89-151), run timed steps, and report the average
time per all-reduce.

Where the reference times ``sess.run`` of a chained graph, we time calls
of a jitted ``shard_map`` program over the replica mesh; the spec-driven
algorithm selection (psum / reduce-scatter+all-gather / hierarchical)
comes from ops/allreduce.py, sharing the reference's spec grammar.

Run: python -m kf_benchmarks_tpu.all_reduce_benchmark --model=resnet50 \
         --num_batches=10 --all_reduce_spec=psum
"""

from __future__ import annotations

import time
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from kf_benchmarks_tpu import flags
from kf_benchmarks_tpu.models import model_config
from kf_benchmarks_tpu.ops import allreduce
from kf_benchmarks_tpu.parallel import mesh as mesh_lib
from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS
from kf_benchmarks_tpu.utils import sync
from kf_benchmarks_tpu.utils import log as log_util

if "iters_per_step" not in flags.param_specs:
  flags.DEFINE_integer(
      "iters_per_step", 5,
      "Number of chained all-reduce iterations inside one compiled step "
      "(ref: all_reduce_benchmark.py flag of the same name).")


def get_var_shapes(model, nclass: int = 1001) -> List[Tuple[int, ...]]:
  """Return the model's trainable-variable shapes (ref:
  all_reduce_benchmark.py:60-66 builds the graph just to read var shapes;
  here we init the flax module and read the param tree)."""
  module = model.make_module(nclass=nclass, phase_train=True,
                             data_format="NHWC")
  size = getattr(model, "image_size", 224)
  images = jnp.zeros((1, size, size, 3), jnp.float32)
  rng = jax.random.PRNGKey(0)
  variables = jax.eval_shape(
      lambda: module.init({"params": rng, "dropout": rng}, images))
  leaves = jax.tree_util.tree_leaves(variables.get("params", variables))
  return [tuple(l.shape) for l in leaves]


def build_all_reduce_step(shapes: Sequence[Tuple[int, ...]], mesh,
                          iters_per_step: int, planner=None):
  """Compile one step: ``iters_per_step`` chained all-reduces of the
  tensor list (ref: build_all_reduce_iterations,
  all_reduce_benchmark.py:89-151). Chaining by data dependency: the
  reduced output of iteration i is the input of iteration i+1, so XLA
  cannot elide or overlap the iterations away."""

  def body(*tensors):
    tensors = list(tensors)
    for i in range(iters_per_step):
      if planner is not None:
        tensors = planner.reduce(tensors, REPLICA_AXIS)
      else:
        tensors = [lax.pmean(t, REPLICA_AXIS) for t in tensors]
      # Perturb between iterations so successive reductions are not
      # fixpoints (pmean of an already-averaged value); mirrors the
      # reference reusing live gradient values per iteration.
      if i + 1 < iters_per_step:
        tensors = [t + jnp.asarray(1e-6, t.dtype) for t in tensors]
    return tuple(tensors)

  specs = tuple(P(REPLICA_AXIS) for _ in shapes)
  fn = jax.shard_map(body, mesh=mesh, in_specs=specs, out_specs=specs)
  jitted = jax.jit(lambda tensors: fn(*tensors))
  return jitted


def run_benchmark(params) -> Dict[str, float]:
  """Build + time the all-reduce program; returns timing stats
  (ref: all_reduce_benchmark.py:155-180 run_benchmark)."""
  from kf_benchmarks_tpu.data import datasets
  model = model_config.get_model_config(params.model, params.data_name)
  dataset = datasets.create_dataset(params.data_dir, params.data_name)
  shapes = get_var_shapes(model, nclass=dataset.num_classes)
  devices = mesh_lib.get_devices(params.device, params.num_devices or None)
  mesh = mesh_lib.build_mesh(devices=devices)
  n = mesh.devices.size
  planner = allreduce.build_planner(params)
  iters = getattr(params, "iters_per_step", 5)
  dtype = jnp.bfloat16 if params.use_fp16 else jnp.float32

  step = build_all_reduce_step(shapes, mesh, iters, planner)

  rng = np.random.RandomState(0)
  sharding = NamedSharding(mesh, P(REPLICA_AXIS))
  tensors = [
      jax.device_put(
          rng.normal(size=(n,) + s).astype(dtype), sharding)
      for s in shapes]

  num_bytes = sum(int(np.prod(s)) for s in shapes) * jnp.dtype(dtype).itemsize
  log_util.log_fn(
      f"All-reduce benchmark: {len(shapes)} tensors, "
      f"{num_bytes / 1e6:.2f} MB/replica, {n} replicas, "
      f"{iters} iters/step")

  num_steps = params.num_batches or 10
  warmup = params.num_warmup_batches
  if warmup is None:
    warmup = 2

  # Both regions end with a real value fetch of the smallest output
  # tensor: fetching the model-sized tensors themselves would time the
  # host transfer instead of the all-reduce, and block_until_ready does
  # not synchronize on the tunneled TPU backend (utils/sync.py).
  for _ in range(max(warmup, 1)):  # includes compile
    out = step(tensors)
  sync.drain(out)

  start = time.monotonic()
  for _ in range(num_steps):
    out = step(tensors)
  sync.drain(out)
  elapsed = time.monotonic() - start

  avg_step = elapsed / num_steps
  avg_all_reduce = avg_step / iters
  log_util.log_fn(f"Average time per step: {avg_step:.6f} sec")
  log_util.log_fn(f"Average all-reduce time: {avg_all_reduce:.6f} sec")
  return {
      "average_time_per_step": avg_step,
      "average_all_reduce_time": avg_all_reduce,
      "num_tensors": len(shapes),
      "bytes_per_replica": num_bytes,
  }


def main(positional_arguments):
  from absl import app
  from kf_benchmarks_tpu import params as params_lib
  if len(positional_arguments) > 1:
    raise app.UsageError(
        "Received unknown positional arguments: %s" % positional_arguments[1:])
  from kf_benchmarks_tpu import benchmark
  params = params_lib.make_params_from_flags()
  params = benchmark.setup(params)
  run_benchmark(params)


def run_main():
  from absl import app
  from kf_benchmarks_tpu import params as params_lib
  flags.define_flags(aliases=params_lib.ALIASES)
  app.run(main)


if __name__ == "__main__":
  run_main()
