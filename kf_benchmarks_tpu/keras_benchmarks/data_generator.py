"""Synthetic input generators (ref: keras_benchmarks/data_generator.py)."""

import numpy as np


def generate_img_input_data(input_shape, num_classes=10):
  """(ref: data_generator.py:5-22) random images + integer labels."""
  x_train = np.random.randint(0, 255, input_shape)
  y_train = np.random.randint(0, num_classes, (input_shape[0],))
  return x_train, y_train


def generate_text_input_data(input_shape, p=0.05, return_as_bool=True):
  """(ref: data_generator.py:22-40) sparse one-hot-ish text tensors and a
  one-hot target over the last feature dimension."""
  x = (np.random.uniform(size=input_shape) < p)
  y_idx = np.random.randint(0, input_shape[-1], (input_shape[0],))
  y = np.zeros((input_shape[0], input_shape[-1]), dtype=bool)
  y[np.arange(input_shape[0]), y_idx] = True
  if not return_as_bool:
    return x.astype(np.float32), y.astype(np.float32)
  return x, y


def to_categorical(y, num_classes):
  """keras.utils.to_categorical analog."""
  out = np.zeros((len(y), num_classes), np.float32)
  out[np.arange(len(y)), np.asarray(y, np.int64)] = 1.0
  return out
