"""Benchmark metric upload (ref: keras_benchmarks/upload_benchmarks_bq.py).

The reference streams rows to BigQuery; that client is not part of this
image, so metrics land in a local JSONL sink with the same row schema,
and the BigQuery path is gated on the library being importable.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Optional

DEFAULT_SINK = os.environ.get("KERAS_BENCHMARKS_SINK",
                              "keras_benchmarks_metrics.jsonl")


def upload_metrics(test_name, total_time, epochs, batch_size, backend_type,
                   backend_version, cpu_num_cores, cpu_memory,
                   cpu_memory_info, gpu_count, gpu_platform, platform_type,
                   platform_machine_type, framework_version,
                   sample_type=None, sink_path: Optional[str] = None):
  """Same row schema as the reference's BigQuery table
  (ref: upload_benchmarks_bq.py:7-60)."""
  row = {
      "test_id": str(uuid.uuid4()),
      "test_name": test_name,
      "total_time": total_time,
      "epochs": epochs,
      "batch_size": batch_size,
      "backend_type": backend_type,
      "backend_version": backend_version,
      "cpu_num_cores": cpu_num_cores,
      "cpu_memory": cpu_memory,
      "cpu_memory_info": cpu_memory_info,
      "gpu_count": gpu_count,
      "gpu_platform": gpu_platform,
      "platform_type": platform_type,
      "platform_machine_type": platform_machine_type,
      "framework_version": framework_version,
      "sample_type": sample_type,
      "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
  }
  try:
    from google.cloud import bigquery  # noqa: F401
    # A BigQuery client is available: the reference's streaming-insert
    # path could run here; dataset/table wiring is deployment-specific,
    # so the local sink below remains the record of truth.
  except ImportError:
    pass
  path = sink_path or DEFAULT_SINK
  with open(path, "a") as f:
    f.write(json.dumps(row) + "\n")
  return row
