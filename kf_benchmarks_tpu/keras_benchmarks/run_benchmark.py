"""Entry point for the secondary benchmark suite
(ref: keras_benchmarks/run_benchmark.py:19-84).

Run: python -m kf_benchmarks_tpu.keras_benchmarks.run_benchmark \
         --mode=cpu_config
"""

from __future__ import annotations

import argparse
import json
import os

import jax

from kf_benchmarks_tpu.keras_benchmarks import upload_benchmarks
from kf_benchmarks_tpu.keras_benchmarks.models import (
    cifar10_cnn_benchmark, lstm_benchmark, mnist_mlp_benchmark)


def get_backend_version() -> str:
  return jax.__version__


def run(mode: str, sink_path=None):
  config_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "config.json")
  with open(config_path) as f:
    config = json.load(f)[mode]

  results = []
  for benchmark_cls in (mnist_mlp_benchmark.MnistMlpBenchmark,
                        cifar10_cnn_benchmark.Cifar10CnnBenchmark,
                        lstm_benchmark.LstmBenchmark):
    current = benchmark_cls()
    current.run_benchmark(gpus=config["gpus"])
    row = upload_benchmarks.upload_metrics(
        test_name=current.test_name,
        total_time=current.total_time,
        epochs=current.epochs,
        batch_size=current.batch_size,
        backend_type="jax",
        backend_version=get_backend_version(),
        cpu_num_cores=config["cpu_num_cores"],
        cpu_memory=config["cpu_memory"],
        cpu_memory_info=config["cpu_memory_info"],
        gpu_count=config["gpus"],
        gpu_platform=config["gpu_platform"],
        platform_type=config["platform_type"],
        platform_machine_type=config["platform_machine_type"],
        framework_version=get_backend_version(),
        sample_type=current.sample_type,
        sink_path=sink_path)
    print(f"{current.test_name}: total_time={current.total_time:.3f}s "
          f"({current.epochs} epochs, first excluded)")
    results.append(row)
  return results


def main():
  parser = argparse.ArgumentParser()
  parser.add_argument(
      "--mode", default="cpu_config",
      help="cpu_config | gpu_config | multi_gpu_config | tpu_config")
  args = parser.parse_args()
  run(args.mode)


if __name__ == "__main__":
  main()
