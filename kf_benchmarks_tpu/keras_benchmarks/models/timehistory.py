"""Per-epoch timing callback (ref: keras_benchmarks/models/timehistory.py)."""

import time


class TimeHistory:
  """Records wall time per epoch; used to exclude the first (compile)
  epoch from total_time (ref: run_benchmark total_time loops from 1)."""

  def __init__(self):
    self.times = []
    self._start = None

  def on_train_begin(self):
    self.times = []

  def on_epoch_begin(self):
    self._start = time.time()

  def on_epoch_end(self):
    self.times.append(time.time() - self._start)
