"""MNIST MLP benchmark (ref: keras_benchmarks/models/mnist_mlp_benchmark.py
:21-60): 784 -> Dense512/relu/dropout x2 -> 10, RMSprop, 2 epochs over
1000 random samples; total_time excludes epoch 0."""

import flax.linen as nn
import optax

from kf_benchmarks_tpu.keras_benchmarks import data_generator, fit
from kf_benchmarks_tpu.keras_benchmarks.models import timehistory


class _Mlp(nn.Module):
  @nn.compact
  def __call__(self, x):
    x = nn.relu(nn.Dense(512)(x))
    x = nn.Dropout(0.2, deterministic=False)(x)
    x = nn.relu(nn.Dense(512)(x))
    x = nn.Dropout(0.2, deterministic=False)(x)
    return nn.Dense(10)(x)


class MnistMlpBenchmark:

  def __init__(self):
    self.test_name = "mnist_mlp"
    self.sample_type = "images"
    self.total_time = 0
    self.batch_size = 128
    self.epochs = 2
    self.num_samples = 1000

  def run_benchmark(self, gpus: int = 0):
    x_train, y_train = data_generator.generate_img_input_data(
        (self.num_samples, 28, 28), 10)
    x_train = (x_train.reshape(self.num_samples, 784)
               .astype("float32") / 255.0)
    y_train = data_generator.to_categorical(y_train, 10)

    time_callback = timehistory.TimeHistory()
    fit.fit(_Mlp(), x_train, y_train, batch_size=self.batch_size,
            epochs=self.epochs, tx=optax.rmsprop(1e-3),
            time_callback=time_callback, num_devices=max(gpus, 1))

    # First epoch pays compilation; exclude it (ref: run loop from 1).
    self.total_time = sum(time_callback.times[1:])
    return self.total_time
