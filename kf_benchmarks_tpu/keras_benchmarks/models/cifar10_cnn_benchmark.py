"""CIFAR10 CNN benchmark (ref: keras_benchmarks/models/
cifar10_cnn_benchmark.py:20-75): conv32x2/pool/dropout ->
conv64x2/pool/dropout -> dense512 -> 10, RMSprop(1e-4), 2 epochs over
1000 random samples."""

import flax.linen as nn
import optax

from kf_benchmarks_tpu.keras_benchmarks import data_generator, fit
from kf_benchmarks_tpu.keras_benchmarks.models import timehistory


class _Cnn(nn.Module):
  @nn.compact
  def __call__(self, x):
    x = nn.relu(nn.Conv(32, (3, 3), padding="SAME")(x))
    x = nn.relu(nn.Conv(32, (3, 3), padding="VALID")(x))
    x = nn.max_pool(x, (2, 2), (2, 2))
    x = nn.Dropout(0.25, deterministic=False)(x)
    x = nn.relu(nn.Conv(64, (3, 3), padding="SAME")(x))
    x = nn.relu(nn.Conv(64, (3, 3), padding="VALID")(x))
    x = nn.max_pool(x, (2, 2), (2, 2))
    x = nn.Dropout(0.25, deterministic=False)(x)
    x = x.reshape((x.shape[0], -1))
    x = nn.relu(nn.Dense(512)(x))
    x = nn.Dropout(0.5, deterministic=False)(x)
    return nn.Dense(10)(x)


class Cifar10CnnBenchmark:

  def __init__(self):
    self.test_name = "cifar10_cnn"
    self.sample_type = "images"
    self.total_time = 0
    self.batch_size = 32
    self.epochs = 2
    self.num_samples = 1000

  def run_benchmark(self, gpus: int = 0):
    x_train, y_train = data_generator.generate_img_input_data(
        (self.num_samples, 3, 32, 32), 10)
    x_train = x_train.transpose(0, 2, 3, 1).astype("float32") / 255.0
    y_train = data_generator.to_categorical(y_train, 10)

    time_callback = timehistory.TimeHistory()
    fit.fit(_Cnn(), x_train, y_train, batch_size=self.batch_size,
            epochs=self.epochs, tx=optax.rmsprop(1e-4),
            time_callback=time_callback, num_devices=max(gpus, 1))
    self.total_time = sum(time_callback.times[1:])
    return self.total_time
