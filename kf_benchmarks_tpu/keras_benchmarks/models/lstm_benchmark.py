"""LSTM benchmark (ref: keras_benchmarks/models/lstm_benchmark.py:18-70):
a single 128-unit LSTM over (40, 60) text tensors -> dense softmax over
60, RMSprop(1e-2), 2 epochs over 1000 random samples."""

import flax.linen as nn
import optax

from kf_benchmarks_tpu.keras_benchmarks import data_generator, fit
from kf_benchmarks_tpu.keras_benchmarks.models import timehistory


class _Lstm(nn.Module):
  @nn.compact
  def __call__(self, x):
    outs = nn.RNN(nn.OptimizedLSTMCell(128))(x)
    return nn.Dense(60)(outs[:, -1, :])


class LstmBenchmark:

  def __init__(self):
    self.test_name = "lstm"
    self.sample_type = "text"
    self.total_time = 0
    self.batch_size = 128
    self.epochs = 2
    self.num_samples = 1000

  def run_benchmark(self, gpus: int = 0):
    x, y = data_generator.generate_text_input_data(
        (self.num_samples, 40, 60))
    time_callback = timehistory.TimeHistory()
    fit.fit(_Lstm(), x.astype("float32"), y.astype("float32"),
            batch_size=self.batch_size, epochs=self.epochs,
            tx=optax.rmsprop(1e-2), time_callback=time_callback,
            num_devices=max(gpus, 1))
    self.total_time = sum(time_callback.times[1:])
    return self.total_time
