"""Minimal Sequential-style fit loop over flax/optax.

The shared trainer behind the three benchmark models (the model.compile +
model.fit role of the reference suite). Data parallelism over multiple
devices uses a batch NamedSharding and lets the XLA SPMD partitioner
insert the gradient collectives (the multi_gpu_model analog,
ref: run_benchmark.py / gpu_mode.py).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kf_benchmarks_tpu.keras_benchmarks.models.timehistory import TimeHistory
from kf_benchmarks_tpu.utils import sync


def fit(module, x_train, y_train, *, batch_size: int, epochs: int,
        tx: optax.GradientTransformation,
        loss: str = "categorical_crossentropy",
        time_callback: Optional[TimeHistory] = None,
        num_devices: int = 1, seed: int = 0):
  """Train; returns (final_params, history dict)."""
  n = x_train.shape[0]
  # Drop the ragged tail so every step has a static shape (XLA-friendly;
  # with the reference's sample counts the tail is at most one batch).
  steps = n // batch_size
  if num_devices > 1:
    devices = jax.devices()[:num_devices]
    mesh = Mesh(np.asarray(devices), ("batch",))
    data_sharding = NamedSharding(mesh, P("batch"))
  else:
    data_sharding = None

  rng = jax.random.PRNGKey(seed)
  sample = jnp.asarray(x_train[:batch_size], jnp.float32)
  variables = module.init({"params": rng, "dropout": rng}, sample)
  params = variables["params"]
  opt_state = tx.init(params)

  def loss_fn(params, x, y, rng):
    preds = module.apply({"params": params}, x, rngs={"dropout": rng})
    if loss == "categorical_crossentropy":
      logp = jax.nn.log_softmax(preds)
      return -jnp.mean(jnp.sum(y * logp, axis=-1))
    raise ValueError(f"Unsupported loss {loss!r}")

  @jax.jit
  def train_step(params, opt_state, x, y, rng):
    value, grads = jax.value_and_grad(loss_fn)(params, x, y, rng)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, value

  history = {"loss": []}
  if time_callback is not None:
    time_callback.on_train_begin()
  for epoch in range(epochs):
    if time_callback is not None:
      time_callback.on_epoch_begin()
    epoch_losses = []
    for step in range(steps):
      lo = step * batch_size
      x = jnp.asarray(x_train[lo:lo + batch_size], jnp.float32)
      y = jnp.asarray(y_train[lo:lo + batch_size], jnp.float32)
      if data_sharding is not None:
        x = jax.device_put(x, data_sharding)
        y = jax.device_put(y, data_sharding)
      rng, step_rng = jax.random.split(rng)
      params, opt_state, value = train_step(params, opt_state, x, y,
                                            step_rng)
      epoch_losses.append(value)
    # Real per-device fetch: block_until_ready does not synchronize on
    # the tunneled TPU backend (utils/sync.py), and the epoch timing
    # callback fires right after this.
    sync.drain(params)
    history["loss"].append(float(jnp.mean(jnp.stack(epoch_losses))))
    if time_callback is not None:
      time_callback.on_epoch_end()
  return params, history
