"""Secondary benchmark suite: small MLP/CNN/LSTM models with per-epoch
timing and metric upload (ref: scripts/keras_benchmarks/, SURVEY 2.8).

The reference's multi-backend (TF/Theano/CNTK) Keras suite maps onto one
backend here -- flax/optax on XLA -- with the same three models, the same
synthetic-data generators, the same first-epoch-excluded total_time
semantics, and a local-JSON metric sink replacing the BigQuery uploader.
"""
