"""Unified run tracing: host-side span timeline, Chrome-trace export,
compile ledger, streaming latency percentiles.

Re-design of the reference's one-step post-hoc tracing (``--trace_file``
captures a FULL_TRACE of step -2 and converts it through
``timeline.Timeline`` into a Chrome trace, ref: benchmark_cnn.py:270-275,
:806-817) into a WHOLE-RUN host-side span timeline: every wall-clock
boundary the run crosses -- DeviceFeeder fetches and consumer waits,
dispatch issue, device chunk completion, compile episodes, checkpoint
save/restore, mid-training eval, elastic resize seams, fault
injections -- is one span/instant event, exported as Chrome trace-event
JSON (``--trace_events_file``; loads in Perfetto / chrome://tracing)
with ``pid`` = process rank and ``tid`` = subsystem.  The jax.profiler
``--trace_file`` device-level capture is untouched; this timeline is the
host-side picture AROUND it (observability.maybe_trace_step drops a
marker span so the two line up).

Hard contract (enforced by the program-contract auditor's twin-trace
rule, analysis/audit.rule_trace_twin): tracing is HOST-ONLY.  The
trace-on step program is structurally identical to the trace-off one,
and per-step losses are bit-identical (tests/test_tracing.py pins it
through ``--steps_per_dispatch`` / ``--num_grad_accum`` /
``--shard_optimizer_state``).

Timing discipline: spans are measured with ``time.monotonic`` on the
host and anchored to the wall clock once at session start (so ranks
merge onto one comparable axis).  Device work is NEVER timed with
``jax.block_until_ready`` (it lies on the tunneled backend,
utils/sync.py): dispatch-issue spans bracket the async jit call alone,
and per-chunk device spans are attributed DIFFERENTIALLY from the
metric-pipeline arrival intervals (utils/pipeline.py) with the measured
host issue overhead (~70 ms tunnel RTT, PERF.md) carried in the span
args -- the same differential-measurement discipline as
experiments/pallas_fused_chain_probe.py.

On top of the same spans:

* **Compile ledger** -- per-program-shape compile wall times keyed on
  the auditor's contract fingerprint keys
  (analysis/baseline.config_fingerprint_key), persisted/merged to
  ``train_dir/compile_ledger.json`` and printed as a table at run end:
  the groundwork for the persistent compile cache (ROADMAP item 5 --
  pay the 30-minute first compile once per program shape ever).
* **Streaming latency percentiles** -- p50/p90/p99 of chunk wall, feed
  wait and checkpoint save, printed at run end and carried in the
  benchmark stats + bench.py JSON: the SLO-telemetry groundwork for the
  serving path (ROADMAP item 2).

Pure stdlib (no jax): importable from faults.py and loadable standalone
by the hazard lint.  Span/event EMISSION is single-sourced here -- the
lint rule ``trace-event-emission`` (analysis/lint.py) bans Chrome
trace-event construction and percentile helpers outside this module,
the same single-sourcing pattern as the step-line rule.  The flight
recorder (telemetry.py) shares this session's run id and cross-links
rows to span ids, so a post-mortem dump lays over the timeline.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Subsystem lanes (Chrome tid; one timeline row per subsystem under
# each rank's pid). Order fixes the tid numbering so merged multi-rank
# timelines line up row-for-row. "serving" is the request engine's lane
# (serving/engine.py: enqueue/shed instants, prefill/decode-step spans,
# whole-request spans).
SUBSYSTEMS = ("run", "compile", "dispatch", "device", "feed",
              "checkpoint", "eval", "elastic", "faults", "profiler",
              "serving")

# Canonical latency-sample keys (the percentile lines / stats fields).
# The serving/* entries come from the request engine: TTFT per request,
# decode-step wall per emitted token, and the accepted speculative
# prefix length per slot per verify round (serving/engine.py).
SAMPLE_KEYS = ("chunk_wall", "feed_wait", "checkpoint_save",
               "serving/ttft", "serving/token_latency",
               "serving/accept_len")

# Reported quantiles. Every ``<key>_p<q>`` stats/bench-JSON field is
# SAMPLE_KEYS x QUANTILES; the metric registry (metrics.py) registers
# each rendered key literally and its schema audit cross-checks the
# registration against these two tuples, so the set cannot drift.
QUANTILES = (50, 90, 99)

# The persisted per-train_dir compile ledger (write_ledger /
# read_ledger below).
LEDGER_FILENAME = "compile_ledger.json"


def resolve_run_id(wall_fn=time.time) -> str:
  """One run id shared by the trace and the flight recorder.

  Under kfrun every worker inherits KF_RUN_ID from the launcher, so all
  ranks of one job share a single id (the merge invariant); standalone
  processes mint a wall-clock/pid-derived one."""
  env = os.environ.get("KF_RUN_ID")
  if env:
    return env
  return f"run-{int(wall_fn() * 1000.0):x}-{os.getpid():x}"


def percentile(values, q: float) -> Optional[float]:
  """Linear-interpolated percentile (numpy's default convention) in
  pure deterministic python; None on an empty sample set."""
  vs = sorted(float(v) for v in values)
  if not vs:
    return None
  if len(vs) == 1:
    return vs[0]
  pos = (len(vs) - 1) * (q / 100.0)
  lo = int(pos)
  hi = min(lo + 1, len(vs) - 1)
  frac = pos - lo
  return vs[lo] * (1.0 - frac) + vs[hi] * frac


def _event_sort_key(e):
  """Metadata rows first, then epoch order -- the ONE event ordering
  every export and merge path shares (a forked copy of this key or of
  the payload shape below is exactly the schema drift the
  trace-event-emission lint rule exists to prevent, so both are
  single-sourced here even within this module)."""
  return (e.get("ph") != "M", e.get("ts", 0.0))


def _payload(events, run_id: str, **extra_meta) -> Dict[str, Any]:
  """The ONE Chrome trace-event JSON payload shape."""
  meta: Dict[str, Any] = {"run_id": run_id,
                          "format": "kf_benchmarks_tpu run trace"}
  meta.update(extra_meta)
  return {"traceEvents": events, "displayTimeUnit": "ms",
          "metadata": meta}


def rank_path(path: str, rank: int) -> str:
  """Per-rank span-file path: rank 0 owns the canonical ``path`` (and
  the merged timeline); other ranks write rank-suffixed siblings the
  rank-0 exit merge collects -- the flight_recorder_path convention."""
  if rank == 0:
    return path
  base, ext = os.path.splitext(path)
  return f"{base}.rank{rank}{ext or '.json'}"


def validate_chrome_trace(obj) -> List[str]:
  """Structural check of a Chrome trace-event JSON object; returns
  problem strings (empty = valid). The schema contract the export tests
  pin (the Trace Event Format: ph/ts/dur/pid/tid/name fields)."""
  problems = []
  if not isinstance(obj, dict):
    return ["top level is not an object"]
  events = obj.get("traceEvents")
  if not isinstance(events, list):
    return ["traceEvents missing or not a list"]
  for i, e in enumerate(events):
    if not isinstance(e, dict):
      problems.append(f"event {i} is not an object")
      continue
    ph = e.get("ph")
    if ph not in ("M", "X", "i"):
      problems.append(f"event {i}: unknown ph {ph!r}")
      continue
    if not isinstance(e.get("name"), str) or not e["name"]:
      problems.append(f"event {i}: missing name")
    if not isinstance(e.get("pid"), int) or not isinstance(
        e.get("tid"), int):
      problems.append(f"event {i}: pid/tid must be ints")
    if ph in ("X", "i"):
      ts = e.get("ts")
      if not isinstance(ts, (int, float)) or ts < 0:
        problems.append(f"event {i}: bad ts {ts!r}")
    if ph == "X":
      dur = e.get("dur")
      if not isinstance(dur, (int, float)) or dur < 0:
        problems.append(f"event {i}: bad dur {dur!r}")
  return problems


class RunTrace:
  """One process's span timeline + latency samples + compile ledger.

  Host-side only and always cheap: with no ``path`` the span list is
  not retained (samples and the ledger still are, so percentile lines
  and bench JSON fields work without ``--trace_events_file``). All
  methods are thread-safe (the DeviceFeeder worker emits feed spans
  from its own thread). ``time_fn``/``wall_fn`` are injectable so the
  unit tests drive a deterministic clock.
  """

  MAX_SPANS = 200_000  # bound memory on very long runs; drops counted
  # Per-key latency-sample bound: at the cap the list decimates 2:1 and
  # the key's stride doubles (keep every 2^k-th sample), so a multi-day
  # run's feed_wait stream stays bounded while the percentile estimate
  # keeps its shape; reported n stays the TRUE observation count.
  MAX_SAMPLES = 16_384

  def __init__(self, path: Optional[str] = None, rank: int = 0,
               num_ranks: int = 1, run_id: Optional[str] = None,
               chrome_format: bool = True, time_fn=time.monotonic,
               wall_fn=time.time, log_fn=None):
    self.path = path
    self.rank = int(rank)
    self.num_ranks = max(1, int(num_ranks))
    self.chrome_format = bool(chrome_format)
    self.run_id = run_id or resolve_run_id(wall_fn=wall_fn)
    self._time = time_fn
    self._wall = wall_fn
    self._log = log_fn or (lambda s: None)
    self._lock = threading.Lock()
    # Wall anchor: spans are monotonic-clocked; export maps them onto
    # the epoch axis via this one (wall, mono) pair so ranks merge onto
    # a comparable timeline.
    self._anchor_mono = self._time()
    self._anchor_wall = self._wall()
    self._keep_spans = path is not None
    self._spans: List[Dict[str, Any]] = []
    self._dropped = 0
    self._next_id = 1
    self._tids: Dict[str, int] = {s: i for i, s in enumerate(SUBSYSTEMS)}
    self._samples: Dict[str, List[float]] = {}
    self._sample_counts: Dict[str, int] = {}
    self._sample_strides: Dict[str, int] = {}
    self._ledger: List[Dict[str, Any]] = []

  # -- clock ------------------------------------------------------------------

  def now(self) -> float:
    """This session's monotonic clock (the injectable one -- callers
    attributing spans retrospectively must read time here, not
    time.monotonic, or fake-clock tests skew)."""
    return self._time()

  def _tid(self, subsystem: str) -> int:
    if subsystem not in self._tids:
      self._tids[subsystem] = len(self._tids)
    return self._tids[subsystem]

  # -- span emission (the ONE place trace records are built) ------------------

  def add_span(self, subsystem: str, name: str, t0: float, dur_s: float,
               args: Optional[Dict[str, Any]] = None) -> int:
    """Record a completed span retrospectively (``t0`` from ``now()``);
    returns its id, or 0 when the span was NOT retained (no export
    path, or the MAX_SPANS cap dropped it) -- so a cross-link consumer
    (the flight recorder's span_id) never references a span absent
    from the exported timeline. The retrospective form exists for
    durations measured elsewhere -- the pipeline's chunk arrival
    intervals, the feeder's consumer wait -- where wrapping a ``with``
    block around the measured region is not possible."""
    return self._emit("X", subsystem, name, float(t0),
                      max(0.0, float(dur_s)), dict(args or {}))

  def instant(self, subsystem: str, name: str, **args) -> int:
    """A zero-duration marker event (fault injections, profiler-capture
    markers); returns its id, or 0 when not retained."""
    return self._emit("i", subsystem, name, self._time(), 0.0,
                      dict(args))

  def _emit(self, ph: str, subsystem: str, name: str, t0: float,
            dur_s: float, args: Dict[str, Any]) -> int:
    with self._lock:
      if not self._keep_spans:
        return 0
      if len(self._spans) >= self.MAX_SPANS:
        self._dropped += 1
        return 0
      sid = self._next_id
      self._next_id += 1
      self._spans.append({
          "id": sid, "ph": ph, "sub": subsystem,
          "tid": self._tid(subsystem), "name": name,
          "t0": t0, "dur": dur_s, "args": args,
      })
    return sid

  @contextlib.contextmanager
  def span(self, subsystem: str, name: str, **args):
    """Context manager form; yields the (mutable) args dict so callers
    can attach results discovered inside the span (e.g. the elastic
    generation number)."""
    t0 = self._time()
    live_args = dict(args)
    try:
      yield live_args
    finally:
      self.add_span(subsystem, name, t0, self._time() - t0, live_args)

  # -- latency samples --------------------------------------------------------

  def add_sample(self, key: str, seconds: float) -> None:
    with self._lock:
      self._sample_counts[key] = self._sample_counts.get(key, 0) + 1
      stride = self._sample_strides.setdefault(key, 1)
      if (self._sample_counts[key] - 1) % stride:
        return  # decimated-out observation (still counted above)
      vs = self._samples.setdefault(key, [])
      vs.append(float(seconds))
      if len(vs) >= self.MAX_SAMPLES:
        # Deterministic 2:1 decimation + stride doubling: memory stays
        # bounded on arbitrarily long runs, the retained subsample
        # keeps the distribution's shape for the percentile estimate.
        self._samples[key] = vs[::2]
        self._sample_strides[key] = stride * 2

  def percentiles(self) -> Dict[str, Dict[str, float]]:
    """{key: {p50, p90, p99, n}} over every sampled latency key; n is
    the TRUE observation count (the retained subsample may be a
    strided decimation on very long runs, see add_sample)."""
    with self._lock:
      samples = {k: list(v) for k, v in self._samples.items()}
      counts = dict(self._sample_counts)
    out = {}
    for key in sorted(samples):
      vs = samples[key]
      out[key] = {"p50": percentile(vs, 50), "p90": percentile(vs, 90),
                  "p99": percentile(vs, 99),
                  "n": counts.get(key, len(vs))}
    return out

  def percentile_fields(self) -> Dict[str, Optional[float]]:
    """Flat ``<key>_p<q>`` seconds fields for the benchmark stats dict
    (bench.py forwards the chunk_wall/feed_wait subset into its JSON
    line)."""
    out: Dict[str, Optional[float]] = {}
    for key, row in self.percentiles().items():
      for q in QUANTILES:
        out[f"{key}_p{q}"] = row[f"p{q}"]
    return out

  def latency_lines(self) -> List[str]:
    """Run-end percentile report, one WHOLE line per sampled key (the
    scrape-guard contract: never interleaves inside step lines)."""
    lines = []
    for key, row in self.percentiles().items():
      lines.append(
          "latency percentiles: %s p50=%.3fms p90=%.3fms p99=%.3fms "
          "(n=%d)" % (key, 1e3 * row["p50"], 1e3 * row["p90"],
                      1e3 * row["p99"], row["n"]))
    return lines

  # -- compile ledger ---------------------------------------------------------

  def note_compile(self, key: str, program: str, wall_s: float,
                   **meta) -> None:
    """Record one compile episode. ``key`` is the program-shape
    fingerprint (analysis/baseline.config_fingerprint_key); ``wall_s``
    the host-observed wall of the first dispatch of that program (which
    blocks on trace+compile -- the benchmark.py compile_s convention)."""
    entry = {"key": key, "program": program,
             "wall_s": round(float(wall_s), 6)}
    entry.update(meta)
    with self._lock:
      self._ledger.append(entry)
    self.add_span("compile", program, self._time() - float(wall_s),
                  float(wall_s), {"fingerprint": key, **meta})

  def compile_ledger(self) -> Dict[str, Any]:
    """This run's ledger summary: distinct program shapes + total
    compile seconds (the bench.py JSON fields)."""
    with self._lock:
      entries = list(self._ledger)
    return {
        "shapes": len({e["key"] for e in entries}),
        "total_compile_s": round(sum(e["wall_s"] for e in entries), 6),
        "entries": entries,
    }

  def ledger_lines(self) -> List[str]:
    """The run-end compile-ledger table, every row a whole
    self-identifying line (scrape-guard contract)."""
    ledger = self.compile_ledger()
    if not ledger["entries"]:
      return []
    lines = ["compile ledger: %d program shape(s), total compile %.2f s"
             % (ledger["shapes"], ledger["total_compile_s"])]
    lines.append("compile ledger: fingerprint        wall_s  program")
    for e in ledger["entries"]:
      extra = "".join(
          f"  {k}={e[k]}" for k in sorted(e)
          if k not in ("key", "program", "wall_s"))
      lines.append("compile ledger: %-16s %8.3f  %s%s" % (
          e["key"][:16], e["wall_s"], e["program"], extra))
    return lines

  def write_ledger(self, train_dir: str) -> Optional[str]:
    """Persist/merge the ledger to ``train_dir/compile_ledger.json``.

    Merged by fingerprint key across runs (compiles count up; best/last
    walls kept), so the file accumulates the per-shape compile history
    the persistent compile cache (ROADMAP item 5) will key on. Returns
    the path, or None when nothing compiled / the write failed."""
    ledger = self.compile_ledger()
    if not ledger["entries"]:
      return None
    path = os.path.join(train_dir, LEDGER_FILENAME)
    entries: Dict[str, Any] = {}
    try:
      with open(path, encoding="utf-8") as f:
        prior = json.load(f)
      if isinstance(prior, dict) and isinstance(prior.get("entries"),
                                                dict):
        entries = prior["entries"]
    except (OSError, ValueError):
      entries = {}
    for e in ledger["entries"]:
      row = entries.setdefault(e["key"], {
          "program": e["program"], "compiles": 0,
          "min_wall_s": e["wall_s"]})
      row["compiles"] = int(row.get("compiles", 0)) + 1
      row["last_wall_s"] = e["wall_s"]
      row["min_wall_s"] = min(float(row.get("min_wall_s", e["wall_s"])),
                              e["wall_s"])
      for k, v in e.items():
        if k not in ("key", "wall_s"):
          row.setdefault(k, v)
      if "cache_hit" in e:
        # Last value wins: the shape's FIRST run legitimately misses
        # and every later run should read as the hit it was.
        row["cache_hit"] = e["cache_hit"]
    payload = {"run_id": self.run_id, "entries": entries}
    try:
      os.makedirs(train_dir, exist_ok=True)
      tmp = path + ".tmp"
      with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
      os.replace(tmp, path)
    except OSError as e:
      self._log(f"compile ledger write failed (non-fatal): {e}")
      return None
    return path

  # -- export -----------------------------------------------------------------

  def _epoch_us(self, t_mono: float) -> float:
    return (self._anchor_wall + (t_mono - self._anchor_mono)) * 1e6

  def chrome_events(self) -> List[Dict[str, Any]]:
    """This rank's spans as Chrome trace events (metadata + X/i)."""
    with self._lock:
      spans = list(self._spans)
      tids = dict(self._tids)
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": self.rank, "tid": 0,
        "args": {"name": f"rank {self.rank}"},
    }]
    used = {s["tid"] for s in spans}
    for sub, tid in sorted(tids.items(), key=lambda kv: kv[1]):
      if tid in used:
        events.append({"ph": "M", "name": "thread_name",
                       "pid": self.rank, "tid": tid,
                       "args": {"name": sub}})
    for s in spans:
      e = {"ph": s["ph"], "name": s["name"], "cat": s["sub"],
           "pid": self.rank, "tid": s["tid"],
           "ts": round(self._epoch_us(s["t0"]), 3),
           "args": {"span_id": s["id"], **s["args"]}}
      if s["ph"] == "X":
        e["dur"] = round(s["dur"] * 1e6, 3)
      else:
        e["s"] = "t"  # instant scope: thread
      events.append(e)
    return events

  def _prior_events(self, path: str) -> List[Dict[str, Any]]:
    """THIS rank's events from an earlier generation's file at
    ``path``: a kfrun checkpoint-restart re-execs the same command with
    the same KF_RUN_ID, and the relaunched generation must EXTEND the
    job's timeline, not truncate it. Foreign run ids (a fresh job
    reusing the path) and unreadable files carry nothing over -- those
    overwrite. Filtered to this rank's pid (rank 0's canonical file may
    be a prior MERGE holding every rank; sibling ranks re-contribute
    their own history through their own rank files) and to non-metadata
    events (metadata regenerates)."""
    try:
      with open(path, encoding="utf-8") as f:
        data = json.load(f)
    except (OSError, ValueError):
      return []
    if not isinstance(data, dict) or \
        data.get("metadata", {}).get("run_id") != self.run_id:
      return []
    return [e for e in data.get("traceEvents", [])
            if isinstance(e, dict) and e.get("ph") != "M"
            and e.get("pid") == self.rank]

  def export(self, merge_wait_s: float = 10.0) -> Optional[str]:
    """Write this rank's span file; rank 0 additionally merges every
    rank's file into one coherent timeline at ``path``.

    Rank files: rank 0 owns ``path`` itself, rank r writes
    ``rank_path(path, r)``. The rank-0 merge waits (bounded, host-side
    file polling -- no process is ever signaled) for sibling files
    because ranks reach run end at slightly different wall times; files
    still missing at the deadline are skipped with a logged note, and
    the per-rank files remain on disk either way. A same-run-id file
    already at the rank path (an earlier restart generation) is
    extended, not truncated."""
    if not self.path:
      return None
    my_path = rank_path(self.path, self.rank)
    my_events: List[Dict[str, Any]] = []
    try:
      os.makedirs(os.path.dirname(my_path) or ".", exist_ok=True)
      # Atomic tmp + os.replace (the write_ledger pattern): the rank-0
      # merge polls for sibling FILES, so a non-atomic write would be
      # seen (and dropped as unreadable) the instant open() creates it.
      tmp = my_path + ".tmp"
      if self.chrome_format:
        my_events = self._prior_events(my_path) + self.chrome_events()
        my_events.sort(key=_event_sort_key)
        with open(tmp, "w", encoding="utf-8") as f:
          json.dump(_payload(my_events, self.run_id,
                             dropped_spans=self._dropped), f)
      else:
        # --use_chrome_trace_format=false: the raw span records, one
        # JSON line each (the flight-recorder-style schema), for
        # consumers that want the unconverted timeline. Same-run-id
        # files extend (restart generations); others are overwritten.
        prior_lines: List[str] = []
        try:
          with open(my_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
          if lines and json.loads(lines[0]).get("run_id") == self.run_id:
            prior_lines = lines
        except (OSError, ValueError):
          pass
        with open(tmp, "w", encoding="utf-8") as f:
          if prior_lines:
            f.write("\n".join(prior_lines) + "\n")
          else:
            f.write(json.dumps({"run_id": self.run_id,
                                "rank": self.rank,
                                "anchor_wall": self._anchor_wall,
                                "anchor_mono": self._anchor_mono})
                    + "\n")
          with self._lock:
            for s in self._spans:
              f.write(json.dumps(s) + "\n")
      os.replace(tmp, my_path)
    except OSError as e:
      self._log(f"trace export failed (non-fatal): {e}")
      return None
    if self.rank != 0 or self.num_ranks <= 1 or not self.chrome_format:
      return my_path
    return self._merge_ranks(my_events, merge_wait_s) or my_path

  def _merge_ranks(self, my_events: List[Dict[str, Any]],
                   wait_s: float) -> Optional[str]:
    """Rank-0 exit merge: one timeline with pid=rank per process.
    ``my_events`` is rank 0's just-exported event list (including any
    prior-generation carry-over)."""
    expected = [rank_path(self.path, r) for r in range(1, self.num_ranks)]
    deadline = time.monotonic() + max(0.0, wait_s)
    while (any(not os.path.exists(p) for p in expected) and
           time.monotonic() < deadline):
      time.sleep(0.1)
    events = list(my_events)
    missing = []
    for p in expected:
      try:
        with open(p, encoding="utf-8") as f:
          data = json.load(f)
        if data.get("metadata", {}).get("run_id") != self.run_id:
          # A stale sibling from a previous job at the same path must
          # not fold foreign epoch-anchored events into THIS run's
          # timeline (same foreign-run-id rule as _prior_events).
          missing.append(p + " (foreign run id)")
          continue
        events.extend(e for e in data.get("traceEvents", [])
                      if isinstance(e, dict))
      except (OSError, ValueError):
        missing.append(p)
    if missing:
      self._log("trace merge: %d rank file(s) missing/unreadable/"
                "foreign at exit (%s); merged what arrived" % (
                    len(missing), ", ".join(missing)))
    events.sort(key=_event_sort_key)
    try:
      with open(self.path, "w", encoding="utf-8") as f:
        json.dump(_payload(events, self.run_id,
                           dropped_spans=self._dropped), f)
    except OSError as e:
      self._log(f"trace merge write failed (non-fatal): {e}")
      return None
    return self.path


# -- compile-ledger query API -------------------------------------------------
# Read side of the persisted ledger (write_ledger above): the autotuner's
# warm pass (analysis/autotune.py) cross-references it to decide which
# program shapes to precompile, and benchmark.py reads the prior keys
# for the cache_hit heuristic. Pure stdlib, like everything here.

def read_ledger(train_dir: str) -> Dict[str, Any]:
  """The persisted compile ledger at ``train_dir/compile_ledger.json``
  ({"entries": {}} when absent/unreadable/foreign-shaped -- a missing
  ledger must read as empty history, never raise)."""
  path = os.path.join(train_dir, LEDGER_FILENAME)
  try:
    with open(path, encoding="utf-8") as f:
      data = json.load(f)
  except (OSError, ValueError):
    return {"entries": {}}
  if not isinstance(data, dict) or not isinstance(data.get("entries"),
                                                  dict):
    return {"entries": {}}
  return data


def ledger_keys(ledger: Dict[str, Any]) -> set:
  """The program-shape fingerprint keys a ledger has seen."""
  return set((ledger or {}).get("entries") or {})


def ledger_programs(ledger: Dict[str, Any]) -> set:
  """The program labels (train_step / train_chunk / eval_step ...) a
  ledger predicts a job of this train_dir will compile."""
  out = set()
  for row in ((ledger or {}).get("entries") or {}).values():
    if isinstance(row, dict) and row.get("program"):
      out.add(str(row["program"]))
  return out


def merge_rank_files(path: str, num_ranks: int,
                     run_id: str = "") -> Optional[str]:
  """Standalone merge of already-written per-rank Chrome files (for
  post-hoc tooling/tests when rank 0's exit merge raced a slow rank)."""
  events: List[Dict[str, Any]] = []
  found = 0
  for r in range(num_ranks):
    p = rank_path(path, r)
    try:
      with open(p, encoding="utf-8") as f:
        data = json.load(f)
    except (OSError, ValueError):
      continue
    found += 1
    events.extend(e for e in data.get("traceEvents", [])
                  if isinstance(e, dict))
    run_id = run_id or data.get("metadata", {}).get("run_id", "")
  if not found:
    return None
  events.sort(key=_event_sort_key)
  with open(path, "w", encoding="utf-8") as f:
    json.dump(_payload(events, run_id, merged_ranks=found), f)
  return path


# -- active-session registry --------------------------------------------------
# Deep call sites (DeviceFeeder's worker thread, checkpoint saves, fault
# firing) emit through the active session instead of threading a handle
# through every signature; with no session active they hit the no-op
# sink below, which keeps the untraced hot path allocation-free.

class _NullTrace:
  """No-op sink with the RunTrace emission AND reporting surface (so
  code paths that never installed a session -- direct _train_loop test
  callers -- report empty rather than crash)."""

  rank = 0
  run_id = ""
  path = None

  def now(self) -> float:
    return 0.0

  def add_span(self, *a, **k) -> int:
    return 0

  def instant(self, *a, **k) -> int:
    return 0

  @contextlib.contextmanager
  def span(self, *a, **k):
    yield {}

  def add_sample(self, *a, **k) -> None:
    pass

  def note_compile(self, *a, **k) -> None:
    pass

  def percentiles(self) -> Dict[str, Any]:
    return {}

  def percentile_fields(self) -> Dict[str, Any]:
    return {}

  def latency_lines(self) -> List[str]:
    return []

  def compile_ledger(self) -> Dict[str, Any]:
    return {"shapes": 0, "total_compile_s": 0.0, "entries": []}

  def ledger_lines(self) -> List[str]:
    return []

  def write_ledger(self, train_dir: str) -> None:
    return None

  def export(self, *a, **k) -> None:
    return None


NULL_TRACE = _NullTrace()
_active: Any = None


def activate(trace: RunTrace) -> RunTrace:
  global _active
  _active = trace
  return trace


def deactivate() -> None:
  global _active
  _active = None


def active():
  """The process's active RunTrace, or the no-op sink."""
  return _active if _active is not None else NULL_TRACE
