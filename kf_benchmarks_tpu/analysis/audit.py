"""Rule engine: check every earned program contract against a config.

Each rule encodes one guarantee a past PR earned and a test pinned for
the configs it happened to cover; here the same invariant is checked
for ANY config (the golden lattice in ``contracts.GOLDEN_CONFIGS``, or
whatever the CLI is pointed at), the way the reference leaned on
graph-mode structure checks before a session ever ran (SURVEY 2).

A rule is (id, applies(config) -> bool, check(contract, tracer) ->
[message]); ``audit_contract`` runs every applicable rule and returns
machine-readable violations. ``tracer`` lets paired rules trace a twin
config (health on vs off) through the same memoized path.

Mutation self-tests (tests/test_program_audit.py) seed violations --
an extra in-loop psum, a leaked f32 wire, a materialized (B, T, V)
buffer -- and assert exactly the intended rule fires, so this engine
cannot rot into a pass-everything stub.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from kf_benchmarks_tpu.analysis.contracts import ProgramContract


@dataclasses.dataclass
class Violation:
  rule: str
  message: str

  def as_dict(self):
    return {"rule": self.rule, "message": self.message}


def _cfg(contract: ProgramContract, name: str, default=None):
  return contract.config.get(name, default)


def _accum(contract) -> int:
  return int(_cfg(contract, "num_grad_accum", 1) or 1)


def _overlap(contract) -> bool:
  return bool(_cfg(contract, "overlap_gradient_reduction", False))


def _replicated_sync(contract) -> bool:
  vu = _cfg(contract, "variable_update", "replicated")
  sync = bool(_cfg(contract, "cross_replica_sync", True))
  return vu in ("replicated", "distributed_replicated", "parameter_server",
                "collective_all_reduce", "distributed_all_reduce") and sync


# -- the earned contracts -----------------------------------------------------

def rule_accum_one_collective(contract, tracer):
  """PR 2: --num_grad_accum pays ONE gradient reduction per step, never
  inside the microbatch scan; with a packing reducer the count is
  literally one."""
  if _accum(contract) <= 1:
    return []
  if _gspmd(contract):
    # GSPMD places the gradient exchange itself; the twin referee's
    # accum leg owns the in-loop check against the manual twin
    # (rule_partitioner_twin; one owner per seeded violation).
    return []
  out = []
  grads = contract.gradient_collectives()
  in_loop = [c for c in grads if c.in_loop]
  if in_loop:
    out.append(f"{len(in_loop)} gradient collective(s) inside the "
               "microbatch scan body -- reduction must be per STEP, "
               "not per microbatch")
  packed = (int(_cfg(contract, "agg_small_grads_max_bytes", 0) or 0) > 0
            or int(_cfg(contract, "gradient_repacking", 0) or 0) > 0)
  if packed and len(grads) != 1:
    out.append(f"expected exactly ONE packed gradient all-reduce per "
               f"accumulated step, found {len(grads)}")
  return out


def rule_overlap_in_backward(contract, tracer):
  """PR 3: in-backward collectives iff --overlap_gradient_reduction.

  Overlap ON with a scanned-layers model: the per-block collective must
  sit INSIDE the backward scan's while body. Overlap OFF (or hooks
  disengaged under --num_grad_accum): NO collective may be in-loop.
  Manual TRAIN programs only: GSPMD decides collective placement
  itself (in-or-out of the scanned backward), so the twin referee
  owns that program shape (rule_partitioner_twin) -- and a tensor-
  parallel serving program's per-block reductions live inside the
  layer scan by construction (same owner)."""
  if _gspmd(contract) or contract.program not in ("train_step",
                                                  "train_chunk"):
    return []
  engaged = _overlap(contract) and _accum(contract) == 1
  in_loop = contract.in_loop_collectives()
  if not engaged:
    if not _replicated_sync(contract):
      # async-PS sequential apply / gossip schedules legitimately issue
      # collectives inside scans; the iff only binds the replicated
      # family the overlap mode is defined for.
      return []
    if _accum(contract) > 1:
      # The microbatch scan is rule_accum_one_collective's territory
      # (one owner per seeded violation, so mutation self-tests can
      # assert exactly one rule fires).
      return []
    if _cfg(contract, "shard_params", False):
      # Full FSDP's per-block gathers/scatters live inside the scan
      # body by DESIGN; rule_fsdp_residency owns that program shape
      # (one owner per seeded violation).
      return []
    if in_loop:
      return [f"{len(in_loop)} collective(s) inside a scanned body with "
              "the in-backward hooks off -- a collective leaked into a "
              "while loop"]
    return []
  out = []
  if contract.aux.get("overlap_module_prefixes"):
    if not in_loop:
      out.append("overlap engaged on a scanned-layers model but no "
                 "collective sits inside the backward scan body")
  expected = contract.aux.get("overlap_step_buckets")
  if expected is not None:
    step_grads = [c for c in contract.gradient_collectives()
                  if not c.in_loop]
    if len(step_grads) != expected:
      out.append(f"step-level gradient collectives {len(step_grads)} != "
                 f"planned bucket count {expected}")
  return out


def rule_no_btv_buffer(contract, tracer):
  """PR 2: the fused-head scanned LM materializes no (B, T, V) logits
  tensor anywhere in the compiled step."""
  btv = contract.aux.get("btv_bytes")
  if btv is None:
    return []
  if contract.largest_tensor_bytes >= btv:
    return [f"largest program buffer {contract.largest_tensor_type} "
            f"({contract.largest_tensor_bytes} B) >= the (B, T, V) "
            f"logits tensor ({btv} B) the fused head exists to avoid"]
  return []


def rule_trace_twin(contract, tracer):
  """PR 9: run tracing is HOST-ONLY. The trace-on step program
  (--trace_events_file set, tracing.py) must be STRUCTURALLY IDENTICAL
  to the trace-off twin -- full fingerprint identity (collective
  inventory, wires, donation, optimizer scope, host transfers), not
  just a collective-count bound: a device-side reduction, a host
  transfer or a lost donation smuggled in by instrumentation is exactly
  the regression this rule exists to catch."""
  if not _cfg(contract, "trace_events_file"):
    return []
  if tracer is None:
    return []
  from kf_benchmarks_tpu.analysis import baseline as baseline_lib
  twin_cfg = dict(contract.config)
  twin_cfg.pop("trace_events_file")
  twin = tracer(twin_cfg, contract.program)
  on = baseline_lib.contract_fingerprint(contract)
  off = baseline_lib.contract_fingerprint(twin)
  # The config field differs by construction (it carries the flag).
  on.pop("config", None)
  off.pop("config", None)
  return [
      f"trace-on program differs from the trace-off twin at {field}: "
      f"{off_v!r} (off) vs {on_v!r} (on) -- tracing must stay host-only"
      for field, off_v, on_v in baseline_lib.diff_fingerprints(off, on)]


def rule_metrics_twin(contract, tracer):
  """PR 11: the metrics fabric is HOST-ONLY. A step program traced
  with --metrics_port / --run_store_dir set (metrics.py registry,
  endpoint, run store) must be STRUCTURALLY IDENTICAL to the twin
  without them -- the rule_trace_twin contract, extended to the
  metrics session: device-side instrumentation smuggled in through the
  registry is exactly the regression this catches."""
  if not (_cfg(contract, "metrics_port") or
          _cfg(contract, "run_store_dir")):
    return []
  if tracer is None:
    return []
  from kf_benchmarks_tpu.analysis import baseline as baseline_lib
  twin_cfg = dict(contract.config)
  twin_cfg.pop("metrics_port", None)
  twin_cfg.pop("run_store_dir", None)
  twin = tracer(twin_cfg, contract.program)
  on = baseline_lib.contract_fingerprint(contract)
  off = baseline_lib.contract_fingerprint(twin)
  on.pop("config", None)
  off.pop("config", None)
  return [
      f"metrics-on program differs from the metrics-off twin at "
      f"{field}: {off_v!r} (off) vs {on_v!r} (on) -- the metrics "
      "fabric must stay host-only"
      for field, off_v, on_v in baseline_lib.diff_fingerprints(off, on)]


def rule_health_no_extra_collective(contract, tracer):
  """PR 4: the health-on step carries NO additional collective (the
  stats ride the loss pmean)."""
  if not contract.aux.get("health_stats"):
    return []
  if tracer is None:
    return []
  twin_cfg = dict(contract.config)
  twin_cfg["health_stats"] = False
  twin = tracer(twin_cfg, contract.program)
  n_on = sum(1 for c in contract.collectives if c.kind == "all-reduce")
  n_off = sum(1 for c in twin.collectives if c.kind == "all-reduce")
  if n_on > n_off:
    return [f"health stats added collectives: {n_on} all-reduces vs "
            f"{n_off} with stats off"]
  return []


def rule_wire_dtype(contract, tracer):
  """PR 3 satellite: gradients ride a bf16 wire iff the compact
  transfer engages (--use_fp16, or --compact_gradient_transfer_f32 on
  a packed path); pure-f32 training keeps an f32 wire."""
  grads = contract.gradient_collectives()
  if not grads:
    return []
  compact_16 = bool(_cfg(contract, "compact_gradient_transfer_f32")
                    or _cfg(contract, "use_fp16"))
  # The lowered-level wire (what the program REQUESTS -- the TPU wire)
  # when the tracer recorded it; the compiled dump's dtypes otherwise
  # (XLA:CPU legalizes 16-bit collectives to f32 while compiling).
  requested = contract.aux.get("requested_grad_wires")
  wire = set(requested) if requested else {c.dtype for c in grads}
  if compact_16 and "f32" in wire:
    return [f"16-bit wire expected but f32 gradient all-reduce(s) "
            f"found (wire dtypes: {sorted(wire)})"]
  if not compact_16 and wire != {"f32"}:
    return [f"f32 wire expected (no 16-bit compaction engaged) but "
            f"found wire dtypes {sorted(wire)}"]
  return []


def _sharded(contract) -> bool:
  return bool(_cfg(contract, "shard_optimizer_state", False))


def _gspmd(contract) -> bool:
  """True when the contract's program was partitioned by GSPMD
  (--partitioner=gspmd). The hand-written collective-shape rules
  (sharded exchange kinds, FSDP gather residency, replica-group
  shapes) encode the MANUAL shard_map program; GSPMD is free to pick
  a different-but-correct exchange, so those rules stand down and
  rule_partitioner_twin referees the divergence instead (one owner
  per seeded violation)."""
  return _cfg(contract, "partitioner") == "gspmd"


def _group_sizes(replica_groups: str):
  """Parse an HLO ``{{0,1},{2,3}}`` replica-groups string into the list
  of group sizes (empty when the attribute was absent)."""
  inner = replica_groups.strip().strip("{}")
  if not inner:
    return []
  return [len([t for t in grp.split(",") if t.strip() != ""])
          for grp in inner.split("},{")]


def rule_sharded_collectives(contract, tracer):
  """PR 6: a --shard_optimizer_state step meets its gradients in
  reduce-scatter and returns params by all-gather -- NO full-gradient
  all-reduce may remain (the ZeRO exchange, ops/sharded.py), each
  reduce-scatter group spans the 'batch' axis (B data replicas) and
  each all-gather group the whole mesh, and f32 training keeps f32
  wires on both. Binds only on the MANUAL partitioner's programs --
  GSPMD may legally choose a different exchange (see _gspmd)."""
  if not _sharded(contract) or _gspmd(contract):
    return []
  out = []
  rs = [c for c in contract.collectives
        if c.kind == "reduce-scatter" and not c.scalar]
  ag = [c for c in contract.collectives
        if c.kind == "all-gather" and not c.scalar]
  if not rs:
    out.append("no reduce-scatter in the sharded step program -- the "
               "gradient exchange fell back to something else")
  if not ag:
    out.append("no all-gather in the sharded step program -- updated "
               "params are not being re-assembled from the shards")
  grads = contract.gradient_collectives()
  if grads:
    out.append(f"{len(grads)} full-gradient all-reduce(s) in a sharded "
               "step -- the reduce-scatter path is being duplicated "
               "(or replaced) by the replicated exchange")
  n = contract.aux.get("num_devices")
  n_data = contract.aux.get("num_data_replicas") or n
  if n:
    bad_rs = [c for c in rs if c.replica_groups and
              set(_group_sizes(c.replica_groups)) != {n_data}]
    if bad_rs:
      out.append(
          f"{len(bad_rs)} reduce-scatter(s) with groups not spanning "
          f"the {n_data}-replica 'batch' axis (e.g. "
          f"{bad_rs[0].replica_groups}) -- the scattered mean would "
          "meet the wrong contribution set")
    bad_ag = [c for c in ag if c.replica_groups and
              set(_group_sizes(c.replica_groups)) != {n}]
    if bad_ag:
      out.append(
          f"{len(bad_ag)} all-gather(s) with groups not spanning the "
          f"full {n}-device mesh (e.g. {bad_ag[0].replica_groups}) -- "
          "devices would re-assemble partial parameter trees")
  compact_16 = bool(_cfg(contract, "compact_gradient_transfer_f32")
                    or _cfg(contract, "use_fp16"))
  wires = contract.aux.get("requested_collective_wires") or {}
  sharded_wires = set(wires.get("reduce-scatter", []) +
                      wires.get("all-gather", []))
  if not compact_16 and sharded_wires and sharded_wires != {"f32"}:
    out.append(f"f32 wire expected on the sharded exchange (no 16-bit "
               f"compaction engaged) but found {sorted(sharded_wires)}")
  return out


def rule_sharded_opt_bytes(contract, tracer):
  """PR 6: per-device optimizer-state bytes under
  --shard_optimizer_state are ~|state|/n of the replicated twin's (the
  ZeRO partitioning bound; slack covers the per-leaf zero pad and the
  per-shard scalar counts)."""
  if not _sharded(contract) or tracer is None:
    return []
  per_device = contract.aux.get("opt_state_bytes_per_device")
  n = contract.aux.get("num_devices")
  if per_device is None or not n:
    return []
  twin_cfg = dict(contract.config)
  twin_cfg.pop("shard_optimizer_state")
  # A model axis is only valid WITH sharded state (validation.py), so
  # the replicated twin must drop the mesh too -- the comparison is
  # against the same device count's 1-D replicated state either way.
  twin_cfg.pop("mesh_shape", None)
  # ... and --shard_params requires --shard_optimizer_state, so the
  # replicated twin drops it with the rest.
  twin_cfg.pop("shard_params", None)
  # ... and --partitioner=gspmd requires sharded state too (the twin
  # is the plain replicated program either way -- the ZeRO bound is
  # about the state bytes, not who inserted the collectives).
  twin_cfg.pop("partitioner", None)
  twin = tracer(twin_cfg, contract.program)
  full = twin.aux.get("opt_state_bytes_per_device")
  if full is None:
    return []
  bound = int(full / n * 1.05) + 4096
  if per_device > bound:
    return [f"per-device optimizer state {per_device} B exceeds the "
            f"ZeRO bound ~|state|/n = {full}/{n} B (+pad slack "
            f"{bound} B) -- state is leaking back to replicated"]
  return []


def _fsdp(contract) -> bool:
  return bool(_cfg(contract, "shard_params", False))


def _collective_bytes(c) -> int:
  from kf_benchmarks_tpu.analysis import contracts as contracts_lib
  return int(c.elems) * contracts_lib._ITEMSIZE.get(c.dtype, 4)


def rule_fsdp_residency(contract, tracer):
  """PR 10 (round 15): a --shard_params step never materializes the
  full parameter tree.

  Checks, against the traced aux (contracts.py): (a) scanned FSDP
  models carry their per-block all-gather INSIDE the scan while body;
  (b) the out-of-loop all-gather inventory never exceeds the planned
  step-bucket count -- a whole-tree re-assembly (the round-11 trailing
  gather) would show up as extra gathers here; (c) no single
  all-gather result reaches half the full parameter-tree bytes --
  every live re-assembled param buffer is bucket/block-sized. Under
  --num_grad_accum the in-compute gathers disengage by design (one
  whole-tree gather per step, train_step.py), so only the size bound
  binds there. Manual-partitioner programs only (see _gspmd) -- the
  gspmd twin's residency is refereed by rule_partitioner_twin's
  largest-live-buffer bound against this very program."""
  if not _fsdp(contract) or contract.program != "train_step" or \
      _gspmd(contract):
    return []
  out = []
  full_bytes = contract.aux.get("fsdp_param_full_bytes")
  ags = [c for c in contract.collectives
         if c.kind == "all-gather" and not c.scalar]
  in_loop = [c for c in ags if c.in_loop]
  out_loop = [c for c in ags if not c.in_loop]
  if contract.aux.get("fsdp_engaged", True):
    if contract.aux.get("fsdp_scan_prefixes") and not in_loop:
      out.append(
          "scanned FSDP model but no all-gather inside a scan while "
          "body -- the per-block parameter gather left the loop (full "
          "stack residency)")
    planned = contract.aux.get("fsdp_step_gathers")
    if planned is not None and len(out_loop) > planned:
      out.append(
          f"{len(out_loop)} all-gather(s) outside the scan bodies vs "
          f"{planned} planned step gather bucket(s) -- a full-tree "
          "re-assembly (the round-11 trailing gather) leaked back into "
          "the steady state")
  if full_bytes:
    # Per-gather residency bound: half the full tree, floored at the
    # largest PLANNED bucket result (a tree dominated by one layer --
    # trivial's 1001-way head -- legitimately gathers most of its
    # bytes in that layer's bucket; what must never appear is a gather
    # larger than any planned bucket, i.e. a whole-tree re-assembly).
    planned_max = contract.aux.get("fsdp_max_gather_bytes") or 0
    bound = max(full_bytes // 2, planned_max + 1)
    for where, group in (("in-loop", in_loop), ("step-level", out_loop)):
      big = [c for c in group if _collective_bytes(c) >= bound]
      if big:
        out.append(
            f"{len(big)} {where} all-gather(s) re-assemble "
            f"{_collective_bytes(big[0])} B >= the residency bound "
            f"{bound} B (full tree {full_bytes} B, largest planned "
            f"bucket {planned_max} B) -- params leaked back to "
            "replicated residency")
  return out


def rule_packed_no_overhead(contract, tracer):
  """PR 8 (round 13): --packed_sequences must not change the program
  class. The packed LM still carries no (B, T, V) logits buffer (the
  btv aux must be present so rule_no_btv_buffer binds -- segment
  masking must not have detoured through a dense-head path), and the
  packed step carries NO more collectives than its unpacked twin,
  kind-for-kind: segment masks are pointwise/tile-local and the
  token-weighted metric combine PACKS the loss pmeans into one vector
  (train_step.py), so any count increase is a leak."""
  if not _cfg(contract, "packed_sequences", False):
    return []
  out = []
  if contract.aux.get("btv_bytes") is None:
    out.append("packed transformer_lm contract carries no (B, T, V) "
               "bound aux -- the no-logits rule cannot bind on the "
               "packed program")
  if tracer is None:
    return out
  twin_cfg = dict(contract.config)
  twin_cfg.pop("packed_sequences")
  twin = tracer(twin_cfg, contract.program)

  def counts(c):
    by_kind: Dict[str, int] = {}
    for x in c.collectives:
      by_kind[x.kind] = by_kind.get(x.kind, 0) + 1
    return by_kind

  on, off = counts(contract), counts(twin)
  for kind in sorted(on):
    if on[kind] > off.get(kind, 0):
      out.append(
          f"packed step has {on[kind]} {kind}(s) vs {off.get(kind, 0)} "
          "unpacked -- packing added a collective (the weighted "
          "metric combine must ride ONE packed vector)")
  n_grad_on = len(contract.gradient_collectives())
  n_grad_off = len(twin.gradient_collectives())
  if n_grad_on != n_grad_off:
    out.append(
        f"packed step's gradient collective count {n_grad_on} != "
        f"unpacked twin's {n_grad_off} -- packing must not touch the "
        "gradient exchange")
  return out


def _twin_inventory(contract):
  """Collective inventory keyed on (kind, dtype, rank, placement):
  count, total wire bytes, and the replica-group sizes seen -- the
  rows the partitioner referee diffs between the twins."""
  rows: Dict[tuple, Dict[str, Any]] = {}
  for c in contract.collectives:
    key = (c.kind, c.dtype, "scalar" if c.scalar else "tensor",
           "in_loop" if c.in_loop else "top_level")
    row = rows.setdefault(key, {"count": 0, "bytes": 0, "groups": set()})
    row["count"] += 1
    row["bytes"] += _collective_bytes(c)
    if c.replica_groups:
      row["groups"].update(_group_sizes(c.replica_groups))
  return rows


def _twin_wire_bytes(inventory) -> int:
  """Total non-scalar wire bytes an inventory moves (scalar control
  reductions are noise at any partitioner's scale)."""
  return sum(row["bytes"] for (k, d, r, p), row in inventory.items()
             if r == "tensor")


def partitioner_twin_verdict(contract, twin) -> Dict[str, Any]:
  """ISSUE 17: the twin referee. Diff the gspmd contract against its
  manual twin -- collective inventory (kind/wire/elems/groups/in-loop
  placement) and largest live buffer -- and CLASSIFY the divergence:

  - ``equivalent``: identical inventory rows and buffer within 5%.
  - ``manual-wins`` / ``gspmd-wins``: the programs legitimately
    diverge (GSPMD chose a different exchange); the side moving fewer
    wire bytes (buffer as tiebreak) wins. Not a violation -- the diff
    table IS the deliverable (PERF.md reads it from the report).
  - ``bug``: a divergence no partitioner choice explains -- a host
    transfer only the gspmd side carries, donation lost, a gradient
    collective re-entering the microbatch scan, or the largest live
    buffer blowing past 2x the manual twin's. These violate.

  Returns the machine-readable verdict dict embedded in the audit
  report (classification, per-row diff, buffer ratio, bug messages)."""
  inv_g = _twin_inventory(contract)
  inv_m = _twin_inventory(twin)
  rows = []
  for key in sorted(set(inv_g) | set(inv_m), key=repr):
    g, m = inv_g.get(key), inv_m.get(key)
    if g == m:
      continue
    kind, dtype, rank, placement = key
    rows.append({
        "kind": kind, "dtype": dtype, "rank": rank,
        "placement": placement,
        "manual": {"count": m["count"], "bytes": m["bytes"],
                   "groups": sorted(m["groups"])} if m else None,
        "gspmd": {"count": g["count"], "bytes": g["bytes"],
                  "groups": sorted(g["groups"])} if g else None,
    })
  bytes_g, bytes_m = _twin_wire_bytes(inv_g), _twin_wire_bytes(inv_m)
  buf_g = contract.largest_tensor_bytes
  buf_m = twin.largest_tensor_bytes
  buf_ratio = (buf_g / buf_m) if buf_m else None

  bugs = []
  extra_host = [h for h in contract.host_transfers
                if h not in twin.host_transfers]
  if extra_host:
    bugs.append(f"gspmd-only host transfer(s) {extra_host} -- GSPMD "
                "smuggled a host round-trip into the step the manual "
                "program does without")
  if twin.donated_buffers > 0 and contract.donated_buffers == 0:
    bugs.append("manual twin donates its state but the gspmd program "
                "lost the aliasing -- HBM footprint doubles under "
                "GSPMD for no partitioning reason")
  if _accum(contract) > 1:
    grads_in_loop_g = [c for c in contract.gradient_collectives()
                       if c.in_loop]
    grads_in_loop_m = [c for c in twin.gradient_collectives()
                       if c.in_loop]
    if grads_in_loop_g and not grads_in_loop_m:
      bugs.append(
          f"{len(grads_in_loop_g)} gradient collective(s) inside the "
          "microbatch scan on the gspmd side only -- GSPMD moved the "
          "once-per-step reduction into the per-microbatch body")
  if buf_m and buf_g > 2 * buf_m:
    bugs.append(
        f"gspmd largest live buffer {contract.largest_tensor_type} "
        f"({buf_g} B) blows past 2x the manual twin's "
        f"{twin.largest_tensor_type} ({buf_m} B) -- GSPMD "
        "materialized something the manual program keeps sharded")

  if bugs:
    classification = "bug"
  elif not rows and (buf_ratio is None or 0.95 <= buf_ratio <= 1.05):
    classification = "equivalent"
  elif bytes_g < bytes_m or (bytes_g == bytes_m and buf_g < buf_m):
    classification = "gspmd-wins"
  elif bytes_m < bytes_g or (bytes_g == bytes_m and buf_m < buf_g):
    classification = "manual-wins"
  else:
    classification = "equivalent"
  return {
      "classification": classification,
      "inventory_diff": rows,
      "wire_bytes": {"manual": bytes_m, "gspmd": bytes_g},
      "largest_buffer": {"manual": buf_m, "gspmd": buf_g,
                         "ratio": buf_ratio},
      "bugs": bugs,
  }


def _twin_manual_config(contract) -> Optional[Dict[str, Any]]:
  """The manual twin's config for a gspmd-side contract, or None when
  the referee does not bind. Train programs: the config carries
  ``partitioner='gspmd'``; the twin drops the flag (manual is the
  default). Serving programs: the config carries ``model_shards``; the
  twin is the unsharded decode of the same spec."""
  if contract.program in ("train_step", "train_chunk"):
    if _cfg(contract, "partitioner") != "gspmd":
      return None
    twin_cfg = dict(contract.config)
    twin_cfg.pop("partitioner")
    return twin_cfg
  if contract.program in ("serving_decode", "serving_verify"):
    if not _cfg(contract, "model_shards"):
      return None
    twin_cfg = dict(contract.config)
    twin_cfg.pop("model_shards")
    return twin_cfg
  return None


def rule_partitioner_twin(contract, tracer):
  """ISSUE 17: the gspmd/manual twin referee. A --partitioner=gspmd
  step (or a model-sharded serving decode) is the SAME math lowered
  through GSPMD's propagation instead of the hand-written shard_map
  collectives; the referee traces the manual twin, diffs collective
  inventory + largest live buffer, and classifies
  (partitioner_twin_verdict). Only the ``bug`` class violates --
  equivalent/manual-wins/gspmd-wins are legitimate partitioner
  divergences the report tables for PERF.md."""
  twin_cfg = _twin_manual_config(contract)
  if twin_cfg is None or tracer is None:
    return []
  twin = tracer(twin_cfg, contract.program)
  verdict = partitioner_twin_verdict(contract, twin)
  return [f"gspmd/manual twin divergence classified as a BUG: {msg}"
          for msg in verdict["bugs"]]


def rule_serving_bounded_decode(contract, tracer):
  """Round 18: the serving decode step is a bounded-executable, cache-
  resident program. Binds only on ``serving_decode`` contracts
  (contracts.trace_serving_contract): (a) the decode batch is a
  bucket-ladder member -- the engine may only ever compile ladder
  shapes, which is what bounds the executable set (the e2e half of the
  same invariant pins ledger compiles <= len(ladder),
  tests/test_serving.py); (b) the ring-buffer caches are donated
  (updated in place -- losing the alias doubles serving HBM and breaks
  the AOT call convention); (c) no program buffer reaches the (B, T,
  V) logits tensor's size, and nothing exceeds one KV ring buffer (the
  largest legitimate array) -- a bigger temp is a shape-polymorphic
  materialization leaking into the per-token step."""
  if contract.program != "serving_decode":
    return []
  out = []
  ladder = contract.aux.get("bucket_ladder") or []
  bucket = contract.aux.get("decode_batch")
  if ladder and bucket not in ladder:
    out.append(f"decode batch {bucket} is not a bucket-ladder member "
               f"{ladder} -- an off-ladder shape breaks the bounded "
               "executable set")
  if contract.donated_buffers == 0:
    out.append("KV ring buffers not donated -- the decode step must "
               "update its cache in place (aliasing lost)")
  btv = contract.aux.get("vocab_logits_bytes")
  ring = contract.aux.get("kv_ring_bytes")
  if "kv_pool_bytes" in contract.aux:
    # Paged-KV decode: rule_serving_paged_kv owns the buffer bound for
    # this program shape (one owner per seeded violation) -- the
    # legitimate ceiling there is the page POOL, which must itself sit
    # strictly under the dense ring.
    return out
  # The ring is the largest LEGITIMATE array, so only buffers beyond
  # it are leaks; name the (B, T, V) materialization only when that
  # ceiling genuinely sits above the ring (a small-vocab spec can put
  # btv BELOW the ring -- there the ring bound alone binds, and the
  # ring itself must never fire a false logits violation).
  if ring and contract.largest_tensor_bytes > ring:
    if btv and btv > ring and contract.largest_tensor_bytes >= btv:
      out.append(f"largest decode buffer {contract.largest_tensor_type} "
                 f"({contract.largest_tensor_bytes} B) reaches the "
                 f"(B, T, V) logits tensor ({btv} B) -- the per-token "
                 "step materialized a full-sequence product")
    else:
      out.append(f"largest decode buffer {contract.largest_tensor_type} "
                 f"({contract.largest_tensor_bytes} B) exceeds one KV "
                 f"ring buffer ({ring} B), the largest legitimate "
                 "array in the decode step")
  elif btv and not ring and contract.largest_tensor_bytes >= btv:
    out.append(f"largest decode buffer {contract.largest_tensor_type} "
               f"({contract.largest_tensor_bytes} B) reaches the "
               f"(B, T, V) logits tensor ({btv} B) -- the per-token "
               "step materialized a full-sequence product")
  return out


def rule_serving_paged_kv(contract, tracer):
  """Round 19: the paged-KV decode step's memory bound. Binds on
  ``serving_decode`` contracts whose aux carries ``kv_pool_bytes`` --
  i.e. the spec set ``kv_page_size`` and the cache is a fixed-size
  block pool instead of the dense per-slot ring slab. Two legs: (a)
  the pool itself must sit strictly UNDER the dense ring ceiling
  (``kv_ring_bytes``) -- a pool that reaches the slab it replaces has
  lost paging's whole point (that bound is what lets the engine admit
  more concurrent sessions per HBM byte); (b) no live program buffer
  may reach the dense-slab ceiling either -- a buffer that does is a
  densification leak (e.g. the gather path materializing the
  per-slot (T_max,) view for every slot at once)."""
  if contract.program != "serving_decode":
    return []
  pool = contract.aux.get("kv_pool_bytes")
  if not pool:
    return []
  out = []
  ring = contract.aux.get("kv_ring_bytes")
  if ring and pool >= ring:
    out.append(f"paged KV pool ({pool} B) reaches the dense ring slab "
               f"it replaces ({ring} B) -- the pool must stay strictly "
               "under the dense ceiling or paging buys no concurrency")
  if ring and contract.largest_tensor_bytes >= ring:
    out.append(f"largest paged-decode buffer "
               f"{contract.largest_tensor_type} "
               f"({contract.largest_tensor_bytes} B) reaches the dense "
               f"KV slab ceiling ({ring} B) -- a live buffer at the "
               "slab size is a densification leak in the paged step")
  return out


def rule_serving_verify_bounded(contract, tracer):
  """Round 19: the speculative-decoding verify step scores all k draft
  proposals in ONE prefill-shaped call, with the logits argmax chunked
  (lax.scan over (B, chunk, V) slices). Binds on ``serving_verify``
  contracts: (a) the verify batch is a bucket-ladder member (same
  bounded-executable-set invariant as decode); (b) no program buffer
  reaches the full (B, T, V) logits tensor -- the chunked argmax
  exists precisely so verification never materializes what the fused
  head avoids; the (B, chunk, V) slice (``verify_logits_bytes``) is
  the legitimate ceiling."""
  if contract.program != "serving_verify":
    return []
  out = []
  ladder = contract.aux.get("bucket_ladder") or []
  bucket = contract.aux.get("decode_batch")
  if ladder and bucket not in ladder:
    out.append(f"verify batch {bucket} is not a bucket-ladder member "
               f"{ladder} -- an off-ladder shape breaks the bounded "
               "executable set")
  btv = contract.aux.get("vocab_logits_bytes")
  if btv and contract.largest_tensor_bytes >= btv:
    out.append(f"largest verify buffer {contract.largest_tensor_type} "
               f"({contract.largest_tensor_bytes} B) reaches the "
               f"(B, T, V) logits tensor ({btv} B) -- the chunked "
               "argmax must never materialize the full logits")
  return out


# -- program-shape invariants (every config) ----------------------------------

def rule_no_host_transfer(contract, tracer):
  """The step program must stay device-resident: any infeed/outfeed/
  send/recv would put a host round-trip (~70 ms tunnel RTT) in the
  step."""
  if contract.host_transfers:
    return [f"host-transfer ops in the step program: "
            f"{contract.host_transfers}"]
  return []


def rule_state_donated(contract, tracer):
  """TrainState is donated (donate_argnums=(0,)): losing the aliasing
  doubles the state's HBM footprint."""
  if contract.program == "serving_decode":
    # The serving step donates its KV ring, not a TrainState;
    # rule_serving_bounded_decode owns that program shape (one owner
    # per seeded violation).
    return []
  if contract.program == "serving_verify":
    # The verify step is a pure function of (variables, token rows) --
    # it owns no mutable state, so it donates nothing by design.
    return []
  if contract.donated_buffers == 0:
    return ["no input/output buffer aliasing -- the donated TrainState "
            "stopped aliasing (HBM footprint doubles)"]
  return []


def rule_single_optimizer_apply(contract, tracer):
  """Exactly one optimizer apply per step, outside every scan (async-PS
  sequential_apply is the documented exception and is excluded)."""
  vu = _cfg(contract, "variable_update", "replicated")
  if vu == "parameter_server" and not _cfg(contract, "cross_replica_sync",
                                           True):
    return []
  if contract.program != "train_step":
    return []  # the chunked program scans the WHOLE step by design
  out = []
  if not contract.optimizer_apply_present:
    out.append("optimizer_apply scope missing from the step program "
               "(train_step.py's named_scope)")
  elif contract.optimizer_apply_in_loop:
    out.append("optimizer apply inside a scanned body -- the update "
               "must run once per step, after any microbatch scan")
  return out


def rule_full_mesh_replica_groups(contract, tracer):
  """Replicated-family reductions span the full replica mesh as one
  group -- a split group means a silent partial reduction. On a 2-D
  sharded mesh with a model axis, the metric pmeans legitimately span
  the BATCH axis only (M groups of B devices; model-axis peers hold
  identical values), so groups of exactly num_data_replicas are also
  admitted there. Manual programs only: GSPMD derives its own group
  shapes from the sharding propagation (rule_partitioner_twin diffs
  them against the manual twin's)."""
  if not _replicated_sync(contract) or _gspmd(contract):
    return []
  n = contract.aux.get("num_devices")
  if not n:
    return []
  ok_sizes = {n}
  n_data = contract.aux.get("num_data_replicas")
  if _sharded(contract) and n_data:
    ok_sizes.add(n_data)
  want = "{{" + ",".join(str(i) for i in range(n)) + "}}"
  bad = [c for c in contract.collectives
         if c.kind == "all-reduce" and c.replica_groups
         and set(_group_sizes(c.replica_groups)) not in
         [{s} for s in ok_sizes]]
  if bad:
    alt = (f" or {n_data}-wide batch groups" if len(ok_sizes) > 1
           else "")
    return [f"{len(bad)} all-reduce(s) with partial replica groups "
            f"(want {want}{alt}, got e.g. {bad[0].replica_groups})"]
  return []


# -- one-owner meta-audit (ISSUE 20 satellite) --------------------------------

# The "one owner per seeded violation / per program shape" comments
# above, made checkable. Each row declares (owning rule, property,
# binds(contract)): the rule that owns checking `property` on contracts
# where `binds` holds. The stand-down comments in
# rule_accum_one_collective / rule_overlap_in_backward /
# rule_fsdp_residency / rule_serving_bounded_decode /
# rule_state_donated are the prose versions of these predicates; this
# table is what rule_one_owner enforces, so a future rule (or a widened
# predicate) that silently double-claims a property fails the audit
# with BOTH rule names instead of making the mutation self-tests
# ambiguous about which rule must fire.
OWNERSHIP = [
    ("accum-one-collective", "in-scan-gradient-exchange",
     lambda c: c.program in ("train_step", "train_chunk")
     and not _gspmd(c) and _accum(c) > 1),
    ("overlap-in-backward", "in-scan-gradient-exchange",
     lambda c: c.program in ("train_step", "train_chunk")
     and not _gspmd(c) and _accum(c) == 1 and _replicated_sync(c)
     and not _fsdp(c)),
    ("partitioner-twin", "in-scan-gradient-exchange",
     lambda c: c.program in ("train_step", "train_chunk")
     and _gspmd(c)),
    ("fsdp-residency", "param-gather-residency",
     lambda c: c.program == "train_step" and _fsdp(c)
     and not _gspmd(c)),
    ("partitioner-twin", "param-gather-residency",
     lambda c: c.program in ("train_step", "train_chunk")
     and _gspmd(c)),
    ("serving-bounded-decode", "decode-buffer-bound",
     lambda c: c.program == "serving_decode"
     and "kv_pool_bytes" not in c.aux),
    ("serving-paged-kv", "decode-buffer-bound",
     lambda c: c.program == "serving_decode"
     and "kv_pool_bytes" in c.aux),
    ("state-donated", "state-donation",
     lambda c: c.program not in ("serving_decode", "serving_verify")),
    ("serving-bounded-decode", "state-donation",
     lambda c: c.program == "serving_decode"),
]


def rule_one_owner(contract, tracer):
  """ISSUE 20 satellite: no golden program shape may have TWO rules
  claiming ownership of the same property (see OWNERSHIP). Runs as an
  ordinary rule so every audited contract is checked; a conflict names
  both rules and the contested property."""
  by_property: Dict[str, set] = {}
  for rule_id, prop, binds in OWNERSHIP:
    if binds(contract):
      by_property.setdefault(prop, set()).add(rule_id)
  out = []
  for prop, owners in sorted(by_property.items()):
    if len(owners) > 1:
      out.append(
          f"property '{prop}' is claimed by {len(owners)} rules on "
          f"this program shape: {sorted(owners)} -- exactly one rule "
          "may own a seeded violation (the mutation self-tests assert "
          "ONE rule fires); tighten the OWNERSHIP predicates")
  return out


# -- resume-time contract re-verification -------------------------------------

def check_resumed_state(state, mesh, sharded_state: bool) -> List[str]:
  """Host-side structural re-verification of a TrainState that was just
  rebuilt onto a (possibly different) mesh -- after an elastic rescale
  or a cross-topology checkpoint restore (benchmark.py calls this at
  both seams; the traced-program half of the same contract lives in the
  ``sharded_rescale`` golden).

  Cheap (shape/dtype reads only, no device work) and deliberately
  strict: a rescale that silently produced a wrong-topology state would
  train -- broadcast semantics make almost any leading dim "work" --
  and corrupt the run long after the seam. Returns problem strings
  (empty = contract holds)."""
  problems = []
  n = int(mesh.devices.size)

  def leading(tree, what):
    for leaf in _tree_leaves(tree):
      shape = tuple(getattr(leaf, "shape", ()))
      if not shape or shape[0] != n:
        problems.append(
            f"{what} leaf shape {shape} does not carry the {n}-row "
            "stacked leading dim of the rebuilt mesh")
        return

  leading(state.params, "params")
  leading(state.batch_stats, "batch_stats")
  if sharded_state:
    for leaf in _tree_leaves(state.opt_state):
      shape = tuple(getattr(leaf, "shape", ()))
      if not shape or shape[0] != n:
        problems.append(
            f"sharded opt_state leaf shape {shape} is not an (n, k) "
            f"shard stack for the {n}-device mesh -- the rescale left "
            "state at the old shard count")
        break
  else:
    leading(state.opt_state, "opt_state")
  if tuple(getattr(state.step, "shape", ())) != ():
    problems.append("step is not a replicated scalar after resume")
  return problems


def _tree_leaves(tree):
  try:
    import jax
    return jax.tree.leaves(tree)
  except Exception:
    return []


RULES: Dict[str, Callable] = {
    "trace-twin": rule_trace_twin,
    "metrics-twin": rule_metrics_twin,
    "accum-one-collective": rule_accum_one_collective,
    "overlap-in-backward": rule_overlap_in_backward,
    "no-btv-buffer": rule_no_btv_buffer,
    "health-no-extra-collective": rule_health_no_extra_collective,
    "wire-dtype": rule_wire_dtype,
    "partitioner-twin": rule_partitioner_twin,
    "sharded-collectives": rule_sharded_collectives,
    "sharded-opt-bytes": rule_sharded_opt_bytes,
    "fsdp-residency": rule_fsdp_residency,
    "packed-no-overhead": rule_packed_no_overhead,
    "serving-bounded-decode": rule_serving_bounded_decode,
    "serving-paged-kv": rule_serving_paged_kv,
    "serving-verify-bounded": rule_serving_verify_bounded,
    "no-host-transfer": rule_no_host_transfer,
    "state-donated": rule_state_donated,
    "single-optimizer-apply": rule_single_optimizer_apply,
    "full-mesh-replica-groups": rule_full_mesh_replica_groups,
    "one-owner": rule_one_owner,
}


def audit_contract(contract: ProgramContract,
                   tracer: Optional[Callable] = None,
                   rules: Optional[Dict[str, Callable]] = None
                   ) -> List[Violation]:
  """Run every rule over one contract; return machine-readable
  violations. ``tracer(overrides, program) -> ProgramContract`` serves
  the paired rules (health twin); None skips them."""
  out = []
  for rule_id, rule in (rules or RULES).items():
    for msg in rule(contract, tracer):
      out.append(Violation(rule=rule_id, message=msg))
  return out


def make_memo_tracer() -> Callable:
  """A memoizing ``tracer(overrides, program) -> ProgramContract`` so a
  config traced for the audit is not re-compiled for the golden diff
  (or for a paired rule's twin)."""
  from kf_benchmarks_tpu.analysis import contracts as contracts_lib
  memo: Dict[str, ProgramContract] = {}

  def tracer(overrides, program="train_step"):
    key = repr(sorted(overrides.items())) + program
    if key not in memo:
      if program.startswith("serving"):
        # Serving contracts lower through the engine's own AOT recipe
        # (LMSpec overrides), not make_params -- route them so paired
        # rules (the partitioner-twin referee) can trace serving twins
        # through the same memo.
        memo[key] = contracts_lib.trace_serving_contract(
            dict(overrides), program)
      else:
        memo[key] = contracts_lib.trace_contract(dict(overrides), program)
    return memo[key]

  return tracer


def audit_configs(configs: Dict[str, Dict[str, Any]],
                  tracer: Optional[Callable] = None) -> Dict[str, Any]:
  """Trace + audit each named config; returns the machine-readable
  report the CLI emits as JSON."""
  tracer = tracer or make_memo_tracer()
  report = {"configs": {}, "violations": 0}
  for name, overrides in configs.items():
    contract = tracer(dict(overrides), "train_step")
    violations = audit_contract(contract, tracer)
    report["configs"][name] = {
        "config": dict(overrides),
        "violations": [v.as_dict() for v in violations],
        "collectives": len(contract.collectives),
        "in_loop_collectives": len(contract.in_loop_collectives()),
        "gradient_collectives": len(contract.gradient_collectives()),
    }
    twin_cfg = _twin_manual_config(contract)
    if twin_cfg is not None:
      # The referee's full verdict rides the report (PERF.md's twin
      # inventory-diff table is generated from it); only the "bug"
      # class fed report["violations"] above.
      report["configs"][name]["partitioner_twin"] = (
          partitioner_twin_verdict(contract,
                                   tracer(twin_cfg, contract.program)))
    report["violations"] += len(violations)
  return report
