"""Rule engine: check every earned program contract against a config.

Each rule encodes one guarantee a past PR earned and a test pinned for
the configs it happened to cover; here the same invariant is checked
for ANY config (the golden lattice in ``contracts.GOLDEN_CONFIGS``, or
whatever the CLI is pointed at), the way the reference leaned on
graph-mode structure checks before a session ever ran (SURVEY 2).

A rule is (id, applies(config) -> bool, check(contract, tracer) ->
[message]); ``audit_contract`` runs every applicable rule and returns
machine-readable violations. ``tracer`` lets paired rules trace a twin
config (health on vs off) through the same memoized path.

Mutation self-tests (tests/test_program_audit.py) seed violations --
an extra in-loop psum, a leaked f32 wire, a materialized (B, T, V)
buffer -- and assert exactly the intended rule fires, so this engine
cannot rot into a pass-everything stub.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from kf_benchmarks_tpu.analysis.contracts import ProgramContract


@dataclasses.dataclass
class Violation:
  rule: str
  message: str

  def as_dict(self):
    return {"rule": self.rule, "message": self.message}


def _cfg(contract: ProgramContract, name: str, default=None):
  return contract.config.get(name, default)


def _accum(contract) -> int:
  return int(_cfg(contract, "num_grad_accum", 1) or 1)


def _overlap(contract) -> bool:
  return bool(_cfg(contract, "overlap_gradient_reduction", False))


def _replicated_sync(contract) -> bool:
  vu = _cfg(contract, "variable_update", "replicated")
  sync = bool(_cfg(contract, "cross_replica_sync", True))
  return vu in ("replicated", "distributed_replicated", "parameter_server",
                "collective_all_reduce", "distributed_all_reduce") and sync


# -- the earned contracts -----------------------------------------------------

def rule_accum_one_collective(contract, tracer):
  """PR 2: --num_grad_accum pays ONE gradient reduction per step, never
  inside the microbatch scan; with a packing reducer the count is
  literally one."""
  if _accum(contract) <= 1:
    return []
  out = []
  grads = contract.gradient_collectives()
  in_loop = [c for c in grads if c.in_loop]
  if in_loop:
    out.append(f"{len(in_loop)} gradient collective(s) inside the "
               "microbatch scan body -- reduction must be per STEP, "
               "not per microbatch")
  packed = (int(_cfg(contract, "agg_small_grads_max_bytes", 0) or 0) > 0
            or int(_cfg(contract, "gradient_repacking", 0) or 0) > 0)
  if packed and len(grads) != 1:
    out.append(f"expected exactly ONE packed gradient all-reduce per "
               f"accumulated step, found {len(grads)}")
  return out


def rule_overlap_in_backward(contract, tracer):
  """PR 3: in-backward collectives iff --overlap_gradient_reduction.

  Overlap ON with a scanned-layers model: the per-block collective must
  sit INSIDE the backward scan's while body. Overlap OFF (or hooks
  disengaged under --num_grad_accum): NO collective may be in-loop."""
  engaged = _overlap(contract) and _accum(contract) == 1
  in_loop = contract.in_loop_collectives()
  if not engaged:
    if not _replicated_sync(contract):
      # async-PS sequential apply / gossip schedules legitimately issue
      # collectives inside scans; the iff only binds the replicated
      # family the overlap mode is defined for.
      return []
    if _accum(contract) > 1:
      # The microbatch scan is rule_accum_one_collective's territory
      # (one owner per seeded violation, so mutation self-tests can
      # assert exactly one rule fires).
      return []
    if in_loop:
      return [f"{len(in_loop)} collective(s) inside a scanned body with "
              "the in-backward hooks off -- a collective leaked into a "
              "while loop"]
    return []
  out = []
  if contract.aux.get("overlap_module_prefixes"):
    if not in_loop:
      out.append("overlap engaged on a scanned-layers model but no "
                 "collective sits inside the backward scan body")
  expected = contract.aux.get("overlap_step_buckets")
  if expected is not None:
    step_grads = [c for c in contract.gradient_collectives()
                  if not c.in_loop]
    if len(step_grads) != expected:
      out.append(f"step-level gradient collectives {len(step_grads)} != "
                 f"planned bucket count {expected}")
  return out


def rule_no_btv_buffer(contract, tracer):
  """PR 2: the fused-head scanned LM materializes no (B, T, V) logits
  tensor anywhere in the compiled step."""
  btv = contract.aux.get("btv_bytes")
  if btv is None:
    return []
  if contract.largest_tensor_bytes >= btv:
    return [f"largest program buffer {contract.largest_tensor_type} "
            f"({contract.largest_tensor_bytes} B) >= the (B, T, V) "
            f"logits tensor ({btv} B) the fused head exists to avoid"]
  return []


def rule_health_no_extra_collective(contract, tracer):
  """PR 4: the health-on step carries NO additional collective (the
  stats ride the loss pmean)."""
  if not contract.aux.get("health_stats"):
    return []
  if tracer is None:
    return []
  twin_cfg = dict(contract.config)
  twin_cfg["health_stats"] = False
  twin = tracer(twin_cfg, contract.program)
  n_on = sum(1 for c in contract.collectives if c.kind == "all-reduce")
  n_off = sum(1 for c in twin.collectives if c.kind == "all-reduce")
  if n_on > n_off:
    return [f"health stats added collectives: {n_on} all-reduces vs "
            f"{n_off} with stats off"]
  return []


def rule_wire_dtype(contract, tracer):
  """PR 3 satellite: gradients ride a bf16 wire iff the compact
  transfer engages (--use_fp16, or --compact_gradient_transfer_f32 on
  a packed path); pure-f32 training keeps an f32 wire."""
  grads = contract.gradient_collectives()
  if not grads:
    return []
  compact_16 = bool(_cfg(contract, "compact_gradient_transfer_f32")
                    or _cfg(contract, "use_fp16"))
  # The lowered-level wire (what the program REQUESTS -- the TPU wire)
  # when the tracer recorded it; the compiled dump's dtypes otherwise
  # (XLA:CPU legalizes 16-bit collectives to f32 while compiling).
  requested = contract.aux.get("requested_grad_wires")
  wire = set(requested) if requested else {c.dtype for c in grads}
  if compact_16 and "f32" in wire:
    return [f"16-bit wire expected but f32 gradient all-reduce(s) "
            f"found (wire dtypes: {sorted(wire)})"]
  if not compact_16 and wire != {"f32"}:
    return [f"f32 wire expected (no 16-bit compaction engaged) but "
            f"found wire dtypes {sorted(wire)}"]
  return []


# -- program-shape invariants (every config) ----------------------------------

def rule_no_host_transfer(contract, tracer):
  """The step program must stay device-resident: any infeed/outfeed/
  send/recv would put a host round-trip (~70 ms tunnel RTT) in the
  step."""
  if contract.host_transfers:
    return [f"host-transfer ops in the step program: "
            f"{contract.host_transfers}"]
  return []


def rule_state_donated(contract, tracer):
  """TrainState is donated (donate_argnums=(0,)): losing the aliasing
  doubles the state's HBM footprint."""
  if contract.donated_buffers == 0:
    return ["no input/output buffer aliasing -- the donated TrainState "
            "stopped aliasing (HBM footprint doubles)"]
  return []


def rule_single_optimizer_apply(contract, tracer):
  """Exactly one optimizer apply per step, outside every scan (async-PS
  sequential_apply is the documented exception and is excluded)."""
  vu = _cfg(contract, "variable_update", "replicated")
  if vu == "parameter_server" and not _cfg(contract, "cross_replica_sync",
                                           True):
    return []
  if contract.program != "train_step":
    return []  # the chunked program scans the WHOLE step by design
  out = []
  if not contract.optimizer_apply_present:
    out.append("optimizer_apply scope missing from the step program "
               "(train_step.py's named_scope)")
  elif contract.optimizer_apply_in_loop:
    out.append("optimizer apply inside a scanned body -- the update "
               "must run once per step, after any microbatch scan")
  return out


def rule_full_mesh_replica_groups(contract, tracer):
  """Replicated-family reductions span the full replica mesh as one
  group -- a split group means a silent partial reduction."""
  if not _replicated_sync(contract):
    return []
  n = contract.aux.get("num_devices")
  if not n:
    return []
  want = "{{" + ",".join(str(i) for i in range(n)) + "}}"
  bad = [c for c in contract.collectives
         if c.kind == "all-reduce" and c.replica_groups
         and c.replica_groups != want]
  if bad:
    return [f"{len(bad)} all-reduce(s) with partial replica groups "
            f"(want {want}, got e.g. {bad[0].replica_groups})"]
  return []


RULES: Dict[str, Callable] = {
    "accum-one-collective": rule_accum_one_collective,
    "overlap-in-backward": rule_overlap_in_backward,
    "no-btv-buffer": rule_no_btv_buffer,
    "health-no-extra-collective": rule_health_no_extra_collective,
    "wire-dtype": rule_wire_dtype,
    "no-host-transfer": rule_no_host_transfer,
    "state-donated": rule_state_donated,
    "single-optimizer-apply": rule_single_optimizer_apply,
    "full-mesh-replica-groups": rule_full_mesh_replica_groups,
}


def audit_contract(contract: ProgramContract,
                   tracer: Optional[Callable] = None,
                   rules: Optional[Dict[str, Callable]] = None
                   ) -> List[Violation]:
  """Run every rule over one contract; return machine-readable
  violations. ``tracer(overrides, program) -> ProgramContract`` serves
  the paired rules (health twin); None skips them."""
  out = []
  for rule_id, rule in (rules or RULES).items():
    for msg in rule(contract, tracer):
      out.append(Violation(rule=rule_id, message=msg))
  return out


def make_memo_tracer() -> Callable:
  """A memoizing ``tracer(overrides, program) -> ProgramContract`` so a
  config traced for the audit is not re-compiled for the golden diff
  (or for a paired rule's twin)."""
  from kf_benchmarks_tpu.analysis import contracts as contracts_lib
  memo: Dict[str, ProgramContract] = {}

  def tracer(overrides, program="train_step"):
    key = repr(sorted(overrides.items())) + program
    if key not in memo:
      memo[key] = contracts_lib.trace_contract(dict(overrides), program)
    return memo[key]

  return tracer


def audit_configs(configs: Dict[str, Dict[str, Any]],
                  tracer: Optional[Callable] = None) -> Dict[str, Any]:
  """Trace + audit each named config; returns the machine-readable
  report the CLI emits as JSON."""
  tracer = tracer or make_memo_tracer()
  report = {"configs": {}, "violations": 0}
  for name, overrides in configs.items():
    contract = tracer(dict(overrides), "train_step")
    violations = audit_contract(contract, tracer)
    report["configs"][name] = {
        "config": dict(overrides),
        "violations": [v.as_dict() for v in violations],
        "collectives": len(contract.collectives),
        "in_loop_collectives": len(contract.in_loop_collectives()),
        "gradient_collectives": len(contract.gradient_collectives()),
    }
    report["violations"] += len(violations)
  return report
