"""CLI: ``python -m kf_benchmarks_tpu.analysis
[lint|audit|autotune|warm|all]``.

``lint``/``audit`` are CPU-only and device-free: the audit lowers+
compiles step programs on an 8-virtual-device host mesh (same recipe
as tests/conftest.py) and never executes one; the lint is a pure AST
pass. The audit additionally validates any tuned-config table it finds
(the repo-root table, or ``--table``) against the knob registry --
the ``run_tests.py --audit`` tuned-table leg. Exit status is nonzero
on any lint violation, audit-rule violation, golden diff or
tuned-table problem (stale-jax-version entries are warnings only).

``autotune`` runs the contract-driven knob search (autotune.py:
prune -> rank -> probe) for the named models on the virtual CPU mesh
and writes a tuned-config table; ``--dry-run`` stops after the static
stages (candidates compile but never execute -- the CPU-only CI
rehearsal). ``warm`` precompiles every (tuned-table x ledger) shape of
a train_dir into its persistent XLA cache (run it on the chip BEFORE
a hardware window; serialized, never under a kill timeout).

    python -m kf_benchmarks_tpu.analysis              # lint + audit
    python -m kf_benchmarks_tpu.analysis lint
    python -m kf_benchmarks_tpu.analysis audit [--configs a,b] [--json F]
    python -m kf_benchmarks_tpu.analysis audit --write-goldens
    python -m kf_benchmarks_tpu.analysis autotune --models trivial,lenet \
        --batch_size 4 --out tuned_configs.json [--dry-run]
    python -m kf_benchmarks_tpu.analysis warm --train_dir D [--table T]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _force_virtual_cpu_mesh() -> None:
  """The conftest recipe (tests/conftest.py): XLA_FLAGS must carry the
  host-device count before the backend initializes, and the platform
  flip must happen through jax.config AFTER import (overriding the
  pinned JAX_PLATFORMS env breaks the axon relay -- CLAUDE.md)."""
  xla_flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
  import jax
  jax.config.update("jax_platforms", "cpu")


def run_lint(args) -> int:
  from kf_benchmarks_tpu.analysis import lint
  return lint.main(["--rules", args.rules] if args.rules else [])


def run_tuned_table_audit(args) -> int:
  """The tuned-table schema leg: validate every table in sight (the
  committed repo-root table plus --table) against the knob registry,
  re-derive every entry's fingerprint, flag stale-jax entries."""
  from kf_benchmarks_tpu.analysis import autotune

  paths = []
  if getattr(args, "table", None):
    paths.append(args.table)
  default = os.path.join(REPO_ROOT, autotune.TABLE_FILENAME)
  if os.path.exists(default) and default not in paths:
    paths.append(default)
  n_problems = n_warnings = 0
  for path in paths:
    try:
      table = autotune.load_table(path)
    except autotune.AutotuneError as e:
      print(f"TUNED-TABLE PROBLEM [{path}] {e}")
      n_problems += 1
      continue
    problems, warnings = autotune.validate_table(table)
    for p in problems:
      print(f"TUNED-TABLE PROBLEM [{path}] {p}")
    for w in warnings:
      print(f"tuned-table warning [{path}] {w}")
    n_problems += len(problems)
    n_warnings += len(warnings)
  print(f"tuned-table audit: {n_problems} problem(s), {n_warnings} "
        f"warning(s) across {len(paths)} table(s)")
  return 1 if n_problems else 0


def run_audit(args) -> int:
  _force_virtual_cpu_mesh()
  from kf_benchmarks_tpu.analysis import audit, baseline, contracts

  known = dict(contracts.GOLDEN_CONFIGS)
  known.update(contracts.SERVING_GOLDEN_CONFIGS)
  names = (args.configs.split(",") if args.configs else list(known))
  unknown = [n for n in names if n not in known]
  if unknown:
    print(f"unknown golden config(s): {unknown}; have {list(known)}")
    return 2

  train_names = [n for n in names if n in contracts.GOLDEN_CONFIGS]
  serving_names = [n for n in names
                   if n in contracts.SERVING_GOLDEN_CONFIGS]
  configs = {n: contracts.GOLDEN_CONFIGS[n] for n in train_names}
  tracer = audit.make_memo_tracer()
  report = audit.audit_configs(configs, tracer=tracer)

  # Serving-path contracts: traced through their own lowering recipe
  # (the engine's AOT decode program), audited by the same rule engine.
  serving_contracts = {}
  for name in serving_names:
    cfg = dict(contracts.SERVING_GOLDEN_CONFIGS[name])
    program = cfg.get("program", "serving_decode")
    contract = tracer(cfg, program)
    serving_contracts[name] = contract
    violations = audit.audit_contract(contract, tracer)
    report["configs"][name] = {
        "config": dict(contracts.SERVING_GOLDEN_CONFIGS[name]),
        "violations": [v.as_dict() for v in violations],
        "collectives": len(contract.collectives),
        "in_loop_collectives": len(contract.in_loop_collectives()),
        "gradient_collectives": len(contract.gradient_collectives()),
    }
    twin_cfg = audit._twin_manual_config(contract)
    if twin_cfg is not None:
      report["configs"][name]["partitioner_twin"] = (
          audit.partitioner_twin_verdict(
              contract, tracer(twin_cfg, contract.program)))
    report["violations"] += len(violations)

  diff_total = 0
  for name in names:
    contract = (serving_contracts[name] if name in serving_contracts
                else tracer(configs[name], "train_step"))
    if args.write_goldens:
      path = baseline.write_golden(name, contract)
      print(f"golden written: {path}")
      continue
    diffs = baseline.check_against_golden(name, contract)
    report["configs"][name]["golden_diffs"] = [
        {"field": f, "golden": g, "current": c} for f, g, c in diffs]
    diff_total += len(diffs)
    for f, g, c in diffs:
      print(f"GOLDEN DIFF [{name}] {f}: golden={g!r} current={c!r}")

  # Fourth audit family (ISSUE 20): the SPMD divergence analyzer
  # (analysis/spmd.py) -- ordered-schedule drift the inventory diff
  # cannot see, plus cross-world-size schedule agreement for every
  # sharded golden config ({2,4,8} on the same memoized tracer; only
  # the `bug` class fails, the gspmd twins table as `documented`).
  spmd_total = 0
  if not args.write_goldens:
    from kf_benchmarks_tpu.analysis import spmd
    drift = []
    for name in names:
      contract = (serving_contracts[name] if name in serving_contracts
                  else tracer(configs[name], "train_step"))
      for msg in spmd.schedule_drift(name, contract):
        drift.append({"config": name, "message": msg})
        print(f"SPMD SCHEDULE DRIFT [{name}] {msg}")
    ws = spmd.audit_world_sizes(
        spmd.sharded_world_size_configs(configs), tracer)
    for name, verdict in sorted(ws["verdicts"].items()):
      print(f"spmd world-size [{name}] sizes={verdict['sizes']}: "
            f"{verdict['classification']}")
    for v in ws["violations"]:
      print(f"SPMD DIVERGENCE [{v['config']}] {v['message']}")
    report["spmd"] = {"schedule_drift": drift, "world_size": ws}
    spmd_total = len(drift) + len(ws["violations"])
    print(f"spmd audit: {len(drift)} schedule drift(s), "
          f"{len(ws['violations'])} world-size divergence(s) across "
          f"{len(ws['verdicts'])} sharded config(s)")

  for name, entry in report["configs"].items():
    for v in entry["violations"]:
      print(f"CONTRACT VIOLATION [{name}] [{v['rule']}] {v['message']}")
    status = ("OK" if not entry["violations"]
              and not entry.get("golden_diffs") else "FAIL")
    print(f"audit [{name}]: {status} ({entry['collectives']} collectives, "
          f"{entry['gradient_collectives']} gradient, "
          f"{entry['in_loop_collectives']} in-loop)")

  if args.json:
    with open(args.json, "w", encoding="utf-8") as f:
      json.dump(report, f, indent=2, sort_keys=True)
    print(f"report written: {args.json}")
  print(f"program-contract audit: {report['violations']} violation(s), "
        f"{diff_total} golden diff(s) across {len(names)} config(s)")
  if args.write_goldens:
    # Regeneration mode's exit code reflects golden regeneration only:
    # the intentional-program-change scenario it exists for is exactly
    # when the tuned table's re-derivation leg fires (the table is
    # regenerated separately, with `analysis autotune` -- the ordinary
    # audit keeps failing until it is).
    return 1 if report["violations"] else 0
  rc_tables = run_tuned_table_audit(args)
  return 1 if (report["violations"] or diff_total or spmd_total
               or rc_tables) else 0


def run_autotune(args) -> int:
  if not args.tpu:
    _force_virtual_cpu_mesh()
  from kf_benchmarks_tpu.analysis import autotune

  models = [m for m in (args.models or "").split(",") if m]
  if not models:
    print("autotune: pass --models model[,model...]")
    return 2
  bases = []
  for model in models:
    base = {"model": model}
    if args.batch_size:
      base["batch_size"] = args.batch_size
    if args.tpu:
      # Explicit device so autotune_config's cpu/8-virtual-mesh
      # defaults never apply under --tpu: the probes must measure the
      # real backend (one chip, one process -- serialized), not a CPU
      # stand-in written into the table as the backend's tuning.
      base.update(device="tpu", num_devices=1)
    bases.append(base)
  table = autotune.autotune_configs(
      bases, out=args.out, seed=args.seed, top_k=args.top_k,
      max_candidates=args.max_candidates,
      probe_dispatches=args.probe_dispatches, dry_run=args.dry_run)
  problems, _ = autotune.validate_table(table)
  for p in problems:
    print(f"TUNED-TABLE PROBLEM {p}")
  return 1 if problems else 0


def run_warm(args) -> int:
  if not args.train_dir:
    print("warm: pass --train_dir (the ledger + persistent-cache home)")
    return 2
  if not args.tpu:
    _force_virtual_cpu_mesh()
  from kf_benchmarks_tpu.analysis import autotune

  summary = autotune.warm(args.train_dir, table_path=args.table)
  print(f"warm: {len(summary['warmed'])} shape(s) compiled, "
        f"{len(summary['skipped'])} already warm -> "
        f"{summary['cache_dir']}")
  return 0


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m kf_benchmarks_tpu.analysis", description=__doc__)
  parser.add_argument("mode", nargs="?", default="all",
                      choices=("all", "lint", "audit", "autotune",
                               "warm"))
  parser.add_argument("--configs", default=None,
                      help="comma-separated golden-config names "
                           "(default: all)")
  parser.add_argument("--rules", default=None,
                      help="comma-separated lint rule ids (default: all)")
  parser.add_argument("--json", default=None,
                      help="write the audit report as JSON to this path")
  parser.add_argument("--write-goldens", action="store_true",
                      help="(re)generate tests/golden_contracts/*.json "
                           "from the current tree instead of diffing")
  parser.add_argument("--models", default=None,
                      help="autotune: comma-separated model names")
  parser.add_argument("--batch_size", type=int, default=None,
                      help="autotune: per-device batch for every model "
                           "(default: each model's own)")
  parser.add_argument("--out", default=None,
                      help="autotune: tuned-table output path")
  parser.add_argument("--seed", type=int, default=0,
                      help="autotune: candidate-subsample seed")
  parser.add_argument("--top_k", type=int, default=3,
                      help="autotune: cost-ranked survivors to probe")
  parser.add_argument("--max_candidates", type=int, default=24,
                      help="autotune: seeded grid-subsample bound")
  parser.add_argument("--probe_dispatches", type=int, default=4,
                      help="autotune: differential probe window size")
  parser.add_argument("--dry-run", action="store_true", dest="dry_run",
                      help="autotune: static stages only (trace + "
                           "prune + cost rank); nothing executes -- "
                           "the CPU-only CI rehearsal")
  parser.add_argument("--table", default=None,
                      help="tuned-config table path (warm input / "
                           "audit target beyond the repo-root table)")
  parser.add_argument("--train_dir", default=None,
                      help="warm: the job's train_dir (compile ledger "
                           "+ persistent XLA cache live here)")
  parser.add_argument("--tpu", action="store_true",
                      help="autotune/warm: keep the process on the "
                           "real backend instead of forcing the "
                           "8-virtual-device CPU mesh (serialize TPU "
                           "work; never wrap in a kill timeout)")
  args = parser.parse_args(argv)
  if args.mode == "autotune":
    return run_autotune(args)
  if args.mode == "warm":
    return run_warm(args)
  rc = 0
  if args.mode in ("all", "lint"):
    rc |= run_lint(args)
  if args.mode in ("all", "audit"):
    rc |= run_audit(args)
  return rc


if __name__ == "__main__":
  sys.exit(main())
