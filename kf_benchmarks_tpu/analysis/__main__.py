"""CLI: ``python -m kf_benchmarks_tpu.analysis [lint|audit|all]``.

CPU-only, device-free: the audit lowers+compiles step programs on an
8-virtual-device host mesh (same recipe as tests/conftest.py) and never
executes one; the lint is a pure AST pass. Exit status is nonzero on
any lint violation, audit-rule violation, or golden diff -- the CI
contract ``run_tests.py --audit`` relies on.

    python -m kf_benchmarks_tpu.analysis              # lint + audit
    python -m kf_benchmarks_tpu.analysis lint
    python -m kf_benchmarks_tpu.analysis audit [--configs a,b] [--json F]
    python -m kf_benchmarks_tpu.analysis audit --write-goldens
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_virtual_cpu_mesh() -> None:
  """The conftest recipe (tests/conftest.py): XLA_FLAGS must carry the
  host-device count before the backend initializes, and the platform
  flip must happen through jax.config AFTER import (overriding the
  pinned JAX_PLATFORMS env breaks the axon relay -- CLAUDE.md)."""
  xla_flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
  import jax
  jax.config.update("jax_platforms", "cpu")


def run_lint(args) -> int:
  from kf_benchmarks_tpu.analysis import lint
  return lint.main(["--rules", args.rules] if args.rules else [])


def run_audit(args) -> int:
  _force_virtual_cpu_mesh()
  from kf_benchmarks_tpu.analysis import audit, baseline, contracts

  names = (args.configs.split(",") if args.configs
           else list(contracts.GOLDEN_CONFIGS))
  unknown = [n for n in names if n not in contracts.GOLDEN_CONFIGS]
  if unknown:
    print(f"unknown golden config(s): {unknown}; have "
          f"{list(contracts.GOLDEN_CONFIGS)}")
    return 2

  configs = {n: contracts.GOLDEN_CONFIGS[n] for n in names}
  tracer = audit.make_memo_tracer()
  report = audit.audit_configs(configs, tracer=tracer)

  diff_total = 0
  for name in names:
    contract = tracer(configs[name], "train_step")
    if args.write_goldens:
      path = baseline.write_golden(name, contract)
      print(f"golden written: {path}")
      continue
    diffs = baseline.check_against_golden(name, contract)
    report["configs"][name]["golden_diffs"] = [
        {"field": f, "golden": g, "current": c} for f, g, c in diffs]
    diff_total += len(diffs)
    for f, g, c in diffs:
      print(f"GOLDEN DIFF [{name}] {f}: golden={g!r} current={c!r}")

  for name, entry in report["configs"].items():
    for v in entry["violations"]:
      print(f"CONTRACT VIOLATION [{name}] [{v['rule']}] {v['message']}")
    status = ("OK" if not entry["violations"]
              and not entry.get("golden_diffs") else "FAIL")
    print(f"audit [{name}]: {status} ({entry['collectives']} collectives, "
          f"{entry['gradient_collectives']} gradient, "
          f"{entry['in_loop_collectives']} in-loop)")

  if args.json:
    with open(args.json, "w", encoding="utf-8") as f:
      json.dump(report, f, indent=2, sort_keys=True)
    print(f"report written: {args.json}")
  print(f"program-contract audit: {report['violations']} violation(s), "
        f"{diff_total} golden diff(s) across {len(names)} config(s)")
  if args.write_goldens:
    return 1 if report["violations"] else 0
  return 1 if (report["violations"] or diff_total) else 0


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m kf_benchmarks_tpu.analysis", description=__doc__)
  parser.add_argument("mode", nargs="?", default="all",
                      choices=("all", "lint", "audit"))
  parser.add_argument("--configs", default=None,
                      help="comma-separated golden-config names "
                           "(default: all)")
  parser.add_argument("--rules", default=None,
                      help="comma-separated lint rule ids (default: all)")
  parser.add_argument("--json", default=None,
                      help="write the audit report as JSON to this path")
  parser.add_argument("--write-goldens", action="store_true",
                      help="(re)generate tests/golden_contracts/*.json "
                           "from the current tree instead of diffing")
  args = parser.parse_args(argv)
  rc = 0
  if args.mode in ("all", "lint"):
    rc |= run_lint(args)
  if args.mode in ("all", "audit"):
    rc |= run_audit(args)
  return rc


if __name__ == "__main__":
  sys.exit(main())
