"""Repo-wide hazard lint: CLAUDE.md's hard-won rules as an AST pass.

Each rule encodes an operational hazard this environment taught the
hard way (a wedged TPU tunnel, a lying sync primitive, a silently
unvalidated flag) -- see CLAUDE.md's TPU-environment-hazards section.
Pure stdlib: this file imports nothing beyond the standard library, so
loaded by path (as ``run_tests.py --audit`` does) the lint runs in any
interpreter in ~a second -- note that importing it as
``kf_benchmarks_tpu.analysis.lint`` pulls the package ``__init__``,
which imports jax.

Rules (ids):

* ``block-until-ready`` -- ``jax.block_until_ready`` returns before
  device execution completes on the tunneled backend; every sync must
  go through ``utils.sync.drain``. Banned outside ``utils/sync.py``.
* ``version-gate-comment`` -- jax version gates (``hasattr(jax.lax,
  "pcast")``-style probes, ``jax.__version__`` comparisons) require a
  nearby comment/docstring naming the missing API, so a gate can be
  retired when the API lands (CLAUDE.md: "Add no new version gates
  without a comment naming the missing API").
* ``kill-timeout`` -- a kill-based ``timeout=`` on a subprocess that
  talks to the TPU is the wedge trigger (a client killed mid-claim
  wedges ``jax.devices()`` for hours; round-4 incident). Banned in
  tests AND experiments around TPU-bound subprocesses (experiments
  judge TPU-boundness at module level -- sweep scripts assemble their
  TPU arg lists far from the subprocess call); the compliant pattern
  is the monitored wait (experiments/serving_sweep.monitored_cli:
  short poll ticks, heartbeats, clean-exit retry, never a kill).
* ``step-line-format`` -- the reference step-line format literal is
  single-sourced in ``utils/log.py`` (tests scrape stdout; a drifted
  second copy would print lines the scrapers half-match).
* ``flag-validation`` -- every flag in the params registry either
  appears in ``validation.py`` or carries an explicit entry in its
  ``NO_CROSS_FLAG_VALIDATION`` marker (with a reason); a flag that is
  both is a stale marker.
* ``signal-chain`` -- a ``signal.signal`` registration outside
  ``telemetry.py``/``faults.py`` must capture the previous handler so
  it can chain (the PR-4 SIGTERM contract: a handler that discards the
  chain silences the flight-recorder post-mortem, or eats ctrl-C). A
  bare ``signal.signal(...)`` statement drops the old handler on the
  floor; the compliant form assigns it.
* ``trace-event-emission`` -- run-trace span emission and timing
  helpers are single-sourced in ``tracing.py`` (the same pattern as
  the step-line rule): constructing Chrome trace-event dicts (a dict
  literal carrying a ``"ph"`` or ``"traceEvents"`` key) or defining a
  percentile/chrome-trace helper anywhere else in the package would
  fork the trace schema the tests validate. READING profiler output
  (``e.get("ph")``, observability.py) is fine -- only construction is
  emission.
* ``metric-key-literal`` -- metric keys are single-sourced in the
  metric registry schema (``metrics.py``; the same pattern as the
  step-line and trace-event rules): a string literal in one of the
  schema's namespaces (``health/<k>``, ``<k>_p50/_p90/_p99``) that is
  NOT a registered key, or an f-string ASSEMBLING such a key outside
  ``metrics.py``, forks the key corpus the run stats / bench JSON /
  flight-recorder rows render from. Reading registered keys is free --
  only unregistered lookalikes and out-of-home construction are
  violations; a reasoned allowlist (staleness-checked) covers the one
  producer that cannot import the registry.
* ``citation`` -- every top-level module (and subpackage) cites the
  reference ``file:line`` span it covers, with a reasoned allowlist
  for TPU-native-only modules (folded in from the former standalone
  citation lint; tests/test_citation_lint.py pins it).
* ``rank-divergent-collective`` -- the host-side leg of the SPMD
  divergence analyzer (ISSUE 20; the compiled-program legs live in
  analysis/spmd.py): a collective/barrier call (run_barrier,
  kfcoord_barrier, multihost_utils.*, the ops/ psum/all_gather
  helpers) reachable under a branch on ``jax.process_index()`` /
  ``process_count()`` / ``KFCOORD_RANK_HINT`` / ``is_chief`` is the
  multi-host deadlock class -- one rank skips the rendezvous, every
  other rank hangs (on our tunnel indistinguishable from the wedge).
  Requires a nearby ``all-ranks:`` justification comment; plain
  unguarded barrier calls need the same marker as the documented
  barrier convention (MIGRATION.md, SURVEY 2.9 KungFu exit barrier).
* ``rank-guarded-write`` -- a filesystem write (checkpoint /
  run-store / golden artifacts) under a rank branch must carry the
  ``rank0-owns:`` ownership marker: the one-writer convention has to
  be explicit at the site, or an elastic/resharded run double-writes.

Every allowlist entry is checked for staleness: an entry whose file no
longer trips the rule must be removed, so allowlists cannot rot into
blanket exemptions.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, List, NamedTuple, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_SCAN_DIRS = ("kf_benchmarks_tpu", "tests", "experiments")
_SKIP_PARTS = {"__pycache__", ".git", "native"}


class LintViolation(NamedTuple):
  rule: str
  path: str    # repo-relative, forward slashes
  line: int
  message: str

  def render(self) -> str:
    return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# -- allowlists (every entry carries its reason; staleness-checked) ----------

BLOCK_UNTIL_READY_ALLOWLIST = {
    "experiments/gossip_hier_scale_probe.py":
        "CPU-mesh probe (build_mesh(n, 'cpu')): block_until_ready is "
        "trustworthy on the host platform; the lie is tunnel-specific",
    "experiments/pallas_conv_probe.py":
        "round-2 probe predating the drain discovery; kept verbatim as "
        "the committed measurement artifact behind PERF.md round 2 "
        "(superseded methodology documented in "
        "experiments/pallas_fused_chain_probe.py)",
}

VERSION_GATE_ALLOWLIST = {
    "kf_benchmarks_tpu/compat.py":
        "the version bridge itself: its module docstring names every "
        "shimmed API (jax.shard_map, check_vma/check_rep, lax.axis_size)",
    "tests/test_allreduce.py":
        "pre-vma skip marker: the reason names the missing CPU gloo "
        "cross-host path rather than the gate attr (CLAUDE.md lists it)",
    "tests/test_transformer_scan_remat.py":
        "pre-vma skip marker: composed-program oracle gap "
        "(compat.py check_rep note; CLAUDE.md lists it)",
    "tests/test_tensor_parallel.py":
        "pre-vma skip marker: the Megatron 1-collective HLO assertion "
        "holds on current jax only (CLAUDE.md lists it)",
}

KILL_TIMEOUT_ALLOWLIST: Dict[str, str] = {
    "experiments/serving_sweep.py":
        "the monitored-wait helper itself (monitored_cli): "
        "proc.wait(timeout=POLL_S) is the poll TICK of the no-kill "
        "loop -- TimeoutExpired only logs a heartbeat and keeps "
        "waiting, the child is never signaled. The one compliant use "
        "of a timeout= kwarg; every TPU-bound experiment subprocess "
        "(zoo_sweep, real_data_occupancy) routes through it",
}

SIGNAL_CHAIN_ALLOWLIST: Dict[str, str] = {}

# Citation allowlist (moved here from tests/test_citation_lint.py):
# TPU-native-only units with NO reference analog; each entry names why.
# Directory entries (trailing '/') cover a whole subpackage.
CITATION_ALLOWLIST = {
    "compat.py": "jax-version bridge for THIS image (pre-vma 0.4.37); "
                 "no reference analog",
    "elastic.py": "elastic scaling lives in KungFu's external runtime, "
                  "not the reference repo (SURVEY 2.9); TPU-native "
                  "design module",
    "faults.py": "deterministic fault injection for the elastic tests; "
                 "the reference never kills a worker (KungFu's failure "
                 "handling is external runtime, SURVEY 2.9)",
    "telemetry.py": "runtime training-health layer; the reference's "
                    "observability is post-hoc only (SURVEY 5.1/9)",
    # "analysis/" left the allowlist in round 22: spmd.py cites the
    # reference KungFu exit-barrier span it guards against, so the
    # subpackage now carries a real citation.
}


# -- file plumbing -----------------------------------------------------------

class _Source(NamedTuple):
  path: str          # repo-relative
  text: str
  lines: List[str]
  tree: Optional[ast.AST]
  doc_lines: Dict[int, str]      # line -> comment/string text on that line
  comment_lines: Dict[int, str]  # line -> comment text only


def _doc_lines(text: str, tree: Optional[ast.AST]):
  """(comments+strings, comments-only) text by line: the 'documentation
  channel' the version-gate rule searches for API names. The
  comments-only channel lets the rule discard a gate's own string
  argument without also discarding a trailing comment on that line."""
  out: Dict[int, str] = {}
  comments: Dict[int, str] = {}

  def add(d: Dict[int, str], line: int, s: str) -> None:
    d[line] = d.get(line, "") + " " + s

  try:
    for tok in tokenize.generate_tokens(io.StringIO(text).readline):
      if tok.type == tokenize.COMMENT:
        add(out, tok.start[0], tok.string)
        add(comments, tok.start[0], tok.string)
  except (tokenize.TokenError, IndentationError):
    pass  # malformed file: the string channel below still applies
  if tree is not None:
    for node in ast.walk(tree):
      if isinstance(node, ast.Constant) and isinstance(node.value, str):
        for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
          add(out, line, node.value)
  return out, comments


def iter_sources(root: str) -> List[_Source]:
  files = []
  for entry in sorted(os.listdir(root)):
    full = os.path.join(root, entry)
    if entry.endswith(".py") and os.path.isfile(full):
      files.append(entry)
    elif entry in _SCAN_DIRS and os.path.isdir(full):
      for dirpath, dirnames, filenames in os.walk(full):
        dirnames[:] = [d for d in sorted(dirnames) if d not in _SKIP_PARTS]
        for name in sorted(filenames):
          if name.endswith(".py"):
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            files.append(rel.replace(os.sep, "/"))
  sources = []
  for rel in files:
    text = open(os.path.join(root, rel), encoding="utf-8").read()
    try:
      tree = ast.parse(text)
    except SyntaxError:
      tree = None
    docs, comments = _doc_lines(text, tree)
    sources.append(_Source(rel, text, text.splitlines(), tree, docs,
                           comments))
  return sources


def _enclosing_function_text(src: _Source, lineno: int) -> str:
  """Source text of the smallest def containing ``lineno`` (module
  +-30 lines when at top level) -- the context window the kill-timeout
  rule inspects for TPU-boundness."""
  best = None
  if src.tree is not None:
    for node in ast.walk(src.tree):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        end = node.end_lineno or node.lineno
        if node.lineno <= lineno <= end:
          if best is None or (end - node.lineno) < (
              (best.end_lineno or best.lineno) - best.lineno):
            best = node
  if best is not None:
    return "\n".join(src.lines[best.lineno - 1:(best.end_lineno or
                                                best.lineno)])
  lo, hi = max(0, lineno - 31), min(len(src.lines), lineno + 30)
  return "\n".join(src.lines[lo:hi])


def _stale_allowlist(rule: str, allowlist: Dict[str, str],
                     hit_paths, known_paths) -> List[LintViolation]:
  out = []
  for path, why in sorted(allowlist.items()):
    if path not in known_paths:
      out.append(LintViolation(rule, path, 0,
                               f"stale allowlist entry (file gone): {why}"))
    elif path not in hit_paths:
      out.append(LintViolation(
          rule, path, 0,
          "stale allowlist entry (no longer trips the rule) -- remove "
          f"it: {why}"))
  return out


# -- rule: block-until-ready -------------------------------------------------

def rule_block_until_ready(sources: List[_Source]) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if src.path == "kf_benchmarks_tpu/utils/sync.py" or src.tree is None:
      continue
    for node in ast.walk(src.tree):
      if isinstance(node, ast.Attribute) and \
          node.attr == "block_until_ready":
        hits.add(src.path)
        if src.path in BLOCK_UNTIL_READY_ALLOWLIST:
          continue
        out.append(LintViolation(
            "block-until-ready", src.path, node.lineno,
            "jax.block_until_ready returns before device execution "
            "completes on the tunneled backend (CLAUDE.md); use "
            "kf_benchmarks_tpu.utils.sync.drain at wall-clock "
            "boundaries"))
  out += _stale_allowlist("block-until-ready", BLOCK_UNTIL_READY_ALLOWLIST,
                          hits, {s.path for s in sources})
  return out


# -- rule: version-gate-comment ----------------------------------------------

def _gate_attr(node: ast.Call) -> Optional[str]:
  """The gated attr name when ``node`` is a jax version probe
  (hasattr(jax[.lax], "attr")), else None."""
  if not (isinstance(node.func, ast.Name) and node.func.id == "hasattr"
          and len(node.args) == 2
          and isinstance(node.args[1], ast.Constant)
          and isinstance(node.args[1].value, str)):
    return None
  target = ast.unparse(node.args[0])
  if target == "jax" or target.endswith("lax") or target.startswith("jax."):
    return node.args[1].value
  return None


def rule_version_gate_comment(sources: List[_Source]
                              ) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if src.tree is None:
      continue
    gates = []
    for node in ast.walk(src.tree):
      if isinstance(node, ast.Call):
        attr = _gate_attr(node)
        if attr is not None:
          gates.append((node.lineno, attr, node.args[1].lineno))
      elif isinstance(node, ast.Compare):
        names = {ast.unparse(n) for n in ast.walk(node)
                 if isinstance(n, ast.Attribute)}
        if any(n.endswith("__version__") and "jax" in n for n in names):
          gates.append((node.lineno, "version", node.lineno))
    for lineno, attr, arg_line in gates:
      # The documentation channel: comments/strings in the surrounding
      # window. On the gate's own argument line only COMMENTS count
      # (hasattr's string arg names the attr by construction, but a
      # trailing comment there is legitimate documentation).
      window = ""
      for line in range(max(1, lineno - 12), lineno + 4):
        channel = (src.comment_lines if line == arg_line
                   else src.doc_lines)
        window += channel.get(line, "")
      if attr in window:
        continue
      hits.add(src.path)
      if src.path in VERSION_GATE_ALLOWLIST:
        continue
      out.append(LintViolation(
          "version-gate-comment", src.path, lineno,
          f"version gate on {attr!r} without a nearby comment naming "
          "the missing API (CLAUDE.md: gates must say what API absence "
          "they bridge, so they can be retired when it lands)"))
  out += _stale_allowlist("version-gate-comment", VERSION_GATE_ALLOWLIST,
                          hits, {s.path for s in sources})
  return out


# -- rule: kill-timeout ------------------------------------------------------

_SUBPROCESS_ATTRS = {"run", "call", "check_call", "check_output",
                     "communicate", "wait", "Popen"}
_TPU_MARKERS = ("--device=tpu", "device=tpu", 'pop("JAX_PLATFORMS"',
                "pop('JAX_PLATFORMS'")
# Experiments assemble their TPU CLI arg lists far from the subprocess
# call (main() builds them, a helper runs them), so TPU-boundness is
# judged on the WHOLE module, and the default-device argparse idiom
# counts as a marker too.
_TPU_MARKERS_EXPERIMENTS = _TPU_MARKERS + ('default="tpu"',
                                           "default='tpu'")


def rule_kill_timeout(sources: List[_Source]) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    in_tests = src.path.startswith("tests/")
    in_experiments = src.path.startswith("experiments/")
    if not (in_tests or in_experiments) or src.tree is None:
      continue
    for node in ast.walk(src.tree):
      if not (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _SUBPROCESS_ATTRS
              and any(kw.arg == "timeout" for kw in node.keywords)):
        continue
      if in_tests:
        context = _enclosing_function_text(src, node.lineno)
        markers = _TPU_MARKERS
      else:
        context = src.text
        markers = _TPU_MARKERS_EXPERIMENTS
      if not any(marker in context for marker in markers):
        continue
      hits.add(src.path)
      if src.path in KILL_TIMEOUT_ALLOWLIST:
        continue
      out.append(LintViolation(
          "kill-timeout", src.path, node.lineno,
          "kill-based timeout= around a TPU-bound subprocess: the "
          "timeout kill mid-claim is the tunnel-wedge trigger "
          "(CLAUDE.md round-4 incident) -- monitor without killing "
          "(experiments/serving_sweep.monitored_cli is the compliant "
          "pattern), or drop the timeout"))
  out += _stale_allowlist("kill-timeout", KILL_TIMEOUT_ALLOWLIST, hits,
                          {s.path for s in sources})
  return out


# -- rule: signal-chain ------------------------------------------------------

# The two modules allowed to own handler registration: telemetry.py
# (the chained SIGTERM/SIGINT post-mortem handlers, PR 4) and faults.py
# (the injection harness that exercises them).
_SIGNAL_HOMES = ("kf_benchmarks_tpu/telemetry.py",
                 "kf_benchmarks_tpu/faults.py")


def _imported_signal_names(tree: ast.AST):
  """(direct, modules): local names bound to signal.signal by ``from
  signal import signal [as X]`` (the direct-call form) and local names
  the signal MODULE is bound to by ``import signal [as Y]`` (the
  ``Y.signal(...)`` form)."""
  direct, modules = set(), set()
  for node in ast.walk(tree):
    if isinstance(node, ast.ImportFrom) and node.module == "signal":
      for alias in node.names:
        if alias.name == "signal":
          direct.add(alias.asname or alias.name)
    elif isinstance(node, ast.Import):
      for alias in node.names:
        if alias.name == "signal":
          modules.add(alias.asname or alias.name)
  return direct, modules


def _is_signal_signal_call(node: ast.Call, direct_names: set,
                           module_names: set) -> bool:
  if isinstance(node.func, ast.Attribute) and node.func.attr == "signal":
    base = ast.unparse(node.func.value).split(".")[-1]
    return base == "signal" or base in module_names
  if isinstance(node.func, ast.Name):
    return node.func.id in direct_names
  return False


def rule_signal_chain(sources: List[_Source]) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if src.path in _SIGNAL_HOMES or src.tree is None:
      continue
    direct_names, module_names = _imported_signal_names(src.tree)
    for node in ast.walk(src.tree):
      # A registration whose RESULT is discarded (a bare expression
      # statement) drops the previous handler; the compliant form
      # assigns it so the new handler can chain.
      if not (isinstance(node, ast.Expr)
              and isinstance(node.value, ast.Call)
              and _is_signal_signal_call(node.value, direct_names,
                                         module_names)):
        continue
      hits.add(src.path)
      if src.path in SIGNAL_CHAIN_ALLOWLIST:
        continue
      out.append(LintViolation(
          "signal-chain", src.path, node.lineno,
          "signal.signal registration discards the previous handler -- "
          "capture it (`old = signal.signal(...)`) and chain, or move "
          "the registration into telemetry.py/faults.py (the PR-4 "
          "SIGTERM chaining contract: an unchained handler silences "
          "the flight-recorder post-mortem or eats ctrl-C)"))
  out += _stale_allowlist("signal-chain", SIGNAL_CHAIN_ALLOWLIST, hits,
                          {s.path for s in sources})
  return out


# -- rule: step-line-format --------------------------------------------------

# Concatenated so this module's own constants never contain the marker
# (the rule scans every package file, this one included).
_STEP_LINE_MARKER = "images/sec" + ":"
_STEP_LINE_HOME = "kf_benchmarks_tpu/utils/log.py"


def rule_step_line_format(sources: List[_Source]) -> List[LintViolation]:
  out = []
  for src in sources:
    if not (src.path.startswith("kf_benchmarks_tpu/")
            or src.path == "bench.py"):
      continue
    if src.path == _STEP_LINE_HOME or src.tree is None:
      continue
    for node in ast.walk(src.tree):
      if isinstance(node, ast.Constant) and isinstance(node.value, str) \
          and _STEP_LINE_MARKER in node.value:
        out.append(LintViolation(
            "step-line-format", src.path, node.lineno,
            f"step-line format literal outside {_STEP_LINE_HOME}: tests "
            "scrape stdout against the single-sourced format "
            "(utils/log.py format_step_line/format_total_line); call "
            "the helper instead of re-stating the literal"))
  return out


# -- rule: trace-event-emission ----------------------------------------------

# Trace-event construction markers: a dict literal carrying one of
# these keys IS a Chrome trace event being built. Reads
# (e.get("ph"), data["traceEvents"]) do not match -- only construction.
_TRACE_EVENT_KEYS = {"ph", "traceEvents"}
# Helper names whose definitions outside the home fork the timing
# conventions the exported schema depends on.
_TRACE_HELPER_NAMES = {"percentile", "percentiles", "chrome_events",
                       "chrome_trace_events"}
_TRACE_HOME = "kf_benchmarks_tpu/tracing.py"

TRACE_EMISSION_ALLOWLIST: Dict[str, str] = {}


def rule_trace_event_emission(sources: List[_Source]
                              ) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if not (src.path.startswith("kf_benchmarks_tpu/")
            or src.path == "bench.py"):
      continue
    if src.path == _TRACE_HOME or src.tree is None:
      continue
    findings = []
    for node in ast.walk(src.tree):
      if isinstance(node, ast.Dict):
        keys = {k.value for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}
        if keys & _TRACE_EVENT_KEYS:
          findings.append((node.lineno,
                           "Chrome trace-event dict constructed"))
      elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
          and node.name in _TRACE_HELPER_NAMES:
        findings.append((node.lineno,
                         f"trace helper {node.name}() defined"))
    for lineno, what in findings:
      hits.add(src.path)
      if src.path in TRACE_EMISSION_ALLOWLIST:
        continue
      out.append(LintViolation(
          "trace-event-emission", src.path, lineno,
          f"{what} outside {_TRACE_HOME}: span emission and timing "
          "helpers are single-sourced there (the exported Chrome "
          "schema is validated against that one writer; emit through "
          "tracing.active() / import tracing.percentile instead)"))
  out += _stale_allowlist("trace-event-emission", TRACE_EMISSION_ALLOWLIST,
                          hits, {s.path for s in sources})
  return out


# -- rule: metric-key-literal ------------------------------------------------

_METRICS_HOME = "kf_benchmarks_tpu/metrics.py"
# Schema-registration helper names in the home (the first literal arg
# of each call IS a registered key); parsed from the AST so this lint
# stays pure stdlib (importing metrics.py as a package module would
# pull jax via the package __init__).
_METRIC_REGISTER_FUNCS = {"_register", "_gauge", "_counter", "_hist",
                          "_info"}
# The key namespaces the schema owns: a whole-string literal matching
# one of these is a metric key by construction.
_METRIC_KEY_PATTERNS = (
    re.compile(r"health/\w+"),
    re.compile(r"\w+_p(?:50|90|99)"),
)


def _is_metric_key_fragment(s: str) -> bool:
  """A string FRAGMENT that assembles a schema-namespace key when
  joined with other pieces (f-string parts, '+'-concatenation
  operands): the health/ prefix, or a percentile suffix -- bare
  (``"_p" + q``) or literal (``f"{key}_p50"``)."""
  return ("health/" in s or s.endswith("_p")
          or bool(re.search(r"_p(?:50|90|99)$", s)))

METRIC_KEY_ALLOWLIST = {
    "kf_benchmarks_tpu/tracing.py":
        "percentile_fields builds <key>_p<q> over SAMPLE_KEYS x "
        "QUANTILES -- the one producer that cannot import the registry "
        "(tracing.py must stay loadable standalone, and the package "
        "import would pull jax); metrics.schema_audit cross-checks "
        "every rendered key against the schema instead",
}


def _registered_metric_keys(sources: List[_Source]):
  """(keys, found_home): literal first args of the schema-registration
  calls in metrics.py."""
  keys = set()
  src = next((s for s in sources if s.path == _METRICS_HOME), None)
  if src is None or src.tree is None:
    return keys, False
  for node in ast.walk(src.tree):
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
        and node.func.id in _METRIC_REGISTER_FUNCS and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)):
      keys.add(node.args[0].value)
  return keys, True


# Publish methods whose labels= keyword names must come from the
# schema's LABEL_NAMES tuple (the dimensional half of single-sourcing:
# an emitter inventing a label name is the same hazard as inventing a
# key -- the runtime check catches it live, this catches it in CI).
_METRIC_PUBLISH_METHODS = {"set", "inc", "observe"}


def _registered_label_names(sources: List[_Source]):
  """The LABEL_NAMES tuple literal from metrics.py, parsed from the
  AST (same stdlib-only discipline as _registered_metric_keys)."""
  src = next((s for s in sources if s.path == _METRICS_HOME), None)
  if src is None or src.tree is None:
    return set()
  for node in ast.walk(src.tree):
    if (isinstance(node, ast.Assign) and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
        and node.targets[0].id == "LABEL_NAMES"
        and isinstance(node.value, (ast.Tuple, ast.List))):
      return {e.value for e in node.value.elts
              if isinstance(e, ast.Constant)
              and isinstance(e.value, str)}
  return set()


def rule_metric_key_literal(sources: List[_Source]) -> List[LintViolation]:
  keys, found_home = _registered_metric_keys(sources)
  label_names = _registered_label_names(sources)
  out, hits = [], set()
  for src in sources:
    if not (src.path.startswith("kf_benchmarks_tpu/")
            or src.path == "bench.py"):
      continue
    if src.path == _METRICS_HOME or src.tree is None:
      continue
    # String constants that sit inside an ASSEMBLY expression are
    # judged as fragments there, not as whole-key literals here.
    assembled_constants = set()
    for node in ast.walk(src.tree):
      if isinstance(node, ast.JoinedStr):
        for v in node.values:
          if isinstance(v, ast.Constant):
            assembled_constants.add(id(v))
      elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        for side in (node.left, node.right):
          if isinstance(side, ast.Constant):
            assembled_constants.add(id(side))
    findings = []
    for node in ast.walk(src.tree):
      if isinstance(node, ast.Constant) and isinstance(node.value, str) \
          and id(node) not in assembled_constants:
        if any(p.fullmatch(node.value) for p in _METRIC_KEY_PATTERNS) \
            and node.value not in keys:
          findings.append((node.lineno,
                           f"metric-key literal {node.value!r} is not "
                           "registered in the metrics.py schema"))
      elif isinstance(node, ast.JoinedStr):
        parts = [v.value for v in node.values
                 if isinstance(v, ast.Constant)
                 and isinstance(v.value, str)]
        if any(_is_metric_key_fragment(p) for p in parts):
          findings.append((node.lineno,
                           "metric key assembled by f-string"))
      elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        sides = [s.value for s in (node.left, node.right)
                 if isinstance(s, ast.Constant)
                 and isinstance(s.value, str)]
        if any(_is_metric_key_fragment(s) for s in sides):
          findings.append((node.lineno,
                           "metric key assembled by concatenation"))
      elif (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_PUBLISH_METHODS
            and label_names):
        for kw in node.keywords:
          if kw.arg != "labels" or not isinstance(kw.value, ast.Dict):
            continue
          for k in kw.value.keys:
            if (isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and k.value not in label_names):
              findings.append((
                  node.lineno,
                  f"unregistered metric label name {k.value!r} "
                  f"(LABEL_NAMES declares {sorted(label_names)})"))
    for lineno, what in findings:
      hits.add(src.path)
      if src.path in METRIC_KEY_ALLOWLIST:
        continue
      msg = (f"{what} outside {_METRICS_HOME}: metric keys are "
             "single-sourced in the registry schema (register the key "
             "there, or build it through its helpers -- "
             "metrics.health_key / the registered percentile fields)")
      if not found_home:
        msg = (f"{what}: no {_METRICS_HOME} schema found to check "
               "against (package moved?)")
      out.append(LintViolation("metric-key-literal", src.path, lineno,
                               msg))
  out += _stale_allowlist("metric-key-literal", METRIC_KEY_ALLOWLIST,
                          hits, {s.path for s in sources})
  return out


# -- rule: flag-validation ---------------------------------------------------

def _registry_flags(src: _Source) -> List[str]:
  names = []
  if src.tree is None:
    return names
  for node in ast.walk(src.tree):
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
        and node.func.attr.startswith("DEFINE_") and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)):
      names.append(node.args[0].value)
  return names


def _marker_dict(src: _Source):
  """(entries, lineno_span) of validation.py's NO_CROSS_FLAG_VALIDATION
  marker dict, or ({}, None)."""
  if src.tree is None:
    return {}, None
  for node in ast.walk(src.tree):
    if (isinstance(node, ast.Assign) and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
        and node.targets[0].id == "NO_CROSS_FLAG_VALIDATION"
        and isinstance(node.value, ast.Dict)):
      entries = {}
      for k, v in zip(node.value.keys, node.value.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
          entries[k.value] = (ast.unparse(v) if not isinstance(
              v, ast.Constant) else v.value)
      return entries, (node.lineno, node.end_lineno or node.lineno)
  return {}, None


def rule_flag_validation(sources: List[_Source]) -> List[LintViolation]:
  by_path = {s.path: s for s in sources}
  params_src = by_path.get("kf_benchmarks_tpu/params.py")
  val_src = by_path.get("kf_benchmarks_tpu/validation.py")
  if params_src is None or val_src is None:
    return []
  flags = _registry_flags(params_src)
  marked, span = _marker_dict(val_src)
  # Mentions are searched OUTSIDE the marker dict (a marker entry must
  # not count as validation coverage).
  lines = list(val_src.lines)
  if span is not None:
    for line in range(span[0], span[1] + 1):
      lines[line - 1] = ""
  val_text = "\n".join(lines)
  out = []
  for name in flags:
    mentioned = re.search(rf"\b{re.escape(name)}\b", val_text)
    if mentioned and name in marked:
      out.append(LintViolation(
          "flag-validation", "kf_benchmarks_tpu/validation.py", span[0],
          f"stale NO_CROSS_FLAG_VALIDATION marker: --{name} now appears "
          "in validation.py -- remove the marker entry"))
    elif not mentioned and name not in marked:
      out.append(LintViolation(
          "flag-validation", "kf_benchmarks_tpu/params.py", 0,
          f"--{name} neither appears in validation.py nor carries a "
          "NO_CROSS_FLAG_VALIDATION marker entry (validation.py): add "
          "a cross-flag check or an explicit reasoned marker"))
  for name in marked:
    if name not in flags:
      out.append(LintViolation(
          "flag-validation", "kf_benchmarks_tpu/validation.py",
          span[0] if span else 0,
          f"NO_CROSS_FLAG_VALIDATION marker for unknown flag --{name}"))
  return out


# -- rule: citation ----------------------------------------------------------

_FILE_LINE_CITE = re.compile(r"[\w/.\-]+\.(?:py|cc|md|proto|sh):\d+")
_MD_SECTION_CITE = re.compile(r'[\w/.\-]+\.md "[^"]+"')


def _has_citation(text: str) -> bool:
  return bool(_FILE_LINE_CITE.search(text) or _MD_SECTION_CITE.search(text))


def rule_citation(sources: List[_Source]) -> List[LintViolation]:
  pkg = "kf_benchmarks_tpu/"
  modules = {}   # unit name ("foo.py" or "sub/") -> [texts]
  for src in sources:
    if not src.path.startswith(pkg):
      continue
    rel = src.path[len(pkg):]
    if "/" in rel:
      unit = rel.split("/", 1)[0] + "/"
    else:
      unit = rel
    modules.setdefault(unit, []).append(src.text)
  if len(modules) < 15:
    # Guard against the walker silently matching nothing (e.g. a moved
    # package): the tree this lint protects has >= 15 top-level units.
    return [LintViolation("citation", pkg, 0,
                          f"citation walker found only {len(modules)} "
                          "units -- package moved?")]
  out = []
  for unit, texts in sorted(modules.items()):
    cited = any(_has_citation(t) for t in texts)
    if unit in CITATION_ALLOWLIST:
      if cited:
        out.append(LintViolation(
            "citation", pkg + unit, 0,
            "allowlist entry now carries a citation -- remove it from "
            "CITATION_ALLOWLIST"))
      continue
    if not cited:
      out.append(LintViolation(
          "citation", pkg + unit, 0,
          "module missing the reference file:line citation comment "
          "(CLAUDE.md convention): cite the reference span it covers, "
          "or add a CITATION_ALLOWLIST entry stating why there is no "
          "analog"))
  for unit, why in CITATION_ALLOWLIST.items():
    if unit not in modules:
      out.append(LintViolation(
          "citation", pkg + unit, 0,
          f"stale CITATION_ALLOWLIST entry (unit gone): {why}"))
  return out


# -- rules: rank-divergence (ISSUE 20 leg c) ---------------------------------

# Host-level calls that issue or await a cross-rank rendezvous: every
# rank must reach them or the job hangs. These are the HOST-side sites
# the compiler never sees (the compiled step's schedule is checked by
# analysis/spmd.py; this rule owns the python control flow around it).
_BARRIER_CALL_NAMES = {"run_barrier", "kfcoord_barrier", "barrier",
                       "sync_global_devices",
                       "make_array_from_process_local_data"}
_BARRIER_TEXT_MARKERS = ("multihost_utils", "distributed.initialize")
# In-SPMD collective helpers (ops/, parallel/kungfu.py): fine unguarded
# (the compiler schedules them identically on every rank), but reached
# under a rank branch they are the same deadlock hazard.
_COLLECTIVE_HELPER_NAMES = {"allreduce_mean", "broadcast", "pair_average",
                            "sync_average", "gossip_shift", "psum",
                            "pmean", "all_gather", "ppermute",
                            "all_to_all"}
# Host control flow that diverges by rank: tests mentioning any of
# these make the branch rank-divergent.
_RANK_TEST_MARKERS = ("process_index", "process_count",
                      "KFCOORD_RANK_HINT", "is_chief", "current_rank")
# Justification markers; COMMENT channel only (a docstring merely
# mentioning the convention must not silence the rule). Concatenated so
# this module's own constants never contain them.
_ALL_RANKS_MARKER = "all-ranks" + ":"
_RANK0_MARKER = "rank0-owns" + ":"

RANK_DIVERGENCE_ALLOWLIST: Dict[str, str] = {}

RANK_WRITE_ALLOWLIST: Dict[str, str] = {}


def _call_names(node: ast.Call):
  """(last, dotted) name of a call target: the final attr/id plus the
  full dotted text (for module-path markers like multihost_utils)."""
  func = node.func
  last = (func.attr if isinstance(func, ast.Attribute)
          else func.id if isinstance(func, ast.Name) else "")
  try:
    dotted = ast.unparse(func)
  except Exception:
    dotted = last
  return last, dotted


def _rank_guard_regions(src: _Source):
  """[(guard_line, lo, hi)] line spans where host control flow has
  already diverged by rank: each rank-test If's own span, plus -- for
  the early-return shape (``if <rank-test>: return/raise`` with no
  else, checkpoint.save_checkpoint's idiom) -- the remainder of the
  smallest enclosing function (or module)."""
  if src.tree is None:
    return []
  funcs = [n for n in ast.walk(src.tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
  regions = []
  for node in ast.walk(src.tree):
    if not isinstance(node, ast.If):
      continue
    try:
      test_text = ast.unparse(node.test)
    except Exception:
      continue
    if not any(m in test_text for m in _RANK_TEST_MARKERS):
      continue
    end = node.end_lineno or node.lineno
    regions.append((node.lineno, node.lineno, end))
    terminal = bool(node.body) and isinstance(
        node.body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))
    if terminal and not node.orelse:
      scope_end = len(src.lines)
      best_span = None
      for f in funcs:
        f_end = f.end_lineno or f.lineno
        if f.lineno <= node.lineno <= f_end:
          span = f_end - f.lineno
          if best_span is None or span < best_span:
            best_span, scope_end = span, f_end
      regions.append((node.lineno, end + 1, scope_end))
  return regions


def _rank_guard_for(regions, lineno: int) -> Optional[int]:
  """The nearest rank-test guard line whose divergent region covers
  ``lineno``, or None when the site is reached by every rank."""
  best = None
  for guard, lo, hi in regions:
    if lo <= lineno <= hi and (best is None or guard > best):
      best = guard
  return best


def _marker_in_comments(src: _Source, marker: str, lo: int,
                        hi: int) -> bool:
  return any(marker in src.comment_lines.get(line, "")
             for line in range(max(1, lo), hi + 1))


def rule_rank_divergent_collective(sources: List[_Source]
                                   ) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if not src.path.startswith("kf_benchmarks_tpu/") or src.tree is None:
      continue
    regions = _rank_guard_regions(src)
    for node in ast.walk(src.tree):
      if not isinstance(node, ast.Call):
        continue
      last, dotted = _call_names(node)
      is_barrier = (last in _BARRIER_CALL_NAMES
                    or any(m in dotted for m in _BARRIER_TEXT_MARKERS))
      is_helper = last in _COLLECTIVE_HELPER_NAMES
      if not (is_barrier or is_helper):
        continue
      guard = _rank_guard_for(regions, node.lineno)
      if guard is not None:
        lo = guard
      elif is_barrier:
        # The barrier convention: even an unguarded cross-rank barrier
        # documents at the site why every rank reaches it.
        lo = node.lineno - 4
      else:
        continue  # unguarded in-SPMD helper: the compiler's schedule
      if _marker_in_comments(src, _ALL_RANKS_MARKER, lo,
                             node.lineno + 1):
        continue
      hits.add(src.path)
      if src.path in RANK_DIVERGENCE_ALLOWLIST:
        continue
      if guard is not None:
        msg = (f"collective/barrier call {last or dotted}() is "
               f"rank-divergent (rank-test guard at line {guard}) "
               f"without an '{_ALL_RANKS_MARKER}' justification "
               "comment -- a rank that skips the rendezvous hangs "
               "every other rank (the multi-host deadlock class; on "
               "our tunnel indistinguishable from the wedge hazard)")
      else:
        msg = (f"cross-rank barrier call {last or dotted}() without "
               f"an '{_ALL_RANKS_MARKER}' convention comment naming "
               "why every rank reaches it (the lint-enforced barrier "
               "convention -- MIGRATION.md, SURVEY 2.9 KungFu exit "
               "barrier)")
      out.append(LintViolation("rank-divergent-collective", src.path,
                               node.lineno, msg))
  out += _stale_allowlist("rank-divergent-collective",
                          RANK_DIVERGENCE_ALLOWLIST, hits,
                          {s.path for s in sources})
  return out


# Filesystem mutations the one-writer convention covers. `dump`/`open`
# appear everywhere; they only count here when RANK-GUARDED.
_WRITE_CALL_NAMES = {"makedirs", "mkdir", "save_checkpoint",
                     "write_golden", "write_text", "dump", "replace",
                     "rename", "unlink", "remove", "rmtree"}


def _is_write_open(node: ast.Call) -> bool:
  last, _ = _call_names(node)
  if last != "open":
    return False
  mode = None
  if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
    mode = node.args[1].value
  for kw in node.keywords:
    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
      mode = kw.value.value
  return isinstance(mode, str) and any(c in mode for c in "wax")


def rule_rank_guarded_write(sources: List[_Source]) -> List[LintViolation]:
  out, hits = [], set()
  for src in sources:
    if not src.path.startswith("kf_benchmarks_tpu/") or src.tree is None:
      continue
    regions = _rank_guard_regions(src)
    if not regions:
      continue
    for node in ast.walk(src.tree):
      if not isinstance(node, ast.Call):
        continue
      last, _ = _call_names(node)
      if not (last in _WRITE_CALL_NAMES or _is_write_open(node)):
        continue
      guard = _rank_guard_for(regions, node.lineno)
      if guard is None:
        continue
      if _marker_in_comments(src, _RANK0_MARKER, guard,
                             node.lineno + 1):
        continue
      hits.add(src.path)
      if src.path in RANK_WRITE_ALLOWLIST:
        continue
      out.append(LintViolation(
          "rank-guarded-write", src.path, node.lineno,
          f"rank-guarded filesystem write {last or 'open'}() (rank-test "
          f"guard at line {guard}) without a '{_RANK0_MARKER}' "
          "ownership comment -- the rank-0-owns-it convention must be "
          "explicit at the site (checkpoint/run-store/golden artifacts "
          "have exactly one writer; an elastic or resharded run would "
          "otherwise double-write)"))
  out += _stale_allowlist("rank-guarded-write", RANK_WRITE_ALLOWLIST,
                          hits, {s.path for s in sources})
  return out


# -- driver ------------------------------------------------------------------

RULES = {
    "block-until-ready": rule_block_until_ready,
    "version-gate-comment": rule_version_gate_comment,
    "kill-timeout": rule_kill_timeout,
    "signal-chain": rule_signal_chain,
    "step-line-format": rule_step_line_format,
    "trace-event-emission": rule_trace_event_emission,
    "metric-key-literal": rule_metric_key_literal,
    "flag-validation": rule_flag_validation,
    "citation": rule_citation,
    "rank-divergent-collective": rule_rank_divergent_collective,
    "rank-guarded-write": rule_rank_guarded_write,
}


def run_lint(root: str = REPO,
             rules: Optional[List[str]] = None) -> List[LintViolation]:
  sources = iter_sources(root)
  out: List[LintViolation] = []
  for rule_id, rule in RULES.items():
    if rules is not None and rule_id not in rules:
      continue
    out.extend(rule(sources))
  return sorted(out)


def main(argv=None) -> int:
  import argparse
  parser = argparse.ArgumentParser(description="repo hazard lint")
  parser.add_argument("--root", default=REPO)
  parser.add_argument("--rules", default=None,
                      help="comma-separated rule ids (default: all)")
  args = parser.parse_args(argv)
  rules = args.rules.split(",") if args.rules else None
  violations = run_lint(args.root, rules)
  for v in violations:
    print(v.render())
  print(f"hazard lint: {len(violations)} violation(s) across "
        f"{len(RULES if rules is None else rules)} rule(s)")
  return 1 if violations else 0


if __name__ == "__main__":
  raise SystemExit(main())
