"""Static analysis of the framework: program contracts + hazard lint.

TPU-NATIVE-ONLY subsystem (no single reference file to cite; the
reference analog is its reliance on GRAPH-MODE STRUCTURE -- variable
scopes, collective op counts, staging-area wiring -- asserted by
inspecting the built tf.Graph before any session ran. Here the
compiled XLA program plays the graph's role, so the same guarantees
are checked by lowering ``jit`` programs without executing them. See the
graph-structure-assumptions section of MIGRATION.md and COVERAGE.md.)

Two layers:

* ``contracts`` / ``audit`` / ``baseline`` -- the **program-contract
  auditor**: trace (never execute) the train step for a
  ``BenchmarkParams`` config on the abstract 8-device mesh via
  ``jit(...).lower(...).compile()``, extract a structured
  :class:`~kf_benchmarks_tpu.analysis.contracts.ProgramContract`
  (collective inventory with wire dtypes and loop placement, host
  transfers, optimizer-apply scope, donation, largest live buffers),
  check every earned invariant per config (``audit``), and diff
  against checked-in goldens (``baseline``,
  ``tests/golden_contracts/*.json``).

* ``lint`` -- the **hazard lint**: an AST pass over the repo encoding
  CLAUDE.md's hard-won environment rules (``jax.block_until_ready``
  banned outside ``utils/sync.py``, version gates need a comment
  naming the missing API, kill-based timeouts around TPU subprocesses
  banned in tests and experiments, step-line format literals
  single-sourced, flags must be cross-validated or carry an explicit
  no-validation marker, reference citations per module). Pure stdlib:
  importing ``lint`` never imports jax.

* ``autotune`` -- the **contract-driven autotuner**: the auditor's
  tracing machinery turned search oracle. Candidates over the tuned
  program-shaping knobs are pruned statically against memory/
  collective bounds (never executed), cost-ranked from the contract
  inventory, confirmed with differential measured probes, and emitted
  as a versioned tuned-config table ``--autotuned_config`` applies at
  startup; the same module's ``warm`` precompiles every
  (table x compile-ledger) program shape into the persistent XLA
  cache ahead of a hardware window.

CLI: ``python -m kf_benchmarks_tpu.analysis`` (see ``__main__``);
CI entry: ``python run_tests.py --audit``.
"""
