"""Static SPMD divergence analyzer (ISSUE 20): deadlock-freedom checks
for the multi-host leg, run before any 2-process job touches hardware.

The classic multi-host failure mode is a cross-rank collective mismatch:
one rank issues an all-gather the others never reach, the job hangs
silently, and on our tunnel that is indistinguishable from the wedge
hazard in CLAUDE.md. The reference had exactly this class of bug in its
KungFu exit path (SURVEY 2.9, tf_cnn_benchmarks.py:58-60 barrier). The
existing audit checks collective *inventories* (unordered multisets);
two programs with identical inventories can still deadlock each other
when their *schedules* -- the rendezvous order -- differ. This pass has
two device-free legs (the third leg, the rank-divergence lint, is an
AST pass in analysis/lint.py):

* **Ordered schedules** (:func:`schedule_drift`): every golden contract
  now pins its ``collective_schedule`` (contracts.Collective
  .schedule_entry rows in compiled-dump definition order). When the
  schedule drifts while the inventory still matches, the audit fails
  with the exact regen command -- an inventory-equal reorder is
  precisely the silent class the old golden diff missed.
* **Cross-world-size agreement** (:func:`world_size_verdict`): every
  sharded golden config is traced at world sizes {2, 4, 8} on the
  virtual CPU mesh (checkpoint._reshard re-addresses the (n, k) shard
  stacks at ANY n', so these are all reachable elastic-rescale sizes)
  and the schedules must be identical modulo replica-group arity and
  commutation of scalar control reductions (:func:`schedule_diffs`:
  the tensor exchange chain compares as a strict sequence, scalar
  metric pmeans as a multiset -- their textual position floats).
  Divergences classify like audit.rule_partitioner_twin's referee:
  ``benign_arity`` (same sequence, groups differ only in width --
  the expected shape), ``documented`` (a gspmd-partitioned program:
  GSPMD legally re-plans the exchange per topology, sharding
  thresholds and divisibility change with n -- tabled, not failed),
  ``bug`` (a manual program whose rendezvous order changed with the
  world size -- the deadlock class; the only failing verdict).

Static tracing only: every trace goes through the audit's memoized
tracer (jit().lower().compile(); nothing executes). The serving
tensor-parallel twin is out of scope here -- its model mesh is pinned
by head-count divisibility (serving_decode_tp, M | n_heads), not by the
elastic world size _reshard ranges over.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple

from kf_benchmarks_tpu.analysis.contracts import (
    GOLDEN_CONFIGS, N_REPLICAS, ProgramContract)

# The exact command the schedule-drift failure names (an intentional
# program change regenerates the pinned schedules the same way every
# other golden field regenerates).
REGEN_COMMAND = "python -m kf_benchmarks_tpu.analysis audit --write-goldens"

# The elastic world sizes the agreement leg traces (all reachable:
# checkpoint._reshard re-addresses zero-padded row-major shard stacks
# at any n', and sharded_rescale's golden already pins n=4).
WORLD_SIZES = (2, 4, 8)


def schedule_key(entry: Dict[str, Any]) -> Tuple[str, str, str, str]:
  """The arity-free identity of one schedule row: everything two ranks
  must agree on for the collective to rendezvous. Group sizes are
  excluded -- they widen with the world size by construction -- and the
  index is the row's list position."""
  return (entry["kind"], entry["dtype"], entry["rank"],
          entry["placement"])


def normalize_schedule(schedule: List[Dict[str, Any]]
                       ) -> List[Tuple[str, str, str, str]]:
  """A schedule modulo replica-group arity (see :func:`schedule_key`)."""
  return [schedule_key(e) for e in schedule]


def schedule_diffs(ref: List[Dict[str, Any]],
                   other: List[Dict[str, Any]]) -> List[str]:
  """Human-readable divergences of two schedules modulo group arity
  AND modulo commutation of scalar control reductions; empty when they
  agree.

  TENSOR collectives (the gradient/param exchange chain) compare as a
  strict sequence: they are data-dependent on each other, so their
  order IS the rendezvous order -- a reorder is the deadlock class.
  SCALAR collectives (loss/metric pmeans) compare as a multiset: a
  scalar reduction is data-independent of the exchange chain, so its
  HLO textual position legally floats with the topology (measured:
  sharded_base's loss pmean prints at position 0 for n=8 and position
  2 for n=2 around a bit-identical exchange) -- textual definition
  order is a DAG print order, not an execution order, for independent
  ops."""
  na, nb = normalize_schedule(ref), normalize_schedule(other)
  ta = [r for r in na if r[2] == "tensor"]
  tb = [r for r in nb if r[2] == "tensor"]
  sa = Counter(r for r in na if r[2] == "scalar")
  sb = Counter(r for r in nb if r[2] == "scalar")
  if ta == tb and sa == sb:
    return []
  out = []
  if ta != tb:
    if len(ta) != len(tb):
      out.append(f"tensor-collective sequence length {len(ta)} vs "
                 f"{len(tb)}")
    for i, (a, b) in enumerate(zip(ta, tb)):
      if a != b:
        out.append(f"first tensor-sequence divergence at position {i}: "
                   f"{'/'.join(a)} vs {'/'.join(b)}")
        break
    else:
      i = min(len(ta), len(tb))
      longer = ta if len(ta) > len(tb) else tb
      if i < len(longer):
        out.append(f"first tensor-sequence divergence at position {i}: "
                   f"trailing {'/'.join(longer[i])} on one side only")
  for row in sorted(set(sa) | set(sb)):
    if sa[row] != sb[row]:
      out.append(f"scalar collective {'/'.join(row)} count "
                 f"{sa[row]} vs {sb[row]}")
  return out


# -- leg (a): ordered-schedule drift vs the golden ----------------------------

def schedule_drift(name: str, contract: ProgramContract) -> List[str]:
  """Schedule drift the inventory diff cannot see: the golden's
  unordered collective inventory still matches, but the ORDERED
  ``collective_schedule`` differs (a reorder, or a same-row swap
  between loop bodies). Returns failure messages naming the exact
  regen command; empty when the schedule holds, when the golden is
  missing (the whole-file diff owns that), or when the inventory
  itself drifted (the field-level golden diff owns that)."""
  from kf_benchmarks_tpu.analysis import baseline

  if not os.path.exists(baseline.golden_path(name)):
    return []
  golden = baseline.load_golden(name)
  current = baseline.contract_fingerprint(contract)
  if golden.get("collectives") != current.get("collectives"):
    return []
  g_sched = golden.get("collective_schedule")
  if g_sched is None:
    return [f"golden '{name}' predates the collective_schedule field -- "
            f"regenerate the goldens: {REGEN_COMMAND}"]
  c_sched = current["collective_schedule"]
  if g_sched == c_sched:
    return []
  where = schedule_diffs(g_sched, c_sched) or ["group arity changed at "
                                               "a fixed topology"]
  for i, (g, c) in enumerate(zip(g_sched, c_sched)):
    if g != c:
      where.append(f"golden[{i}]={g} current[{i}]={c}")
      break
  return [("ordered collective schedule drifted while the inventory "
           f"matched ({'; '.join(where)}) -- an inventory-equal reorder "
           "can still deadlock ranks cross-host; if the change is "
           f"intentional, regenerate: {REGEN_COMMAND}")]


# -- leg (b): cross-world-size agreement --------------------------------------

def sharded_world_size_configs(
    configs: Optional[Dict[str, Dict[str, Any]]] = None
    ) -> Dict[str, Dict[str, Any]]:
  """The golden configs the agreement leg binds on: every sharded
  train config (--shard_optimizer_state; the elastic/multi-host
  family _reshard re-addresses)."""
  configs = GOLDEN_CONFIGS if configs is None else configs
  return {name: dict(cfg) for name, cfg in configs.items()
          if cfg.get("shard_optimizer_state")}


def world_size_verdict(name: str, overrides: Dict[str, Any],
                       tracer: Callable,
                       sizes: Tuple[int, ...] = WORLD_SIZES
                       ) -> Dict[str, Any]:
  """Trace ``overrides`` at every world size; compare the schedules
  modulo group arity against the config's own (golden) size; classify
  (see module docstring). ``tracer(overrides, program)`` is the
  audit's memoized tracer, so the golden size costs nothing extra."""
  own = int(overrides.get("num_devices", N_REPLICAS))
  all_sizes = sorted(set(int(s) for s in sizes) | {own})
  schedules: Dict[int, List[Dict[str, Any]]] = {}
  for s in all_sizes:
    cfg = dict(overrides)
    cfg["num_devices"] = s
    schedules[s] = tracer(cfg, "train_step").collective_schedule()
  ref = schedules[own]
  diffs: List[Dict[str, Any]] = []
  arity_differs = False
  for s in all_sizes:
    if s == own:
      continue
    d = schedule_diffs(ref, schedules[s])
    if d:
      diffs.append({"size": s, "diffs": d})
    elif ([e["group_sizes"] for e in ref] !=
          [e["group_sizes"] for e in schedules[s]]):
      arity_differs = True
  gspmd = overrides.get("partitioner") == "gspmd"
  note = ""
  if diffs and gspmd:
    classification = "documented"
    note = ("GSPMD re-plans the exchange per topology (sharding "
            "divisibility changes with n) -- the documented "
            "reassociation class; tabled, not failed")
  elif diffs:
    classification = "bug"
  elif arity_differs:
    classification = "benign_arity"
  else:
    classification = "agree"
  return {
      "config": name,
      "sizes": all_sizes,
      "golden_size": own,
      "schedule_lengths": {str(s): len(schedules[s]) for s in all_sizes},
      "classification": classification,
      "diffs": diffs,
      "note": note,
  }


def world_size_violations(verdict: Dict[str, Any]) -> List[str]:
  """The failing messages of one verdict: only the ``bug`` class --
  a manual program whose rendezvous order changed with the world size
  is the deadlock class no partitioner choice explains."""
  if verdict["classification"] != "bug":
    return []
  out = []
  for d in verdict["diffs"]:
    out.append(
        f"collective schedule at world size {d['size']} diverges from "
        f"the golden size {verdict['golden_size']} "
        f"({'; '.join(d['diffs'])}) -- ranks lowered at different "
        "world sizes would not rendezvous (the multi-host deadlock "
        "class); the manual partitioner's schedule must be invariant "
        "modulo group arity")
  return out


def audit_world_sizes(configs: Dict[str, Dict[str, Any]],
                      tracer: Callable,
                      sizes: Tuple[int, ...] = WORLD_SIZES
                      ) -> Dict[str, Any]:
  """Run the agreement leg over ``configs``; returns the report block
  the CLI embeds under ``spmd.world_size`` (per-config verdicts +
  the flat failing messages)."""
  verdicts, violations = {}, []
  for name, overrides in configs.items():
    verdict = world_size_verdict(name, overrides, tracer, sizes)
    verdicts[name] = verdict
    for msg in world_size_violations(verdict):
      violations.append({"config": name, "message": msg})
  return {"verdicts": verdicts, "violations": violations}
