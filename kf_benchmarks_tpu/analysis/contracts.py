"""Program-contract extraction: trace a config's train step, never run it.

The single source of the HLO-scraping conventions the test suite pins
against (previously triplicated across tests/test_overlap_reduction.py,
tests/test_grad_accum.py and tests/test_telemetry.py):

* an *all-reduce definition* is an instruction-definition line matching
  :data:`ALL_REDUCE_DEF` (``-start`` covers async pairs);
* a collective is *in the backward loop* when its jax ``op_name``
  metadata places it inside a scanned (``while``) body -- the backward
  of a lax.scan/nn.scan lowers to a while loop, and a collective issued
  by an in-backward hook carries the loop in its op_name;
* *gradient traffic* is the non-scalar all-reduce
  (:data:`GRAD_MIN_ELEMS` guards the packed health/metric vectors);
  ``f32[]`` reductions are the step's metric pmeans.

On top of the shared helpers, :func:`trace_contract` builds a config's
step program exactly as the runtime does (``BenchmarkCNN._build``),
lowers it over the abstract 8-device mesh with ``jax.eval_shape`` +
``jit(...).lower(...)`` -- no train step ever executes, only XLA
compilation runs -- and extracts a :class:`ProgramContract` that
``audit`` checks and ``baseline`` diffs against goldens.
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import OrderedDict
from typing import Any, Dict, List, Optional

# -- shared HLO-scraping helpers (the tests import these) ---------------------

ALL_REDUCE_DEF = re.compile(r"=\s+\S+\s+all-reduce(-start)?\(")

# A non-scalar all-reduce below this element count is a packed
# metric/health vector (telemetry packs ~10 floats onto the loss pmean),
# not gradient traffic; every real gradient bucket is far larger.
GRAD_MIN_ELEMS = 128


def all_reduce_defs(hlo: str) -> List[str]:
  """All-reduce instruction definition lines of a compiled-HLO dump."""
  return [ln for ln in hlo.splitlines() if ALL_REDUCE_DEF.search(ln)]


def in_backward_loop(defs) -> List[str]:
  """Defs whose jax op_name places them inside a scanned (while) body --
  the in-backward position the overlap hooks pin."""
  return [ln for ln in defs if "while" in ln]


_SCALAR_ALL_REDUCE = re.compile(r"=\s+\w+\[\]\s+all-reduce")


def grad_all_reduce_defs(hlo: str):
  """(all defs, gradient defs): gradient traffic is the non-scalar
  all-reduce; ``f32[]`` reductions are the step's metric pmeans.

  Intentionally LOOSER than :meth:`Collective.is_gradient_traffic`:
  no :data:`GRAD_MIN_ELEMS` floor, because the test pins that import
  this helper drive tiny toy models whose real gradient buckets can be
  under the floor, and their programs carry no packed health vector to
  exclude. The auditor's real-config predicate needs the floor; keep
  the two in mind if a pin ever mixes health stats with this helper."""
  defs = all_reduce_defs(hlo)
  grad = [ln for ln in defs if not _SCALAR_ALL_REDUCE.search(ln)]
  return defs, grad


# -- structured contract ------------------------------------------------------

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all")
_COLLECTIVE_DEF = re.compile(
    r"=\s+(?P<type>[^\s].*?)\s+"
    r"(?P<kind>" + "|".join(_COLLECTIVE_KINDS) + r")(?P<start>-start)?\(")
_ARRAY_TYPE = re.compile(
    r"\b(pred|bf16|f16|f32|f64|s8|s16|s32|s64|u8|u16|u32|u64|c64|c128)"
    r"\[([0-9,]*)\]")
_REPLICA_GROUPS = re.compile(r"replica_groups=(\{\{[0-9, ]*(?:\},\{[0-9, ]*)*\}\})")
_CUSTOM_CALL_TARGET = re.compile(r'custom_call_target="([^"]+)"')
_ALIAS_ENTRY = re.compile(r"(?:may|must)-alias")
_HOST_TRANSFER_KINDS = ("infeed", "outfeed", " send(", " recv(",
                        "send-done", "recv-done")

_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
             "u16": 2, "f32": 4, "s32": 4, "u32": 4, "c64": 8, "f64": 8,
             "s64": 8, "u64": 8, "c128": 16}

# A stablehlo collective's result type in LOWERED (pre-optimization)
# text: "... }) : (tensor<4101097xbf16>) -> tensor<4101097xbf16>". The
# wire dtype must be read here: XLA:CPU legalizes 16-bit collectives to
# f32 during compilation, so the COMPILED dump shows the backend's
# wire, not the program's requested one (which is what the TPU runs).
def _stablehlo_result_types(lowered_text: str, op: str):
  pat = re.compile(r'"stablehlo\.%s".*?-> tensor<([0-9a-z_]+)>' % op,
                   re.S)
  out = []
  for spec in pat.findall(lowered_text):
    parts = spec.split("x")
    dtype = parts[-1]
    elems = math.prod(int(d) for d in parts[:-1]) if len(parts) > 1 else 1
    out.append((dtype, elems))
  return out


def requested_all_reduce_wires(lowered_text: str):
  """[(dtype, elems), ...] of every all_reduce in a lowered module."""
  return _stablehlo_result_types(lowered_text, "all_reduce")


def requested_collective_wires(lowered_text: str):
  """{kind: sorted wire dtypes of non-scalar ops} at the LOWERED level
  for the sharded path's collective mix (reduce_scatter / all_gather /
  all_reduce) -- read here for the same reason as
  :func:`requested_all_reduce_wires`: XLA:CPU legalizes 16-bit
  collectives to f32 while compiling, so the compiled dump shows the
  backend's wire, not the program's requested (TPU) one."""
  out = {}
  for op in ("all_reduce", "reduce_scatter", "all_gather"):
    dtypes = sorted({dtype for dtype, elems
                     in _stablehlo_result_types(lowered_text, op)
                     if elems > 1})
    if dtypes:
      out[op.replace("_", "-")] = dtypes
  return out


def _array_bytes(dtype: str, dims: str) -> int:
  elems = math.prod(int(d) for d in dims.split(",") if d) if dims else 1
  return elems * _ITEMSIZE[dtype]


@dataclasses.dataclass
class Collective:
  """One collective instruction of the compiled step program."""
  kind: str            # all-reduce | all-gather | reduce-scatter | ...
  dtype: str           # wire dtype of the (first) array operand
  elems: int           # element count (1 for scalars)
  scalar: bool
  in_loop: bool        # inside a scanned (while) body
  replica_groups: str  # "" when the kind has none (collective-permute)
  # Position in the compiled dump's definition order (the ORDERED
  # schedule the SPMD divergence pass compares; -1 for hand-built
  # Collectives that never went through extract_contract).
  index: int = -1

  def is_gradient_traffic(self) -> bool:
    return (self.kind == "all-reduce" and not self.scalar
            and self.elems >= GRAD_MIN_ELEMS)

  def schedule_entry(self) -> Dict[str, Any]:
    """The golden-worthy row of the ordered collective schedule: every
    field two ranks must agree on for the programs to rendezvous
    (kind, wire dtype, scalar/tensor rank, loop placement), plus the
    replica-group SIZES (arity) -- group member ids are topology
    labels, not schedule structure -- and the position index."""
    inner = self.replica_groups.strip().strip("{}")
    sizes = ([len([t for t in grp.split(",") if t.strip() != ""])
              for grp in inner.split("},{")] if inner else [])
    return {
        "index": self.index, "kind": self.kind, "dtype": self.dtype,
        "rank": "scalar" if self.scalar else "tensor",
        "placement": "in_loop" if self.in_loop else "top_level",
        "group_sizes": sizes,
    }


@dataclasses.dataclass
class ProgramContract:
  """Structured statics of one compiled step program."""
  config: Dict[str, Any]          # the param overrides that produced it
  program: str                    # "train_step" | "train_chunk"
  collectives: List[Collective]
  host_transfers: List[str]       # infeed/outfeed/send/recv kinds found
  custom_call_targets: List[str]  # informational (backend-dependent)
  optimizer_apply_present: bool   # train_step.py's named_scope found
  optimizer_apply_in_loop: bool   # ... inside a while body
  donated_buffers: int            # input_output_alias entry count
  largest_tensor_bytes: int       # biggest single array in the program
  largest_tensor_type: str        # e.g. "f32[4096,1001]"
  temp_bytes: Optional[int]       # memory_analysis().temp_size_in_bytes
  aux: Dict[str, Any] = dataclasses.field(default_factory=dict)

  def gradient_collectives(self) -> List[Collective]:
    return [c for c in self.collectives if c.is_gradient_traffic()]

  def in_loop_collectives(self) -> List[Collective]:
    return [c for c in self.collectives if c.in_loop]

  def collective_schedule(self) -> List[Dict[str, Any]]:
    """The ORDERED collective schedule (ISSUE 20 leg a): one
    :meth:`Collective.schedule_entry` row per collective, in compiled-
    dump definition order. Two programs with identical unordered
    inventories can still deadlock each other cross-rank when their
    schedules differ -- the inventory is a multiset, the schedule is
    the rendezvous order; analysis/spmd.py compares these."""
    return [c.schedule_entry() for c in self.collectives]


def extract_contract(hlo: str, config: Optional[dict] = None,
                     program: str = "train_step",
                     temp_bytes: Optional[int] = None,
                     aux: Optional[dict] = None) -> ProgramContract:
  """Parse a compiled-HLO text dump into a :class:`ProgramContract`.

  Pure text analysis (no jax): tests feed hand-built programs through
  this to seed violations the audit rules must catch.
  """
  collectives = []
  host_transfers = []
  for ln in hlo.splitlines():
    m = _COLLECTIVE_DEF.search(ln)
    if m:
      arr = _ARRAY_TYPE.search(m.group("type"))
      dtype, dims = (arr.group(1), arr.group(2)) if arr else ("f32", "")
      elems = (math.prod(int(d) for d in dims.split(",") if d)
               if dims else 1)
      groups = _REPLICA_GROUPS.search(ln)
      collectives.append(Collective(
          kind=m.group("kind"), dtype=dtype, elems=elems,
          scalar=not dims, in_loop="while" in ln,
          replica_groups=groups.group(1).replace(" ", "") if groups
          else "", index=len(collectives)))
    # Only the instruction text counts (op_name metadata may quote a
    # jax scope containing e.g. 'send' without the op being one).
    head = ln.split("metadata")[0]
    for kind in _HOST_TRANSFER_KINDS:
      if kind in head and "=" in head:
        host_transfers.append(kind.strip().strip("("))
  opt_lines = [ln for ln in hlo.splitlines() if "optimizer_apply" in ln]
  largest_bytes, largest_type = 0, ""
  for dtype, dims in _ARRAY_TYPE.findall(hlo):
    b = _array_bytes(dtype, dims)
    if b > largest_bytes:
      largest_bytes, largest_type = b, f"{dtype}[{dims}]"
  return ProgramContract(
      config=dict(config or {}), program=program,
      collectives=collectives, host_transfers=sorted(set(host_transfers)),
      custom_call_targets=sorted(set(_CUSTOM_CALL_TARGET.findall(hlo))),
      optimizer_apply_present=bool(opt_lines),
      optimizer_apply_in_loop=any("while" in ln for ln in opt_lines),
      donated_buffers=len(_ALIAS_ENTRY.findall(hlo)),
      largest_tensor_bytes=largest_bytes, largest_tensor_type=largest_type,
      temp_bytes=temp_bytes, aux=dict(aux or {}))


# -- config -> contract (trace, never execute) --------------------------------

N_REPLICAS = 8  # the abstract mesh every golden traces on (conftest's)


def lower_step_program(bench, program: str = "train_step"):
  """Lower (never execute) a built runtime's step program over abstract
  ``ShapeDtypeStruct`` inputs -- the one build+lower recipe shared by
  :func:`trace_contract` and the autotuner's warm pass (the warm pass
  compiles the result against the persistent XLA cache). Returns
  ``(state_sds, lowered)``."""
  import jax
  fns = bench._build()
  init_state, train_step, train_chunk = fns[0], fns[1], fns[4]
  in_shapes = bench.model.get_input_shapes("train")
  in_dtypes = bench.model.get_input_data_types("train")
  sample = jax.ShapeDtypeStruct(tuple(in_shapes[0]), in_dtypes[0])
  state_sds = jax.eval_shape(init_state, jax.random.PRNGKey(0), sample)
  n = bench.num_devices
  # Global batch follows the DATA-parallel width (model-axis peers of a
  # 2-D mesh re-compute the same shard; == n on 1-D meshes).
  n_data = int(getattr(bench, "num_data_replicas", n))
  gx = jax.ShapeDtypeStruct(
      (in_shapes[0][0] * n_data,) + tuple(in_shapes[0][1:]), in_dtypes[0])
  gy = jax.ShapeDtypeStruct(
      (in_shapes[1][0] * n_data,) + tuple(in_shapes[1][1:]), in_dtypes[1])
  if program == "train_chunk":
    if train_chunk is None:
      raise ValueError("train_chunk requested but --steps_per_dispatch=1")
    # Synthetic resident chunk: leading staged-steps axis of 1.
    gx = jax.ShapeDtypeStruct((1,) + gx.shape, gx.dtype)
    gy = jax.ShapeDtypeStruct((1,) + gy.shape, gy.dtype)
    return state_sds, train_chunk.lower(state_sds, gx, gy)
  return state_sds, train_step.lower(state_sds, gx, gy)


def trace_contract(overrides: Dict[str, Any],
                   program: str = "train_step") -> ProgramContract:
  """Build + lower + compile the step program for ``overrides``; extract.

  Mirrors the runtime exactly (``BenchmarkCNN._build``), but the state
  is ``jax.eval_shape``-abstract and inputs are ``ShapeDtypeStruct``s:
  nothing executes, only XLA compilation runs. Requires the 8-device
  CPU mesh (tests get it from conftest; the CLI sets XLA_FLAGS).
  """
  import jax
  import jax.numpy as jnp
  from kf_benchmarks_tpu import benchmark
  from kf_benchmarks_tpu import params as params_lib
  from kf_benchmarks_tpu.ops import overlap as overlap_lib

  kw = dict(device="cpu", num_devices=N_REPLICAS, num_batches=2)
  kw.update(overrides)
  p = params_lib.make_params(**kw)
  bench = benchmark.BenchmarkCNN(p)
  state_sds, lowered = lower_step_program(bench, program)
  in_shapes = bench.model.get_input_shapes("train")
  in_dtypes = bench.model.get_input_data_types("train")
  n = bench.num_devices
  n_data = int(getattr(bench, "num_data_replicas", n))
  compiled = lowered.compile()

  aux: Dict[str, Any] = {
      "model": bench.model.get_name(),
      "num_devices": n,
      "num_data_replicas": n_data,
      "per_device_batch": int(in_shapes[0][0]),
      "health_stats": bool(bench.params.health_stats),
      # Gradient wire dtypes the PROGRAM requests (lowered level; the
      # compiled CPU dump legalizes 16-bit collectives to f32).
      "requested_grad_wires": sorted({
          dtype for dtype, elems in requested_all_reduce_wires(
              lowered.as_text())
          if elems >= GRAD_MIN_ELEMS}),
  }
  # --shard_optimizer_state contract inputs (audit.rule_sharded_*): the
  # requested reduce-scatter/all-gather wire dtypes, and the per-device
  # optimizer-state bytes read from the ABSTRACT state -- exactly what
  # each device will hold, one row of every (n, k) shard stack.
  if bool(getattr(bench.params, "shard_optimizer_state", False)):
    aux["sharded_state"] = True
    aux["requested_collective_wires"] = requested_collective_wires(
        lowered.as_text())
  # --shard_params contract inputs (audit.rule_fsdp_residency): the
  # full-tree parameter bytes (the residency denominator), the planned
  # step-level gather-bucket count (what the out-of-loop all-gather
  # inventory must not exceed), and the module-gathered scanned
  # prefixes (whose per-block gathers must sit INSIDE the scan body).
  if bool(getattr(bench.params, "shard_params", False)):
    from kf_benchmarks_tpu.ops import overlap as fsdp_overlap_lib
    aux["fsdp_params"] = True
    prefixes = tuple(
        getattr(bench.model, "fsdp_gathered_prefixes", ()) or ())
    aux["fsdp_scan_prefixes"] = list(prefixes)
    # Template exactly as the step builder derives it (train_step.py):
    # abstract init of the training module.
    train_module = bench.model.make_module(
        nclass=bench.dataset.num_classes, phase_train=True,
        data_format=bench.params.data_format,
        dtype=bench.compute_dtype, param_dtype=bench.param_dtype)
    template = jax.eval_shape(
        lambda: train_module.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(0)},
            jnp.zeros(tuple(in_shapes[0]), in_dtypes[0])))["params"]
    aux["fsdp_param_full_bytes"] = sum(
        int(math.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(template))
    mb = (getattr(bench.params, "reduce_bucket_mb", None)
          or fsdp_overlap_lib.DEFAULT_BUCKET_MB)
    buckets, _ = fsdp_overlap_lib.fsdp_plan_buckets(
        template, int(mb) * 1024 * 1024, exclude_prefixes=prefixes)
    aux["fsdp_step_gathers"] = len(buckets)
    # Exact planned bytes of the largest step-level gather RESULT
    # (bucket leaves re-assemble as n * ceil(size/n) elements each):
    # the per-gather residency bound rule_fsdp_residency admits --
    # models whose tree is dominated by ONE layer (trivial's 1001-way
    # head) legitimately gather more than half the tree in that
    # layer's bucket.
    t_flat = jax.tree_util.tree_leaves(template)
    def _gather_bytes(idxs):
      total = 0
      for i in idxs:
        leaf = t_flat[i]
        size = int(math.prod(leaf.shape)) if leaf.shape else 1
        total += n * (-(-size // n)) * jnp.dtype(leaf.dtype).itemsize
      return total
    aux["fsdp_max_gather_bytes"] = max(
        (_gather_bytes(b) for b in buckets), default=0)
    aux["fsdp_engaged"] = int(bench.params.num_grad_accum or 1) == 1
  # Shape/dtype-based, so the ONE accounting serves both the bench
  # JSON field (concrete arrays) and this abstract state.
  aux["opt_state_bytes_per_device"] = benchmark.opt_state_bytes_per_device(
      state_sds.opt_state)
  # The (B, T, V) bound the fused-head LM contract is checked against:
  # the bytes of the logits tensor the program must NOT materialize.
  if bench.model.get_name() == "transformer_lm":
    from kf_benchmarks_tpu.models import transformer_lm as lm
    itemsize = jnp.dtype(bench.compute_dtype).itemsize
    aux["btv_bytes"] = int(in_shapes[0][0]) * lm.SEQ_LEN * lm.VOCAB * itemsize
  # Expected step-level bucket count when the overlap hooks engage
  # (module-reduced prefixes are excluded -- their reduction is the
  # in-loop per-block collective).
  spec = overlap_lib.build(p)
  if spec is not None and int(p.num_grad_accum or 1) == 1:
    import types
    params_tree = jax.tree.map(
        lambda s: types.SimpleNamespace(
            size=math.prod(s.shape[1:]), dtype=s.dtype),
        state_sds.params)
    module_prefixes = tuple(
        getattr(bench.model, "in_backward_reduced_prefixes", ()) or ())
    buckets, _ = overlap_lib.plan_buckets(
        params_tree, spec.bucket_bytes, exclude_prefixes=module_prefixes)
    aux["overlap_step_buckets"] = len(buckets)
    aux["overlap_module_prefixes"] = list(module_prefixes)

  # Static flop count (the cost-analysis surface the --tfprof_file dump
  # reads): the autotuner's cost model consumes it from the aux; absent
  # on backends without cost analysis. Not part of the golden
  # fingerprint (baseline.contract_fingerprint reads named aux keys).
  try:
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
      cost = cost[0] if cost else {}
    flops = dict(cost or {}).get("flops")
    if flops is not None and math.isfinite(float(flops)):
      aux["flops"] = float(flops)
  except Exception:  # backend-dependent surface
    pass
  temp = None
  try:
    temp = int(compiled.memory_analysis().temp_size_in_bytes)
  except Exception:  # backend without memory analysis
    temp = None
  return extract_contract(compiled.as_text(), config=dict(overrides),
                          program=program, temp_bytes=temp, aux=aux)


# -- the golden lattice -------------------------------------------------------

# Every earned program-level contract, sampled across the flag lattice.
# Keys are the golden names (tests/golden_contracts/<name>.json); values
# are make_params overrides on top of the cpu/8-device/trivial defaults.
GOLDEN_CONFIGS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict([
    # The monolithic default program (the PERF.md envelope).
    ("base", dict(model="trivial", batch_size=4)),
    # PR 2: --num_grad_accum pays ONE packed gradient collective per
    # step, outside the microbatch scan (agg packing makes "one"
    # literal, as in tests/test_grad_accum.py).
    ("accum4_packed", dict(model="trivial", batch_size=4, num_grad_accum=4,
                           agg_small_grads_max_bytes=1 << 30,
                           agg_small_grads_max_group=1000)),
    # PR 3: bucketed in-backward reduction, step-level hooks.
    ("overlap", dict(model="trivial", batch_size=4,
                     overlap_gradient_reduction=True)),
    # PR 3 satellite: the f32-training bf16 wire opt-in.
    ("overlap_bf16_wire", dict(model="trivial", batch_size=4,
                               overlap_gradient_reduction=True,
                               compact_gradient_transfer_f32=True)),
    # PR 4: in-step health stats ride the loss pmean (no new collective).
    ("health", dict(model="trivial", batch_size=4, health_stats=True)),
    # PR 2: the scanned fused-head LM never materializes (B, T, V).
    ("lm_base", dict(model="transformer_lm", batch_size=8)),
    # PR 3: the scanned LM's per-block collective lands INSIDE the
    # backward scan's while body.
    ("lm_overlap", dict(model="transformer_lm", batch_size=8,
                        overlap_gradient_reduction=True)),
    # PR 6: ZeRO sharded optimizer state on the named 2-D mesh
    # (--shard_optimizer_state resolves an 8x1 ('batch', 'model') mesh
    # here): gradients meet in reduce-scatter, params return by
    # all-gather, NO full-gradient all-reduce, per-device opt state
    # ~|state|/n (audit.rule_sharded_collectives / _opt_bytes).
    # (momentum, not the sgd default: sgd's only slot is a schedule
    # count, which would leave the ZeRO memory bound vacuous.)
    ("sharded_base", dict(model="trivial", batch_size=4,
                          optimizer="momentum",
                          shard_optimizer_state=True)),
    # PR 6: composition with --num_grad_accum -- the microbatch scan
    # still pays its reductions once per STEP, now as the scatter.
    ("sharded_accum", dict(model="trivial", batch_size=4,
                           num_grad_accum=4, optimizer="momentum",
                           shard_optimizer_state=True)),
    # PR 6: the scanned fused-head LM under sharded state -- the
    # (B, T, V) bound and the sharded collective mix must hold at once.
    ("lm_sharded", dict(model="transformer_lm", batch_size=8,
                        optimizer="momentum",
                        shard_optimizer_state=True)),
    # PR 8 (round 13): the packed-sequence LM program. Segment-aware
    # masks + the weighted chunked loss must keep the program class:
    # still no (B, T, V) logits buffer, and the token-weighted metric
    # combine PACKS the loss pmeans into one vector, so the packed
    # step carries no more collectives than lm_base
    # (audit.rule_packed_no_overhead).
    ("lm_packed", dict(model="transformer_lm", batch_size=8,
                       packed_sequences=True)),
    # PR 7: the elastic-rescale RESUME shape -- sharded_base after an
    # 8 -> 4 resize (the program benchmark.py rebuilds at the new mesh
    # and resumes into from the resliced checkpoint). Every sharded
    # rule re-checks at n=4: 4-wide scatter groups, full-4-device
    # gathers, no full-gradient all-reduce -- so a resumed run's
    # program shape is golden-pinned, not just the original's.
    ("sharded_rescale", dict(model="trivial", batch_size=4,
                             num_devices=4, optimizer="momentum",
                             shard_optimizer_state=True)),
    # PR 10 (round 15): full FSDP (--shard_params). The CNN shape:
    # params live as (n, k) shard stacks, every builder-layer bucket
    # re-assembles with ONE packed all-gather at the loss top whose
    # backward reduce-scatters the bucket cotangent, the optimizer
    # applies on the shard, and the round-11 trailing full-tree
    # all-gather is GONE (audit.rule_fsdp_residency: out-of-loop
    # gather count == planned bucket count, every gather < half the
    # full tree).
    ("fsdp_base", dict(model="trivial", batch_size=4,
                       optimizer="momentum",
                       shard_optimizer_state=True, shard_params=True)),
    # PR 10: the scanned fused-head LM under full FSDP -- the per-
    # block parameter gather sits INSIDE the nn.scan while body (under
    # remat: the backward re-gathers in the loop too), the scanned
    # stack never materializes whole, and the (B, T, V) bound plus the
    # sharded collective mix must hold at once.
    ("fsdp_lm", dict(model="transformer_lm", batch_size=8,
                     optimizer="momentum",
                     shard_optimizer_state=True, shard_params=True)),
    # PR 9 (round 14): the twin-trace rule's anchor. Run tracing
    # (--trace_events_file, tracing.py) is HOST-ONLY by contract: the
    # trace-on step program must be STRUCTURALLY IDENTICAL to the
    # trace-off one (audit.rule_trace_twin diffs the full fingerprint
    # against the twin without the flag -- the same paired-trace
    # pattern as rule_health_no_extra_collective, but exact identity
    # rather than a collective-count bound). The path is never opened
    # during tracing (the span session lives in the train LOOP, not
    # the step program).
    ("traced", dict(model="trivial", batch_size=4,
                    trace_events_file="trace_events.json")),
    # PR 11 (round 16): the metrics-twin rule's anchor. The metric
    # registry + live /metrics endpoint (--metrics_port, metrics.py)
    # and the run-record store (--run_store_dir) are HOST-ONLY by the
    # same contract as tracing: the metrics-on step program must be
    # STRUCTURALLY IDENTICAL to the metrics-off twin
    # (audit.rule_metrics_twin). No socket is bound during tracing --
    # the endpoint lives in the train LOOP, not the step program.
    ("metrics_on", dict(model="trivial", batch_size=4,
                        metrics_port=9309,
                        run_store_dir="run_store")),
    # ISSUE 17 (round 20): the GSPMD twin lattice. Each entry is an
    # existing sharded golden's config plus --partitioner=gspmd: the
    # SAME per-replica step function lowered under plain jit with
    # NamedSharding-annotated state on the same ('batch', 'model')
    # mesh, letting the XLA SPMD partitioner insert the collectives
    # the manual shard_map programs write by hand (train_step.py
    # _gspmd_wrap). The twin referee (audit.rule_partitioner_twin)
    # traces each one's manual twin (config minus the flag), diffs
    # collective inventory + largest live buffer, and classifies the
    # divergence -- only the "bug" class violates; the full verdict
    # rides the report for PERF.md's inventory-diff table. Losses are
    # bit-identical between the twins (tests/test_partitioner.py).
    ("gspmd_sharded_base", dict(model="trivial", batch_size=4,
                                optimizer="momentum",
                                shard_optimizer_state=True,
                                partitioner="gspmd")),
    ("gspmd_fsdp_base", dict(model="trivial", batch_size=4,
                             optimizer="momentum",
                             shard_optimizer_state=True,
                             shard_params=True,
                             partitioner="gspmd")),
    ("gspmd_lm_sharded", dict(model="transformer_lm", batch_size=8,
                              optimizer="momentum",
                              shard_optimizer_state=True,
                              partitioner="gspmd")),
    # The accum twin: the once-per-step gradient exchange must stay
    # OUT of the microbatch scan on the gspmd side too -- the
    # referee's in-loop-gradient bug leg binds here (and the mutation
    # self-test seeds exactly that regression).
    ("gspmd_accum", dict(model="trivial", batch_size=4,
                         optimizer="momentum",
                         shard_optimizer_state=True,
                         num_grad_accum=2,
                         partitioner="gspmd")),
])


# -- serving-path contracts (round 18) ----------------------------------------

# Serving goldens trace the ENGINE's decode-step program (never a train
# step): overrides are serving/decode.LMSpec fields plus the decode
# bucket. The production (fast 1-row attention) program at the zoo
# transformer_lm's real dims -- the shape the engine AOT-compiles per
# ladder bucket and the bounded-executable rule binds against
# (audit.rule_serving_bounded_decode).
SERVING_GOLDEN_CONFIGS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict([
    ("serving_decode", dict(bucket=4)),
    # Decode-cost variants (ISSUE 16): each leg's program pinned at the
    # same bucket so a variant regression (e.g. dequantize hoisted out
    # of the step, or the paged gather collapsing back to a dense
    # slab) diffs against ITS OWN golden, not serving_decode's.
    ("serving_decode_int8", dict(bucket=4, quantize="int8")),
    ("serving_decode_paged", dict(bucket=4, kv_page_size=128)),
    # The speculative TARGET's verify program (prefill-shaped full
    # forward + chunked argmax; program="serving_verify" routes the
    # tracer to verify_lowering_args).
    ("serving_verify", dict(bucket=4, speculative_k=4,
                            draft_n_layers=2,
                            program="serving_verify")),
    # ISSUE 17 (round 20): the tensor-parallel decode twin -- the same
    # bucket-4 decode step lowered with Megatron-style NamedShardings
    # over a 2-device ('model',) mesh (decode.tp_shardings: KV cache
    # sharded on the head axis, attention/MLP kernels column/row-
    # parallel) and GSPMD inserting the block reductions. The twin
    # referee (audit.rule_partitioner_twin) diffs it against
    # serving_decode's program and classifies; the compiled HLO is the
    # per-partition module, so buffer bounds here are per-shard.
    ("serving_decode_tp", dict(bucket=4, model_shards=2)),
])


def trace_serving_contract(overrides: Dict[str, Any],
                           program: str = "serving_decode"
                           ) -> ProgramContract:
  """Lower + compile (never execute) a serving program for an LMSpec
  override dict; extract its contract.

  Mirrors the engine's AOT path exactly (serving/engine._decode_exe /
  _verify_exe: jit + donation + lower + compile over abstract
  ShapeDtypeStructs), so the golden pins the program the engine will
  actually cache per bucket. A ``program`` key in ``overrides`` routes
  the trace (``serving_decode`` -> the decode step,
  ``serving_verify`` -> the speculative verify forward) -- that is how
  the golden table encodes per-program entries."""
  import dataclasses as _dc

  import jax
  import jax.numpy as jnp
  from kf_benchmarks_tpu.serving import decode as decode_lib
  from kf_benchmarks_tpu.serving import engine as engine_lib

  kw = dict(overrides)
  program = kw.pop("program", program)
  bucket = int(kw.pop("bucket", 4))
  field_names = {f.name for f in _dc.fields(decode_lib.LMSpec)}
  unknown = sorted(set(kw) - field_names)
  if unknown:
    raise ValueError(f"unknown LMSpec override(s) {unknown}; have "
                     f"{sorted(field_names)}")
  spec = decode_lib.LMSpec(**kw)
  # The engine's OWN lowering recipes (decode.decode_lowering_args /
  # verify_lowering_args are the single source), so this golden pins
  # the program the engine actually caches per bucket.
  if program == "serving_verify":
    fn, args, donate = decode_lib.verify_lowering_args(spec, bucket)
  else:
    fn, args, donate = decode_lib.decode_lowering_args(spec, bucket)
  compiled = decode_lib.aot_jit(spec, fn, program, bucket,
                                donate).lower(*args).compile()
  itemsize = jnp.dtype(spec.dtype).itemsize
  aux: Dict[str, Any] = {
      "bucket_ladder": list(engine_lib.DEFAULT_BUCKET_LADDER),
      "decode_batch": bucket,
      # One DENSE ring buffer's bytes (k or v; the largest LEGITIMATE
      # array in the dense decode program) -- the residency bound the
      # bounded-executable rule admits. Anything bigger is a leak
      # (e.g. a (B, T, V) logits buffer: vocab_logits_bytes below).
      # For paged programs this is the ceiling the pool must stay
      # strictly UNDER (rule serving-paged-kv).
      "kv_ring_bytes": (spec.n_layers * bucket * spec.max_len *
                        spec.n_heads * spec.head_dim * itemsize),
      "vocab_logits_bytes": bucket * spec.max_len * spec.vocab * itemsize,
  }
  if spec.kv_page_size:
    aux["kv_page_size"] = spec.kv_page_size
    aux["kv_pool_bytes"] = (
        spec.n_layers * decode_lib.kv_pool_pages(spec, bucket) *
        spec.kv_page_size * spec.n_heads * spec.head_dim * itemsize)
  if program == "serving_verify":
    # The verify program's own residency bound: its chunked argmax
    # head must keep every live logits buffer under the dense
    # (B, T, V) tensor (rule serving-verify-bounded).
    aux["verify_chunk"] = decode_lib.verify_chunk(spec)
    aux["verify_logits_bytes"] = (
        bucket * decode_lib.verify_chunk(spec) * spec.vocab * itemsize)
  temp = None
  try:
    temp = int(compiled.memory_analysis().temp_size_in_bytes)
  except Exception:  # backend without memory analysis
    temp = None
  return extract_contract(compiled.as_text(), config=dict(overrides),
                          program=program, temp_bytes=temp, aux=aux)
