"""Golden program contracts: serialize, load, diff.

``tests/golden_contracts/<name>.json`` pins the STRUCTURAL contract of
each golden config (collective inventory by kind/dtype/placement,
donation, optimizer-apply scope, host transfers) so a regression --
a duplicated pmean, a dtype drift, a collective sliding out of the
backward loop -- fails with a field-level diff instead of a silent
perf cliff on the serialized TPU chip.

Volatile statics (buffer sizes, custom-call targets, temp totals) stay
OUT of the goldens: they move with the XLA version, and the memory
contracts are enforced as rules (audit.rule_no_btv_buffer) against
bounds derived from the config, not pinned bytes.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import Counter
from typing import Any, Dict, List, Tuple

from kf_benchmarks_tpu.analysis.contracts import ProgramContract

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "golden_contracts")


def contract_fingerprint(contract: ProgramContract) -> Dict[str, Any]:
  """The stable, golden-worthy subset of a contract."""
  inventory = Counter(
      (c.kind, c.dtype, "scalar" if c.scalar else "tensor",
       "in_loop" if c.in_loop else "top_level")
      for c in contract.collectives)
  return {
      "config": dict(contract.config),
      "program": contract.program,
      "collectives": _sorted_collectives(
          {"kind": k, "dtype": d, "rank": r, "placement": p, "count": n}
          for (k, d, r, p), n in inventory.items()),
      # The ORDERED schedule (ISSUE 20): definition-order rows with
      # group ARITY only (member ids are topology labels). Two ranks
      # whose programs agree on the inventory above but not on this
      # sequence can still deadlock each other -- analysis/spmd.py
      # fails schedule drift with the exact regen command.
      "collective_schedule": contract.collective_schedule(),
      "gradient_collectives": len(contract.gradient_collectives()),
      "in_loop_collectives": len(contract.in_loop_collectives()),
      "host_transfers": list(contract.host_transfers),
      "optimizer_apply_present": contract.optimizer_apply_present,
      "optimizer_apply_in_loop": contract.optimizer_apply_in_loop,
      "state_donated": contract.donated_buffers > 0,
      # Lowered-level gradient wire dtypes (the TPU wire; see
      # contracts.requested_all_reduce_wires).
      "requested_grad_wires": contract.aux.get("requested_grad_wires"),
      # Sharded-path collective wires (reduce-scatter/all-gather mix of
      # --shard_optimizer_state programs; None elsewhere).
      "requested_collective_wires": contract.aux.get(
          "requested_collective_wires"),
  }


def _sorted_collectives(entries):
  return sorted(entries, key=lambda e: json.dumps(e, sort_keys=True))


# Param fields that do NOT shape the compiled step program: artifact
# sinks, cadences, and host-side-only observability/launcher knobs.
# Excluded from the program-shape fingerprint so the compile ledger
# (tracing.py) -- and the persistent compile cache it is groundwork for
# (ROADMAP item 5) -- is not fragmented by paths and cadences that
# change every run. Fields that DO reach the traced program (model,
# batch, mesh, reducers, dtypes, accumulation, ...) all stay in.
PROGRAM_SHAPE_EXCLUDE = frozenset({
    "train_dir", "data_dir", "eval_dir", "benchmark_log_dir",
    "benchmark_test_id", "trace_file", "trace_events_file",
    "tfprof_file", "graph_file", "partitioned_graph_file_prefix",
    "aot_save_path", "aot_load_path", "backbone_model_path",
    "use_chrome_trace_format", "display_every", "save_model_secs",
    "save_model_steps", "save_summaries_steps", "summary_verbosity",
    "max_ckpts_to_keep", "eval_interval_secs",
    "flight_recorder_window", "health_grad_norm_sigma",
    "stall_watchdog_factor", "fault_schedule",
    "elastic_check_every_n_steps", "sync_on_finish",
    "metrics_port", "run_store_dir",
    # The tuned-table PATH (--autotuned_config) is plumbing, not a
    # program shape: the knobs a table APPLIES are ordinary
    # program-shaping params (TUNED_KNOBS below) and land in the
    # fingerprint through their own fields, so a tuned run and a
    # default run can never share a fingerprint -- but WHICH file the
    # values came from must not fragment the key corpus.
    "autotuned_config",
})

# The program-shaping knobs the autotuner (analysis/autotune.py)
# searches. Deliberately NOT in PROGRAM_SHAPE_EXCLUDE: each one changes
# the compiled program or its dispatch schedule, so two runs that
# differ in a tuned knob must key differently in the run store /
# compile ledger (tests/test_autotune.py pins each knob's effect on
# config_fingerprint_key). The autotuner strips exactly this set (plus
# the run-length counters below) to derive the table key a tuned and a
# default run of the same base config share.
TUNED_KNOBS = (
    "steps_per_dispatch",
    "num_grad_accum",
    "reduce_bucket_mb",
    "input_prefetch_depth",
    "attn_block",
    # Round 20: who inserts the sharded step's collectives -- None/
    # "manual" (hand-written shard_map programs) or "gspmd" (plain jit
    # + NamedShardings, XLA SPMD chooses). The one string-valued knob:
    # the table validator admits {"manual","gspmd"} for it only.
    "partitioner",
)

# Run-length counters: in the full fingerprint (the LR schedule can
# embed the total step count as a program constant), but OUT of the
# tuned-table base key -- a table tuned at one sweep length must apply
# to production runs of any length.
_RUN_LENGTH_FIELDS = ("num_batches", "num_warmup_batches", "num_epochs")


def fingerprint_key(payload: Dict[str, Any]) -> str:
  """Short stable key of a canonical-JSON payload (sha256 hex, 16
  chars) -- the identity scheme the compile ledger shares with the
  golden fingerprints."""
  canon = json.dumps(payload, sort_keys=True, default=str)
  return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:16]


def _canonical_value(v):
  """Numeric canonicalization for fingerprinting: an integral float
  keys as its int. The CLI parser materializes float flags as 0.0
  where ``make_params`` keeps a registry-literal 0 -- Python-equal,
  canonical-JSON-different -- and both shape the SAME program, so a
  CLI run and a library run of one config must share a fingerprint
  (found when the tuned-table lookup missed from the CLI; the same
  split silently fragmented the compile ledger). Bools pass through
  (they are typed consistently on both paths)."""
  if isinstance(v, float) and not isinstance(v, bool) and \
      v.is_integer():
    return int(v)
  return v


def config_fingerprint_key(config: Dict[str, Any],
                           program: str = "train_step") -> str:
  """The program-shape fingerprint key a compile episode is ledgered
  under (tracing.py note_compile): the param fields that shape the
  compiled program, plus the program name and the jax version (an XLA
  upgrade recompiles everything, so a persistent cache must key on
  it). Call it with the full ``params._asdict()`` (the ledger
  convention: two runs key equal iff every program-shaping field --
  explicit or default -- agrees); None values and the excluded
  host-side fields drop out first, and integral floats key as ints
  (see :func:`_canonical_value`)."""
  shape = {k: _canonical_value(v) for k, v in config.items()
           if v is not None and k not in PROGRAM_SHAPE_EXCLUDE}
  try:
    import jax
    jax_version = jax.__version__
  except Exception:  # pure-stdlib caller (lint harness)
    jax_version = ""
  return fingerprint_key({"config": shape, "program": program,
                          "jax": jax_version})


def base_fingerprint_key(config: Dict[str, Any],
                         program: str = "train_step") -> str:
  """The tuned-table key: :func:`config_fingerprint_key` of ``config``
  with the tuned knobs (TUNED_KNOBS) and the run-length counters
  stripped first -- the identity a default run, a tuned run, and the
  table entry that tuned it all share. Call it with the full
  ``params._asdict()`` at the MAKE_PARAMS level (before BenchmarkCNN's
  auto-resolutions -- e.g. the --health_stats auto bool -- mutate the
  dict): the table is consulted at startup, so its keys live on the
  pre-resolution config, unlike the compile ledger's resolved keys."""
  stripped = {k: v for k, v in config.items()
              if k not in TUNED_KNOBS and k not in _RUN_LENGTH_FIELDS}
  return config_fingerprint_key(stripped, program)


def diff_fingerprints(golden: Dict[str, Any], current: Dict[str, Any]
                      ) -> List[Tuple[str, Any, Any]]:
  """Field-level diff: [(field, golden_value, current_value), ...].

  Collective inventories diff per-entry so the report names the exact
  (kind, dtype, placement) row that changed count; the ordered
  collective_schedule diffs at the first divergent position (plus a
  length row) instead of dumping both full sequences."""
  diffs = []
  keys = sorted(set(golden) | set(current))
  for key in keys:
    g, c = golden.get(key), current.get(key)
    if key == "collective_schedule":
      g_rows, c_rows = list(g or []), list(c or [])
      if g_rows == c_rows:
        continue
      if len(g_rows) != len(c_rows):
        diffs.append(("collective_schedule.length",
                      len(g_rows), len(c_rows)))
      for i, (gr, cr) in enumerate(zip(g_rows, c_rows)):
        if gr != cr:
          diffs.append((f"collective_schedule[{i}]", gr, cr))
          break
    elif key == "collectives":
      g_rows = {json.dumps({k: v for k, v in e.items() if k != "count"},
                           sort_keys=True): e.get("count")
                for e in (g or [])}
      c_rows = {json.dumps({k: v for k, v in e.items() if k != "count"},
                           sort_keys=True): e.get("count")
                for e in (c or [])}
      for row in sorted(set(g_rows) | set(c_rows)):
        if g_rows.get(row) != c_rows.get(row):
          diffs.append((f"collectives[{row}].count",
                        g_rows.get(row), c_rows.get(row)))
    elif g != c:
      diffs.append((key, g, c))
  return diffs


def golden_path(name: str) -> str:
  return os.path.join(GOLDEN_DIR, f"{name}.json")


def load_golden(name: str) -> Dict[str, Any]:
  with open(golden_path(name), encoding="utf-8") as f:
    return json.load(f)


def write_golden(name: str, contract: ProgramContract) -> str:
  os.makedirs(GOLDEN_DIR, exist_ok=True)
  path = golden_path(name)
  with open(path, "w", encoding="utf-8") as f:
    json.dump(contract_fingerprint(contract), f, indent=2, sort_keys=True)
    f.write("\n")
  return path


def check_against_golden(name: str, contract: ProgramContract
                         ) -> List[Tuple[str, Any, Any]]:
  """Diff a traced contract against its checked-in golden; a missing
  golden is itself a (whole-file) diff."""
  path = golden_path(name)
  if not os.path.exists(path):
    return [("<golden file>", "missing", path)]
  return diff_fingerprints(load_golden(name), contract_fingerprint(contract))
