"""Contract-driven autotuner: search the program-shaping knobs with the
static analyzer as the oracle, confirm with cheap measured probes.

The reference ships one hand-tuned flag set per model (SURVEY 2's
per-model defaults), and this repo has already paid for constants that
encode one host's envelope (the round-6 1.5x-throughput-bar incident,
PERF.md). This module replaces both with a measured search per
(model, batch, mesh):

1. **Enumerate** a deterministic, seeded candidate grid over the tuned
   knobs (``analysis/baseline.TUNED_KNOBS``: --steps_per_dispatch,
   --num_grad_accum, --reduce_bucket_mb, --input_prefetch_depth,
   --attn_block), filtered through the ordinary cross-flag validation
   so the grid can never propose a combination the CLI would reject.
2. **Prune statically** -- every surviving candidate is traced (never
   executed) through ``contracts.trace_contract`` on the abstract mesh,
   and rejected when its contract violates the memory/collective
   bounds (largest live buffer vs the HBM budget, collective-count and
   step-bucket caps) before any probe runs. A pruned candidate is
   never measured (tests assert 0 executions).
3. **Rank** survivors with a deterministic cost model over the
   contract's flop/collective/buffer inventory plus the dispatch
   amortization term K divides (the ~70 ms tunnel RTT, PERF.md).
4. **Probe** the top-k (plus the incumbent default, always) with short
   differential paired windows -- the dispatch_amortization_probe
   methodology: warm one dispatch, ``utils.sync.drain`` at every
   boundary (never ``jax.block_until_ready``), time an n-dispatch and
   a 2n-dispatch window and difference them so constant overheads
   cancel. Probes run in-process and strictly sequentially, so TPU
   work stays serialized by construction (CLAUDE.md).

The winner is the measured argmax over a set that always contains the
default config, so the emitted table can never regress a base config
against its own measured bar -- the no-regression bar is the run's own
default measurement, never a constant.

Output: a versioned tuned-config table (``tuned_configs.json``), keyed
on ``analysis/baseline.base_fingerprint_key`` (the config fingerprint
sans the tuned knobs and run-length counters), which
``--autotuned_config=PATH`` applies at startup with a logged
provenance line and ``experiments/zoo_sweep.py --autotune`` produces
for the whole zoo.

On top of the same table, **ledger-informed warming**: :func:`warm`
cross-references the persisted compile ledger (tracing.py) with the
tuned table and precompiles every (config, program) shape a job will
need into the persistent XLA compilation cache -- the 30-minute
first-compile-over-the-tunnel hazard (CLAUDE.md) is paid in a
controlled warm pass, not mid-run. The warm pass seeds the train_dir
compile ledger under the exact fingerprint keys the runtime computes,
so a follow-up run's ledger reads ``cache_hit`` on every warmed shape.

Not in the v1 knob space: the transformer remat/layer policy stays on
its env switches (KF_TRANSFORMER_LM_LAYERS) -- env knobs are invisible
to the params fingerprint, so tuning them here would fragment the
table identity; promote them to flags first.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from kf_benchmarks_tpu.analysis import baseline
from kf_benchmarks_tpu.analysis import contracts
from kf_benchmarks_tpu.analysis.baseline import TUNED_KNOBS

TABLE_SCHEMA_VERSION = 1
TABLE_FILENAME = "tuned_configs.json"

# Knobs that do not shape the compiled train_step program (host-side
# feed depth; the dispatch chunking wraps the SAME step in a scan):
# dropped from the static-trace key so candidates differing only in
# them share one memoized compile, and ranked purely by the cost
# model's dispatch term / confirmed by the measured probe.
NON_PROGRAM_KNOBS = ("steps_per_dispatch", "input_prefetch_depth")

# Static-prune defaults. The HBM budget is the v5e single-chip 16 GiB
# minus a 1 GiB runtime reserve -- a BOUND, not a tuning constant: a
# candidate whose traced contract already exceeds it would OOM before
# producing a throughput number at all (override per backend).
DEFAULT_HBM_BUDGET_BYTES = 15 * 2**30
DEFAULT_MAX_COLLECTIVES = 256
DEFAULT_MAX_STEP_BUCKETS = 64

# Cost-model constants. Deterministic and documented; the model only
# RANKS candidates (the measured probe confirms), so what matters is
# monotonicity -- more collective bytes, more collective dispatches,
# bigger live buffers, fewer amortized host dispatches all cost more.
COST_PEAK_FLOPS = 197e12          # v5e bf16 peak (PERF.md roofline)
COST_ICI_BYTES_PER_S = 4.5e10     # interconnect order of magnitude
COST_HBM_BYTES_PER_S = 8.0e11    # HBM stream order of magnitude
COST_COLLECTIVE_LATENCY_S = 1e-5  # per-collective issue latency
COST_DISPATCH_OVERHEAD_S = 0.07   # measured tunnel RTT per dispatch


class AutotuneError(ValueError):
  """A tuned-config table problem (missing/invalid file, bad entry)."""


# -- candidate grid -----------------------------------------------------------

def default_axes(base_params) -> "collections.OrderedDict[str, tuple]":
  """The per-knob candidate values for a base config. ``None`` means
  the knob's own default; axes only appear when the base config can
  legally consume them (the cross-flag validation would reject the
  rest anyway -- this just keeps the grid small)."""
  axes: "collections.OrderedDict[str, tuple]" = collections.OrderedDict()
  axes["steps_per_dispatch"] = (1, 2, 4, 8)
  axes["num_grad_accum"] = (1, 2, 4)
  if bool(getattr(base_params, "overlap_gradient_reduction", False)) or \
      bool(getattr(base_params, "shard_params", False)):
    axes["reduce_bucket_mb"] = (None, 1, 4, 16)
  if getattr(base_params, "model", None) == "transformer_lm":
    axes["attn_block"] = (None, 256, 512, 1024)
  if getattr(base_params, "data_dir", None) or \
      bool(getattr(base_params, "packed_sequences", False)):
    axes["input_prefetch_depth"] = (None, 2, 4)
  # The gspmd twin is only legal where the manual program shards
  # something (validation.py rejects it elsewhere) -- same families
  # the twin-referee audits.
  if bool(getattr(base_params, "shard_optimizer_state", False)) or \
      bool(getattr(base_params, "shard_params", False)):
    axes["partitioner"] = (None, "gspmd")
  return axes


def _canon(knobs: Dict[str, Any]) -> str:
  return json.dumps(knobs, sort_keys=True)


def merged_overrides(base: Dict[str, Any],
                     knobs: Dict[str, Any]) -> Dict[str, Any]:
  """Base overrides + candidate knob values; a ``None`` knob value
  means 'the flag default' and removes any base override of it."""
  out = dict(base)
  for k, v in knobs.items():
    if v is None:
      out.pop(k, None)
    else:
      out[k] = v
  return out


def enumerate_candidates(axes: Dict[str, tuple],
                         defaults: Dict[str, Any],
                         seed: int = 0,
                         max_candidates: int = 24
                         ) -> List[Dict[str, Any]]:
  """The deterministic candidate list: full cross product of ``axes``,
  seeded-subsampled to ``max_candidates``, with the incumbent default
  candidate always present and always first."""
  default_cand = collections.OrderedDict(
      (k, defaults.get(k)) for k in axes)
  seen = {_canon(default_cand)}
  grid: List[Dict[str, Any]] = []
  for combo in itertools.product(*(axes[k] for k in axes)):
    cand = collections.OrderedDict(zip(axes, combo))
    c = _canon(cand)
    if c in seen:
      continue
    seen.add(c)
    grid.append(cand)
  if len(grid) + 1 > max_candidates:
    rng = random.Random(seed)
    keep = sorted(rng.sample(range(len(grid)),
                             max(0, max_candidates - 1)))
    grid = [grid[i] for i in keep]
  return [default_cand] + grid


# -- static oracle: prune + rank ----------------------------------------------

def prune_reasons(contract, *,
                  hbm_budget_bytes: int = DEFAULT_HBM_BUDGET_BYTES,
                  max_collectives: int = DEFAULT_MAX_COLLECTIVES,
                  max_step_buckets: int = DEFAULT_MAX_STEP_BUCKETS
                  ) -> List[str]:
  """The memory/collective bounds a candidate's contract must satisfy
  BEFORE it may execute; reasons (empty = survives)."""
  out = []
  live = max(int(contract.temp_bytes or 0),
             int(contract.largest_tensor_bytes or 0))
  if hbm_budget_bytes and live > hbm_budget_bytes:
    out.append(f"largest live buffer {live} B exceeds the HBM budget "
               f"{hbm_budget_bytes} B")
  n = len(contract.collectives)
  if max_collectives and n > max_collectives:
    out.append(f"{n} collectives exceed the per-step cap "
               f"{max_collectives}")
  for aux_key, what in (("overlap_step_buckets", "overlap bucket"),
                        ("fsdp_step_gathers", "FSDP gather bucket")):
    planned = contract.aux.get(aux_key)
    if planned is not None and max_step_buckets and \
        int(planned) > max_step_buckets:
      out.append(f"{planned} planned {what}s exceed the cap "
                 f"{max_step_buckets} (per-bucket dispatch latency "
                 "would dominate the overlap win)")
  return out


def _collective_bytes(c) -> int:
  return int(c.elems) * contracts._ITEMSIZE.get(c.dtype, 4)


def candidate_cost(contract, overrides: Dict[str, Any]) -> float:
  """Deterministic per-step cost estimate from the contract inventory.

  Monotone (tests pin it) in: collective bytes, collective count, live
  buffer bytes; decreasing in the dispatch amortization K. Ranks only
  -- the measured probe is the arbiter."""
  k = int(overrides.get("steps_per_dispatch") or 1)
  flops = float(contract.aux.get("flops") or 0.0)
  coll_bytes = sum(_collective_bytes(c) for c in contract.collectives)
  n_coll = len(contract.collectives)
  live = max(int(contract.temp_bytes or 0),
             int(contract.largest_tensor_bytes or 0))
  return (flops / COST_PEAK_FLOPS
          + coll_bytes / COST_ICI_BYTES_PER_S
          + n_coll * COST_COLLECTIVE_LATENCY_S
          + live / COST_HBM_BYTES_PER_S
          + COST_DISPATCH_OVERHEAD_S / max(k, 1))


def static_overrides(merged: Dict[str, Any]) -> Dict[str, Any]:
  """The candidate's program-shaping projection (NON_PROGRAM_KNOBS
  dropped): what the static oracle traces, and the memo key that lets
  candidates differing only in host-side knobs share one compile."""
  return {k: v for k, v in merged.items() if k not in NON_PROGRAM_KNOBS}


# -- measured probe -----------------------------------------------------------

def measure_candidate(overrides: Dict[str, Any],
                      probe_dispatches: int = 4) -> float:
  """Measured throughput (examples/sec) of one candidate via short
  differential paired windows (the dispatch_amortization_probe
  methodology): warm one dispatch, then time an n-window and a
  2n-window with ``utils.sync.drain`` at each boundary and difference
  them, so compile residue and constant per-window overheads cancel.
  Runs in-process (TPU work stays serialized) and never calls
  ``jax.block_until_ready`` (it lies on the tunneled backend)."""
  import jax
  import jax.numpy as jnp
  from kf_benchmarks_tpu import benchmark
  from kf_benchmarks_tpu import params as params_lib
  from kf_benchmarks_tpu.utils import sync

  merged = dict(overrides)
  k = int(merged.get("steps_per_dispatch") or 1)
  # Probe-only run-length fields (outside the base key, baseline.py):
  # long enough that the runtime never clamps K down.
  merged.setdefault("num_batches", max(100, 3 * k * probe_dispatches))
  merged.setdefault("num_warmup_batches", 0)
  p = params_lib.make_params(**merged)
  bench = benchmark.BenchmarkCNN(p)
  init_state, train_step, _, broadcast_init, train_chunk = bench._build()
  rng = jax.random.PRNGKey(0)
  next_batch, stop = bench._input_iterator(rng, "train", chunk=k)
  try:
    batch = next_batch()
    in_shapes = bench.model.get_input_shapes("train")
    in_dtypes = bench.model.get_input_data_types("train")
    sample = jnp.zeros(tuple(in_shapes[0]), in_dtypes[0])
    state = init_state(rng, sample)
    state = state.replace(params=broadcast_init(state.params))
    fn = train_chunk if k > 1 else train_step
    state, metrics = fn(state, *batch)  # compile + warm
    sync.drain(metrics)

    def window(n: int) -> float:
      nonlocal state
      t0 = time.monotonic()
      m = metrics
      for _ in range(n):
        state, m = fn(state, *batch)
      sync.drain(m)
      return time.monotonic() - t0

    n = max(1, int(probe_dispatches))
    t_short = window(n)
    t_long = window(2 * n)
    wall = max(t_long - t_short, 1e-9)
    return n * k * bench.batch_size / wall
  finally:
    if stop is not None:
      stop()


# -- the search ---------------------------------------------------------------

def autotune_config(base: Dict[str, Any], *,
                    seed: int = 0,
                    axes: Optional[Dict[str, tuple]] = None,
                    hbm_budget_bytes: int = DEFAULT_HBM_BUDGET_BYTES,
                    max_collectives: int = DEFAULT_MAX_COLLECTIVES,
                    max_step_buckets: int = DEFAULT_MAX_STEP_BUCKETS,
                    top_k: int = 3,
                    max_candidates: int = 24,
                    probe_dispatches: int = 4,
                    tracer: Optional[Callable] = None,
                    measure_fn: Optional[Callable] = None,
                    dry_run: bool = False,
                    log: Callable[[str], None] = print
                    ) -> Tuple[str, Dict[str, Any]]:
  """Run the full prune -> rank -> probe pipeline for one base config;
  returns ``(table_key, entry)``.

  ``tracer(overrides, program) -> ProgramContract`` and
  ``measure_fn(merged_overrides) -> examples/sec`` are injectable so
  the unit tests drive seeded contracts and count probe executions;
  the defaults are the real oracle (``audit.make_memo_tracer``) and
  :func:`measure_candidate`. ``dry_run`` stops after the static stages
  (CPU-only: candidates compile but never execute) and records the
  cost-model favourite with no measured fields."""
  from kf_benchmarks_tpu import params as params_lib
  from kf_benchmarks_tpu import validation
  from kf_benchmarks_tpu.analysis import audit

  base = dict(base)
  base.setdefault("device", "cpu")
  base.setdefault("num_devices", contracts.N_REPLICAS)
  base_params = params_lib.make_params(**base)
  base_dict = base_params._asdict()
  key = baseline.base_fingerprint_key(base_dict, "train_step")
  axes = collections.OrderedDict(axes if axes is not None
                                 else default_axes(base_params))
  defaults = {k: base_dict.get(k) for k in axes}
  candidates = enumerate_candidates(axes, defaults, seed=seed,
                                    max_candidates=max_candidates)
  tracer = tracer or audit.make_memo_tracer()
  measure_fn = measure_fn or measure_candidate

  n_invalid = n_pruned = 0
  survivors: List[Tuple[float, int, Dict[str, Any]]] = []
  default_cand = candidates[0]
  default_pruned = False
  for i, cand in enumerate(candidates):
    m = merged_overrides(base, cand)
    try:
      p = params_lib.make_params(**m)
      validation.validate_cross_flags(p)
      contract = tracer(static_overrides(m), "train_step")
    except (validation.ParamError, ValueError) as e:
      n_invalid += 1
      log(f"autotune[{base_params.model}]: candidate {_canon(cand)} "
          f"invalid: {e}")
      continue
    reasons = prune_reasons(contract,
                            hbm_budget_bytes=hbm_budget_bytes,
                            max_collectives=max_collectives,
                            max_step_buckets=max_step_buckets)
    if reasons:
      n_pruned += 1
      if i == 0:
        default_pruned = True
      log(f"autotune[{base_params.model}]: candidate {_canon(cand)} "
          f"pruned statically: {'; '.join(reasons)}")
      continue
    survivors.append((candidate_cost(contract, m), i, cand))

  survivors.sort(key=lambda t: (t[0], _canon(t[2])))
  entry: Dict[str, Any] = {
      "model": base_params.model,
      "program": "train_step",
      "base_config": {k: v for k, v in base.items()
                      if k not in TUNED_KNOBS},
      "default": dict(defaults),
      "candidates": len(candidates),
      "invalid": n_invalid,
      "pruned": n_pruned,
      "seed": seed,
      "dry_run": bool(dry_run),
      "jax_version": _jax_version(),
  }
  if default_pruned:
    # The incumbent itself violates the static bounds: nothing may
    # execute (the 0-executions contract covers the default too), so
    # the entry records the finding and keeps the flag values.
    log(f"autotune[{base_params.model}]: base config violates the "
        "static bounds; no probes run, table keeps the defaults")
    entry.update(tuned=dict(defaults), probed=0,
                 default_images_per_sec=None, tuned_images_per_sec=None,
                 note="base config pruned by the static oracle")
    return key, entry

  if dry_run:
    best = survivors[0][2] if survivors else default_cand
    entry.update(tuned=dict(best), probed=0,
                 default_images_per_sec=None,
                 tuned_images_per_sec=None)
    return key, entry

  # Probe set: the incumbent default ALWAYS, then the cost-ranked
  # top-k survivors. Every probed candidate passed the static oracle.
  probe: List[Dict[str, Any]] = [default_cand]
  seen = {_canon(default_cand)}
  for _, _, cand in survivors:
    if len(probe) >= top_k + 1:
      break
    c = _canon(cand)
    if c not in seen:
      seen.add(c)
      probe.append(cand)
  measured: List[Tuple[Dict[str, Any], float]] = []
  for cand in probe:
    ips = float(measure_fn(merged_overrides(base, cand)))
    measured.append((cand, ips))
    log(f"autotune[{base_params.model}]: probe {_canon(cand)} -> "
        f"{ips:.1f} examples/s")
  # Strict > with the default first: ties keep the incumbent, so the
  # winner's measured throughput is >= the default's by construction
  # (the no-regression bar is the run's own default measurement).
  best_cand, best_ips = measured[0]
  for cand, ips in measured[1:]:
    if ips > best_ips:
      best_cand, best_ips = cand, ips
  entry.update(tuned=dict(best_cand), probed=len(measured),
               default_images_per_sec=round(measured[0][1], 2),
               tuned_images_per_sec=round(best_ips, 2))
  return key, entry


def _jax_version() -> str:
  try:
    import jax
    return jax.__version__
  except Exception:  # pure-stdlib caller (table validation harness)
    return ""


def new_table(seed: int = 0) -> Dict[str, Any]:
  return {"schema_version": TABLE_SCHEMA_VERSION, "seed": seed,
          "jax_version": _jax_version(), "entries": {}}


def autotune_configs(bases: List[Dict[str, Any]], *,
                     out: Optional[str] = None,
                     seed: int = 0,
                     log: Callable[[str], None] = print,
                     **kwargs) -> Dict[str, Any]:
  """Search each base config; return (and optionally write) the table.
  Strictly sequential -- on TPU that IS the serialization rule."""
  table = new_table(seed)
  for base in bases:
    key, entry = autotune_config(dict(base), seed=seed, log=log,
                                 **kwargs)
    table["entries"][key] = entry
    log(f"autotune[{entry['model']}]: entry {key[:16]} tuned="
        f"{_canon(entry['tuned'])} default={entry['default_images_per_sec']} "
        f"tuned_ips={entry['tuned_images_per_sec']}")
  if out:
    write_table(table, out)
    log(f"tuned-config table written: {out} "
        f"({len(table['entries'])} entr{'y' if len(table['entries']) == 1 else 'ies'})")
  return table


# -- table I/O + validation ---------------------------------------------------

def write_table(table: Dict[str, Any], path: str) -> str:
  """Atomic, canonical write (sorted keys, stable indent): same seed +
  same contracts + same measurements => byte-identical file (the
  determinism contract tests pin)."""
  os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
  tmp = path + ".tmp"
  with open(tmp, "w", encoding="utf-8") as f:
    json.dump(table, f, indent=2, sort_keys=True)
    f.write("\n")
  os.replace(tmp, path)
  return path


def load_table(path: str) -> Dict[str, Any]:
  try:
    with open(path, encoding="utf-8") as f:
      table = json.load(f)
  except OSError as e:
    raise AutotuneError(f"tuned-config table unreadable: {path}: {e}")
  except ValueError as e:
    raise AutotuneError(f"tuned-config table is not valid JSON: "
                        f"{path}: {e}")
  if not isinstance(table, dict) or \
      not isinstance(table.get("entries"), dict):
    raise AutotuneError(f"tuned-config table has no entries object: "
                        f"{path}")
  return table


def validate_table(table: Dict[str, Any], *,
                   rederive: bool = True
                   ) -> Tuple[List[str], List[str]]:
  """(problems, warnings) for a tuned-config table -- the
  ``run_tests.py --audit`` tuned-table leg.

  Problems (audit-fatal): schema shape, knobs outside the registry
  (baseline.TUNED_KNOBS), non-integer knob values, a tuned measurement
  below the entry's own default measurement, and -- with ``rederive``
  -- an entry key that no longer re-derives from its stored base
  config (a program-shaping flag default changed underneath the table;
  regenerate with `python -m kf_benchmarks_tpu.analysis autotune`).
  Warnings (non-fatal): entries recorded under a different jax version
  (an XLA upgrade recompiles everything; the tuning may be stale)."""
  problems: List[str] = []
  warnings: List[str] = []
  ver = table.get("schema_version")
  if not isinstance(ver, int) or not 1 <= ver <= TABLE_SCHEMA_VERSION:
    problems.append(f"schema_version {ver!r} outside "
                    f"[1, {TABLE_SCHEMA_VERSION}]")
  entries = table.get("entries")
  if not isinstance(entries, dict):
    return problems + ["entries missing or not an object"], warnings
  current_jax = _jax_version()
  for key in sorted(entries):
    entry = entries[key]
    where = f"entry {key[:16]}"
    if not isinstance(entry, dict):
      problems.append(f"{where}: not an object")
      continue
    tuned = entry.get("tuned")
    if not isinstance(tuned, dict):
      problems.append(f"{where}: tuned knobs missing")
      tuned = {}
    for k, v in sorted(tuned.items()):
      if k not in TUNED_KNOBS:
        problems.append(f"{where}: tuned knob {k!r} is not in the "
                        f"knob registry {list(TUNED_KNOBS)}")
      elif k == "partitioner":
        # The one string-valued knob (see baseline.TUNED_KNOBS).
        if v is not None and v not in ("manual", "gspmd"):
          problems.append(f"{where}: tuned value partitioner={v!r} is "
                          "not 'manual', 'gspmd', or null")
      elif v is not None and (isinstance(v, bool)
                              or not isinstance(v, int)):
        problems.append(f"{where}: tuned value {k}={v!r} is not an "
                        "integer or null")
    d_ips = entry.get("default_images_per_sec")
    t_ips = entry.get("tuned_images_per_sec")
    if d_ips is not None and t_ips is not None and t_ips < d_ips:
      problems.append(
          f"{where}: tuned_images_per_sec {t_ips} < the entry's own "
          f"default measurement {d_ips} -- the search must never emit "
          "a measured regression over its own bar")
    if entry.get("jax_version") and current_jax and \
        entry["jax_version"] != current_jax:
      warnings.append(
          f"{where}: recorded under jax {entry['jax_version']} "
          f"(current {current_jax}); tuning may be stale -- "
          "regenerate after validating on the new runtime")
    if rederive:
      base_cfg = entry.get("base_config")
      if not isinstance(base_cfg, dict):
        problems.append(f"{where}: base_config missing")
        continue
      try:
        from kf_benchmarks_tpu import params as params_lib
        params = params_lib.make_params(**base_cfg)
        derived = baseline.base_fingerprint_key(
            params._asdict(), entry.get("program", "train_step"))
      except Exception as e:
        problems.append(f"{where}: base_config does not build: {e}")
        continue
      if derived != key:
        problems.append(
            f"{where}: fingerprint does not re-derive (got "
            f"{derived[:16]}): a program-shaping flag changed "
            "underneath the table -- regenerate it with `python -m "
            "kf_benchmarks_tpu.analysis autotune`")
  return problems, warnings


# -- startup application ------------------------------------------------------

def lookup_entry(path: str, params
                 ) -> Tuple[str, Optional[Dict[str, Any]]]:
  """(base_key, entry or None) for a resolved Params against the table
  at ``path``. Stable across application: the base key strips exactly
  the knobs the table sets, so a tuned run looks itself up under the
  same key as its default twin."""
  table = load_table(path)
  key = baseline.base_fingerprint_key(params._asdict(), "train_step")
  entry = table["entries"].get(key)
  return key, entry if isinstance(entry, dict) else None


def apply_tuned_config(params, log_fn: Callable[[str], None] = print):
  """Apply --autotuned_config at startup (benchmark.setup calls this
  before the runtime is constructed): look the run's base fingerprint
  up in the table and replace the tuned knobs, with one logged
  provenance line either way. Returns ``(params, provenance)`` --
  provenance is the ``{path, entry}`` payload the stats/run record
  carries (entry None when the table held no row), or None when the
  flag is unset; the caller threads it through so the recorded
  provenance can never disagree with what was actually applied."""
  path = getattr(params, "autotuned_config", None)
  if not path:
    return params, None
  from kf_benchmarks_tpu import validation
  if params.eval or params.forward_only:
    raise validation.ParamError(
        "--autotuned_config tunes the training step's program-shaping "
        "knobs (analysis/autotune.py); it cannot be combined with "
        "--eval or --forward_only")
  try:
    key, entry = lookup_entry(path, params)
  except AutotuneError as e:
    raise validation.ParamError(str(e))
  if entry is None:
    log_fn(f"autotuned config: no entry for base fingerprint "
           f"{key[:16]} in {path}; running with the flag values")
    return params, {"path": path, "entry": None}
  tuned = {k: v for k, v in (entry.get("tuned") or {}).items()
           if k in TUNED_KNOBS}
  params = params._replace(**tuned)
  applied = ", ".join(f"{k}={tuned[k]}" for k in sorted(tuned))
  log_fn(f"autotuned config: applied {applied} from {path} "
         f"(entry {key[:16]}, model {entry.get('model')}, "
         f"measured {entry.get('tuned_images_per_sec')} vs default "
         f"{entry.get('default_images_per_sec')} examples/s)")
  return params, {"path": path, "entry": key}


def tuned_provenance(params) -> Optional[Dict[str, Any]]:
  """The run-record provenance payload: table path + matched entry
  fingerprint (None when the table had no entry for this config), or
  None when --autotuned_config is unset. Best-effort -- a table that
  disappeared between setup and the stats build reports entry None
  rather than failing the run."""
  path = getattr(params, "autotuned_config", None)
  if not path:
    return None
  try:
    key, entry = lookup_entry(path, params)
  except AutotuneError:
    return {"path": path, "entry": None}
  return {"path": path, "entry": key if entry is not None else None}


# -- ledger-informed warming --------------------------------------------------

def warm(train_dir: str, *,
         table_path: Optional[str] = None,
         configs: Optional[List[Dict[str, Any]]] = None,
         cache_dir: Optional[str] = None,
         log: Callable[[str], None] = print) -> Dict[str, Any]:
  """Precompile every (config, program) shape a job will need into the
  persistent XLA compilation cache, ahead of a hardware window.

  Shapes come from the tuned table at ``table_path`` (default:
  ``train_dir/tuned_configs.json``; each entry's base config + tuned
  knobs) and/or explicit ``configs``; the persisted compile ledger
  (tracing.read_ledger) is cross-referenced so already-warm shapes are
  skipped and ledgered program labels beyond the config's own
  prediction are warmed too. Every compile is keyed exactly as the
  runtime keys it (config_fingerprint_key over the RESOLVED params)
  and written back to the train_dir ledger, so a follow-up run reads
  ``cache_hit`` on every warmed shape. Strictly sequential: on the
  real chip this is the controlled place to pay the 30-minute
  first-compile (never under a kill timeout -- CLAUDE.md)."""
  from kf_benchmarks_tpu import benchmark
  from kf_benchmarks_tpu import params as params_lib
  from kf_benchmarks_tpu import tracing as tracing_lib

  cache_dir = cache_dir or os.path.join(train_dir, "xla_cache")
  benchmark._configure_compile_cache(cache_dir)
  log(f"warm: persistent XLA cache {cache_dir}")
  ledger = tracing_lib.read_ledger(train_dir)
  prior_keys = tracing_lib.ledger_keys(ledger)
  ledger_progs = tracing_lib.ledger_programs(ledger)
  cache_warm = False
  try:
    cache_warm = any(os.scandir(cache_dir))
  except OSError:
    cache_warm = False

  jobs: List[Dict[str, Any]] = [dict(c) for c in (configs or [])]
  path = table_path or os.path.join(train_dir, TABLE_FILENAME)
  if table_path or os.path.exists(path):
    table = load_table(path)
    for key in sorted(table["entries"]):
      entry = table["entries"][key]
      full = merged_overrides(dict(entry.get("base_config") or {}),
                              entry.get("tuned") or {})
      jobs.append(full)

  trace = tracing_lib.RunTrace(log_fn=log)
  warmed, skipped = [], []
  for full in jobs:
    # num_batches is NOT defaulted here: a job that leaves it unset
    # keys with the field ABSENT (the runtime resolves the count into
    # an attribute, never back into params), so injecting a value
    # would key a shape no real run ever looks up. Jobs that DO set
    # --num_batches must pass it in ``configs`` (the tuned table's
    # base configs strip run-length fields by design). The train_dir
    # IS mirrored: it is fingerprint-excluded itself, but its
    # PRESENCE feeds the --health_stats auto-resolution
    # (telemetry.py), which IS a program-shaping bool -- a warm pass
    # without it would key the health-off twin of the job's program.
    full.setdefault("train_dir", train_dir)
    bench = benchmark.BenchmarkCNN(params_lib.make_params(**full))
    spd = int(bench.params.steps_per_dispatch or 1)
    programs = ["train_step"]
    if spd > 1:
      programs.append("train_chunk")
    # Ledger labels beyond what this config can build here (eval_step,
    # or train_chunk at K=1) are reported, not silently covered.
    unbuildable = ledger_progs - set(programs)
    if unbuildable:
      log(f"warm: ledger names program(s) {sorted(unbuildable)} this "
          f"config cannot build (K={spd}); not warmed")
    for prog in programs:
      key = baseline.config_fingerprint_key(bench.params._asdict(),
                                            prog)
      if cache_warm and key in prior_keys:
        skipped.append((key, prog))
        log(f"warm: {bench.model.get_name()}/{prog} {key[:16]} "
            "already warm; skipped")
        continue
      t0 = time.monotonic()
      _, lowered = contracts.lower_step_program(bench, prog)
      lowered.compile()
      wall = time.monotonic() - t0
      trace.note_compile(key, prog, wall,
                         model=bench.model.get_name(),
                         num_devices=bench.num_devices,
                         warm_pass=True)
      warmed.append((key, prog))
      log(f"warm: compiled {bench.model.get_name()}/{prog} "
          f"{key[:16]} in {wall:.2f} s")
  out_path = trace.write_ledger(train_dir)
  return {"cache_dir": cache_dir, "warmed": warmed,
          "skipped": skipped, "ledger": out_path}
