"""JPEG directory -> TFRecord shard converter.

The analog of the reference's data-fetch utilities
(ref: scripts/tf_cnn_benchmarks/get_tf_record.py -- JPEG dir to
TFRecord). Its sibling ``data/get_imagenet.py`` covers the reference's
tfds-download path (import-gated: this image has no network egress);
this converter consumes an already-downloaded ImageNet-layout directory.

Expected layout (the standard ImageNet raw layout):

    <root>/train/<wnid>/*.JPEG
    <root>/validation/<wnid>/*.JPEG

Labels are 1-based indices of the sorted wnid directory names (the
ImageNet convention the reference's parser expects: label 0 = background,
ref: preprocessing.py:27-81). Output shards are named
``<subset>-%05d-of-%05d`` so datasets.create_dataset / tfrecord
.list_shards find them.

Usage:
    python -m kf_benchmarks_tpu.data.get_tf_record \
        --input_dir /data/imagenet-raw --output_dir /data/imagenet-tf \
        --train_shards 128 --validation_shards 16
"""

from __future__ import annotations

import argparse
import os
from typing import List, Tuple

import numpy as np

from kf_benchmarks_tpu.data import example as example_lib
from kf_benchmarks_tpu.data import tfrecord

_IMAGE_EXTS = (".jpeg", ".jpg", ".JPEG", ".JPG")


def _list_images(subset_dir: str) -> Tuple[List[Tuple[str, int]],
                                           List[str]]:
  """[(path, 1-based label)] plus the sorted wnid list."""
  wnids = sorted(d for d in os.listdir(subset_dir)
                 if os.path.isdir(os.path.join(subset_dir, d)))
  files = []
  for label, wnid in enumerate(wnids, start=1):
    d = os.path.join(subset_dir, wnid)
    for name in sorted(os.listdir(d)):
      if name.endswith(_IMAGE_EXTS):
        files.append((os.path.join(d, name), label))
  return files, wnids


def convert_subset(input_dir: str, output_dir: str, subset: str,
                   num_shards: int, shuffle_seed: int = 0) -> int:
  """Convert one subset; returns the number of examples written."""
  subset_dir = os.path.join(input_dir, subset)
  if not os.path.isdir(subset_dir):
    raise ValueError(f"No {subset}/ directory under {input_dir}")
  files, _ = _list_images(subset_dir)
  if not files:
    raise ValueError(f"No JPEG files under {subset_dir}")
  order = np.random.RandomState(shuffle_seed).permutation(len(files))
  os.makedirs(output_dir, exist_ok=True)
  per_shard = -(-len(files) // num_shards)  # ceil
  written = 0
  for shard in range(num_shards):
    path = tfrecord.shard_path(output_dir, subset, shard, num_shards)
    with tfrecord.TFRecordWriter(path) as w:
      for idx in order[shard * per_shard:(shard + 1) * per_shard]:
        fpath, label = files[idx]
        with open(fpath, "rb") as f:
          image_bytes = f.read()
        w.write(example_lib.encode_example({
            "image/encoded": image_bytes,
            "image/class/label": np.asarray([label], np.int64),
            "image/filename": os.path.basename(fpath).encode(),
        }))
        written += 1
  return written


def main(argv=None):
  parser = argparse.ArgumentParser(
      description="Convert an ImageNet-layout JPEG directory to "
                  "TFRecord shards")
  parser.add_argument("--input_dir", required=True)
  parser.add_argument("--output_dir", required=True)
  parser.add_argument("--train_shards", type=int, default=128)
  parser.add_argument("--validation_shards", type=int, default=16)
  args = parser.parse_args(argv)
  for subset, shards in (("train", args.train_shards),
                         ("validation", args.validation_shards)):
    n = convert_subset(args.input_dir, args.output_dir, subset, shards)
    print(f"{subset}: wrote {n} examples in {shards} shards")


if __name__ == "__main__":
  main()
