"""Fake Librispeech SequenceExample generator.

Writes the record format the reference's DeepSpeech2 path consumes
(ref: scripts/tf_cnn_benchmarks/preprocessing.py:1081-1112): per-frame
161-bin spectrogram features as a sequence feature plus context labels/
lengths. Real Librispeech prep computes these features offline from the
audio (the official deepspeech featurizer); this generator fabricates
short random utterances so the pipeline and CTC training run end-to-end
without the 1000-hour corpus.
"""

from __future__ import annotations

import os

import numpy as np

from kf_benchmarks_tpu.data import example as example_lib
from kf_benchmarks_tpu.data import tfrecord

NUM_FEATURE_BINS = 161
# Character labels 1..28 (a-z, space, apostrophe); 0 reserved, 28 blank
# in the model's alphabet (ref: deepspeech.py labels).
NUM_CHAR_CLASSES = 27


def write_fake_librispeech(data_dir: str, num_train: int = 8,
                           num_validation: int = 4,
                           min_frames: int = 40, max_frames: int = 120,
                           max_label_len: int = 30, seed: int = 0) -> None:
  os.makedirs(data_dir, exist_ok=True)
  rng = np.random.RandomState(seed)
  for subset, count in (("train", num_train),
                        ("validation", num_validation)):
    path = os.path.join(data_dir, f"{subset}-00000-of-00001")
    with tfrecord.TFRecordWriter(path) as w:
      for _ in range(count):
        t = int(rng.randint(min_frames, max_frames + 1))
        l = int(rng.randint(5, max_label_len + 1))
        frames = rng.randn(t, NUM_FEATURE_BINS).astype(np.float32)
        labels = rng.randint(1, NUM_CHAR_CLASSES + 1,
                             size=l).astype(np.int64)
        record = example_lib.encode_sequence_example(
            context={
                "labels": labels,
                "input_length": np.asarray([t], np.int64),
                "label_length": np.asarray([l], np.int64),
            },
            feature_lists={"features": [frames[i] for i in range(t)]})
        w.write(record)
