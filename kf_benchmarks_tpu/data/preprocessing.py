"""Host-side input preprocessing (real-data pipeline).

TPU-native re-design of the reference's input layer (ref:
scripts/tf_cnn_benchmarks/preprocessing.py). The reference builds tf.data /
RecordInput graphs with per-device StagingAreas; here the host pipeline is
plain Python/numpy/PIL running in a thread pool, and device transfer is a
double-buffered ``jax.device_put`` onto the batch sharding (the
MultiDeviceIterator / gpu_compute_stage analog lives in device_feed.py).

Semantics preserved from the reference:

* final images are float32 in [-1, 1]: ``x / 127.5 - 1``
  (ref: preprocessing.py:130-133 normalized_image)
* train: sampled distorted bbox crop (min_object_covered=0.1, aspect
  [0.75, 1.33], area [0.05, 1.0], 100 attempts), resize with per-position
  round-robin method, random horizontal flip, optional color distortion
  (ref: train_image, preprocessing.py:192-308)
* eval: central crop of 87.5% then resize (ref: eval_image,
  preprocessing.py:137-190)
* cifar10: zero-pad 4px each side, random 32x32 crop, random flip
  (ref: Cifar10ImagePreprocessor._distort_image, preprocessing.py:656-676);
  data loaded from the python pickle batches (ref: datasets.py:140-189)
* sharded readers de-overlap workers by shifting the shard assignment by
  ``shift_ratio`` (ref: RecordInput shift_ratio, preprocessing.py:601-617)
"""

from __future__ import annotations

import concurrent.futures
import io
import itertools
import os
import pickle
import random
import threading
import time
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from kf_benchmarks_tpu.data import example as example_lib
from kf_benchmarks_tpu.data import tfrecord

try:
  from PIL import Image, ImageEnhance
  _HAVE_PIL = True
except ImportError:  # pragma: no cover
  _HAVE_PIL = False

# (ref: preprocessing.py:75-97 _RESIZE_METHOD_MAP + round_robin)
_RESIZE_METHODS = ("nearest", "bilinear", "bicubic", "area")


def _pil_resize_method(name: str):
  return {
      "nearest": Image.NEAREST,
      "bilinear": Image.BILINEAR,
      "bicubic": Image.BICUBIC,
      "area": Image.BOX,
  }[name]


def get_image_resize_method(resize_method: str, batch_position: int = 0):
  """Round-robin per batch position (ref: preprocessing.py:85-127)."""
  if resize_method != "round_robin":
    return _pil_resize_method(resize_method)
  methods = [_pil_resize_method(m) for m in _RESIZE_METHODS]
  return methods[batch_position % len(methods)]


def normalized_image(images: np.ndarray) -> np.ndarray:
  """[0, 255] -> [-1, 1] (ref: preprocessing.py:130-133)."""
  return images.astype(np.float32) * (1.0 / 127.5) - 1.0


# -- Example proto parsing (ref: preprocessing.py:27-81) ---------------------

def parse_example_proto(record: bytes):
  """Returns (image_buffer, label, bbox[N,4] ymin,xmin,ymax,xmax)."""
  feats = example_lib.parse_example(record)
  image_buffer = feats["image/encoded"][0]
  label = int(np.asarray(feats["image/class/label"])[0])
  def _coords(key):
    v = feats.get(key)
    return np.asarray(v, np.float32) if v is not None and len(v) else (
        np.zeros((0,), np.float32))
  xmin, ymin = _coords("image/object/bbox/xmin"), _coords(
      "image/object/bbox/ymin")
  xmax, ymax = _coords("image/object/bbox/xmax"), _coords(
      "image/object/bbox/ymax")
  bbox = np.stack([ymin, xmin, ymax, xmax], axis=-1) if len(xmin) else (
      np.zeros((0, 4), np.float32))
  return image_buffer, label, bbox


# -- crop sampling (tf.image.sample_distorted_bounding_box semantics) --------

def sample_distorted_bounding_box(
    rng: random.Random, height: int, width: int, bboxes: np.ndarray,
    min_object_covered: float = 0.1,
    aspect_ratio_range: Tuple[float, float] = (0.75, 1.33),
    area_range: Tuple[float, float] = (0.05, 1.0),
    max_attempts: int = 100) -> Tuple[int, int, int, int]:
  """Sample a crop window (y, x, h, w); whole image on failure.

  Numpy re-implementation of the sampling the reference gets from
  ``tf.image.sample_distorted_bounding_box`` (ref: preprocessing.py:219-247
  train_image's distorted crop).
  """
  img_area = float(height * width)
  for _ in range(max_attempts):
    aspect = rng.uniform(*aspect_ratio_range)
    area = rng.uniform(*area_range) * img_area
    # h * w = area; w / h = aspect  =>  h = sqrt(area / aspect)
    h = int(round((area / aspect) ** 0.5))
    w = int(round(h * aspect))
    if h <= 0 or w <= 0 or h > height or w > width:
      continue
    y = rng.randint(0, height - h)
    x = rng.randint(0, width - w)
    if len(bboxes):
      # min_object_covered: the crop must cover >= the fraction of at
      # least one object box.
      covered = False
      for ymin, xmin, ymax, xmax in bboxes:
        by0, bx0 = ymin * height, xmin * width
        by1, bx1 = ymax * height, xmax * width
        barea = max(by1 - by0, 0.0) * max(bx1 - bx0, 0.0)
        if barea <= 0:
          continue
        iy = max(0.0, min(by1, y + h) - max(by0, y))
        ix = max(0.0, min(bx1, x + w) - max(bx0, x))
        if iy * ix >= min_object_covered * barea:
          covered = True
          break
      if not covered:
        continue
    return y, x, h, w
  return 0, 0, height, width


# -- color distortion (ref: distort_color, preprocessing.py:268-308) ---------

def distort_color(img: "Image.Image", batch_position: int,
                  rng: random.Random) -> "Image.Image":
  """Brightness/saturation/contrast jitter, order by batch position
  (ref fast-mode orderings; hue omitted as in the reference's fast path)."""
  def brightness(i):
    # max_delta = 32/255 in [0,1] space == factor jitter around 1.
    return ImageEnhance.Brightness(i).enhance(
        1.0 + rng.uniform(-32.0 / 255.0, 32.0 / 255.0))
  def saturation(i):
    return ImageEnhance.Color(i).enhance(rng.uniform(0.5, 1.5))
  def contrast(i):
    return ImageEnhance.Contrast(i).enhance(rng.uniform(0.5, 1.5))
  if batch_position % 2 == 0:
    ops = (brightness, saturation, contrast)
  else:
    ops = (brightness, contrast, saturation)
  for op in ops:
    img = op(img)
  return img


def _draft_decode(img: "Image.Image", need_w: int, need_h: int):
  """DCT-domain reduced-scale JPEG decode (PIL ``draft``): ask libjpeg to
  decode at 1/2, 1/4, or 1/8 scale when the consumer only needs
  ``need_w x need_h`` of the full frame. This is the single biggest
  host-decode win on photo-sized inputs and the PIL analog of the
  reference's fused decode-and-crop JPEG path (ref:
  preprocessing.py:192-265 fuse_decode_and_crop). Returns the
  (possibly scaled) image; a no-op for non-JPEG content. Callers must
  rescale any full-frame pixel coordinates by the returned image's
  size ratio."""
  img.draft("RGB", (max(1, int(need_w)), max(1, int(need_h))))
  return img


def train_image(image_buffer: bytes, height: int, width: int,
                bbox: np.ndarray, batch_position: int,
                resize_method: str, distortions: bool,
                rng: random.Random) -> np.ndarray:
  """Distorted-crop training path -> float32 [0,255] HWC
  (ref: train_image, preprocessing.py:192-265)."""
  img = Image.open(io.BytesIO(image_buffer))
  iw, ih = img.size
  # The crop is sampled in FULL-frame coordinates (the rng stream is
  # independent of the decode scale), then the decode runs at the
  # smallest DCT scale that still covers the target resolution inside
  # the crop, and the coordinates are mapped onto the decoded frame.
  y, x, h, w = sample_distorted_bounding_box(rng, ih, iw, bbox)
  _draft_decode(img, iw * width / max(w, 1), ih * height / max(h, 1))
  img = img.convert("RGB")
  sx, sy = img.size[0] / iw, img.size[1] / ih
  # fuse_decode_and_crop analog: crop before the (expensive) resize.
  img = img.crop((int(x * sx), int(y * sy),
                  max(int(x * sx) + 1, int((x + w) * sx)),
                  max(int(y * sy) + 1, int((y + h) * sy))))
  method = get_image_resize_method(resize_method, batch_position)
  img = img.resize((width, height), method)
  if rng.random() < 0.5:
    img = img.transpose(Image.FLIP_LEFT_RIGHT)
  if distortions:
    img = distort_color(img, batch_position, rng)
  return np.asarray(img, dtype=np.float32)


def eval_image(image_buffer: bytes, height: int, width: int,
               batch_position: int, resize_method: str) -> np.ndarray:
  """Central-crop-87.5% eval path -> float32 [0,255] HWC
  (ref: eval_image, preprocessing.py:137-190)."""
  img = Image.open(io.BytesIO(image_buffer))
  # 87.5% central crop resized to HxW only needs ~H/0.875 of the frame.
  _draft_decode(img, width / 0.875, height / 0.875)
  img = img.convert("RGB")
  iw, ih = img.size
  ch, cw = int(ih * 0.875), int(iw * 0.875)
  y, x = (ih - ch) // 2, (iw - cw) // 2
  img = img.crop((x, y, x + cw, y + ch))
  method = get_image_resize_method(resize_method, batch_position)
  img = img.resize((width, height), method)
  return np.asarray(img, dtype=np.float32)


# -- preprocessors -----------------------------------------------------------

class InputPreprocessor:
  """Base preprocessor (ref: preprocessing.py:311-548). Yields numpy
  (images[global_batch, H, W, C] float32 normalized, labels[int32])."""

  def __init__(self, batch_size: int, output_shape: Sequence[int],
               train: bool = True, distortions: bool = False,
               resize_method: str = "bilinear", seed: int = 301,
               shift_ratio: float = 0.0, num_threads: int = 8,
               repeat_cached_sample: bool = False,
               use_caching: bool = False):
    self.batch_size = batch_size
    self.height, self.width, self.depth = output_shape
    self.train = train
    self.distortions = distortions
    self.resize_method = resize_method
    self.seed = seed
    self.shift_ratio = shift_ratio
    self.num_threads = max(1, num_threads)
    # --datasets_repeat_cached_sample: serve the first record forever to
    # emulate memory-speed IO (ref: preprocessing create_dataset
    # take(1).cache().repeat(), :879-882).
    self.repeat_cached_sample = repeat_cached_sample
    # --datasets_use_caching: hold the raw records in memory after the
    # first pass (ref: ds.cache(), :254-258).
    self.use_caching = use_caching

  def minibatches(self, dataset, subset: str) -> Iterator[
      Tuple[np.ndarray, np.ndarray]]:
    raise NotImplementedError

  def _record_stream(self, dataset, subset: str) -> Iterator[bytes]:
    """Shared TFRecord shard stream: shift_ratio de-overlap (ref:
    RecordInput shift_ratio, preprocessing.py:601-617), shard-order
    shuffle + endless replay for training, ONE pass for eval (the
    reference bounds eval by num_eval_batches over a single epoch;
    consumers handle exhaustion -- see BenchmarkCNN._eval_once)."""
    shards = tfrecord.list_shards(dataset.data_dir, subset)
    shift = int(len(shards) * self.shift_ratio) % max(len(shards), 1)
    shards = shards[shift:] + shards[:shift]
    if self.repeat_cached_sample:
      first = next(iter(tfrecord.read_records(shards[0])), None)
      if first is None:
        raise ValueError(
            f"datasets_repeat_cached_sample: first shard {shards[0]} "
            "contains no records")
      while True:
        yield first
    rng = random.Random(self.seed)
    cache = [] if self.use_caching else None
    first_pass = True
    while True:
      if cache is not None and not first_pass:
        order2 = list(cache)
        if self.train:
          rng.shuffle(order2)
        yield from order2
        continue
      order = list(shards)
      if self.train:
        rng.shuffle(order)
      for path in order:
        for record in tfrecord.read_records(path):
          if cache is not None:
            cache.append(record)
          yield record
      first_pass = False
      if not self.train:
        break

  def supports_datasets(self) -> bool:
    return True


class RecordInputImagePreprocessor(InputPreprocessor):
  """TFRecord image classification pipeline
  (ref: preprocessing.py:551-632)."""

  def _preprocess_one(self, record: bytes, batch_position: int,
                      rng: random.Random) -> Tuple[np.ndarray, int]:
    image_buffer, label, bbox = parse_example_proto(record)
    if self.train:
      img = train_image(image_buffer, self.height, self.width, bbox,
                        batch_position, self.resize_method,
                        self.distortions, rng)
    else:
      img = eval_image(image_buffer, self.height, self.width,
                       batch_position, self.resize_method)
    return normalized_image(img), label

  def minibatches(self, dataset, subset: str):
    if not _HAVE_PIL:  # pragma: no cover
      raise NotImplementedError("PIL is required for the real-data pipeline")
    stream = self._record_stream(dataset, subset)
    rngs = [random.Random(self.seed + 7919 * i)
            for i in range(self.batch_size)]
    # Serial fast path: a 1-worker executor adds only GIL hand-off
    # overhead (experiments/input_pipeline_bench.py).
    pool = (concurrent.futures.ThreadPoolExecutor(self.num_threads)
            if self.num_threads > 1 else None)
    try:
      while True:
        records = list(itertools.islice(stream, self.batch_size))
        if len(records) < self.batch_size:
          return  # eval stream exhausted (train replays forever)
        if pool is None:
          results = [self._preprocess_one(rec, i, rngs[i])
                     for i, rec in enumerate(records)]
        else:
          futs = [pool.submit(self._preprocess_one, rec, i, rngs[i])
                  for i, rec in enumerate(records)]
          results = [f.result() for f in futs]
        images = np.stack([r[0] for r in results])
        labels = np.asarray([r[1] for r in results], np.int32)
        yield images, labels
    finally:
      if pool is not None:
        pool.shutdown(wait=False)


class OfficialImagenetPreprocessor(RecordInputImagePreprocessor):
  """The official-models ImageNet preprocessing variant
  (ref: preprocessing.py:635-652 ImagenetPreprocessor, which delegates to
  official.vision...imagenet_preprocessing.preprocess_image).

  Differences from the default pipeline: eval resizes preserving aspect
  ratio so the short side is 256 then takes a central HxW crop (instead
  of the 87.5% crop), train never color-distorts, and normalization
  subtracts the ImageNet channel means in [0,255] space with no std
  scaling (the official CHANNEL_MEANS convention)."""

  CHANNEL_MEANS = np.asarray([123.68, 116.779, 103.939], np.float32)
  RESIZE_MIN = 256

  def _preprocess_one(self, record: bytes, batch_position: int,
                      rng: random.Random):
    image_buffer, label, bbox = parse_example_proto(record)
    if self.train:
      # Same crop/flip pipeline as the default path, bilinear, no color
      # distortion (the official preprocess_image train path).
      arr = train_image(image_buffer, self.height, self.width, bbox,
                        batch_position, "bilinear", distortions=False,
                        rng=rng)
    else:
      img = Image.open(io.BytesIO(image_buffer)).convert("RGB")
      iw, ih = img.size
      scale = self.RESIZE_MIN / min(iw, ih)
      img = img.resize((max(int(iw * scale), self.width),
                        max(int(ih * scale), self.height)),
                       Image.BILINEAR)
      iw, ih = img.size
      x, y = (iw - self.width) // 2, (ih - self.height) // 2
      img = img.crop((x, y, x + self.width, y + self.height))
      arr = np.asarray(img, np.float32)
    return arr - self.CHANNEL_MEANS, label


def _mp_decode_worker(task_q, done_q, shm_name, buf_shape, in_shm_name,
                      in_shape, pre_bytes):
  """Decode worker for MultiprocessImagePreprocessor. Runs in a SPAWNED
  process (no inherited device/tunnel file descriptors, no jax import):
  pulls one task per BATCH SLICE -- (buffer, batch_index, entries) with
  each entry locating a record's raw bytes in the shared input ring (or
  carrying them inline on staging overflow) -- decodes with the pickled
  preprocessor's single-image path, writes each image directly into its
  final batch position in the shared output ring, and posts ONE done
  message per slice. Per-image queue traffic was the dispatch
  bottleneck at real rates (VERDICT r3 weak #2: ~2,600 pickled
  ~100 KB messages/sec through one Queue)."""
  from multiprocessing import shared_memory  # noqa: PLC0415
  pre = pickle.loads(pre_bytes)
  shm = shared_memory.SharedMemory(name=shm_name)
  in_shm = shared_memory.SharedMemory(name=in_shm_name)
  ring = np.ndarray(buf_shape, np.float32, buffer=shm.buf)
  in_ring = np.ndarray(in_shape, np.uint8, buffer=in_shm.buf)
  try:
    while True:
      task = task_q.get()
      if task is None:
        return
      buf, batch_idx, entries = task
      labels = []
      err = None
      for pos, off, length, inline in entries:
        record = (inline if inline is not None
                  else bytes(in_ring[buf, off:off + length]))
        # Deterministic per-(position, batch) stream: workers hold no
        # cross-batch rng state, so the stream is derived, not advanced.
        rng = random.Random(pre.seed + 7919 * pos + 104729 * batch_idx)
        try:
          img, label = pre._preprocess_one(record, pos, rng)
          ring[buf, pos] = img
          labels.append((pos, int(label)))
        except Exception as e:  # surface decode errors to the parent
          err = (pos, repr(e))
          break
      # One message per slice; count covers the whole slice even on
      # error (the parent raises before using the batch).
      done_q.put((buf, len(entries), labels, err))
  finally:
    shm.close()
    in_shm.close()


class MultiprocessImagePreprocessor(RecordInputImagePreprocessor):
  """Process-parallel TFRecord image pipeline: the RecordInput /
  tf.data-C++-threadpool analog for multi-core hosts (ref:
  preprocessing.py:505-548 parallel interleave/map, :601-617
  RecordInput; VERDICT r2 #2).

  The Python thread pool above cannot scale JPEG decode past ~1 core
  (GIL); this variant spawns decode worker PROCESSES that write images
  straight into their final batch slot in a shared-memory ring of
  ``num_buffers`` global batches -- one memcpy per batch at yield, no
  pickling of decoded tensors. Batches are dispatched one ahead so
  workers decode batch k+1 while the consumer holds batch k. Workers
  are spawned (not forked): the parent holds live device-tunnel file
  descriptors a fork would duplicate.

  Dispatch is BATCHED (the RecordInput C++ batch semantics, ref:
  preprocessing.py:601-617): raw record bytes are staged into a shared
  input ring and each worker gets one task message per contiguous batch
  slice (entries = shm offsets), answering with one done message per
  slice -- 2*num_processes queue messages per batch instead of
  2*batch_size pickled records. Records larger than the staging slot
  fall back to inline bytes in the task message (correct, just slower).

  Select with --input_preprocessor=multiprocess. ``num_threads`` is
  interpreted as the worker-process count.
  """

  def __init__(self, *args, num_processes: Optional[int] = None,
               num_buffers: int = 3,
               input_bytes_per_image: int = 256 << 10, **kwargs):
    super().__init__(*args, **kwargs)
    try:  # available (affinity/cgroup-visible) cores, not host cores
      cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
      cores = os.cpu_count() or 1
    if num_processes:
      # An EXPLICIT worker count is honored (experiments sweep
      # oversubscription on purpose; tests exercise multi-worker slice
      # paths on 1-core hosts) -- with the measured warning attached.
      self.num_processes = max(1, num_processes)
      if self.num_processes > cores:
        from kf_benchmarks_tpu.utils import log as log_util
        log_util.log_fn(
            f"Decode pool oversubscribed: {self.num_processes} workers "
            f"on {cores} available core(s) -- contention HALVED decode "
            "throughput at 8-on-1 (PERF.md round-4 measurement)")
    else:
      # The DEFAULTED size is capped at the available cores: workers
      # beyond them only contend (8 workers on 1 core halved decode
      # throughput, PERF.md round 4). num_threads is always >= 1
      # (RecordInputImagePreprocessor.__init__).
      self.num_processes = min(self.num_threads, cores)
    self.num_buffers = max(2, num_buffers)
    # Staging capacity per image slot; 256 KiB covers ~99% of ImageNet
    # JPEGs (mean ~110 KiB). Oversized records ride the inline fallback.
    self.input_bytes_per_image = max(1, int(input_bytes_per_image))
    # Cumulative parent-side dispatch cost (staging + enqueue), readable
    # by experiments/input_pipeline_bench.py's dispatcher-cost probe.
    self.dispatch_seconds = 0.0
    self.dispatch_calls = 0

  def minibatches(self, dataset, subset: str):
    if not _HAVE_PIL:  # pragma: no cover
      raise NotImplementedError("PIL is required for the real-data pipeline")
    import multiprocessing  # noqa: PLC0415
    from multiprocessing import shared_memory  # noqa: PLC0415
    ctx = multiprocessing.get_context("spawn")
    stream = self._record_stream(dataset, subset)
    shape = (self.num_buffers, self.batch_size, self.height, self.width,
             self.depth)
    nbytes = int(np.prod(shape)) * 4
    shm = shared_memory.SharedMemory(create=True, size=nbytes)
    ring = np.ndarray(shape, np.float32, buffer=shm.buf)
    # Input staging ring: raw record bytes per buffer, so workers read
    # their slice from shared memory instead of unpickling it per image.
    in_shape = (self.num_buffers,
                self.batch_size * self.input_bytes_per_image)
    in_shm = shared_memory.SharedMemory(create=True,
                                        size=int(np.prod(in_shape)))
    in_ring = np.ndarray(in_shape, np.uint8, buffer=in_shm.buf)
    task_q = ctx.Queue()
    done_q = ctx.Queue()
    pre_bytes = pickle.dumps(self)
    workers = [
        ctx.Process(target=_mp_decode_worker,
                    args=(task_q, done_q, shm.name, shape, in_shm.name,
                          in_shape, pre_bytes),
                    daemon=True)
        for _ in range(self.num_processes)]
    for w in workers:
      w.start()
    # Per-buffer bookkeeping for the one-batch-ahead pipeline.
    remaining = [0] * self.num_buffers
    labels_buf = [np.empty(self.batch_size, np.int32)
                  for _ in range(self.num_buffers)]

    def dispatch(batch_idx: int) -> bool:
      records = list(itertools.islice(stream, self.batch_size))
      if len(records) < self.batch_size:
        return False
      t0 = time.time()
      buf = batch_idx % self.num_buffers
      remaining[buf] = self.batch_size
      # Stage record bytes contiguously into the buffer's input slot;
      # an oversized tail record rides the task message inline.
      cap = in_shape[1]
      off = 0
      entries = []
      for pos, rec in enumerate(records):
        if off + len(rec) <= cap:
          in_ring[buf, off:off + len(rec)] = np.frombuffer(rec, np.uint8)
          entries.append((pos, off, len(rec), None))
          off += len(rec)
        else:
          entries.append((pos, 0, 0, rec))
      # One task message per worker-sized contiguous slice.
      per = -(-self.batch_size // self.num_processes)  # ceil div
      for s in range(0, self.batch_size, per):
        task_q.put((buf, batch_idx, entries[s:s + per]))
      self.dispatch_seconds += time.time() - t0
      self.dispatch_calls += 1
      return True

    def collect(buf: int):
      import queue as queue_lib  # noqa: PLC0415
      while remaining[buf] > 0:
        try:
          b, count, labels, err = done_q.get(timeout=0.5)
        except queue_lib.Empty:
          # A worker killed hard (OOM/segfault in libjpeg) never posts
          # its completion; poll liveness so the trainer fails loudly
          # instead of hanging (same pattern as DeviceFeeder.__next__).
          dead = [w for w in workers if not w.is_alive()]
          if dead:
            raise RuntimeError(
                f"{len(dead)} decode worker(s) died (exitcodes "
                f"{[w.exitcode for w in dead]}) with "
                f"{remaining[buf]} images outstanding")
          continue
        if err is not None:
          pos, msg = err
          raise RuntimeError(f"decode worker failed at buffer {b} "
                             f"position {pos}: {msg}")
        for pos, label in labels:
          labels_buf[b][pos] = label
        remaining[b] -= count

    try:
      if not dispatch(0):
        return
      batch_idx = 0
      while True:
        has_next = dispatch(batch_idx + 1)
        buf = batch_idx % self.num_buffers
        collect(buf)
        # Copy-out keeps the slot reusable regardless of how long the
        # consumer holds the batch (device_put may be asynchronous).
        yield ring[buf].copy(), labels_buf[buf].copy()
        if not has_next:
          return
        batch_idx += 1
    finally:
      for _ in workers:
        task_q.put(None)
      for w in workers:
        w.join(timeout=5)
        if w.is_alive():  # pragma: no cover
          w.terminate()
      task_q.close()
      done_q.close()
      shm.close()
      shm.unlink()
      in_shm.close()
      in_shm.unlink()


class Cifar10ImagePreprocessor(InputPreprocessor):
  """In-memory numpy CIFAR-10 pipeline (ref: preprocessing.py:653-741;
  pickle loading ref: datasets.py:140-189)."""

  def _read_data_files(self, dataset, subset: str) -> Tuple[np.ndarray,
                                                            np.ndarray]:
    if subset == "train":
      names = [f"data_batch_{i}" for i in range(1, 6)]
    else:
      names = ["test_batch"]
    images, labels = [], []
    base = dataset.data_dir
    sub = os.path.join(base, "cifar-10-batches-py")
    if os.path.isdir(sub):
      base = sub
    for name in names:
      with open(os.path.join(base, name), "rb") as f:
        batch = pickle.load(f, encoding="bytes")
      images.append(np.asarray(batch[b"data"], np.uint8))
      labels.append(np.asarray(batch[b"labels"], np.int32))
    # stored CHW row-major; reshape+transpose to HWC
    data = np.concatenate(images).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return data, np.concatenate(labels)

  def _distort(self, image: np.ndarray, rng: random.Random) -> np.ndarray:
    padded = np.zeros((self.height + 8, self.width + 8, self.depth),
                      image.dtype)
    padded[4:4 + self.height, 4:4 + self.width] = image
    y = rng.randint(0, 8)
    x = rng.randint(0, 8)
    out = padded[y:y + self.height, x:x + self.width]
    if rng.random() < 0.5:
      out = out[:, ::-1]
    return out

  def minibatches(self, dataset, subset: str):
    all_images, all_labels = self._read_data_files(dataset, subset)
    n = len(all_images)
    rng = random.Random(self.seed)
    nprng = np.random.RandomState(self.seed)
    while True:
      idx = nprng.randint(0, n, size=self.batch_size) if self.train else None
      if idx is None:
        # sequential epochs for eval
        for start in range(0, n - self.batch_size + 1, self.batch_size):
          sel = np.arange(start, start + self.batch_size)
          imgs = all_images[sel].astype(np.float32)
          yield normalized_image(imgs), all_labels[sel].astype(np.int32)
        continue
      imgs = all_images[idx]
      if self.train and self.distortions:
        imgs = np.stack([self._distort(im, rng) for im in imgs])
      yield (normalized_image(imgs.astype(np.float32)),
             all_labels[idx].astype(np.int32))


class COCOPreprocessor(InputPreprocessor):
  """SSD COCO detection pipeline (ref: preprocessing.py:742-894
  COCOPreprocessor; ssd_dataloader.py:114-254 ssd_crop/color_jitter/
  normalize_image).

  Train batches: (images, (encoded_boxes, classes, num_matched)) -- the
  anchor-space targets the SSD loss consumes (4-tuple, ref :806-811).
  Eval batches: (images, (boxes, classes, source_ids, raw_shapes)) with
  boxes trimmed/padded to MAX_NUM_EVAL_BOXES (5-tuple, ref :813-835).

  Boxes are (ymin, xmin, ymax, xmax) normalized throughout -- the order
  the TF example decoder and our encode_labels use (the reference's
  ssd_crop mixes x-first crop rects with y-first boxes; we keep one
  order).
  """

  @staticmethod
  def parse_coco_example(record: bytes):
    """COCO TF Example -> (image_buffer, boxes ltrb [N,4], classes [N]
    contiguous 1..80, source_id). Raw 90-class COCO category ids map
    through CLASS_MAP (ref: preprocessing.py:786-790)."""
    from kf_benchmarks_tpu.models import ssd_constants
    feats = example_lib.parse_example(record)
    image_buffer = feats["image/encoded"][0]
    def _coords(key):
      v = feats.get(key)
      return (np.asarray(v, np.float32) if v is not None and len(v)
              else np.zeros((0,), np.float32))
    ymin, xmin = _coords("image/object/bbox/ymin"), _coords(
        "image/object/bbox/xmin")
    ymax, xmax = _coords("image/object/bbox/ymax"), _coords(
        "image/object/bbox/xmax")
    boxes = (np.stack([ymin, xmin, ymax, xmax], axis=-1) if len(ymin)
             else np.zeros((0, 4), np.float32))
    raw = feats.get("image/object/class/label")
    raw = np.asarray(raw, np.int64) if raw is not None else np.zeros(
        (0,), np.int64)
    class_map = np.asarray(ssd_constants.CLASS_MAP, np.int32)
    classes = np.where((raw >= 0) & (raw < len(class_map)),
                       class_map[np.clip(raw, 0, len(class_map) - 1)],
                       -1).astype(np.int32)
    keep = classes > 0
    sid = feats.get("image/source_id")
    if sid is not None and len(sid):
      s = sid[0]
      source_id = int(s) if not isinstance(s, bytes) else int(
          s.decode() or 0)
    else:
      source_id = 0
    return image_buffer, boxes[keep], classes[keep], source_id

  def _ssd_crop(self, rng: "np.random.RandomState", boxes: np.ndarray):
    """IoU-biased random crop sampling (ref: ssd_dataloader.py:114-227
    ssd_crop). Returns (crop ltrb, box mask) in normalized coords.

    Per pass: with P_NO_CROP probability keep the whole image; otherwise
    draw NUM_CROP_PASSES candidate rects (side in [0.3,1], aspect < 2),
    require every gt box's IoU with the rect above a randomly drawn
    threshold and at least one box center inside; take the highest-index
    valid candidate (the reference's max-index selection). Repeat until
    a crop is accepted (bounded here; whole image on exhaustion)."""
    from kf_benchmarks_tpu.models import ssd_constants
    whole = np.array([0.0, 0.0, 1.0, 1.0], np.float32)
    all_mask = np.ones((len(boxes),), bool)
    for _ in range(100):
      if rng.uniform() < ssd_constants.P_NO_CROP_PER_PASS:
        return whole, all_mask
      n = ssd_constants.NUM_CROP_PASSES
      h = rng.uniform(0.3, 1.0, size=n)
      w = rng.uniform(0.3, 1.0, size=n)
      top = rng.uniform(0, 1, size=n) * (1 - h)
      left = rng.uniform(0, 1, size=n) * (1 - w)
      rects = np.stack([top, left, top + h, left + w], axis=1)
      min_iou = ssd_constants.CROP_MIN_IOU_CHOICES[
          rng.randint(len(ssd_constants.CROP_MIN_IOU_CHOICES))]
      from kf_benchmarks_tpu.models import ssd_dataloader
      ious = ssd_dataloader.calc_iou_matrix(rects.astype(np.float32),
                                            boxes)
      yc = 0.5 * (boxes[:, 0] + boxes[:, 2])
      xc = 0.5 * (boxes[:, 1] + boxes[:, 3])
      centers_in = ((yc[None, :] > rects[:, 0:1]) &
                    (yc[None, :] < rects[:, 2:3]) &
                    (xc[None, :] > rects[:, 1:2]) &
                    (xc[None, :] < rects[:, 3:4]))
      valid_aspect = (h / w < 2) & (w / h < 2)
      valid = (valid_aspect & np.all(ious > min_iou, axis=1) &
               np.any(centers_in, axis=1))
      if np.any(valid):
        i = int(np.max(np.nonzero(valid)[0]))
        return rects[i].astype(np.float32), centers_in[i]
    return whole, all_mask

  def _color_jitter(self, img: "Image.Image",
                    rng: "np.random.RandomState") -> "Image.Image":
    """brightness=0.125, contrast=0.5, saturation=0.5, hue=0.05
    (ref: ssd_dataloader.py:230-243 color_jitter)."""
    img = ImageEnhance.Brightness(img).enhance(
        1.0 + rng.uniform(-0.125, 0.125))
    img = ImageEnhance.Contrast(img).enhance(rng.uniform(0.5, 1.5))
    img = ImageEnhance.Color(img).enhance(rng.uniform(0.5, 1.5))
    # Hue shift +/-0.05 of the hue circle, via the HSV plane.
    hsv = np.asarray(img.convert("HSV"), np.int16)
    hsv[..., 0] = (hsv[..., 0] +
                   int(rng.uniform(-0.05, 0.05) * 255)) % 256
    return Image.fromarray(hsv.astype(np.uint8), "HSV").convert("RGB")

  def _normalize(self, arr: np.ndarray) -> np.ndarray:
    """[0,255] uint8 -> zero-mean unit-var float32 per ImageNet stats
    (ref: ssd_dataloader.py:246-254 normalize_image)."""
    from kf_benchmarks_tpu.models import ssd_constants
    arr = arr.astype(np.float32) / 255.0
    mean = np.asarray(ssd_constants.NORMALIZATION_MEAN, np.float32)
    std = np.asarray(ssd_constants.NORMALIZATION_STD, np.float32)
    return (arr - mean) / std

  def _preprocess_train(self, parsed, rng: "np.random.RandomState"):
    from kf_benchmarks_tpu.models import ssd_dataloader
    image_buffer, boxes, classes, _ = parsed
    img = Image.open(io.BytesIO(image_buffer)).convert("RGB")
    crop, mask = self._ssd_crop(rng, boxes)
    iw, ih = img.size
    y0, x0, y1, x1 = crop
    img = img.crop((int(x0 * iw), int(y0 * ih),
                    max(int(x1 * iw), int(x0 * iw) + 1),
                    max(int(y1 * ih), int(y0 * ih) + 1)))
    img = img.resize((self.width, self.height), Image.BILINEAR)
    boxes, classes = boxes[mask], classes[mask]
    # Clip surviving boxes to the crop and renormalize to crop coords.
    ch, cw = max(y1 - y0, 1e-6), max(x1 - x0, 1e-6)
    boxes = np.stack([
        (np.clip(boxes[:, 0], y0, y1) - y0) / ch,
        (np.clip(boxes[:, 1], x0, x1) - x0) / cw,
        (np.clip(boxes[:, 2], y0, y1) - y0) / ch,
        (np.clip(boxes[:, 3], x0, x1) - x0) / cw,
    ], axis=1) if len(boxes) else boxes
    if rng.uniform() < 0.5:  # random_horizontal_flip (image + boxes)
      img = img.transpose(Image.FLIP_LEFT_RIGHT)
      if len(boxes):
        boxes = np.stack([boxes[:, 0], 1.0 - boxes[:, 3],
                          boxes[:, 2], 1.0 - boxes[:, 1]], axis=1)
    if self.distortions:
      img = self._color_jitter(img, rng)
    image = self._normalize(np.asarray(img, np.uint8))
    encoded, enc_classes, num_matched = ssd_dataloader.encode_labels(
        boxes.astype(np.float32), classes)
    return image, encoded, enc_classes, np.float32(num_matched)

  def _preprocess_eval(self, parsed):
    from kf_benchmarks_tpu.models import ssd_constants
    image_buffer, boxes, classes, source_id = parsed
    img = Image.open(io.BytesIO(image_buffer)).convert("RGB")
    iw, ih = img.size
    img = img.resize((self.width, self.height), Image.BILINEAR)
    image = self._normalize(np.asarray(img, np.uint8))
    m = ssd_constants.MAX_NUM_EVAL_BOXES

    def trim_and_pad(arr, width):
      arr = arr[:m]
      out = np.zeros((m, width), np.float32)
      if len(arr):
        out[:len(arr)] = arr.reshape(len(arr), width)
      return out

    return (image, trim_and_pad(boxes, 4),
            trim_and_pad(classes.astype(np.float32), 1),
            np.int32(source_id), np.asarray([ih, iw, 3], np.int32))

  def minibatches(self, dataset, subset: str):
    if not _HAVE_PIL:  # pragma: no cover
      raise NotImplementedError("PIL is required for the COCO pipeline")
    stream = self._record_stream(dataset, subset)
    pool = concurrent.futures.ThreadPoolExecutor(self.num_threads)
    rngs = [np.random.RandomState(self.seed + 7919 * i)
            for i in range(self.batch_size)]
    try:
      exhausted = False
      while not exhausted:
        batch_parsed = []
        for record in stream:
          parsed = self.parse_coco_example(record)
          # Training filters examples with no ground-truth boxes
          # (ref :887-888); eval keeps them -- their ground truth is
          # empty, but dropping images would bias mAP's recall
          # denominator (every val image must be scored).
          if self.train and not len(parsed[1]):
            continue
          batch_parsed.append(parsed)
          if len(batch_parsed) == self.batch_size:
            break
        if len(batch_parsed) < self.batch_size:
          exhausted = True  # eval: still yield the final partial batch
          if not batch_parsed:
            return
        if self.train:
          futs = [pool.submit(self._preprocess_train, parsed, rngs[i])
                  for i, parsed in enumerate(batch_parsed)]
          results = [f.result() for f in futs]
          images = np.stack([r[0] for r in results])
          boxes = np.stack([r[1] for r in results])
          classes = np.stack([r[2] for r in results])
          num_matched = np.asarray([r[3] for r in results], np.float32)
          yield images, (boxes, classes, num_matched)
        else:
          futs = [pool.submit(self._preprocess_eval, parsed)
                  for parsed in batch_parsed]
          results = [f.result() for f in futs]
          yield (np.stack([r[0] for r in results]),
                 (np.stack([r[1] for r in results]),
                  np.stack([r[2] for r in results]),
                  np.asarray([r[3] for r in results], np.int32),
                  np.stack([r[4] for r in results])))
    finally:
      pool.shutdown(wait=False)


class LibrispeechPreprocessor(InputPreprocessor):
  """Librispeech speech pipeline (ref: preprocessing.py:977-1112
  LibrispeechPreprocessor).

  Records are SequenceExample protos carrying precomputed spectrogram
  features (sequence feature 'features', [T, 161] float32 frames) plus
  context 'labels' (varlen int64), 'input_length', 'label_length' --
  exactly what the reference parses with parse_single_sequence_example
  (:1081-1112). The reference pads per-batch via padded_batch (dynamic
  shapes); XLA needs static shapes, so every utterance pads to the
  model's max_time_steps/max_label_length (over-long utterances truncate
  and clamp their lengths) -- the static-shape analog of its bucketing.

  Batches: (spectrogram [n, max_T, bins, 1],
            (labels [n, max_label], input_lengths [n], label_lengths [n])).
  """

  def __init__(self, *args, max_label_length: int = 576, **kwargs):
    super().__init__(*args, **kwargs)
    # output_shape carries the model's (max_time_steps, num_bins, 1).
    self.max_time_steps = self.height
    self.num_feature_bins = self.width
    self.max_label_length = max_label_length

  def _parse_utterance(self, record: bytes):
    context, seqs = example_lib.parse_sequence_example(record)
    frames = seqs.get("features", [])
    feats = (np.stack([np.asarray(f, np.float32) for f in frames])
             if frames else np.zeros((0, self.num_feature_bins),
                                     np.float32))
    labels = np.asarray(context.get("labels", []), np.int64)
    t = min(len(feats), self.max_time_steps)
    l = min(len(labels), self.max_label_length)
    spec = np.zeros((self.max_time_steps, self.num_feature_bins, 1),
                    np.float32)
    spec[:t, :, 0] = feats[:t, :self.num_feature_bins]
    lab = np.zeros((self.max_label_length,), np.int32)
    lab[:l] = labels[:l]
    return spec, lab, np.int32(t), np.int32(l)

  def minibatches(self, dataset, subset: str):
    stream = self._record_stream(dataset, subset)
    pool = concurrent.futures.ThreadPoolExecutor(self.num_threads)
    try:
      while True:
        records = []
        for record in stream:
          records.append(record)
          if len(records) == self.batch_size:
            break
        if len(records) < self.batch_size:
          return
        futs = [pool.submit(self._parse_utterance, rec)
                for rec in records]
        results = [f.result() for f in futs]
        yield (np.stack([r[0] for r in results]),
               (np.stack([r[1] for r in results]),
                np.asarray([r[2] for r in results], np.int32),
                np.asarray([r[3] for r in results], np.int32)))
    finally:
      pool.shutdown(wait=False)


class TestImagePreprocessor(InputPreprocessor):
  """Injects fake numpy data as "real" input (ref:
  preprocessing.py:896-975). ``set_fake_data`` then iterate."""

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self.fake_images: Optional[np.ndarray] = None
    self.fake_labels: Optional[np.ndarray] = None
    self.expected_subset: Optional[str] = None

  def set_fake_data(self, images: np.ndarray, labels: np.ndarray) -> None:
    self.fake_images = np.asarray(images)
    self.fake_labels = np.asarray(labels)

  def minibatches(self, dataset, subset: str):
    del dataset
    if self.expected_subset is not None:
      assert subset == self.expected_subset, (subset, self.expected_subset)
    assert self.fake_images is not None, "call set_fake_data first"
    n = len(self.fake_images)
    pos = 0
    while True:
      sel = [(pos + i) % n for i in range(self.batch_size)]
      pos = (pos + self.batch_size) % n
      yield (self.fake_images[sel].astype(np.float32),
             self.fake_labels[sel].astype(np.int32))


_PREPROCESSORS = {
    "imagenet": RecordInputImagePreprocessor,
    "cifar10": Cifar10ImagePreprocessor,
    "coco": COCOPreprocessor,
    "librispeech": LibrispeechPreprocessor,
    "test": TestImagePreprocessor,
}


def get_preprocessor(dataset_name: str, kind: str = "default"):
  """Name -> preprocessor class (ref: datasets.py:208-229 maps)."""
  if kind == "test":
    return TestImagePreprocessor
  if kind == "official_models_imagenet":
    # (ref: the imagenet map's second entry, datasets.py:208-229 +
    # preprocessing.py:635-652)
    if dataset_name != "imagenet":
      raise ValueError("official_models_imagenet preprocessing applies "
                       f"to the imagenet dataset, not {dataset_name!r}")
    return OfficialImagenetPreprocessor
  if kind == "multiprocess":
    # Process-parallel decode (the RecordInput/tf.data C++-threadpool
    # throughput analog) for multi-core hosts.
    if dataset_name != "imagenet":
      raise ValueError("multiprocess preprocessing applies to the "
                       f"imagenet dataset, not {dataset_name!r}")
    return MultiprocessImagePreprocessor
  if kind != "default":
    raise ValueError(f"Unknown input preprocessor {kind!r}; expected "
                     "'default', 'official_models_imagenet', "
                     "'multiprocess', or 'test'")
  if dataset_name not in _PREPROCESSORS:
    raise NotImplementedError(
        f"No input preprocessor for dataset {dataset_name!r}")
  return _PREPROCESSORS[dataset_name]
