"""Double-buffered host->device input feed.

The analog of the reference's per-device StagingArea / MultiDeviceIterator
prefetch chain (ref: scripts/tf_cnn_benchmarks/benchmark_cnn.py:2572-2600
CPU staging, :2993-3006 gpu_compute_stage H2D boundary;
preprocessing.py:368-399 MultiDeviceIterator): a background thread pulls
host batches from the preprocessor iterator and ``jax.device_put``s them
onto the global batch sharding ahead of the step loop, so the H2D copy
overlaps the previous step's compute.

Chunk mode (--steps_per_dispatch=K): ``chunk=K`` makes the worker stage K
host batches at a time -- stacked on a new leading axis host-side and
transferred as ONE (K, batch, ...) array onto the chunk sharding -- so a
K-step scanned dispatch finds its whole input staged and never waits on
H2D mid-scan. The queue then counts chunks (``prefetch`` stays in
batches and is rounded up to whole chunks), keeping roughly the same
number of batches in flight as the unchunked feed. A stream that ends
mid-chunk yields a final partial stack (leading axis < K); the consumer
runs those through the single-step program.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from kf_benchmarks_tpu import metrics as metrics_lib
from kf_benchmarks_tpu import tracing
from kf_benchmarks_tpu.parallel import mesh as mesh_lib


class DeviceFeeder:
  """Prefetching device-transfer iterator (depth-``prefetch`` pipeline).

  Instrumented: every ``__next__`` records the consumer's blocked-wait
  time and the queue depth it found, so ``stats()`` can answer the
  question the reference never measured about its StagingArea chain --
  does the prefetch actually OVERLAP host work with device compute?
  ``feed_stall_fraction`` (consumer wait / wall time across the consume
  window) ~0 means the feed hides behind the step; ~1 means the loop is
  input-bound and ``--input_prefetch_depth`` (or more host threads) is
  the lever. Rides the benchmark stats and the bench JSON line.
  """

  def __init__(self, host_iterator: Iterator, sharding,
               prefetch: int = 2, chunk: int = 1):
    self._host_iterator = host_iterator
    self._sharding = sharding
    self._chunk = max(1, chunk)
    self.prefetch_batches = max(1, prefetch)
    depth = -(-self.prefetch_batches // self._chunk)  # batches -> chunks
    self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    self._stop = threading.Event()
    self._error: Optional[BaseException] = None
    # Consumer-side instrumentation (all under the consumer thread; no
    # locking needed -- __next__ is single-consumer by contract).
    self._wait_s = 0.0
    self._fetches = 0
    self._depth_sum = 0
    self._depth_max = 0
    self._window_start: Optional[float] = None
    self._window_end: Optional[float] = None
    self._thread = threading.Thread(target=self._worker, daemon=True,
                                    name="device-feeder")
    self._thread.start()

  def _pull(self, it):
    """Next host item: one batch, or a chunk of up to ``chunk`` batches
    stacked on a new leading axis. None at stream end."""
    if self._chunk == 1:
      try:
        return next(it)
      except StopIteration:
        return None
    batches = []
    while len(batches) < self._chunk and not self._stop.is_set():
      try:
        batches.append(next(it))
      except StopIteration:
        break
    if not batches:
      return None
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)

  def _worker(self) -> None:
    try:
      it = iter(self._host_iterator)
      # Check the stop flag BEFORE pulling: pulling is where the host
      # preprocessing work happens, so a stopped feeder must not decode
      # another full global batch just to discard it.
      while not self._stop.is_set():
        # Run-trace feed lane (tracing.py active session; no-op sink
        # otherwise): "fetch" is the host preprocessing pull, "h2d" the
        # device_put -- the producer half of the overlap question
        # stats() answers from the consumer side.
        trace = tracing.active()
        t0 = trace.now()
        batch = self._pull(it)
        if batch is None:
          break
        trace.add_span("feed", "fetch", t0, trace.now() - t0,
                       {"chunk": self._chunk})
        t1 = trace.now()
        device_batch = mesh_lib.put_batch(batch, self._sharding)
        trace.add_span("feed", "h2d", t1, trace.now() - t1)
        while not self._stop.is_set():
          try:
            self._queue.put(device_batch, timeout=0.5)
            break
          except queue.Full:
            continue
      if not self._stop.is_set():
        self._queue.put(None)
    except BaseException as e:  # surfaced on the consumer side
      self._error = e

  def __iter__(self):
    return self

  def __next__(self):
    t0 = time.monotonic()
    # The span anchor reads the TRACE clock (injectable; mixing it with
    # raw monotonic would skew fake-clock tests, tracing.RunTrace.now);
    # the stats/sample below keep the real monotonic measurement.
    trace = tracing.active()
    t0_trace = trace.now()
    if self._window_start is None:
      self._window_start = t0
    depth = self._queue.qsize()
    # Poll with a timeout so a worker error is surfaced even when the
    # queue is full at error time and the sentinel could not be enqueued.
    while True:
      try:
        item = self._queue.get(timeout=0.5)
        break
      except queue.Empty:
        if self._error is not None:
          raise self._error
        if not self._thread.is_alive():
          raise StopIteration
    if item is None:
      # End-of-stream sentinel: not a delivered batch -- counting its
      # (terminal-drain) wait would read a healthy finite stream as
      # input-bound.
      if self._error is not None:
        raise self._error
      raise StopIteration
    now = time.monotonic()
    waited = now - t0
    self._wait_s += waited
    self._window_end = now
    self._fetches += 1
    # Consumer-wait lane + percentile sample (tracing.py): every fetch
    # feeds the feed_wait p50/p90/p99, and a traced run shows each wait
    # as a span (bracketed on the trace clock captured at entry).
    trace.add_span("feed", "wait", t0_trace, trace.now() - t0_trace,
                   {"queue_depth": depth * self._chunk})
    trace.add_sample("feed_wait", waited)
    # Live metric lanes (metrics.py active registry; no-op sink when no
    # endpoint/registry session is active): the /metrics scrape shows
    # queue depth and the feed-wait distribution WHILE the run feeds,
    # not just the run-end stats() aggregate.
    registry = metrics_lib.active()
    registry.inc("fetches")
    registry.set("queue_depth", depth * self._chunk)
    registry.observe("feed_wait_s", waited)
    # Queue depth in BATCH units (the queue itself holds chunks when
    # chunk > 1), so the number reads against prefetch_batches.
    self._depth_sum += depth * self._chunk
    self._depth_max = max(self._depth_max, depth * self._chunk)
    return item

  def stats(self) -> dict:
    """Consumer-side feed stats: total blocked wait, the wall window
    spanning the fetches, the stall fraction (wait / window -- the
    fraction of loop wall the feed failed to hide), and queue depth at
    fetch time (mean/max; depth ~prefetch means the worker keeps up).
    The first fetch's wait covers pipeline warm-fill and is counted --
    report stats over a run long enough to amortize it."""
    window = ((self._window_end - self._window_start)
              if self._fetches and self._window_end is not None else 0.0)
    return {
        "fetches": self._fetches,
        "consumer_wait_s": self._wait_s,
        "window_s": window,
        "feed_stall_fraction": (self._wait_s / window if window > 0
                                else None),
        "queue_depth_mean": (self._depth_sum / self._fetches
                             if self._fetches else None),
        "queue_depth_max": self._depth_max,
        "prefetch_batches": self.prefetch_batches,
    }

  def stop(self) -> None:
    self._stop.set()
    # Drain so the worker unblocks, then join it and close the host
    # iterator so generator cleanup (e.g. the preprocessor's thread pool
    # shutdown in its finally block) runs deterministically rather than
    # at GC time.
    while self._thread.is_alive():
      try:
        while True:
          self._queue.get_nowait()
      except queue.Empty:
        pass
      self._thread.join(timeout=0.1)
    close = getattr(self._host_iterator, "close", None)
    if close is not None:
      close()
