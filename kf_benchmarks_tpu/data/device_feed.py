"""Double-buffered host->device input feed.

The analog of the reference's per-device StagingArea / MultiDeviceIterator
prefetch chain (ref: scripts/tf_cnn_benchmarks/benchmark_cnn.py:2572-2600
CPU staging, :2993-3006 gpu_compute_stage H2D boundary;
preprocessing.py:368-399 MultiDeviceIterator): a background thread pulls
host batches from the preprocessor iterator and ``jax.device_put``s them
onto the global batch sharding ahead of the step loop, so the H2D copy
overlaps the previous step's compute.

Chunk mode (--steps_per_dispatch=K): ``chunk=K`` makes the worker stage K
host batches at a time -- stacked on a new leading axis host-side and
transferred as ONE (K, batch, ...) array onto the chunk sharding -- so a
K-step scanned dispatch finds its whole input staged and never waits on
H2D mid-scan. The queue then counts chunks (``prefetch`` stays in
batches and is rounded up to whole chunks), keeping roughly the same
number of batches in flight as the unchunked feed. A stream that ends
mid-chunk yields a final partial stack (leading axis < K); the consumer
runs those through the single-step program.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from kf_benchmarks_tpu.parallel import mesh as mesh_lib


class DeviceFeeder:
  """Prefetching device-transfer iterator (depth-``prefetch`` pipeline)."""

  def __init__(self, host_iterator: Iterator, sharding,
               prefetch: int = 2, chunk: int = 1):
    self._host_iterator = host_iterator
    self._sharding = sharding
    self._chunk = max(1, chunk)
    depth = -(-max(1, prefetch) // self._chunk)  # batches -> whole chunks
    self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    self._stop = threading.Event()
    self._error: Optional[BaseException] = None
    self._thread = threading.Thread(target=self._worker, daemon=True,
                                    name="device-feeder")
    self._thread.start()

  def _pull(self, it):
    """Next host item: one batch, or a chunk of up to ``chunk`` batches
    stacked on a new leading axis. None at stream end."""
    if self._chunk == 1:
      try:
        return next(it)
      except StopIteration:
        return None
    batches = []
    while len(batches) < self._chunk and not self._stop.is_set():
      try:
        batches.append(next(it))
      except StopIteration:
        break
    if not batches:
      return None
    return jax.tree.map(lambda *xs: np.stack(xs), *batches)

  def _worker(self) -> None:
    try:
      it = iter(self._host_iterator)
      # Check the stop flag BEFORE pulling: pulling is where the host
      # preprocessing work happens, so a stopped feeder must not decode
      # another full global batch just to discard it.
      while not self._stop.is_set():
        batch = self._pull(it)
        if batch is None:
          break
        device_batch = mesh_lib.put_batch(batch, self._sharding)
        while not self._stop.is_set():
          try:
            self._queue.put(device_batch, timeout=0.5)
            break
          except queue.Full:
            continue
      if not self._stop.is_set():
        self._queue.put(None)
    except BaseException as e:  # surfaced on the consumer side
      self._error = e

  def __iter__(self):
    return self

  def __next__(self):
    # Poll with a timeout so a worker error is surfaced even when the
    # queue is full at error time and the sentinel could not be enqueued.
    while True:
      try:
        item = self._queue.get(timeout=0.5)
        break
      except queue.Empty:
        if self._error is not None:
          raise self._error
        if not self._thread.is_alive():
          raise StopIteration
    if item is None:
      if self._error is not None:
        raise self._error
      raise StopIteration
    return item

  def stop(self) -> None:
    self._stop.set()
    # Drain so the worker unblocks, then join it and close the host
    # iterator so generator cleanup (e.g. the preprocessor's thread pool
    # shutdown in its finally block) runs deterministically rather than
    # at GC time.
    while self._thread.is_alive():
      try:
        while True:
          self._queue.get_nowait()
      except queue.Empty:
        pass
      self._thread.join(timeout=0.1)
    close = getattr(self._host_iterator, "close", None)
    if close is not None:
      close()
