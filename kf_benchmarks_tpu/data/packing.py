"""Variable-length sequence packing for the transformer_lm input pipeline.

BEYOND-REFERENCE: the reference's input story is fixed-shape images
through the StagingArea / MultiDeviceIterator prefetch chain (ref:
benchmark_cnn.py:2572-2600, preprocessing.py:368-399) and its only
variable-length machinery is DeepSpeech2 utterance padding (ref:
preprocessing.py:977-1112) -- every slot is either full or padded.
LM pretraining traffic is variable-length documents at a fixed context
(2048 here), where padding waste is a direct multiplier on useful
tokens/s; the standard input form is BIN-PACKED documents with segment
ids (T5 / GPT-NeoX style packing), which this module provides as the
host-side half of ``--packed_sequences``:

* ``PackedBatchStream`` -- an infinite, seeded host iterator yielding
  ``(images, labels)`` batches where ``images`` is the ``(B, 3, T)``
  int32 stack of ``[tokens, segment_ids, positions]`` and ``labels``
  the in-document next-token ids. Document lengths draw from a clipped
  lognormal (the realistic heavy-tailed doc-length shape); packing is
  deterministic FIRST-FIT over a bounded lookahead window, so the same
  seed always produces the same batches (the A/B and resume contract).
* Conventions: ``segment_ids`` are 1-based per row in placement order
  with 0 = padding; documents are never split across rows or batches;
  ``positions`` restart at 0 at each document start (the position
  embedding is per-document, so a packed document computes exactly what
  it would alone); padding sits at the row tail only.
* ``token_weights_from_segments`` -- the ONE derivation of the
  per-token loss weights (1.0 where a token has an in-document
  next-token label, 0.0 at padding and each document's final slot),
  shared by the model's loss/metrics and the train step's
  token-weighted metric combine so the two cannot drift.

The device-side halves are the segment-aware masks in
``parallel/sequence.py`` and the weighted chunked loss in
``ops/fused_loss.py``.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

# First-fit lookahead bound: how many pending documents the packer may
# scan past the head-of-line to fill a row. Bounded so host latency per
# batch stays O(B * lookahead) and the stream order stays near-FIFO.
DEFAULT_LOOKAHEAD = 64

# Clipped-lognormal document-length distribution defaults: median well
# under the context so rows hold several documents (the regime where
# packing pays), sigma wide enough for a realistic heavy tail.
DEFAULT_MEAN_FRACTION = 0.4
DEFAULT_SIGMA = 0.8


def token_weights_from_segments(segment_ids):
  """Per-token loss weights from packed segment ids: 1.0 where the slot
  holds a real token whose NEXT slot continues the same document (i.e.
  the position has an in-document next-token label), else 0.0 --
  padding (id 0), each document's final token, and the row's last slot
  all weigh 0. Works on numpy or jax arrays of shape (..., T)."""
  if isinstance(segment_ids, np.ndarray):
    xp = np
  else:
    import jax.numpy as xp  # jnp inside jit; numpy for host-side tests
  seg = segment_ids
  nxt = xp.concatenate(
      [seg[..., 1:], xp.zeros_like(seg[..., :1])], axis=-1)
  return ((seg != 0) & (nxt == seg)).astype(xp.float32)


def packing_efficiency(segment_ids) -> float:
  """Fraction of slots holding real tokens (padding excluded)."""
  seg = np.asarray(segment_ids)
  return float(np.count_nonzero(seg)) / float(max(seg.size, 1))


def sample_document_lengths(rng: np.random.Generator, n: int,
                            seq_len: int,
                            mean_fraction: float = DEFAULT_MEAN_FRACTION,
                            sigma: float = DEFAULT_SIGMA) -> np.ndarray:
  """``n`` document lengths from a lognormal with median
  ``mean_fraction * seq_len``, clipped to [1, seq_len] -- clipping (not
  rejection) keeps the draw count deterministic, and the packer's
  no-split contract needs every document to fit one row."""
  mu = np.log(max(mean_fraction * seq_len, 1.0))
  lengths = np.exp(rng.normal(mu, sigma, size=n))
  return np.clip(lengths.astype(np.int64), 1, seq_len)


class PackedBatch(collections.abc.Sequence):
  """One packed batch: ``images`` (B, 3, T) int32 [tokens, segment_ids,
  positions] and ``labels`` (B, T) int32 in-document next-token ids
  (0 where no in-document label exists; those slots weigh 0). Sequence
  protocol yields (images, labels) so callers can tuple-unpack."""

  def __init__(self, images: np.ndarray, labels: np.ndarray):
    self.images = images
    self.labels = labels

  @property
  def tokens(self):
    return self.images[:, 0]

  @property
  def segment_ids(self):
    return self.images[:, 1]

  @property
  def positions(self):
    return self.images[:, 2]

  def __len__(self):
    return 2

  def __getitem__(self, i):
    return (self.images, self.labels)[i]


def _materialize(rows: List[List[np.ndarray]], batch_size: int,
                 seq_len: int) -> PackedBatch:
  images = np.zeros((batch_size, 3, seq_len), np.int32)
  labels = np.zeros((batch_size, seq_len), np.int32)
  for r, docs in enumerate(rows):
    off = 0
    for s, doc in enumerate(docs, start=1):
      ln = len(doc)
      images[r, 0, off:off + ln] = doc
      images[r, 1, off:off + ln] = s
      images[r, 2, off:off + ln] = np.arange(ln)
      labels[r, off:off + ln - 1] = doc[1:]
      off += ln
  return PackedBatch(images, labels)


def pack_documents(docs: Iterable[np.ndarray], seq_len: int,
                   batch_size: int,
                   lookahead: int = DEFAULT_LOOKAHEAD
                   ) -> Iterator[PackedBatch]:
  """Deterministic first-fit packing of a document stream into
  ``(batch_size, seq_len)`` rows.

  For each batch: scan the bounded lookahead window in stream order and
  place the first document that fits into the first row with room
  (opening rows up to ``batch_size``); repeat until nothing in the
  window fits, then emit. Documents are never split; a document longer
  than ``seq_len`` raises. The final batch may be partial (trailing
  all-padding rows) but always carries the full static shape.
  """
  if lookahead < 1:
    raise ValueError(f"lookahead must be >= 1, got {lookahead}")
  it = iter(docs)
  window: collections.deque = collections.deque()
  exhausted = False

  def refill():
    nonlocal exhausted
    while not exhausted and len(window) < lookahead:
      try:
        doc = np.asarray(next(it))
      except StopIteration:
        exhausted = True
        return
      if doc.ndim != 1 or doc.size < 1:
        raise ValueError("documents must be non-empty 1-D token arrays")
      if doc.size > seq_len:
        raise ValueError(
            f"document of {doc.size} tokens exceeds the {seq_len}-token "
            "context; the packer never splits documents")
      window.append(doc)

  refill()
  while window:
    rows: List[List[np.ndarray]] = []
    remaining: List[int] = []
    while True:
      refill()
      placed = False
      for w_idx, doc in enumerate(window):
        row = next((r for r in range(len(rows))
                    if remaining[r] >= doc.size), None)
        if row is None and len(rows) < batch_size:
          rows.append([])
          remaining.append(seq_len)
          row = len(rows) - 1
        if row is not None:
          rows[row].append(doc)
          remaining[row] -= doc.size
          del window[w_idx]
          placed = True
          break
      if not placed:
        break
    yield _materialize(rows, batch_size, seq_len)


def pack_prompts(prompts: Sequence[np.ndarray], seq_len: int,
                 batch_size: int):
  """First-fit placement of serving prompts into ONE packed batch,
  reporting where each prompt landed.

  The serving engine's prefill half (serving/decode.py): mixed-length
  prompts pack into a single ``(batch_size, 3, seq_len)`` stack --
  same layout and conventions as :func:`pack_documents` (1-based
  segment ids in placement order, per-document positions restarting at
  0, padding at the row tail) -- so they all prefill in ONE dispatch,
  and the engine can slice each prompt's K/V span back out of the
  packed forward. Returns ``(images, placements)`` where
  ``placements[i]`` is ``(row, offset)`` for prompt ``i``, or ``None``
  when it did not fit this batch (the engine re-queues those). Rows
  are filled greedily in prompt order; a prompt longer than
  ``seq_len`` raises (documents are never split).
  """
  rows: List[List[int]] = []          # prompt indices per row
  offsets: List[Optional[tuple]] = [None] * len(prompts)
  remaining: List[int] = []
  for i, doc in enumerate(prompts):
    doc = np.asarray(doc)
    if doc.ndim != 1 or doc.size < 1:
      raise ValueError("prompts must be non-empty 1-D token arrays")
    if doc.size > seq_len:
      raise ValueError(
          f"prompt of {doc.size} tokens exceeds the {seq_len}-token "
          "context; prompts are never split")
    row = next((r for r in range(len(rows))
                if remaining[r] >= doc.size), None)
    if row is None:
      if len(rows) >= batch_size:
        continue  # does not fit this batch; placement stays None
      rows.append([])
      remaining.append(seq_len)
      row = len(rows) - 1
    offsets[i] = (row, seq_len - remaining[row])
    rows[row].append(i)
    remaining[row] -= doc.size
  batch = _materialize(
      [[np.asarray(prompts[i]) for i in docs] for docs in rows],
      batch_size, seq_len)
  return batch.images, offsets


class PackedBatchStream:
  """Infinite seeded packed-batch iterator (the host half of
  ``--packed_sequences``): documents of random tokens with lognormal
  lengths, first-fit packed, yielding ``(images, labels)`` tuples the
  ``DeviceFeeder`` stages like any host pipeline.

  ``one_per_row=True`` is the A/B baseline: each row holds ONE document
  padded to the context (the naive variable-length feed), so the
  packed-vs-padded useful-tokens/s ratio isolates exactly what packing
  buys (experiments/packing_probe.py).

  ``stats()`` reports cumulative documents/real-token counts and the
  measured packing efficiency the observability feed line prints.
  """

  def __init__(self, seq_len: int, batch_size: int, vocab: int,
               seed: int = 0, lookahead: int = DEFAULT_LOOKAHEAD,
               mean_fraction: float = DEFAULT_MEAN_FRACTION,
               sigma: float = DEFAULT_SIGMA,
               one_per_row: bool = False):
    self.seq_len = seq_len
    self.batch_size = batch_size
    self.vocab = vocab
    self._rng = np.random.default_rng(seed)
    self._mean_fraction = mean_fraction
    self._sigma = sigma
    self._documents = 0
    self._real_tokens = 0
    self._slots = 0
    if one_per_row:
      self._batches = map(
          lambda docs: _materialize([[d] for d in docs], batch_size,
                                    seq_len),
          self._doc_groups(batch_size))
    else:
      self._batches = pack_documents(self._docs(), seq_len, batch_size,
                                     lookahead=lookahead)

  def _docs(self) -> Iterator[np.ndarray]:
    while True:
      ln = int(sample_document_lengths(
          self._rng, 1, self.seq_len, self._mean_fraction,
          self._sigma)[0])
      yield self._rng.integers(0, self.vocab, size=ln, dtype=np.int32)

  def _doc_groups(self, n: int) -> Iterator[List[np.ndarray]]:
    docs = self._docs()
    while True:
      yield [next(docs) for _ in range(n)]

  def __iter__(self):
    return self

  def __next__(self):
    batch = next(self._batches)
    self._real_tokens += int(np.count_nonzero(batch.segment_ids))
    self._slots += batch.segment_ids.size
    # Documents counted at EMIT time (segment ids are dense 1..S per
    # row, so per-row max = the row's document count); counting at
    # draw time would overstate by the packer's buffered lookahead.
    self._documents += int(batch.segment_ids.max(axis=1).sum())
    return batch.images, batch.labels

  def stats(self) -> dict:
    return {
        "documents": self._documents,
        "real_tokens": self._real_tokens,
        "token_slots": self._slots,
        "packing_efficiency": (self._real_tokens / self._slots
                               if self._slots else None),
    }
