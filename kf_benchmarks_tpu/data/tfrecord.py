"""Pure-Python TFRecord file codec.

The reference reads ImageNet as TFRecord shards via tf.data /
``data_flow_ops.RecordInput`` (ref: scripts/tf_cnn_benchmarks/
preprocessing.py:601-617, datasets.py:124-137). This image has no
TensorFlow, so the framework carries its own reader/writer for the TFRecord
wire format, which is simply a sequence of:

    uint64 length (little-endian)
    uint32 masked_crc32c(length_bytes)
    byte   data[length]
    uint32 masked_crc32c(data)

CRC32C uses the Castagnoli polynomial with TFRecord's mask
(((crc >> 15) | (crc << 17)) + 0xa282ead8). Verification is optional on
read (off by default in the hot path; the step loop is device-bound and
the reference's RecordInput does not re-verify either).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Sequence

import numpy as np

_CRC_TABLE: Optional[np.ndarray] = None
_MASK_DELTA = 0xA282EAD8


def _crc_table() -> np.ndarray:
  global _CRC_TABLE
  if _CRC_TABLE is None:
    poly = 0x82F63B78  # reversed Castagnoli
    table = np.empty(256, dtype=np.uint32)
    for i in range(256):
      crc = i
      for _ in range(8):
        crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
      table[i] = crc
    _CRC_TABLE = table
  return _CRC_TABLE


def crc32c(data: bytes) -> int:
  table = _crc_table()
  buf = np.frombuffer(data, dtype=np.uint8)
  # Table-driven, byte at a time, vectorized over nothing -- fine for the
  # record sizes involved (headers are 8 bytes; payload CRC is optional).
  crc_int = 0xFFFFFFFF
  for b in buf:
    crc_int = (crc_int >> 8) ^ int(table[(crc_int ^ int(b)) & 0xFF])
  return crc_int ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
  crc = crc32c(data)
  return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


class TFRecordWriter:
  """Writes TFRecord files (fixture generation; get_tf_record.py analog)."""

  def __init__(self, path: str):
    self._f = open(path, "wb")

  def write(self, record: bytes) -> None:
    header = struct.pack("<Q", len(record))
    self._f.write(header)
    self._f.write(struct.pack("<I", masked_crc32c(header)))
    self._f.write(record)
    self._f.write(struct.pack("<I", masked_crc32c(record)))

  def close(self) -> None:
    self._f.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def read_records(path: str, verify: bool = False) -> Iterator[bytes]:
  """Yield raw record payloads from one TFRecord file."""
  with open(path, "rb") as f:
    while True:
      header = f.read(8)
      if not header:
        return
      if len(header) != 8:
        raise IOError(f"Truncated TFRecord header in {path}")
      (length,) = struct.unpack("<Q", header)
      length_crc_bytes = f.read(4)
      if len(length_crc_bytes) != 4:
        raise IOError(f"Truncated TFRecord length CRC in {path}")
      if verify and masked_crc32c(header) != struct.unpack(
          "<I", length_crc_bytes)[0]:
        raise IOError(f"Corrupt TFRecord length CRC in {path}")
      data = f.read(length)
      if len(data) != length:
        raise IOError(f"Truncated TFRecord payload in {path}")
      data_crc_bytes = f.read(4)
      if len(data_crc_bytes) != 4:
        raise IOError(f"Truncated TFRecord payload CRC in {path}")
      if verify and masked_crc32c(data) != struct.unpack(
          "<I", data_crc_bytes)[0]:
        raise IOError(f"Corrupt TFRecord payload CRC in {path}")
      yield data


def shard_path(data_dir: str, subset: str, index: int, total: int) -> str:
  """Canonical shard filename, matched by :func:`list_shards`
  (``<subset>-%05d-of-%05d``, the reference's naming convention)."""
  return os.path.join(data_dir, f"{subset}-{index:05d}-of-{total:05d}")


def list_shards(data_dir: str, subset: str) -> List[str]:
  """Shard discovery: ``<subset>-*-of-*`` files, the naming the reference's
  datasets use (ref: datasets.py:131-137 tf_record_pattern)."""
  prefix = {"train": "train", "validation": "validation"}[subset]
  names = sorted(n for n in os.listdir(data_dir) if n.startswith(prefix + "-"))
  if not names:
    raise ValueError(f"No TFRecord shards matching {prefix}-* in {data_dir}")
  return [os.path.join(data_dir, n) for n in names]
