"""Synthetic colored-square TFRecord fixture generator.

Analog of the reference's test-data generator (ref:
scripts/tf_cnn_benchmarks/test_data/tfrecord_image_generator.py): writes
ImageNet-style Example protos (JPEG bytes + label + bbox) whose images are
solid colored squares, for input-pipeline tests and smoke runs.
"""

from __future__ import annotations

import io
import os
from typing import Sequence

import numpy as np

from kf_benchmarks_tpu.data import example as example_lib
from kf_benchmarks_tpu.data import tfrecord


def _jpeg_bytes(rgb, size: int = 64) -> bytes:
  from PIL import Image
  arr = np.zeros((size, size, 3), np.uint8)
  arr[:, :] = rgb
  buf = io.BytesIO()
  Image.fromarray(arr).save(buf, format="JPEG", quality=95)
  return buf.getvalue()


def write_color_square_records(
    data_dir: str, num_train_shards: int = 2, num_validation_shards: int = 1,
    examples_per_shard: int = 8, num_classes: int = 10,
    image_size: int = 64) -> None:
  os.makedirs(data_dir, exist_ok=True)
  rng = np.random.RandomState(0)
  for subset, num_shards in (("train", num_train_shards),
                             ("validation", num_validation_shards)):
    for shard in range(num_shards):
      path = os.path.join(
          data_dir, f"{subset}-{shard:05d}-of-{num_shards:05d}")
      with tfrecord.TFRecordWriter(path) as w:
        for i in range(examples_per_shard):
          label = int(rng.randint(1, num_classes + 1))
          rgb = tuple(int(c) for c in rng.randint(0, 256, size=3))
          record = example_lib.encode_example({
              "image/encoded": _jpeg_bytes(rgb, image_size),
              "image/class/label": np.array([label], np.int64),
              "image/object/bbox/xmin": np.array([0.1], np.float32),
              "image/object/bbox/ymin": np.array([0.1], np.float32),
              "image/object/bbox/xmax": np.array([0.9], np.float32),
              "image/object/bbox/ymax": np.array([0.9], np.float32),
          })
          w.write(record)
