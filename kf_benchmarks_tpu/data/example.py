"""Minimal tf.train.Example wire-format codec (no TensorFlow/protobuf dep).

The reference parses ImageNet Example protos with
``tf.parse_single_example`` (ref: scripts/tf_cnn_benchmarks/
preprocessing.py:27-81). This is a hand-rolled encoder/decoder for the
small, stable subset of protobuf wire format those protos use:

    Example      { Features features = 1; }
    Features     { map<string, Feature> feature = 1; }
    Feature      { oneof { BytesList bytes_list = 1;
                           FloatList float_list = 2;
                           Int64List int64_list = 3; } }
    BytesList    { repeated bytes value = 1; }
    FloatList    { repeated float value = 1 [packed]; }
    Int64List    { repeated int64 value = 1 [packed]; }

Decoded form: dict[str, list[bytes] | np.ndarray(float32) | np.ndarray(int64)].
"""

from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Union

import numpy as np

FeatureValue = Union[List[bytes], np.ndarray]


# -- varint ------------------------------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
  while True:
    b = value & 0x7F
    value >>= 7
    if value:
      out.append(b | 0x80)
    else:
      out.append(b)
      return


def _read_varint(buf: bytes, pos: int):
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7


def _read_len_delimited(buf: bytes, pos: int):
  length, pos = _read_varint(buf, pos)
  return buf[pos:pos + length], pos + length


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
  if wire_type == 0:
    _, pos = _read_varint(buf, pos)
  elif wire_type == 1:
    pos += 8
  elif wire_type == 2:
    length, pos = _read_varint(buf, pos)
    pos += length
  elif wire_type == 5:
    pos += 4
  else:
    raise ValueError(f"Unsupported wire type {wire_type}")
  return pos


# -- decode ------------------------------------------------------------------

def _parse_list(buf: bytes, kind: int) -> FeatureValue:
  """kind: 1=bytes_list, 2=float_list, 3=int64_list."""
  pos = 0
  if kind == 1:
    values: List[bytes] = []
    while pos < len(buf):
      tag, pos = _read_varint(buf, pos)
      if tag == (1 << 3) | 2:
        v, pos = _read_len_delimited(buf, pos)
        values.append(bytes(v))
      else:
        pos = _skip_field(buf, pos, tag & 7)
    return values
  floats: List[float] = []
  ints: List[int] = []
  while pos < len(buf):
    tag, pos = _read_varint(buf, pos)
    field, wt = tag >> 3, tag & 7
    if field != 1:
      pos = _skip_field(buf, pos, wt)
    elif kind == 2:  # float_list: packed (wt=2) or unpacked (wt=5)
      if wt == 2:
        packed, pos = _read_len_delimited(buf, pos)
        floats.extend(np.frombuffer(packed, dtype="<f4").tolist())
      else:
        floats.append(struct.unpack_from("<f", buf, pos)[0])
        pos += 4
    else:  # int64_list: packed (wt=2) or unpacked (wt=0)
      if wt == 2:
        packed, pos = _read_len_delimited(buf, pos)
        p2 = 0
        while p2 < len(packed):
          v, p2 = _read_varint(packed, p2)
          ints.append(v - (1 << 64) if v >= (1 << 63) else v)
      else:
        v, pos = _read_varint(buf, pos)
        ints.append(v - (1 << 64) if v >= (1 << 63) else v)
  if kind == 2:
    return np.asarray(floats, dtype=np.float32)
  return np.asarray(ints, dtype=np.int64)


def _parse_feature(buf: bytes) -> FeatureValue:
  pos = 0
  while pos < len(buf):
    tag, pos = _read_varint(buf, pos)
    field, wt = tag >> 3, tag & 7
    if wt == 2 and field in (1, 2, 3):
      inner, pos = _read_len_delimited(buf, pos)
      return _parse_list(inner, field)
    pos = _skip_field(buf, pos, wt)
  return []


def _parse_map_entries(buf: bytes):
  """Iterate (key, value_buf) of a map<string, Message> field -- map
  entries are repeated messages { key = 1; value = 2; }."""
  pos = 0
  while pos < len(buf):
    tag, pos = _read_varint(buf, pos)
    if tag == (1 << 3) | 2:
      entry, pos = _read_len_delimited(buf, pos)
      key = None
      value_buf = b""
      p2 = 0
      while p2 < len(entry):
        t2, p2 = _read_varint(entry, p2)
        if t2 == (1 << 3) | 2:
          k, p2 = _read_len_delimited(entry, p2)
          key = k.decode("utf-8")
        elif t2 == (2 << 3) | 2:
          value_buf, p2 = _read_len_delimited(entry, p2)
        else:
          p2 = _skip_field(entry, p2, t2 & 7)
      if key is not None:
        yield key, value_buf
    else:
      pos = _skip_field(buf, pos, tag & 7)


def _parse_features(feats_buf: bytes) -> Dict[str, FeatureValue]:
  """Features { map<string, Feature> feature = 1 }."""
  return {key: _parse_feature(value_buf)
          for key, value_buf in _parse_map_entries(feats_buf)}


def parse_example(record: bytes) -> Dict[str, FeatureValue]:
  """Decode a serialized Example into {feature_name: value}."""
  pos = 0
  # Example { features = 1 }
  feats_buf = b""
  while pos < len(record):
    tag, pos = _read_varint(record, pos)
    if tag == (1 << 3) | 2:
      feats_buf, pos = _read_len_delimited(record, pos)
    else:
      pos = _skip_field(record, pos, tag & 7)
  return _parse_features(feats_buf)


def parse_sequence_example(record: bytes):
  """Decode a serialized SequenceExample into (context, feature_lists).

  SequenceExample { Features context = 1; FeatureLists feature_lists = 2; }
  FeatureLists    { map<string, FeatureList> feature_list = 1; }
  FeatureList     { repeated Feature feature = 1; }

  The reference parses these with ``tf.io.parse_single_sequence_example``
  (ref: preprocessing.py:1081-1101 LibrispeechPreprocessor). Returns
  (dict[str, FeatureValue], dict[str, list[FeatureValue]]).
  """
  pos = 0
  context_buf = b""
  lists_buf = b""
  while pos < len(record):
    tag, pos = _read_varint(record, pos)
    if tag == (1 << 3) | 2:
      context_buf, pos = _read_len_delimited(record, pos)
    elif tag == (2 << 3) | 2:
      lists_buf, pos = _read_len_delimited(record, pos)
    else:
      pos = _skip_field(record, pos, tag & 7)
  feature_lists: Dict[str, List[FeatureValue]] = {}
  for key, fl_buf in _parse_map_entries(lists_buf):
    steps: List[FeatureValue] = []
    p = 0
    while p < len(fl_buf):
      tag, p = _read_varint(fl_buf, p)
      if tag == (1 << 3) | 2:
        feat_buf, p = _read_len_delimited(fl_buf, p)
        steps.append(_parse_feature(feat_buf))
      else:
        p = _skip_field(fl_buf, p, tag & 7)
    feature_lists[key] = steps
  return _parse_features(context_buf), feature_lists


# -- encode ------------------------------------------------------------------

def _len_delimited(out: bytearray, field: int, payload: bytes) -> None:
  _write_varint(out, (field << 3) | 2)
  _write_varint(out, len(payload))
  out.extend(payload)


def _encode_feature(value) -> bytes:
  inner = bytearray()
  if isinstance(value, (list, tuple)) and value and isinstance(
      value[0], (bytes, str)):
    lst = bytearray()
    for v in value:
      _len_delimited(lst, 1, v.encode() if isinstance(v, str) else v)
    _len_delimited(inner, 1, bytes(lst))
  elif isinstance(value, bytes):
    lst = bytearray()
    _len_delimited(lst, 1, value)
    _len_delimited(inner, 1, bytes(lst))
  else:
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.floating):
      packed = arr.astype("<f4").tobytes()
      lst = bytearray()
      _len_delimited(lst, 1, packed)
      _len_delimited(inner, 2, bytes(lst))
    else:
      lst = bytearray()
      payload = bytearray()
      for v in arr.astype(np.int64).ravel().tolist():
        _write_varint(payload, v & ((1 << 64) - 1))
      _len_delimited(lst, 1, bytes(payload))
      _len_delimited(inner, 3, bytes(lst))
  return bytes(inner)


def _encode_features(features: Dict[str, FeatureValue]) -> bytes:
  feats = bytearray()
  for key, value in features.items():
    entry = bytearray()
    _len_delimited(entry, 1, key.encode("utf-8"))
    _len_delimited(entry, 2, _encode_feature(value))
    _len_delimited(feats, 1, bytes(entry))
  return bytes(feats)


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
  out = bytearray()
  _len_delimited(out, 1, _encode_features(features))
  return bytes(out)


def encode_sequence_example(context: Dict[str, FeatureValue],
                            feature_lists: Dict[str, Sequence]) -> bytes:
  """Encode a SequenceExample (inverse of parse_sequence_example).
  ``feature_lists`` values are sequences of per-step feature values."""
  lists = bytearray()
  for key, steps in feature_lists.items():
    fl = bytearray()
    for step in steps:
      _len_delimited(fl, 1, _encode_feature(step))
    entry = bytearray()
    _len_delimited(entry, 1, key.encode("utf-8"))
    _len_delimited(entry, 2, bytes(fl))
    _len_delimited(lists, 1, bytes(entry))
  out = bytearray()
  _len_delimited(out, 1, _encode_features(context))
  _len_delimited(out, 2, bytes(lists))
  return bytes(out)
