"""Dataset registry.

Re-design of the reference registry (ref:
scripts/tf_cnn_benchmarks/datasets.py). A dataset is synthetic iff it has
no data_dir (ref: datasets.py:82-83); name->class map + dir-name sniffing
``create_dataset`` (ref: datasets.py:208-251).
"""

from __future__ import annotations

import os
from typing import Optional


class Dataset:
  """Abstract dataset (ref: datasets.py:44-121)."""

  def __init__(self, name: str, data_dir: Optional[str] = None,
               queue_runner_required: bool = False, num_classes: int = 1000):
    self.name = name
    self.data_dir = data_dir
    self._num_classes = num_classes

  def use_synthetic_gpu_inputs(self) -> bool:
    """Synthetic iff no data_dir (ref: datasets.py:82-83)."""
    return not self.data_dir

  @property
  def num_classes(self) -> int:
    return self._num_classes

  @num_classes.setter
  def num_classes(self, val: int) -> None:
    self._num_classes = val

  def num_examples_per_epoch(self, subset: str = "train") -> int:
    raise NotImplementedError

  def get_input_preprocessor(self, input_preprocessor: str = "default"):
    """Resolved lazily to avoid importing the pipeline for synthetic runs."""
    try:
      from kf_benchmarks_tpu.data import preprocessing
    except ImportError as e:
      raise NotImplementedError(
          "Real-data input pipeline not available yet; run with synthetic "
          "data (no --data_dir)") from e
    return preprocessing.get_preprocessor(self.name, input_preprocessor)

  def __str__(self):
    return self.name


class ImagenetDataset(Dataset):
  """(ref: datasets.py:124-137)"""

  def __init__(self, data_dir=None):
    # 1001 classes: TFRecord labels are 1-based with 0 reserved for
    # background, and flow to the logits unshifted (ref: datasets.py:116,
    # preprocessing.py:57 keeps the raw label).
    super().__init__("imagenet", data_dir, num_classes=1001)

  def num_examples_per_epoch(self, subset="train"):
    if subset == "train":
      return 1281167
    if subset == "validation":
      return 50000
    raise ValueError(f"Invalid data subset {subset!r}")


class Cifar10Dataset(Dataset):
  """(ref: datasets.py:140-189)"""

  def __init__(self, data_dir=None):
    super().__init__("cifar10", data_dir, num_classes=10)

  def num_examples_per_epoch(self, subset="train"):
    if subset == "train":
      return 50000
    if subset == "validation":
      return 10000
    raise ValueError(f"Invalid data subset {subset!r}")


class COCODataset(Dataset):
  """(ref: datasets.py:192-205)"""

  def __init__(self, data_dir=None):
    super().__init__("coco", data_dir, num_classes=81)

  def num_examples_per_epoch(self, subset="train"):
    if subset == "train":
      return 118287
    if subset == "validation":
      return 4952
    raise ValueError(f"Invalid data subset {subset!r}")


class LibrispeechDataset(Dataset):
  """(ref: datasets.py:86-103)"""

  def __init__(self, data_dir=None):
    super().__init__("librispeech", data_dir, num_classes=29)

  def num_examples_per_epoch(self, subset="train"):
    if subset == "train":
      return 281241
    if subset == "validation":
      return 5567
    raise ValueError(f"Invalid data subset {subset!r}")


_DATASETS = {
    "imagenet": ImagenetDataset,
    "cifar10": Cifar10Dataset,
    "coco": COCODataset,
    "librispeech": LibrispeechDataset,
}


def create_dataset(data_dir: Optional[str],
                   data_name: Optional[str]) -> Dataset:
  """Name->class with dir-name sniffing (ref: datasets.py:232-251)."""
  if not data_dir and not data_name:
    data_name = "imagenet"  # synthetic default (ref :236-237)
  if data_name == "synthetic":
    # Accepted wherever model_config accepts it: synthetic imagenet.
    data_name, data_dir = "imagenet", None
  if data_name is None:
    for name in _DATASETS:
      if name in os.path.basename(data_dir).lower():
        data_name = name
        break
    else:
      raise ValueError(
          f"Could not identify name of dataset. Please specify with "
          f"--data_name option. data_dir={data_dir}")
  if data_name not in _DATASETS:
    raise ValueError(f"Unknown dataset. Must be one of "
                     f"{sorted(_DATASETS)}, got {data_name!r}")
  return _DATASETS[data_name](data_dir)
