"""Fake COCO TFRecord + annotation generator.

The analog of the reference's test-data fetch/generation utilities
(ref: scripts/tf_cnn_benchmarks/test_data/tfrecord_image_generator.py and
get_tf_record.py) for the detection path: writes object-detection-format
TF Examples (image/encoded + image/object/bbox/* + image/object/
class/label + image/source_id, the fields COCOPreprocessor parses) and a
matching COCO ``instances`` annotation json so the mAP evaluator can run
end-to-end against ground truth it can actually score.

Images are solid-color squares with one bright axis-aligned rectangle per
ground-truth box, deterministic per source_id.
"""

from __future__ import annotations

import io
import json
import os
from typing import List, Tuple

import numpy as np

from kf_benchmarks_tpu.data import example as example_lib
from kf_benchmarks_tpu.data import tfrecord
from kf_benchmarks_tpu.models import ssd_constants


def _jpeg_with_boxes(rng: np.random.RandomState, size: int,
                     boxes: np.ndarray) -> bytes:
  from PIL import Image
  arr = np.full((size, size, 3), rng.randint(0, 64, size=3), np.uint8)
  for ymin, xmin, ymax, xmax in boxes:
    y0, x0 = int(ymin * size), int(xmin * size)
    y1, x1 = max(int(ymax * size), y0 + 1), max(int(xmax * size), x0 + 1)
    arr[y0:y1, x0:x1] = rng.randint(192, 256, size=3)
  buf = io.BytesIO()
  Image.fromarray(arr).save(buf, format="JPEG", quality=95)
  return buf.getvalue()


def _random_boxes(rng: np.random.RandomState, n: int) -> np.ndarray:
  """[n, 4] normalized (ymin, xmin, ymax, xmax), comfortably inside."""
  y0 = rng.uniform(0.05, 0.5, size=n)
  x0 = rng.uniform(0.05, 0.5, size=n)
  h = rng.uniform(0.2, 0.45, size=n)
  w = rng.uniform(0.2, 0.45, size=n)
  return np.stack([y0, x0, np.minimum(y0 + h, 0.95),
                   np.minimum(x0 + w, 0.95)], axis=1).astype(np.float32)


def write_fake_coco(data_dir: str, num_train: int = 16,
                    num_validation: int = 8, image_size: int = 300,
                    max_boxes: int = 3, seed: int = 0) -> str:
  """Write train/validation COCO TFRecord shards plus the annotation
  json at ssd_constants.ANNOTATION_FILE. Returns the annotation path."""
  os.makedirs(data_dir, exist_ok=True)
  rng = np.random.RandomState(seed)
  images_json: List[dict] = []
  annotations_json: List[dict] = []
  ann_id = 1
  next_source_id = 1
  for subset, count in (("train", num_train),
                        ("validation", num_validation)):
    path = os.path.join(data_dir, f"{subset}-00000-of-00001")
    with tfrecord.TFRecordWriter(path) as w:
      for _ in range(count):
        source_id = next_source_id
        next_source_id += 1
        n = int(rng.randint(1, max_boxes + 1))
        boxes = _random_boxes(rng, n)
        # Raw (90-class) COCO category ids, as real records carry.
        raw_classes = np.asarray(
            [ssd_constants.CLASS_INV_MAP[int(rng.randint(1, 81))]
             for _ in range(n)], np.int64)
        record = example_lib.encode_example({
            "image/encoded": _jpeg_with_boxes(rng, image_size, boxes),
            "image/source_id": str(source_id).encode(),
            "image/object/bbox/ymin": boxes[:, 0],
            "image/object/bbox/xmin": boxes[:, 1],
            "image/object/bbox/ymax": boxes[:, 2],
            "image/object/bbox/xmax": boxes[:, 3],
            "image/object/class/label": raw_classes,
        })
        w.write(record)
        if subset == "validation":
          images_json.append({"id": source_id, "height": image_size,
                              "width": image_size})
          for b, cls in zip(boxes, raw_classes):
            x, y = float(b[1]) * image_size, float(b[0]) * image_size
            bw = float(b[3] - b[1]) * image_size
            bh = float(b[2] - b[0]) * image_size
            annotations_json.append({
                "id": ann_id, "image_id": source_id,
                "category_id": int(cls),
                "bbox": [x, y, bw, bh],
                "area": bw * bh, "iscrowd": 0,
            })
            ann_id += 1
  ann_path = os.path.join(data_dir, ssd_constants.ANNOTATION_FILE)
  os.makedirs(os.path.dirname(ann_path), exist_ok=True)
  with open(ann_path, "w") as f:
    json.dump({
        "images": images_json,
        "annotations": annotations_json,
        "categories": [{"id": int(c)} for c in
                       ssd_constants.CLASS_INV_MAP[1:]],
    }, f)
  return ann_path
