"""Datasets + input pipeline (ref: datasets.py, preprocessing.py)."""
