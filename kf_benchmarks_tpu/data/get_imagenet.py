"""Fetch an ImageNet subset and convert it to the framework's TFRecord
layout (ref: scripts/tf_cnn_benchmarks/get_imagenet.py -- a tfds
imagenet2012_subset loader).

The reference script downloads `imagenet2012_subset/1pct` through
tensorflow_datasets and inspects a few samples. This analog goes one
step further and materializes the samples as `train-*` TFRecord shards
in the layout `data/preprocessing.py` reads, so a downloaded subset is
immediately trainable with `--data_dir`.

tensorflow_datasets (and network egress) are not part of the baked
environment; the import is gated with a clear error. On air-gapped
hosts, use `data/get_tf_record.py` to convert a local JPEG directory
instead.

Run: python -m kf_benchmarks_tpu.data.get_imagenet \
         --out_dir=/tmp/imagenet_subset --num_samples=1000
"""

from __future__ import annotations

import argparse
import io
import os


def fetch(out_dir: str, num_samples: int = 1000, shards: int = 8,
          subset: str = "imagenet2012_subset/1pct") -> int:
  """Download `num_samples` images via tfds and write TFRecord shards.

  Returns the number of examples written.
  """
  # Refuse to mix shard generations BEFORE the tfds import gate: any
  # leftover train-* file not part of THIS run's shard set (including a
  # .incomplete orphan from a hard-killed run) would survive alongside
  # the new set, and the reader's 'train-*' listing
  # (data/tfrecord.py list_shards) would consume the union, silently
  # training on duplicated or truncated data. This run's own names are
  # exempt: its .incomplete temps are overwritten and its final names
  # replaced atomically.
  import glob  # noqa: PLC0415
  from kf_benchmarks_tpu.data import tfrecord  # noqa: PLC0415
  want_shards = max(1, min(shards, num_samples))
  expected = set()
  for i in range(want_shards):
    base = os.path.basename(
        tfrecord.shard_path(out_dir, "train", i, want_shards))
    expected.add(base)
    expected.add(base + ".incomplete")
  stale = [p for p in glob.glob(os.path.join(out_dir, "train-*"))
           if os.path.basename(p) not in expected]
  if stale:
    raise SystemExit(
        f"{out_dir} already holds {len(stale)} train file(s) from a run "
        f"with a different shard count (e.g. {os.path.basename(stale[0])}); "
        "remove them first -- the reader lists every 'train-*' file and "
        "would consume both generations.")
  try:
    import tensorflow_datasets as tfds  # noqa: PLC0415
  except ImportError as e:
    raise SystemExit(
        "get_imagenet requires tensorflow_datasets (and network egress), "
        "which this environment does not provide. On an air-gapped host, "
        "convert a local JPEG directory with "
        "`python -m kf_benchmarks_tpu.data.get_tf_record` instead."
    ) from e
  import numpy as np  # noqa: PLC0415
  from PIL import Image  # noqa: PLC0415

  from kf_benchmarks_tpu.data import example as example_lib  # noqa: PLC0415
  from kf_benchmarks_tpu.data import tfrecord  # noqa: PLC0415

  dataset = tfds.load(subset, split=f"train[:{num_samples}]",
                      as_supervised=True)
  os.makedirs(out_dir, exist_ok=True)
  # Never more shards than samples (empty shards break shard rotation),
  # and write to temp names so an interrupted download can't leave a
  # complete-looking-but-truncated shard set for training to consume.
  shards = want_shards
  paths = [tfrecord.shard_path(out_dir, "train", i, shards)
           for i in range(shards)]
  writers = [tfrecord.TFRecordWriter(p + ".incomplete") for p in paths]
  n = 0
  try:
    for image, label in tfds.as_numpy(dataset):
      buf = io.BytesIO()
      Image.fromarray(np.asarray(image)).save(buf, format="JPEG")
      writers[n % shards].write(example_lib.encode_example({
          # 1-based labels (0 = background), the layout the ImageNet
          # Example parser expects (data/preprocessing.py).
          "image/encoded": buf.getvalue(),
          "image/class/label": np.asarray([int(label) + 1], np.int64),
      }))
      n += 1
  except BaseException:
    for w in writers:
      w.close()
    for p in paths:
      if os.path.exists(p + ".incomplete"):
        os.remove(p + ".incomplete")
    raise
  for w in writers:
    w.close()
  for p in paths:
    os.replace(p + ".incomplete", p)
  return n


def main():
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--out_dir", required=True)
  parser.add_argument("--num_samples", type=int, default=1000)
  parser.add_argument("--shards", type=int, default=8)
  parser.add_argument("--subset", default="imagenet2012_subset/1pct")
  args = parser.parse_args()
  n = fetch(args.out_dir, args.num_samples, args.shards, args.subset)
  print(f"Wrote {n} examples to {args.out_dir}")


if __name__ == "__main__":
  main()
